#!/usr/bin/env bash
# Test runner (analog of the reference's runtests.sh — SURVEY §2.13).
# Runs the whole suite on a virtual 8-device CPU mesh; pass extra pytest
# args through, e.g. ./runtests.sh -k keras
set -euo pipefail
cd "$(dirname "$0")"
exec python -m pytest tests/ -q "$@"
