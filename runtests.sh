#!/usr/bin/env bash
# Test runner (analog of the reference's runtests.sh — SURVEY §2.13).
# Runs the whole suite on a virtual 8-device CPU mesh; pass extra pytest
# args through, e.g. ./runtests.sh -k keras
set -euo pipefail
cd "$(dirname "$0")"
# --examples: the examples/ smoke tier (each walkthrough runs as a
# subprocess with DL4J_EXAMPLE_SMOKE=1 and must exit rc=0)
if [[ "${1:-}" == "--examples" ]]; then
  shift
  exec python -m pytest tests/test_examples.py -q -m slow "$@"
fi
# static-analysis tier (graftlint): host-sync patterns in the jit hot
# paths PLUS donation-safety / recompile-hazard / thread-discipline /
# tracer-leak over the whole package. Baseline-aware (the committed
# triage backlog doesn't fail; any NEW finding does) with a hard 10 s
# wall-clock budget so the pre-test tier stays fast.
# tools/check_host_sync.py remains as a back-compat shim over the
# host-sync rule.
python -m tools.graftlint --baseline tools/graftlint/baseline.json \
  --max-seconds 10
# perf tier: compiled-in telemetry WITH in-step histograms (the flight
# recorder's config) must stay within a 3% step-overhead budget on the
# CPU path — the observe/ "one fetch per flush interval" claim
JAX_PLATFORMS=cpu python -m benchmarks.telemetry_overhead \
  --steps 150 --with-histograms --assert-overhead --tolerance 0.03
# input-pipeline tier: the fed fit path must replay the unfed
# trajectory bitwise and leave host_to_device span evidence
# (correctness only — the timed fed-vs-unfed A/B is not CI-gated)
JAX_PLATFORMS=cpu python -m benchmarks.input_pipeline --smoke
# serving tier: engine outputs bitwise-equal to direct model.output,
# zero recompiles after the warmup sweep (watchdog-asserted), and
# pipelined dispatch >=1.3x the blocking dispatcher closed-loop
JAX_PLATFORMS=cpu python -m benchmarks.serving --smoke
# quantization tier: int8 serving arm answers within the top-1 budget
# of f32, every precision arm warm (zero post-warmup recompiles), and
# int8's bytes-moved-per-request proxy strictly below bf16's
JAX_PLATFORMS=cpu python -m benchmarks.serving --precision-ab --smoke
# fleet tier: multi-process Poisson soak through the front-door router
# (admission control + SLO shedding) — zero post-warmup recompiles,
# shed rate < 100%, served p99 under the CPU-calibrated bound
JAX_PLATFORMS=cpu python -m benchmarks.serving --smoke-fleet
# cluster tier: chaos soak through the multi-node tier — 2 worker-node
# subprocesses join a gossiped registry + shared artifact store; one is
# SIGKILLed mid-soak and rejoins under the same id (breaker opens and
# recovers, zero live compiles from the shared store), the other is
# SIGTERM-drained (finishes in-flight, deregisters, exits 0); client
# errors bounded by the killed node's in-flight window, p99 gated
JAX_PLATFORMS=cpu python -m benchmarks.serving --smoke-cluster
# chaos tier: deterministic fault injection under an armed DL4J_CHAOS
# plan — torn registry record classified dead then healed, corrupted
# AOT blob quarantined + live-compiled warm, chaos-delayed remote sends
# absorbed with zero client errors, broker drops + restart survived,
# same-seed replay bitwise identical; plus expired-deadline requests
# answered 504 at the front door WITHOUT device dispatch, and the
# graftlint chaos-hygiene baseline stays empty
JAX_PLATFORMS=cpu python -m benchmarks.serving --smoke-chaos
# retrieval tier: interleaved A/B over the fused distance+top-k path —
# jitted brute >= host VPTree qps on worst-case pruning-hostile
# queries over the same corpus (>=10x in the full 1M run), int8 and
# IVF recall@10 >= 0.95 vs the exact f32 oracle, repeated queries
# bitwise identical (including distance ties), zero live compiles
# after the warmup sweep, int8 bytes/query < 0.3x f32 and IVF < brute
JAX_PLATFORMS=cpu python -m benchmarks.neighbors --smoke
# retrieval-cluster tier: scatter-gather chaos — two serve
# --neighbors-index subprocesses own disjoint shard slices; one is
# SIGKILLed mid-stream (every in-flight query answers full or
# partial:true, never an exception), rejoins under the same id warm
# from the shared store with zero live compiles, full answers resume,
# and the survivor SIGTERM-drains to exit 0 deregistered
JAX_PLATFORMS=cpu python -m benchmarks.neighbors --smoke-cluster
# autotune tier: one measured sweep (interleaved A/B per tunable)
# persists a fingerprinted TunedConfig artifact; it must reload
# bit-for-bit, size a consumer engine whose outputs stay bitwise-equal
# to direct model.output, and warm a SECOND process from the shared
# store with zero live compiles; the nprobe recall floor must actually
# exclude a candidate (constraint, not preference) and the measured
# winner must be >= the hand-tuned default on the serving tunable
JAX_PLATFORMS=cpu python -m benchmarks.autotune --smoke
# elastic tier: with one straggler, bounded-staleness ASYNC_ELASTIC
# sustains >=1.5x the SYNC round rate with divergence under the
# hard-sync threshold, and reduces exactly to AVERAGING without one
JAX_PLATFORMS=cpu python -m benchmarks.elastic --smoke
# online tier: train-and-serve in one process — a broker-fed learner's
# improved params hot-promote into the warm executables within the
# window (zero recompiles, watchdog-asserted), a degraded candidate is
# rejected, a forced degrade is sentinel-rolled-back to bitwise params,
# and client p99 stays bounded through every swap
JAX_PLATFORMS=cpu python -m benchmarks.online --smoke
# generation tier: continuous-batching decode — 16 Poisson-staggered
# SSE streams through POST /api/generate, every greedy output bitwise-
# equal to the sequential reference decode with slots reused mid-flight,
# zero live compiles after warmup (watchdog-asserted), token p99 + TTFT
# under the CPU bounds, and the pretrained int8 head strictly fewer
# bytes/token than bf16 within the next-token agreement budget; plus
# the v2 serving modes: chunked prefill TTFT strictly below tick
# prefill at 256-token prompts (bitwise-equal output), the speculative
# stream bitwise-equal to plain decode on the pretrained artifact, and
# a session resumed on a second in-proc node from the shared store
# checkpoint — bitwise continuation with zero live compiles
JAX_PLATFORMS=cpu python -m benchmarks.generation --smoke
# native tier: build the C kernels when a toolchain exists, then gate
# the fused pair producer — native must be >= the numpy fallback in
# tokens/s AND hand the device a bitwise-identical dispatch stream
# (toolchain-less checkouts skip the build; the fallback tier below
# still proves the numpy path)
if command -v c++ >/dev/null 2>&1 || command -v g++ >/dev/null 2>&1; then
  make -s -C native
  JAX_PLATFORMS=cpu python -m benchmarks.baseline_suite \
    doc2vec_producer --native-ab --smoke
else
  echo "native tier: no C++ toolchain, skipping build + A/B gate"
fi
# fallback-forced tier: the pairgen suite re-run with the native
# library kill-switched off (DL4J_NATIVE=0) — the numpy producer must
# train every mode end-to-end on its own
DL4J_NATIVE=0 JAX_PLATFORMS=cpu python -m pytest tests/test_pairgen.py -q
exec python -m pytest tests/ -q "$@"
