"""Flight recorder tests: forced-NaN post-mortem parse-back, exception
classification, zero-extra-fetch guarantee with the recorder armed,
healthz degradation, and cross-replica divergence telemetry."""

import json
import urllib.error
import urllib.request
from pathlib import Path

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.observe import (
    MetricsRegistry,
    TelemetryCollector,
)
from deeplearning4j_tpu.observe.flight_recorder import (
    FlightRecorder,
    _classify,
)
from deeplearning4j_tpu.observe.health import health_status
from deeplearning4j_tpu.optimize.listeners import TrainingListener


def _model(lr=1e-2, updater=None, seed=1):
    from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.inputs import InputType
    from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
    from deeplearning4j_tpu.nn.layers.output import OutputLayer
    from deeplearning4j_tpu.models.multi_layer_network import (
        MultiLayerNetwork)
    from deeplearning4j_tpu.ops.losses import LossFunction
    from deeplearning4j_tpu.optimize.updaters import Adam
    conf = (NeuralNetConfiguration.Builder().seed(seed)
            .updater(updater if updater is not None else Adam(lr))
            .list()
            .layer(DenseLayer(n_out=8))
            .layer(OutputLayer(n_out=3, loss=LossFunction.MCXENT))
            .set_input_type(InputType.feed_forward(5)).build())
    return MultiLayerNetwork(conf).init()


def _batches(n, batch=16, seed=0, scale=1.0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        x = (rng.normal(size=(batch, 5)) * scale).astype(np.float32)
        y = np.zeros((batch, 3), np.float32)
        y[np.arange(batch), rng.integers(0, 3, batch)] = 1.0
        out.append(DataSet(x, y))
    return out


class _ListIter:
    def __init__(self, batches):
        self.batches = batches

    def __iter__(self):
        return iter(self.batches)

    def reset(self):
        pass


def _nan_model():
    """Sgd with an absurd learning rate + huge inputs: the params blow
    up to inf/NaN within a couple of steps — deterministic NaN storm."""
    from deeplearning4j_tpu.optimize.updaters import Sgd
    return _model(updater=Sgd(1e28))


class _DumpListener(TrainingListener):
    def __init__(self):
        self.dumps = []

    def on_crash_dump(self, model, path, reason):
        self.dumps.append((path, reason))


class TestNaNDump:
    def test_forced_nan_writes_parseable_dump(self, tmp_path):
        m = _nan_model()
        tel = TelemetryCollector(flush_interval=2,
                                 registry=MetricsRegistry(),
                                 histograms=True, hist_interval=1)
        m.set_telemetry(tel)
        rec = FlightRecorder(dump_dir=str(tmp_path), enabled=True)
        m.set_flight_recorder(rec)
        lst = _DumpListener()
        m.set_listeners(lst)

        m.fit(_ListIter(_batches(6, scale=1e6)), epochs=1)

        assert len(rec.dumps) == 1, "NaN run must write exactly one dump"
        dump = Path(rec.dumps[0])
        assert dump.is_dir() and "nonfinite" in dump.name
        # the listener hook announced the same dump
        assert lst.dumps == [(str(dump), "nonfinite")]

        # every section parses back
        telj = json.loads((dump / "telemetry.json").read_text())
        assert telj["records"], "dump must carry decoded telemetry rows"
        assert any(r.get("nonfinite_count", 0) > 0
                   or not np.isfinite(r.get("loss", 0.0))
                   for r in telj["records"])
        assert "loss" in telj["metric_names"]

        hist = json.loads((dump / "histograms.json").read_text())
        assert hist["records"], "in-step histograms must be in the dump"
        layers = hist["records"][-1]["layers"]
        assert set(layers) == {"layer_0", "layer_1"}
        for by_kind in layers.values():
            assert set(by_kind) == {"param", "grad", "update"}

        mem = json.loads((dump / "memory.json").read_text())
        assert mem["devices"], "device watermarks missing"
        env = json.loads((dump / "environment.json").read_text())
        assert env["model_class"] == "MultiLayerNetwork"

        report = (dump / "report.md").read_text()
        assert "nonfinite" in report
        assert "telemetry.json" in report

        # the health surface degrades off the same registry
        h = health_status(tel.registry)
        assert h["status"] == "degraded"
        assert any("nonfinite" in r for r in h["reasons"])

    def test_reason_dedupe_and_max_dumps(self, tmp_path):
        m = _nan_model()
        tel = TelemetryCollector(flush_interval=2,
                                 registry=MetricsRegistry())
        m.set_telemetry(tel)
        rec = FlightRecorder(dump_dir=str(tmp_path), enabled=True)
        m.set_flight_recorder(rec)
        m.fit(_ListIter(_batches(6, scale=1e6)), epochs=1)
        # a NaN STORM (every later flush is non-finite too) still dumps
        # only once per reason
        m.fit(_ListIter(_batches(6, scale=1e6)), epochs=1)
        assert len(rec.dumps) == 1
        assert rec.record_crash(m, reason="nonfinite") is None

    def test_disabled_recorder_writes_nothing(self, tmp_path):
        m = _nan_model()
        tel = TelemetryCollector(flush_interval=2,
                                 registry=MetricsRegistry())
        m.set_telemetry(tel)
        rec = FlightRecorder(dump_dir=str(tmp_path), enabled=False)
        m.set_flight_recorder(rec)
        m.fit(_ListIter(_batches(4, scale=1e6)), epochs=1)
        assert rec.dumps == []
        assert list(tmp_path.iterdir()) == []


class TestExceptionDump:
    def test_exception_dump_and_reraise(self, tmp_path):
        class _Boom(TrainingListener):
            def iteration_done(self, model, iteration, epoch, loss,
                               etl_ms, examples):
                raise RuntimeError("boom at iteration_done")

        m = _model()
        rec = FlightRecorder(dump_dir=str(tmp_path), enabled=True)
        m.set_flight_recorder(rec)
        m.set_listeners(_Boom())
        with pytest.raises(RuntimeError, match="boom"):
            m.fit(_ListIter(_batches(2)), epochs=1)
        assert len(rec.dumps) == 1
        dump = Path(rec.dumps[0])
        assert "exception" in dump.name
        report = (dump / "report.md").read_text()
        assert "RuntimeError" in report
        assert "boom at iteration_done" in report

    def test_oom_classification(self):
        assert _classify(RuntimeError(
            "RESOURCE_EXHAUSTED: Out of memory allocating 1073741824 "
            "bytes")) == "oom"
        assert _classify(ValueError("plain failure")) == "exception"
        assert _classify(None) == "exception"

    def test_crash_handler_never_raises(self, tmp_path):
        rec = FlightRecorder(dump_dir=str(tmp_path), enabled=True)
        # a model-shaped object whose attributes all explode must not
        # mask the original crash
        class _Hostile:
            def __getattr__(self, name):
                raise RuntimeError("hostile attribute")
        assert rec.record_crash(_Hostile(), exc=ValueError("x")) is None


class TestOneFetchWithRecorder:
    def test_histograms_and_recorder_add_zero_fetches(self, monkeypatch,
                                                      tmp_path):
        """The acceptance property extended: histogram rows + per-layer
        rings + an ARMED flight recorder still cost exactly one
        jax.device_get per flush interval (3 flushes + tail = 4)."""
        fetches = []
        real = jax.device_get

        def counting(x):
            fetches.append(type(x).__name__)
            return real(x)

        m = _model()
        tel = TelemetryCollector(flush_interval=4,
                                 registry=MetricsRegistry(),
                                 histograms=True, hist_interval=2)
        m.set_telemetry(tel)
        rec = FlightRecorder(dump_dir=str(tmp_path), enabled=True)
        m.set_flight_recorder(rec)
        monkeypatch.setattr(jax, "device_get", counting)
        m.fit(_ListIter(_batches(12)), epochs=1)
        monkeypatch.setattr(jax, "device_get", real)
        assert tel.fetch_count == 4
        assert len(fetches) == 4
        # the histograms really were decoded from those same 4 fetches
        assert tel.hist_history
        assert len(tel.history) == 12
        # healthy run: the armed recorder stayed silent
        assert rec.dumps == []


class TestHealthz:
    def test_healthz_degrades_to_503(self):
        from deeplearning4j_tpu.ui import InMemoryStatsStorage, UIServer

        reg = MetricsRegistry()
        reg.counter("dl4j_nonfinite_values_total",
                    "non-finite values").inc(7.0, session="s")
        srv = UIServer(port=0, registry=reg).attach(
            InMemoryStatsStorage()).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(f"{srv.url}/healthz")
            assert ei.value.code == 503
            body = json.loads(ei.value.read())
            assert body["status"] == "degraded"
            assert any("nonfinite" in r for r in body["reasons"])
        finally:
            srv.stop()

    def test_health_status_ok_on_clean_registry(self):
        assert health_status(MetricsRegistry())["status"] == "ok"


class TestEvalCheckpointSpans:
    def test_earlystopping_emits_eval_and_checkpoint_spans(self):
        from deeplearning4j_tpu.datasets.dataset import (
            ArrayDataSetIterator)
        from deeplearning4j_tpu.earlystopping import (
            DataSetLossCalculator,
            EarlyStoppingConfiguration,
            EarlyStoppingTrainer,
            InMemoryModelSaver,
            MaxEpochsTerminationCondition,
        )
        from deeplearning4j_tpu.observe import SpanTracer

        rng = np.random.default_rng(0)
        x = rng.normal(size=(32, 5)).astype(np.float32)
        y = np.zeros((32, 3), np.float32)
        y[np.arange(32), rng.integers(0, 3, 32)] = 1.0
        train = ArrayDataSetIterator(DataSet(x, y), batch_size=16)
        test = ArrayDataSetIterator(DataSet(x, y), batch_size=32)
        esc = (EarlyStoppingConfiguration.Builder()
               .epoch_termination_conditions(
                   MaxEpochsTerminationCondition(2))
               .score_calculator(DataSetLossCalculator(test))
               .model_saver(InMemoryModelSaver())
               .build())
        m = _model()
        m.set_tracer(SpanTracer())
        EarlyStoppingTrainer(esc, m, train).fit()
        names = {e["name"] for e in m.tracer.events}
        assert "eval" in names, "held-out scoring must open an eval span"
        assert "checkpoint" in names, \
            "best-model save must open a checkpoint span"

    def test_elastic_trainer_emits_checkpoint_spans(self, tmp_path):
        from deeplearning4j_tpu.observe import SpanTracer
        from deeplearning4j_tpu.parallel.checkpoint import ElasticTrainer

        m = _model()
        m.set_tracer(SpanTracer())
        ElasticTrainer(m, str(tmp_path / "ckpt"),
                       checkpoint_every=2).fit(_ListIter(_batches(4)),
                                               epochs=1)
        ckpt = [e for e in m.tracer.events if e["name"] == "checkpoint"]
        assert ckpt, "periodic/tail saves must open checkpoint spans"


@pytest.mark.skipif(jax.device_count() < 2,
                    reason="needs multiple (virtual) devices")
class TestReplicaDivergence:
    def test_divergence_fires_on_desynced_replica(self):
        from deeplearning4j_tpu.parallel.wrapper import (
            ParallelWrapper, TrainingMode)

        m = _model()
        reg = MetricsRegistry()
        tel = TelemetryCollector(flush_interval=2, registry=reg)
        m.set_telemetry(tel)
        w = (ParallelWrapper.builder(m)
             .training_mode(TrainingMode.AVERAGING)
             .workers(jax.device_count())
             .averaging_frequency(2).build())
        nw = jax.device_count()
        batches = _batches(4, batch=8 * nw)
        # worker 0's shard (the first batch/W rows) sees inputs 1e4x
        # larger: its loss/grad-norm must stand out in the per-replica
        # rows and push the divergence gauge up
        for b in batches:
            b.features[:8] *= 1e4
        w.fit(_ListIter(batches), epochs=1)

        assert tel.replica_history, "per-replica rows must have flushed"
        last = tel.replica_history[-1]
        assert len(last["loss"]) == nw
        assert len(last["grad_norm"]) == nw
        div = reg.gauge("dl4j_replica_divergence").get(session="train")
        assert div is not None and div > 1.0

    def test_divergence_quiet_on_healthy_replicas(self):
        from deeplearning4j_tpu.parallel.wrapper import (
            ParallelWrapper, TrainingMode)

        m = _model(seed=3)
        reg = MetricsRegistry()
        tel = TelemetryCollector(flush_interval=2, registry=reg)
        m.set_telemetry(tel)
        w = (ParallelWrapper.builder(m)
             .training_mode(TrainingMode.SHARED_GRADIENTS)
             .workers(jax.device_count()).build())
        w.fit(_ListIter(_batches(4, batch=8 * jax.device_count(),
                                 seed=3)), epochs=1)
        assert tel.replica_history
        # sync replicas hold identical params: the fingerprint column is
        # flat and the divergence gauge stays ~0
        div = reg.gauge("dl4j_replica_divergence").get(session="train")
        assert div is not None and div < 1e-3
