"""Finite-difference gradient checks.

Analog of the reference's 16 gradient-check suites
(deeplearning4j-core/src/test/java/org/deeplearning4j/gradientcheck/ —
GradientChecksTests, CNNGradientCheckTest, LSTMGradientCheckTests,
GradientCheckTestsMasking, NoBiasGradientCheckTests, ...). One shared
checker (gradientcheck/gradient_check_util.py), many architectures.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.gradientcheck.gradient_check_util import (
    check_model_gradients,
)
from deeplearning4j_tpu.models.computation_graph import ComputationGraph
from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
from deeplearning4j_tpu.nn.graph.vertices import ElementWiseVertex, MergeVertex
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.convolution import (
    ConvolutionLayer,
    ConvolutionMode,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
from deeplearning4j_tpu.nn.layers.normalization import (
    BatchNormalization,
    LayerNormalization,
)
from deeplearning4j_tpu.nn.layers.output import (
    GlobalPoolingLayer,
    OutputLayer,
    RnnOutputLayer,
)
from deeplearning4j_tpu.nn.layers.recurrent import (
    LSTM,
    Bidirectional,
    GravesLSTM,
    LastTimeStep,
    SimpleRnn,
)
from deeplearning4j_tpu.nn.layers.convolution import PoolingType
from deeplearning4j_tpu.ops.activations import Activation
from deeplearning4j_tpu.ops.losses import LossFunction
from deeplearning4j_tpu.optimize.updaters import Sgd

RNG = np.random.default_rng(42)


def onehot(idx, n):
    out = np.zeros((len(idx), n), np.float64)
    out[np.arange(len(idx)), idx] = 1.0
    return out


def small_ds(n=8, f=4, classes=3):
    x = RNG.normal(size=(n, f))
    y = onehot(RNG.integers(0, classes, n), classes)
    return DataSet(x, y)


def build(layers, input_type, seed=12345):
    b = NeuralNetConfiguration.Builder().seed(seed).updater(Sgd(0.1)).list()
    for l in layers:
        b = b.layer(l)
    return MultiLayerNetwork(b.set_input_type(input_type).build()).init()


def test_mlp_mcxent():
    m = build([DenseLayer(n_out=6, activation=Activation.TANH),
               OutputLayer(n_out=3, loss=LossFunction.MCXENT,
                           activation=Activation.SOFTMAX)],
              InputType.feed_forward(4))
    assert check_model_gradients(m, small_ds())


def test_mlp_activations():
    for act in [Activation.RELU, Activation.ELU, Activation.SOFTPLUS,
                Activation.SIGMOID, Activation.SWISH]:
        m = build([DenseLayer(n_out=5, activation=act),
                   OutputLayer(n_out=3)],
                  InputType.feed_forward(4), seed=hash(act.name) % 100000)
        assert check_model_gradients(m, small_ds(), max_params_per_leaf=8), act


def test_losses():
    for loss, out_act, labels_kind in [
        (LossFunction.MSE, Activation.IDENTITY, "real"),
        (LossFunction.L1, Activation.IDENTITY, "real"),
        (LossFunction.XENT, Activation.SIGMOID, "binary"),
        (LossFunction.MCXENT, Activation.SOFTMAX, "onehot"),
        (LossFunction.POISSON, Activation.SOFTPLUS, "count"),
    ]:
        n, f, c = 8, 4, 3
        x = RNG.normal(size=(n, f))
        if labels_kind == "real":
            y = RNG.normal(size=(n, c))
        elif labels_kind == "binary":
            y = RNG.integers(0, 2, size=(n, c)).astype(np.float64)
        elif labels_kind == "count":
            y = RNG.integers(0, 5, size=(n, c)).astype(np.float64)
        else:
            y = onehot(RNG.integers(0, c, n), c)
        m = build([DenseLayer(n_out=6, activation=Activation.TANH),
                   OutputLayer(n_out=c, loss=loss, activation=out_act)],
                  InputType.feed_forward(f))
        assert check_model_gradients(m, DataSet(x, y),
                                     max_params_per_leaf=8), loss


def test_l1_l2_regularization():
    m = build([DenseLayer(n_out=6, activation=Activation.TANH, l1=0.01,
                          l2=0.02),
               OutputLayer(n_out=3, l2=0.01)],
              InputType.feed_forward(4))
    assert check_model_gradients(m, small_ds())


def test_no_bias():
    m = build([DenseLayer(n_out=6, activation=Activation.TANH, has_bias=False),
               OutputLayer(n_out=3, has_bias=False)],
              InputType.feed_forward(4))
    assert check_model_gradients(m, small_ds())


def test_cnn():
    n = 4
    x = RNG.normal(size=(n, 6, 6, 2))
    y = onehot(RNG.integers(0, 3, n), 3)
    m = build([ConvolutionLayer(n_out=3, kernel_size=(3, 3),
                                activation=Activation.TANH,
                                convolution_mode=ConvolutionMode.SAME),
               SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)),
               OutputLayer(n_out=3)],
              InputType.convolutional(6, 6, 2))
    assert check_model_gradients(m, DataSet(x, y), max_params_per_leaf=8)


def test_cnn_avg_pool_batchnorm():
    n = 4
    x = RNG.normal(size=(n, 6, 6, 2))
    y = onehot(RNG.integers(0, 3, n), 3)
    m = build([ConvolutionLayer(n_out=3, kernel_size=(3, 3),
                                activation=Activation.ELU,
                                convolution_mode=ConvolutionMode.SAME),
               BatchNormalization(),
               SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2),
                                pooling_type=PoolingType.AVG),
               OutputLayer(n_out=3)],
              InputType.convolutional(6, 6, 2))
    assert check_model_gradients(m, DataSet(x, y), max_params_per_leaf=6)


def test_lstm():
    n, t, f = 4, 5, 3
    x = RNG.normal(size=(n, t, f))
    y = onehot(RNG.integers(0, 3, n), 3)
    m = build([LastTimeStep(inner=LSTM(n_in=f, n_out=4)),
               OutputLayer(n_out=3)],
              InputType.recurrent(f, t))
    assert check_model_gradients(m, DataSet(x, y), max_params_per_leaf=8)


def test_graves_lstm_and_simple_rnn():
    n, t, f = 4, 5, 3
    x = RNG.normal(size=(n, t, f))
    y = np.stack([onehot(RNG.integers(0, 3, n), 3)] * t, axis=1)
    for cell in [GravesLSTM(n_in=f, n_out=4), SimpleRnn(n_in=f, n_out=4)]:
        m = build([cell, RnnOutputLayer(n_out=3)],
                  InputType.recurrent(f, t))
        assert check_model_gradients(m, DataSet(x, y),
                                     max_params_per_leaf=6), type(cell)


def test_bidirectional():
    n, t, f = 4, 5, 3
    x = RNG.normal(size=(n, t, f))
    y = onehot(RNG.integers(0, 3, n), 3)
    m = build([Bidirectional(fwd=LSTM(n_in=f, n_out=4), mode="concat"),
               GlobalPoolingLayer(pooling_type=PoolingType.AVG),
               OutputLayer(n_out=3)],
              InputType.recurrent(f, t))
    assert check_model_gradients(m, DataSet(x, y), max_params_per_leaf=6)


def test_masking():
    """Gradient check with sequence masks (reference:
    GradientCheckTestsMasking)."""
    n, t, f = 4, 6, 3
    x = RNG.normal(size=(n, t, f))
    y = np.stack([onehot(RNG.integers(0, 3, n), 3)] * t, axis=1)
    lengths = RNG.integers(2, t + 1, n)
    mask = (np.arange(t)[None, :] < lengths[:, None]).astype(np.float64)
    m = build([LSTM(n_in=f, n_out=4), RnnOutputLayer(n_out=3)],
              InputType.recurrent(f, t))
    assert check_model_gradients(
        m, DataSet(x, y, features_mask=mask, labels_mask=mask),
        max_params_per_leaf=6)


def test_computation_graph_gradients():
    n, f = 6, 4
    x = RNG.normal(size=(n, f))
    y = onehot(RNG.integers(0, 3, n), 3)
    conf = (NeuralNetConfiguration.Builder()
            .seed(12345).updater(Sgd(0.1)).graph_builder()
            .add_inputs("in")
            .add_layer("a", DenseLayer(n_out=5, activation=Activation.TANH), "in")
            .add_layer("b", DenseLayer(n_out=5, activation=Activation.SIGMOID), "in")
            .add_vertex("m", MergeVertex(), "a", "b")
            .add_layer("c", DenseLayer(n_out=4, activation=Activation.TANH), "m")
            .add_vertex("ew", ElementWiseVertex(op="add"), "c", "c")
            .add_layer("out", OutputLayer(n_out=3), "ew")
            .set_outputs("out")
            .set_input_types(InputType.feed_forward(f))
            .build())
    model = ComputationGraph(conf).init()
    assert check_model_gradients(model, DataSet(x, y), max_params_per_leaf=8)


def test_layernorm_gradients():
    m = build([DenseLayer(n_out=6, activation=Activation.TANH),
               LayerNormalization(),
               OutputLayer(n_out=3)],
              InputType.feed_forward(4))
    assert check_model_gradients(m, small_ds())


def test_self_attention_gradients():
    from deeplearning4j_tpu.nn.layers.attention import SelfAttentionLayer
    n, t, f = 3, 6, 4
    x = RNG.normal(size=(n, t, f))
    y = onehot(RNG.integers(0, 3, n), 3)
    m = build([SelfAttentionLayer(n_in=f, n_out=4, n_heads=2),
               GlobalPoolingLayer(pooling_type=PoolingType.AVG),
               OutputLayer(n_out=3)],
              InputType.recurrent(f, t))
    assert check_model_gradients(m, DataSet(x, y), max_params_per_leaf=6)


def test_graves_bidirectional_lstm_gradients():
    from deeplearning4j_tpu.nn.layers.recurrent import (
        GravesBidirectionalLSTM)
    n, t, f = 3, 5, 3
    x = RNG.normal(size=(n, t, f))
    y = np.stack([onehot(RNG.integers(0, 3, n), 3)] * t, axis=1)
    m = build([GravesBidirectionalLSTM(n_in=f, n_out=4),
               RnnOutputLayer(n_out=3)],
              InputType.recurrent(f, t))
    assert check_model_gradients(m, DataSet(x, y), max_params_per_leaf=4)


def test_center_loss_gradients():
    from deeplearning4j_tpu.nn.layers.output import CenterLossOutputLayer
    m = build([DenseLayer(n_out=6, activation=Activation.TANH),
               CenterLossOutputLayer(n_out=3, alpha=0.1, lambda_=0.01)],
              InputType.feed_forward(4))
    assert check_model_gradients(m, small_ds(), max_params_per_leaf=8)
