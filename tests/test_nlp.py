"""NLP stack tests: tokenization, vocab/Huffman, Word2Vec (SG/CBOW, NS/HS),
ParagraphVectors, GloVe, serialization, vectorizers.

Mirrors the reference's test strategy (SURVEY §4): Word2Vec sanity on a
small corpus with structural similarity assertions + serde round-trips
(deeplearning4j-nlp/src/test).
"""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.nlp import (
    BasicLineIterator,
    CommonPreprocessor,
    DefaultTokenizerFactory,
    Glove,
    Huffman,
    ParagraphVectors,
    VocabConstructor,
    Word2Vec,
    WordVectorSerializer,
)
from deeplearning4j_tpu.nlp.bagofwords import TfidfVectorizer
from deeplearning4j_tpu.nlp.sentence_iterators import LabelledDocument
from deeplearning4j_tpu.nlp.tokenization import NGramTokenizerFactory
from deeplearning4j_tpu.nlp.word2vec import StaticWord2Vec


def _toy_corpus(n=120):
    """Two topic clusters: (cat,dog,pet) and (car,truck,road) co-occur
    within topics, never across — similarity must reflect that."""
    rng = np.random.default_rng(0)
    animals = ["cat", "dog", "pet", "fur", "paw"]
    vehicles = ["car", "truck", "road", "wheel", "engine"]
    out = []
    for _ in range(n):
        pool = animals if rng.random() < 0.5 else vehicles
        out.append(" ".join(rng.choice(pool, size=6)))
    return out


class TestTokenization:
    def test_default_tokenizer(self):
        tf = DefaultTokenizerFactory()
        assert tf.create("hello world foo").get_tokens() == \
            ["hello", "world", "foo"]

    def test_preprocessor(self):
        tf = DefaultTokenizerFactory()
        tf.set_token_pre_processor(CommonPreprocessor())
        assert tf.create("Hello, World! 123").get_tokens() == \
            ["hello", "world"]

    def test_ngrams(self):
        tf = NGramTokenizerFactory(min_n=1, max_n=2)
        toks = tf.create("a b c").get_tokens()
        assert "a b" in toks and "b c" in toks and "a" in toks


class TestVocab:
    def test_min_frequency_cutoff(self):
        seqs = [["a", "a", "a", "b", "b", "c"]]
        cache = VocabConstructor(min_word_frequency=2).build_vocab(seqs)
        assert cache.contains_word("a") and cache.contains_word("b")
        assert not cache.contains_word("c")
        assert cache.index_of("a") == 0  # descending frequency order

    def test_huffman_codes(self):
        seqs = [["a"] * 8 + ["b"] * 4 + ["c"] * 2 + ["d"]]
        cache = VocabConstructor().build_vocab(seqs)
        Huffman(cache.vocab_words()).build()
        words = {w.word: w for w in cache.vocab_words()}
        # most frequent word gets the shortest code
        assert len(words["a"].codes) <= len(words["d"].codes)
        for w in words.values():
            assert len(w.codes) == len(w.points)
            assert all(p < cache.num_words() - 1 for p in w.points)


class TestWord2Vec:
    @pytest.mark.parametrize("mode", ["ns", "hs", "cbow"])
    def test_topic_similarity(self, mode):
        w2v = Word2Vec(layer_size=24, window_size=3, min_word_frequency=1,
                       epochs=12, negative=4,
                       use_hierarchic_softmax=(mode == "hs"),
                       use_cbow=(mode == "cbow"),
                       learning_rate=0.05, batch_size=256, seed=7)
        w2v.fit(_toy_corpus())
        in_topic = w2v.similarity("cat", "dog")
        cross = w2v.similarity("cat", "truck")
        assert in_topic > cross, (in_topic, cross)

    def test_words_nearest(self):
        w2v = Word2Vec(layer_size=24, window_size=3, epochs=12,
                       negative=4, learning_rate=0.05, seed=7)
        w2v.fit(_toy_corpus())
        near = w2v.words_nearest("car", top_n=3)
        assert set(near) <= {"truck", "road", "wheel", "engine"}

    def test_sentence_iterator_and_text_format(self, tmp_path):
        p = tmp_path / "corpus.txt"
        p.write_text("\n".join(_toy_corpus(40)))
        w2v = Word2Vec(layer_size=8, epochs=2, negative=2, seed=1)
        w2v.fit(BasicLineIterator(str(p)))
        out = tmp_path / "vecs.txt"
        WordVectorSerializer.write_word_vectors(w2v, str(out))
        loaded = WordVectorSerializer.read_word_vectors(str(out))
        assert loaded.has_word("cat")
        np.testing.assert_allclose(loaded.get_word_vector("cat"),
                                   w2v.get_word_vector("cat"), atol=1e-4)

    def test_full_model_roundtrip(self, tmp_path):
        w2v = Word2Vec(layer_size=8, epochs=2, negative=2, seed=1)
        w2v.fit(_toy_corpus(30))
        path = str(tmp_path / "model.npz")
        WordVectorSerializer.write_full_model(w2v, path)
        loaded = WordVectorSerializer.read_full_model(path)
        assert loaded.vocab.num_words() == w2v.vocab.num_words()
        np.testing.assert_allclose(loaded.get_word_vector("cat"),
                                   w2v.get_word_vector("cat"), atol=1e-6)
        loaded.fit(_toy_corpus(10))  # resumable

    @pytest.mark.parametrize("mode", ["ns", "hs", "cbow"])
    def test_overlap_pairgen_bitwise_equal(self, mode):
        """The double-buffered producer-thread fit (overlap_pairgen,
        round 5) makes the same rng calls in the same order as the
        serial loop — syn0 must come out bitwise identical."""
        def run(overlap):
            w2v = Word2Vec(layer_size=16, window_size=3,
                           min_word_frequency=1, epochs=3, negative=4,
                           use_hierarchic_softmax=(mode == "hs"),
                           use_cbow=(mode == "cbow"),
                           learning_rate=0.05, batch_size=256, seed=11,
                           overlap_pairgen=overlap)
            w2v.fit(_toy_corpus(60))
            return np.asarray(w2v.syn0)
        np.testing.assert_array_equal(run(True), run(False))

    def test_overlap_consumer_error_propagates(self):
        """A device-side dispatch failure during an overlapped fit must
        surface promptly (not deadlock against the full bounded queue
        — code-review r5)."""
        w2v = Word2Vec(layer_size=8, epochs=2, negative=2, seed=1)

        def boom(prep):
            raise RuntimeError("device dispatch failed")

        w2v._dispatch_chunks = boom
        with pytest.raises(RuntimeError, match="device dispatch failed"):
            w2v.fit(_toy_corpus(40))

    def test_mixed_iterator_corpus_materialized(self):
        """A corpus whose first element is a list but that hides
        single-use iterators must still be materialized (the no-copy
        fast path requires ALL elements to be lists)."""
        corpus = _toy_corpus(20)
        seqs = [s.split() for s in corpus]
        seqs[5] = iter(corpus[5].split())
        w2v = Word2Vec(layer_size=8, epochs=2, negative=2, seed=1)
        w2v.fit(seqs)
        for tok in corpus[5].split():
            assert w2v.has_word(tok)

    def test_cbow_lr_anneals_within_one_slab(self):
        """The corpus-level CBOW producer must SPREAD anneal progress
        over pushed rows (code-review r5): a corpus that fits in one
        slab must still see the lr walk from ~learning_rate down, not
        snap to min_learning_rate before the first chunk seals."""
        w2v = Word2Vec(layer_size=8, window_size=3, use_cbow=True,
                       min_word_frequency=1, epochs=1, negative=2,
                       batch_size=512, seed=1)
        calls = []
        orig = w2v._lr
        w2v._lr = lambda seen, total: (calls.append(seen / max(total, 1))
                                       or orig(seen, total))
        w2v.fit(_toy_corpus(400))
        assert len(calls) >= 4
        assert calls[0] < 0.3, calls[:3]      # first seal: early anneal
        assert calls[-1] > 0.7, calls[-3:]    # last seal: near the end

    def test_static_copy(self):
        w2v = Word2Vec(layer_size=8, epochs=1, negative=2, seed=1)
        w2v.fit(_toy_corpus(20))
        st = StaticWord2Vec.from_model(w2v)
        assert st.similarity("cat", "cat") == pytest.approx(1.0, abs=1e-5)


class TestParagraphVectors:
    def _docs(self):
        docs = []
        for i in range(30):
            docs.append(LabelledDocument(
                "cat dog pet fur paw cat dog", ["ANIMAL"]))
            docs.append(LabelledDocument(
                "car truck road wheel engine car", ["VEHICLE"]))
        return docs

    @pytest.mark.parametrize("dm", [False, True])
    def test_label_vectors_separate(self, dm):
        pv = ParagraphVectors(dm=dm, layer_size=16, window_size=3,
                              epochs=6, negative=4, learning_rate=0.05,
                              seed=3, batch_size=256)
        pv.fit(self._docs())
        assert set(pv.labels()) == {"ANIMAL", "VEHICLE"}
        va = pv.get_label_vector("ANIMAL")
        cat = pv.get_word_vector("cat")
        car = pv.get_word_vector("car")
        cos = lambda a, b: float(a @ b / (np.linalg.norm(a) *
                                          np.linalg.norm(b) + 1e-9))
        assert cos(va, cat) > cos(va, car)

    def test_dbow_lr_anneals_within_one_slab(self):
        """The corpus-level DBOW producer must SPREAD anneal progress
        over pushed pairs exactly like the CBOW/SGNS walks (the
        first-seal/last-seal contract from code-review r5): a corpus
        that fits in one slab must see the lr walk down smoothly, not
        snap to min_learning_rate before the first chunk seals."""
        docs = [LabelledDocument(
            f"cat dog pet fur paw tail whisker meow purr claw d{i % 7}",
            [f"DOC_{i}"]) for i in range(120)]
        pv = ParagraphVectors(dm=False, layer_size=8, window_size=3,
                              min_word_frequency=1, epochs=1, negative=2,
                              batch_size=512, seed=1)
        calls = []
        orig = pv._lr
        pv._lr = lambda seen, total: (calls.append(seen / max(total, 1))
                                      or orig(seen, total))
        pv.fit(docs)
        assert len(calls) >= 4
        assert calls[0] < 0.3, calls[:3]      # first seal: early anneal
        assert calls[-1] > 0.7, calls[-3:]    # last seal: near the end

    def test_infer_and_predict(self):
        pv = ParagraphVectors(layer_size=16, window_size=3, epochs=6,
                              negative=4, learning_rate=0.05, seed=3)
        pv.fit(self._docs())
        assert pv.predict("cat dog fur") == "ANIMAL"
        assert pv.predict("truck road engine") == "VEHICLE"


class TestGlove:
    def test_glove_similarity(self):
        g = Glove(layer_size=16, window_size=4, epochs=30,
                  learning_rate=0.1, seed=5, batch_size=256)
        g.fit([s.split() for s in _toy_corpus(80)])
        assert g.similarity("cat", "dog") > g.similarity("cat", "truck")
        assert g.last_loss is not None and np.isfinite(g.last_loss)


class TestVectorizers:
    def test_tfidf(self):
        corpus = ["cat dog cat", "dog truck", "truck road truck"]
        v = TfidfVectorizer()
        mat = v.fit_transform(corpus)
        assert mat.shape == (3, v.vocab.num_words())
        cat_col = v.vocab.index_of("cat")
        assert mat[0, cat_col] > 0 and mat[1, cat_col] == 0


def test_glove_accepts_raw_strings():
    """Regression: raw-string corpora must tokenize by whitespace, not
    decompose into characters (list('cat') == ['c','a','t'])."""
    from deeplearning4j_tpu.nlp.glove import Glove
    g = Glove(layer_size=8, window_size=3, epochs=2,
              min_word_frequency=1, seed=5)
    g.fit(["the cat sat on the mat", "the dog sat on the rug"] * 4)
    assert g.has_word("cat") and g.has_word("dog")
    assert not g.has_word("c")
