"""ParallelInference + stats/UI pipeline tests (SURVEY §2.11, §2.12)."""

import json
import threading
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.parallel.inference import (
    InferenceMode,
    ParallelInference,
)
from deeplearning4j_tpu.ui import (
    InMemoryStatsStorage,
    RemoteUIStatsStorageRouter,
    SqliteStatsStorage,
    StatsListener,
    UIServer,
)


def _tiny_model():
    from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.inputs import InputType
    from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
    from deeplearning4j_tpu.nn.layers.output import OutputLayer
    from deeplearning4j_tpu.models.multi_layer_network import (
        MultiLayerNetwork)
    from deeplearning4j_tpu.ops.losses import LossFunction
    from deeplearning4j_tpu.optimize.updaters import Adam
    conf = (NeuralNetConfiguration.Builder().seed(1).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=8))
            .layer(OutputLayer(n_out=3, loss=LossFunction.MCXENT))
            .set_input_type(InputType.feed_forward(5)).build())
    return MultiLayerNetwork(conf).init()


class TestParallelInference:
    def test_inplace(self):
        m = _tiny_model()
        pi = ParallelInference(m, InferenceMode.INPLACE)
        x = np.random.default_rng(0).normal(size=(4, 5)).astype(np.float32)
        np.testing.assert_allclose(pi.output(x), np.asarray(m.output(x)),
                                   rtol=1e-6)

    def test_batched_concurrent(self):
        m = _tiny_model()
        rng = np.random.default_rng(1)
        xs = [rng.normal(size=(n, 5)).astype(np.float32)
              for n in (1, 2, 3, 1, 4, 2)]
        expected = [np.asarray(m.output(x)) for x in xs]
        results = [None] * len(xs)
        with ParallelInference(m, InferenceMode.BATCHED,
                               batch_limit=8) as pi:
            threads = [threading.Thread(
                target=lambda i=i: results.__setitem__(
                    i, pi.output(xs[i]))) for i in range(len(xs))]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        for got, want in zip(results, expected):
            np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_error_propagates(self):
        class Broken:
            def output(self, x):
                raise RuntimeError("boom")
        with ParallelInference(Broken(), InferenceMode.BATCHED) as pi:
            with pytest.raises(RuntimeError, match="boom"):
                pi.output(np.zeros((1, 5), np.float32))


def _fit_with_listener(storage):
    from deeplearning4j_tpu.datasets.dataset import DataSet
    m = _tiny_model()
    listener = StatsListener(storage, session_id="s1")
    m.set_listeners(listener)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(16, 5)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
    for _ in range(5):
        m.fit(DataSet(x, y))
    return m


class TestStatsPipeline:
    def test_listener_to_memory(self):
        st = InMemoryStatsStorage()
        _fit_with_listener(st)
        assert st.list_session_ids() == ["s1"]
        ups = st.get_all_updates("s1")
        assert len(ups) == 5
        assert all(np.isfinite(u["score"]) for u in ups)
        assert "param_stats" in ups[0]
        info = st.get_static_info("s1")
        assert info["num_params"] > 0
        # update (delta) stats appear from iteration 2 on
        assert "update_stats" in ups[1]

    def test_sqlite_roundtrip(self, tmp_path):
        st = SqliteStatsStorage(str(tmp_path / "stats.db"))
        _fit_with_listener(st)
        st2 = SqliteStatsStorage(str(tmp_path / "stats.db"))
        assert st2.list_session_ids() == ["s1"]
        assert len(st2.get_all_updates("s1")) == 5
        assert st2.get_static_info("s1")["model_class"] == \
            "MultiLayerNetwork"

    def test_ui_server_and_remote_router(self):
        st = InMemoryStatsStorage()
        server = UIServer(port=0).attach(st)
        server.start()
        try:
            # remote worker posts through the HTTP router
            router = RemoteUIStatsStorageRouter(server.url)
            router.put_static_info({"session_id": "r1", "hostname": "h"})
            router.put_update({"session_id": "r1", "iteration": 0,
                               "score": 1.5, "timestamp": 1.0})
            router.put_update({"session_id": "r1", "iteration": 1,
                               "score": 1.0, "timestamp": 2.0})
            router.flush()
            with urllib.request.urlopen(
                    server.url + "/api/overview?session=r1") as r:
                data = json.loads(r.read())
            assert data["scores"] == [1.5, 1.0]
            assert data["static_info"]["hostname"] == "h"
            with urllib.request.urlopen(server.url + "/") as r:
                assert b"Training overview" in r.read()
        finally:
            server.stop()

    def test_model_system_tabs_from_live_run(self):
        """Model-graph + system endpoints render from a live training run
        (VERDICT next#10: both tabs from the existing stats records)."""
        st = InMemoryStatsStorage()
        _fit_with_listener(st)
        server = UIServer(port=0).attach(st)
        server.start()
        try:
            with urllib.request.urlopen(
                    server.url + "/api/model?session=s1") as r:
                md = json.loads(r.read())
            names = [n["name"] for n in md["graph"]]
            assert names == ["layer_0", "layer_1"]
            assert md["graph"][0]["type"] == "DenseLayer"
            assert md["graph"][0]["n_params"] == 5 * 8 + 8
            assert md["graph"][1]["inputs"] == ["layer_0"]
            assert "layer_0" in md["latest_param_stats"]
            with urllib.request.urlopen(
                    server.url + "/api/system?session=s1") as r:
                sysd = json.loads(r.read())
            assert "bytes_in_use" in sysd
            with urllib.request.urlopen(server.url + "/") as r:
                page = r.read()
            assert b"Model graph" in page and b"t-SNE" in page
        finally:
            server.stop()

    def test_activation_images_from_conv_training(self):
        """ConvolutionalListener streams per-layer activation PNGs that
        the Activations tab serves (ConvolutionalListenerModule analog)."""
        import base64
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.models.multi_layer_network import (
            MultiLayerNetwork)
        from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.inputs import InputType
        from deeplearning4j_tpu.nn.layers.convolution import (
            ConvolutionLayer, SubsamplingLayer)
        from deeplearning4j_tpu.nn.layers.output import OutputLayer
        from deeplearning4j_tpu.optimize.updaters import Adam
        from deeplearning4j_tpu.ui.convolutional import (
            ConvolutionalListener)

        conf = (NeuralNetConfiguration.Builder().seed(2).updater(Adam(1e-2))
                .list()
                .layer(ConvolutionLayer(n_out=4, kernel_size=(3, 3)))
                .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
                .layer(OutputLayer(n_out=3))
                .set_input_type(InputType.convolutional(8, 8, 1))
                .build())
        m = MultiLayerNetwork(conf).init()
        st = InMemoryStatsStorage()
        rng = np.random.default_rng(1)
        x = rng.normal(size=(8, 8, 8, 1)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]
        m.set_listeners(ConvolutionalListener(st, session_id="conv1",
                                              frequency=1).set_example(x))
        for _ in range(2):
            m.fit(DataSet(x, y))

        server = UIServer(port=0).attach(st)
        server.start()
        try:
            with urllib.request.urlopen(
                    server.url + "/api/activations?session=conv1") as r:
                act = json.loads(r.read())
            imgs = act["activations_png"]
            assert "layer_0" in imgs
            png = base64.b64decode(imgs["layer_0"])
            assert png[:8] == b"\x89PNG\r\n\x1a\n"
        finally:
            server.stop()

    def test_activation_history_by_iteration(self):
        """Round 3: the Activations tab serves the FULL recorded history
        — any iteration retrievable, not just the latest."""
        st = InMemoryStatsStorage()
        for it in (1, 2, 3):
            st.put_update({"session_id": "h", "iteration": it,
                           "timestamp": float(it),
                           "type": "activations",
                           "activations_png": {"layer_0": f"img{it}"}})
        server = UIServer(port=0).attach(st)
        server.start()
        try:
            with urllib.request.urlopen(
                    server.url + "/api/activations?session=h") as r:
                act = json.loads(r.read())
            assert act["iterations"] == [1, 2, 3]
            assert act["iteration"] == 3          # latest by default
            with urllib.request.urlopen(
                    server.url
                    + "/api/activations?session=h&iteration=2") as r:
                act2 = json.loads(r.read())
            assert act2["iteration"] == 2
            assert act2["activations_png"]["layer_0"] == "img2"
        finally:
            server.stop()

    def test_layer_drilldown_endpoint(self):
        """Round 3: /api/layer serves per-layer param/update stats over
        time + latest histograms (the TrainModule drill-down)."""
        st = InMemoryStatsStorage()
        for it in (1, 2):
            st.put_update({
                "session_id": "d", "iteration": it, "timestamp": float(it),
                "param_stats": {"layer_0": {
                    "mean_magnitude": 0.1 * it, "stdev": 0.05,
                    "histogram": {"counts": [1, 2], "min": 0.0,
                                  "max": 1.0}}},
                "update_stats": {"layer_0": {
                    "mean_magnitude": 0.01 * it,
                    "histogram": {"counts": [3, 4], "min": -1.0,
                                  "max": 1.0}}},
            })
        server = UIServer(port=0).attach(st)
        server.start()
        try:
            with urllib.request.urlopen(
                    server.url + "/api/layer?session=d&name=layer_0") as r:
                d = json.loads(r.read())
            assert d["iterations"] == [1, 2]
            assert d["param_mean_magnitude"] == [0.1, 0.2]
            assert d["update_mean_magnitude"] == [0.01, 0.02]
            assert d["update_ratio"][1] == pytest.approx(0.1)
            assert d["param_histogram"]["counts"] == [1, 2]
            assert d["update_histogram"]["counts"] == [3, 4]
        finally:
            server.stop()

    def test_tsne_listener_auto_populates(self):
        """Round 3: TsneListener embeds the live model's activations and
        fills the t-SNE tab with no manual upload."""
        import time
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.models.multi_layer_network import (
            MultiLayerNetwork)
        from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.inputs import InputType
        from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
        from deeplearning4j_tpu.nn.layers.output import OutputLayer
        from deeplearning4j_tpu.optimize.updaters import Adam
        from deeplearning4j_tpu.ui import TsneListener

        conf = (NeuralNetConfiguration.Builder().seed(3).updater(Adam(1e-2))
                .list()
                .layer(DenseLayer(n_out=8))
                .layer(OutputLayer(n_out=3))
                .set_input_type(InputType.feed_forward(5)).build())
        m = MultiLayerNetwork(conf).init()
        st = InMemoryStatsStorage()
        server = UIServer(port=0).attach(st)
        server.start()
        try:
            rng = np.random.default_rng(4)
            x = rng.normal(size=(40, 5)).astype(np.float32)
            y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 40)]
            m.set_listeners(TsneListener(server, frequency=1, n_iter=30,
                                         perplexity=5.0)
                            .set_example(x, rng.integers(0, 3, 40)))
            m.fit(DataSet(x, y))
            for _ in range(100):       # background embedding thread
                with urllib.request.urlopen(server.url
                                            + "/api/tsne") as r:
                    d = json.loads(r.read())
                if d["points"]:
                    break
                time.sleep(0.2)
            assert len(d["points"]) == 40
            assert len(d["labels"]) == 40
            assert all(np.isfinite(p).all() for p in
                       np.asarray(d["points"]))
        finally:
            server.stop()

    def test_tsne_tab_upload_and_fetch(self):
        st = InMemoryStatsStorage()
        st.put_update({"session_id": "t", "iteration": 0, "score": 1.0,
                       "timestamp": 0.0})
        server = UIServer(port=0).attach(st)
        server.start()
        try:
            server.upload_tsne([[0.0, 1.0], [2.0, 3.0]], ["a", "b"])
            with urllib.request.urlopen(server.url + "/api/tsne") as r:
                d = json.loads(r.read())
            assert d["points"] == [[0.0, 1.0], [2.0, 3.0]]
            assert d["labels"] == ["a", "b"]
            # remote POST path (the reference's coordinate upload)
            req = urllib.request.Request(
                server.url + "/api/tsne",
                data=json.dumps({"points": [[9, 9]],
                                 "labels": ["z"]}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req) as r:
                assert json.loads(r.read())["ok"]
            with urllib.request.urlopen(server.url + "/api/tsne") as r:
                assert json.loads(r.read())["labels"] == ["z"]
        finally:
            server.stop()


class TestUIModuleSPI:
    """UIModule SPI + i18n (round 5 — reference: UIModule.java routes +
    I18NProvider/DefaultI18N bundles)."""

    def _srv(self):
        from deeplearning4j_tpu.ui.server import UIServer
        from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage
        return UIServer(port=0).attach(InMemoryStatsStorage())

    def test_custom_module_routes(self):
        import json as _json
        import urllib.request
        from deeplearning4j_tpu.ui.modules import Route, UIModule

        class EchoModule(UIModule):
            def __init__(self):
                self.attached = None
                self.records = []

            def get_routes(self):
                return [
                    Route("GET", "/api/echo",
                          lambda ctx, q, body: {
                              "echo": q.get("msg", ""),
                              "has_storage": ctx.storage is not None}),
                    Route("POST", "/api/echo",
                          lambda ctx, q, body: {"got": body}),
                ]

            def on_attach(self, storage):
                self.attached = storage

            def on_update(self, record):
                self.records.append(record)

        mod = EchoModule()
        srv = self._srv().register_module(mod).start()
        try:
            assert mod.attached is not None
            with urllib.request.urlopen(
                    srv.url + "/api/echo?msg=hi") as r:
                data = _json.loads(r.read())
            assert data == {"echo": "hi", "has_storage": True}
            req = urllib.request.Request(
                srv.url + "/api/echo",
                data=_json.dumps({"x": 1}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req) as r:
                assert _json.loads(r.read()) == {"got": {"x": 1}}
            # remote records fan out to modules (reportStorageEvents)
            req = urllib.request.Request(
                srv.url + "/remote",
                data=_json.dumps({"record": {"session_id": "s",
                                             "score": 1.0}}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req) as r:
                assert _json.loads(r.read())["ok"]
            assert mod.records and mod.records[0]["score"] == 1.0
        finally:
            srv.stop()

    def test_module_error_does_not_kill_server(self):
        import json as _json
        import urllib.error
        import urllib.request
        from deeplearning4j_tpu.ui.modules import Route, UIModule

        class BadModule(UIModule):
            def get_routes(self):
                return [Route("GET", "/api/boom",
                              lambda ctx, q, body: 1 / 0)]

        srv = self._srv().register_module(BadModule()).start()
        try:
            try:
                urllib.request.urlopen(srv.url + "/api/boom")
                raise AssertionError("expected 500")
            except urllib.error.HTTPError as e:
                assert e.code == 500
                assert "module route failed" in _json.loads(
                    e.read())["error"]
            # server still serves built-ins afterwards
            with urllib.request.urlopen(srv.url + "/api/sessions") as r:
                assert r.status == 200
        finally:
            srv.stop()

    def test_module_error_detail_stays_server_side(self):
        import json as _json
        import urllib.error
        import urllib.request
        from deeplearning4j_tpu.ui.modules import Route, UIModule

        class LeakyModule(UIModule):
            def get_routes(self):
                def boom(ctx, q, body):
                    raise RuntimeError("secret /etc/path in message")
                return [Route("GET", "/api/leak", boom)]

        srv = self._srv().register_module(LeakyModule()).start()
        try:
            try:
                urllib.request.urlopen(srv.url + "/api/leak")
                raise AssertionError("expected 500")
            except urllib.error.HTTPError as e:
                assert e.code == 500
                err = _json.loads(e.read())["error"]
            # clients learn the exception class, never the message
            assert "RuntimeError" in err
            assert "secret" not in err and "/etc/path" not in err
        finally:
            srv.stop()

    def test_module_bad_return_type_is_500(self):
        import json as _json
        import urllib.error
        import urllib.request
        from deeplearning4j_tpu.ui.modules import Route, UIModule

        class WrongModule(UIModule):
            def get_routes(self):
                return [
                    Route("GET", "/api/str",
                          lambda ctx, q, body: "not a dict"),
                    Route("GET", "/api/none",
                          lambda ctx, q, body: None),
                ]

        srv = self._srv().register_module(WrongModule()).start()
        try:
            for path in ("/api/str", "/api/none"):
                try:
                    urllib.request.urlopen(srv.url + path)
                    raise AssertionError(f"expected 500 for {path}")
                except urllib.error.HTTPError as e:
                    assert e.code == 500
                    assert "TypeError" in _json.loads(
                        e.read())["error"]
        finally:
            srv.stop()

    def test_i18n_bundles_and_page(self):
        import json as _json
        import urllib.request
        from deeplearning4j_tpu.ui.i18n import I18N

        i18n = I18N.get_instance()
        assert i18n.get_message("train.nav.overview") == "Overview"
        assert i18n.get_message("train.nav.overview", "ja") == "概要"
        assert i18n.get_message("train.nav.overview", "de") == "Übersicht"
        # unknown key falls through to itself; unknown lang → English
        assert i18n.get_message("no.such.key", "ja") == "no.such.key"
        assert i18n.get_message("train.nav.model", "xx") == "Model"

        srv = self._srv().start()
        try:
            with urllib.request.urlopen(srv.url + "/?lang=ja") as r:
                page = r.read().decode("utf-8")
            assert "概要" in page and "{{i18n:" not in page
            with urllib.request.urlopen(srv.url + "/") as r:
                page = r.read().decode("utf-8")
            assert "Overview" in page
            with urllib.request.urlopen(
                    srv.url + "/api/i18n?lang=de") as r:
                data = _json.loads(r.read())
            assert data["messages"]["train.nav.system"] == "System"
            assert "ja" in data["languages"]
        finally:
            srv.stop()
