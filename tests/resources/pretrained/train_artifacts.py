"""Train and package the committed pretrained zoo artifacts
(VERDICT r3 #4 — the reference publishes checksummed weights,
ZooModel.java:40-51; zero-egress forbids downloading ImageNet weights,
not committing SELF-TRAINED ones for the small models).

Artifacts land in deeplearning4j_tpu/zoo/weights/ as checkpoint zips
plus ``.adler32`` sidecars; the zoo's PRETRAINED dicts reference them as
package resources.

Run from the repo root:  python tests/resources/pretrained/train_artifacts.py
"""

import json
import os
import sys
import zlib

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(os.path.dirname(os.path.dirname(HERE)))
sys.path.insert(0, REPO)
WEIGHTS = os.path.join(REPO, "deeplearning4j_tpu", "zoo", "weights")

CORPUS = os.path.join(HERE, "corpus.txt")
VOCAB_SIZE = 77
TIMESTEPS = 60


def adler32(path):
    v = 1
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            v = zlib.adler32(chunk, v)
    return v


def finish(path):
    c = adler32(path)
    with open(path + ".adler32", "w") as f:
        f.write(str(c))
    print(path, os.path.getsize(path), "bytes, adler32", c)


def train_lenet():
    from deeplearning4j_tpu.datasets.fetchers import DigitsDataSetIterator
    from deeplearning4j_tpu.models.serialization import save_model
    from deeplearning4j_tpu.zoo.models import LeNet

    model = LeNet(compute_dtype="float32").init()
    model.fit(DigitsDataSetIterator(batch_size=64, train=True), epochs=14)
    ev = model.evaluate(DigitsDataSetIterator(batch_size=64, train=False,
                                              shuffle=False))
    acc = ev.accuracy()
    print("LeNet digits test accuracy:", acc)
    assert acc >= 0.98, acc
    out = os.path.join(WEIGHTS, "lenet_digits.zip")
    save_model(model, out)
    finish(out)


def char_vocab(text):
    """Stable top-(VOCAB_SIZE-1) characters by frequency; index 0 is
    the unknown/other bucket."""
    from collections import Counter
    common = Counter(text).most_common(VOCAB_SIZE - 1)
    chars = sorted(c for c, _ in common)
    return {c: i + 1 for i, c in enumerate(chars)}


def train_textgen():
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.models.serialization import save_model
    from deeplearning4j_tpu.zoo.models import TextGenerationLSTM

    text = open(CORPUS, encoding="utf-8").read()
    vocab = char_vocab(text)
    ids = np.array([vocab.get(c, 0) for c in text], np.int32)
    T = TIMESTEPS
    stride = 3
    starts = np.arange(0, len(ids) - T - 1, stride)
    xs = np.stack([ids[s:s + T] for s in starts])
    ys = np.stack([ids[s + 1:s + T + 1] for s in starts])
    eye = np.eye(VOCAB_SIZE, dtype=np.float32)
    X = eye[xs]                             # (N, T, V) one-hot
    Y = eye[ys]
    model = TextGenerationLSTM().init()
    rng = np.random.default_rng(0)
    n = X.shape[0]
    batch = 128
    for epoch in range(5):
        order = rng.permutation(n)
        losses = []
        for lo in range(0, n - batch + 1, batch):
            idx = order[lo:lo + batch]
            model.fit(DataSet(X[idx], Y[idx]))
            losses.append(float(model._last_loss))
        print(f"textgen epoch {epoch}: loss {np.mean(losses):.4f}")
    final = np.mean(losses)
    # a char-LSTM that learned anything sits well under the ln(77)=4.34
    # uniform baseline on its own training distribution
    assert final < 2.0, final
    out = os.path.join(WEIGHTS, "textgen_lstm.zip")
    save_model(model, out)
    finish(out)
    with open(os.path.join(WEIGHTS, "textgen_vocab.json"), "w") as f:
        json.dump({c: i for c, i in vocab.items()}, f)


def train_simplecnn():
    """SimpleCNN on the real UCI digits (28x28 upscale) — the online-
    learning demo model (ISSUE 10): conv+batchnorm stack, small enough
    to hot-promote on CPU."""
    from deeplearning4j_tpu.datasets.dataset import (
        ArrayDataSetIterator, DataSet)
    from deeplearning4j_tpu.datasets.fetchers import DigitsDataSetIterator
    from deeplearning4j_tpu.models.serialization import save_model
    from deeplearning4j_tpu.zoo.models import SimpleCNN

    def nhwc(train):
        # SimpleCNN's input type is convolutional (NHWC), not the
        # flat variant LeNet uses — reshape the real digits ourselves
        x, y = DigitsDataSetIterator.fetch(train)
        oh = np.eye(10, dtype=np.float32)[y]
        return DataSet(x.reshape(-1, 28, 28, 1), oh)

    model = SimpleCNN(num_classes=10, height=28, width=28,
                      channels=1).init()
    model.fit(ArrayDataSetIterator(nhwc(True), 64, shuffle=True),
              epochs=8)
    ev = model.evaluate(ArrayDataSetIterator(nhwc(False), 64))
    acc = ev.accuracy()
    print("SimpleCNN digits test accuracy:", acc)
    assert acc >= 0.95, acc
    out = os.path.join(WEIGHTS, "simplecnn_digits.zip")
    save_model(model, out)
    finish(out)


if __name__ == "__main__":
    os.makedirs(WEIGHTS, exist_ok=True)
    only = sys.argv[1] if len(sys.argv) > 1 else None
    if only in (None, "lenet"):
        train_lenet()
    if only in (None, "textgen"):
        train_textgen()
    if only in (None, "simplecnn"):
        train_simplecnn()
