"""Generate the Keras-3 (.keras) half of the committed fixture corpus
(VERDICT r4 #5 — one end-to-end fixture per converter).

Each fixture is a small model SAVED BY THE INSTALLED KERAS 3 itself,
with a ``<name>_io.npz`` holding a fixed input and Keras' own
``model(x)`` output — an independent golden (the import path under test
never touches Keras at test time; the .keras bytes + golden are
committed). The Keras-1/2 dialects and the community layers Keras 3
cannot emit (AtrousConvolution2D, LRN, PoolHelper, SpaceToDepth, K1
Merge) live in the handwritten fixtures of ``gen_fixtures.py``.

Run from the repo root to regenerate:
    python tests/resources/keras/gen_keras3_fixtures.py
"""

import os

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
SEED = 20260731


def save_io(name, x, y):
    np.savez(os.path.join(HERE, f"{name}_io.npz"),
             x=np.asarray(x, np.float32), y=np.asarray(y, np.float32))


def k3_conv():
    """Conv family: Conv2D, SeparableConv2D, Conv2DTranspose,
    BatchNormalization, LeakyReLU, ELU, ZeroPadding2D, Cropping2D,
    UpSampling2D, SpatialDropout2D, MaxPooling2D, AveragePooling2D,
    GlobalAveragePooling2D, Dense, Softmax."""
    import keras
    from keras import layers as L

    keras.utils.set_random_seed(SEED)
    inp = keras.Input((12, 12, 3))
    x = L.Conv2D(8, 3, padding="same")(inp)
    x = L.BatchNormalization()(x)
    x = L.LeakyReLU(negative_slope=0.2)(x)
    x = L.SeparableConv2D(8, 3, padding="valid")(x)
    x = L.ELU()(x)
    x = L.ZeroPadding2D(((1, 2), (2, 1)))(x)
    x = L.Conv2DTranspose(6, 3, padding="valid")(x)
    x = L.Cropping2D(((1, 1), (2, 2)))(x)
    x = L.UpSampling2D(2)(x)
    x = L.SpatialDropout2D(0.2)(x)
    x = L.MaxPooling2D(2)(x)
    x = L.AveragePooling2D(2)(x)
    x = L.GlobalAveragePooling2D()(x)
    x = L.Dense(5)(x)
    out = L.Softmax()(x)
    m = keras.Model(inp, out, name="k3_conv")
    xin = np.random.default_rng(0).normal(
        size=(3, 12, 12, 3)).astype(np.float32)
    m.save(os.path.join(HERE, "k3_conv.keras"))
    save_io("k3_conv", xin, m(xin, training=False))


def k3_temporal():
    """Temporal family: Embedding, Conv1D, MaxPooling1D, SimpleRNN,
    Bidirectional(LSTM), GaussianDropout, GlobalAveragePooling1D,
    Dense."""
    import keras
    from keras import layers as L

    keras.utils.set_random_seed(SEED + 1)
    inp = keras.Input((16,))
    x = L.Embedding(32, 12)(inp)
    x = L.Conv1D(10, 3, padding="same", activation="relu")(x)
    x = L.MaxPooling1D(2)(x)
    x = L.SimpleRNN(8, return_sequences=True)(x)
    x = L.Bidirectional(L.LSTM(6, return_sequences=True))(x)
    x = L.GaussianDropout(0.1)(x)
    x = L.GlobalAveragePooling1D()(x)
    out = L.Dense(4, activation="softmax")(x)
    m = keras.Model(inp, out, name="k3_temporal")
    xin = np.random.default_rng(1).integers(
        0, 32, (4, 16)).astype(np.float32)
    m.save(os.path.join(HERE, "k3_temporal.keras"))
    save_io("k3_temporal", xin, m(xin, training=False))


def k3_merges():
    """Functional merge family: Add, Subtract, Multiply, Average,
    Maximum, Concatenate (+ InputLayer, Dense, Activation, Dropout,
    Flatten, Reshape, Permute, GaussianNoise)."""
    import keras
    from keras import layers as L

    keras.utils.set_random_seed(SEED + 2)
    inp = keras.Input((8,))
    a = L.Dense(6, activation="tanh")(inp)
    b = L.Dense(6, activation="sigmoid")(inp)
    s = L.Add()([a, b])
    d = L.Subtract()([a, b])
    p = L.Multiply()([a, b])
    v = L.Average()([a, b])
    mx = L.Maximum()([a, b])
    cat = L.Concatenate()([s, d, p, v, mx])          # (30,)
    x = L.GaussianNoise(0.1)(cat)
    x = L.Dropout(0.25)(x)
    x = L.Reshape((5, 6))(x)
    x = L.Permute((2, 1))(x)
    x = L.Flatten()(x)
    x = L.Activation("relu")(x)
    out = L.Dense(3)(x)
    m = keras.Model(inp, out, name="k3_merges")
    xin = np.random.default_rng(2).normal(size=(5, 8)).astype(np.float32)
    m.save(os.path.join(HERE, "k3_merges.keras"))
    save_io("k3_merges", xin, m(xin, training=False))


def k3_attention():
    """Attention family: LayerNormalization, MultiHeadAttention
    (self-attention), GlobalMaxPooling1D, AlphaDropout, Dense."""
    import keras
    from keras import layers as L

    keras.utils.set_random_seed(SEED + 3)
    inp = keras.Input((10, 12))
    x = L.LayerNormalization(epsilon=1e-6)(inp)
    x = L.MultiHeadAttention(num_heads=3, key_dim=4)(x, x)
    x = L.AlphaDropout(0.1)(x)
    x = L.GlobalMaxPooling1D()(x)
    out = L.Dense(2)(x)
    m = keras.Model(inp, out, name="k3_attention")
    xin = np.random.default_rng(3).normal(
        size=(4, 10, 12)).astype(np.float32)
    m.save(os.path.join(HERE, "k3_attention.keras"))
    save_io("k3_attention", xin, m(xin, training=False))


def k3_pool_extras():
    """Remaining pooling/upsampling: GlobalMaxPooling2D, UpSampling1D,
    ZeroPadding1D, Conv1D(valid)."""
    import keras
    from keras import layers as L

    keras.utils.set_random_seed(SEED + 4)
    inp = keras.Input((9, 9, 2))
    x = L.Conv2D(4, 3, activation="relu")(inp)
    g = L.GlobalMaxPooling2D()(x)
    x = L.Reshape((7 * 7, 4))(x)
    x = L.ZeroPadding1D((1, 2))(x)
    x = L.UpSampling1D(2)(x)
    x = L.Conv1D(3, 4, strides=4)(x)
    x = L.GlobalAveragePooling1D()(x)
    x = L.Concatenate()([x, g])
    out = L.Dense(3)(x)
    m = keras.Model(inp, out, name="k3_pool_extras")
    xin = np.random.default_rng(4).normal(
        size=(3, 9, 9, 2)).astype(np.float32)
    m.save(os.path.join(HERE, "k3_pool_extras.keras"))
    save_io("k3_pool_extras", xin, m(xin, training=False))


if __name__ == "__main__":
    k3_conv()
    k3_temporal()
    k3_merges()
    k3_attention()
    k3_pool_extras()
    print("keras3 fixtures written to", HERE)
