"""Generate the committed Keras fixture corpus (reference analog:
deeplearning4j-modelimport/src/test/resources + KerasModelEndToEndTest).

Writes genuine Keras-1-FORMAT and Keras-2-FORMAT .h5 files byte-by-byte
with h5py (the installed Keras is v3 and cannot emit the old dialects),
plus a ``<name>_io.npz`` with a fixed input and the expected output
computed by independent numpy reference math — so the e2e test checks
import fidelity against something other than our own layers.

Run from the repo root to regenerate:  python tests/resources/keras/gen_fixtures.py
"""

import json
import os

import h5py
import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
RNG = np.random.default_rng(20260730)


# ---- numpy reference math -------------------------------------------------

def relu(x):
    return np.maximum(x, 0.0)


def softmax(x):
    e = np.exp(x - x.max(axis=-1, keepdims=True))
    return e / e.sum(axis=-1, keepdims=True)


def dense(x, W, b):
    return x @ W + b


def conv2d_valid(x, W, b, dilation=1):
    n, h, w, cin = x.shape
    kh, kw, _, cout = W.shape
    eh, ew = (kh - 1) * dilation + 1, (kw - 1) * dilation + 1
    oh, ow = h - eh + 1, w - ew + 1
    y = np.zeros((n, oh, ow, cout))
    for di in range(kh):
        for dj in range(kw):
            patch = x[:, di * dilation:di * dilation + oh,
                      dj * dilation:dj * dilation + ow, :]
            y += np.einsum("nhwc,co->nhwo", patch, W[di, dj])
    return y + b


def maxpool2d(x, k=2, s=2):
    n, h, w, c = x.shape
    oh, ow = (h - k) // s + 1, (w - k) // s + 1
    y = np.full((n, oh, ow, c), -np.inf)
    for di in range(k):
        for dj in range(k):
            y = np.maximum(y, x[:, di:di + oh * s:s, dj:dj + ow * s:s, :])
    return y


def conv1d_valid(x, W, b, dilation=1):
    n, t, cin = x.shape
    k, _, cout = W.shape
    et = (k - 1) * dilation + 1
    ot = t - et + 1
    y = np.zeros((n, ot, cout))
    for d in range(k):
        y += np.einsum("ntc,co->nto", x[:, d * dilation:d * dilation + ot],
                       W[d])
    return y + b


def lstm_last(x, Wg, Ug, bg):
    """Per-gate Keras-1 LSTM (activation=tanh, inner_activation=sigmoid);
    returns the last hidden state. Wg/Ug/bg keyed by gate letter."""
    sig = lambda v: 1.0 / (1.0 + np.exp(-v))
    n, t, _ = x.shape
    hdim = Wg["i"].shape[1]
    h = np.zeros((n, hdim))
    c = np.zeros((n, hdim))
    for step in range(t):
        xt = x[:, step]
        i = sig(xt @ Wg["i"] + h @ Ug["i"] + bg["i"])
        f = sig(xt @ Wg["f"] + h @ Ug["f"] + bg["f"])
        o = sig(xt @ Wg["o"] + h @ Ug["o"] + bg["o"])
        g = np.tanh(xt @ Wg["c"] + h @ Ug["c"] + bg["c"])
        c = f * c + i * g
        h = o * np.tanh(c)
    return h


def lrn(x, k=2.0, n=5, alpha=1e-4, beta=0.75):
    half = n // 2
    sq = np.square(x)
    pad = [(0, 0)] * (x.ndim - 1) + [(half, half)]
    sq_pad = np.pad(sq, pad)
    ssum = sum(sq_pad[..., i:i + x.shape[-1]] for i in range(n))
    return x / np.power(k + alpha * ssum, beta)


def space_to_depth(x, b=2):
    n, h, w, c = x.shape
    x = x.reshape(n, h // b, b, w // b, b, c)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(n, h // b, w // b,
                                                 b * b * c)


# ---- h5 writers -----------------------------------------------------------

def write_k1(path, model_config_list, layer_weights, training_config=None):
    """Genuine Keras-1 file layout: model_config is a bare LIST; weights
    are flat per-layer datasets named '<layer>_W' etc. (no ':0', no
    paths); keras_version 1.2.2 at root."""
    with h5py.File(path, "w") as f:
        f.attrs["keras_version"] = np.bytes_("1.2.2")
        f.attrs["model_config"] = np.bytes_(json.dumps(
            {"class_name": "Sequential", "config": model_config_list}))
        if training_config:
            f.attrs["training_config"] = np.bytes_(
                json.dumps(training_config))
        mw = f.create_group("model_weights")
        mw.attrs["layer_names"] = np.array(
            [np.bytes_(n) for n in layer_weights])
        for lname, weights in layer_weights.items():
            g = mw.create_group(lname)
            g.attrs["weight_names"] = np.array(
                [np.bytes_(wn) for wn in weights])
            for wn, arr in weights.items():
                g.create_dataset(wn, data=arr.astype(np.float32))


def write_k2(path, layers_config, layer_weights, training_config=None):
    """Keras-2 file layout: model_config {'layers': [...]}, weight names
    '<layer>/<weight>:0', keras_version 2.2.4 on the weights group."""
    with h5py.File(path, "w") as f:
        f.attrs["keras_version"] = np.bytes_("2.2.4")
        f.attrs["model_config"] = np.bytes_(json.dumps(
            {"class_name": "Sequential",
             "config": {"name": "sequential", "layers": layers_config}}))
        if training_config:
            f.attrs["training_config"] = np.bytes_(
                json.dumps(training_config))
        mw = f.create_group("model_weights")
        mw.attrs["keras_version"] = np.bytes_("2.2.4")
        mw.attrs["layer_names"] = np.array(
            [np.bytes_(n) for n in layer_weights])
        for lname, weights in layer_weights.items():
            g = mw.create_group(lname)
            names = [f"{lname}/{wn}:0" for wn in weights]
            g.attrs["weight_names"] = np.array(
                [np.bytes_(n) for n in names])
            sub = g.create_group(lname)
            for wn, arr in weights.items():
                sub.create_dataset(f"{wn}:0", data=arr.astype(np.float32))


def save_io(name, x, y):
    np.savez(os.path.join(HERE, f"{name}_io.npz"),
             x=x.astype(np.float32), y=y.astype(np.float32))


# ---- fixtures -------------------------------------------------------------

def k1_mlp():
    W1 = RNG.normal(0, 0.4, (8, 16))
    b1 = RNG.normal(0, 0.1, (16,))
    W2 = RNG.normal(0, 0.4, (16, 4))
    b2 = RNG.normal(0, 0.1, (4,))
    cfg = [
        {"class_name": "Dense", "config": {
            "name": "dense_1", "output_dim": 16, "input_dim": 8,
            "batch_input_shape": [None, 8], "activation": "relu",
            "init": "glorot_uniform", "bias": True}},
        {"class_name": "Dense", "config": {
            "name": "dense_2", "output_dim": 4, "activation": "linear",
            "init": "glorot_uniform", "bias": True}},
        {"class_name": "Activation", "config": {
            "name": "activation_1", "activation": "softmax"}},
    ]
    weights = {"dense_1": {"dense_1_W": W1, "dense_1_b": b1},
               "dense_2": {"dense_2_W": W2, "dense_2_b": b2},
               "activation_1": {}}
    write_k1(os.path.join(HERE, "k1_mlp.h5"), cfg, weights,
             {"loss": "categorical_crossentropy"})
    x = RNG.normal(0, 1, (5, 8))
    save_io("k1_mlp", x, softmax(dense(relu(dense(x, W1, b1)), W2, b2)))


def k1_cnn_atrous():
    Wc = RNG.normal(0, 0.3, (3, 3, 2, 4))
    bc = RNG.normal(0, 0.05, (4,))
    Wa = RNG.normal(0, 0.3, (3, 3, 4, 6))
    ba = RNG.normal(0, 0.05, (6,))
    Wd = RNG.normal(0, 0.2, (54, 3))
    bd = RNG.normal(0, 0.05, (3,))
    cfg = [
        {"class_name": "Convolution2D", "config": {
            "name": "convolution2d_1", "nb_filter": 4, "nb_row": 3,
            "nb_col": 3, "border_mode": "valid", "subsample": [1, 1],
            "dim_ordering": "tf", "activation": "relu",
            "batch_input_shape": [None, 12, 12, 2], "bias": True}},
        {"class_name": "AtrousConvolution2D", "config": {
            "name": "atrousconvolution2d_1", "nb_filter": 6, "nb_row": 3,
            "nb_col": 3, "atrous_rate": [2, 2], "border_mode": "valid",
            "subsample": [1, 1], "dim_ordering": "tf",
            "activation": "relu", "bias": True}},
        {"class_name": "MaxPooling2D", "config": {
            "name": "maxpooling2d_1", "pool_size": [2, 2],
            "strides": [2, 2], "border_mode": "valid",
            "dim_ordering": "tf"}},
        {"class_name": "Flatten", "config": {"name": "flatten_1"}},
        {"class_name": "Dense", "config": {
            "name": "dense_1", "output_dim": 3, "activation": "softmax",
            "init": "glorot_uniform", "bias": True}},
    ]
    weights = {
        "convolution2d_1": {"convolution2d_1_W": Wc,
                            "convolution2d_1_b": bc},
        "atrousconvolution2d_1": {"atrousconvolution2d_1_W": Wa,
                                  "atrousconvolution2d_1_b": ba},
        "maxpooling2d_1": {}, "flatten_1": {},
        "dense_1": {"dense_1_W": Wd, "dense_1_b": bd},
    }
    write_k1(os.path.join(HERE, "k1_cnn_atrous.h5"), cfg, weights,
             {"loss": "categorical_crossentropy"})
    x = RNG.normal(0, 1, (3, 12, 12, 2))
    h = relu(conv2d_valid(x, Wc, bc))          # 10x10x4
    h = relu(conv2d_valid(h, Wa, ba, dilation=2))  # 6x6x6
    h = maxpool2d(h)                           # 3x3x6
    h = h.reshape(h.shape[0], -1)              # 54
    save_io("k1_cnn_atrous", x, softmax(dense(h, Wd, bd)))


def k1_lstm():
    F, H = 6, 8
    Wg = {g: RNG.normal(0, 0.3, (F, H)) for g in "ifco"}
    Ug = {g: RNG.normal(0, 0.3, (H, H)) for g in "ifco"}
    bg = {g: RNG.normal(0, 0.05, (H,)) for g in "ifco"}
    Wd = RNG.normal(0, 0.3, (H, 4))
    bd = RNG.normal(0, 0.05, (4,))
    cfg = [
        {"class_name": "LSTM", "config": {
            "name": "lstm_1", "output_dim": H, "activation": "tanh",
            "inner_activation": "sigmoid", "return_sequences": False,
            "batch_input_shape": [None, 7, F]}},
        {"class_name": "Dense", "config": {
            "name": "dense_1", "output_dim": 4, "activation": "softmax",
            "init": "glorot_uniform", "bias": True}},
    ]
    lw = {}
    for g in "ifco":
        lw[f"lstm_1_W_{g}"] = Wg[g]
        lw[f"lstm_1_U_{g}"] = Ug[g]
        lw[f"lstm_1_b_{g}"] = bg[g]
    weights = {"lstm_1": lw,
               "dense_1": {"dense_1_W": Wd, "dense_1_b": bd}}
    write_k1(os.path.join(HERE, "k1_lstm.h5"), cfg, weights,
             {"loss": "categorical_crossentropy"})
    x = RNG.normal(0, 1, (4, 7, F))
    h = lstm_last(x, Wg, Ug, bg)
    save_io("k1_lstm", x, softmax(dense(h, Wd, bd)))


def k2_googlenet_bits():
    """LRN + PoolHelper: the GoogLeNet-era community layers (reference
    registers them via registerCustomLayer; we convert built-in)."""
    Wc = RNG.normal(0, 0.3, (3, 3, 2, 4))
    bc = RNG.normal(0, 0.05, (4,))
    Wd = RNG.normal(0, 0.2, (64, 3))
    bd = RNG.normal(0, 0.05, (3,))
    cfg = [
        {"class_name": "Conv2D", "config": {
            "name": "conv2d_1", "filters": 4, "kernel_size": [3, 3],
            "strides": [1, 1], "padding": "valid", "activation": "relu",
            "use_bias": True, "batch_input_shape": [None, 11, 11, 2]}},
        {"class_name": "LRN", "config": {
            "name": "lrn_1", "alpha": 1e-4, "beta": 0.75, "k": 2, "n": 5}},
        {"class_name": "PoolHelper", "config": {"name": "poolhelper_1"}},
        {"class_name": "MaxPooling2D", "config": {
            "name": "maxpooling2d_1", "pool_size": [2, 2],
            "strides": [2, 2], "padding": "valid"}},
        {"class_name": "Flatten", "config": {"name": "flatten_1"}},
        {"class_name": "Dense", "config": {
            "name": "dense_1", "units": 3, "activation": "softmax",
            "use_bias": True}},
    ]
    weights = {"conv2d_1": {"kernel": Wc, "bias": bc},
               "lrn_1": {}, "poolhelper_1": {}, "maxpooling2d_1": {},
               "flatten_1": {},
               "dense_1": {"kernel": Wd, "bias": bd}}
    write_k2(os.path.join(HERE, "k2_googlenet_bits.h5"), cfg, weights,
             {"loss": "categorical_crossentropy"})
    x = RNG.normal(0, 1, (3, 11, 11, 2))
    h = relu(conv2d_valid(x, Wc, bc))   # 9x9x4
    h = lrn(h)
    h = h[:, 1:, 1:, :]                 # PoolHelper: strip first row/col
    h = maxpool2d(h)                    # 4x4x4
    h = h.reshape(h.shape[0], -1)       # 64
    save_io("k2_googlenet_bits", x, softmax(dense(h, Wd, bd)))


def k2_yolo_bits():
    """SpaceToDepth, the YOLO passthrough layer."""
    Wc = RNG.normal(0, 0.3, (3, 3, 3, 4))
    bc = RNG.normal(0, 0.05, (4,))
    Wd = RNG.normal(0, 0.2, (144, 5))
    bd = RNG.normal(0, 0.05, (5,))
    cfg = [
        {"class_name": "Conv2D", "config": {
            "name": "conv2d_1", "filters": 4, "kernel_size": [3, 3],
            "strides": [1, 1], "padding": "valid", "activation": "relu",
            "use_bias": True, "batch_input_shape": [None, 8, 8, 3]}},
        {"class_name": "SpaceToDepth", "config": {
            "name": "space_to_depth_1", "block_size": 2}},
        {"class_name": "Flatten", "config": {"name": "flatten_1"}},
        {"class_name": "Dense", "config": {
            "name": "dense_1", "units": 5, "activation": "softmax",
            "use_bias": True}},
    ]
    weights = {"conv2d_1": {"kernel": Wc, "bias": bc},
               "space_to_depth_1": {}, "flatten_1": {},
               "dense_1": {"kernel": Wd, "bias": bd}}
    write_k2(os.path.join(HERE, "k2_yolo_bits.h5"), cfg, weights,
             {"loss": "categorical_crossentropy"})
    x = RNG.normal(0, 1, (2, 8, 8, 3))
    h = relu(conv2d_valid(x, Wc, bc))   # 6x6x4
    h = space_to_depth(h)               # 3x3x16
    h = h.reshape(h.shape[0], -1)       # 144
    save_io("k2_yolo_bits", x, softmax(dense(h, Wd, bd)))


def k2_reshape_permute():
    """Non-flat Reshape + (2,1) Permute + GaussianNoise: the layers round
    2 imported silently-wrong (VERDICT r2 missing #1). GaussianNoise is
    inference-inert; Reshape/Permute change every downstream value, so
    the expected output catches a skip immediately."""
    Wc = RNG.normal(0, 0.3, (3, 3, 2, 3))
    bc = RNG.normal(0, 0.05, (3,))
    Wd = RNG.normal(0, 0.2, (8, 3))
    bd = RNG.normal(0, 0.05, (3,))
    cfg = [
        {"class_name": "Conv2D", "config": {
            "name": "conv2d_1", "filters": 3, "kernel_size": [3, 3],
            "strides": [1, 1], "padding": "valid", "activation": "relu",
            "use_bias": True, "batch_input_shape": [None, 6, 6, 2]}},
        {"class_name": "GaussianNoise", "config": {
            "name": "gaussian_noise_1", "stddev": 0.3}},
        {"class_name": "Reshape", "config": {
            "name": "reshape_1", "target_shape": [8, 6]}},
        {"class_name": "Permute", "config": {
            "name": "permute_1", "dims": [2, 1]}},
        {"class_name": "GlobalMaxPooling1D", "config": {
            "name": "global_max_pooling1d_1"}},
        {"class_name": "Dense", "config": {
            "name": "dense_1", "units": 3, "activation": "softmax",
            "use_bias": True}},
    ]
    weights = {"conv2d_1": {"kernel": Wc, "bias": bc},
               "gaussian_noise_1": {}, "reshape_1": {}, "permute_1": {},
               "global_max_pooling1d_1": {},
               "dense_1": {"kernel": Wd, "bias": bd}}
    write_k2(os.path.join(HERE, "k2_reshape_permute.h5"), cfg, weights,
             {"loss": "categorical_crossentropy"})
    x = RNG.normal(0, 1, (4, 6, 6, 2))
    h = relu(conv2d_valid(x, Wc, bc))       # 4x4x3
    h = h.reshape(h.shape[0], 8, 6)         # non-flat Reshape
    h = h.transpose(0, 2, 1)                # Permute (2,1) -> (6, 8)
    h = h.max(axis=1)                       # GlobalMaxPooling1D -> 8
    save_io("k2_reshape_permute", x, softmax(dense(h, Wd, bd)))


def k2_temporal():
    """ZeroPadding1D + dilated Conv1D + UpSampling1D."""
    F = 3
    Wc = RNG.normal(0, 0.3, (3, F, 5))
    bc = RNG.normal(0, 0.05, (5,))
    Wd = RNG.normal(0, 0.2, (5, 2))
    bd = RNG.normal(0, 0.05, (2,))
    cfg = [
        {"class_name": "ZeroPadding1D", "config": {
            "name": "zero_padding1d_1", "padding": 2,
            "batch_input_shape": [None, 10, F]}},
        {"class_name": "Conv1D", "config": {
            "name": "conv1d_1", "filters": 5, "kernel_size": [3],
            "strides": [1], "padding": "valid", "dilation_rate": [2],
            "activation": "relu", "use_bias": True}},
        {"class_name": "UpSampling1D", "config": {
            "name": "up_sampling1d_1", "size": 2}},
        {"class_name": "GlobalMaxPooling1D", "config": {
            "name": "global_max_pooling1d_1"}},
        {"class_name": "Dense", "config": {
            "name": "dense_1", "units": 2, "activation": "softmax",
            "use_bias": True}},
    ]
    weights = {"zero_padding1d_1": {},
               "conv1d_1": {"kernel": Wc, "bias": bc},
               "up_sampling1d_1": {}, "global_max_pooling1d_1": {},
               "dense_1": {"kernel": Wd, "bias": bd}}
    write_k2(os.path.join(HERE, "k2_temporal.h5"), cfg, weights,
             {"loss": "categorical_crossentropy"})
    x = RNG.normal(0, 1, (4, 10, F))
    h = np.pad(x, ((0, 0), (2, 2), (0, 0)))
    h = relu(conv1d_valid(h, Wc, bc, dilation=2))  # 14 -> 10
    h = np.repeat(h, 2, axis=1)
    h = h.max(axis=1)
    save_io("k2_temporal", x, softmax(dense(h, Wd, bd)))


def k2_selu_alpha_dropout():
    """SELU Dense + AlphaDropout (VERDICT r3 missing #4: the runtime
    AlphaDropout existed but a Keras model containing it would not
    import). AlphaDropout is inference-inert, so the expected output
    checks the rest of the stack imported around it."""
    Wd1 = RNG.normal(0, 0.3, (6, 10))
    bd1 = RNG.normal(0, 0.05, (10,))
    Wd2 = RNG.normal(0, 0.2, (10, 4))
    bd2 = RNG.normal(0, 0.05, (4,))
    cfg = [
        {"class_name": "Dense", "config": {
            "name": "dense_1", "units": 10, "activation": "selu",
            "use_bias": True, "batch_input_shape": [None, 6]}},
        {"class_name": "AlphaDropout", "config": {
            "name": "alpha_dropout_1", "rate": 0.3}},
        {"class_name": "Dense", "config": {
            "name": "dense_2", "units": 4, "activation": "softmax",
            "use_bias": True}},
    ]
    weights = {"dense_1": {"kernel": Wd1, "bias": bd1},
               "alpha_dropout_1": {},
               "dense_2": {"kernel": Wd2, "bias": bd2}}
    write_k2(os.path.join(HERE, "k2_selu_alpha_dropout.h5"), cfg, weights,
             {"loss": "categorical_crossentropy"})
    x = RNG.normal(0, 1, (5, 6))
    alpha, scale = 1.6732632423543772, 1.0507009873554805
    z = dense(x, Wd1, bd1)
    h = np.where(z > 0, scale * z, scale * alpha * (np.exp(z) - 1.0))
    save_io("k2_selu_alpha_dropout", x, softmax(dense(h, Wd2, bd2)))


def write_k1_model(path, layers, input_layers, output_layers,
                   layer_weights):
    """Keras-1 FUNCTIONAL file: class_name 'Model', layers carrying
    K1-style inbound_nodes [[["src", 0, 0], ...]]."""
    with h5py.File(path, "w") as f:
        f.attrs["keras_version"] = np.bytes_("1.2.2")
        f.attrs["model_config"] = np.bytes_(json.dumps(
            {"class_name": "Model", "config": {
                "name": "model_1", "layers": layers,
                "input_layers": [[n, 0, 0] for n in input_layers],
                "output_layers": [[n, 0, 0] for n in output_layers]}}))
        mw = f.create_group("model_weights")
        mw.attrs["layer_names"] = np.array(
            [np.bytes_(n) for n in layer_weights])
        for lname, weights in layer_weights.items():
            g = mw.create_group(lname)
            g.attrs["weight_names"] = np.array(
                [np.bytes_(wn) for wn in weights])
            for wn, arr in weights.items():
                g.create_dataset(wn, data=arr.astype(np.float32))


def k1_merge():
    """Keras-1 functional Model with the K1 ``Merge`` layer in two modes
    (sum + concat) — the 'Merge: resolved by mode' registry row gets
    real e2e coverage."""
    Wa = RNG.normal(0, 0.3, (6, 5))
    ba = RNG.normal(0, 0.05, (5,))
    Wb = RNG.normal(0, 0.3, (6, 5))
    bb = RNG.normal(0, 0.05, (5,))
    Wo = RNG.normal(0, 0.3, (10, 3))
    bo = RNG.normal(0, 0.05, (3,))
    layers = [
        {"class_name": "InputLayer", "name": "in_1",
         "config": {"name": "in_1",
                    "batch_input_shape": [None, 6]},
         "inbound_nodes": []},
        {"class_name": "Dense", "name": "dense_a",
         "config": {"name": "dense_a", "output_dim": 5,
                    "activation": "tanh", "bias": True},
         "inbound_nodes": [[["in_1", 0, 0]]]},
        {"class_name": "Dense", "name": "dense_b",
         "config": {"name": "dense_b", "output_dim": 5,
                    "activation": "sigmoid", "bias": True},
         "inbound_nodes": [[["in_1", 0, 0]]]},
        {"class_name": "Merge", "name": "merge_sum",
         "config": {"name": "merge_sum", "mode": "sum"},
         "inbound_nodes": [[["dense_a", 0, 0], ["dense_b", 0, 0]]]},
        {"class_name": "Merge", "name": "merge_cat",
         "config": {"name": "merge_cat", "mode": "concat"},
         "inbound_nodes": [[["merge_sum", 0, 0], ["dense_a", 0, 0]]]},
        {"class_name": "Dense", "name": "dense_out",
         "config": {"name": "dense_out", "output_dim": 3,
                    "activation": "linear", "bias": True},
         "inbound_nodes": [[["merge_cat", 0, 0]]]},
    ]
    weights = {"dense_a": {"dense_a_W": Wa, "dense_a_b": ba},
               "dense_b": {"dense_b_W": Wb, "dense_b_b": bb},
               "merge_sum": {}, "merge_cat": {},
               "dense_out": {"dense_out_W": Wo, "dense_out_b": bo}}
    write_k1_model(os.path.join(HERE, "k1_merge.h5"), layers,
                   ["in_1"], ["dense_out"], weights)
    x = RNG.normal(0, 1, (4, 6))
    a = np.tanh(dense(x, Wa, ba))
    b = 1.0 / (1.0 + np.exp(-dense(x, Wb, bb)))
    cat = np.concatenate([a + b, a], axis=1)
    save_io("k1_merge", x, dense(cat, Wo, bo))


if __name__ == "__main__":
    for fn in (k1_mlp, k1_cnn_atrous, k1_lstm, k1_merge,
               k2_googlenet_bits, k2_yolo_bits, k2_temporal,
               k2_reshape_permute, k2_selu_alpha_dropout):
        fn()
        print("wrote", fn.__name__)
