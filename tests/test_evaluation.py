"""Evaluation metric breadth + curve exports (VERDICT r4 missing #1/#2
— reference: Evaluation.java:96,1093,1119,1225,1287,1306 and
eval/curves/*.java). All goldens hand-computed, no sklearn."""

import json

import numpy as np
import pytest

from deeplearning4j_tpu.evaluation.curves import (
    Histogram, PrecisionRecallCurve, ReliabilityDiagram, RocCurve,
    from_json)
from deeplearning4j_tpu.evaluation.evaluation import (
    ROC, Evaluation, EvaluationCalibration, ROCBinary, ROCMultiClass)


def _eval_from_confusion(c, **kw):
    """Build an Evaluation whose confusion matrix equals ``c`` by
    feeding index labels/one-hot predictions pair by pair."""
    c = np.asarray(c)
    n = c.shape[0]
    ev = Evaluation(num_classes=n, **kw)
    labels, preds = [], []
    for a in range(n):
        for p in range(n):
            for _ in range(int(c[a, p])):
                labels.append(a)
                one = np.full(n, 0.01)
                one[p] = 0.9
                preds.append(one)
    ev.eval(np.array(labels), np.array(preds))
    return ev


class TestEvaluationMetrics:
    # confusion: rows=actual, cols=predicted
    #   [[2,1,0],
    #    [0,3,1],
    #    [1,0,2]]   → tp=[2,3,2] fp=[1,1,1] fn=[1,1,1] tn=[6,5,6]
    C = [[2, 1, 0], [0, 3, 1], [1, 0, 2]]

    def test_per_class_counts(self):
        ev = _eval_from_confusion(self.C)
        assert ev.true_positives() == {0: 2, 1: 3, 2: 2}
        assert ev.false_positives() == {0: 1, 1: 1, 2: 1}
        assert ev.false_negatives() == {0: 1, 1: 1, 2: 1}
        assert ev.true_negatives() == {0: 6, 1: 5, 2: 6}

    def test_precision_recall_macro_micro(self):
        ev = _eval_from_confusion(self.C)
        assert ev.accuracy() == pytest.approx(0.7)
        assert ev.precision(0) == pytest.approx(2 / 3)
        assert ev.precision(1) == pytest.approx(3 / 4)
        assert ev.recall(2) == pytest.approx(2 / 3)
        macro_p = (2 / 3 + 3 / 4 + 2 / 3) / 3
        assert ev.precision() == pytest.approx(macro_p)
        # micro-averaged P == R == accuracy for all-inclusive multiclass
        assert ev.precision(averaging="micro") == pytest.approx(0.7)
        assert ev.recall(averaging="micro") == pytest.approx(0.7)

    def test_fbeta_gmeasure(self):
        ev = _eval_from_confusion(self.C)
        # class 1: p == r == 0.75 → every F_beta == 0.75, G == 0.75
        assert ev.f_beta(2.0, 1) == pytest.approx(0.75)
        assert ev.f_beta(0.5, 1) == pytest.approx(0.75)
        assert ev.g_measure(1) == pytest.approx(0.75)
        # class 0: p == r == 2/3
        assert ev.f1(0) == pytest.approx(2 / 3)
        assert ev.g_measure() == pytest.approx((2 / 3 + 3 / 4 + 2 / 3) / 3)

    def test_matthews_correlation(self):
        ev = _eval_from_confusion(self.C)
        # class 0: (2*6 - 1*1)/sqrt(3*3*7*7) = 11/21
        assert ev.matthews_correlation(0) == pytest.approx(11 / 21)
        # class 1: (3*5 - 1*1)/sqrt(4*4*6*6) = 14/24
        assert ev.matthews_correlation(1) == pytest.approx(14 / 24)
        macro = (11 / 21 + 14 / 24 + 11 / 21) / 3
        assert ev.matthews_correlation() == pytest.approx(macro)

    def test_false_rates(self):
        ev = _eval_from_confusion(self.C)
        assert ev.false_positive_rate(0) == pytest.approx(1 / 7)
        assert ev.false_negative_rate(0) == pytest.approx(1 / 3)
        fpr = (1 / 7 + 1 / 6 + 1 / 7) / 3
        fnr = (1 / 3 + 1 / 4 + 1 / 3) / 3
        assert ev.false_alarm_rate() == pytest.approx((fpr + fnr) / 2)

    def test_binary_positive_class_mode(self):
        # 2-class: no-arg P/R/F1 report the positive class only
        # (reference's binaryPositiveClass=1 default)
        c = [[8, 2], [1, 9]]        # tp1=9 fp1=2 fn1=1
        ev = _eval_from_confusion(c)
        assert ev.precision() == pytest.approx(9 / 11)
        assert ev.recall() == pytest.approx(9 / 10)
        p, r = 9 / 11, 9 / 10
        assert ev.f1() == pytest.approx(2 * p * r / (p + r))
        # opting out macro-averages instead
        ev2 = _eval_from_confusion(c, binary_positive_class=None)
        assert ev2.precision() == pytest.approx((8 / 9 + 9 / 11) / 2)
        # an explicit averaging request overrides binary mode (the
        # reference's EvaluationAveraging overloads)
        assert ev.precision(averaging="micro") == pytest.approx(17 / 20)
        assert ev.precision(averaging="macro") == pytest.approx(
            (8 / 9 + 9 / 11) / 2)

    def test_top_n_accuracy(self):
        ev = Evaluation(top_n=2)
        labels = np.array([0, 1, 2, 2])
        preds = np.array([
            [0.6, 0.3, 0.1],     # top-1 correct
            [0.5, 0.4, 0.1],     # wrong, but class 1 is 2nd → top-2 ok
            [0.4, 0.35, 0.25],   # class 2 is 3rd → top-2 wrong
            [0.1, 0.2, 0.7],     # top-1 correct
        ])
        ev.eval(labels, preds)
        assert ev.accuracy() == pytest.approx(0.5)
        assert ev.top_n_accuracy() == pytest.approx(0.75)
        assert " Top 2 Accuracy:  0.7500" in ev.stats()

    def test_stats_table(self):
        ev = _eval_from_confusion(self.C, label_names=["a", "b", "c"])
        s = ev.stats()
        assert "Predictions labeled as a classified by model as b: 1 times" in s
        assert "Per-class Statistics" in s
        assert "macro-averaged" in s
        # class b row carries its per-class numbers
        assert "0.7500" in s

    def test_stats_never_predicted_warning(self):
        # class 1 is never predicted (tp=0, fp=0) → excluded from the
        # macro precision average, and stats() warns about it
        ev = _eval_from_confusion([[3, 0, 0], [2, 0, 0], [1, 0, 1]])
        assert ev.precision() == pytest.approx((3 / 6 + 1 / 1) / 2)
        assert "never predicted" in ev.stats()
        assert "never predicted" not in ev.stats(suppress_warnings=True)

    def test_empty_roc_curves(self):
        r = ROC()
        c = r.get_roc_curve()
        assert c.calculate_auc() == 0.0
        pr = r.get_precision_recall_curve()
        assert pr.total_count == 0


class TestRocCurves:
    # y=[1,0,1,0] scores=[0.9,0.8,0.7,0.6]
    Y = np.array([1.0, 0.0, 1.0, 0.0])
    S = np.array([0.9, 0.8, 0.7, 0.6])

    def _roc(self):
        r = ROC()
        r.eval(self.Y, self.S)
        return r

    def test_roc_curve_points(self):
        c = self._roc().get_roc_curve()
        np.testing.assert_allclose(c.threshold, [1.0, 0.9, 0.8, 0.7, 0.6])
        np.testing.assert_allclose(c.fpr, [0, 0, 0.5, 0.5, 1.0])
        np.testing.assert_allclose(c.tpr, [0, 0.5, 0.5, 1.0, 1.0])
        assert c.calculate_auc() == pytest.approx(0.75)
        # matches the accumulator's own AUC
        assert self._roc().calculate_auc() == pytest.approx(0.75)
        assert c.num_points() == 5
        assert c.get_threshold(1) == pytest.approx(0.9)
        assert c.get_true_positive_rate(3) == pytest.approx(1.0)
        assert "Area=0.75" in c.title

    def test_roc_curve_ties_collapse(self):
        r = ROC()
        r.eval(np.array([1, 0, 1, 0.0]), np.array([0.8, 0.8, 0.8, 0.2]))
        c = r.get_roc_curve()
        # one point for the tied 0.8 group + one for 0.2 + origin
        np.testing.assert_allclose(c.threshold, [1.0, 0.8, 0.2])
        np.testing.assert_allclose(c.tpr, [0, 1.0, 1.0])
        np.testing.assert_allclose(c.fpr, [0, 0.5, 1.0])

    def test_tied_scores_auc_order_independent(self):
        """Accumulator AUC runs on the tie-collapsed threshold points:
        tied scores must give the same (correct) AUC regardless of
        eval() insertion order, and agree with the curve export."""
        a = ROC(); a.eval(np.array([1, 0.0]), np.array([0.8, 0.8]))
        b = ROC(); b.eval(np.array([0, 1.0]), np.array([0.8, 0.8]))
        assert a.calculate_auc() == pytest.approx(0.5)
        assert b.calculate_auc() == pytest.approx(0.5)
        assert a.calculate_auc() == pytest.approx(
            a.get_roc_curve().calculate_auc())
        assert a.calculate_auprc() == pytest.approx(b.calculate_auprc())

    def test_precision_recall_curve(self):
        c = self._roc().get_precision_recall_curve()
        np.testing.assert_allclose(c.threshold, [0.6, 0.7, 0.8, 0.9, 1.0])
        np.testing.assert_allclose(c.precision, [0.5, 2 / 3, 0.5, 1, 1])
        np.testing.assert_allclose(c.recall, [1, 1, 0.5, 0.5, 0])
        np.testing.assert_array_equal(c.tp_count, [2, 2, 1, 1, 0])
        np.testing.assert_array_equal(c.fp_count, [2, 1, 1, 0, 0])
        np.testing.assert_array_equal(c.fn_count, [0, 0, 1, 1, 2])
        assert c.total_count == 4
        t, p, r = c.get_point_at_threshold(0.65)
        assert (t, p, r) == (0.7, pytest.approx(2 / 3), 1.0)
        t, p, r = c.get_point_at_precision(0.6)
        assert (t, r) == (0.7, 1.0)
        t, p, r = c.get_point_at_recall(1.0)
        assert p == pytest.approx(2 / 3)

    def test_curve_json_roundtrip(self):
        roc = self._roc()
        for curve in (roc.get_roc_curve(),
                      roc.get_precision_recall_curve()):
            back = from_json(curve.to_json())
            assert type(back) is type(curve)
            np.testing.assert_allclose(back.threshold, curve.threshold)
            np.testing.assert_allclose(back.get_x(), curve.get_x())
            np.testing.assert_allclose(back.get_y(), curve.get_y())

    def test_multiclass_and_binary_wrappers(self):
        labels = np.eye(3)[np.array([0, 1, 2, 1, 0])]
        preds = np.array([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1],
                          [0.2, 0.2, 0.6], [0.3, 0.5, 0.2],
                          [0.6, 0.3, 0.1]])
        m = ROCMultiClass()
        m.eval(labels, preds)
        c = m.get_roc_curve(0)
        assert isinstance(c, RocCurve)
        assert c.calculate_auc() == pytest.approx(m.calculate_auc(0))
        b = ROCBinary()
        b.eval(labels, preds)
        assert isinstance(b.get_precision_recall_curve(1),
                          PrecisionRecallCurve)


class TestCalibrationExports:
    def _cal(self):
        cal = EvaluationCalibration(reliability_bins=4,
                                    histogram_bins=4)
        rng = np.random.default_rng(7)
        p1 = rng.uniform(0, 1, 200)
        labels = np.stack([1 - (p1 > 0.5), (p1 > 0.5)], axis=1)
        preds = np.stack([1 - p1, p1], axis=1)
        cal.eval(labels, preds)
        return cal

    def test_empty_calibration_curves(self):
        """Curve exports on an un-eval'd accumulator return empty
        curves, mirroring the empty-ROC contract."""
        cal = EvaluationCalibration()
        assert len(cal.get_reliability_diagram().mean_predicted_value) == 0
        assert cal.get_residual_histogram().bin_counts.sum() == 0
        assert cal.get_probability_histogram().bin_counts.sum() == 0
        assert cal.expected_calibration_error() == 0.0

    def test_reliability_diagram_export(self):
        d = self._cal().get_reliability_diagram()
        assert isinstance(d, ReliabilityDiagram)
        assert d.num_points() > 0
        assert len(d.get_x()) == len(d.get_y())
        back = ReliabilityDiagram.from_json(d.to_json())
        np.testing.assert_allclose(back.mean_predicted_value,
                                   d.mean_predicted_value)

    def test_histogram_exports(self):
        cal = self._cal()
        h = cal.get_probability_histogram()
        assert isinstance(h, Histogram)
        assert h.n_bins == 4
        assert h.bin_counts.sum() == 400      # both columns of 200 rows
        np.testing.assert_allclose(h.get_bin_lower_bounds(),
                                   [0, 0.25, 0.5, 0.75])
        np.testing.assert_allclose(h.get_bin_mid_values(),
                                   [0.125, 0.375, 0.625, 0.875])
        hr = cal.get_residual_histogram()
        assert hr.bin_counts.sum() == 400
        back = from_json(h.to_json())
        np.testing.assert_array_equal(back.bin_counts, h.bin_counts)


class TestEvaluationTabE2E:
    def test_upload_and_fetch(self):
        import urllib.request
        from deeplearning4j_tpu.ui.server import UIServer
        from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage
        roc = ROC()
        roc.eval(TestRocCurves.Y, TestRocCurves.S)
        cal = TestCalibrationExports()._cal()
        srv = UIServer(port=0).attach(InMemoryStatsStorage()).start()
        try:
            srv.upload_evaluation(roc=roc, calibration=cal)
            with urllib.request.urlopen(srv.url + "/api/evaluation") as r:
                data = json.loads(r.read())
            assert data["auc"] == pytest.approx(0.75)
            assert data["roc"]["tpr"] == [0, 0.5, 0.5, 1.0, 1.0]
            assert data["pr"]["@type"] == "PrecisionRecallCurve"
            assert len(data["reliability"]["meanPredictedValueX"]) > 0
            assert sum(data["probability_histogram"]["binCounts"]) == 400
            # POST path (remote client uploading pre-built curves)
            body = json.dumps({"roc": roc.get_roc_curve().to_dict(),
                               "auc": 0.75}).encode()
            req = urllib.request.Request(
                srv.url + "/api/evaluation", data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req) as r:
                assert json.loads(r.read())["ok"]
            with urllib.request.urlopen(srv.url + "/api/evaluation") as r:
                data = json.loads(r.read())
            assert data["roc"]["threshold"][0] == 1.0
            # the dashboard page itself carries the Evaluation tab
            with urllib.request.urlopen(srv.url + "/") as r:
                page = r.read().decode()
            assert "evaluation" in page and "rocplot" in page
        finally:
            srv.stop()
