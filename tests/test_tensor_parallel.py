"""Tensor-parallel golden tests on the 8-device virtual CPU mesh.

The TP analog of the reference's "distributed == single machine" golden
test (dl4j-spark TestCompareParameterAveragingSparkVsSingleMachine.java:1):
a Megatron row/column-sharded train step must produce the same gradients
and parameter trajectory as the replicated model — GSPMD shardings change
the schedule, never the math.
"""

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu.datasets.dataset import ArrayDataSetIterator, DataSet
from deeplearning4j_tpu.datasets.fetchers import IrisDataSetIterator
from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.attention import TransformerEncoderBlock
from deeplearning4j_tpu.nn.layers.feedforward import (
    DenseLayer,
    EmbeddingSequenceLayer,
)
from deeplearning4j_tpu.nn.layers.output import OutputLayer, RnnOutputLayer
from deeplearning4j_tpu.ops.activations import Activation
from deeplearning4j_tpu.optimize.updaters import Adam, Sgd
from deeplearning4j_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, create_mesh
from deeplearning4j_tpu.parallel.tensor_parallel import plan_tp
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper, TrainingMode


def mlp_conf(lr=0.1, updater=None):
    return (NeuralNetConfiguration.Builder()
            .seed(7)
            .updater(updater or Sgd(lr))
            .list()
            .layer(DenseLayer(n_out=16, activation=Activation.TANH))
            .layer(DenseLayer(n_out=16, activation=Activation.RELU))
            .layer(OutputLayer(n_out=3))
            .set_input_type(InputType.feed_forward(4))
            .build())


def transformer_conf(vocab=12, width=8, classes=4):
    # SGD, not Adam: the loss is invariant to the K-part of bqkv (a key
    # bias shifts every score in a softmax row equally), so those grads
    # are mathematically zero and Adam would amplify each run's float
    # noise into sign(noise)*lr updates — a test artifact, not TP error
    return (NeuralNetConfiguration.Builder()
            .seed(3)
            .updater(Sgd(0.05))
            .list()
            .layer(EmbeddingSequenceLayer(n_in=vocab, n_out=width))
            .layer(TransformerEncoderBlock(n_out=width, n_heads=2))
            .layer(TransformerEncoderBlock(n_out=width, n_heads=2))
            .layer(RnnOutputLayer(n_out=classes))
            .set_input_type(InputType.recurrent(1, 6))
            .build())


def _assert_trees_close(a, b, rtol=2e-4, atol=2e-5):
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=rtol, atol=atol)


def test_plan_pairs_consecutive_dense():
    """layer_0 opens a column pair, layer_1 closes it row-parallel, the
    3-class output layer (not divisible by 4) stays replicated."""
    model = MultiLayerNetwork(mlp_conf()).init()
    mesh = create_mesh({DATA_AXIS: 2, MODEL_AXIS: 4})
    plan = plan_tp(model, mesh)
    sh = plan.param_shardings
    assert sh["layer_0"]["W"].spec == P(None, MODEL_AXIS)
    assert sh["layer_0"]["b"].spec == P(MODEL_AXIS)
    assert sh["layer_1"]["W"].spec == P(MODEL_AXIS, None)
    assert sh["layer_1"]["b"].spec == P()
    assert sh["layer_2"]["W"].spec == P()
    assert plan.act_kinds["layer_0"] == "sharded"
    assert plan.act_kinds["layer_1"] == "replicated"


def test_plan_transformer_block_megatron_layout():
    model = MultiLayerNetwork(transformer_conf()).init()
    mesh = create_mesh({DATA_AXIS: 4, MODEL_AXIS: 2})
    plan = plan_tp(model, mesh)
    blk = plan.param_shardings["layer_1"]
    assert blk["attn"]["Wqkv"].spec == P(None, MODEL_AXIS)
    assert blk["attn"]["Wo"].spec == P(MODEL_AXIS, None)
    assert blk["W1"].spec == P(None, MODEL_AXIS)
    assert blk["W2"].spec == P(MODEL_AXIS, None)
    assert blk["ln1"]["gamma"].spec == P()
    # final 4-class output layer: Megatron LM-head (class-sharded logits)
    assert plan.param_shardings["layer_3"]["W"].spec == P(None, MODEL_AXIS)


def test_tp_training_matches_replicated_mlp():
    """3 epochs of TP-sharded SGD == 3 epochs on the replicated model."""
    it = IrisDataSetIterator(batch_size=64)

    single = MultiLayerNetwork(mlp_conf()).init()
    single.fit(it, epochs=3)

    tp_model = MultiLayerNetwork(mlp_conf()).init()
    mesh = create_mesh({DATA_AXIS: 2, MODEL_AXIS: 4})
    w = (ParallelWrapper.builder(tp_model)
         .mesh(mesh)
         .training_mode(TrainingMode.SHARED_GRADIENTS)
         .tensor_parallel()
         .build())
    w.fit(it, epochs=3)

    # the TP model's params live sharded on the mesh; values must match
    _assert_trees_close(single.params, tp_model.params)


def test_tp_grads_match_replicated_transformer():
    """One Adam train step on a 2-block transformer: TP grads (via the
    post-step params) == replicated grads, with head-parallel attention
    and column/row FFN engaged."""
    rng = np.random.default_rng(0)
    n, t, vocab, classes = 16, 6, 12, 4
    feats = rng.integers(0, vocab, (n, t)).astype(np.float32)
    labels = np.zeros((n, t, classes), np.float32)
    labels[np.arange(n)[:, None], np.arange(t)[None, :],
           rng.integers(0, classes, (n, t))] = 1.0
    it = ArrayDataSetIterator(DataSet(feats, labels), batch_size=n)

    single = MultiLayerNetwork(transformer_conf()).init()
    single.fit(it, epochs=1)
    it.reset()

    tp_model = MultiLayerNetwork(transformer_conf()).init()
    mesh = create_mesh({DATA_AXIS: 4, MODEL_AXIS: 2})
    w = (ParallelWrapper.builder(tp_model)
         .mesh(mesh)
         .tensor_parallel()
         .build())
    w.fit(it, epochs=1)

    _assert_trees_close(single.params, tp_model.params,
                        rtol=5e-4, atol=5e-5)


def test_tp_computation_graph_imported_bert():
    """TP on a ComputationGraph (the imported-BERT path): per-node specs
    shard block internals; one train step runs and matches the
    replicated graph's loss."""
    keras = pytest.importorskip("keras")
    from deeplearning4j_tpu.datasets.dataset import MultiDataSet
    from deeplearning4j_tpu.modelimport.bert import (
        example_inputs, import_bert_base)
    from deeplearning4j_tpu.parallel.tensor_parallel import plan_tp

    model, _ = import_bert_base(seq_len=8, vocab=32, width=16,
                                n_layers=2, n_heads=2, ffn=32, max_len=8)
    mesh = create_mesh({DATA_AXIS: 4, MODEL_AXIS: 2})
    plan = plan_tp(model, mesh)
    blk = plan.param_shardings["l0_mha"]
    assert blk["Wqkv"].spec == P(None, MODEL_AXIS)
    assert blk["Wo"].spec == P(MODEL_AXIS, None)

    from deeplearning4j_tpu.parallel.tensor_parallel import (
        shard_train_state)
    shard_train_state(model, plan)
    model._tp_plan = plan
    ids, pos = example_inputs(8, 8, 32)
    y_ref = np.asarray(model.output(ids, pos))
    assert np.isfinite(y_ref).all()


def test_tp_output_unchanged_after_training():
    """Inference through the TP-sharded model matches the replicated
    model bit-for-bit on logits (same params, sharded layout)."""
    it = IrisDataSetIterator(batch_size=32)
    tp_model = MultiLayerNetwork(mlp_conf()).init()
    mesh = create_mesh({DATA_AXIS: 2, MODEL_AXIS: 4})
    w = (ParallelWrapper.builder(tp_model).mesh(mesh)
         .tensor_parallel().build())
    w.fit(it, epochs=1)

    x = np.random.default_rng(1).normal(size=(8, 4)).astype(np.float32)
    y_tp = np.asarray(tp_model.output(x))
    # pull params to host, rebuild a plain model, compare
    plain = MultiLayerNetwork(mlp_conf()).init()
    host_params = jax.tree_util.tree_map(np.asarray, tp_model.params)
    plain.train_state = plain.train_state._replace(
        params=jax.tree_util.tree_map(lambda a: a, host_params))
    plain._tp_plan = None
    y_plain = np.asarray(plain.output(x))
    np.testing.assert_allclose(y_tp, y_plain, rtol=1e-5, atol=1e-6)
