"""Online learning subsystem tests (ISSUE 10).

Promotion-gate edge cases (a worse/equal/NaN/unscoreable candidate
NEVER reaches serving), bitwise param rollback, the param swap racing
in-flight requests, the stream's serde/holdout/malformed handling, and
broker reconnect with bounded backoff.
"""

import math
import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.observe.registry import MetricsRegistry
from deeplearning4j_tpu.online import (
    OnlineLearner,
    OnlineServing,
    PromotionController,
    RegressionSentinel,
    SampleStreamIterator,
    pack_samples,
    publish_samples,
    unpack_samples,
)
from deeplearning4j_tpu.parallel.fleet import FleetRouter
from deeplearning4j_tpu.streaming.broker import (
    InProcessTransport,
    NDArrayPublisher,
    TcpTransport,
)

N_IN = 5
N_OUT = 3


def _tiny_model(seed: int = 1):
    from deeplearning4j_tpu.models.multi_layer_network import (
        MultiLayerNetwork)
    from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.inputs import InputType
    from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
    from deeplearning4j_tpu.nn.layers.output import OutputLayer
    from deeplearning4j_tpu.ops.losses import LossFunction
    from deeplearning4j_tpu.optimize.updaters import Adam
    conf = (NeuralNetConfiguration.Builder().seed(seed)
            .updater(Adam(1e-2)).list()
            .layer(DenseLayer(n_out=8))
            .layer(OutputLayer(n_out=N_OUT, loss=LossFunction.MCXENT))
            .set_input_type(InputType.feed_forward(N_IN)).build())
    return MultiLayerNetwork(conf).init()


def _batch(rng, n):
    x = rng.normal(size=(n, N_IN)).astype(np.float32)
    y = np.eye(N_OUT, dtype=np.float32)[rng.integers(0, N_OUT, size=n)]
    return x, y


def _router(model, **kw):
    reg = kw.pop("registry", None) or MetricsRegistry()
    router = FleetRouter(registry=reg)
    router.add_pool("m", model, version="v0", feature_shape=(N_IN,),
                    batch_limit=8, **kw)
    return router


def _host_params(router):
    return router.pool("m").engines[0].committed_host()


def _trees_equal(a, b):
    import jax
    la, ta = jax.tree_util.tree_flatten(a)
    lb, tb = jax.tree_util.tree_flatten(b)
    return ta == tb and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb))


class _ScriptedCalc:
    """ScoreCalculator stand-in: returns scripted scores in call
    order (candidate first, then the lazy active baseline)."""
    minimize_score = True

    def __init__(self, scores):
        self.scores = list(scores)
        self.calls = 0

    def calculate_score(self, model):
        self.calls += 1
        s = self.scores.pop(0)
        if isinstance(s, Exception):
            raise s
        return s


def _stream_with_holdout(n_examples=8):
    rng = np.random.default_rng(0)
    s = SampleStreamIterator(InProcessTransport(), "t",
                            registry=MetricsRegistry())
    s._add_holdout(DataSet(*_batch(rng, n_examples)))
    return s


def _controller(router, calc, stream=None, model=None, **kw):
    model = model if model is not None else _tiny_model(seed=3)
    learner = OnlineLearner(
        model, stream if stream is not None else _stream_with_holdout())
    return PromotionController(
        router, "m", learner, calc, model.clone(),
        registry=MetricsRegistry(), **kw)


# ---------------------------------------------------------------------------
# stream serde / holdout / malformed
# ---------------------------------------------------------------------------

class TestStream:
    def test_pack_unpack_roundtrip_ragged_and_4d(self):
        rng = np.random.default_rng(1)
        for shape in ((7, N_IN), (3, 4, 4, 2)):
            x = rng.normal(size=shape).astype(np.float32)
            y = np.eye(3, dtype=np.float32)[
                rng.integers(0, 3, size=shape[0])]
            packed, key = pack_samples(x, y)
            ds = unpack_samples(packed, key)
            np.testing.assert_array_equal(ds.features, x)
            np.testing.assert_array_equal(ds.labels, y)

    def test_unpack_rejects_key_geometry_disagreement(self):
        packed, _ = pack_samples(
            np.zeros((2, N_IN), np.float32), np.zeros((2, 3), np.float32))
        with pytest.raises(ValueError):
            unpack_samples(packed, str(N_IN + 3))   # eats the labels
        with pytest.raises(ValueError):
            unpack_samples(packed, "not-a-shape")

    def test_holdout_divert_malformed_skip_and_bound(self):
        transport = InProcessTransport()
        rng = np.random.default_rng(2)
        reg = MetricsRegistry()
        stream = SampleStreamIterator(
            transport, "t", holdout_every=3, holdout_max=8,
            max_batches=9, registry=reg)
        for _ in range(4):
            publish_samples(transport, "t", *_batch(rng, 4))
        # one mid-stream frame whose key disagrees with its geometry:
        # must be counted + skipped, not kill the iterator (malformed
        # frames don't count against max_batches)
        NDArrayPublisher(transport, "t").publish(
            np.zeros((2, 4), np.float32), key="999")
        for _ in range(5):
            publish_samples(transport, "t", *_batch(rng, 4))
        trained = list(stream)
        # 9 consumed batches, every 3rd diverted to holdout
        assert len(trained) == 6
        assert stream.batches_consumed == 9
        assert stream.malformed == 1
        c = reg.get_metric("dl4j_online_stream_malformed_total")
        assert c.get(topic="t") == 1.0
        # reservoir bounded by examples (8): 3 diverted 4-example
        # batches, oldest evicted
        assert stream.holdout_examples == 8
        snap = stream.holdout_snapshot()
        assert snap.num_examples() == 8
        # the live view re-batches the current reservoir
        view = list(stream.holdout_view(batch_size=3))
        assert sum(b.num_examples() for b in view) == 8


# ---------------------------------------------------------------------------
# promotion gate edge cases
# ---------------------------------------------------------------------------

class TestPromotionGate:
    def _run_rejection(self, scores, expect_reason):
        router = _router(_tiny_model())
        before_version = router.pool("m").active_version
        before = _host_params(router)
        ctl = _controller(router, _ScriptedCalc(scores))
        d = ctl.run_once()
        assert d.promoted is False
        assert d.reason == expect_reason
        # the active params are untouched, bitwise
        assert router.pool("m").active_version == before_version
        assert _trees_equal(before, _host_params(router))
        assert ctl.promotions == 0 and ctl.rejections == 1
        router.shutdown()

    def test_worse_candidate_never_promotes(self):
        # candidate scored first (2.0), then the active baseline (1.0)
        self._run_rejection([2.0, 1.0], "worse")

    def test_equal_candidate_never_promotes(self):
        self._run_rejection([1.0, 1.0], "equal")

    def test_within_min_delta_rejected_as_equal(self):
        router = _router(_tiny_model())
        ctl = _controller(router, _ScriptedCalc([0.95, 1.0]),
                          min_delta=0.1)
        d = ctl.run_once()
        assert (d.promoted, d.reason) == (False, "equal")
        router.shutdown()

    def test_nan_candidate_never_promotes(self):
        # NaN rejects before the active baseline is even scored
        self._run_rejection([float("nan")], "nan")

    def test_inf_candidate_never_promotes(self):
        self._run_rejection([math.inf], "nan")

    def test_scoring_error_never_promotes(self):
        self._run_rejection([RuntimeError("holdout exploded")], "error")

    def test_no_holdout_never_promotes(self):
        router = _router(_tiny_model())
        ctl = _controller(router, _ScriptedCalc([]),
                          stream=_stream_with_holdout(0))
        # empty reservoir: candidate exists but nothing to score on
        ctl.learner.stream._holdout.clear()
        ctl.learner.stream._holdout_examples = 0
        d = ctl.run_once()
        assert (d.promoted, d.reason) == (False, "no_holdout")
        router.shutdown()

    def test_improved_candidate_promotes_and_arms_sentinel(self):
        router = _router(_tiny_model())
        sentinel = RegressionSentinel(router, "m",
                                      registry=MetricsRegistry())
        ctl = _controller(router, _ScriptedCalc([0.5, 1.0]))
        ctl.sentinel = sentinel
        d = ctl.run_once()
        assert d.promoted and d.reason == "improved"
        assert router.pool("m").active_version == d.version
        assert sentinel.watching
        assert ctl.active_score == 0.5
        router.shutdown()

    def test_score_budget_is_advisory(self):
        router = _router(_tiny_model())

        class SlowCalc(_ScriptedCalc):
            def calculate_score(self, model):
                time.sleep(0.05)
                return super().calculate_score(model)

        ctl = _controller(router, SlowCalc([2.0, 1.0]),
                          score_budget_s=0.001)
        d = ctl.run_once()
        assert d.over_budget is True
        assert d.reason == "worse"       # flagged, never fatal
        router.shutdown()


# ---------------------------------------------------------------------------
# hot swap + rollback
# ---------------------------------------------------------------------------

class TestSwapRollback:
    def test_rollback_restores_bitwise_params(self):
        m2 = _tiny_model(seed=99)
        router = _router(_tiny_model())
        before_params, before_mstate = _host_params(router)
        import jax
        router.promote_params(
            "m",
            jax.tree_util.tree_map(np.asarray, m2.train_state.params),
            jax.tree_util.tree_map(np.asarray,
                                   m2.train_state.model_state),
            version="v1")
        assert router.pool("m").active_version == "v1"
        assert not _trees_equal(before_params, _host_params(router)[0])
        router.rollback_params("m")
        after_params, after_mstate = _host_params(router)
        assert _trees_equal(before_params, after_params)
        assert _trees_equal(before_mstate, after_mstate)
        assert router.pool("m").active_version == "v0"
        # the whole dance paid zero recompiles
        router.assert_warm()
        router.shutdown()

    def test_rollback_without_standby_raises(self):
        router = _router(_tiny_model())
        with pytest.raises(RuntimeError):
            router.rollback_params("m")
        router.shutdown()

    def test_structural_mismatch_rejected_before_commit(self):
        router = _router(_tiny_model())
        before = _host_params(router)
        with pytest.raises(ValueError):
            router.promote_params(
                "m", {"nope": np.zeros(3, np.float32)}, {})
        assert _trees_equal(before, _host_params(router))
        router.shutdown()

    def test_swap_races_inflight_futures(self):
        """Requests submitted concurrently with promote/rollback must
        ALL complete (old or new params, never an error / hang), and
        the engines stay warm."""
        m2 = _tiny_model(seed=7)
        router = _router(_tiny_model())
        import jax
        p2 = jax.tree_util.tree_map(np.asarray, m2.train_state.params)
        s2 = jax.tree_util.tree_map(np.asarray,
                                    m2.train_state.model_state)
        rng = np.random.default_rng(0)
        x = rng.normal(size=(4, N_IN)).astype(np.float32)
        stop = threading.Event()
        errors, done = [], [0] * 4

        def client(i):
            while not stop.is_set():
                try:
                    fut = router.submit(x, model="m")
                    out = np.asarray(fut.result(timeout=10))
                    assert out.shape == (4, N_OUT)
                    done[i] += 1
                except Exception as e:      # pragma: no cover
                    errors.append(e)
                    return

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        try:
            deadline = time.time() + 30
            for _ in range(10):
                router.promote_params("m", p2, s2, version="vX")
                router.rollback_params("m")
                time.sleep(0.02)
            # every client must land at least one request THROUGH the
            # swap storm before we stop the presses
            while not all(n > 0 for n in done) and not errors \
                    and time.time() < deadline:
                router.promote_params("m", p2, s2, version="vX")
                router.rollback_params("m")
                time.sleep(0.02)
        finally:
            stop.set()
            for t in threads:
                t.join(10)
        assert not errors
        assert all(n > 0 for n in done)
        router.assert_warm()
        router.shutdown()


# ---------------------------------------------------------------------------
# sentinel
# ---------------------------------------------------------------------------

class TestSentinel:
    def test_score_regression_rolls_back_bitwise(self):
        router = _router(_tiny_model())
        good = _host_params(router)
        rolled = []
        sentinel = RegressionSentinel(
            router, "m", score_fn=lambda: 5.0, score_delta=0.5,
            on_rollback=rolled.append, registry=MetricsRegistry())
        ctl = _controller(router, _ScriptedCalc([0.5, 1.0]))
        ctl.sentinel = sentinel
        d = ctl.run_once()
        assert d.promoted
        # live score 5.0 vs pre-swap baseline 1.0: regression
        assert sentinel.check() == "score"
        assert rolled == ["score"]
        assert _trees_equal(good[0], _host_params(router)[0])
        assert router.pool("m").active_version == "v0"
        router.shutdown()

    def test_survived_window_retires_baseline(self):
        router = _router(_tiny_model())
        sentinel = RegressionSentinel(
            router, "m", score_fn=lambda: 0.4, score_delta=0.0,
            window_s=0.0, registry=MetricsRegistry())
        ctl = _controller(router, _ScriptedCalc([0.5, 1.0]))
        ctl.sentinel = sentinel
        assert ctl.run_once().promoted
        time.sleep(0.01)
        # live score fine, window elapsed: promotion stands, idle
        assert sentinel.check() is None
        assert not sentinel.watching
        assert sentinel.rollbacks == 0
        router.shutdown()

    def test_nan_live_score_rolls_back(self):
        router = _router(_tiny_model())
        sentinel = RegressionSentinel(
            router, "m", score_fn=lambda: float("nan"),
            registry=MetricsRegistry())
        ctl = _controller(router, _ScriptedCalc([0.5, 1.0]))
        ctl.sentinel = sentinel
        assert ctl.run_once().promoted
        assert sentinel.check() == "nan"
        router.shutdown()


# ---------------------------------------------------------------------------
# broker reconnect
# ---------------------------------------------------------------------------

class TestBrokerReconnect:
    def test_reconnect_after_server_restart(self):
        from deeplearning4j_tpu.streaming.broker import NDArrayConsumer
        srv = TcpTransport().serve()
        port = srv.port
        reg = MetricsRegistry()
        client = TcpTransport(port=port, backoff_base_s=0.01,
                              backoff_max_s=0.05, registry=reg)
        a = np.arange(4, dtype=np.float32)
        NDArrayPublisher(client, "x").publish(a, key="k")
        consumer = TcpTransport(port=port)
        assert NDArrayConsumer(consumer, "x").poll(timeout=2) is not None
        consumer.close()
        # restart the broker on the same port; the client's half-open
        # connection dies with it (simulated with a local close — the
        # server's RST would surface as the same OSError)
        srv.close()
        srv2 = TcpTransport(port=port).serve()
        client._sock.close()
        try:
            NDArrayPublisher(client, "x").publish(a, key="k2")
            consumer2 = TcpTransport(port=port)
            msg = NDArrayConsumer(consumer2, "x").poll(timeout=2)
            assert msg is not None and msg.key == "k2"
            consumer2.close()
            assert client.reconnects >= 1
            c = reg.get_metric("dl4j_stream_reconnects_total")
            assert c.get(endpoint=f"127.0.0.1:{port}",
                         op="publish") >= 1.0
        finally:
            client.close()
            srv2.close()

    def test_retries_exhausted_raises_connection_error(self):
        # nothing listens here; bounded backoff then a clear error
        client = TcpTransport(port=1, max_retries=2,
                              backoff_base_s=0.005, backoff_max_s=0.01,
                              registry=MetricsRegistry())
        t0 = time.perf_counter()
        with pytest.raises(ConnectionError, match="2 reconnect"):
            client.publish("x", b"payload")
        assert time.perf_counter() - t0 < 5.0
        assert client.reconnects == 2

    def test_reconnect_disabled_fails_fast(self):
        client = TcpTransport(port=1, reconnect=False,
                              registry=MetricsRegistry())
        with pytest.raises(ConnectionError):
            client.publish("x", b"payload")
        assert client.reconnects == 0


# ---------------------------------------------------------------------------
# end to end (tiny model, in-process broker)
# ---------------------------------------------------------------------------

class TestOnlineServingEndToEnd:
    def test_learn_promote_serve_loop(self):
        transport = InProcessTransport()
        online = OnlineServing(
            _tiny_model(), transport, topic="train", model_name="m",
            feature_shape=(N_IN,), batch_limit=8, holdout_every=4,
            holdout_batch=8, registry=MetricsRegistry())
        rng = np.random.default_rng(5)
        # a learnable mapping: labels depend on the features
        w = rng.normal(size=(N_IN, N_OUT)).astype(np.float32)
        def batch(n, g):
            x = g.normal(size=(n, N_IN)).astype(np.float32)
            y = np.eye(N_OUT, dtype=np.float32)[np.argmax(x @ w, axis=1)]
            return x, y
        online.start(background_promotion=False)
        # steady publisher: the promoter's snapshot handshake is
        # serviced BETWEEN steps, so the learner must keep stepping
        pub_stop = threading.Event()

        def feed():
            prng = np.random.default_rng(6)
            while not pub_stop.is_set():
                publish_samples(transport, "train",
                                *batch(int(prng.integers(2, 9)), prng))
                pub_stop.wait(0.02)

        pub = threading.Thread(target=feed, daemon=True)
        pub.start()
        try:
            deadline = time.time() + 60
            while (online.learner.iterations < 30
                   or online.stream.holdout_examples == 0):
                assert time.time() < deadline, online.stats()
                assert online.learner.alive, online.learner.error
                time.sleep(0.1)
            # serving works while training
            out = np.asarray(online.output(
                rng.normal(size=(3, N_IN)).astype(np.float32)))
            assert out.shape == (3, N_OUT)
            d = None
            while time.time() < deadline:
                d = online.promoter.run_once()
                if d.promoted:
                    break
                time.sleep(0.5)
            assert d is not None and d.promoted, d
            assert online.pool.active_version == d.version
            assert online.sentinel.check() is None   # good swap stands
            online.router.assert_warm()
            stats = online.stats()
            assert stats["promotion"]["promotions"] == 1
            assert stats["stream"]["holdout_examples"] > 0
        finally:
            pub_stop.set()
            pub.join(5)
            online.stop()
