"""Worker for the multihost chaos / uneven-device tests
(tests/test_multihost_chaos.py). Launched as

  python multihost_chaos_worker.py <rank> <nprocs> <port> <outdir> \
      <devices_csv> <die_rank> <die_step> <epochs>

``devices_csv`` lists EVERY rank's device count (e.g. "2,1,1"), so each
process can size its proportional slice of the global batch.

Each process owns ``local_devices`` virtual CPU devices (UNEVEN counts
across ranks are the point — a 2+1+1 layout is the honest simulation of
heterogeneous hosts). Training runs through ElasticTrainer with
frequent COMMITTED checkpoints; rank ``die_rank`` (if >= 0) dies
abruptly (os._exit) at iteration ``die_step`` — mid-fit, after at least
one checkpoint committed. Survivors detect the broken collective,
record it, and exit cleanly; the relaunched (smaller) job resumes from
the last COMMITTED checkpoint and reshards onto its new mesh —
the reference's recovery semantics (Spark recompute + driver-held
params, SURVEY §5.3) re-expressed as restore-and-reshard.
"""

import json
import os
import sys

rank, nprocs, port, outdir, devices_csv, die_rank, die_step, epochs = (
    int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4],
    sys.argv[5], int(sys.argv[6]), int(sys.argv[7]), int(sys.argv[8]))
counts = [int(c) for c in devices_csv.split(",")]
local_devices = counts[rank]

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + f" --xla_force_host_platform_device_count={local_devices}")

import jax

jax.config.update("jax_platforms", "cpu")


def main():
    from deeplearning4j_tpu.parallel.mesh import initialize_distributed
    initialize_distributed(f"127.0.0.1:{port}", num_processes=nprocs,
                           process_id=rank)
    assert jax.local_device_count() == local_devices

    import numpy as np
    from deeplearning4j_tpu.datasets.dataset import (
        ArrayDataSetIterator, DataSet)
    from deeplearning4j_tpu.models.multi_layer_network import (
        MultiLayerNetwork)
    from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.inputs import InputType
    from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
    from deeplearning4j_tpu.nn.layers.output import OutputLayer
    from deeplearning4j_tpu.ops.activations import Activation
    from deeplearning4j_tpu.optimize.listeners import TrainingListener
    from deeplearning4j_tpu.optimize.updaters import Sgd
    from deeplearning4j_tpu.parallel.checkpoint import ElasticTrainer
    from deeplearning4j_tpu.parallel.mesh import DATA_AXIS, create_mesh
    from deeplearning4j_tpu.parallel.wrapper import (
        ParallelWrapper, TrainingMode)

    n_dev = jax.device_count()
    conf = (NeuralNetConfiguration.Builder().seed(1).updater(Sgd(0.05))
            .list()
            .layer(DenseLayer(n_out=16, activation=Activation.TANH))
            .layer(OutputLayer(n_out=3))
            .set_input_type(InputType.feed_forward(4)).build())
    model = MultiLayerNetwork(conf).init()
    mesh = create_mesh({DATA_AXIS: n_dev})

    # fixed GLOBAL batch of 64 rows; this process feeds the contiguous
    # slice proportional to its device share (uneven across ranks)
    rng = np.random.default_rng(0)
    gx = rng.normal(size=(64, 4)).astype(np.float32)
    gy = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 64)]
    per_row = 64 // n_dev
    sizes = [per_row * counts[r] for r in range(nprocs)]
    off = sum(sizes[:rank])
    lx = gx[off:off + sizes[rank]]
    ly = gy[off:off + sizes[rank]]

    w = (ParallelWrapper.builder(model).mesh(mesh)
         .training_mode(TrainingMode.SHARED_GRADIENTS).build())

    ckpt_dir = os.path.join(outdir, "ckpt")
    trainer = ElasticTrainer(model, ckpt_dir, checkpoint_every=2,
                             mesh=mesh)
    resumed = trainer.resume()
    start_iter = int(model.train_state.iteration)

    class _Killer(TrainingListener):
        def iteration_done(self, m, iteration, epoch, loss, etl_ms, n):
            if rank == die_rank and die_step >= 0 and \
                    iteration >= die_step:
                sys.stdout.flush()
                os._exit(17)   # abrupt death mid-fit, no cleanup

    if die_rank >= 0:
        model.add_listeners(_Killer())

    it = ArrayDataSetIterator(DataSet(lx, ly), batch_size=sizes[rank],
                              shuffle=False)

    # ElasticTrainer saves through the model fit loop; the wrapper owns
    # the distributed step, so attach the trainer's saver semantics by
    # checkpointing every N wrapper iterations via a listener
    class _Saver(TrainingListener):
        def __init__(self):
            self.last = start_iter

        def iteration_done(self, m, iteration, epoch, loss, etl_ms, n):
            if iteration - self.last >= trainer.checkpoint_every:
                from deeplearning4j_tpu.parallel.checkpoint import (
                    save_sharded)
                save_sharded(m.train_state, ckpt_dir)
                trainer._prune()
                self.last = int(iteration)

    model.add_listeners(_Saver())

    try:
        w.fit(it, epochs=epochs)
    except BaseException as e:     # a dead peer breaks the collective
        with open(os.path.join(outdir, f"survivor_{rank}.json"),
                  "w") as f:
            json.dump({"rank": rank, "detected": True,
                       "error": type(e).__name__,
                       "message": str(e)[:500],
                       "iteration": int(model.train_state.iteration)}, f)
        print(f"rank {rank}: peer failure detected ({type(e).__name__}: "
              f"{str(e)[:300]})", flush=True)
        return

    params = jax.tree_util.tree_map(np.asarray, model.params)
    flat = np.concatenate([l.ravel() for l in
                           jax.tree_util.tree_leaves(params)])
    with open(os.path.join(outdir, f"result_{rank}.json"), "w") as f:
        json.dump({"rank": rank, "loss": float(model._last_loss),
                   "param_sum": float(flat.sum()),
                   "resumed": bool(resumed),
                   "start_iteration": start_iter,
                   "final_iteration": int(model.train_state.iteration),
                   "n_devices": n_dev,
                   "local_batch": int(sizes[rank])}, f)
    print(f"rank {rank} done", flush=True)


if __name__ == "__main__":
    main()
