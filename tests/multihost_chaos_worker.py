"""Worker for the multihost chaos / uneven-device tests
(tests/test_multihost_chaos.py). Launched as

  python multihost_chaos_worker.py <rank> <nprocs> <port> <outdir> \
      <devices_csv> <die_rank> <die_step> <epochs> [mode]

``devices_csv`` lists EVERY rank's device count (e.g. "2,1,1"), so each
process can size its proportional slice of the global batch.

``mode`` is "dp" (default — the 1D data-parallel MLP job) or
"3d:DPxTPxPP" (e.g. "3d:2x2x1") — a composed dp×tp×pp
PipelinedTransformerLM job whose checkpoints restore across DIFFERENT
3D layouts via restore_sharded's explicit param_shardings path.

Each process owns ``local_devices`` virtual CPU devices (UNEVEN counts
across ranks are the point — a 2+1+1 layout is the honest simulation of
heterogeneous hosts). Training runs with frequent COMMITTED
checkpoints; rank ``die_rank`` (if >= 0) dies abruptly (os._exit) at
iteration ``die_step`` — mid-fit, after at least one checkpoint
committed. Survivors detect the broken collective through the
CollectiveWatchdog (heartbeat classification: dead peer vs straggler),
write the peer_loss forensics + resumable marker, and exit cleanly; the
relaunched (smaller/reshaped) job resumes from the last COMMITTED
checkpoint and reshards onto its new mesh — the reference's recovery
semantics (Spark recompute + driver-held params, SURVEY §5.3)
re-expressed as restore-and-reshard.
"""

import json
import os
import sys

rank, nprocs, port, outdir, devices_csv, die_rank, die_step, epochs = (
    int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4],
    sys.argv[5], int(sys.argv[6]), int(sys.argv[7]), int(sys.argv[8]))
mode = sys.argv[9] if len(sys.argv) > 9 else "dp"
counts = [int(c) for c in devices_csv.split(",")]
local_devices = counts[rank]

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + f" --xla_force_host_platform_device_count={local_devices}")

import jax

jax.config.update("jax_platforms", "cpu")


def _make_watchdog(model, ckpt_dir):
    """Shared watchdog arming: heartbeats in outdir/hb, peer-loss
    markers + emergency checkpoint next to the training checkpoints.
    exit_on_loss covers the silent-hang path; the raise path goes
    through on_collective_error in the except handlers below."""
    from deeplearning4j_tpu.parallel.cluster import CollectiveWatchdog
    wd = CollectiveWatchdog(
        os.path.join(outdir, "hb"), rank=rank, n_ranks=nprocs,
        interval_s=0.25, deadline_s=20.0, dead_after_s=2.0,
        model=model, checkpoint_dir=ckpt_dir, exit_on_loss=True)
    return wd.start()


def _write_survivor(e, wd, iteration):
    with open(os.path.join(outdir, f"survivor_{rank}.json"), "w") as f:
        json.dump({"rank": rank, "detected": True,
                   "error": type(e).__name__,
                   "message": str(e)[:500],
                   "peer_loss": wd is not None
                   and wd.peer_loss_event is not None,
                   "iteration": iteration}, f)
    print(f"rank {rank}: peer failure detected ({type(e).__name__}: "
          f"{str(e)[:300]})", flush=True)


def main_dp():
    import numpy as np
    from deeplearning4j_tpu.datasets.dataset import (
        ArrayDataSetIterator, DataSet)
    from deeplearning4j_tpu.models.multi_layer_network import (
        MultiLayerNetwork)
    from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.inputs import InputType
    from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
    from deeplearning4j_tpu.nn.layers.output import OutputLayer
    from deeplearning4j_tpu.ops.activations import Activation
    from deeplearning4j_tpu.optimize.listeners import TrainingListener
    from deeplearning4j_tpu.optimize.updaters import Sgd
    from deeplearning4j_tpu.parallel.checkpoint import ElasticTrainer
    from deeplearning4j_tpu.parallel.mesh import DATA_AXIS, create_mesh
    from deeplearning4j_tpu.parallel.wrapper import (
        ParallelWrapper, TrainingMode)

    n_dev = jax.device_count()
    conf = (NeuralNetConfiguration.Builder().seed(1).updater(Sgd(0.05))
            .list()
            .layer(DenseLayer(n_out=16, activation=Activation.TANH))
            .layer(OutputLayer(n_out=3))
            .set_input_type(InputType.feed_forward(4)).build())
    model = MultiLayerNetwork(conf).init()
    mesh = create_mesh({DATA_AXIS: n_dev})

    # fixed GLOBAL batch of 64 rows; this process feeds the contiguous
    # slice proportional to its device share (uneven across ranks)
    rng = np.random.default_rng(0)
    gx = rng.normal(size=(64, 4)).astype(np.float32)
    gy = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 64)]
    per_row = 64 // n_dev
    sizes = [per_row * counts[r] for r in range(nprocs)]
    off = sum(sizes[:rank])
    lx = gx[off:off + sizes[rank]]
    ly = gy[off:off + sizes[rank]]

    ckpt_dir = os.path.join(outdir, "ckpt")
    wd = _make_watchdog(model, ckpt_dir)

    w = (ParallelWrapper.builder(model).mesh(mesh)
         .training_mode(TrainingMode.SHARED_GRADIENTS)
         .watchdog(wd).build())

    trainer = ElasticTrainer(model, ckpt_dir, checkpoint_every=2,
                             mesh=mesh)
    resumed = trainer.resume()
    start_iter = int(model.train_state.iteration)

    class _Killer(TrainingListener):
        def iteration_done(self, m, iteration, epoch, loss, etl_ms, n):
            if rank == die_rank and die_step >= 0 and \
                    iteration >= die_step:
                sys.stdout.flush()
                os._exit(17)   # abrupt death mid-fit, no cleanup

    if die_rank >= 0:
        model.add_listeners(_Killer())

    it = ArrayDataSetIterator(DataSet(lx, ly), batch_size=sizes[rank],
                              shuffle=False)

    # ElasticTrainer saves through the model fit loop; the wrapper owns
    # the distributed step, so attach the trainer's saver semantics by
    # checkpointing every N wrapper iterations via a listener
    class _Saver(TrainingListener):
        def __init__(self):
            self.last = start_iter

        def iteration_done(self, m, iteration, epoch, loss, etl_ms, n):
            if iteration - self.last >= trainer.checkpoint_every:
                from deeplearning4j_tpu.parallel.checkpoint import (
                    save_sharded)
                save_sharded(m.train_state, ckpt_dir)
                trainer._prune()
                self.last = int(iteration)

    model.add_listeners(_Saver())

    try:
        w.fit(it, epochs=epochs)
    except BaseException as e:     # a dead peer breaks the collective
        _write_survivor(e, wd, int(model.train_state.iteration))
        return
    finally:
        wd.stop()

    params = jax.tree_util.tree_map(np.asarray, model.params)
    flat = np.concatenate([l.ravel() for l in
                           jax.tree_util.tree_leaves(params)])
    with open(os.path.join(outdir, f"result_{rank}.json"), "w") as f:
        json.dump({"rank": rank, "loss": float(model._last_loss),  # host-sync-ok: end-of-run result dump
                   "param_sum": float(flat.sum()),  # host-sync-ok: end-of-run result dump
                   "resumed": bool(resumed),
                   "start_iteration": start_iter,
                   "final_iteration": int(model.train_state.iteration),
                   "n_devices": n_dev,
                   "local_batch": int(sizes[rank])}, f)
    print(f"rank {rank} done", flush=True)


def main_3d():
    """Composed dp×tp×pp chaos: a PipelinedTransformerLM trained with a
    manual jitted SGD step on a 3-axis mesh (GSPMD sequential path —
    jax 0.4.x cannot lower the partial-auto pipelined schedule, see
    tests/test_3d_parallel.py), sharded checkpoints every 2 steps, and
    resume onto whatever layout THIS launch specifies via
    restore_sharded's explicit param_shardings."""
    import numpy as np
    from types import SimpleNamespace

    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from deeplearning4j_tpu.optimize.solver import TrainState
    from deeplearning4j_tpu.parallel.checkpoint import (
        latest_checkpoint, restore_sharded, save_sharded)
    from deeplearning4j_tpu.parallel.mesh import create_3d_mesh
    from deeplearning4j_tpu.parallel.pipeline import (
        PipelinedTransformerLM, restack_stages)

    dp, tp, pp = (int(x) for x in mode.split(":")[1].split("x"))
    n_dev = jax.device_count()
    assert n_dev == dp * tp * pp, (n_dev, dp, tp, pp)
    mesh = create_3d_mesh(dp, tp, pp)
    lm = PipelinedTransformerLM(vocab=16, width=8, n_heads=2,
                                n_layers=4, max_len=6, mesh=mesh,
                                remat=True)
    ckpt_dir = os.path.join(outdir, "ckpt")

    # deterministic init, materialized ALREADY SHARDED onto the 3D
    # layout (jit + out_shardings — every process runs the same SPMD
    # program, so this works multi-host where a host-side device_put
    # of non-addressable shards would not)
    key = jax.random.PRNGKey(7)
    tmpl = jax.eval_shape(lm.init, key)
    shardings = lm.param_shardings(tmpl)
    repl = NamedSharding(mesh, P())
    with mesh:
        params = jax.jit(lm.init, out_shardings=shardings)(key)
        it_dev = jax.jit(lambda: jnp.zeros((), jnp.int32),
                         out_shardings=repl)()

    # ---- resume from the last COMMITTED checkpoint, reshaped --------
    latest = latest_checkpoint(ckpt_dir)
    resumed = latest is not None
    prev_pp = None
    layout_file = os.path.join(ckpt_dir, "layout.json")
    if resumed:
        shim = SimpleNamespace(train_state=TrainState(
            tmpl, {}, {}, jnp.zeros((), jnp.int32)))
        restored = restore_sharded(shim, latest, mesh=mesh,
                                   param_shardings=shardings)
        params = dict(restored.params)
        it_dev = restored.iteration
        if os.path.exists(layout_file):
            with open(layout_file) as f:
                prev_pp = json.load(f).get("pp")
        if prev_pp and prev_pp != pp:
            # stage-dim order is device-major: a pp-layout change
            # permutes the stacked blocks (tests/test_3d_parallel.py)
            params["blocks"] = restack_stages(
                params["blocks"], from_devices=prev_pp, to_devices=pp)
    start_iter = int(it_dev)  # host-sync-ok: replicated scalar, once at startup

    wd = _make_watchdog(None, ckpt_dir)

    # fixed global batch, sharded over the data axis; this process owns
    # a contiguous dp-slice proportional to its device share
    rng = np.random.default_rng(0)
    g_toks = rng.integers(0, 16, (8, 6)).astype(np.int32)
    g_tgts = rng.integers(0, 16, (8, 6)).astype(np.int32)
    batch_sh = NamedSharding(mesh, P("data", None))
    # rows land on dp-groups: each process owns counts[rank] devices =
    # counts[rank]/(tp*pp) dp rows; 8 global rows split over dp rows
    dp_rows_owned = counts[rank] // (tp * pp)
    rows = 8 // dp * dp_rows_owned
    off = 8 // dp * sum(counts[r] // (tp * pp) for r in range(rank))
    l_toks = g_toks[off:off + rows]
    l_tgts = g_tgts[off:off + rows]
    toks = jax.make_array_from_process_local_data(batch_sh, l_toks,
                                                  (8, 6))
    tgts = jax.make_array_from_process_local_data(batch_sh, l_tgts,
                                                  (8, 6))

    @jax.jit
    def step(p, it, toks, tgts):
        loss, g = jax.value_and_grad(
            lambda p: lm.loss(p, toks, tgts, pipelined=False))(p)
        return (jax.tree_util.tree_map(lambda a, b: a - 0.1 * b, p, g),
                it + 1, loss)

    def save(p, it_arr, it_host):
        ts = TrainState(p, {}, {}, it_arr)
        save_sharded(ts, ckpt_dir)
        if jax.process_index() == 0:
            with open(layout_file, "w") as f:
                json.dump({"dp": dp, "tp": tp, "pp": pp,
                           "step": it_host}, f)

    loss_v = None
    it_host = start_iter
    try:
        with mesh:
            for _ in range(epochs):     # epochs == steps here
                params, it_dev, loss = step(params, it_dev, toks, tgts)
                with wd.guard(it_host + 1):
                    # the fetch IS the blocking collective wait the
                    # watchdog classifies on a dead peer
                    loss_v = float(loss)  # host-sync-ok: guarded per-step wait
                it_host = int(it_dev)  # host-sync-ok: replicated scalar after the guarded wait
                wd.iteration = it_host
                if rank == die_rank and die_step >= 0 \
                        and it_host >= die_step:
                    sys.stdout.flush()
                    os._exit(17)        # abrupt death mid-fit
                if it_host % 2 == 0:
                    save(params, it_dev, it_host)
    except BaseException as e:
        if not wd.on_collective_error(e):
            raise                       # our own bug — fail loudly
        _write_survivor(e, wd, wd.iteration)
        return
    finally:
        wd.stop()

    # cross-process param fingerprint: a replicated global reduction
    # (host-side np.asarray of non-addressable shards would throw)
    with mesh:
        fp = jax.jit(
            lambda p: sum(
                (jnp.sum(l.astype(jnp.float32))
                 for l in jax.tree_util.tree_leaves(p)),
                jnp.zeros((), jnp.float32)),
            out_shardings=repl)(params)
    with open(os.path.join(outdir, f"result_{rank}.json"), "w") as f:
        json.dump({"rank": rank, "loss": loss_v,
                   "param_sum": float(fp),  # host-sync-ok: end-of-run replicated fingerprint
                   "resumed": resumed,
                   "start_iteration": start_iter,
                   "final_iteration": int(it_dev),
                   "n_devices": n_dev,
                   "layout": [dp, tp, pp],
                   "prev_pp": prev_pp}, f)
    print(f"rank {rank} done", flush=True)


def main():
    from deeplearning4j_tpu.parallel.mesh import initialize_distributed
    initialize_distributed(f"127.0.0.1:{port}", num_processes=nprocs,
                           process_id=rank)
    assert jax.local_device_count() == local_devices
    if mode.startswith("3d:"):
        main_3d()
    else:
        main_dp()


if __name__ == "__main__":
    main()
