"""Int8 post-training quantization tests (PR 9).

The quantization contract across its three layers:

- **ops/quantize.py**: per-channel symmetric int8 round-trips within
  half a scale step, dead channels never divide by zero, degenerate
  activation stats degrade to the identity scale.
- **parallel/quant.py**: calibration is bitwise deterministic for the
  same sample stream, the quantized walk reproduces ``f32`` EXACTLY
  when every layer falls back (the walk itself adds no drift), and
  within-budget layers quantize with the error the report claims.
- **serving/fleet**: PrecisionPolicy threads through the engine (the
  deprecated ``bf16`` flag still works, once, with a warning), int8
  engines serve warm with precision-labelled metrics, and the accuracy
  gate admits/blocks FleetRouter versions as a hard precondition.

The committed-zoo acceptance (int8 passes the gate on the real
pretrained artifacts) lives in ``TestZooGate``.
"""

import warnings

import numpy as np
import pytest

from deeplearning4j_tpu.observe.registry import MetricsRegistry
from deeplearning4j_tpu.ops import quantize as qz
from deeplearning4j_tpu.parallel.quant import (
    PrecisionPolicy,
    QuantizationError,
    calibrate,
    params_nbytes,
    quantize_model,
)
from deeplearning4j_tpu.parallel.serving import ServingEngine

N_IN = 6


def _model(seed: int = 3, width: int = 16, n_out: int = 4):
    from deeplearning4j_tpu.models.multi_layer_network import (
        MultiLayerNetwork)
    from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.inputs import InputType
    from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
    from deeplearning4j_tpu.nn.layers.output import OutputLayer
    from deeplearning4j_tpu.ops.losses import LossFunction
    from deeplearning4j_tpu.optimize.updaters import Adam
    conf = (NeuralNetConfiguration.Builder().seed(seed)
            .updater(Adam(1e-2)).list()
            .layer(DenseLayer(n_out=width))
            .layer(OutputLayer(n_out=n_out, loss=LossFunction.MCXENT))
            .set_input_type(InputType.feed_forward(N_IN)).build())
    return MultiLayerNetwork(conf).init()


def _calib(n: int = 64, seed: int = 0) -> np.ndarray:
    rng = np.random.default_rng(seed)
    return rng.normal(size=(n, N_IN)).astype(np.float32)


# ---------------------------------------------------------------------------
# numeric primitives
# ---------------------------------------------------------------------------

class TestQuantOps:
    def test_weight_round_trip_within_half_step(self):
        rng = np.random.default_rng(1)
        w = rng.normal(size=(9, 5)).astype(np.float32) * 3.0
        w_q, scales = qz.quantize_weight(w)
        assert w_q.dtype == np.int8 and scales.dtype == np.float32
        assert scales.shape == (5,)
        # symmetric: -128 never used
        assert w_q.min() >= -qz.Q_MAX
        err = np.abs(w_q.astype(np.float32) * scales - w)
        assert np.all(err <= scales / 2 + 1e-7)

    def test_dead_channel_gets_identity_scale(self):
        w = np.zeros((4, 3), np.float32)
        w[:, 0] = 1.0
        w_q, scales = qz.quantize_weight(w)
        assert scales[1] == 1.0 and scales[2] == 1.0
        assert np.all(w_q[:, 1:] == 0)

    def test_activation_scale_degenerate(self):
        assert qz.activation_scale(0.0) == np.float32(1.0)
        assert qz.activation_scale(float("nan")) == np.float32(1.0)
        assert qz.activation_scale(float("inf")) == np.float32(1.0)
        assert qz.activation_scale(qz.Q_MAX) == np.float32(1.0)

    def test_int8_dot_matches_dequant_reference(self):
        rng = np.random.default_rng(2)
        x = rng.normal(size=(4, 7)).astype(np.float32)
        w = rng.normal(size=(7, 3)).astype(np.float32)
        w_q, w_scale = qz.quantize_weight(w)
        x_scale = qz.activation_scale(np.abs(x).max())
        got = np.asarray(qz.int8_dot(x, w_q, w_scale, x_scale))
        x_q = np.clip(np.round(x / x_scale), -127, 127)
        want = (x_q @ w_q.astype(np.float32)) * (x_scale * w_scale)
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


# ---------------------------------------------------------------------------
# calibration + quantize_model
# ---------------------------------------------------------------------------

class TestCalibration:
    def test_same_stream_bitwise_identical(self):
        m = _model()
        pol = PrecisionPolicy.int8(_calib())
        c1 = calibrate(m, pol)
        c2 = calibrate(m, pol)
        assert c1.scales == c2.scales           # exact float equality
        assert c1.hash() == c2.hash()

    def test_percentile_tighter_than_absmax(self):
        m = _model()
        feats = _calib(256)
        ab = calibrate(m, PrecisionPolicy.int8(feats, calib_batch_size=32))
        pc = calibrate(m, PrecisionPolicy.int8(
            feats, calibration="percentile", percentile=75.0,
            calib_batch_size=32))
        assert ab.hash() != pc.hash()
        assert all(pc.amax[k] <= ab.amax[k] for k in ab.amax)

    def test_int8_without_samples_raises(self):
        with pytest.raises(QuantizationError, match="samples"):
            quantize_model(_model(), PrecisionPolicy(mode="int8"))


class TestQuantizeModel:
    def test_quantizes_within_budget_and_shrinks(self):
        m = _model()
        qm = quantize_model(m, PrecisionPolicy.int8(_calib()))
        assert qm.quantized_layers      # something actually quantized
        for name, rep in qm.report.items():
            if rep["quantized"]:
                assert rep["error"] <= qm.policy.error_budget
        assert params_nbytes(qm.params) < \
            params_nbytes(m.train_state.params)
        x = _calib(8, seed=9)
        y_q = np.asarray(qm.build_inference_fn()(
            qm.params, m.train_state.model_state, x, None))
        y_f = np.asarray(m.output(x))
        assert y_q.shape == y_f.shape
        # budgeted layers: outputs agree on the argmax for easy inputs
        assert np.mean(y_q.argmax(-1) == y_f.argmax(-1)) >= 0.9

    def test_all_fallback_is_bitwise_f32(self):
        # an impossible budget forces every layer back to f32: the
        # quantized WALK must then reproduce build_inference_fn exactly
        # (proof the walk replication adds zero numeric drift)
        m = _model()
        qm = quantize_model(
            m, PrecisionPolicy.int8(_calib(), error_budget=-1.0))
        assert qm.quantized_layers == []
        assert sorted(qm.fallback) == sorted(qm.report)
        x = _calib(8, seed=11)
        y_q = np.asarray(qm.build_inference_fn()(
            qm.params, m.train_state.model_state, x, None))
        assert np.array_equal(y_q, np.asarray(m.output(x)))

    def test_calibration_hash_tracks_fallback(self):
        m = _model()
        qm_a = quantize_model(m, PrecisionPolicy.int8(_calib()))
        qm_b = quantize_model(
            m, PrecisionPolicy.int8(_calib(), error_budget=-1.0))
        assert qm_a.calibration_hash() != qm_b.calibration_hash()


# ---------------------------------------------------------------------------
# ServingEngine precision plumbing
# ---------------------------------------------------------------------------

def _engine(model, **kw):
    kw.setdefault("batch_limit", 4)
    kw.setdefault("feature_shape", (N_IN,))
    kw.setdefault("registry", MetricsRegistry())
    return ServingEngine(model, **kw)


class TestEnginePrecision:
    def test_int8_serves_warm_with_labelled_metrics(self):
        m = _model()
        reg = MetricsRegistry()
        eng = _engine(m, registry=reg,
                      precision=PrecisionPolicy.int8(_calib()),
                      session_id="q8")
        try:
            x = _calib(3, seed=5)
            y = np.asarray(eng.output(x))
            assert np.mean(y.argmax(-1) ==
                           np.asarray(m.output(x)).argmax(-1)) >= 0.9
            eng.assert_warm()
            st = eng.stats()
            assert st["precision"] == "int8"
            assert st["quant"]["layers"]
            assert st["params_resident_bytes"] == \
                eng.params_resident_bytes
        finally:
            eng.shutdown()
        text = reg.render()
        assert 'dl4j_serving_precision{' in text
        assert 'precision="int8"' in text
        assert "dl4j_quant_layer_error{" in text

    def test_int8_resident_bytes_below_f32(self):
        m = _model()
        e8 = _engine(m, precision=PrecisionPolicy.int8(_calib()))
        ef = _engine(m)
        try:
            assert e8.params_resident_bytes < ef.params_resident_bytes
            assert ef.stats()["precision"] == "f32"
        finally:
            e8.shutdown()
            ef.shutdown()

    def test_bf16_kwarg_deprecated_but_works(self):
        m = _model()
        with pytest.warns(DeprecationWarning, match="precision"):
            eng = _engine(m, bf16=True)
        try:
            assert eng.precision.mode == "bf16"
            assert eng.stats()["precision"] == "bf16"
        finally:
            eng.shutdown()

    def test_precision_string_accepted(self):
        eng = _engine(_model(), precision="bf16")
        try:
            assert eng.precision.mode == "bf16"
        finally:
            eng.shutdown()

    def test_both_flags_rejected(self):
        with pytest.raises(ValueError, match="not both"):
            _engine(_model(), bf16=True,
                    precision=PrecisionPolicy.f32())


# ---------------------------------------------------------------------------
# accuracy gate: standalone + fleet warm-swap precondition
# ---------------------------------------------------------------------------

class TestQuantGate:
    def test_gate_pass_and_fail_shapes(self):
        from deeplearning4j_tpu.evaluation import (
            QuantGate, QuantGateError, enforce_quant_gate,
            run_quant_gate)
        m = _model()
        pol = PrecisionPolicy.int8(_calib())
        ok = run_quant_gate(m, pol, QuantGate(top1_budget=0.5))
        assert ok.passed and ok.n_examples > 0
        assert "PASS" in ok.summary()
        with pytest.raises(QuantGateError) as ei:
            enforce_quant_gate(m, pol, QuantGate(top1_budget=-1.0))
        assert not ei.value.result.passed
        assert "FAIL" in str(ei.value)

    def test_fleet_gate_blocks_swap_keeps_serving(self):
        from deeplearning4j_tpu.evaluation import (
            QuantGate, QuantGateError)
        from deeplearning4j_tpu.parallel.fleet import FleetRouter
        feats = _calib()
        router = FleetRouter(session_id="quant-gate-t")
        try:
            pool = router.add_pool(
                "m", _model(), version="v1",
                precision=PrecisionPolicy.int8(feats),
                quant_gate=QuantGate(top1_budget=0.5, samples=feats),
                feature_shape=(N_IN,), batch_limit=4)
            assert pool.gate_results and pool.gate_results[-1].passed
            assert router.stats()["pools"]["m"]["engines"][0][
                "precision"] == "int8"
            y1 = np.asarray(router.output(feats[:2], model="m"))
            # impossible budget: swap must raise BEFORE any engine
            # exists and v1 must keep answering
            pool.quant_gate = QuantGate(top1_budget=-1.0, samples=feats)
            with pytest.raises(QuantGateError):
                router.swap("m", _model(seed=8), "v2")
            assert pool.active_version == "v1"
            assert np.array_equal(
                np.asarray(router.output(feats[:2], model="m")), y1)
            text = router.registry.render()
            assert 'dl4j_fleet_quant_gate_total{model="m",' \
                   'outcome="fail"} 1.0' in text
            assert 'outcome="pass"} 1.0' in text
        finally:
            router.shutdown()

    def test_gate_skipped_for_f32_pool(self):
        from deeplearning4j_tpu.evaluation import QuantGate
        from deeplearning4j_tpu.parallel.fleet import FleetRouter
        router = FleetRouter(session_id="quant-gate-f32")
        try:
            pool = router.add_pool(
                "m", _model(), quant_gate=QuantGate(top1_budget=-1.0),
                feature_shape=(N_IN,), batch_limit=4)
            assert pool.gate_results == []      # gate not applicable
        finally:
            router.shutdown()


# ---------------------------------------------------------------------------
# committed-zoo acceptance: int8 passes the gate on real weights
# ---------------------------------------------------------------------------

class TestZooGate:
    def test_committed_zoo_models_pass_gate(self):
        from deeplearning4j_tpu.evaluation import run_zoo_gates
        results = run_zoo_gates()
        assert len(results) >= 2        # LeNet + TextGenerationLSTM
        for r in results:
            assert r.passed, r.summary()
            assert r.n_examples > 0
        # the convnet actually exercised the int8 conv path
        lenet = next(r for r in results if r.model == "LeNet")
        assert lenet.layer_errors and not lenet.fallback
