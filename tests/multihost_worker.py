"""Worker process for the REAL multi-process distributed test
(tests/test_multihost.py). Not collected by pytest — launched as
``python multihost_worker.py <rank> <nprocs> <port> <outdir>``.

Each process owns 2 virtual CPU devices; together they form one global
4-device ``data`` mesh spanning 2 OS processes — the honest simulation
of two TPU hosts (separate runtimes, gloo/TCP collectives, per-process
data shards), not 8 devices faked inside one process.
"""

import json
import os
import sys

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=2")

import jax

jax.config.update("jax_platforms", "cpu")


def main():
    rank, nprocs, port, outdir = (int(sys.argv[1]), int(sys.argv[2]),
                                  sys.argv[3], sys.argv[4])
    from deeplearning4j_tpu.parallel.mesh import initialize_distributed
    initialize_distributed(f"127.0.0.1:{port}", num_processes=nprocs,
                           process_id=rank)
    assert jax.process_count() == nprocs
    assert jax.device_count() == 2 * nprocs

    import numpy as np
    from deeplearning4j_tpu.datasets.dataset import (
        ArrayDataSetIterator, DataSet)
    from deeplearning4j_tpu.models.multi_layer_network import (
        MultiLayerNetwork)
    from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.inputs import InputType
    from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
    from deeplearning4j_tpu.nn.layers.output import OutputLayer
    from deeplearning4j_tpu.ops.activations import Activation
    from deeplearning4j_tpu.optimize.updaters import Sgd
    from deeplearning4j_tpu.parallel.mesh import DATA_AXIS, create_mesh
    from deeplearning4j_tpu.parallel.wrapper import (
        ParallelWrapper, TrainingMode)

    conf = (NeuralNetConfiguration.Builder().seed(1).updater(Sgd(0.1))
            .list()
            .layer(DenseLayer(n_out=16, activation=Activation.TANH))
            .layer(OutputLayer(n_out=3))
            .set_input_type(InputType.feed_forward(4)).build())
    model = MultiLayerNetwork(conf).init()
    mesh = create_mesh({DATA_AXIS: 2 * nprocs})

    # fixed GLOBAL dataset; this process feeds its contiguous shard
    rng = np.random.default_rng(0)
    gx = rng.normal(size=(64, 4)).astype(np.float32)
    gy = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 64)]
    per = 64 // nprocs
    lx = gx[rank * per:(rank + 1) * per]
    ly = gy[rank * per:(rank + 1) * per]

    w = (ParallelWrapper.builder(model).mesh(mesh)
         .training_mode(TrainingMode.SHARED_GRADIENTS).build())
    w.fit(ArrayDataSetIterator(DataSet(lx, ly), batch_size=per,
                               shuffle=False), epochs=5)

    params = jax.tree_util.tree_map(np.asarray, model.params)
    flat = np.concatenate([l.ravel() for l in
                           jax.tree_util.tree_leaves(params)])
    result = {"rank": rank, "loss": float(model._last_loss),
              "param_sum": float(flat.sum()),
              "param_head": flat[:5].tolist()}

    # multihost-safe sharded checkpoint: every process writes ONLY its
    # addressable shards; process 0 publishes the manifest
    from deeplearning4j_tpu.parallel.checkpoint import save_sharded
    ckpt = os.path.join(outdir, "ckpt")
    save_sharded(model.train_state, ckpt)

    # AVERAGING (local-SGD) mode across processes too: each process
    # contributes its slice of every (k, B) averaging round
    avg_model = MultiLayerNetwork(conf).init()
    wa = (ParallelWrapper.builder(avg_model).mesh(mesh)
          .training_mode(TrainingMode.AVERAGING)
          .averaging_frequency(2).build())
    wa.fit(ArrayDataSetIterator(DataSet(lx, ly), batch_size=per // 2,
                                shuffle=False), epochs=2)
    aflat = np.concatenate(
        [np.asarray(l).ravel() for l in
         jax.tree_util.tree_leaves(avg_model.params)])
    result["avg_param_sum"] = float(aflat.sum())
    assert np.isfinite(aflat).all()

    with open(os.path.join(outdir, f"result_{rank}.json"), "w") as f:
        json.dump(result, f)
    print(f"rank {rank} done", flush=True)


if __name__ == "__main__":
    main()
