"""observe/ subsystem tests: ring-buffer telemetry, one-fetch flush,
scan/unscan equivalence, tracer export, recompile watchdog, Prometheus
endpoint, host-sync lint."""

import json
import re
import subprocess
import sys
import urllib.request
from pathlib import Path
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.observe import (
    MetricsRegistry,
    RecompileWatchdog,
    SpanTracer,
    TelemetryCollector,
    TelemetrySpec,
)
from deeplearning4j_tpu.observe.telemetry import has_buffer
from deeplearning4j_tpu.optimize.solver import (
    TrainState,
    make_scan_train_step,
    make_train_step,
)

REPO = Path(__file__).resolve().parent.parent


def _tiny_model(seed=1):
    from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.inputs import InputType
    from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
    from deeplearning4j_tpu.nn.layers.output import OutputLayer
    from deeplearning4j_tpu.models.multi_layer_network import (
        MultiLayerNetwork)
    from deeplearning4j_tpu.ops.losses import LossFunction
    from deeplearning4j_tpu.optimize.updaters import Adam
    conf = (NeuralNetConfiguration.Builder().seed(seed).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=8))
            .layer(OutputLayer(n_out=3, loss=LossFunction.MCXENT))
            .set_input_type(InputType.feed_forward(5)).build())
    return MultiLayerNetwork(conf).init()


def _batches(n, batch=16, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        x = rng.normal(size=(batch, 5)).astype(np.float32)
        y = np.zeros((batch, 3), np.float32)
        y[np.arange(batch), rng.integers(0, 3, batch)] = 1.0
        out.append(DataSet(x, y))
    return out


class _ListIter:
    def __init__(self, batches):
        self.batches = batches

    def __iter__(self):
        return iter(self.batches)

    def reset(self):
        pass


class TestTelemetrySpec:
    def test_metric_catalog(self):
        spec = TelemetrySpec(("a", "b"), capacity=8)
        assert spec.metric_names == ("loss", "grad_norm",
                                     "nonfinite_count",
                                     "update_ratio/a", "update_ratio/b")
        buf = spec.init()
        assert buf.rows.shape == (8, 5)
        assert int(buf.count) == 0

    def test_ring_wraparound_drops_oldest(self):
        # 10 rows through a 4-slot ring: flush sees the newest 4, reports
        # the 6 overwritten ones as dropped
        tel = TelemetryCollector(flush_interval=4, capacity=4,
                                 per_layer=False,
                                 registry=MetricsRegistry())
        spec = tel.spec_for(SimpleNamespace(layer_names=()))
        buf = spec.init()
        g = {"w": jnp.ones((2,), jnp.float32)}
        for i in range(10):
            buf = spec.record(buf, loss=jnp.float32(i), grads=g,
                              params=g, prev_params=g,
                              iteration=jnp.int32(i))
        ts = TrainState({}, {}, {}, jnp.int32(10), buf)
        records = tel.flush(ts)
        assert [r["loss"] for r in records] == [6.0, 7.0, 8.0, 9.0]
        assert [r["iteration"] for r in records] == [7, 8, 9, 10]
        assert tel.dropped_rows == 6
        assert tel.registry.counter(
            "dl4j_telemetry_dropped_rows_total").get(
            session="train") == 6.0

    def test_nonfinite_counted(self):
        spec = TelemetrySpec((), capacity=2)
        buf = spec.init()
        g = {"w": jnp.array([1.0, jnp.nan, jnp.inf], jnp.float32)}
        buf = spec.record(buf, loss=jnp.float32(0.5), grads=g,
                          params=g, prev_params=g,
                          iteration=jnp.int32(0))
        row = np.asarray(buf.rows[0])
        assert row[2] == 2.0          # nan + inf in grads, finite loss


class TestOneFetchFlush:
    def test_single_device_fetch_per_interval(self, monkeypatch):
        """The acceptance property: N=4 steps per flush -> the whole fit
        performs exactly ceil(12/4)+1 tail = 4 host transfers, counted at
        jax.device_get itself."""
        fetches = []
        real = jax.device_get

        def counting(x):
            fetches.append(type(x).__name__)
            return real(x)

        m = _tiny_model()
        tel = TelemetryCollector(flush_interval=4,
                                 registry=MetricsRegistry())
        m.set_telemetry(tel)
        monkeypatch.setattr(jax, "device_get", counting)
        m.fit(_ListIter(_batches(12)), epochs=1)
        monkeypatch.setattr(jax, "device_get", real)
        assert tel.fetch_count == 4       # steps 4, 8, 12 + tail flush
        assert len(fetches) == 4
        assert len(tel.history) == 12
        # rows decode in iteration order with no gaps
        assert [r["iteration"] for r in tel.history] == list(range(1, 13))

    def test_listener_values_come_from_flush(self):
        from deeplearning4j_tpu.optimize.listeners import (
            ScoreIterationListener)
        m = _tiny_model()
        tel = TelemetryCollector(flush_interval=4,
                                 registry=MetricsRegistry())
        m.set_telemetry(tel)
        lst = ScoreIterationListener(frequency=1)
        m.set_listeners(lst)
        m.fit(_ListIter(_batches(6)), epochs=1)
        # iterations 1-3 ran before the first flush: no score, no sync;
        # from 4 on the flushed value is visible
        assert len(lst.scores) == 3
        assert all(np.isfinite(s) for s in lst.scores)
        assert lst.scores[-1] == tel.history[3]["loss"]

    def test_buffer_attaches_once(self):
        m = _tiny_model()
        tel = TelemetryCollector(flush_interval=4,
                                 registry=MetricsRegistry())
        m.set_telemetry(tel)
        m.fit(_batches(1)[0])
        assert has_buffer(m.train_state.telemetry)
        ts = m.train_state
        assert tel.ensure_buffer(ts) is ts

    def test_capacity_below_interval_rejected(self):
        with pytest.raises(ValueError):
            TelemetryCollector(flush_interval=64, capacity=8)

    def test_collector_rejects_different_layers(self):
        tel = TelemetryCollector(registry=MetricsRegistry())
        tel.spec_for(SimpleNamespace(layer_names=("a",)))
        with pytest.raises(ValueError):
            tel.spec_for(SimpleNamespace(layer_names=("b",)))


class TestScanEquivalence:
    def test_scanned_and_unscanned_buffers_match(self):
        """make_scan_train_step must record the identical telemetry rows
        as k dispatches of make_train_step."""
        k = 6
        params = {"lin": {"w": jnp.arange(3, dtype=jnp.float32) / 3.0}}
        tx = optax.sgd(0.1)

        def loss_fn(p, ms, x, y, fm, lm, rng, it):
            pred = jnp.sum(p["lin"]["w"] * x, axis=-1)
            return jnp.mean((pred - y) ** 2), ms

        spec = TelemetrySpec(("lin",), capacity=16)
        rng = np.random.default_rng(3)
        xs = jnp.asarray(rng.normal(size=(k, 4, 3)).astype(np.float32))
        ys = jnp.asarray(rng.normal(size=(k, 4)).astype(np.float32))

        def init_state():
            return TrainState(params, {}, tx.init(params),
                              jnp.zeros((), jnp.int32), spec.init())

        step = make_train_step(loss_fn, tx, donate=False, telemetry=spec)
        ts_a = init_state()
        key = jax.random.PRNGKey(0)
        for i in range(k):
            ts_a, _ = step(ts_a, xs[i], ys[i], None, None, key)

        steps = make_scan_train_step(loss_fn, tx, donate=False,
                                     telemetry=spec)
        ts_b, _ = steps(init_state(), xs, ys, None, None, key)

        np.testing.assert_allclose(np.asarray(ts_a.telemetry.rows),
                                   np.asarray(ts_b.telemetry.rows),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_array_equal(np.asarray(ts_a.telemetry.iters),
                                      np.asarray(ts_b.telemetry.iters))
        assert int(ts_a.telemetry.count) == int(ts_b.telemetry.count) == k

    def test_trainstate_default_slot_backcompat(self):
        # 4-positional construction (all pre-observe call sites) still
        # works and carries the empty sentinel
        ts = TrainState({}, {}, {}, jnp.int32(0))
        assert ts.telemetry == ()
        assert not has_buffer(ts.telemetry)


class TestTracer:
    def test_chrome_trace_export(self, tmp_path):
        import time
        tr = SpanTracer()
        with tr.span("dispatch", cat="step", k=3):
            pass
        start = time.perf_counter()
        tr.add_span("etl", start, time.perf_counter(), cat="data")
        tr.instant("recompile")
        doc = tr.to_chrome_trace()
        assert doc["displayTimeUnit"] == "ms"
        names = [e["name"] for e in doc["traceEvents"]]
        assert names == ["dispatch", "etl", "recompile"]
        for ev in doc["traceEvents"]:
            assert ev["ph"] in ("X", "i")
            assert ev["ts"] >= 0
        path = tr.save(str(tmp_path / "trace.json"))
        loaded = json.loads(Path(path).read_text())
        assert len(loaded["traceEvents"]) == 3

    def test_disabled_tracer_records_nothing(self):
        tr = SpanTracer(enabled=False)
        with tr.span("x"):
            pass
        assert tr.events == []

    def test_fit_emits_phase_spans(self):
        m = _tiny_model()
        tr = SpanTracer()
        m.set_tracer(tr)
        m.fit(_ListIter(_batches(2)), epochs=1)
        cats = {e["name"] for e in tr.events}
        assert {"etl", "host_to_device", "dispatch"} <= cats


class TestRecompileWatchdog:
    def test_new_signature_detected(self):
        reg = MetricsRegistry()
        wd = RecompileWatchdog(registry=reg)
        a = jnp.zeros((4, 5))
        assert wd.observe("train_step", a, None)        # first compile
        assert not wd.observe("train_step", a, None)    # same signature
        assert wd.count("train_step") == 0
        # batch-size drift = new signature = recompile
        assert wd.observe("train_step", jnp.zeros((7, 5)), None)
        # dtype drift too
        assert wd.observe("train_step", a.astype(jnp.bfloat16), None)
        # optional mask appearing flips the compiled branch
        assert wd.observe("train_step", a, jnp.ones((4,)))
        assert wd.count("train_step") == 3
        assert reg.counter("dl4j_recompiles_total").get(
            session="train") == 3.0

    def test_per_step_key_isolation(self):
        wd = RecompileWatchdog(registry=MetricsRegistry())
        wd.observe("train_step", jnp.zeros((2, 2)))
        wd.observe("tbptt_step", jnp.zeros((2, 2)))
        assert wd.count() == 0          # each key's first compile is free


_PROM_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[a-zA-Z_][a-zA-Z0-9_]*="
    r"\"[^\"]*\"(,[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\")*\})? "
    r"(-?[0-9.e+-]+|NaN|[+-]Inf)$")


class TestMetricsEndpoint:
    def test_registry_render_format(self):
        reg = MetricsRegistry()
        reg.gauge("g", "a gauge").set(1.5, session="s")
        reg.counter("c", "a counter").inc(2.0)
        txt = reg.render()
        assert "# TYPE g gauge" in txt
        assert "# TYPE c counter" in txt
        assert 'g{session="s"} 1.5' in txt
        for line in txt.splitlines():
            if line and not line.startswith("#"):
                assert _PROM_LINE.match(line), line

    def test_registry_kind_conflict(self):
        reg = MetricsRegistry()
        reg.gauge("m")
        with pytest.raises(TypeError):
            reg.counter("m")

    def test_metrics_and_healthz_endpoints(self):
        """curl localhost:<port>/metrics returns valid Prometheus text
        with the loss / grad-norm / steps-per-sec / recompile series."""
        from deeplearning4j_tpu.observe import default_registry
        from deeplearning4j_tpu.ui import InMemoryStatsStorage, UIServer

        m = _tiny_model()
        tel = TelemetryCollector(flush_interval=2,
                                 registry=default_registry())
        m.set_telemetry(tel)
        m.set_recompile_watchdog(RecompileWatchdog())
        m.fit(_ListIter(_batches(4)), epochs=1)

        srv = UIServer(port=0).attach(InMemoryStatsStorage()).start()
        try:
            with urllib.request.urlopen(f"{srv.url}/metrics") as r:
                ctype = r.headers["Content-Type"]
                body = r.read().decode()
            assert ctype.startswith("text/plain")
            assert "version=0.0.4" in ctype
            for series in ("dl4j_loss{", "dl4j_grad_norm{",
                           "dl4j_steps_per_second{",
                           "dl4j_recompiles_total{",
                           "dl4j_telemetry_flushes_total{"):
                assert series in body, f"missing {series} in /metrics"
            for line in body.splitlines():
                if line and not line.startswith("#"):
                    assert _PROM_LINE.match(line), line
            with urllib.request.urlopen(f"{srv.url}/healthz") as r:
                health = json.loads(r.read())
            assert health["status"] == "ok"
        finally:
            srv.stop()


class TestHostSyncChecker:
    def _run(self, *args):
        return subprocess.run(
            [sys.executable, str(REPO / "tools" / "check_host_sync.py"),
             *args], capture_output=True, text=True)

    def test_hot_paths_clean(self):
        r = self._run()
        assert r.returncode == 0, r.stdout + r.stderr

    def test_flags_unallowed_sync(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("x = float(loss)\ny = arr.item()\n"
                       "z = np.asarray(dev)\nok = jnp.asarray(dev)\n")
        r = self._run("--paths", str(bad))
        assert r.returncode == 1
        assert "bad.py:1" in r.stderr
        assert "bad.py:2" in r.stderr
        assert "bad.py:3" in r.stderr
        assert "bad.py:4" not in r.stderr   # jnp.asarray is device-side

    def test_pragma_allowlists(self, tmp_path):
        ok = tmp_path / "ok.py"
        ok.write_text(
            "x = float(dh) ** 0.5  # host-sync-ok: static shape\n")
        r = self._run("--paths", str(ok))
        assert r.returncode == 0, r.stderr
