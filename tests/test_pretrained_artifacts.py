"""Committed pretrained zoo artifacts: init_pretrained() restores REAL
weights (no synthetic file:// mirror) and they predict (VERDICT r3 #4 —
reference contract: ZooModel.initPretrained, ZooModel.java:40-51)."""

import json
import os

import numpy as np
import pytest

from deeplearning4j_tpu.zoo.models import (
    LeNet, SimpleCNN, TextGenerationLSTM)

WEIGHTS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "deeplearning4j_tpu", "zoo", "weights")


def test_lenet_pretrained_digits_accuracy():
    """End-to-end: restore the committed checkpoint through the
    checksum-verified resource path, evaluate on the real held-out
    digits split, ≥98%."""
    from deeplearning4j_tpu.datasets.fetchers import DigitsDataSetIterator
    model = LeNet().init_pretrained(flavor="digits")
    ev = model.evaluate(DigitsDataSetIterator(batch_size=64, train=False,
                                              shuffle=False))
    assert ev.accuracy() >= 0.98, ev.accuracy()


def test_lenet_pretrained_checksum_enforced():
    bad = dict(LeNet.PRETRAINED)
    bad["digits"] = dict(bad["digits"], checksum=1234)
    orig = LeNet.PRETRAINED
    LeNet.PRETRAINED = bad
    try:
        with pytest.raises(IOError, match="Adler32"):
            LeNet().init_pretrained(flavor="digits")
    finally:
        LeNet.PRETRAINED = orig


def test_simplecnn_pretrained_digits_accuracy():
    """The online-learning demo artifact (ISSUE 10): SimpleCNN's
    conv+batchnorm stack restored through the checksum-verified
    resource path; NHWC input (SimpleCNN uses InputType.convolutional,
    not LeNet's flat variant), ≥95% on the held-out digits split."""
    from deeplearning4j_tpu.datasets.dataset import ArrayDataSetIterator
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.fetchers import DigitsDataSetIterator
    model = SimpleCNN().init_pretrained(flavor="digits")
    x, y = DigitsDataSetIterator.fetch(train=False)
    ds = DataSet(x.reshape(-1, 28, 28, 1), np.eye(10, dtype=np.float32)[y])
    ev = model.evaluate(ArrayDataSetIterator(ds, 64))
    assert ev.accuracy() >= 0.95, ev.accuracy()


def test_textgen_pretrained_predicts_text():
    """The committed char-LSTM must assign its training corpus a
    per-char cross-entropy far below the uniform ln(77)=4.34 baseline
    and generate deterministic output."""
    model = TextGenerationLSTM().init_pretrained()
    vocab = json.load(open(os.path.join(WEIGHTS, "textgen_vocab.json")))
    corpus = open(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "resources",
        "pretrained", "corpus.txt"), encoding="utf-8").read()[:4096]
    ids = np.array([vocab.get(c, 0) for c in corpus], np.int32)
    T, V = 60, 77
    starts = np.arange(0, len(ids) - T - 1, T)
    eye = np.eye(V, dtype=np.float32)
    X = eye[np.stack([ids[s:s + T] for s in starts])]
    Y = np.stack([ids[s + 1:s + T + 1] for s in starts])
    probs = np.asarray(model.output(X))          # (N, T, V) softmax
    n, t = Y.shape
    p_true = probs[np.arange(n)[:, None], np.arange(t)[None, :], Y]
    xent = -np.mean(np.log(np.maximum(p_true, 1e-9)))
    assert xent < 2.5, xent
    # greedy generation is deterministic given the stored weights
    out1 = np.argmax(np.asarray(model.output(X[:1])), axis=-1)
    out2 = np.argmax(np.asarray(model.output(X[:1])), axis=-1)
    np.testing.assert_array_equal(out1, out2)
