"""Real-data correctness tests (VERDICT weak#7 / next#8).

Two claims, both previously resting on synthetic data:

1. A LeNet-class model reaches high test accuracy on REAL handwritten
   digits — using the genuine UCI optical-digits scans that ship inside
   scikit-learn (the only real image corpus available in a zero-egress
   environment).
2. The cached-real-file MNIST path (IDX parsing) works end to end:
   canonical gzipped IDX files are written byte-for-byte per the MNIST
   format spec, the fetcher reads them back (NOT the synthetic
   fallback), and training runs on their contents.
   Reference: MnistDataFetcher.java:1 (same file contract).
"""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets import fetchers
from deeplearning4j_tpu.datasets.fetchers import (
    DigitsDataSetIterator,
    MnistDataSetIterator,
    write_idx_gz,
)
from deeplearning4j_tpu.zoo.models import LeNet


def test_digits_iterator_is_real_data():
    """The corpus is the 1797-scan UCI digits set, not a generator."""
    it = DigitsDataSetIterator(batch_size=64, train=True)
    imgs, labels = DigitsDataSetIterator.fetch(train=True)
    t_imgs, t_labels = DigitsDataSetIterator.fetch(train=False)
    assert imgs.shape[0] + t_imgs.shape[0] == 1797
    assert imgs.shape[1] == 28 * 28
    # disjoint deterministic split
    assert set(np.arange(1797)[np.arange(1797) % 5 == 0]).isdisjoint(
        np.arange(1797)[np.arange(1797) % 5 != 0])
    # all ten classes present in both splits
    assert set(labels.tolist()) == set(range(10))
    assert set(t_labels.tolist()) == set(range(10))


@pytest.mark.slow
def test_lenet_real_digits_accuracy():
    """LeNet >= 98% test accuracy on real handwritten digits — the
    real-data replacement for the synthetic 'accuracy 1.0' claim."""
    model = LeNet(compute_dtype="float32").init()
    train_it = DigitsDataSetIterator(batch_size=64, train=True)
    model.fit(train_it, epochs=12)
    ev = model.evaluate(DigitsDataSetIterator(batch_size=64, train=False,
                                              shuffle=False))
    acc = ev.accuracy()
    assert acc >= 0.98, f"accuracy {acc}"


def test_mnist_real_file_path_roundtrip(tmp_path, monkeypatch):
    """write_idx_gz -> MnistDataFetcher reads the REAL files: contents
    match the written scans exactly (synthetic fallback would not)."""
    imgs, labels = DigitsDataSetIterator.fetch(train=True)
    scans = (imgs.reshape(-1, 28, 28) * 255).astype(np.uint8)[:256]
    lab = labels[:256].astype(np.uint8)
    base = tmp_path / "mnist"
    write_idx_gz(scans, lab, str(base), "train")
    write_idx_gz(scans[:64], lab[:64], str(base), "t10k")
    monkeypatch.setattr(fetchers, "DATA_DIR", str(tmp_path))

    got_imgs, got_labels = fetchers.MnistDataFetcher(train=True).fetch()
    assert got_imgs.shape == (256, 784)
    np.testing.assert_allclose(got_imgs,
                               scans.reshape(256, 784) / 255.0, atol=1e-7)
    np.testing.assert_array_equal(got_labels, lab)

    # the iterator trains off the real files
    it = MnistDataSetIterator(batch_size=64)
    model = LeNet(compute_dtype="float32").init()
    model.fit(it, epochs=1)
    assert np.isfinite(float(model._last_loss))


def test_cifar_real_file_path_roundtrip(tmp_path, monkeypatch):
    """write_cifar_bin -> CifarDataSetIterator reads the REAL canonical
    bin layout, not the synthetic fallback."""
    from deeplearning4j_tpu.datasets.fetchers import (
        CifarDataSetIterator, write_cifar_bin)
    rng = np.random.default_rng(0)
    imgs = rng.integers(0, 256, (40, 32, 32, 3)).astype(np.uint8)
    labels = rng.integers(0, 10, 40).astype(np.uint8)
    base = tmp_path / "cifar-10-batches-bin"
    for i in range(1, 6):
        write_cifar_bin(imgs[(i - 1) * 8: i * 8],
                        labels[(i - 1) * 8: i * 8],
                        str(base / f"data_batch_{i}.bin"))
    write_cifar_bin(imgs[:8], labels[:8], str(base / "test_batch.bin"))
    monkeypatch.setattr(fetchers, "DATA_DIR", str(tmp_path))

    it = CifarDataSetIterator(batch_size=8, train=True, seed=1)
    got = np.concatenate([np.asarray(b.features) for b in it])
    assert got.shape == (40, 32, 32, 3)
    # content equality (order shuffled): compare sorted pixel sums
    np.testing.assert_allclose(
        np.sort(got.sum((1, 2, 3))),
        np.sort(imgs.astype(np.float32).sum((1, 2, 3)) / 255.0),
        rtol=1e-5)
