"""CJK segmentation quality against gold segmentations (VERDICT r3 #8):
the bundled frequency dictionaries (nlp/data/) must segment non-trivial
real sentences correctly — the parity bar the reference's vendored
Ansj/Kuromoji analyzers set."""

import pytest

from deeplearning4j_tpu.nlp.language_packs import (
    ChineseTokenizerFactory,
    JapaneseTokenizerFactory,
    _load_bundled_freq,
    _load_bundled_words,
)


def test_bundled_dictionaries_present_and_substantial():
    zh = _load_bundled_freq("chinese_freq.txt.gz")
    ja = _load_bundled_words("japanese_words.txt.gz")
    assert len(zh) >= 50_000
    assert len(ja) >= 4_000
    assert "经济" in zh and "科学家" in zh
    assert all(isinstance(v, float) for v in list(zh.values())[:5])


# gold segmentations: word-level splits any mainstream Chinese segmenter
# (jieba/Ansj/THULAC) produces for these sentences
ZH_GOLD = [
    ("今天天气真好", ["今天", "天气", "真", "好"]),
    ("我们正在学习自然语言处理", ["我们", "正在", "学习", "自然语言", "处理"]),
    ("北京大学的学生在图书馆看书",
     ["北京大学", "的", "学生", "在", "图书馆", "看书"]),
    ("科学家发现了一种新的方法",
     ["科学家", "发现", "了", "一种", "新", "的", "方法"]),
    ("机器学习模型需要大量数据",
     ["机器", "学习", "模型", "需要", "大量", "数据"]),
    ("中华人民共和国成立于一九四九年",
     ["中华人民共和国", "成立", "于", "一九四九年"]),
]


@pytest.mark.parametrize("sentence,gold", ZH_GOLD)
def test_chinese_gold_segmentation(sentence, gold):
    toks = ChineseTokenizerFactory().create(sentence).get_tokens()
    # score by word-boundary F1 against gold rather than exact match:
    # legitimate segmenters differ on fine splits (自然语言 vs 自然+语言)
    def bounds(words):
        out, i = set(), 0
        for w in words:
            out.add((i, i + len(w)))
            i += len(w)
        return out
    g, t = bounds(gold), bounds(toks)
    f1 = 2 * len(g & t) / (len(g) + len(t))
    assert f1 >= 0.7, (toks, gold, f1)


def test_chinese_ambiguity_resolved_by_frequency():
    """FMM greedily eats 研究生 in 研究生命 ('research life'); the
    unigram DP picks the higher-probability 研究 + 生命 split."""
    toks = ChineseTokenizerFactory().create("研究生命的起源").get_tokens()
    assert "生命" in toks, toks
    # but a true 研究生 context keeps the trigram
    toks2 = ChineseTokenizerFactory().create("他是研究生").get_tokens()
    assert "研究生" in toks2, toks2


JA_GOLD = [
    # Botchan-vocabulary compounds must split out of kanji runs
    ("東京大学", {"東京", "大学"}),
    ("日本語の勉強", {"日本語", "勉強"}),
    ("先生と学校に行く", {"先生", "学校"}),
]


@pytest.mark.parametrize("sentence,expect", JA_GOLD)
def test_japanese_gold_segmentation(sentence, expect):
    toks = set(JapaneseTokenizerFactory().create(sentence).get_tokens())
    missing = expect - toks
    assert not missing, (sorted(toks), missing)


def test_japanese_bundled_vocab_improves_compounds():
    """A compound absent from the seed but present in the bundled
    Botchan vocabulary still splits."""
    ja = _load_bundled_words("japanese_words.txt.gz")
    # pick real bundled 2-char KANJI words not in the seed set (hiragana
    # runs legitimately go through particle splitting instead)
    from deeplearning4j_tpu.nlp.language_packs import _JA_SEED
    kanji = [w for w in sorted(ja - set(_JA_SEED))
             if len(w) == 2 and all("一" <= c <= "鿿" for c in w)]
    extra = kanji[:5]
    assert extra
    for w in extra:
        toks = JapaneseTokenizerFactory().create(w + "勉強").get_tokens()
        assert w in toks, (w, toks)


def test_cache_dir_upgrade_contract_still_works(tmp_path, monkeypatch):
    import deeplearning4j_tpu.nlp.language_packs as lp
    d = tmp_path / "dicts"
    d.mkdir()
    (d / "chinese.txt").write_text("深度学习框架\n", encoding="utf-8")
    monkeypatch.setattr(lp, "_DATA_DIR", str(tmp_path))
    toks = lp.ChineseTokenizerFactory().create("深度学习框架").get_tokens()
    assert "深度学习框架" in toks


class TestJapaneseMorphology:
    """Kuromoji Token.getPartOfSpeech/getReading analog (round 5 —
    VERDICT r4 missing #4): coarse ipadic POS + katakana readings from
    the bundled lexicon, script heuristics for OOV."""

    def test_lexicon_pos_and_readings(self):
        from deeplearning4j_tpu.nlp.language_packs import (
            JapaneseTokenizerFactory)
        f = JapaneseTokenizerFactory()
        toks = {t.surface: t for t in
                f.analyze("東京で勉強をする。")}
        assert toks["東京"].part_of_speech == "名詞"
        assert toks["東京"].reading == "トウキョウ"
        assert toks["を"].part_of_speech == "助詞"
        assert toks["する"].part_of_speech == "動詞"
        assert toks["勉強"].reading == "ベンキョウ"

    def test_oov_heuristics(self):
        from deeplearning4j_tpu.nlp.language_packs import (
            JapaneseTokenizerFactory)
        f = JapaneseTokenizerFactory()
        # katakana loanword OOV: noun, reading = the run itself
        toks = {t.surface: t for t in f.analyze("バズワードです。")}
        assert toks["バズワード"].part_of_speech == "名詞"
        assert toks["バズワード"].reading == "バズワード"
        assert toks["です"].part_of_speech == "助動詞"

    def test_pos_lexicon_substantial(self):
        from deeplearning4j_tpu.nlp.language_packs import (
            _load_bundled_pos)
        lex = _load_bundled_pos("japanese_pos.txt.gz")
        assert len(lex) > 5000
        pos_values = {p for p, _ in lex.values()}
        assert {"名詞", "動詞", "助詞", "形容詞"} <= pos_values
