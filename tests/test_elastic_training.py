"""ASYNC_ELASTIC bounded-staleness training + the collective watchdog
(ISSUE 7 tentpole): straggler-free equivalence to AVERAGING, straggler
drop/merge/discard accounting, the divergence-guarded hard sync, and
dead-peer vs slow-peer classification."""

import json
import os
import threading
import time

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.fetchers import IrisDataSetIterator
from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
from deeplearning4j_tpu.nn.layers.output import OutputLayer
from deeplearning4j_tpu.ops.activations import Activation
from deeplearning4j_tpu.optimize.updaters import Sgd
from deeplearning4j_tpu.parallel.cluster import (
    PEER_LOSS_EXIT_CODE, PEER_LOSS_MARKER, CollectiveWatchdog,
    classify_heartbeat_age)
from deeplearning4j_tpu.parallel.wrapper import (
    ElasticOptions, ParallelWrapper, TrainingMode)


@pytest.fixture(autouse=True)
def _restore_default_registry():
    """fit() and the watchdog publish dl4j_elastic_* series into the
    process-global default registry; a peer-loss counter or a staleness
    gauge left behind would flip /healthz to 503 for every LATER test
    in the same pytest process. Snapshot the registry's series before
    each test here and restore them after."""
    from deeplearning4j_tpu.observe.registry import default_registry
    r = default_registry()
    with r._lock:
        snap = {name: dict(m._series) for name, m in r._metrics.items()}
    yield
    with r._lock:
        for name in list(r._metrics):
            if name in snap:
                r._metrics[name]._series = dict(snap[name])
            else:
                del r._metrics[name]


def mlp_conf(seed=1, lr=0.05):
    return (NeuralNetConfiguration.Builder().seed(seed).updater(Sgd(lr))
            .list()
            .layer(DenseLayer(n_out=16, activation=Activation.TANH))
            .layer(OutputLayer(n_out=3))
            .set_input_type(InputType.feed_forward(4)).build())


def _fit_elastic(policy=None, epochs=10, workers=4, k=4, opts=None):
    model = MultiLayerNetwork(mlp_conf()).init()
    if opts is None:
        opts = ElasticOptions(straggler_policy=policy)
    w = (ParallelWrapper.builder(model)
         .training_mode(TrainingMode.ASYNC_ELASTIC)
         .workers(workers).averaging_frequency(k)
         .elastic_options(opts).build())
    w.fit(IrisDataSetIterator(batch_size=32), epochs=epochs)
    return model, w


class TestAsyncElastic:
    def test_straggler_free_matches_averaging(self):
        """With every worker present every round, the delta merge
        collapses to plain parameter averaging — the two modes must
        converge to (numerically) the same params."""
        ma = MultiLayerNetwork(mlp_conf()).init()
        wa = (ParallelWrapper.builder(ma)
              .training_mode(TrainingMode.AVERAGING)
              .workers(4).averaging_frequency(4).build())
        wa.fit(IrisDataSetIterator(batch_size=32), epochs=15)

        me, _ = _fit_elastic(policy=None, epochs=15)
        for a, b in zip(jax.tree_util.tree_leaves(ma.params),
                        jax.tree_util.tree_leaves(me.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)
        assert float(me._last_loss) == pytest.approx(
            float(ma._last_loss), rel=1e-3)

    def test_straggler_dropped_and_divergence_bounded(self):
        """A worker missing every other round is dropped from those
        rounds' averages; the run still converges and the divergence
        gauge stays under the hard-sync threshold."""
        def policy(rnd, n):
            d = [0.0] * n
            if rnd % 2 == 0:
                d[1] = 1e9          # worker 1 misses even rounds
            return d

        model, w = _fit_elastic(policy=policy, epochs=10)
        from deeplearning4j_tpu.observe.registry import default_registry
        r = default_registry()
        dropped = r.counter(
            "dl4j_elastic_stragglers_dropped_total").get(session="elastic")
        assert dropped and dropped > 0
        merged = r.counter(
            "dl4j_elastic_stale_merged_total").get(session="elastic")
        assert merged and merged > 0   # it rejoins one round late
        div = r.gauge("dl4j_replica_divergence").get(session="elastic")
        assert div is not None and np.isfinite(div)
        assert div < w.elastic_options.divergence_threshold
        # training still works on the members
        acc = model.evaluate(
            IrisDataSetIterator(batch_size=150)).accuracy()
        assert acc > 0.7, acc

    def test_stale_contribution_discarded_past_bound(self):
        """A worker absent longer than staleness_bound rounds has its
        eventual contribution discarded (weight 0), not merged."""
        def policy(rnd, n):
            d = [0.0] * n
            if 0 <= rnd < 5:
                d[2] = 1e9          # worker 2 misses 5 straight rounds
            return d

        opts = ElasticOptions(staleness_bound=3, straggler_policy=policy)
        _fit_elastic(epochs=8, opts=opts)
        from deeplearning4j_tpu.observe.registry import default_registry
        r = default_registry()
        disc = r.counter(
            "dl4j_elastic_stale_discarded_total").get(session="elastic")
        assert disc and disc > 0

    def test_divergence_forces_hard_sync(self):
        """Divergence past the threshold forces the next round into a
        hard sync: every worker adopts, staleness resets."""
        def policy(rnd, n):
            d = [0.0] * n
            d[1] = 1e9              # worker 1 never reports...
            return d

        # threshold 0 => every round trips the guard => next round is
        # hard => worker 1 is force-synced anyway => staleness stays 0
        opts = ElasticOptions(divergence_threshold=0.0,
                              straggler_policy=policy)
        _fit_elastic(epochs=6, opts=opts)
        from deeplearning4j_tpu.observe.registry import default_registry
        r = default_registry()
        hard = r.counter(
            "dl4j_elastic_hard_syncs_total").get(session="elastic")
        assert hard and hard > 0
        # hard rounds adopt everyone: the perpetual straggler cannot
        # accumulate unbounded staleness
        stale = r.gauge("dl4j_elastic_staleness").get(session="elastic")
        assert stale is not None and stale <= 2.0

    def test_replicas_identical_after_straggler_free_round(self):
        model, w = _fit_elastic(policy=None, epochs=1)
        for leaf in jax.tree_util.tree_leaves(model.params):
            assert leaf.sharding.is_fully_replicated

    def test_bad_policy_shape_rejected(self):
        with pytest.raises(ValueError, match="one delay per worker"):
            _fit_elastic(policy=lambda rnd, n: [0.0], epochs=1)


class TestCollectiveWatchdog:
    def _beat_as(self, hb_dir, rank, stop):
        def loop():
            while not stop.wait(0.05):
                with open(os.path.join(hb_dir, f"hb_{rank}.json"),
                          "w") as f:
                    json.dump({"rank": rank, "time": time.time(),
                               "iteration": 0}, f)
        t = threading.Thread(target=loop, daemon=True)
        t.start()
        return t

    def test_dead_peer_detected(self, tmp_path):
        """Peer 1 never heartbeats: an over-deadline collective is
        classified as peer loss — marker + event, no exit (disarmed)."""
        hb = str(tmp_path / "hb")
        ck = str(tmp_path / "ckpt")
        wd = CollectiveWatchdog(hb, rank=0, n_ranks=2, interval_s=0.05,
                                deadline_s=0.3, dead_after_s=0.2,
                                checkpoint_dir=ck, exit_on_loss=False)
        events = []
        wd.on_peer_loss = events.append
        wd.start()
        with wd.guard(iteration=7):
            time.sleep(1.5)
        wd.stop()
        assert wd.peer_loss_event is not None
        assert wd.peer_loss_event["dead_ranks"] == [1]
        assert wd.peer_loss_event["iteration"] == 7
        assert events and events[0]["reason"] == "peer_loss"
        assert os.path.exists(
            os.path.join(ck, f"{PEER_LOSS_MARKER}.0"))

    def test_slow_peer_is_straggler_not_loss(self, tmp_path):
        """A peer that keeps beating extends the deadline instead of
        tripping peer loss — the dead-vs-slow distinction."""
        hb = tmp_path / "hb"
        hb.mkdir()
        stop = threading.Event()
        self._beat_as(str(hb), 1, stop)
        wd = CollectiveWatchdog(str(hb), rank=0, n_ranks=2,
                                interval_s=0.05, deadline_s=0.3,
                                dead_after_s=10.0, exit_on_loss=False)
        wd.start()
        with wd.guard():
            time.sleep(1.2)
        stop.set()
        wd.stop()
        assert wd.peer_loss_event is None
        assert wd.straggler_waits > 0

    def test_collective_error_classified(self, tmp_path):
        """An exception out of a collective with a stale peer heartbeat
        is peer loss (True, full handling, no exit); with all peers
        fresh it is the caller's own bug (False, untouched)."""
        hb = tmp_path / "hb"
        hb.mkdir()
        # stale peer: one old heartbeat
        with open(hb / "hb_1.json", "w") as f:
            json.dump({"rank": 1, "time": time.time() - 60,
                       "iteration": 3}, f)
        wd = CollectiveWatchdog(str(hb), rank=0, n_ranks=2,
                                interval_s=0.05, dead_after_s=0.5,
                                checkpoint_dir=str(tmp_path / "ck"),
                                exit_on_loss=True)   # must NOT exit here
        assert wd.on_collective_error(RuntimeError("gloo reset")) is True
        assert wd.peer_loss_event is not None

        hb2 = tmp_path / "hb2"
        hb2.mkdir()
        stop = threading.Event()
        self._beat_as(str(hb2), 1, stop)
        time.sleep(0.2)
        wd2 = CollectiveWatchdog(str(hb2), rank=0, n_ranks=2,
                                 interval_s=0.05, dead_after_s=0.6,
                                 exit_on_loss=False)
        try:
            assert wd2.on_collective_error(ValueError("my bug")) is False
        finally:
            stop.set()
        assert wd2.peer_loss_event is None

    def test_exit_code_constant(self):
        # the relauncher contract: distinct, stable, not a shell code
        assert PEER_LOSS_EXIT_CODE == 43

    def test_rejoining_rank_reuses_stale_heartbeat_file(self, tmp_path):
        """A crashed rank leaves its heartbeat file behind; the
        relaunched rank (same id) just overwrites it — the watchdog must
        see the rejoiner as alive, not keep condemning the stale record
        (same contract as a serving node rejoining the NodeRegistry)."""
        hb = tmp_path / "hb"
        hb.mkdir()
        # the crash artifact: rank 1's heartbeat, a minute stale
        with open(hb / "hb_1.json", "w") as f:
            json.dump({"rank": 1, "time": time.time() - 60,
                       "iteration": 3}, f)
        wd = CollectiveWatchdog(str(hb), rank=0, n_ranks=2,
                                interval_s=0.05, dead_after_s=0.5,
                                exit_on_loss=False)
        assert list(wd.dead_peers()) == [1]     # stale record = dead
        # rank 1 relaunches and beats into the SAME file
        stop = threading.Event()
        self._beat_as(str(hb), 1, stop)
        try:
            deadline = time.time() + 5.0
            while wd.dead_peers() and time.time() < deadline:
                time.sleep(0.05)
            assert wd.dead_peers() == {}        # rejoiner is alive
        finally:
            stop.set()

    def test_peer_loss_counter_degrades_health(self, tmp_path):
        from deeplearning4j_tpu.observe.health import health_status
        from deeplearning4j_tpu.observe.registry import MetricsRegistry
        r = MetricsRegistry()
        r.counter("dl4j_elastic_peer_loss_total", "").inc(session="s")
        st = health_status(r)
        assert st["status"] == "degraded"
        assert any("peer_loss" in x for x in st["reasons"])


class TestHeartbeatBoundary:
    """classify_heartbeat_age is THE staleness boundary — shared by the
    watchdog and the serving NodeRegistry so the two tiers can never
    disagree off-by-one. Exactly at a threshold is always the less
    severe class; only strictly-past evidence kills a peer."""

    def test_exactly_at_stale_is_slow_not_alive(self):
        assert classify_heartbeat_age(1.99, 6.0, 2.0) == "alive"
        assert classify_heartbeat_age(2.0, 6.0, 2.0) == "slow"

    def test_exactly_at_dead_is_slow_one_past_is_dead(self):
        assert classify_heartbeat_age(6.0, 6.0, 2.0) == "slow"
        assert classify_heartbeat_age(6.000001, 6.0, 2.0) == "dead"

    def test_single_threshold_watchdog_case(self):
        # slow_after_s defaults to dead_after_s: exactly-at is slow
        # (the watchdog's dead_peers() keeps waiting), strictly past
        # is dead
        assert classify_heartbeat_age(0.49, 0.5) == "alive"
        assert classify_heartbeat_age(0.5, 0.5) == "slow"
        assert classify_heartbeat_age(0.51, 0.5) == "dead"

    def test_missing_heartbeat_is_dead(self):
        assert classify_heartbeat_age(None, 0.5) == "dead"

    def test_staleness_gauge_degrades_health(self):
        from deeplearning4j_tpu.observe.health import health_status
        from deeplearning4j_tpu.observe.registry import MetricsRegistry
        r = MetricsRegistry()
        r.gauge("dl4j_elastic_staleness", "").set(9.0, session="s")
        st = health_status(r)
        assert st["status"] == "degraded"
        assert any("elastic_staleness" in x for x in st["reasons"])
        r2 = MetricsRegistry()
        r2.gauge("dl4j_elastic_staleness", "").set(1.0, session="s")
        assert health_status(r2)["status"] == "ok"

    def test_flight_recorder_context_section(self, tmp_path, monkeypatch):
        """record_crash(extra=...) lands the watchdog's forensics in a
        context.json section of the dump."""
        monkeypatch.setenv("DL4J_CRASH_DUMP_DIR", str(tmp_path))
        from deeplearning4j_tpu.observe.flight_recorder import (
            FlightRecorder)
        rec = FlightRecorder()
        path = rec.record_crash(None, reason="peer_loss",
                                extra={"dead_ranks": [2],
                                       "iteration": 11})
        assert path is not None
        with open(os.path.join(path, "context.json")) as f:
            ctx = json.load(f)
        assert ctx["dead_ranks"] == [2] and ctx["iteration"] == 11
