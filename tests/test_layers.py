"""Per-layer forward-shape and known-value tests.

Analog of the reference's layer unit tests
(deeplearning4j-core/src/test/java/org/deeplearning4j/nn/layers/**).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import LayerContext
from deeplearning4j_tpu.nn.layers.convolution import (
    Convolution1DLayer,
    ConvolutionLayer,
    ConvolutionMode,
    Cropping2D,
    Deconvolution2D,
    PoolingType,
    SeparableConvolution2D,
    SpaceToDepthLayer,
    SubsamplingLayer,
    Upsampling2D,
    ZeroPaddingLayer,
)
from deeplearning4j_tpu.nn.layers.feedforward import (
    ActivationLayer,
    DenseLayer,
    DropoutLayer,
    EmbeddingLayer,
    EmbeddingSequenceLayer,
)
from deeplearning4j_tpu.nn.layers.normalization import (
    BatchNormalization,
    LayerNormalization,
    LocalResponseNormalization,
)
from deeplearning4j_tpu.nn.layers.output import GlobalPoolingLayer
from deeplearning4j_tpu.nn.layers.recurrent import (
    LSTM,
    Bidirectional,
    GravesLSTM,
    LastTimeStep,
    SimpleRnn,
)
from deeplearning4j_tpu.ops.activations import Activation

KEY = jax.random.PRNGKey(0)
CTX = LayerContext(train=False)
TRAIN_CTX = LayerContext(train=True, rng=jax.random.PRNGKey(1))


def run(layer, input_type, x, ctx=CTX):
    params = layer.initialize(KEY, input_type) if layer.has_params else {}
    state = layer.init_state(input_type)
    y, new_state = layer.apply(params, state, jnp.asarray(x), ctx)
    expected = layer.output_type(input_type)
    assert y.shape[1:] == tuple(
        s for s in expected.shape() if s != -1) or -1 in expected.shape()
    return y, params, new_state


def test_dense_shape_and_value():
    layer = DenseLayer(n_in=4, n_out=3, activation=Activation.IDENTITY)
    params = layer.initialize(KEY, InputType.feed_forward(4))
    x = jnp.ones((2, 4))
    y, _ = layer.apply(params, {}, x, CTX)
    assert y.shape == (2, 3)
    expect = x @ params["W"] + params["b"]
    np.testing.assert_allclose(np.asarray(y), np.asarray(expect), rtol=1e-6)


def test_dense_on_sequence():
    layer = DenseLayer(n_in=4, n_out=3)
    params = layer.initialize(KEY, InputType.recurrent(4))
    y, _ = layer.apply(params, {}, jnp.ones((2, 5, 4)), CTX)
    assert y.shape == (2, 5, 3)


def test_conv2d_shapes():
    it = InputType.convolutional(28, 28, 1)
    layer = ConvolutionLayer(n_out=8, kernel_size=(5, 5), stride=(1, 1))
    y, _, _ = run(layer, it, np.random.randn(2, 28, 28, 1).astype(np.float32))
    assert y.shape == (2, 24, 24, 8)
    same = ConvolutionLayer(n_out=8, kernel_size=(3, 3), stride=(2, 2),
                            convolution_mode=ConvolutionMode.SAME)
    y2, _, _ = run(same, it, np.random.randn(2, 28, 28, 1).astype(np.float32))
    assert y2.shape == (2, 14, 14, 8)


def test_conv2d_known_value():
    """3x3 all-ones kernel over constant input = 9*c."""
    it = InputType.convolutional(5, 5, 1)
    layer = ConvolutionLayer(n_in=1, n_out=1, kernel_size=(3, 3),
                             has_bias=False)
    params = {"W": jnp.ones((3, 3, 1, 1))}
    x = jnp.full((1, 5, 5, 1), 2.0)
    y, _ = layer.apply(params, {}, x, CTX)
    np.testing.assert_allclose(np.asarray(y), 18.0, rtol=1e-6)


def test_separable_and_deconv_shapes():
    it = InputType.convolutional(16, 16, 4)
    x = np.random.randn(2, 16, 16, 4).astype(np.float32)
    sep = SeparableConvolution2D(n_out=8, kernel_size=(3, 3),
                                 convolution_mode=ConvolutionMode.SAME)
    y, _, _ = run(sep, it, x)
    assert y.shape == (2, 16, 16, 8)
    dec = Deconvolution2D(n_out=8, kernel_size=(2, 2), stride=(2, 2))
    y2, _, _ = run(dec, it, x)
    assert y2.shape == (2, 32, 32, 8)


def test_subsampling_max_avg():
    it = InputType.convolutional(4, 4, 2)
    x = np.arange(32, dtype=np.float32).reshape(1, 4, 4, 2)
    mx = SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2),
                          pooling_type=PoolingType.MAX)
    y, _, _ = run(mx, it, x)
    assert y.shape == (1, 2, 2, 2)
    assert float(y[0, 0, 0, 0]) == 10.0  # max of {0,2,8,10}
    av = SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2),
                          pooling_type=PoolingType.AVG)
    y2, _, _ = run(av, it, x)
    assert float(y2[0, 0, 0, 0]) == 5.0


def test_upsample_pad_crop_s2d():
    it = InputType.convolutional(4, 4, 3)
    x = np.random.randn(2, 4, 4, 3).astype(np.float32)
    y, _, _ = run(Upsampling2D(size=(2, 2)), it, x)
    assert y.shape == (2, 8, 8, 3)
    y, _, _ = run(ZeroPaddingLayer(pad=(1, 1, 2, 2)), it, x)
    assert y.shape == (2, 6, 8, 3)
    y, _, _ = run(Cropping2D(crop=(1, 1, 1, 1)), it, x)
    assert y.shape == (2, 2, 2, 3)
    y, _, _ = run(SpaceToDepthLayer(block_size=2), it, x)
    assert y.shape == (2, 2, 2, 12)


def test_conv1d():
    it = InputType.recurrent(8, 10)
    layer = Convolution1DLayer(n_out=16, kernel_size=3,
                               convolution_mode=ConvolutionMode.SAME)
    y, _, _ = run(layer, it, np.random.randn(2, 10, 8).astype(np.float32))
    assert y.shape == (2, 10, 16)


def test_batchnorm_train_and_eval():
    it = InputType.feed_forward(6)
    layer = BatchNormalization()
    params = layer.initialize(KEY, it)
    state = layer.init_state(it)
    x = jnp.asarray(np.random.randn(64, 6).astype(np.float32) * 3 + 1)
    y, new_state = layer.apply(params, state, x, TRAIN_CTX)
    # normalized output ~ zero mean unit var
    assert abs(float(jnp.mean(y))) < 0.1
    assert abs(float(jnp.std(y)) - 1.0) < 0.1
    # running stats moved toward batch stats
    assert float(jnp.max(jnp.abs(new_state["mean"]))) > 0.0
    # eval mode uses running stats
    y2, s2 = layer.apply(params, new_state, x, CTX)
    assert s2 == new_state or jnp.allclose(s2["mean"], new_state["mean"])


def test_batchnorm_nhwc():
    it = InputType.convolutional(8, 8, 4)
    layer = BatchNormalization()
    params = layer.initialize(KEY, it)
    state = layer.init_state(it)
    x = jnp.asarray(np.random.randn(4, 8, 8, 4).astype(np.float32))
    y, _ = layer.apply(params, state, x, TRAIN_CTX)
    assert y.shape == x.shape


def test_layernorm_and_lrn():
    it = InputType.feed_forward(16)
    ln = LayerNormalization()
    params = ln.initialize(KEY, it)
    x = jnp.asarray(np.random.randn(4, 16).astype(np.float32))
    y, _ = ln.apply(params, {}, x, CTX)
    np.testing.assert_allclose(np.asarray(jnp.mean(y, -1)), 0.0, atol=1e-5)

    itc = InputType.convolutional(4, 4, 8)
    lrn = LocalResponseNormalization()
    xc = jnp.asarray(np.random.randn(2, 4, 4, 8).astype(np.float32))
    y, _ = lrn.apply({}, {}, xc, CTX)
    assert y.shape == xc.shape


def test_embedding():
    layer = EmbeddingLayer(n_in=10, n_out=4)
    params = layer.initialize(KEY, InputType.feed_forward(1))
    idx = jnp.asarray([[1], [3]])
    y, _ = layer.apply(params, {}, idx, CTX)
    assert y.shape == (2, 4)
    seq = EmbeddingSequenceLayer(n_in=10, n_out=4)
    sp = seq.initialize(KEY, InputType.recurrent(1, 5))
    y2, _ = seq.apply(sp, {}, jnp.zeros((2, 5), jnp.int32), CTX)
    assert y2.shape == (2, 5, 4)


def test_dropout_train_vs_eval():
    layer = DropoutLayer(dropout=0.5)
    x = jnp.ones((10, 20))
    y_eval, _ = layer.apply({}, {}, x, CTX)
    np.testing.assert_allclose(np.asarray(y_eval), 1.0)
    y_train, _ = layer.apply({}, {}, x, TRAIN_CTX)
    vals = np.unique(np.asarray(y_train))
    assert set(np.round(vals, 4)).issubset({0.0, 2.0})


def test_lstm_shapes_and_state():
    it = InputType.recurrent(8, 6)
    layer = LSTM(n_in=8, n_out=12)
    params = layer.initialize(KEY, it)
    x = jnp.asarray(np.random.randn(3, 6, 8).astype(np.float32))
    y, state = layer.apply(params, {}, x, CTX)
    assert y.shape == (3, 6, 12)
    assert state["last_h"].shape == (3, 12)
    assert state["last_c"].shape == (3, 12)
    # last output equals last hidden state
    np.testing.assert_allclose(np.asarray(y[:, -1]),
                               np.asarray(state["last_h"]), rtol=1e-5)


def test_lstm_masking_freezes_state():
    it = InputType.recurrent(4, 5)
    layer = LSTM(n_in=4, n_out=3)
    params = layer.initialize(KEY, it)
    x = jnp.asarray(np.random.randn(2, 5, 4).astype(np.float32))
    mask = jnp.asarray([[1, 1, 1, 0, 0], [1, 1, 1, 1, 1]], jnp.float32)
    ctx = LayerContext(train=False, mask=mask)
    y, state = layer.apply(params, {}, x, ctx)
    # masked timesteps emit zeros
    np.testing.assert_allclose(np.asarray(y[0, 3:]), 0.0, atol=1e-7)
    # final state of example 0 equals state at t=2
    y_full, state3 = layer.apply(params, {}, x[:, :3], CTX)
    np.testing.assert_allclose(np.asarray(state["last_h"][0]),
                               np.asarray(state3["last_h"][0]), rtol=1e-5)


def test_lstm_step_one_matches_scan():
    it = InputType.recurrent(4, 3)
    layer = LSTM(n_in=4, n_out=5)
    params = layer.initialize(KEY, it)
    x = jnp.asarray(np.random.randn(2, 3, 4).astype(np.float32))
    y, _ = layer.apply(params, {}, x, CTX)
    h = jnp.zeros((2, 5))
    c = jnp.zeros((2, 5))
    for t in range(3):
        h, c = layer.step_one(params, x[:, t], (h, c))
    np.testing.assert_allclose(np.asarray(y[:, -1]), np.asarray(h), rtol=1e-5)


def test_graves_lstm_and_simple_rnn():
    it = InputType.recurrent(4, 6)
    x = np.random.randn(2, 6, 4).astype(np.float32)
    y, p, _ = run(GravesLSTM(n_in=4, n_out=7), it, x)
    assert y.shape == (2, 6, 7)
    assert "pI" in p
    y2, _, _ = run(SimpleRnn(n_in=4, n_out=7), it, x)
    assert y2.shape == (2, 6, 7)


def test_bidirectional_modes():
    it = InputType.recurrent(4, 6)
    x = np.random.randn(2, 6, 4).astype(np.float32)
    for mode, width in [("concat", 10), ("add", 5), ("average", 5)]:
        layer = Bidirectional(fwd=LSTM(n_in=4, n_out=5), mode=mode)
        y, _, _ = run(layer, it, x)
        assert y.shape == (2, 6, width)


def test_last_time_step():
    it = InputType.recurrent(4, 6)
    layer = LastTimeStep(inner=LSTM(n_in=4, n_out=5))
    params = layer.initialize(KEY, it)
    x = jnp.asarray(np.random.randn(2, 6, 4).astype(np.float32))
    y, _ = layer.apply(params, {}, x, CTX)
    assert y.shape == (2, 5)
    # with mask: pick last unmasked step
    mask = jnp.asarray([[1, 1, 0, 0, 0, 0], [1, 1, 1, 1, 1, 1]], jnp.float32)
    y2, _ = layer.apply(params, {}, x, LayerContext(train=False, mask=mask))
    inner_y, _ = layer.inner.apply(params, {}, x,
                                   LayerContext(train=False, mask=mask))
    np.testing.assert_allclose(np.asarray(y2[0]), np.asarray(inner_y[0, 1]),
                               rtol=1e-5)


def test_global_pooling():
    itc = InputType.convolutional(4, 4, 3)
    x = np.random.randn(2, 4, 4, 3).astype(np.float32)
    y, _, _ = run(GlobalPoolingLayer(pooling_type=PoolingType.AVG), itc, x)
    assert y.shape == (2, 3)
    np.testing.assert_allclose(np.asarray(y), x.mean(axis=(1, 2)), rtol=1e-5)
    itr = InputType.recurrent(3, 5)
    xs = np.random.randn(2, 5, 3).astype(np.float32)
    mask = jnp.asarray([[1, 1, 1, 0, 0], [1, 1, 1, 1, 1]], jnp.float32)
    layer = GlobalPoolingLayer(pooling_type=PoolingType.AVG)
    y2, _ = layer.apply({}, {}, jnp.asarray(xs),
                        LayerContext(train=False, mask=mask))
    np.testing.assert_allclose(np.asarray(y2[0]), xs[0, :3].mean(axis=0),
                               rtol=1e-5)


def test_activation_layer():
    y, _, _ = run(ActivationLayer(activation=Activation.RELU),
                  InputType.feed_forward(4),
                  np.array([[-1.0, 2.0, -3.0, 4.0]], np.float32))
    np.testing.assert_allclose(np.asarray(y), [[0, 2, 0, 4]])
