"""Scope/transfer sanitizers (SURVEY §5.2 — the reference's workspace
SCOPE_PANIC / race detection analog, VERDICT partial #71)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.utils.sanitizers import (
    check_not_donated,
    is_deleted,
    no_implicit_transfers,
)


def test_transfer_guard_catches_implicit_transfer():
    x = np.arange(8.0)
    with no_implicit_transfers():
        with pytest.raises(Exception, match="[Tt]ransfer"):
            jnp.sin(x) + x  # implicit host->device convert
        # explicit transfers stay legal
        d = jax.device_put(x)
        float(jax.device_get(jnp.sum(d)))


def test_check_not_donated_detects_stale_state():
    @jax.jit
    def bump(t):
        return jax.tree_util.tree_map(lambda a: a + 1, t)

    donating = jax.jit(lambda t: jax.tree_util.tree_map(
        lambda a: a * 2, t), donate_argnums=(0,))

    tree = {"w": jnp.ones((4,)), "b": jnp.zeros((2,))}
    check_not_donated(tree)          # fresh: fine
    out = donating(tree)
    check_not_donated(out)           # result: fine
    if not any(is_deleted(l) for l in jax.tree_util.tree_leaves(tree)):
        pytest.skip("backend ignores buffer donation")
    with pytest.raises(RuntimeError, match="SCOPE_PANIC"):
        check_not_donated(tree, what="stale tree")


def test_fit_rejects_donated_train_state():
    """Using a model whose TrainState leaked through a donating step
    fails eagerly in fit() with the scope-panic message."""
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.models.multi_layer_network import (
        MultiLayerNetwork)
    from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.inputs import InputType
    from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
    from deeplearning4j_tpu.nn.layers.output import OutputLayer
    from deeplearning4j_tpu.optimize.updaters import Sgd

    conf = (NeuralNetConfiguration.Builder().seed(1).updater(Sgd(0.1))
            .list()
            .layer(DenseLayer(n_out=8))
            .layer(OutputLayer(n_out=3))
            .set_input_type(InputType.feed_forward(4)).build())
    m = MultiLayerNetwork(conf).init()
    x = np.random.default_rng(0).normal(size=(8, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[np.random.default_rng(1)
                                    .integers(0, 3, 8)]
    stale = m.train_state
    m.fit(DataSet(x, y))     # donates `stale`'s buffers
    m.train_state = stale    # simulate holding the old reference
    if not any(is_deleted(l)
               for l in jax.tree_util.tree_leaves(stale.params)):
        pytest.skip("backend ignores buffer donation")
    with pytest.raises(RuntimeError, match="SCOPE_PANIC"):
        m.fit(DataSet(x, y))


def test_train_step_is_transfer_clean():
    """The jitted train step with device-resident batches performs no
    implicit host<->device transfers — the workspace-hygiene guarantee,
    now enforced by the guard rather than assumed."""
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.models.multi_layer_network import (
        MultiLayerNetwork)
    from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.inputs import InputType
    from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
    from deeplearning4j_tpu.nn.layers.output import OutputLayer
    from deeplearning4j_tpu.optimize.updaters import Sgd

    conf = (NeuralNetConfiguration.Builder().seed(2).updater(Sgd(0.1))
            .list()
            .layer(DenseLayer(n_out=8))
            .layer(OutputLayer(n_out=3))
            .set_input_type(InputType.feed_forward(4)).build())
    m = MultiLayerNetwork(conf).init()
    step = m._build_train_step()
    rng = np.random.default_rng(3)
    x = jax.device_put(jnp.asarray(
        rng.normal(size=(8, 4)).astype(np.float32)))
    y = jax.device_put(jnp.asarray(
        np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]))
    key = jax.random.PRNGKey(0)
    ts = m.train_state
    ts, loss = step(ts, x, y, None, None, key)  # compile outside guard
    with no_implicit_transfers():
        ts, loss = step(ts, x, y, None, None, key)
    assert np.isfinite(float(loss))
