"""Native C++ host runtime vs the numpy reference implementations.

The reference validates native helpers against the built-in path
(SURVEY §4, accelerated-vs-reference); here the ctypes-bound C++ codec
and record readers must agree exactly with the numpy fallbacks. Skipped
wholesale when no toolchain can build the library.
"""

import struct

import numpy as np
import pytest

from deeplearning4j_tpu.parallel import compression as C
from deeplearning4j_tpu.utils import native

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native library unavailable")


def test_codec_matches_numpy_roundtrip(rng):
    signs = rng.choice([-1, 0, 0, 1], size=1000).astype(np.int8)
    msg_native = native.encode(signs)
    msg_numpy = (C.encode_bitmap(signs)
                 if int(msg_native[0]) == C.BITMAP_ENCODING
                 else C.encode_flexible(signs))
    np.testing.assert_array_equal(msg_native, msg_numpy)
    np.testing.assert_array_equal(native.decode(msg_numpy), signs)


@pytest.mark.parametrize("density", [0.01, 0.5])
def test_codec_both_kinds(rng, density):
    signs = np.where(rng.random(513) < density,
                     rng.choice([-1, 1], size=513), 0).astype(np.int8)
    msg = native.encode(signs)
    np.testing.assert_array_equal(native.decode(msg), signs)


def test_decode_axpy_fused(rng):
    signs = rng.choice([-1, 0, 1], size=257).astype(np.int8)
    msg = native.encode(signs)
    acc = rng.normal(size=257).astype(np.float32)
    expect = acc + signs.astype(np.float32) * 0.125
    assert native.decode_axpy(msg, 0.125, acc)
    np.testing.assert_allclose(acc, expect, rtol=1e-6)


def test_decode_rejects_malformed():
    with pytest.raises(ValueError):
        native.decode(np.array([7, 10, 1, 3], np.int32))   # unknown kind
    with pytest.raises(ValueError):
        # flexible message with out-of-range index
        native.decode(np.array([0, 4, 1, 99], np.int32))


def test_csv_parser(rng):
    mat = rng.normal(size=(37, 5)).astype(np.float32)
    text = "\n".join(",".join(f"{v:.6g}" for v in row) for row in mat)
    out = native.parse_csv(text)
    np.testing.assert_allclose(out, mat, rtol=1e-5)


def test_csv_parser_crlf_and_blank_lines():
    text = "1,2,3\r\n\r\n4,5,6\r\n"
    out = native.parse_csv(text)
    np.testing.assert_allclose(out, [[1, 2, 3], [4, 5, 6]])


def test_csv_parser_rejects_ragged():
    with pytest.raises(ValueError):
        native.parse_csv("1,2,3\n4,5\n")


def test_idx_decoder():
    imgs = np.arange(2 * 3 * 4, dtype=np.uint8).reshape(2, 3, 4)
    raw = struct.pack(">BBBB", 0, 0, 0x08, 3)
    raw += struct.pack(">III", 2, 3, 4)
    raw += imgs.tobytes()
    arr, shape = native.decode_idx(raw)
    assert shape == (2, 3, 4)
    np.testing.assert_allclose(arr, imgs.astype(np.float32) / 255.0,
                               rtol=1e-6)


def test_idx_decoder_rejects_garbage():
    with pytest.raises(ValueError):
        native.decode_idx(b"\x00\x00\x42\x01\x00")


def test_compression_module_uses_native(rng):
    """compression.encode/decode route through the C++ codec and stay
    wire-compatible with the numpy implementation."""
    signs = rng.choice([-1, 0, 1], size=129).astype(np.int8)
    msg = C.encode(signs)
    np.testing.assert_array_equal(C.decode(msg), signs)


@pytest.mark.parametrize("n", [1, 15, 17, 100, 993])
def test_codec_tail_lengths(rng, n):
    """n % 16 != 0: the bitmap codec's word-packing tail must agree
    with numpy bit for bit (the historical class of codec bugs)."""
    signs = rng.choice([-1, 0, 0, 1], size=n).astype(np.int8)
    msg_native = native.encode(signs)
    msg_numpy = (C.encode_bitmap(signs)
                 if int(msg_native[0]) == C.BITMAP_ENCODING
                 else C.encode_flexible(signs))
    np.testing.assert_array_equal(msg_native, msg_numpy)
    np.testing.assert_array_equal(native.decode(msg_native), signs)


def test_codec_all_zero_signs():
    signs = np.zeros(65, np.int8)
    msg = native.encode(signs)
    np.testing.assert_array_equal(native.decode(msg), signs)


def test_dl4j_native_kill_switch(monkeypatch):
    """DL4J_NATIVE=0 disables the library for the CALL, not the
    process: every wrapper reports unavailable / returns None, and
    clearing the variable restores the loaded library without a
    reload."""
    assert native.available()
    monkeypatch.setenv("DL4J_NATIVE", "0")
    assert not native.available()
    assert not native.pairgen_available()
    assert native.encode(np.zeros(8, np.int8)) is None
    assert native.sm64_fill(1, 0, 4) is None
    monkeypatch.delenv("DL4J_NATIVE")
    assert native.available()
    assert native.encode(np.zeros(8, np.int8)) is not None
