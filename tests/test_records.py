"""Record readers + DataVec-bridge iterators (SURVEY §2.3)."""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.records import (
    AlignmentMode,
    CollectionRecordReader,
    CollectionSequenceRecordReader,
    CSVRecordReader,
    RecordReaderDataSetIterator,
    SequenceRecordReaderDataSetIterator,
)


def test_csv_record_reader(tmp_path):
    p = tmp_path / "data.csv"
    p.write_text("h1,h2,h3\n1,2,0\n3,4,1\n5,6,2\n")
    rr = CSVRecordReader(path=str(p), skip_lines=1)
    rows = list(rr)
    assert len(rows) == 3
    np.testing.assert_allclose(rows[1], [3, 4, 1])


def test_record_reader_dataset_iterator_classification(tmp_path):
    p = tmp_path / "iris-ish.csv"
    lines = [f"{i},{i*2},{i%3}" for i in range(10)]
    p.write_text("\n".join(lines))
    it = RecordReaderDataSetIterator(CSVRecordReader(path=str(p)),
                                     batch_size=4, label_index=2,
                                     num_classes=3)
    batches = list(it)
    assert [b.num_examples() for b in batches] == [4, 4, 2]
    assert batches[0].features.shape == (4, 2)
    assert batches[0].labels.shape == (4, 3)
    np.testing.assert_allclose(batches[0].labels[1],
                               [0, 1, 0])  # row 1 -> class 1


def test_record_reader_dataset_iterator_regression():
    recs = CollectionRecordReader([[1, 2, 3, 4], [5, 6, 7, 8]])
    it = RecordReaderDataSetIterator(recs, batch_size=2, label_index=2,
                                     label_index_to=3)
    b = next(iter(it))
    np.testing.assert_allclose(b.features, [[1, 2], [5, 6]])
    np.testing.assert_allclose(b.labels, [[3, 4], [7, 8]])


def test_classification_requires_num_classes():
    recs = CollectionRecordReader([[1, 0]])
    it = RecordReaderDataSetIterator(recs, batch_size=1, label_index=1)
    with pytest.raises(ValueError):
        list(it)


@pytest.mark.parametrize("alignment,where", [
    (AlignmentMode.ALIGN_START, "start"),
    (AlignmentMode.ALIGN_END, "end"),
])
def test_sequence_iterator_alignment(alignment, where):
    feats = CollectionSequenceRecordReader(
        [[[1, 1], [2, 2], [3, 3]], [[4, 4]]])
    labels = CollectionSequenceRecordReader([[[0]], [[1]]])
    it = SequenceRecordReaderDataSetIterator(
        feats, labels, batch_size=2, num_classes=2, alignment=alignment)
    b = next(iter(it))
    assert b.features.shape == (2, 3, 2)
    assert b.labels.shape == (2, 3, 2)
    if where == "start":
        np.testing.assert_allclose(b.features_mask, [[1, 1, 1], [1, 0, 0]])
        np.testing.assert_allclose(b.labels_mask, [[1, 0, 0], [1, 0, 0]])
        np.testing.assert_allclose(b.labels[1, 0], [0, 1])
    else:
        np.testing.assert_allclose(b.features_mask, [[1, 1, 1], [0, 0, 1]])
        np.testing.assert_allclose(b.labels_mask, [[0, 0, 1], [0, 0, 1]])
        np.testing.assert_allclose(b.labels[1, 2], [0, 1])


def test_sequence_equal_length_rejects_mismatch():
    feats = CollectionSequenceRecordReader([[[1], [2]]])
    labels = CollectionSequenceRecordReader([[[0]]])
    it = SequenceRecordReaderDataSetIterator(
        feats, labels, batch_size=1, num_classes=2,
        alignment=AlignmentMode.EQUAL_LENGTH)
    with pytest.raises(ValueError):
        list(it)


def test_single_reader_per_step_labels():
    """Single-reader mode: last column is the per-timestep class."""
    feats = CollectionSequenceRecordReader(
        [[[0.1, 0.0], [0.2, 1.0]]])
    it = SequenceRecordReaderDataSetIterator(
        feats, None, batch_size=1, num_classes=2)
    b = next(iter(it))
    assert b.features.shape == (1, 2, 1)
    np.testing.assert_allclose(b.labels[0], [[1, 0], [0, 1]])


def test_bridge_feeds_training():
    """End-to-end: CSV -> bridge -> fit (the reference's canonical
    CSV+RecordReaderDataSetIterator workflow)."""
    from deeplearning4j_tpu.models.multi_layer_network import (
        MultiLayerNetwork)
    from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.inputs import InputType
    from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
    from deeplearning4j_tpu.nn.layers.output import OutputLayer
    from deeplearning4j_tpu.ops.activations import Activation
    from deeplearning4j_tpu.ops.losses import LossFunction
    from deeplearning4j_tpu.optimize.updaters import Adam

    rng = np.random.default_rng(0)
    x = rng.normal(size=(60, 3)).astype(np.float32)
    y = (x.sum(1) > 0).astype(np.float32)
    text = "\n".join(
        ",".join(f"{v:.5f}" for v in row) + f",{int(c)}"
        for row, c in zip(x, y))
    it = RecordReaderDataSetIterator(
        CSVRecordReader(text=text), batch_size=20, label_index=3,
        num_classes=2)
    conf = (NeuralNetConfiguration.Builder()
            .seed(0).updater(Adam(5e-2)).list()
            .layer(DenseLayer(n_out=8, activation=Activation.TANH))
            .layer(OutputLayer(n_out=2, loss=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(3))
            .build())
    net = MultiLayerNetwork(conf).init()
    net.fit(it, epochs=30)
    assert net.evaluate(it).accuracy() > 0.9
