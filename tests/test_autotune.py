"""TunedConfig persistence + resolution contracts (ISSUE 20).

The contracts under test (optimize/autotune.py):

- **roundtrip**: a measured TunedConfig saved into an ArtifactStore
  reloads value-for-value (JSON-normalized) with outcome ``loaded``.
- **fingerprint discipline**: EVERY fingerprint field diverging —
  registry version, jax/jaxlib, backend platform/device kind, model
  weights, model version, format version — falls through to the
  committed defaults (empty value map) with outcome ``mismatch``, a
  reason naming the field, and a flight-recorder breadcrumb; never a
  crash. None-valued optional expectation fields (weights, model
  version) are wildcards.
- **corruption**: a blob mangled through the existing ``store.save``
  chaos seam fails its checksum at load, is quarantined
  (``.quarantine`` rename) and falls through to defaults; same for an
  unreadable manifest. The quarantine means the failure is paid once.
- **resolution ladder**: explicit argument > engine TunedConfig >
  process TunedConfig > committed default, in every consumer
  (ServingEngine geometry, RetrievalEngine nprobe/k-ladder where the
  index hint stays the fallback, fit's k_steps degrade-not-raise).
- **nprobe floor**: the sweep's ``choose`` can never pick a candidate
  excluded by the recall constraint, however fast — the measured
  0.941@32 spill case as a decision-level regression fixture.
- **lstm dispatch**: set_dispatch_rules overrides fused_wins at
  runtime and clears back to the committed (empty) table; the CPU
  sweep records an explicit scan-fallback decision.
- **cross-node**: node B (a subprocess) serves from node A's artifact
  via the shared store: loaded outcome, tuned geometry, node A's AOT
  table (zero live compiles), bitwise-identical outputs.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from deeplearning4j_tpu.chaos import plan as chaosplan
from deeplearning4j_tpu.chaos.plan import parse_plan
from deeplearning4j_tpu.observe.flight_recorder import FlightRecorder
from deeplearning4j_tpu.observe.registry import MetricsRegistry
from deeplearning4j_tpu.optimize import autotune
from deeplearning4j_tpu.optimize.autotune import (
    REGISTRY,
    TunedConfig,
    choose,
    load_tuned,
    resolve_tuned,
    save_tuned,
    set_process_tuned,
    tuned_value,
)
from deeplearning4j_tpu.parallel.aot_cache import ArtifactStore

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N_IN = 5


def _tiny_model(seed: int = 1):
    from deeplearning4j_tpu.models.multi_layer_network import (
        MultiLayerNetwork)
    from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.inputs import InputType
    from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
    from deeplearning4j_tpu.nn.layers.output import OutputLayer
    from deeplearning4j_tpu.ops.losses import LossFunction
    from deeplearning4j_tpu.optimize.updaters import Adam
    conf = (NeuralNetConfiguration.Builder().seed(seed)
            .updater(Adam(1e-2)).list()
            .layer(DenseLayer(n_out=8))
            .layer(OutputLayer(n_out=3, loss=LossFunction.MCXENT))
            .set_input_type(InputType.feed_forward(N_IN)).build())
    return MultiLayerNetwork(conf).init()


@pytest.fixture(autouse=True)
def _clean_process_state():
    """No test may leak an armed chaos plan or an installed process
    tuned config into the rest of the suite."""
    yield
    chaosplan.disarm()
    set_process_tuned(None)


def _fp(**over):
    fp = autotune.fingerprint()
    fp.update(over)
    return fp


def _measured(store_dir, values=None, **fp_over):
    cfg = TunedConfig(values or {"serving.batch_limit": 8},
                      fingerprint=_fp(**fp_over), source="measured")
    save_tuned(ArtifactStore(store_dir), cfg)
    return cfg


# ---------------------------------------------------------------------------
# roundtrip + fingerprint discipline
# ---------------------------------------------------------------------------


class TestRoundtrip:
    def test_save_load_roundtrip(self, tmp_path):
        values = {"serving.batch_limit": 16, "fit.k_steps": 4,
                  "retrieval.k_ladder": [10, 100]}
        cfg = TunedConfig(
            values,
            decisions={"fit.k_steps": {"tunable": "fit.k_steps",
                                       "value": 4, "reason": "r"}},
            fingerprint=_fp(), source="measured")
        save_tuned(ArtifactStore(str(tmp_path)), cfg)
        got = load_tuned(ArtifactStore(str(tmp_path)), expect=_fp(),
                         registry=MetricsRegistry())
        assert got.load_outcome == "loaded"
        assert json.dumps(got.values, sort_keys=True) == \
            json.dumps(values, sort_keys=True)
        assert got.decisions["fit.k_steps"]["value"] == 4

    def test_absent_artifact_falls_through(self, tmp_path):
        got = load_tuned(ArtifactStore(str(tmp_path)), expect=_fp(),
                         registry=MetricsRegistry())
        assert got.load_outcome == "absent"
        assert got.values == {}

    def test_manifest_written_atomically_last(self, tmp_path):
        """The blob exists before the manifest does — a reader racing
        the save either sees the complete pair or a clean miss."""
        _measured(str(tmp_path))
        d = tmp_path / "objects" / autotune.TUNED_KEY
        assert (d / autotune.TUNED_BLOB).exists()
        assert (d / autotune.TUNED_MANIFEST).exists()
        assert not (d / (autotune.TUNED_MANIFEST + ".tmp")).exists()

    def test_unknown_tunables_in_blob_are_dropped(self, tmp_path):
        """A future registry's extra keys don't poison an old reader."""
        _measured(str(tmp_path), values={"serving.batch_limit": 8,
                                         "not.a.tunable": 99})
        got = load_tuned(ArtifactStore(str(tmp_path)), expect=_fp(),
                         registry=MetricsRegistry())
        assert got.load_outcome == "loaded"
        assert got.get("serving.batch_limit") == 8
        assert "not.a.tunable" not in got.values

    def test_save_without_fingerprint_raises(self, tmp_path):
        with pytest.raises(ValueError):
            save_tuned(ArtifactStore(str(tmp_path)), TunedConfig())

    def test_expect_none_pins_nothing(self, tmp_path):
        """``expect=None`` accepts any artifact — an inspection tool
        reading a foreign store must not need the producing machine's
        fingerprint (and 'never raises' covers this path too)."""
        _measured(str(tmp_path), jax="0.0.0-other",
                  weights_sha256="0" * 64)
        got = load_tuned(ArtifactStore(str(tmp_path)), expect=None,
                         registry=MetricsRegistry())
        assert got.load_outcome == "loaded"
        assert got.get("serving.batch_limit") == 8


class TestFingerprintMismatch:
    # every field the manifest pins, each diverged one at a time
    FIELDS = [
        ("format_version", -1),
        ("registry_version", -1),
        ("jax", "0.0.0-other"),
        ("jaxlib", "0.0.0-other"),
        ("backend", {"platform": "tpu", "device_kind": "v5e"}),
        ("weights_sha256", "0" * 64),
        ("model_version", "other-model"),
    ]

    @pytest.mark.parametrize("field,bad", FIELDS,
                             ids=[f for f, _ in FIELDS])
    def test_each_field_mismatch_falls_through(self, tmp_path, field,
                                               bad):
        _measured(str(tmp_path), weights_sha256="a" * 64,
                  model_version="m1")
        rec = FlightRecorder(dump_dir=str(tmp_path / "fr"))
        expect = _fp(weights_sha256="a" * 64, model_version="m1")
        expect[field] = bad
        got = load_tuned(ArtifactStore(str(tmp_path)), expect=expect,
                         registry=MetricsRegistry(), recorder=rec)
        assert got.load_outcome == "mismatch"
        assert got.values == {}, \
            "a mismatched artifact must never apply values"
        assert field.split(".")[0] in got.load_reason
        crumb = rec._notes["autotune.tuned_config"]
        assert crumb["outcome"] == "mismatch"
        assert field in crumb["reason"]

    def test_none_expectation_fields_are_wildcards(self, tmp_path):
        """A machine-level consumer (expect carries no weights/model
        binding) accepts a model-bound artifact from the same machine."""
        _measured(str(tmp_path), weights_sha256="a" * 64,
                  model_version="m1")
        got = load_tuned(ArtifactStore(str(tmp_path)), expect=_fp(),
                         registry=MetricsRegistry())
        assert got.load_outcome == "loaded"

    def test_mismatch_counts_by_outcome(self, tmp_path):
        _measured(str(tmp_path))
        reg = MetricsRegistry()
        load_tuned(ArtifactStore(str(tmp_path)),
                   expect=_fp(jax="0.0.0-other"), registry=reg)
        text = reg.render()
        assert 'dl4j_autotune_artifact_loads_total{outcome="mismatch"}' \
            in text.replace("'", '"')


# ---------------------------------------------------------------------------
# corruption through the store.save chaos seam
# ---------------------------------------------------------------------------


def _arm(text: str):
    return chaosplan.arm(parse_plan(text, registry=MetricsRegistry()))


class TestCorruption:
    def test_corrupt_blob_quarantined(self, tmp_path):
        _arm("seed=3;store.save:corrupt(count=1,arg=blob)")
        _measured(str(tmp_path))
        chaosplan.disarm()
        rec = FlightRecorder(dump_dir=str(tmp_path / "fr"))
        got = load_tuned(ArtifactStore(str(tmp_path)), expect=_fp(),
                         registry=MetricsRegistry(), recorder=rec)
        assert got.load_outcome == "corrupt"
        assert got.values == {}
        d = tmp_path / "objects" / autotune.TUNED_KEY
        assert (d / (autotune.TUNED_BLOB + ".quarantine")).exists()
        assert not (d / autotune.TUNED_BLOB).exists()
        assert rec._notes["autotune.tuned_config"]["outcome"] == \
            "corrupt"

    def test_corrupt_manifest_quarantined(self, tmp_path):
        _arm("seed=3;store.save:corrupt(count=1,arg=manifest)")
        _measured(str(tmp_path))
        chaosplan.disarm()
        got = load_tuned(ArtifactStore(str(tmp_path)), expect=_fp(),
                         registry=MetricsRegistry())
        # a mangled manifest either fails JSON parse (quarantined,
        # corrupt) or parses to a diverged fingerprint (mismatch);
        # both are fall-throughs, never a crash
        assert got.load_outcome in ("corrupt", "mismatch")
        assert got.values == {}

    def test_quarantine_means_paid_once(self, tmp_path):
        _arm("seed=3;store.save:corrupt(count=1,arg=blob)")
        _measured(str(tmp_path))
        chaosplan.disarm()
        store = ArtifactStore(str(tmp_path))
        assert load_tuned(store, expect=_fp(),
                          registry=MetricsRegistry()
                          ).load_outcome == "corrupt"
        # second load: the quarantined blob is gone -> clean corrupt
        # fall-through again (blob unreadable), still no crash
        again = load_tuned(store, expect=_fp(),
                           registry=MetricsRegistry())
        assert again.load_outcome == "corrupt"
        assert again.values == {}

    def test_resave_after_quarantine_recovers(self, tmp_path):
        _arm("seed=3;store.save:corrupt(count=1,arg=blob)")
        _measured(str(tmp_path))
        chaosplan.disarm()
        store = ArtifactStore(str(tmp_path))
        load_tuned(store, expect=_fp(), registry=MetricsRegistry())
        _measured(str(tmp_path))           # a clean re-tune overwrites
        got = load_tuned(store, expect=_fp(),
                         registry=MetricsRegistry())
        assert got.load_outcome == "loaded"
        assert got.get("serving.batch_limit") == 8


# ---------------------------------------------------------------------------
# resolution ladder + consumers
# ---------------------------------------------------------------------------


class TestResolution:
    def test_ladder_explicit_beats_tuned_beats_default(self):
        cfg = TunedConfig({"serving.batch_limit": 16})
        assert resolve_tuned(64, cfg, "serving.batch_limit") == 64
        assert resolve_tuned(None, cfg, "serving.batch_limit") == 16
        assert resolve_tuned(None, None, "serving.batch_limit") == \
            REGISTRY["serving.batch_limit"].default

    def test_process_config_is_the_second_fallback(self):
        set_process_tuned(TunedConfig({"serving.batch_limit": 8}))
        engine_cfg = TunedConfig({"serving.batch_limit": 16})
        assert resolve_tuned(None, engine_cfg,
                             "serving.batch_limit") == 16
        assert resolve_tuned(None, None, "serving.batch_limit") == 8
        set_process_tuned(None)
        assert resolve_tuned(None, None, "serving.batch_limit") == 32

    def test_defaults_config_resolves_to_committed(self):
        cfg = TunedConfig.defaults()
        assert cfg.values == {}
        assert tuned_value("fit.k_steps", cfg) is None
        assert cfg.effective("fit.k_steps") == 1

    def test_serving_engine_sizes_from_tuned(self):
        from deeplearning4j_tpu.parallel.serving import ServingEngine
        model = _tiny_model()
        cfg = TunedConfig({"serving.batch_limit": 4})
        eng = ServingEngine(model, tuned_config=cfg,
                            feature_shape=(N_IN,),
                            registry=MetricsRegistry(),
                            session_id="t-tuned")
        try:
            assert eng.batch_limit == 4
            assert eng.ladder[-1] == 4
        finally:
            eng.shutdown()
        eng = ServingEngine(model, batch_limit=2, tuned_config=cfg,
                            feature_shape=(N_IN,),
                            registry=MetricsRegistry(),
                            session_id="t-explicit")
        try:
            assert eng.batch_limit == 2    # explicit beats tuned
        finally:
            eng.shutdown()

    def test_retrieval_engine_nprobe_ladder(self):
        from benchmarks.neighbors import blob_corpus
        from deeplearning4j_tpu.retrieval.engine import RetrievalEngine
        from deeplearning4j_tpu.retrieval.index import ShardedCorpusIndex
        corpus = blob_corpus(512, 8, k_blobs=8, seed=0)

        def _idx():
            # engines take ownership of an index's shard arrays, so
            # each gets its own (seeded-identical) build
            return ShardedCorpusIndex.build(corpus, shard_rows=512,
                                            ivf_clusters=8,
                                            nprobe_hint=3, seed=0)

        cfg = TunedConfig({"retrieval.nprobe": 5,
                           "retrieval.k_ladder": [10, 100]})
        eng = RetrievalEngine(_idx(), max_batch=4, tuned_config=cfg,
                              registry=MetricsRegistry(),
                              session_id="t-np")
        assert eng.nprobe == 5              # tuned beats the hint
        assert eng.k_ladder == (10, 100)    # tuned ladder applies
        eng2 = RetrievalEngine(_idx(), max_batch=4, nprobe=2,
                               tuned_config=cfg,
                               registry=MetricsRegistry(),
                               session_id="t-np2")
        assert eng2.nprobe == 2             # explicit beats tuned
        eng3 = RetrievalEngine(_idx(), max_batch=4,
                               registry=MetricsRegistry(),
                               session_id="t-np3")
        assert eng3.nprobe == 3             # no tuning -> index hint
        assert eng3.k_ladder == (1, 10, 100)

    def test_tuned_k_steps_degrades_without_feeder(self):
        """A machine-tuned fit.k_steps > 1 must not break a fit the
        feeder can't serve — only an EXPLICIT k_steps raises."""
        from benchmarks.input_pipeline import (SleepyIterator,
                                               build_model,
                                               make_batches)
        set_process_tuned(TunedConfig({"fit.k_steps": 4}))
        model = build_model(width=16)
        batches = make_batches(2, batch=4)
        # prefetch=0 disables the feeder; tuned k silently degrades
        model.fit(SleepyIterator(batches, 0.0), epochs=1, prefetch=0)
        with pytest.raises(ValueError):
            model.fit(SleepyIterator(batches, 0.0), epochs=1,
                      k_steps=4, prefetch=0)


# ---------------------------------------------------------------------------
# choose(): the decision rule + the nprobe floor fixture
# ---------------------------------------------------------------------------


class TestChoose:
    def test_higher_is_better_picks_max(self):
        d = choose(REGISTRY["serving.batch_limit"],
                   [(8, 100.0), (16, 150.0), (32, 120.0)])
        assert d["value"] == 16 and d["score"] == 150.0

    def test_lower_is_better_picks_min(self):
        d = choose(REGISTRY["generation.prefill_chunk"],
                   [(0, 40.0), (16, 25.0), (64, 30.0)])
        assert d["value"] == 16

    def test_tie_prefers_committed_default(self):
        d = choose(REGISTRY["serving.batch_limit"],
                   [(8, 100.0), (32, 100.0)])
        assert d["value"] == 32

    def test_excluded_candidate_never_wins(self):
        """The measured 0.941@32 spill case as a decision fixture:
        nprobe=32 is the fastest cell but sits below the recall floor
        — it must lose to the slower in-floor candidate."""
        d = choose(REGISTRY["retrieval.nprobe"],
                   [(32, 900.0), (64, 610.0)],
                   excluded={32: "recall@10 0.941 below the 0.95 "
                                 "floor"})
        assert d["value"] == 64
        assert d["excluded"] == [[32, "recall@10 0.941 below the 0.95 "
                                      "floor"]]

    def test_all_excluded_keeps_default(self):
        d = choose(REGISTRY["retrieval.nprobe"],
                   [(4, 900.0), (8, 800.0)],
                   excluded={4: "floor", 8: "floor"})
        assert d["value"] == REGISTRY["retrieval.nprobe"].default
        assert d["score"] is None
        assert "kept default" in d["reason"]


# ---------------------------------------------------------------------------
# lstm dispatch table
# ---------------------------------------------------------------------------


class TestLstmDispatch:
    def test_runtime_rules_override_and_clear(self):
        from deeplearning4j_tpu.ops import pallas_lstm
        assert not pallas_lstm.fused_wins(64, 256, 128)  # committed ()
        try:
            pallas_lstm.set_dispatch_rules([[32, 128, 64]])
            assert pallas_lstm.fused_wins(64, 256, 128)
            assert not pallas_lstm.fused_wins(8, 256, 128)
            assert pallas_lstm.dispatch_rules() == ((32, 128, 64),)
        finally:
            pallas_lstm.set_dispatch_rules(None)
        assert pallas_lstm.dispatch_rules() == ()

    def test_process_tuned_installs_rules(self):
        from deeplearning4j_tpu.ops import pallas_lstm
        set_process_tuned(TunedConfig(
            {"ops.lstm_dispatch": [[16, 64, 32]]}))
        assert pallas_lstm.fused_wins(16, 64, 32)
        set_process_tuned(None)
        assert not pallas_lstm.fused_wins(16, 64, 32)

    def test_cpu_sweep_records_explicit_fallback(self):
        """On a non-TPU backend the tuner must say WHY the table is
        empty, not leave it silently unpopulated."""
        import jax
        if jax.default_backend() == "tpu":
            pytest.skip("chip attached: the fallback branch is moot")
        from benchmarks.autotune import sweep_lstm_dispatch
        d = sweep_lstm_dispatch(rounds=1,
                                cells=MetricsRegistry().counter(
                                    "dl4j_autotune_cells_total", "t"))
        assert d["value"] == []
        assert d["impl"] == "scan"
        assert "scan fallback" in d["reason"]


# ---------------------------------------------------------------------------
# two-process cross-node load (node B serves node A's artifact)
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestCrossNode:
    def test_node_b_serves_node_a_artifact(self, tmp_path):
        from benchmarks.autotune import AOT_KEY
        from benchmarks.serving import build_model
        from deeplearning4j_tpu.observe.registry import MetricsRegistry
        from deeplearning4j_tpu.parallel.serving import ServingEngine

        store = ArtifactStore(str(tmp_path))
        # node A: a (hand-rolled) measured artifact bound to the bench
        # model's weights, plus its published AOT executable table
        model = build_model(width=64)
        fp = autotune.fingerprint(model.train_state.params,
                                  model_version="bench")
        cfg = TunedConfig({"serving.batch_limit": 8},
                          fingerprint=fp, source="measured")
        save_tuned(store, cfg)
        eng = ServingEngine(model, tuned_config=cfg,
                            feature_shape=(128,),
                            registry=MetricsRegistry(),
                            session_id="tune-consumer",
                            aot_cache_dir=store.cache_dir(AOT_KEY),
                            model_version="bench")
        try:
            x = np.random.default_rng(0).normal(
                size=(5, 128)).astype(np.float32)
            want = np.asarray(eng.output(x))
        finally:
            eng.shutdown()

        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        out = subprocess.run(
            [sys.executable, "-m", "benchmarks.autotune",
             "--verify-node", "--store", str(tmp_path),
             "--width", "64", "--seed", "0"],
            cwd=_ROOT, env=env, capture_output=True, text=True,
            timeout=600)
        assert out.returncode == 0, out.stdout + out.stderr
        report = json.loads(out.stdout.strip().splitlines()[-1])
        assert report["outcome"] == "loaded"
        assert report["batch_limit"] == 8
        assert report["recompiles"] == 0
        assert report["aot_hits"] >= 1, \
            "node B compiled instead of loading node A's AOT table"
        import hashlib
        assert report["digest"] == hashlib.sha256(
            want.tobytes()).hexdigest()
