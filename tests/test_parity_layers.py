"""Round-4 parity layers: RnnLossLayer, ElementWiseMultiplicationLayer,
MaskLayer, plus Sleepy/ParamAndGradient listeners (VERDICT r3 missing
#3/#5 — reference: nn/conf/layers/RnnLossLayer.java,
nn/conf/layers/misc/ElementWiseMultiplicationLayer.java,
nn/conf/layers/util/MaskLayer.java,
optimize/listeners/SleepyTrainingListener.java,
optimize/listeners/ParamAndGradientIterationListener.java)."""

import time

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.gradientcheck.gradient_check_util import (
    check_model_gradients,
)
from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.feedforward import (
    DenseLayer,
    ElementWiseMultiplicationLayer,
)
from deeplearning4j_tpu.nn.layers.misc import MaskLayer
from deeplearning4j_tpu.nn.layers.output import OutputLayer, RnnLossLayer
from deeplearning4j_tpu.nn.layers.recurrent import LSTM
from deeplearning4j_tpu.ops.activations import Activation
from deeplearning4j_tpu.ops.losses import LossFunction
from deeplearning4j_tpu.optimize.updaters import Sgd

RNG = np.random.default_rng(404)


def build(layers, input_type, seed=12345):
    b = NeuralNetConfiguration.Builder().seed(seed).updater(Sgd(0.1)).list()
    for l in layers:
        b = b.layer(l)
    return MultiLayerNetwork(b.set_input_type(input_type).build()).init()


# ---- ElementWiseMultiplicationLayer ---------------------------------------

def test_elementwise_mult_forward_math():
    m = build([ElementWiseMultiplicationLayer(activation=Activation.IDENTITY),
               OutputLayer(n_out=3)], InputType.feed_forward(5))
    x = RNG.normal(size=(4, 5))
    params = m.train_state.params
    # public activations API: first layer output must be x ⊙ w + b
    acts = m.feed_forward(x)
    w = np.asarray(params[list(params.keys())[0]]["W"])
    b = np.asarray(params[list(params.keys())[0]]["b"])
    np.testing.assert_allclose(np.asarray(acts[0]), x * w + b,
                               rtol=1e-5, atol=1e-6)


def test_elementwise_mult_rejects_mismatched_sizes():
    with pytest.raises(ValueError, match="same input"):
        ElementWiseMultiplicationLayer(n_in=4, n_out=6)


def test_elementwise_mult_gradients():
    y = np.zeros((6, 3))
    y[np.arange(6), RNG.integers(0, 3, 6)] = 1.0
    m = build([ElementWiseMultiplicationLayer(activation=Activation.TANH),
               OutputLayer(n_out=3, loss=LossFunction.MCXENT,
                           activation=Activation.SOFTMAX)],
              InputType.feed_forward(4))
    assert check_model_gradients(m, DataSet(RNG.normal(size=(6, 4)), y))


def test_elementwise_mult_serde_roundtrip():
    from deeplearning4j_tpu.utils.serde import from_json, to_json
    layer = ElementWiseMultiplicationLayer(n_in=7, n_out=7,
                                           activation=Activation.RELU)
    assert from_json(to_json(layer)) == layer


# ---- RnnLossLayer ---------------------------------------------------------

def test_rnn_loss_layer_trains_and_matches_identity_output():
    n, t, f = 4, 5, 3
    x = RNG.normal(size=(n, t, f))
    y = np.zeros((n, t, f))
    y[..., 0] = 1.0
    m = build([LSTM(n_out=f, activation=Activation.TANH),
               RnnLossLayer(loss=LossFunction.MCXENT,
                            activation=Activation.SOFTMAX)],
              InputType.recurrent(f))
    out = np.asarray(m.output(x))
    assert out.shape == (n, t, f)          # no projection: size == input
    np.testing.assert_allclose(out.sum(-1), np.ones((n, t)), rtol=1e-5)
    s0 = float(m.score(DataSet(x, y)))
    for _ in range(8):
        m.fit(DataSet(x, y))
    assert float(m.score(DataSet(x, y))) < s0


def test_rnn_loss_layer_masked_gradients():
    n, t, f = 4, 6, 3
    x = RNG.normal(size=(n, t, f))
    y = np.zeros((n, t, f))
    y[..., RNG.integers(0, f)] = 1.0
    mask = np.ones((n, t))
    mask[:, 4:] = 0.0
    m = build([LSTM(n_out=f, activation=Activation.TANH),
               RnnLossLayer(loss=LossFunction.MCXENT,
                            activation=Activation.SOFTMAX)],
              InputType.recurrent(f))
    assert check_model_gradients(
        m, DataSet(x, y, features_mask=mask, labels_mask=mask))


def test_rnn_loss_layer_rejects_flat_input():
    with pytest.raises(ValueError, match="recurrent"):
        build([DenseLayer(n_out=4), RnnLossLayer()],
              InputType.feed_forward(4))


# ---- MaskLayer ------------------------------------------------------------

def test_mask_layer_zeroes_masked_timesteps():
    n, t, f = 3, 5, 4
    x = RNG.normal(size=(n, t, f))
    mask = np.ones((n, t))
    mask[:, 3:] = 0.0
    m = build([MaskLayer(),
               RnnLossLayer(loss=LossFunction.MSE,
                            activation=Activation.IDENTITY)],
              InputType.recurrent(f))
    out = np.asarray(m.output(x, mask=mask))
    np.testing.assert_allclose(out[:, 3:], 0.0, atol=1e-7)
    np.testing.assert_allclose(out[:, :3], x[:, :3], rtol=1e-5)


def test_mask_layer_no_mask_is_identity():
    n, t, f = 2, 4, 3
    x = RNG.normal(size=(n, t, f))
    m = build([MaskLayer(),
               RnnLossLayer(loss=LossFunction.MSE,
                            activation=Activation.IDENTITY)],
              InputType.recurrent(f))
    np.testing.assert_allclose(np.asarray(m.output(x)), x, rtol=1e-5)


def test_mask_layer_gradient_check_with_mask():
    n, t, f = 4, 5, 3
    x = RNG.normal(size=(n, t, f))
    y = RNG.normal(size=(n, t, f))
    mask = np.ones((n, t))
    mask[:, 3:] = 0.0
    m = build([LSTM(n_out=f, activation=Activation.TANH),
               MaskLayer(),
               RnnLossLayer(loss=LossFunction.MSE,
                            activation=Activation.IDENTITY)],
              InputType.recurrent(f))
    assert check_model_gradients(
        m, DataSet(x, y, features_mask=mask, labels_mask=mask))


# ---- listeners ------------------------------------------------------------

def _tiny_model():
    return build([DenseLayer(n_out=4, activation=Activation.TANH),
                  OutputLayer(n_out=2)], InputType.feed_forward(3))


def _tiny_ds():
    x = RNG.normal(size=(8, 3))
    y = np.zeros((8, 2))
    y[np.arange(8), RNG.integers(0, 2, 8)] = 1.0
    return DataSet(x, y)


def test_sleepy_listener_throttles_iterations():
    from deeplearning4j_tpu.optimize.listeners import SleepyTrainingListener
    m = _tiny_model()
    ds = _tiny_ds()
    m.fit(ds)                               # compile outside the clock
    t0 = time.perf_counter()
    for _ in range(3):
        m.fit(ds)
    base = time.perf_counter() - t0
    m.set_listeners(SleepyTrainingListener(timer_iteration_ms=50))
    t0 = time.perf_counter()
    for _ in range(3):
        m.fit(ds)
    slept = time.perf_counter() - t0
    assert slept >= base + 0.1              # 3 × 50 ms of sleep

def test_sleepy_listener_connected_mode_subtracts_elapsed():
    from deeplearning4j_tpu.optimize.listeners import SleepyTrainingListener
    lst = SleepyTrainingListener(timer_iteration_ms=80,
                                 time_mode="connected")
    lst.iteration_done(None, 0, 0, 0.0, 0.0, 8)   # first: full sleep
    time.sleep(0.1)                                # > timer elapses
    t0 = time.perf_counter()
    lst.iteration_done(None, 1, 0, 0.0, 0.0, 8)   # target already met
    assert time.perf_counter() - t0 < 0.05


def test_param_and_gradient_listener_writes_stats(tmp_path):
    from deeplearning4j_tpu.optimize.listeners import (
        ParamAndGradientIterationListener)
    path = str(tmp_path / "pg.tsv")
    m = _tiny_model()
    m.set_listeners(ParamAndGradientIterationListener(
        output_to_console=False, file=path))
    ds = _tiny_ds()
    for _ in range(3):
        m.fit(ds)
    lines = open(path).read().strip().split("\n")
    assert len(lines) == 4                  # header + 3 iterations
    header = lines[0].split("\t")
    assert header[0] == "iteration" and header[1] == "score"
    assert any(c.startswith("param_") and c.endswith("_mean")
               for c in header)
    assert any(c.startswith("update_") and c.endswith("_meanAbs")
               for c in header)
    row = lines[2].split("\t")
    assert len(row) == len(header)
    vals = np.array([float(v) for v in row[2:]])
    assert np.isfinite(vals).all()
    # updates are non-zero from the second reported iteration on
    upd_cols = [i for i, c in enumerate(header) if c.startswith("update_")]
    assert np.abs(np.array([float(lines[3].split("\t")[i])
                            for i in upd_cols])).sum() > 0
