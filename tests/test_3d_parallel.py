"""3D dp×tp×pp composition (VERDICT r4 #3): one mesh carrying data,
model, and pipe axes — GSPMD dp batch sharding + Megatron TP inside each
stage + the circular pipeline schedule (shard_map manual over 'pipe'
only). Golden-tested against the sequential single-stack math, plus
sharded checkpoint save→restore across DIFFERENT 3D layouts."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.pipeline import (
    PIPE_AXIS, PipelinedTransformerLM, restack_stages)


VOCAB, WIDTH, T = 16, 8, 6

# The dp×tp×pp composition needs PARTIAL-AUTO shard_map (manual over
# 'pipe', GSPMD-auto over 'data'/'model').  jax 0.4.x lowers that to
# HLO the bundled XLA rejects — axis_index becomes a PartitionId op the
# SPMD partitioner calls ambiguous, and manual-subgroup shardings trip
# CHECK failures in spmd_partitioner.cc even for a minimal
# ppermute+psum body.  jax >= 0.5 (jax.shard_map with axis_names=)
# fixed the lowering; single-axis (fully-manual) meshes work on both.
_partial_auto_ok = pytest.mark.skipif(
    not hasattr(jax, "shard_map"),
    reason="partial-auto shard_map needs jax>=0.5; 0.4.x SPMD "
           "partitioner cannot lower manual-subgroup collectives")


def _mesh(dp, tp, pp):
    devs = np.asarray(jax.devices()[: dp * tp * pp]).reshape(dp, tp, pp)
    return Mesh(devs, ("data", "model", PIPE_AXIS))


def _lm(mesh, n_layers):
    return PipelinedTransformerLM(vocab=VOCAB, width=WIDTH, n_heads=2,
                                  n_layers=n_layers, max_len=T,
                                  mesh=mesh, remat=True)


def _data(batch, seed=0):
    rng = np.random.default_rng(seed)
    return (jnp.asarray(rng.integers(0, VOCAB, (batch, T))),
            jnp.asarray(rng.integers(0, VOCAB, (batch, T))))


class Test3DComposition:
    @_partial_auto_ok
    def test_pipelined_tp_matches_sequential(self):
        mesh = _mesh(2, 2, 2)
        lm = _lm(mesh, n_layers=4)
        params = lm.shard_params(lm.init(jax.random.PRNGKey(3)))
        assert not params["blocks"]["attn"]["Wqkv"].sharding \
            .is_fully_replicated
        toks, tgts = _data(8)
        toks = jax.device_put(toks, NamedSharding(mesh, P("data", None)))
        tgts = jax.device_put(tgts, NamedSharding(mesh, P("data", None)))
        with mesh:
            pipelined = float(jax.jit(lm.loss)(params, toks, tgts))
            ref = float(lm.loss(params, toks, tgts, pipelined=False))
        assert pipelined == pytest.approx(ref, rel=1e-5)

    @_partial_auto_ok
    def test_3d_train_step_moves_params(self):
        mesh = _mesh(2, 2, 2)
        lm = _lm(mesh, n_layers=4)
        params = lm.shard_params(lm.init(jax.random.PRNGKey(4)))
        toks, tgts = _data(8, seed=1)

        @jax.jit
        def step(p, toks, tgts):
            loss, g = jax.value_and_grad(lm.loss)(p, toks, tgts)
            return jax.tree_util.tree_map(
                lambda a, b: a - 0.1 * b, p, g), loss

        with mesh:
            p1, l1 = step(params, toks, tgts)
            p2, l2 = step(p1, toks, tgts)
        assert np.isfinite(float(l1)) and float(l2) < float(l1)
        # TP sharding survives the update
        assert not p2["blocks"]["attn"]["Wqkv"].sharding \
            .is_fully_replicated


class Test3DCheckpointResharding:
    @_partial_auto_ok
    def test_cross_layout_restore(self, tmp_path):
        """Save on a 2dp×2tp×2pp layout (circular, 2 stages × 2
        repeats), restore onto 1dp×2tp×4pp (4 straight stages) — the
        stage-dim restack + explicit target shardings must reproduce
        the exact same function."""
        from types import SimpleNamespace

        from deeplearning4j_tpu.optimize.solver import TrainState
        from deeplearning4j_tpu.parallel.checkpoint import (
            restore_sharded, save_sharded)

        mesh_a = _mesh(2, 2, 2)
        lm_a = _lm(mesh_a, n_layers=4)
        params_a = lm_a.shard_params(lm_a.init(jax.random.PRNGKey(7)))
        toks, tgts = _data(4, seed=2)
        with mesh_a:
            ref = float(jax.jit(lm_a.loss)(params_a, toks, tgts))

        ts = TrainState(params_a, {}, {}, jnp.zeros((), jnp.int32))
        path = save_sharded(ts, str(tmp_path))

        mesh_b = _mesh(1, 2, 4)
        lm_b = _lm(mesh_b, n_layers=4)
        tmpl = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), params_a)
        shim = SimpleNamespace(train_state=TrainState(
            tmpl, {}, {}, jnp.zeros((), jnp.int32)))
        restored = restore_sharded(
            shim, path, mesh=mesh_b,
            param_shardings=lm_b.param_shardings(tmpl))
        params_b = dict(restored.params)
        # layout A stores device-major (2 stages × 2 repeats): global
        # stage order [0,2,1,3]; layout B (4 stages × 1) wants [0,1,2,3]
        params_b["blocks"] = restack_stages(
            params_b["blocks"], from_devices=2, to_devices=4)
        with mesh_b:
            got = float(jax.jit(lm_b.loss)(params_b, toks, tgts))
        assert got == pytest.approx(ref, rel=1e-5)

    def test_restack_roundtrip(self):
        x = {"w": jnp.arange(8.0).reshape(8, 1)}
        there = restack_stages(x, from_devices=4, to_devices=2)
        back = restack_stages(there, from_devices=2, to_devices=4)
        np.testing.assert_array_equal(back["w"], x["w"])
