"""Clustering/NN (SURVEY §2.10), t-SNE (§2.9), graph embeddings (§2.8)."""

import json
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.clustering import (
    KDTree,
    KMeansClustering,
    RandomProjection,
    RandomProjectionLSH,
    SpTree,
    VPTree,
)
from deeplearning4j_tpu.clustering.server import NearestNeighborsServer
from deeplearning4j_tpu.graph import (
    DeepWalk,
    Graph,
    GraphVectors,
    RandomWalkIterator,
    WeightedRandomWalkIterator,
)
from deeplearning4j_tpu.manifold import BarnesHutTsne, Tsne


def _blobs(n_per=40, seed=0):
    rng = np.random.default_rng(seed)
    cs = np.array([[0, 0], [8, 8], [0, 8]], np.float64)
    pts = np.concatenate([c + rng.normal(scale=0.5, size=(n_per, 2))
                          for c in cs])
    labels = np.repeat(np.arange(3), n_per)
    return pts, labels


class TestKMeans:
    def test_recovers_blobs(self):
        pts, labels = _blobs()
        km = KMeansClustering.setup(3, max_iterations=50).apply_to(pts)
        assert km.inertia_ < 200
        # each true cluster maps to exactly one predicted cluster
        for t in range(3):
            pred = km.labels_[labels == t]
            assert len(set(pred.tolist())) == 1
        # predict matches training assignment
        np.testing.assert_array_equal(km.predict(pts), km.labels_)

    def test_too_few_points(self):
        with pytest.raises(ValueError, match="points < "):
            KMeansClustering(5).apply_to(np.zeros((3, 2)))


class TestTrees:
    def test_vptree_exact(self):
        pts, _ = _blobs(seed=1)
        tree = VPTree(pts)
        q = pts[7]
        idxs, dists = tree.search(q, 5)
        # brute force reference
        d = np.linalg.norm(pts - q, axis=1)
        want = np.argsort(d)[:5]
        assert set(idxs) == set(want.tolist())
        assert dists == sorted(dists)

    def test_vptree_cosine(self):
        rng = np.random.default_rng(3)
        pts = rng.normal(size=(50, 8))
        tree = VPTree(pts, distance="cosine")
        q = pts[11]
        idxs, _ = tree.search(q, 1)
        assert idxs[0] == 11

    def test_kdtree_matches_bruteforce(self):
        pts, _ = _blobs(seed=2)
        tree = KDTree(pts)
        q = np.array([1.0, 1.0])
        idxs, dists = tree.knn(q, 4)
        d = np.linalg.norm(pts - q, axis=1)
        assert set(idxs) == set(np.argsort(d)[:4].tolist())
        idx, dist = tree.nearest(q)
        assert idx == int(np.argmin(d))

    def test_sptree_forces_match_exact(self):
        rng = np.random.default_rng(4)
        y = rng.normal(size=(30, 2))
        tree = SpTree(y)
        i = 5
        # theta=0 → exact: compare against brute-force negative forces
        neg, sum_q = tree.compute_non_edge_forces(i, theta=0.0)
        diff = y[i] - np.delete(y, i, axis=0)
        q = 1.0 / (1.0 + np.sum(diff * diff, axis=1))
        np.testing.assert_allclose(sum_q, q.sum(), rtol=1e-9)
        np.testing.assert_allclose(neg, ((q * q)[:, None] * diff).sum(0),
                                   rtol=1e-9, atol=1e-12)

    def test_lsh_and_projection(self):
        rng = np.random.default_rng(5)
        pts = rng.normal(size=(200, 16))
        lsh = RandomProjectionLSH(n_bits=8, n_tables=6).index(pts)
        idxs, dists = lsh.search(pts[17], 3)
        assert idxs[0] == 17 and dists[0] < 1e-9
        rp = RandomProjection(4)
        out = rp.fit_transform(pts)
        assert out.shape == (200, 4)


class TestNearestNeighborsServer:
    def test_rest_knn(self):
        pts, _ = _blobs(seed=6)
        server = NearestNeighborsServer(pts).start()
        try:
            req = urllib.request.Request(
                server.url + "/knn",
                data=json.dumps({"vector": pts[3].tolist(),
                                 "k": 3}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req) as r:
                res = json.loads(r.read())["results"]
            assert res[0]["index"] == 3
            assert len(res) == 3
            # query by stored index + bad request
            req = urllib.request.Request(
                server.url + "/knn",
                data=json.dumps({"index": 5, "k": 2}).encode())
            with urllib.request.urlopen(req) as r:
                assert json.loads(r.read())["results"][0]["index"] == 5
            req = urllib.request.Request(server.url + "/knn",
                                         data=b"{}")
            try:
                urllib.request.urlopen(req)
                assert False, "expected 400"
            except urllib.error.HTTPError as e:
                assert e.code == 400
        finally:
            server.stop()


class TestTsne:
    def test_separates_blobs(self):
        pts, labels = _blobs(n_per=25, seed=7)
        ts = Tsne(perplexity=12.0, n_iter=300, seed=0)
        y = ts.fit_transform(pts)
        assert y.shape == (75, 2)
        assert np.isfinite(ts.kl_divergence_)
        # cluster centroids in embedding space are separated vs intra-spread
        cents = np.stack([y[labels == t].mean(0) for t in range(3)])
        intra = max(np.linalg.norm(y[labels == t] - cents[t], axis=1)
                    .mean() for t in range(3))
        inter = min(np.linalg.norm(cents[a] - cents[b])
                    for a in range(3) for b in range(a + 1, 3))
        assert inter > 2 * intra

    def test_barnes_hut_runs(self):
        pts, labels = _blobs(n_per=15, seed=8)
        bh = BarnesHutTsne(theta=0.5, perplexity=10.0, n_iter=120, seed=0)
        y = bh.fit_transform(pts)
        assert y.shape == (45, 2)
        assert np.isfinite(y).all()
        cents = np.stack([y[labels == t].mean(0) for t in range(3)])
        inter = min(np.linalg.norm(cents[a] - cents[b])
                    for a in range(3) for b in range(a + 1, 3))
        assert inter > 0.1


def _two_cliques(k=6):
    """Two k-cliques joined by one bridge edge → embeddings must cluster."""
    edges = []
    for a in range(k):
        for b in range(a + 1, k):
            edges.append((a, b))
            edges.append((k + a, k + b))
    edges.append((0, k))
    return Graph.from_edges(2 * k, edges)


class TestGraph:
    def test_walks(self):
        g = _two_cliques()
        walks = list(RandomWalkIterator(g, walk_length=5, seed=0))
        assert len(walks) == g.num_vertices()
        for w in walks:
            assert len(w) == 5
            for a, b in zip(w, w[1:]):
                assert b in g.get_connected_vertices(a) or a == b

    def test_weighted_walks_respect_weights(self):
        g = Graph(3, directed=True)
        g.add_edge(0, 1, weight=1000.0)
        g.add_edge(0, 2, weight=0.001)
        nxt = []
        for s in range(20):
            it = WeightedRandomWalkIterator(g, 2, seed=s)
            walk0 = next(w for w in it if w[0] == 0)
            nxt.append(walk0[1])
        assert nxt.count(1) >= 18

    def test_deepwalk_clusters_cliques(self):
        g = _two_cliques()
        dw = DeepWalk(vector_size=16, window_size=3, walk_length=10,
                      walks_per_vertex=8, epochs=5, seed=1,
                      learning_rate=0.05)
        dw.initialize(g)
        dw.fit(g)
        same = dw.similarity_vertices(1, 2)      # same clique
        cross = dw.similarity_vertices(1, 8)     # other clique
        assert same > cross
        gv = GraphVectors.from_deepwalk(dw)
        assert gv.num_vertices() == 12
        assert gv.similarity(1, 2) == pytest.approx(same, abs=1e-5)

    def test_graph_vectors_roundtrip(self, tmp_path):
        gv = GraphVectors(np.random.default_rng(0).normal(
            size=(5, 4)).astype(np.float32))
        p = str(tmp_path / "gv.npz")
        gv.save(p)
        gv2 = GraphVectors.load(p)
        np.testing.assert_allclose(gv2.get_vertex_vector(2),
                                   gv.get_vertex_vector(2))
