"""graftlint v2 tests: the interprocedural layer (summaries, call
graph, fixed points), the distributed-systems rule pack
(deadline-propagation, release-discipline, atomic-write,
metric-hygiene), the chaos seam-coverage audit, the content-hash
summary cache, and the SARIF report.

True-positive fixtures reproduce the historical bug shapes verbatim:
the PR 14 ui-ingress deadline drop and the PR 11 retry-loop inflight
leak. Each has a matching false-positive guard showing the fixed
shape stays clean.
"""

from __future__ import annotations

import ast
import io
import json
import subprocess
import sys
import textwrap
import time
from pathlib import Path

import pytest

from tools.graftlint import REPO_ROOT, get_rules, scan
from tools.graftlint.baseline import fingerprints
from tools.graftlint.cache import SummaryCache
from tools.graftlint.callgraph import CallGraph
from tools.graftlint.engine import ModuleContext, Project
from tools.graftlint.report import render_sarif
from tools.graftlint.rules.chaos_hygiene import ChaosHygieneRule
from tools.graftlint.summaries import build_module_summary

pytestmark = pytest.mark.filterwarnings("ignore::DeprecationWarning")


def lint(tmp_path: Path, source: str, rules, name="snippet.py"):
    f = tmp_path / name
    f.write_text(textwrap.dedent(source), encoding="utf-8")
    return scan([str(f)], rules=get_rules(rules))


def corpus_scan(tmp_path: Path, files, rules):
    """Write {relpath: source} under tmp_path and scan the tree with
    root=tmp_path so cross-module imports resolve inside the
    fixture corpus."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src), encoding="utf-8")
    return scan([str(tmp_path)], rules=get_rules(rules), root=tmp_path)


def summarize(module: str, rel: str, source: str):
    text = textwrap.dedent(source)
    return build_module_summary(ast.parse(text), text, module, rel)


# ---------------------------------------------------------------------------
# summary + call-graph layer
# ---------------------------------------------------------------------------

class TestSummaryLayer:
    def test_deadline_taint_through_derived_timeout(self):
        ms = summarize("m", "m.py", """
            def caller(x, deadline):
                budget = deadline.remaining_s()
                capped = min(budget, 5.0)
                return post(x, timeout=capped)

            def post(x, timeout=None):
                return x
        """)
        cs = [c for c in ms.functions["caller"].calls
              if c.callee == "post"]
        assert len(cs) == 1
        # timeout=capped is derived from the deadline two assignments
        # deep: the taint closure must mark the site as forwarding
        assert cs[0].passes_deadline

    def test_explicit_deadline_kwarg_and_star_kw(self):
        ms = summarize("m", "m.py", """
            def a(x, deadline):
                return post(x, deadline=deadline)

            def b(x, deadline, **kw):
                return post(x, **kw)
        """)
        [ca] = ms.functions["a"].calls
        assert ca.passes_deadline
        [cb] = ms.functions["b"].calls
        assert cb.has_star_kw and not cb.passes_deadline

    def test_exception_edge_leaves_resource_held(self):
        ms = summarize("m", "m.py", """
            class P:
                def leak(self, item):
                    self._sem.acquire()
                    handle(item)
                    self._sem.release()

                def ok(self, item):
                    self._sem.acquire()
                    try:
                        handle(item)
                    finally:
                        self._sem.release()
        """)
        leak = ms.functions["P.leak"].resource_issues
        assert any(ri.kind == "exception" for ri in leak)
        assert ms.functions["P.ok"].resource_issues == ()

    def test_local_tallies_are_not_resources(self):
        ms = summarize("m", "m.py", """
            def count(items):
                pending = 0
                for it in items:
                    pending = pending + 1
                return pending
        """)
        assert ms.functions["count"].resource_issues == ()

    def test_cross_module_resolution_via_from_import(self):
        mods = {
            "a": summarize("a", "a.py", """
                from b import helper

                def caller(x):
                    return helper(x)
            """),
            "b": summarize("b", "b.py", """
                def helper(x):
                    return x
            """),
        }
        cg = CallGraph(mods)
        assert cg.resolve("a", "caller", "helper") == ("b::helper",)

    def test_fixed_point_terminates_on_mutual_recursion(self):
        mods = {
            "a": summarize("a", "a.py", """
                from b import pong

                def ping(n):
                    return pong(n - 1)
            """),
            "b": summarize("b", "b.py", """
                from a import ping

                def pong(n):
                    if n > 0:
                        return ping(n)
                    return seam()

                def seam():
                    return 0
            """),
        }
        cg = CallGraph(mods)
        reach = cg.reaching({"b::seam"})
        # both halves of the cycle reach the seam; the worklist must
        # terminate despite a::ping <-> b::pong
        assert {"a::ping", "b::pong", "b::seam"} <= reach
        fwd = cg.reachable_from({"a::ping"})
        assert {"a::ping", "b::pong", "b::seam"} <= fwd


# ---------------------------------------------------------------------------
# deadline-propagation (the PR 14 shape)
# ---------------------------------------------------------------------------

PR14_SEAM = """
    class RemoteDispatcher:
        def predict(self, x, deadline=None):
            return x

    _DISP = RemoteDispatcher()

    def run_infer(x, deadline=None):
        return _DISP.predict(x, deadline=deadline)
"""


class TestDeadlinePropagation:
    def test_pr14_ui_drop_flagged(self, tmp_path):
        findings = corpus_scan(tmp_path, {
            "gw.py": PR14_SEAM,
            "ui/handlers.py": """
                from gw import run_infer

                def handle(req, deadline):
                    # the PR 14 first-draft bug: ingress parses the
                    # deadline then forgets it one hop in
                    return run_infer(req)
            """,
        }, rules=["deadline-propagation"])
        assert len(findings) == 1
        assert findings[0].path.name == "handlers.py"
        assert "without it" in findings[0].message

    def test_forwarded_deadline_is_clean(self, tmp_path):
        findings = corpus_scan(tmp_path, {
            "gw.py": PR14_SEAM,
            "ui/handlers.py": """
                from gw import run_infer

                def handle(req, deadline):
                    return run_infer(req, deadline=deadline)
            """,
        }, rules=["deadline-propagation"])
        assert findings == []

    def test_derived_timeout_counts_as_forwarding(self, tmp_path):
        findings = corpus_scan(tmp_path, {
            "gw.py": """
                class ServingEngine:
                    def submit(self, x, deadline=None):
                        return x

                _E = ServingEngine()

                def run_infer(x, timeout=None, deadline=None):
                    return _E.submit(x, deadline=deadline)
            """,
            "ui/handlers.py": """
                from gw import run_infer

                def handle(req, deadline):
                    budget = deadline.remaining_s()
                    return run_infer(req, timeout=budget)
            """,
        }, rules=["deadline-propagation"])
        assert findings == []

    def test_callee_that_cannot_carry_flagged(self, tmp_path):
        findings = corpus_scan(tmp_path, {
            "gw.py": """
                class ServingEngine:
                    def submit(self, x, deadline=None):
                        return x

                _E = ServingEngine()

                def run_nc(x):
                    return _E.submit(x)
            """,
            "ui/handlers.py": """
                from gw import run_nc

                def handle(req, deadline):
                    return run_nc(req)
            """,
        }, rules=["deadline-propagation"])
        assert len(findings) == 1
        assert "cannot carry" in findings[0].message

    def test_off_path_deadline_holder_is_clean(self, tmp_path):
        # a deadline-holding function NOT reachable from any ui
        # ingress (e.g. an executor helper) must not be flagged even
        # though its callee reaches a seam
        findings = corpus_scan(tmp_path, {
            "gw.py": PR14_SEAM,
            "worker.py": """
                from gw import run_infer

                def background(req, deadline):
                    return run_infer(req)
            """,
        }, rules=["deadline-propagation"])
        assert findings == []


# ---------------------------------------------------------------------------
# release-discipline (the PR 11 shape)
# ---------------------------------------------------------------------------

PR11_SHAPE = """
    class Dispatcher:
        # the PR 11 inflight-accounting bug: increment, transport
        # raises, retry increments the NEXT node — the first node's
        # count never comes down and least-loaded routing starves it
        def send(self, nodes, payload):
            for n in nodes:
                self._inflight[n] = self._inflight.get(n, 0) + 1
                try:
                    return self._post(n, payload)
                except OSError:
                    continue
"""


class TestReleaseDiscipline:
    def test_pr11_retry_reacquire_flagged(self, tmp_path):
        findings = lint(tmp_path, PR11_SHAPE,
                        rules=["release-discipline"])
        assert any("re-acquires" in f.message for f in findings)

    def test_finally_release_before_retry_is_clean(self, tmp_path):
        findings = lint(tmp_path, """
            class Dispatcher:
                def send(self, nodes, payload):
                    for n in nodes:
                        self._inflight[n] = \\
                            self._inflight.get(n, 0) + 1
                        try:
                            return self._post(n, payload)
                        except OSError:
                            continue
                        finally:
                            self._inflight[n] = \\
                                self._inflight.get(n, 0) - 1
        """, rules=["release-discipline"])
        assert findings == []

    def test_exception_edge_flagged_at_acquire_line(self, tmp_path):
        findings = lint(tmp_path, """
            class Pool:
                def submit(self, item):
                    self._sem.acquire()
                    out = self._process(item)
                    self._sem.release()
                    return out
        """, rules=["release-discipline"])
        assert len(findings) == 1
        assert "exception edge" in findings[0].message
        assert "acquire()" in findings[0].snippet

    def test_exit_path_flagged(self, tmp_path):
        findings = lint(tmp_path, """
            class Pool:
                def claim(self, ok):
                    self._sem.acquire()
                    if ok:
                        self._sem.release()
                        return True
                    return False
        """, rules=["release-discipline"])
        assert len(findings) == 1
        assert "return/fall-through" in findings[0].message

    def test_pragma_documents_cross_method_handoff(self, tmp_path):
        findings = lint(tmp_path, """
            class Pool:
                def submit(self, item):
                    self._sem.acquire()  # graftlint: disable=release-discipline: released by the done-callback
                    return self._spawn(item)
        """, rules=["release-discipline"])
        assert findings == []


# ---------------------------------------------------------------------------
# atomic-write
# ---------------------------------------------------------------------------

class TestAtomicWrite:
    def test_direct_shared_write_flagged(self, tmp_path):
        findings = lint(tmp_path, """
            import json

            def publish(path, records):
                with open(path, "w") as f:
                    json.dump(records, f)
        """, rules=["atomic-write"])
        assert len(findings) == 1
        assert "torn record" in findings[0].message

    def test_tmp_then_replace_is_clean(self, tmp_path):
        findings = lint(tmp_path, """
            import json
            import os
            import tempfile

            def publish(path, records):
                fd, tmp = tempfile.mkstemp(
                    dir=os.path.dirname(path))
                with os.fdopen(fd, "w") as f:
                    json.dump(records, f)
                os.replace(tmp, path)
        """, rules=["atomic-write"])
        assert findings == []

    def test_read_modes_ignored(self, tmp_path):
        findings = lint(tmp_path, """
            def load(path):
                with open(path) as f:
                    return f.read()
        """, rules=["atomic-write"])
        assert findings == []

    def test_scoped_to_shared_path_modules_in_repo(self):
        # ui/stats.py is inside the repo but off the shared-path
        # list: the rule must skip it entirely
        findings = scan(["deeplearning4j_tpu/ui/stats.py"],
                        rules=get_rules(["atomic-write"]))
        assert findings == []


# ---------------------------------------------------------------------------
# metric-hygiene
# ---------------------------------------------------------------------------

class TestMetricHygiene:
    def test_label_drift_vs_catalog_flagged(self, tmp_path):
        (tmp_path / "OBSERVABILITY.md").write_text(
            "- `dl4j_fix_hits_total{session, node}` — per-node hits\n",
            encoding="utf-8")
        findings = corpus_scan(tmp_path, {
            "metrics.py": """
                def report(reg, session):
                    reg.counter("dl4j_fix_hits_total", "h").inc(
                        1.0, session=session)
            """,
        }, rules=["metric-hygiene"])
        assert len(findings) == 1
        assert "cataloged as" in findings[0].message

    def test_matching_catalog_entry_is_clean(self, tmp_path):
        (tmp_path / "OBSERVABILITY.md").write_text(
            "- `dl4j_fix_hits_total{session}` — hits\n",
            encoding="utf-8")
        findings = corpus_scan(tmp_path, {
            "metrics.py": """
                def report(reg, session):
                    reg.counter("dl4j_fix_hits_total", "h").inc(
                        1.0, session=session)
            """,
        }, rules=["metric-hygiene"])
        assert findings == []

    def test_uncataloged_series_flagged(self, tmp_path):
        (tmp_path / "OBSERVABILITY.md").write_text(
            "- `dl4j_other_total{}` — something else\n",
            encoding="utf-8")
        findings = corpus_scan(tmp_path, {
            "metrics.py": """
                def report(reg):
                    reg.counter("dl4j_fix_orphan_total", "h").inc(1.0)
            """,
        }, rules=["metric-hygiene"])
        assert len(findings) == 1
        assert "not in OBSERVABILITY.md" in findings[0].message

    def test_malformed_catalog_token_is_a_finding(self, tmp_path):
        (tmp_path / "OBSERVABILITY.md").write_text(
            "- `dl4j_bad{session` — truncated braces\n",
            encoding="utf-8")
        findings = corpus_scan(tmp_path, {
            "metrics.py": "X = 1\n",
        }, rules=["metric-hygiene"])
        assert len(findings) == 1
        assert findings[0].path.name == "OBSERVABILITY.md"
        assert "unparseable" in findings[0].message

    def test_cross_site_drift_without_catalog(self, tmp_path):
        # no OBSERVABILITY.md in the fixture corpus: fall back to
        # cross-site consistency, majority label set wins
        findings = corpus_scan(tmp_path, {
            "a.py": """
                def r1(reg, s, n):
                    reg.counter("dl4j_fix_total", "h").inc(
                        1.0, session=s, node=n)

                def r2(reg, s, n):
                    reg.counter("dl4j_fix_total", "h").inc(
                        1.0, session=s, node=n)
            """,
            "b.py": """
                def r3(reg, s):
                    reg.counter("dl4j_fix_total", "h").inc(
                        1.0, session=s)
            """,
        }, rules=["metric-hygiene"])
        assert len(findings) == 1
        assert findings[0].path.name == "b.py"
        assert "other" in findings[0].message

    def test_self_attr_handle_resolved_across_methods(self, tmp_path):
        (tmp_path / "OBSERVABILITY.md").write_text(
            "- `dl4j_fix_depth{session}` — queue depth\n",
            encoding="utf-8")
        findings = corpus_scan(tmp_path, {
            "engine.py": """
                class Engine:
                    def __init__(self, reg):
                        self._g_depth = reg.gauge(
                            "dl4j_fix_depth", "queue depth")

                    def tick(self, s, n):
                        self._g_depth.set(3.0, session=s, node=n)
            """,
        }, rules=["metric-hygiene"])
        assert len(findings) == 1
        assert "dl4j_fix_depth" in findings[0].message


# ---------------------------------------------------------------------------
# chaos seam-coverage audit (opt-in)
# ---------------------------------------------------------------------------

UNCOVERED_TRANSPORT = """
    import urllib.request

    class Transport:
        def post(self, url):
            with urllib.request.urlopen(url) as r:
                return r.read()
"""


class TestChaosAudit:
    def audit(self, tmp_path, source, name="snippet.py"):
        f = tmp_path / name
        f.write_text(textwrap.dedent(source), encoding="utf-8")
        return scan([str(f)], rules=[ChaosHygieneRule(
            audit_seams=True)])

    def test_uncovered_socket_seam_flagged(self, tmp_path):
        findings = self.audit(tmp_path, UNCOVERED_TRANSPORT)
        assert len(findings) == 1
        assert "fault injection cannot reach" in findings[0].message

    def test_chaos_site_bound_class_is_covered(self, tmp_path):
        findings = self.audit(tmp_path, """
            import urllib.request
            from deeplearning4j_tpu.chaos.hook import chaos_site

            class Transport:
                def __init__(self):
                    self._chaos = chaos_site("transport.post")

                def post(self, url):
                    with urllib.request.urlopen(url) as r:
                        return r.read()
        """)
        assert findings == []

    def test_audit_off_by_default(self, tmp_path):
        f = tmp_path / "snippet.py"
        f.write_text(textwrap.dedent(UNCOVERED_TRANSPORT),
                     encoding="utf-8")
        findings = scan([str(f)], rules=[ChaosHygieneRule()])
        assert findings == []

    def test_pragma_documents_uncovered_seam(self, tmp_path):
        findings = self.audit(tmp_path, """
            import urllib.request

            class Transport:
                def post(self, url):
                    with urllib.request.urlopen(url) as r:  # graftlint: disable=chaos-hygiene: loopback test server
                        return r.read()
        """)
        assert findings == []


# ---------------------------------------------------------------------------
# summary cache
# ---------------------------------------------------------------------------

def _write_corpus(tmp_path: Path, n_modules=40, n_funcs=25):
    for i in range(n_modules):
        body = "".join(
            f"def f{j}(x, deadline=None):\n"
            f"    y = x + {j}\n"
            f"    return f{(j + 1) % n_funcs}(y)\n\n"
            for j in range(n_funcs))
        (tmp_path / f"mod{i:02d}.py").write_text(body,
                                                 encoding="utf-8")


class TestSummaryCache:
    def test_counters_and_per_file_invalidation(self, tmp_path):
        _write_corpus(tmp_path, n_modules=6, n_funcs=4)
        paths = sorted(tmp_path.glob("*.py"))
        cp = tmp_path / "cache.json"

        def build(cache):
            ctxs = [ModuleContext(p, root=tmp_path) for p in paths]
            Project(ctxs, root=tmp_path, cache=cache)
            cache.save()

        cold = SummaryCache(cp)
        build(cold)
        assert (cold.misses, cold.hits) == (6, 0)

        warm = SummaryCache(cp)
        build(warm)
        assert (warm.misses, warm.hits) == (0, 6)

        # touching one file invalidates exactly that file
        p0 = paths[0]
        p0.write_text(p0.read_text(encoding="utf-8") + "Z = 1\n",
                      encoding="utf-8")
        third = SummaryCache(cp)
        build(third)
        assert (third.misses, third.hits) == (1, 5)

    def test_warm_scan_is_faster_and_identical(self, tmp_path):
        _write_corpus(tmp_path)
        cp = tmp_path / "cache.json"
        rules = ["release-discipline"]

        t0 = time.perf_counter()
        cold = scan([str(tmp_path)], rules=get_rules(rules),
                    root=tmp_path, cache_path=cp)
        t_cold = time.perf_counter() - t0
        assert cp.exists()

        t0 = time.perf_counter()
        warm = scan([str(tmp_path)], rules=get_rules(rules),
                    root=tmp_path, cache_path=cp)
        t_warm = time.perf_counter() - t0

        assert [(f.rel, f.line, f.rule) for f in warm] == \
            [(f.rel, f.line, f.rule) for f in cold]
        # the warm pass skips 1000 function summarizations; even with
        # timer noise it must not be slower than the cold pass
        assert t_warm < t_cold

    def test_cacheless_scan_unchanged(self, tmp_path):
        _write_corpus(tmp_path, n_modules=2, n_funcs=3)
        a = scan([str(tmp_path)], root=tmp_path)
        b = scan([str(tmp_path)], root=tmp_path,
                 cache_path=tmp_path / "cache.json")
        assert [(f.rel, f.line, f.rule) for f in a] == \
            [(f.rel, f.line, f.rule) for f in b]


# ---------------------------------------------------------------------------
# SARIF report
# ---------------------------------------------------------------------------

def _validate_sarif(doc):
    """Hand-rolled structural validation against the SARIF 2.1.0
    required-field subset (no jsonschema dependency)."""
    assert doc["version"] == "2.1.0"
    assert doc["$schema"].endswith("sarif-schema-2.1.0.json")
    assert isinstance(doc["runs"], list) and len(doc["runs"]) == 1
    run = doc["runs"][0]
    driver = run["tool"]["driver"]
    assert driver["name"] == "graftlint"
    rule_ids = set()
    for rule in driver["rules"]:
        assert rule["id"]
        assert rule["shortDescription"]["text"]
        rule_ids.add(rule["id"])
    for res in run["results"]:
        assert res["ruleId"] in rule_ids
        assert res["level"] in ("error", "note")
        assert res["message"]["text"]
        [loc] = res["locations"]
        phys = loc["physicalLocation"]
        assert phys["artifactLocation"]["uri"]
        assert isinstance(phys["region"]["startLine"], int)
        assert res["partialFingerprints"]["graftlint/v1"]


class TestSarif:
    def _findings(self, tmp_path):
        f = tmp_path / "bad.py"
        f.write_text("def hot(loss):\n    return float(loss)\n",
                     encoding="utf-8")
        return scan([str(f)], rules=get_rules(["host-sync"]))

    def test_render_sarif_structure(self, tmp_path):
        findings = self._findings(tmp_path)
        assert len(findings) == 1
        buf = io.StringIO()
        render_sarif(findings, [], [], 1, 0.5, stream=buf)
        doc = json.loads(buf.getvalue())
        _validate_sarif(doc)
        [res] = doc["runs"][0]["results"]
        assert res["ruleId"] == "host-sync"
        assert res["level"] == "error"
        assert res["partialFingerprints"]["graftlint/v1"] == \
            fingerprints(findings)[0]

    def test_baselined_findings_are_notes(self, tmp_path):
        findings = self._findings(tmp_path)
        buf = io.StringIO()
        render_sarif([], findings, [], 1, 0.5, stream=buf)
        doc = json.loads(buf.getvalue())
        _validate_sarif(doc)
        [res] = doc["runs"][0]["results"]
        assert res["level"] == "note"


# ---------------------------------------------------------------------------
# CLI plumbing
# ---------------------------------------------------------------------------

class TestCLIv2:
    def run_cli(self, *args):
        return subprocess.run(
            [sys.executable, "-m", "tools.graftlint", *args],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=120)

    def test_sarif_format(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("def hot(loss):\n    return float(loss)\n",
                       encoding="utf-8")
        r = self.run_cli(str(bad), "--format", "sarif", "--no-cache")
        assert r.returncode == 1
        doc = json.loads(r.stdout)
        _validate_sarif(doc)
        assert len(doc["runs"][0]["results"]) == 1

    def test_chaos_audit_flag(self, tmp_path):
        fix = tmp_path / "transport.py"
        fix.write_text(textwrap.dedent(UNCOVERED_TRANSPORT),
                       encoding="utf-8")
        off = self.run_cli(str(fix), "--no-cache")
        assert off.returncode == 0, off.stderr
        on = self.run_cli(str(fix), "--chaos-audit", "--no-cache")
        assert on.returncode == 1
        assert "fault injection cannot reach" in on.stderr

    def test_new_rules_listed(self):
        r = self.run_cli("--list-rules")
        assert r.returncode == 0
        for rule in ("deadline-propagation", "release-discipline",
                     "atomic-write", "metric-hygiene"):
            assert rule in r.stdout
