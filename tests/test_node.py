"""Cluster node tier tests (PR 11): NodeRegistry gossip, ServingNode
graceful drain, shared-artifact warm start, AutoScaler.

Contracts under test (parallel/node.py + parallel/aot_cache.py):

- registry records are atomic, torn records are invisible, a rejoining
  node with a crashed predecessor's stale file simply overwrites it;
- heartbeat health reuses the watchdog boundary: exactly at
  ``stale_after_s`` is slow (still dispatchable), strictly past
  ``dead_after_s`` is dead;
- graceful drain: new predicts get 503 + ``Retry-After`` the moment the
  drain starts, every ALREADY-ACCEPTED request completes with 200, the
  node deregisters before its server stops, and the drain result says
  so;
- N ServingNodes warm from ONE shared ArtifactStore sweep: the second
  node's AOT cache loads "warm" with zero recompiles after warmup;
- AutoScaler: scale-from-zero on the dispatcher's demand signal is
  immediate, p99-over-SLO pressure must hold before a spawn, sustained
  idleness retires nodes down to ``min_nodes``.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.observe.registry import MetricsRegistry
from deeplearning4j_tpu.parallel.aot_cache import ArtifactStore
from deeplearning4j_tpu.parallel.node import (
    NODE_UP,
    AutoScaler,
    NodeRegistry,
    ServingNode,
)

N_IN = 5
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tiny_model(seed: int = 1):
    from deeplearning4j_tpu.models.multi_layer_network import (
        MultiLayerNetwork)
    from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.inputs import InputType
    from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
    from deeplearning4j_tpu.nn.layers.output import OutputLayer
    from deeplearning4j_tpu.ops.losses import LossFunction
    from deeplearning4j_tpu.optimize.updaters import Adam
    conf = (NeuralNetConfiguration.Builder().seed(seed)
            .updater(Adam(1e-2)).list()
            .layer(DenseLayer(n_out=8))
            .layer(OutputLayer(n_out=3, loss=LossFunction.MCXENT))
            .set_input_type(InputType.feed_forward(N_IN)).build())
    return MultiLayerNetwork(conf).init()


class Slow:
    """Duck-typed model whose forward blocks — holds requests in flight
    deterministically (same trick as test_fleet)."""

    def __init__(self, delay=0.2):
        self.delay = delay

    def output(self, x):
        time.sleep(self.delay)
        return np.zeros((x.shape[0], 3), np.float32)


def _post(url, payload, timeout=10.0):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, dict(r.headers), json.loads(r.read())


class TestNodeRegistry:
    def test_write_read_roundtrip_and_rejoin_overwrite(self, tmp_path):
        reg = NodeRegistry(str(tmp_path / "r"))
        reg.write("a", "http://127.0.0.1:1", stats={"pending": 3})
        rec = reg.read_all()["a"]
        assert rec["url"] == "http://127.0.0.1:1"
        assert rec["state"] == NODE_UP
        assert rec["stats"] == {"pending": 3}
        # a crashed predecessor left this record behind; the rejoining
        # node (same id, new process) just overwrites it
        reg.write("a", "http://127.0.0.1:2")
        assert reg.read_all()["a"]["url"] == "http://127.0.0.1:2"
        reg.deregister("a")
        assert reg.read_all() == {}
        reg.deregister("a")                 # idempotent

    def test_health_boundary_matches_watchdog(self, tmp_path):
        reg = NodeRegistry(str(tmp_path / "r"),
                           stale_after_s=2.0, dead_after_s=6.0)
        reg.write("a", "http://a", now=1000.0)
        assert reg.snapshot(now=1001.9)["a"]["health"] == "alive"
        # exactly at stale_after -> slow (the less severe class)
        assert reg.snapshot(now=1002.0)["a"]["health"] == "slow"
        # exactly at dead_after is still slow; strictly past is dead
        assert reg.snapshot(now=1006.0)["a"]["health"] == "slow"
        assert reg.snapshot(now=1006.01)["a"]["health"] == "dead"

    def test_dispatchable_filters_and_orders(self, tmp_path):
        reg = NodeRegistry(str(tmp_path / "r"),
                           stale_after_s=2.0, dead_after_s=6.0)
        reg.write("slow", "http://s", now=997.0)      # age 3 -> slow
        reg.write("alive", "http://a", now=999.5)     # age .5 -> alive
        reg.write("dead", "http://d", now=900.0)      # age 100 -> dead
        reg.write("drain", "http://x", state="draining", now=999.9)
        got = [r["node_id"] for r in reg.dispatchable(now=1000.0)]
        assert got == ["alive", "slow"]     # alive first, slow last
        #                                     resort; dead/drain absent

    def test_torn_record_classified_dead(self, tmp_path):
        """A torn record (interrupted writer, bit rot) surfaces as a
        DEAD placeholder — visible in the ledger with ``corrupt: True``
        so operators can see it, invisible to dispatch, healed whole by
        the node's next clean beat."""
        reg = NodeRegistry(str(tmp_path / "r"))
        reg.write("good", "http://g")
        (tmp_path / "r" / "node_torn.json").write_text('{"node_id": "t')
        recs = reg.read_all()
        assert sorted(recs) == ["good", "torn"]
        assert recs["torn"]["corrupt"] is True
        assert reg.snapshot()["torn"]["health"] == "dead"
        assert [r["node_id"] for r in reg.dispatchable()] == ["good"]
        reg.write("torn", "http://t")          # clean beat heals it
        healed = reg.snapshot()["torn"]
        assert healed["health"] == "alive" and "corrupt" not in healed

    def test_dead_before_slow_rejected(self, tmp_path):
        with pytest.raises(ValueError, match="dead before slow"):
            NodeRegistry(str(tmp_path / "r"),
                         stale_after_s=5.0, dead_after_s=2.0)


class TestArtifactStore:
    def test_bucket_layout_and_keys(self, tmp_path):
        store = ArtifactStore(str(tmp_path / "store"))
        d = store.cache_dir("model-a")
        assert os.path.isdir(d)
        assert d.endswith(os.path.join("objects", "model-a"))
        assert store.cache_dir("model-a") == d      # stable
        assert store.keys() == ["model-a"]
        assert store.manifest("model-a") is None    # nothing published
        st = store.stats()
        assert st["keys"]["model-a"]["published"] is False

    def test_key_sanitization(self, tmp_path):
        store = ArtifactStore(str(tmp_path / "store"))
        d = store.cache_dir("a/b zoo:v1")
        assert "/b" not in os.path.basename(d)
        assert os.path.basename(d) == "a_b_zoo_v1"
        with pytest.raises(ValueError):
            store.cache_dir("..")


class TestServingNodeDrain:
    def test_drain_completes_inflight_rejects_new_deregisters(
            self, tmp_path):
        reg = NodeRegistry(str(tmp_path / "reg"))
        node = ServingNode(
            Slow(0.8), node_id="n1", registry=reg,
            metrics_registry=MetricsRegistry(), window_s=10.0,
            batch_limit=8, ui_port=0)
        try:
            rec = reg.read_all()["n1"]
            assert rec["state"] == NODE_UP and rec["url"] == node.url
            url = node.url + "/api/predict"
            payload = {"features": [[0.0] * N_IN]}
            results = []

            def client():
                status, _h, body = _post(url, payload)
                results.append((status, body))

            threads = [threading.Thread(target=client)
                       for _ in range(3)]
            for t in threads:
                t.start()
            time.sleep(0.25)                # all three admitted

            drain_result = {}

            def drainer():
                drain_result.update(node.drain(timeout_s=15.0))

            dt = threading.Thread(target=drainer)
            dt.start()
            # the drain gossips "draining" first, then closes the door
            deadline = time.time() + 5.0
            while time.time() < deadline:
                r = reg.read_all().get("n1")
                if r is None or r["state"] == "draining":
                    break
                time.sleep(0.02)
            time.sleep(0.05)
            # a NEW request during the drain is refused with 503 +
            # Retry-After — never accepted, never dropped mid-flight
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(url, payload)
            assert ei.value.code == 503
            assert ei.value.headers.get("Retry-After") is not None
            ei.value.read()

            dt.join(timeout=20)
            for t in threads:
                t.join(timeout=10)
            # every ACCEPTED request completed with a real answer
            assert len(results) == 3
            assert all(status == 200 for status, _ in results)
            assert all(body["n"] == 1 for _, body in results)
            assert drain_result["drained"] is True
            assert drain_result["inflight_left"] == 0
            # deregistered: an orderly departure, not a stale record
            assert "n1" not in reg.read_all()
            assert "dl4j_cluster_drain_seconds" in node.metrics.render()
            # idempotent
            again = node.drain()
            assert again == {"drained": True, "seconds": 0.0,
                             "inflight_left": 0}
        finally:
            node.shutdown()

    @pytest.mark.slow
    def test_sigterm_subprocess_drains_and_exits_zero(self, tmp_path):
        from deeplearning4j_tpu.models.serialization import save_model
        zip_path = str(tmp_path / "m.zip")
        save_model(_tiny_model(), zip_path)
        reg_dir = str(tmp_path / "reg")
        proc = subprocess.Popen(
            [sys.executable, "-m", "deeplearning4j_tpu", "serve",
             "--model", zip_path, "--ui-port", "0",
             "--join", reg_dir, "--node-id", "s1",
             "--batch-limit", "8"],
            cwd=_ROOT, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        try:
            reg = NodeRegistry(reg_dir)
            deadline = time.time() + 180
            while time.time() < deadline:
                rec = reg.read_all().get("s1")
                if rec and rec.get("pid") == proc.pid:
                    break
                time.sleep(0.2)
            else:
                out, _ = proc.communicate(timeout=5)
                raise AssertionError(f"node never registered:\n{out}")
            proc.terminate()                # SIGTERM -> graceful drain
            out, _ = proc.communicate(timeout=60)
            assert proc.returncode == 0, out
            assert "SIGTERM drain" in out
            assert "s1" not in reg.read_all()
        finally:
            if proc.poll() is None:
                proc.kill()


class TestSharedArtifactWarmStart:
    def test_second_node_warms_with_zero_compiles(self, tmp_path):
        model = _tiny_model()
        store = ArtifactStore(str(tmp_path / "store"))
        reg = NodeRegistry(str(tmp_path / "reg"))
        x = np.zeros((2, N_IN), np.float32)

        # node 1 pays the sweep and publishes the shared store
        with ServingNode(model, node_id="w1", registry=reg,
                         artifact_store=store, model_key="m",
                         metrics_registry=MetricsRegistry(),
                         window_s=10.0, batch_limit=8,
                         feature_shape=(N_IN,), ui_port=0) as n1:
            n1.assert_warm()
            want = np.asarray(n1.output(x))
        assert store.manifest("m") is not None
        assert store.stats()["keys"]["m"]["published"] is True

        # node 2 joins later and must warm FROM the store: state
        # "warm", zero live compiles, bitwise-identical answers
        with ServingNode(model, node_id="w2", registry=reg,
                         artifact_store=store, model_key="m",
                         metrics_registry=MetricsRegistry(),
                         window_s=10.0, batch_limit=8,
                         feature_shape=(N_IN,), ui_port=0) as n2:
            n2.assert_warm()
            eng = n2.router.pool("default").engines[0]
            st = eng.stats()
            assert st["aot_cache"]["state"] == "warm"
            assert st["recompiles_after_warmup"] == 0
            got = np.asarray(n2.output(x))
        assert np.array_equal(got, want)


class _FakeFleet:
    """Injected spawn/stop for AutoScaler tests: spawning writes a
    fresh registry record, stopping removes it."""

    def __init__(self, reg):
        self.reg = reg
        self.spawned = []
        self.stopped = []
        self._n = 0

    def spawn(self):
        nid = f"n{self._n}"
        self._n += 1
        self.spawned.append(nid)
        self.reg.write(nid, f"http://{nid}", stats={"requests": 0})

    def stop(self, node_id):
        self.stopped.append(node_id)
        self.reg.deregister(node_id)


class TestAutoScaler:
    def _scaler(self, tmp_path, **kw):
        reg = NodeRegistry(str(tmp_path / "reg"))
        fleet = _FakeFleet(reg)
        clk = {"t": 100.0}
        kw.setdefault("hold_s", 1.0)
        kw.setdefault("idle_after_s", 5.0)
        sc = AutoScaler(reg, spawn=fleet.spawn, stop=fleet.stop,
                        clock=lambda: clk["t"], **kw)
        return reg, fleet, clk, sc

    def test_scale_from_zero_on_demand_is_immediate(self, tmp_path):
        reg, fleet, clk, sc = self._scaler(tmp_path, min_nodes=0)
        assert sc.tick() is None            # no demand, no nodes: rest
        sc.note_demand()                    # the on_no_nodes signal
        assert sc.tick() == "up"            # no hold at zero
        assert fleet.spawned == ["n0"]

    def test_p99_pressure_requires_hold(self, tmp_path):
        reg, fleet, clk, sc = self._scaler(tmp_path, slo_ms=100.0,
                                           max_nodes=3)
        reg.write("a", "http://a",
                  stats={"windowed_p99_ms": 500.0, "requests": 1})
        assert sc.tick() is None            # over, but not HELD yet
        clk["t"] += 1.0
        reg.write("a", "http://a",
                  stats={"windowed_p99_ms": 500.0, "requests": 2})
        assert sc.tick() == "up"
        assert sc.scale_ups == 1

    def test_queue_pressure_scales_up(self, tmp_path):
        reg, fleet, clk, sc = self._scaler(tmp_path, queue_high=4)
        reg.write("a", "http://a",
                  stats={"pending": 9, "queue_depth": 3, "requests": 1})
        sc.tick()
        clk["t"] += 1.0
        reg.write("a", "http://a",
                  stats={"pending": 9, "queue_depth": 3, "requests": 2})
        assert sc.tick() == "up"

    def test_idle_scales_down_to_min_nodes(self, tmp_path):
        reg, fleet, clk, sc = self._scaler(tmp_path, min_nodes=1)
        reg.write("a", "http://a", stats={"requests": 7})
        reg.write("b", "http://b", stats={"requests": 3})
        assert sc.tick() is None            # baseline recorded
        clk["t"] += 5.0
        reg.write("a", "http://a", stats={"requests": 7})
        reg.write("b", "http://b", stats={"requests": 3})
        assert sc.tick() == "down"
        assert fleet.stopped == ["b"]       # highest id retires first
        clk["t"] += 5.0
        reg.write("a", "http://a", stats={"requests": 7})
        assert sc.tick() is None            # total changed (b left):
        #                                     a fresh idle baseline
        clk["t"] += 5.0
        reg.write("a", "http://a", stats={"requests": 7})
        assert sc.tick() is None            # idle again — but the
        assert fleet.stopped == ["b"]       # min_nodes floor holds
        assert sc.scale_downs == 1

    def test_traffic_resets_idleness(self, tmp_path):
        reg, fleet, clk, sc = self._scaler(tmp_path, min_nodes=0)
        reg.write("a", "http://a", stats={"requests": 1})
        sc.tick()
        clk["t"] += 4.0
        reg.write("a", "http://a", stats={"requests": 2})  # traffic!
        assert sc.tick() is None
        clk["t"] += 4.0                     # only 4s since the reset
        reg.write("a", "http://a", stats={"requests": 2})
        assert sc.tick() is None
        clk["t"] += 1.0
        reg.write("a", "http://a", stats={"requests": 2})
        assert sc.tick() == "down"
