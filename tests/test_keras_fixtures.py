"""E2E import over the COMMITTED fixture corpus (reference analog:
KerasModelEndToEndTest.java over 2.0 MB of committed .h5 resources).

Each fixture is a genuine Keras-1- or Keras-2-FORMAT file written by
``tests/resources/keras/gen_fixtures.py`` with expected outputs computed
by independent numpy reference math — the Keras-1 dialect branch
(list-style model_config, layer-prefixed weight names, per-gate LSTM
matrices, nb_filter/border_mode keys) is exercised against real bytes,
not against whatever the installed Keras emits.
"""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.modelimport.keras import (
    import_keras_model_and_weights,
    import_keras_sequential_model_and_weights,
)

HERE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "resources", "keras")

FIXTURES = ["k1_mlp", "k1_cnn_atrous", "k1_lstm",
            "k2_googlenet_bits", "k2_yolo_bits", "k2_temporal",
            "k2_reshape_permute", "k2_selu_alpha_dropout"]


@pytest.mark.parametrize("name", FIXTURES)
def test_fixture_end_to_end(name):
    model = import_keras_sequential_model_and_weights(
        os.path.join(HERE, f"{name}.h5"))
    io = np.load(os.path.join(HERE, f"{name}_io.npz"))
    out = np.asarray(model.output(io["x"]))
    np.testing.assert_allclose(out, io["y"], rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("name", FIXTURES)
def test_fixture_via_generic_entry(name):
    """KerasModelImport-style entry must dispatch Sequential files too."""
    model = import_keras_model_and_weights(os.path.join(HERE, f"{name}.h5"))
    io = np.load(os.path.join(HERE, f"{name}_io.npz"))
    out = np.asarray(model.output(io["x"]))
    np.testing.assert_allclose(out, io["y"], rtol=1e-4, atol=1e-5)


def test_keras1_dialect_detected():
    from deeplearning4j_tpu.modelimport.hdf5 import Hdf5Archive
    with Hdf5Archive(os.path.join(HERE, "k1_mlp.h5")) as a:
        assert a.keras_version() == 1
        assert isinstance(a.model_config()["config"], list)
    with Hdf5Archive(os.path.join(HERE, "k2_yolo_bits.h5")) as a:
        assert a.keras_version() == 2


def test_gaussian_noise_maps_to_additive_noise():
    """GaussianNoise must import as the additive-noise regularizer, not a
    dropout (different train-time math; VERDICT r2 weak #2)."""
    from deeplearning4j_tpu.modelimport.layers import convert_layer
    from deeplearning4j_tpu.nn.dropout import GaussianDropout, GaussianNoise
    conv = convert_layer("GaussianNoise", {"stddev": 0.25}, 2)
    assert isinstance(conv.layer.dropout, GaussianNoise)
    assert conv.layer.dropout.stddev == 0.25
    conv = convert_layer("GaussianDropout", {"rate": 0.3}, 2)
    assert isinstance(conv.layer.dropout, GaussianDropout)
    assert conv.layer.dropout.rate == 0.3


def test_reshape_permute_reject_bad_configs():
    from deeplearning4j_tpu.modelimport.layers import convert_layer
    from deeplearning4j_tpu.nn.inputs import InputType
    with pytest.raises(ValueError, match="target_shape"):
        convert_layer("Reshape", {"name": "r"}, 2)
    with pytest.raises(ValueError, match="dims"):
        convert_layer("Permute", {"name": "p"}, 2)
    conv = convert_layer("Reshape", {"target_shape": [5, 7]}, 2)
    with pytest.raises(ValueError, match="incompatible"):
        conv.layer.output_type(InputType.feed_forward(36))
    conv = convert_layer("Permute", {"dims": [3, 1]}, 2)
    with pytest.raises(ValueError, match="permutation"):
        conv.layer.output_type(InputType.recurrent(4, 6))


def test_reshape_infers_minus_one():
    from deeplearning4j_tpu.nn.layers.feedforward import ReshapeLayer
    from deeplearning4j_tpu.nn.inputs import (ConvolutionalType,
                                              InputType, RecurrentType)
    lyr = ReshapeLayer(shape=(-1, 6))
    out = lyr.output_type(InputType.convolutional(4, 3, 3))
    assert out == RecurrentType(6, 6)
    lyr = ReshapeLayer(shape=(2, 3, 6))
    assert lyr.output_type(InputType.feed_forward(36)) == \
        ConvolutionalType(2, 3, 6)


def test_fixtures_trainable_after_import():
    """Imported models must be live, not inference shells: one fit step."""
    from deeplearning4j_tpu.datasets.dataset import DataSet
    model = import_keras_sequential_model_and_weights(
        os.path.join(HERE, "k1_mlp.h5"))
    rng = np.random.default_rng(0)
    x = rng.normal(size=(16, 8)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 16)]
    before = int(model.train_state.iteration)
    model.fit(DataSet(x, y))
    assert int(model.train_state.iteration) == before + 1
