"""E2E import over the COMMITTED fixture corpus (reference analog:
KerasModelEndToEndTest.java over 2.0 MB of committed .h5 resources).

Each fixture is a genuine Keras-1- or Keras-2-FORMAT file written by
``tests/resources/keras/gen_fixtures.py`` with expected outputs computed
by independent numpy reference math — the Keras-1 dialect branch
(list-style model_config, layer-prefixed weight names, per-gate LSTM
matrices, nb_filter/border_mode keys) is exercised against real bytes,
not against whatever the installed Keras emits.
"""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.modelimport.keras import (
    import_keras_model_and_weights,
    import_keras_sequential_model_and_weights,
)

HERE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "resources", "keras")

FIXTURES = ["k1_mlp", "k1_cnn_atrous", "k1_lstm",
            "k2_googlenet_bits", "k2_yolo_bits", "k2_temporal",
            "k2_reshape_permute", "k2_selu_alpha_dropout"]

# functional fixtures (CG import): K1 Merge graph + the Keras-3 corpus
# written by gen_keras3_fixtures.py with Keras' own outputs as goldens
FUNC_FIXTURES = ["k1_merge", "k3_conv", "k3_temporal", "k3_merges",
                 "k3_attention", "k3_pool_extras"]


def _fixture_path(name):
    ext = ".keras" if name.startswith("k3_") else ".h5"
    return os.path.join(HERE, f"{name}{ext}")


@pytest.mark.parametrize("name", FUNC_FIXTURES)
def test_functional_fixture_end_to_end(name):
    model = import_keras_model_and_weights(_fixture_path(name))
    io = np.load(os.path.join(HERE, f"{name}_io.npz"))
    out = np.asarray(model.output(io["x"]))
    np.testing.assert_allclose(out, io["y"], rtol=1e-4, atol=1e-5)


def test_registry_fully_covered():
    """Executable supported-layer contract (VERDICT r4 #5): every
    converter in the registry appears in >=1 committed e2e fixture
    (aliases inherit their canonical converter's coverage); a new
    converter cannot land without fixture evidence."""
    from deeplearning4j_tpu.modelimport.manifest import (
        coverage, supported_layers, uncovered)
    assert uncovered(HERE) == []
    cov = coverage(HERE)
    assert set(cov) == set(supported_layers())
    # spot-evidence the mapping is real, not vacuous
    assert "k3_conv" in cov["Conv2DTranspose"]
    assert "k1_merge" in cov["Merge"]
    assert "k2_yolo_bits" in cov["SpaceToDepth"]
    assert "k1_cnn_atrous" in cov["AtrousConvolution2D"]
    assert cov["add"] == cov["Add"] != []


def test_manifest_renders():
    from deeplearning4j_tpu.modelimport.manifest import render_markdown
    md = render_markdown(HERE)
    assert "| Conv2D" in md and "alias of Conv2D" in md


def test_committed_manifest_doc_current():
    """SUPPORTED_KERAS_LAYERS.md must carry exactly what
    render_markdown() produces — the doc cannot drift from the code it
    claims to render from."""
    from deeplearning4j_tpu.modelimport.manifest import render_markdown
    doc = open(os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "SUPPORTED_KERAS_LAYERS.md")).read()
    assert render_markdown(HERE) in doc


def test_fused_leaky_relu_string_rejected():
    """Keras 3's fused 'leaky_relu' string (slope 0.2) is not
    representable in the fused activation enum (fixed 0.01) — must
    error clearly, never import silently wrong."""
    from deeplearning4j_tpu.modelimport.layers import convert_layer
    with pytest.raises(ValueError, match="standalone"):
        convert_layer("Dense", {"units": 4, "activation": "leaky_relu"},
                      3)


@pytest.mark.parametrize("name", FIXTURES)
def test_fixture_end_to_end(name):
    model = import_keras_sequential_model_and_weights(
        os.path.join(HERE, f"{name}.h5"))
    io = np.load(os.path.join(HERE, f"{name}_io.npz"))
    out = np.asarray(model.output(io["x"]))
    np.testing.assert_allclose(out, io["y"], rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("name", FIXTURES)
def test_fixture_via_generic_entry(name):
    """KerasModelImport-style entry must dispatch Sequential files too."""
    model = import_keras_model_and_weights(os.path.join(HERE, f"{name}.h5"))
    io = np.load(os.path.join(HERE, f"{name}_io.npz"))
    out = np.asarray(model.output(io["x"]))
    np.testing.assert_allclose(out, io["y"], rtol=1e-4, atol=1e-5)


def test_keras1_dialect_detected():
    from deeplearning4j_tpu.modelimport.hdf5 import Hdf5Archive
    with Hdf5Archive(os.path.join(HERE, "k1_mlp.h5")) as a:
        assert a.keras_version() == 1
        assert isinstance(a.model_config()["config"], list)
    with Hdf5Archive(os.path.join(HERE, "k2_yolo_bits.h5")) as a:
        assert a.keras_version() == 2


def test_gaussian_noise_maps_to_additive_noise():
    """GaussianNoise must import as the additive-noise regularizer, not a
    dropout (different train-time math; VERDICT r2 weak #2)."""
    from deeplearning4j_tpu.modelimport.layers import convert_layer
    from deeplearning4j_tpu.nn.dropout import GaussianDropout, GaussianNoise
    conv = convert_layer("GaussianNoise", {"stddev": 0.25}, 2)
    assert isinstance(conv.layer.dropout, GaussianNoise)
    assert conv.layer.dropout.stddev == 0.25
    conv = convert_layer("GaussianDropout", {"rate": 0.3}, 2)
    assert isinstance(conv.layer.dropout, GaussianDropout)
    assert conv.layer.dropout.rate == 0.3


def test_reshape_permute_reject_bad_configs():
    from deeplearning4j_tpu.modelimport.layers import convert_layer
    from deeplearning4j_tpu.nn.inputs import InputType
    with pytest.raises(ValueError, match="target_shape"):
        convert_layer("Reshape", {"name": "r"}, 2)
    with pytest.raises(ValueError, match="dims"):
        convert_layer("Permute", {"name": "p"}, 2)
    conv = convert_layer("Reshape", {"target_shape": [5, 7]}, 2)
    with pytest.raises(ValueError, match="incompatible"):
        conv.layer.output_type(InputType.feed_forward(36))
    conv = convert_layer("Permute", {"dims": [3, 1]}, 2)
    with pytest.raises(ValueError, match="permutation"):
        conv.layer.output_type(InputType.recurrent(4, 6))


def test_reshape_infers_minus_one():
    from deeplearning4j_tpu.nn.layers.feedforward import ReshapeLayer
    from deeplearning4j_tpu.nn.inputs import (ConvolutionalType,
                                              InputType, RecurrentType)
    lyr = ReshapeLayer(shape=(-1, 6))
    out = lyr.output_type(InputType.convolutional(4, 3, 3))
    assert out == RecurrentType(6, 6)
    lyr = ReshapeLayer(shape=(2, 3, 6))
    assert lyr.output_type(InputType.feed_forward(36)) == \
        ConvolutionalType(2, 3, 6)


def test_fixtures_trainable_after_import():
    """Imported models must be live, not inference shells: one fit step."""
    from deeplearning4j_tpu.datasets.dataset import DataSet
    model = import_keras_sequential_model_and_weights(
        os.path.join(HERE, "k1_mlp.h5"))
    rng = np.random.default_rng(0)
    x = rng.normal(size=(16, 8)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 16)]
    before = int(model.train_state.iteration)
    model.fit(DataSet(x, y))
    assert int(model.train_state.iteration) == before + 1


def test_custom_stateless_layer_keras3_import(tmp_path):
    """A user-registered parameter-free custom layer imports from the
    .keras format without tripping the weights-expected guard
    (round 5; reference: KerasLayer.registerCustomLayer)."""
    import keras
    from keras import layers as L

    from deeplearning4j_tpu.modelimport.layers import (
        Converted, _CUSTOM, register_custom_layer)
    from deeplearning4j_tpu.nn.layers.misc import LambdaLayer

    @keras.saving.register_keras_serializable(package="t")
    class Doubler(L.Layer):
        def call(self, x):
            return x * 2.0

    keras.utils.set_random_seed(0)
    inp = keras.Input((4,))
    out = L.Dense(3)(Doubler()(inp))
    km = keras.Model(inp, out)
    p = str(tmp_path / "m.keras")
    km.save(p)

    register_custom_layer("Doubler", lambda cfg, v: Converted(
        layer=LambdaLayer(fn=lambda x: x * 2.0)))
    try:
        model = import_keras_model_and_weights(p)
        x = np.random.default_rng(0).normal(size=(3, 4)).astype(np.float32)
        np.testing.assert_allclose(np.asarray(model.output(x)),
                                   np.asarray(km(x)), rtol=1e-5,
                                   atol=1e-6)
    finally:
        _CUSTOM.pop("Doubler", None)
