"""Fused pair generation (nlp/pairgen.py + native/dl4j_native.cpp).

The contract under test is the one the A/B bench gate enforces in CI:
the native C walk and the numpy fallback are BITWISE-equal — same
splitmix64 counter streams, same pair order, same negative draws — so
``pairgen="auto"`` and ``pairgen="numpy"`` train identical models.
Kernel-level parity is checked per entry point (including slab-split
invariance), then end to end across every training mode, plus the
seeded-reproducibility and lr-anneal regressions the fused producer
must preserve from the legacy path.

Run under ``DL4J_NATIVE=0`` (runtests.sh's fallback-forced tier) the
parity tests skip and the rest prove the numpy path stands alone.
"""

import hashlib
import os
import subprocess
import sys

import numpy as np
import pytest

from deeplearning4j_tpu.nlp import pairgen as pg
from deeplearning4j_tpu.nlp.paragraph_vectors import ParagraphVectors
from deeplearning4j_tpu.nlp.sentence_iterators import (
    SentenceLabelledIterator,
)
from deeplearning4j_tpu.nlp.sequence_vectors import _corpus_positions
from deeplearning4j_tpu.nlp.word2vec import Word2Vec
from deeplearning4j_tpu.utils import native

needs_native = pytest.mark.skipif(
    not native.pairgen_available(),
    reason="native pairgen unavailable (no toolchain or DL4J_NATIVE=0)")


def _sentences(rng, n_words=120, n_seq=150):
    words = [f"w{i}" for i in range(n_words)]
    return [" ".join(rng.choice(words, rng.integers(3, 13)))
            for _ in range(n_seq)]


def _w2v(pairgen, sents, **kw):
    kw.setdefault("negative", 5)
    m = Word2Vec(layer_size=16, window_size=3, min_word_frequency=1,
                 epochs=2, seed=11, batch_size=64, pairgen=pairgen, **kw)
    m.fit(sents)
    return m


def _pv(pairgen, sents, **kw):
    kw.setdefault("negative", 5)
    m = ParagraphVectors(layer_size=16, window_size=3, dm=False,
                         min_word_frequency=1, epochs=2, seed=11,
                         batch_size=64, pairgen=pairgen, **kw)
    m.fit(SentenceLabelledIterator(sents))
    return m


# ---------------------------------------------------------------------------
# Kernel-level parity: each native entry point vs its numpy fallback.
# ---------------------------------------------------------------------------

@needs_native
class TestKernelParity:
    def _geom(self, rng, n=4000, vocab=400, seqs=90):
        ids = rng.integers(0, vocab, n).astype(np.int32)
        bounds = np.sort(rng.choice(np.arange(1, n), seqs, replace=False))
        seq_id = np.searchsorted(bounds, np.arange(n), side="right")
        pos, length = _corpus_positions(seq_id.astype(np.int64))
        table = rng.integers(0, vocab, 50_000).astype(np.int32)
        return ids, pos, length, table, vocab

    def test_sm64_fill(self):
        a = pg.sm64_fill(0xDEADBEEF, 1000, 4096)
        b = pg.sm64_fill(0xDEADBEEF, 1000, 4096, force_numpy=True)
        np.testing.assert_array_equal(a, b)

    def test_subsample(self, rng):
        ids = rng.integers(0, 50, 5000).astype(np.int32)
        keep_p = rng.random(50)
        a = pg.subsample(ids, keep_p, 42)
        b = pg.subsample(ids, keep_p, 42, force_numpy=True)
        np.testing.assert_array_equal(a, b)

    def test_negatives(self, rng):
        ids, _pos, _length, table, vocab = self._geom(rng)
        a = pg.negatives(table, ids[:2000], 7, vocab, 5, 6, 123)
        b = pg.negatives(table, ids[:2000], 7, vocab, 5, 6, 123,
                         force_numpy=True)
        np.testing.assert_array_equal(a, b)

    def test_negatives_double_collision_cycles(self):
        # a single-word table forces the redraw AND the cycle fallback
        table = np.zeros(8, np.int32)
        positive = np.zeros(16, np.int32)
        for force in (False, True):
            neg = pg.negatives(table, positive, 3, 5, 1, 2, 0,
                               force_numpy=force)
            np.testing.assert_array_equal(neg, np.ones((16, 3), np.int32))

    @pytest.mark.parametrize("window,n_neg", [(1, 0), (3, 0), (5, 5)])
    def test_walk(self, rng, window, n_neg):
        ids, pos, length, table, vocab = self._geom(rng)
        kw = dict(table=table, n_neg=n_neg, n_words=vocab, nseed=77,
                  n2seed=88, pair_base=13)
        a = pg.walk(ids, pos, length, 0, len(ids), window, 999, **kw)
        b = pg.walk(ids, pos, length, 0, len(ids), window, 999,
                    force_numpy=True, **kw)
        np.testing.assert_array_equal(a[0], b[0])
        np.testing.assert_array_equal(a[1], b[1])
        if n_neg:
            np.testing.assert_array_equal(a[2], b[2])

    def test_walk_slab_split_invariant(self, rng):
        # one full walk == concatenated slab walks with the pair_base
        # threaded through — the property the producer loop relies on
        ids, pos, length, table, vocab = self._geom(rng)
        kw = dict(table=table, n_neg=4, n_words=vocab, nseed=7,
                  n2seed=8)
        full = pg.walk(ids, pos, length, 0, len(ids), 4, 555,
                       pair_base=0, **kw)
        for force in (False, True):
            parts, base = [], 0
            for lo in range(0, len(ids), 1024):
                hi = min(len(ids), lo + 1024)
                part = pg.walk(ids, pos, length, lo, hi, 4, 555,
                               pair_base=base, force_numpy=force, **kw)
                base += len(part[0])
                parts.append(part)
            np.testing.assert_array_equal(
                full[0], np.concatenate([p[0] for p in parts]))
            np.testing.assert_array_equal(
                full[1], np.concatenate([p[1] for p in parts]))
            np.testing.assert_array_equal(
                full[2], np.concatenate([p[2] for p in parts]))

    @pytest.mark.parametrize("window", [1, 4])
    def test_walk_cbow(self, rng, window):
        ids, pos, length, table, vocab = self._geom(rng)
        kw = dict(table=table, n_neg=4, n_words=vocab, nseed=1,
                  n2seed=2, row_base=3)
        a = pg.walk_cbow(ids, pos, length, 0, len(ids), window, 31, **kw)
        b = pg.walk_cbow(ids, pos, length, 0, len(ids), window, 31,
                         force_numpy=True, **kw)
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)


# ---------------------------------------------------------------------------
# End-to-end: every mode trains the SAME model on either backend.
# ---------------------------------------------------------------------------

@needs_native
class TestModeParity:
    @pytest.mark.parametrize("kw", [
        {},                                          # SGNS
        {"sampling": 1e-3},                          # SGNS + subsample
        {"use_hierarchic_softmax": True, "negative": 0},
        {"use_cbow": True},
        {"use_cbow": True, "use_hierarchic_softmax": True,
         "negative": 0},
    ], ids=["sgns", "sgns-sub", "hs", "cbow", "cbow-hs"])
    def test_word2vec(self, rng, kw):
        sents = _sentences(rng)
        np.testing.assert_array_equal(
            np.asarray(_w2v("auto", sents, **kw).syn0),
            np.asarray(_w2v("numpy", sents, **kw).syn0))

    @pytest.mark.parametrize("kw", [{}, {"sampling": 1e-3}],
                             ids=["dbow", "dbow-sub"])
    def test_dbow(self, rng, kw):
        sents = _sentences(rng)
        np.testing.assert_array_equal(
            np.asarray(_pv("auto", sents, **kw).syn0),
            np.asarray(_pv("numpy", sents, **kw).syn0))


# ---------------------------------------------------------------------------
# Regressions the fused producer must preserve (any backend).
# ---------------------------------------------------------------------------

class TestProducerContracts:
    def test_pairgen_knob_validated(self):
        with pytest.raises(ValueError):
            Word2Vec(layer_size=8, pairgen="nope")

    def test_seeded_reproducibility_in_process(self, rng):
        sents = _sentences(rng, n_seq=60)
        a = _w2v("auto", sents, sampling=1e-3)
        b = _w2v("auto", sents, sampling=1e-3)
        np.testing.assert_array_equal(np.asarray(a.syn0),
                                      np.asarray(b.syn0))

    def test_seeded_reproducibility_two_process(self):
        # a second PROCESS must converge to the bitwise-same weights:
        # no hidden dependence on hash seeds, dict order or library
        # load order
        script = (
            "import numpy as np, hashlib\n"
            "from deeplearning4j_tpu.nlp.word2vec import Word2Vec\n"
            "rng = np.random.default_rng(21)\n"
            "words = ['w%d' % i for i in range(120)]\n"
            "sents = [' '.join(rng.choice(words, rng.integers(3, 13)))\n"
            "         for _ in range(150)]\n"
            "m = Word2Vec(layer_size=16, window_size=3,\n"
            "             min_word_frequency=1, epochs=2, seed=11,\n"
            "             batch_size=64, negative=5, sampling=1e-3,\n"
            "             pairgen='auto')\n"
            "m.fit(sents)\n"
            "print(hashlib.sha256(np.ascontiguousarray(\n"
            "    np.asarray(m.syn0)).tobytes()).hexdigest())\n")
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PYTHONPATH=os.path.dirname(os.path.dirname(
                       os.path.abspath(__file__))))
        out = subprocess.run([sys.executable, "-c", script], env=env,
                             capture_output=True, text=True, timeout=300)
        assert out.returncode == 0, out.stderr
        child_hash = out.stdout.strip().splitlines()[-1]
        rng2 = np.random.default_rng(21)
        words = [f"w{i}" for i in range(120)]
        sents = [" ".join(rng2.choice(words, rng2.integers(3, 13)))
                 for _ in range(150)]
        m = _w2v("auto", sents, sampling=1e-3)
        mine = hashlib.sha256(np.ascontiguousarray(
            np.asarray(m.syn0)).tobytes()).hexdigest()
        assert mine == child_hash

    def test_overlap_vs_serial_bitwise(self, rng):
        # the producer-thread overlap must make the same counter-stream
        # draws in the same order as the serial path
        sents = _sentences(rng, n_seq=80)
        a = _w2v("auto", sents, overlap_pairgen=True)
        b = _w2v("auto", sents, overlap_pairgen=False)
        np.testing.assert_array_equal(np.asarray(a.syn0),
                                      np.asarray(b.syn0))

    def test_dbow_lr_anneals_within_one_slab(self, rng):
        # the fused slab producer spreads lr-anneal progress over the
        # slab's chunks (via _PairStream tokens accounting) — a
        # regression here snaps small corpora straight to min_lr
        sents = _sentences(rng, n_seq=200)
        pv = ParagraphVectors(layer_size=8, window_size=3, dm=False,
                              negative=3, min_word_frequency=1,
                              epochs=1, seed=5, batch_size=64,
                              overlap_pairgen=False, pairgen="auto")
        docs = list(SentenceLabelledIterator(sents))
        tokenized = [(d.content.split(), d.labels) for d in docs]
        labels = sorted({lb for _t, lbs in tokenized for lb in lbs})
        pv.build_vocab(([t for t, _l in tokenized]),
                       special_tokens=labels)
        pv._init_tables()
        preps = []
        pv._dispatch_chunks = preps.append
        per_epoch = sum(len(t) for t, _l in tokenized)
        pv._fit_fast_dbow(tokenized, max(1, per_epoch * 2))
        lrs = np.concatenate([p[4][p[3] > 0] for p in preps])
        assert len(lrs) >= 3
        assert np.all(np.diff(lrs) <= 0)            # monotone decay
        assert len(np.unique(lrs)) >= 3             # within-slab anneal
        assert lrs[-1] >= pv.min_learning_rate - 1e-9

    def test_fused_sgns_telemetry_counts_tokens(self, rng):
        from deeplearning4j_tpu.observe.registry import default_registry
        reg = default_registry()
        c = reg.counter("dl4j_pairgen_tokens_total", "")
        sents = _sentences(rng, n_seq=40)
        m = _w2v("auto", sents)
        path = "native" if native.pairgen_available() else "numpy"
        got = c.get(path=path)
        assert got is not None and got > 0
        assert np.isfinite(np.asarray(m.syn0)).all()
