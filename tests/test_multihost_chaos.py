"""Multihost beyond the happy path (VERDICT r3 #6): a 3-process run
with UNEVEN per-process device counts, and a chaos test that kills a
live worker mid-fit and asserts the relaunched smaller job resumes from
the last COMMITTED checkpoint with correct resharding."""

import json
import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "multihost_chaos_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _env():
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("JAX_PLATFORMS", None)
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f)
    return env


def _launch(rank, nprocs, port, outdir, devices_csv, die_rank=-1,
            die_step=-1, epochs=3, mode="dp"):
    return subprocess.Popen(
        [sys.executable, WORKER, str(rank), str(nprocs), str(port),
         str(outdir), devices_csv, str(die_rank), str(die_step),
         str(epochs), mode],
        env=_env(), stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)


def _join(procs, timeout=600):
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    return outs


@pytest.mark.slow
def test_three_process_uneven_device_counts(tmp_path):
    """3 OS processes owning 2+1+1 devices train one 4-device mesh; the
    per-process batches are proportional (32/16/16 of a 64 batch) and
    all ranks converge to identical replicated params."""
    port = _free_port()
    procs = [_launch(r, 3, port, tmp_path, "2,1,1") for r in range(3)]
    outs = _join(procs)
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-3000:]
    results = []
    for r in range(3):
        with open(tmp_path / f"result_{r}.json") as f:
            results.append(json.load(f))
    assert [r["local_batch"] for r in results] == [32, 16, 16]
    assert results[0]["n_devices"] == 4
    for r in (1, 2):
        assert results[r]["param_sum"] == pytest.approx(
            results[0]["param_sum"], rel=1e-6)


@pytest.mark.slow
def test_kill_worker_midfit_then_resume_smaller_mesh(tmp_path):
    """Phase 1: 3 even processes train with frequent COMMITTED
    checkpoints; rank 2 dies abruptly mid-fit. Phase 2: a fresh
    2-process job on the SAME checkpoint dir resumes from the last
    COMMITTED step, reshards onto the smaller 2-device mesh, and
    finishes training with identical params on both survivors."""
    port = _free_port()
    procs = [_launch(r, 3, port, tmp_path, "1,1,1",
                     die_rank=2, die_step=6, epochs=60)
             for r in range(3)]
    outs = _join(procs)
    # the victim died with the abrupt-exit code
    assert procs[2].returncode == 17, outs[2][-2000:]
    # at least one checkpoint was COMMITTED before the death
    ckpt = tmp_path / "ckpt"
    steps = sorted(d for d in os.listdir(ckpt) if d.startswith("step_")
                   and (ckpt / d / "COMMITTED").exists())
    assert steps, list(os.listdir(ckpt))
    last_step = max(int(s.split("_")[1]) for s in steps)
    assert last_step >= 2

    # survivors either detected the broken collective and exited with a
    # marker, or were reaped by the harness — both acceptable deaths;
    # what matters is the durable checkpoint state
    for r in (0, 1):
        marker = tmp_path / f"survivor_{r}.json"
        if marker.exists():
            with open(marker) as f:
                assert json.load(f)["detected"]

    # ---- phase 2: relaunch smaller (2-process) job, same ckpt dir ----
    port2 = _free_port()
    procs2 = [_launch(r, 2, port2, tmp_path, "1,1", epochs=3)
              for r in range(2)]
    outs2 = _join(procs2)
    for p, out in zip(procs2, outs2):
        assert p.returncode == 0, out[-3000:]
    results = []
    for r in range(2):
        with open(tmp_path / f"result_{r}.json") as f:
            results.append(json.load(f))
    for r in results:
        assert r["resumed"] is True
        # resumed exactly from the last COMMITTED checkpoint...
        assert r["start_iteration"] == last_step
        # ...on the smaller mesh, and made progress past it
        assert r["n_devices"] == 2
        assert r["final_iteration"] > r["start_iteration"]
    assert results[0]["param_sum"] == pytest.approx(
        results[1]["param_sum"], rel=1e-6)


@pytest.mark.slow
def test_3d_chaos_kill_then_resume_reshaped_layout(tmp_path):
    """The composed tentpole test: a dp×tp×pp PipelinedTransformerLM
    job (2×2×1 over 2 processes × 2 devices) trains with COMMITTED
    sharded checkpoints; rank 1 dies abruptly mid-fit. The survivor
    classifies the failure through the CollectiveWatchdog (peer_loss
    marker, not a hang past the collective deadline — the _join
    timeout enforces that). Phase 2 relaunches on a RESHAPED 3D layout
    (2×1×1 over 2 processes × 1 device), resumes from the last
    COMMITTED step via restore_sharded's explicit param_shardings
    path, and both survivors train to identical params (rel 1e-6)."""
    port = _free_port()
    procs = [_launch(r, 2, port, tmp_path, "2,2",
                     die_rank=1, die_step=5, epochs=40, mode="3d:2x2x1")
             for r in range(2)]
    outs = _join(procs, timeout=600)
    # the victim died with the abrupt-exit code
    assert procs[1].returncode == 17, outs[1][-2000:]
    # survivor: clean classified exit (0, wrote survivor json) or the
    # watchdog's peer-loss exit — never a hang (join timeout above)
    assert procs[0].returncode in (0, 43), outs[0][-3000:]

    ckpt = tmp_path / "ckpt"
    steps = sorted(d for d in os.listdir(ckpt) if d.startswith("step_")
                   and (ckpt / d / "COMMITTED").exists())
    assert steps, list(os.listdir(ckpt))
    last_step = max(int(s.split("_")[1].split(".")[0]) for s in steps)
    assert last_step >= 2

    survivor = tmp_path / "survivor_0.json"
    if survivor.exists():
        with open(survivor) as f:
            s = json.load(f)
        assert s["detected"]
        # the watchdog classified the raise as peer loss and dropped
        # the forensics marker next to the checkpoints
        assert s["peer_loss"], s
        markers = [p for p in os.listdir(ckpt)
                   if p.startswith("PEER_LOSS.json")]
        assert markers, list(os.listdir(ckpt))

    # ---- phase 2: same ckpt dir, RESHAPED layout 2×2×1 -> 2×1×1 ----
    port2 = _free_port()
    procs2 = [_launch(r, 2, port2, tmp_path, "1,1", epochs=6,
                      mode="3d:2x1x1")
              for r in range(2)]
    outs2 = _join(procs2, timeout=600)
    for p, out in zip(procs2, outs2):
        assert p.returncode == 0, out[-3000:]
    results = []
    for r in range(2):
        with open(tmp_path / f"result_{r}.json") as f:
            results.append(json.load(f))
    for r in results:
        assert r["resumed"] is True
        assert r["start_iteration"] == last_step
        assert r["layout"] == [2, 1, 1]
        assert r["final_iteration"] > r["start_iteration"]
        assert r["loss"] is not None
    assert results[0]["param_sum"] == pytest.approx(
        results[1]["param_sum"], rel=1e-6)
