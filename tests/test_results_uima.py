"""Result-API holders and the UIMA type-system/XMI surface."""

import numpy as np
import pytest

from deeplearning4j_tpu.evaluation.results import (
    BinaryClassificationResult,
    RankClassificationResult,
)
from deeplearning4j_tpu.nlp.language_packs import (
    AnalysisPipeline,
    SentenceAnnotator,
    TokenAnnotator,
)
from deeplearning4j_tpu.nlp.uima import (
    DEFAULT_TYPE_SYSTEM,
    TypeDescription,
    TypeSystem,
    from_xmi,
    to_xmi,
)


class TestRankClassificationResult:
    def test_ranks_descending_with_labels(self):
        out = np.array([[0.1, 0.7, 0.2], [0.5, 0.2, 0.3]])
        r = RankClassificationResult(out, labels=["a", "b", "c"])
        assert r.ranked_indices.tolist() == [[1, 2, 0], [0, 2, 1]]
        assert r.max_outcomes() == ["b", "a"]
        assert r.max_outcome_for_row(1) == "a"

    def test_vector_and_default_labels(self):
        r = RankClassificationResult(np.array([0.2, 0.5, 0.3]))
        assert r.max_outcomes() == ["1"]
        assert r.labels == ["0", "1", "2"]

    def test_rejects_rank3(self):
        with pytest.raises(ValueError, match="vectors and matrices"):
            RankClassificationResult(np.zeros((2, 2, 2)))

    def test_label_count_mismatch(self):
        with pytest.raises(ValueError):
            RankClassificationResult(np.zeros((1, 3)), labels=["x"])


class TestBinaryClassificationResult:
    def test_threshold_decisions(self):
        r = BinaryClassificationResult(np.array([0.2, 0.5, 0.9]),
                                       decision_threshold=0.5)
        assert r.decisions().tolist() == [0, 1, 1]

    def test_softmax_column(self):
        r = BinaryClassificationResult(
            np.array([[0.8, 0.2], [0.1, 0.9]]), decision_threshold=0.6)
        assert r.decisions().tolist() == [0, 1]

    def test_class_weights_stored(self):
        r = BinaryClassificationResult(class_weights=[1.0, 3.0])
        assert r.class_weights.tolist() == [1.0, 3.0]
        with pytest.raises(ValueError):
            r.decisions()


class TestTypeSystem:
    def test_subsumption_and_inherited_features(self):
        ts = TypeSystem([
            TypeDescription("entity", features={"id": "uima.cas.String"}),
            TypeDescription("person", supertype="entity",
                            features={"role": "uima.cas.String"}),
        ])
        assert ts.subsumes("entity", "person")
        assert not ts.subsumes("person", "entity")
        assert set(ts.features_of("person")) == {"id", "role"}

    def test_descriptor_xml_roundtrip(self):
        xml = DEFAULT_TYPE_SYSTEM.to_xml()
        ts2 = TypeSystem.from_xml(xml)
        assert set(ts2.types) == set(DEFAULT_TYPE_SYSTEM.types)
        assert ts2.features_of("token")["pos"] == "uima.cas.String"

    def test_validation_catches_problems(self):
        from deeplearning4j_tpu.nlp.language_packs import CAS, Annotation
        cas = CAS("hi")
        cas.add(Annotation("token", 0, 9, "hi"))           # span overflow
        cas.add(Annotation("mystery", 0, 1, "h"))          # unknown type
        cas.add(Annotation("token", 0, 2, "hi", color="x"))  # bad feature
        problems = DEFAULT_TYPE_SYSTEM.validate(cas)
        assert len(problems) == 3, problems


class TestXmi:
    def test_roundtrip_preserves_text_spans_features(self):
        pipeline = AnalysisPipeline([SentenceAnnotator(), TokenAnnotator()])
        cas = pipeline.process("Hello world. Goodbye now.")
        for i, tok in enumerate(cas.select("token")):
            tok.features["pos"] = "NN" if i % 2 else "VB"
        xml = to_xmi(cas)
        assert "sofaString" in xml and "cas:Sofa" in xml

        cas2 = from_xmi(xml, DEFAULT_TYPE_SYSTEM)
        assert cas2.text == cas.text
        assert len(cas2.select("sentence")) == 2
        toks, toks2 = cas.select("token"), cas2.select("token")
        assert [(t.begin, t.end, t.text) for t in toks] == \
               [(t.begin, t.end, t.text) for t in toks2]
        assert toks2[0].features["pos"] == "VB"

    def test_reserved_or_invalid_feature_names_rejected(self):
        from deeplearning4j_tpu.nlp.language_packs import CAS, Annotation
        cas = CAS("abc")
        ann = Annotation("token", 0, 1, "a")
        ann.features["begin"] = "NN"   # constructor kwargs can't collide
        cas.add(ann)
        with pytest.raises(ValueError, match="reserved"):
            to_xmi(cas)
        cas2 = CAS("abc")
        cas2.add(Annotation("token", 0, 1, "a", **{"my pos": "NN"}))
        with pytest.raises(ValueError, match="XML attribute"):
            to_xmi(cas2)

    def test_supertype_cycle_detected(self):
        from deeplearning4j_tpu.nlp.uima import TypeDescription, TypeSystem
        ts = TypeSystem([TypeDescription("a", supertype="b"),
                         TypeDescription("b", supertype="a")])
        with pytest.raises(ValueError, match="cycle"):
            ts.features_of("a")
        with pytest.raises(ValueError, match="cycle"):
            ts.subsumes("x", "b")

    def test_activation_grid_dense_row_not_black(self):
        from deeplearning4j_tpu.ui.png import activation_grid
        g = activation_grid(np.array([0.0, 1.0, 2.0, 3.0]))
        assert g.max() > 0.0  # a row image, not per-pixel black tiles

    def test_from_xmi_validates(self):
        from deeplearning4j_tpu.nlp.language_packs import CAS, Annotation
        cas = CAS("abc")
        cas.add(Annotation("unknown_type", 0, 1, "a"))
        xml = to_xmi(cas)
        with pytest.raises(ValueError, match="unknown type"):
            from_xmi(xml, DEFAULT_TYPE_SYSTEM)
        # without a type system it parses fine
        assert from_xmi(xml).select("unknown_type")[0].text == "a"
