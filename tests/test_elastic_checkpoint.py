"""Tests for distributed checkpointing: sharded save, cross-mesh restore
(resharding), elastic restart (SURVEY §7.2 stage 7)."""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.fetchers import IrisDataSetIterator
from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
from deeplearning4j_tpu.nn.layers.output import OutputLayer
from deeplearning4j_tpu.ops.activations import Activation
from deeplearning4j_tpu.ops.losses import LossFunction
from deeplearning4j_tpu.optimize.updaters import Adam
from deeplearning4j_tpu.parallel.checkpoint import (
    ElasticTrainer,
    latest_checkpoint,
    restore_sharded,
    save_sharded,
)
from deeplearning4j_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    create_mesh,
)


def _conf(seed=1):
    return (NeuralNetConfiguration.Builder().seed(seed).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=16, activation=Activation.TANH))
            .layer(OutputLayer(n_out=3, loss=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(4)).build())


class TestShardedCheckpoint:
    def test_save_restore_exact(self, tmp_path):
        m = MultiLayerNetwork(_conf()).init()
        m.fit(IrisDataSetIterator(30))
        path = save_sharded(m.train_state, str(tmp_path))
        assert os.path.exists(os.path.join(path, "COMMITTED"))

        m2 = MultiLayerNetwork(_conf(seed=99)).init()
        restore_sharded(m2, path)
        x = np.asarray(next(iter(IrisDataSetIterator(30))).features)
        np.testing.assert_allclose(np.asarray(m.output(x)),
                                   np.asarray(m2.output(x)), rtol=1e-6)
        assert int(m2.train_state.iteration) == int(m.train_state.iteration)

    def test_restore_reshards_to_new_mesh(self, tmp_path, devices):
        m = MultiLayerNetwork(_conf()).init()
        m.fit(IrisDataSetIterator(30))
        path = save_sharded(m.train_state, str(tmp_path))

        mesh = create_mesh({DATA_AXIS: 4, MODEL_AXIS: 2}, devices[:8])
        m2 = MultiLayerNetwork(_conf()).init()
        restore_sharded(m2, path, mesh=mesh)
        x = np.asarray(next(iter(IrisDataSetIterator(30))).features)
        np.testing.assert_allclose(np.asarray(m.output(x)),
                                   np.asarray(m2.output(x)), rtol=1e-6)

    def test_partial_checkpoint_ignored(self, tmp_path):
        m = MultiLayerNetwork(_conf()).init()
        path = save_sharded(m.train_state, str(tmp_path))
        os.remove(os.path.join(path, "COMMITTED"))  # simulate torn write
        assert latest_checkpoint(str(tmp_path)) is None

    def test_shape_mismatch_rejected(self, tmp_path):
        m = MultiLayerNetwork(_conf()).init()
        path = save_sharded(m.train_state, str(tmp_path))
        bigger = (NeuralNetConfiguration.Builder().updater(Adam(1e-2))
                  .list()
                  .layer(DenseLayer(n_out=32, activation=Activation.TANH))
                  .layer(OutputLayer(n_out=3, loss=LossFunction.MCXENT,
                                     activation=Activation.SOFTMAX))
                  .set_input_type(InputType.feed_forward(4)).build())
        m2 = MultiLayerNetwork(bigger).init()
        with pytest.raises(ValueError, match="shape"):
            restore_sharded(m2, path)


class TestMultihostSafeLayout:
    def test_sharded_arrays_written_as_pieces(self, tmp_path, devices):
        """save_sharded must write per-shard pieces (format 2), never one
        gathered full array, and restore must reassemble them exactly."""
        import glob
        import json

        import jax

        m = MultiLayerNetwork(_conf()).init()
        m.fit(IrisDataSetIterator(30))
        mesh = create_mesh({DATA_AXIS: 2, MODEL_AXIS: 4}, devices[:8])
        from deeplearning4j_tpu.parallel.sharding import (
            apply_shardings, infer_param_shardings)
        sh = infer_param_shardings(m.train_state.params, mesh)
        m.train_state = m.train_state._replace(
            params=apply_shardings(m.train_state.params, sh))

        path = save_sharded(m.train_state, str(tmp_path))
        with open(os.path.join(path, "manifest.json")) as f:
            manifest = json.load(f)
        assert manifest["format"] == 2
        assert glob.glob(os.path.join(path, "params.proc0000.npz"))
        with open(os.path.join(path, "params.proc0000.idx.json")) as f:
            index = json.load(f)
        # at least one leaf was actually split into >1 piece on disk
        from collections import Counter
        pieces = Counter(meta["leaf"] for meta in index.values())
        assert max(pieces.values()) > 1, pieces

        m2 = MultiLayerNetwork(_conf(seed=5)).init()
        restore_sharded(m2, path)
        x = np.asarray(next(iter(IrisDataSetIterator(30))).features)
        np.testing.assert_allclose(np.asarray(m.output(x)),
                                   np.asarray(m2.output(x)), rtol=1e-6)

    def test_opt_state_resharded_like_params(self, tmp_path, devices):
        """Adam mu/nu must land with the matching param's sharding on
        restore, not fully replicated (ADVICE: 2x params of wasted HBM)."""
        import jax

        m = MultiLayerNetwork(_conf()).init()
        m.fit(IrisDataSetIterator(30))
        path = save_sharded(m.train_state, str(tmp_path))

        mesh = create_mesh({DATA_AXIS: 2, MODEL_AXIS: 4}, devices[:8])
        m2 = MultiLayerNetwork(_conf()).init()
        restore_sharded(m2, path, mesh=mesh)

        params_flat, _ = jax.tree_util.tree_flatten(m2.train_state.params)
        opt_flat, _ = jax.tree_util.tree_flatten(m2.train_state.opt_state)
        param_shardings = {a.shape: a.sharding for a in params_flat}
        mirrored = [a for a in opt_flat
                    if hasattr(a, "shape") and a.shape in param_shardings
                    and a.ndim >= 1]
        assert mirrored, "expected opt leaves mirroring param shapes"
        for a in mirrored:
            assert a.sharding == param_shardings[a.shape], (
                a.shape, a.sharding)


class TestElasticTrainer:
    def test_checkpoint_resume_continue(self, tmp_path, devices):
        d = str(tmp_path / "elastic")
        m = MultiLayerNetwork(_conf()).init()
        it = IrisDataSetIterator(30)
        ElasticTrainer(m, d, checkpoint_every=3).fit(it, epochs=2)
        steps_before = int(m.train_state.iteration)
        assert latest_checkpoint(d) is not None

        # "restart" with a different mesh shape — elastic resize
        mesh = create_mesh({DATA_AXIS: 8, MODEL_AXIS: 1}, devices[:8])
        m2 = MultiLayerNetwork(_conf(seed=7)).init()
        et2 = ElasticTrainer(m2, d, checkpoint_every=3, mesh=mesh)
        assert et2.resume()
        assert int(m2.train_state.iteration) == steps_before
        x = np.asarray(next(iter(IrisDataSetIterator(30))).features)
        np.testing.assert_allclose(np.asarray(m.output(x)),
                                   np.asarray(m2.output(x)), rtol=1e-6)

        et2.fit(it, epochs=1)
        assert int(m2.train_state.iteration) > steps_before

    def test_resume_without_checkpoint(self, tmp_path):
        m = MultiLayerNetwork(_conf()).init()
        et = ElasticTrainer(m, str(tmp_path / "none"))
        assert not et.resume()


class TestBf16Checkpoint:
    def test_bf16_state_roundtrip(self, tmp_path):
        """bf16 leaves (npz can't store them natively) survive save/restore
        via raw-bit encoding + manifest dtype record."""
        from deeplearning4j_tpu.datasets.fetchers import (
            UciSequenceDataSetIterator)
        from deeplearning4j_tpu.nn.layers.recurrent import LSTM
        from deeplearning4j_tpu.nn.layers.output import RnnOutputLayer

        conf = (NeuralNetConfiguration.Builder().seed(3)
                .updater(Adam(5e-3)).compute_dtype("bfloat16").list()
                .layer(LSTM(n_out=8, activation=Activation.TANH))
                .layer(RnnOutputLayer(n_out=6, loss=LossFunction.MCXENT,
                                      activation=Activation.SOFTMAX))
                .set_input_type(InputType.recurrent(1, 60)).build())
        m = MultiLayerNetwork(conf).init()
        m.fit(UciSequenceDataSetIterator(16))
        path = save_sharded(m.train_state, str(tmp_path))
        m2 = MultiLayerNetwork(conf).init()
        with pytest.warns(UserWarning, match="not used"):
            restore_sharded(m2, path)  # fresh model lacks rnn carries
        x = np.asarray(next(iter(UciSequenceDataSetIterator(16))).features)
        np.testing.assert_allclose(np.asarray(m.output(x)),
                                   np.asarray(m2.output(x)),
                                   rtol=1e-5, atol=1e-6)


class TestRestoreFailureModes:
    """restore_sharded beyond the happy path (ISSUE 7 satellite): the
    legacy single-npz format 1, a checkpoint missing a leaf the model
    needs, and the unconsumed-entries warning text."""

    def _save(self, tmp_path):
        m = MultiLayerNetwork(_conf()).init()
        m.fit(IrisDataSetIterator(30))
        return m, save_sharded(m.train_state, str(tmp_path))

    def test_legacy_format1_roundtrip(self, tmp_path):
        """A format-1 checkpoint (whole-leaf npz per group, no piece
        index) restores through the same restore_sharded path."""
        import json as _json

        from deeplearning4j_tpu.parallel.checkpoint import _GroupReader

        m, path = self._save(tmp_path)
        with open(os.path.join(path, "manifest.json")) as f:
            manifest = _json.load(f)
        # demote to format 1: assemble every leaf whole, write the
        # single {group}.npz the old writer produced, drop the pieces
        for group in ("params", "model_state", "opt_state"):
            reader = _GroupReader(path, group, manifest)
            whole = {k: np.asarray(reader.read(k)) for k in reader.keys()}
            for f_ in os.listdir(path):
                if f_.startswith(f"{group}.proc"):
                    os.remove(os.path.join(path, f_))
            np.savez(os.path.join(path, f"{group}.npz"), **whole)
        manifest["format"] = 1
        with open(os.path.join(path, "manifest.json"), "w") as f:
            _json.dump(manifest, f)

        m2 = MultiLayerNetwork(_conf(seed=99)).init()
        restore_sharded(m2, path)
        x = np.asarray(next(iter(IrisDataSetIterator(30))).features)
        np.testing.assert_allclose(np.asarray(m.output(x)),
                                   np.asarray(m2.output(x)), rtol=1e-6)
        assert int(m2.train_state.iteration) == \
            int(m.train_state.iteration)

    def test_missing_leaf_raises_keyerror(self, tmp_path):
        """A leaf the model expects but the checkpoint lacks must raise
        (silently mixing restored and random weights is the failure the
        reference's resume semantics forbid)."""
        import json as _json

        _, path = self._save(tmp_path)
        victim = None
        for f_ in sorted(os.listdir(path)):
            if f_.startswith("params.proc") and f_.endswith(".idx.json"):
                ip = os.path.join(path, f_)
                with open(ip) as fh:
                    idx = _json.load(fh)
                if victim is None:
                    victim = next(iter(idx.values()))["leaf"]
                idx = {k: v for k, v in idx.items()
                       if v["leaf"] != victim}
                with open(ip, "w") as fh:
                    _json.dump(idx, fh)
        assert victim is not None
        m2 = MultiLayerNetwork(_conf()).init()
        with pytest.raises(KeyError, match="missing params leaf"):
            restore_sharded(m2, path)

    def test_unconsumed_msg_complete_listing(self):
        from deeplearning4j_tpu.parallel.checkpoint import _unconsumed_msg
        msg = _unconsumed_msg("params", {"a", "b", "c"})
        assert "['a', 'b', 'c']" in msg
        assert "more" not in msg and "..." not in msg

    def test_unconsumed_msg_truncated_listing(self):
        from deeplearning4j_tpu.parallel.checkpoint import _unconsumed_msg
        keys = {f"k{i}" for i in range(9)}
        msg = _unconsumed_msg("opt_state", keys)
        assert "(+4 more)" in msg
