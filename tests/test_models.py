"""MultiLayerNetwork / ComputationGraph end-to-end tests.

Analog of the reference's core suites in deeplearning4j-core/src/test
(MultiLayerTest, ComputationGraphTestRNN, TestSetGetParameters, conf serde
round-trips).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import ArrayDataSetIterator, DataSet
from deeplearning4j_tpu.datasets.fetchers import (
    IrisDataSetIterator,
    MnistDataSetIterator,
)
from deeplearning4j_tpu.models.computation_graph import ComputationGraph
from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork
from deeplearning4j_tpu.models.serialization import (
    restore_computation_graph,
    restore_multi_layer_network,
    save_model,
)
from deeplearning4j_tpu.nn.config import (
    MultiLayerConfiguration,
    NeuralNetConfiguration,
)
from deeplearning4j_tpu.nn.graph.vertices import (
    ElementWiseVertex,
    MergeVertex,
)
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.convolution import (
    ConvolutionLayer,
    ConvolutionMode,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
from deeplearning4j_tpu.nn.layers.normalization import BatchNormalization
from deeplearning4j_tpu.nn.layers.output import OutputLayer, RnnOutputLayer
from deeplearning4j_tpu.nn.layers.recurrent import LSTM
from deeplearning4j_tpu.ops.activations import Activation
from deeplearning4j_tpu.ops.losses import LossFunction
from deeplearning4j_tpu.optimize.updaters import Adam, Sgd


def iris_mlp_conf(seed=123):
    return (NeuralNetConfiguration.Builder()
            .seed(seed)
            .updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=16, activation=Activation.RELU))
            .layer(DenseLayer(n_out=16, activation=Activation.RELU))
            .layer(OutputLayer(n_out=3, loss=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(4))
            .build())


def test_builder_shape_inference():
    conf = iris_mlp_conf()
    assert conf.layers[0].n_in == 4
    assert conf.layers[1].n_in == 16
    assert conf.layers[2].n_in == 16


def test_mlp_learns_iris():
    model = MultiLayerNetwork(iris_mlp_conf()).init()
    it = IrisDataSetIterator(batch_size=50)
    before = model.evaluate(it).accuracy()
    model.fit(it, epochs=60)
    e = model.evaluate(it)
    assert e.accuracy() > 0.9, e.stats()
    assert e.accuracy() > before


def test_score_decreases():
    model = MultiLayerNetwork(iris_mlp_conf()).init()
    it = IrisDataSetIterator(batch_size=150)
    batch = next(iter(it))
    s0 = model.score(batch)
    model.fit(it, epochs=20)
    assert model.score(batch) < s0


def test_conf_json_roundtrip():
    conf = iris_mlp_conf()
    js = conf.to_json()
    conf2 = MultiLayerConfiguration.from_json(js)
    assert conf2.to_json() == js
    assert conf2.layers[1].n_out == 16
    assert conf2.global_config.updater == Adam(1e-2)
    m = MultiLayerNetwork(conf2).init()
    assert m.output(np.zeros((2, 4), np.float32)).shape == (2, 3)


def test_model_serialization_roundtrip(tmp_path):
    model = MultiLayerNetwork(iris_mlp_conf()).init()
    it = IrisDataSetIterator(batch_size=150)
    model.fit(it, epochs=3)
    x = np.random.default_rng(0).normal(size=(5, 4)).astype(np.float32)
    y1 = np.asarray(model.output(x))
    path = str(tmp_path / "model.zip")
    save_model(model, path, save_updater=True)
    model2 = restore_multi_layer_network(path, load_updater=True)
    y2 = np.asarray(model2.output(x))
    np.testing.assert_allclose(y1, y2, rtol=1e-6)
    # exact training resume: one more batch on each gives identical params
    batch = next(iter(it))
    model.fit(batch)
    model2.fit(batch)
    for a, b in zip(jax.tree_util.tree_leaves(model.params),
                    jax.tree_util.tree_leaves(model2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_small_cnn_trains():
    conf = (NeuralNetConfiguration.Builder()
            .seed(7)
            .updater(Adam(1e-2))
            .list()
            .layer(ConvolutionLayer(n_out=8, kernel_size=(3, 3),
                                    activation=Activation.RELU,
                                    convolution_mode=ConvolutionMode.SAME))
            .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
            .layer(BatchNormalization())
            .layer(DenseLayer(n_out=32, activation=Activation.RELU))
            .layer(OutputLayer(n_out=10))
            .set_input_type(InputType.convolutional_flat(28, 28, 1))
            .build())
    model = MultiLayerNetwork(conf).init()
    it = MnistDataSetIterator(batch_size=64, subset=512, train=True)
    model.fit(it, epochs=3)
    acc = model.evaluate(it).accuracy()
    assert acc > 0.5, f"CNN failed to learn synthetic mnist: {acc}"


def test_lstm_sequence_classification():
    # classify whether the mean of a noisy sequence is positive
    rng = np.random.default_rng(3)
    n, t, f = 256, 10, 4
    x = rng.normal(size=(n, t, f)).astype(np.float32)
    shift = rng.choice([-0.8, 0.8], size=(n, 1, 1)).astype(np.float32)
    x = x + shift
    y = (shift[:, 0, 0] > 0).astype(np.int64)
    labels = np.zeros((n, 2), np.float32)
    labels[np.arange(n), y] = 1.0
    conf = (NeuralNetConfiguration.Builder()
            .seed(5)
            .updater(Adam(5e-3))
            .list()
            .layer(LSTM(n_out=16))
            .layer(OutputLayer(n_out=2))
            .set_input_type(InputType.recurrent(f, t))
            .build())
    from deeplearning4j_tpu.nn.layers.recurrent import LastTimeStep
    # LSTM output is a sequence; use global pooling via LastTimeStep wrap
    conf = (NeuralNetConfiguration.Builder()
            .seed(5)
            .updater(Adam(5e-3))
            .list()
            .layer(LastTimeStep(inner=LSTM(n_in=f, n_out=16)))
            .layer(OutputLayer(n_out=2))
            .set_input_type(InputType.recurrent(f, t))
            .build())
    model = MultiLayerNetwork(conf).init()
    it = ArrayDataSetIterator(DataSet(x, labels), 64, shuffle=True, seed=0)
    model.fit(it, epochs=8)
    assert model.evaluate(it).accuracy() > 0.85


def test_rnn_output_layer_per_timestep():
    rng = np.random.default_rng(4)
    n, t, f = 128, 6, 3
    x = rng.normal(size=(n, t, f)).astype(np.float32)
    y = (x.sum(axis=2) > 0)
    labels = np.stack([1 - y, y], axis=-1).astype(np.float32)
    conf = (NeuralNetConfiguration.Builder()
            .seed(5).updater(Adam(1e-2)).list()
            .layer(LSTM(n_out=16))
            .layer(RnnOutputLayer(n_out=2))
            .set_input_type(InputType.recurrent(f, t))
            .build())
    model = MultiLayerNetwork(conf).init()
    it = ArrayDataSetIterator(DataSet(x, labels), 32)
    model.fit(it, epochs=10)
    preds = np.asarray(model.output(x))
    assert preds.shape == (n, t, 2)
    acc = ((preds.argmax(-1) == y).mean())
    assert acc > 0.8


def test_computation_graph_branches():
    conf = (NeuralNetConfiguration.Builder()
            .seed(9)
            .updater(Adam(1e-2))
            .graph_builder()
            .add_inputs("in")
            .add_layer("a", DenseLayer(n_out=8, activation=Activation.RELU), "in")
            .add_layer("b", DenseLayer(n_out=8, activation=Activation.TANH), "in")
            .add_vertex("merge", MergeVertex(), "a", "b")
            .add_layer("out", OutputLayer(n_out=3), "merge")
            .set_outputs("out")
            .set_input_types(InputType.feed_forward(4))
            .build())
    model = ComputationGraph(conf).init()
    assert conf.node("out").layer.n_in == 16
    it = IrisDataSetIterator(batch_size=50)
    model.fit(it, epochs=40)
    acc = model.evaluate(it).accuracy()
    assert acc > 0.9


def test_computation_graph_residual():
    conf = (NeuralNetConfiguration.Builder()
            .seed(9).updater(Adam(1e-2)).graph_builder()
            .add_inputs("in")
            .add_layer("fc1", DenseLayer(n_out=4, activation=Activation.RELU), "in")
            .add_vertex("res", ElementWiseVertex(op="add"), "fc1", "in")
            .add_layer("out", OutputLayer(n_out=3), "res")
            .set_outputs("out")
            .set_input_types(InputType.feed_forward(4))
            .build())
    model = ComputationGraph(conf).init()
    y = model.output(np.zeros((2, 4), np.float32))
    assert y.shape == (2, 3)


def test_cg_serialization_roundtrip(tmp_path):
    conf = (NeuralNetConfiguration.Builder()
            .seed(9).updater(Sgd(1e-2)).graph_builder()
            .add_inputs("in")
            .add_layer("fc", DenseLayer(n_out=8, activation=Activation.RELU), "in")
            .add_layer("out", OutputLayer(n_out=3), "fc")
            .set_outputs("out")
            .set_input_types(InputType.feed_forward(4))
            .build())
    model = ComputationGraph(conf).init()
    model.fit(IrisDataSetIterator(batch_size=150), epochs=2)
    x = np.zeros((2, 4), np.float32)
    y1 = np.asarray(model.output(x))
    path = str(tmp_path / "cg.zip")
    save_model(model, path)
    model2 = restore_computation_graph(path)
    np.testing.assert_allclose(y1, np.asarray(model2.output(x)), rtol=1e-6)


def test_frozen_layer_not_updated():
    conf = (NeuralNetConfiguration.Builder()
            .seed(1).updater(Adam(1e-2)).list()
            .layer(DenseLayer(n_out=8, activation=Activation.RELU, frozen=True))
            .layer(OutputLayer(n_out=3))
            .set_input_type(InputType.feed_forward(4))
            .build())
    model = MultiLayerNetwork(conf).init()
    w0 = np.asarray(model.params["layer_0"]["W"]).copy()
    model.fit(IrisDataSetIterator(batch_size=150), epochs=3)
    np.testing.assert_allclose(w0, np.asarray(model.params["layer_0"]["W"]))
    # but the output layer DID move
    assert not np.allclose(0, np.asarray(model.params["layer_1"]["W"]) -
                           np.asarray(MultiLayerNetwork(conf).init()
                                      .params["layer_1"]["W"]))


def test_summary_and_num_params():
    model = MultiLayerNetwork(iris_mlp_conf()).init()
    s = model.summary()
    assert "DenseLayer" in s and "OutputLayer" in s
    # 4*16+16 + 16*16+16 + 16*3+3 = 80+272+51
    assert model.num_params() == (4 * 16 + 16) + (16 * 16 + 16) + (16 * 3 + 3)
