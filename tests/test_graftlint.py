"""graftlint analyzer tests: each rule catches its seeded bug shape
(true positives — including the PR 1 use-after-donate and the PR 4
reset-race patterns), the current in-repo code passes clean (false-
positive guard), and pragmas/baselines round-trip.

Fixture snippets are written to tmp_path; files outside the repo root
run every rule regardless of its hot-path scoping, which is exactly
what a fixture corpus wants.
"""

from __future__ import annotations

import json
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from tools.graftlint import (
    REPO_ROOT, Finding, get_rules, load_baseline, scan, split_baselined,
    write_baseline)
from tools.graftlint.baseline import fingerprints
from tools.graftlint.rules.host_sync import HostSyncRule

pytestmark = pytest.mark.filterwarnings("ignore::DeprecationWarning")


def lint(tmp_path: Path, source: str, rules=None, name="snippet.py"):
    f = tmp_path / name
    f.write_text(textwrap.dedent(source), encoding="utf-8")
    return scan([str(f)], rules=get_rules(rules) if rules else None)


def rules_hit(findings):
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# donation-safety
# ---------------------------------------------------------------------------

PR1_SHAPE = """
    import functools
    import jax
    import jax.numpy as jnp
    import numpy as np

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def skipgram_step(syn0, syn1, idx):
        return syn0 * 2, syn1

    def load_and_train(npz):
        # the PR 1 test_nlp_cluster bug: numpy-owned buffers adopted
        # zero-copy by the CPU backend, then donated -> use-after-free
        syn0 = np.asarray(npz["syn0"])
        syn1 = np.asarray(npz["syn1"])
        syn0, syn1 = skipgram_step(syn0, syn1, 3)
        return syn0, syn1
"""


class TestDonationSafety:
    def test_pr1_numpy_into_donated_flagged(self, tmp_path):
        findings = lint(tmp_path, PR1_SHAPE, rules=["donation-safety"])
        assert len(findings) == 2           # syn0 AND syn1
        assert all("numpy-backed" in f.message for f in findings)

    def test_defensive_copy_is_clean(self, tmp_path):
        findings = lint(tmp_path, """
            import functools
            import jax
            import jax.numpy as jnp
            import numpy as np

            @functools.partial(jax.jit, donate_argnums=(0,))
            def step(syn0, idx):
                return syn0 * 2

            def ok(npz):
                syn0 = jnp.array(np.asarray(npz["syn0"]))
                syn0 = step(syn0, 3)
                return syn0
        """, rules=["donation-safety"])
        assert findings == []

    def test_use_after_donate_flagged(self, tmp_path):
        findings = lint(tmp_path, """
            import jax

            def loss_fn(s, b):
                return s, 0.0

            step = jax.jit(loss_fn, donate_argnums=(0,))

            def train(state, batch):
                new_state, loss = step(state, batch)
                return state, loss       # donated binding read again
        """, rules=["donation-safety"])
        assert len(findings) == 1
        assert "was donated at line" in findings[0].message

    def test_rebinding_is_clean(self, tmp_path):
        findings = lint(tmp_path, """
            import jax

            def loss_fn(s, b):
                return s, 0.0

            step = jax.jit(loss_fn, donate_argnums=(0,))

            def train(state, batches):
                for b in batches:
                    state, loss = step(state, b)
                return state, loss
        """, rules=["donation-safety"])
        assert findings == []

    def test_loop_without_rebinding_flagged(self, tmp_path):
        findings = lint(tmp_path, """
            import jax

            def loss_fn(s, b):
                return s, 0.0

            step = jax.jit(loss_fn, donate_argnums=(0,))

            def train(state, batches):
                for b in batches:
                    loss = step(state, b)   # iter N donates, N+1 reads
                return loss
        """, rules=["donation-safety"])
        assert len(findings) == 1
        assert "state" in findings[0].message

    def test_branch_donation_merges_conservatively(self, tmp_path):
        # donated on ONE branch only -> a later read must NOT be flagged
        findings = lint(tmp_path, """
            import jax

            def f(s):
                return s

            step = jax.jit(f, donate_argnums=(0,))

            def g(state, flag):
                if flag:
                    out = step(state)
                else:
                    out = state
                return state        # alive on the else path
        """, rules=["donation-safety"])
        assert findings == []

    def test_cross_module_donation_tracked(self, tmp_path):
        (tmp_path / "kernels.py").write_text(textwrap.dedent("""
            import functools
            import jax

            @functools.partial(jax.jit, donate_argnums=(0,))
            def fused_step(w, grad):
                return w
        """), encoding="utf-8")
        (tmp_path / "caller.py").write_text(textwrap.dedent("""
            import numpy as np
            from kernels import fused_step

            def train(grad):
                w = np.zeros((4, 4))
                w2 = fused_step(w, grad)
                return w2
        """), encoding="utf-8")
        # root=tmp_path so "from kernels import ..." resolves against
        # the fixture corpus's own module namespace
        findings = scan([str(tmp_path)], rules=get_rules(
            ["donation-safety"]), root=tmp_path)
        assert len(findings) == 1
        assert findings[0].path.name == "caller.py"
        assert "numpy-backed 'w'" in findings[0].message

    def test_maker_convention_donates_arg0(self, tmp_path):
        findings = lint(tmp_path, """
            from deeplearning4j_tpu.optimize.solver import make_train_step

            def train(model, state, batches):
                step = make_train_step(model)
                for b in batches:
                    out = step(state, b)    # state never rebound
                return out
        """, rules=["donation-safety"])
        assert len(findings) == 1

    def test_inference_builders_pinned_non_donating(self, tmp_path):
        # the quantized (and plain) inference builders return
        # NON-donating callables: the serving engine replays committed
        # int8 buffers across requests, so reusing the un-rebound
        # params pytree forever is the CORRECT shape — no finding, even
        # with numpy-backed inputs flowing in
        findings = lint(tmp_path, """
            import numpy as np
            from deeplearning4j_tpu.parallel.quant import quantize_model

            def serve(model, policy, mstate, batches):
                qm = quantize_model(model, policy)
                fwd = qm.build_inference_fn()
                outs = []
                for b in batches:
                    x = np.asarray(b)
                    outs.append(fwd(qm.params, mstate, x, None))
                return outs
        """, rules=["donation-safety"])
        assert findings == []

    def test_non_literal_argnums_is_unknown_not_flagged(self, tmp_path):
        findings = lint(tmp_path, """
            import jax

            def f(s):
                return s

            def build(donate):
                step = jax.jit(f, donate_argnums=(0,) if donate else ())
                return step

            def train(state):
                step = build(True)
                out = step(state)
                return state         # unknowable statically: no finding
        """, rules=["donation-safety"])
        assert findings == []


# ---------------------------------------------------------------------------
# recompile-hazard
# ---------------------------------------------------------------------------

class TestRecompileHazard:
    def test_jit_in_loop_flagged(self, tmp_path):
        findings = lint(tmp_path, """
            import jax

            def serve(batches):
                outs = []
                for b in batches:
                    f = jax.jit(lambda a: a + 1)
                    outs.append(f(b))
                return outs
        """, rules=["recompile-hazard"])
        assert len(findings) == 1
        assert "inside a loop" in findings[0].message

    def test_immediately_invoked_jit_flagged(self, tmp_path):
        findings = lint(tmp_path, """
            import jax

            def predict(model_fn, x):
                return jax.jit(model_fn)(x)
        """, rules=["recompile-hazard"])
        assert len(findings) == 1
        assert "invoked in one expression" in findings[0].message

    def test_module_level_and_builder_jits_clean(self, tmp_path):
        findings = lint(tmp_path, """
            import functools
            import jax

            @jax.jit
            def fwd(x):
                return x * 2

            class Engine:
                def __init__(self, fn):
                    self._jit = jax.jit(lambda p, x: fn(p, x))

                def _build_train_step(self, fn):
                    return jax.jit(fn, donate_argnums=(0,))

            @functools.lru_cache(maxsize=4)
            def _range_fn(devs):
                return jax.jit(lambda a: (a.min(), a.max()))
        """, rules=["recompile-hazard"])
        assert findings == []

    def test_data_dependent_static_arg_flagged(self, tmp_path):
        findings = lint(tmp_path, """
            import jax

            def f(x, n):
                return x[:n]

            crop = jax.jit(f, static_argnums=(1,))

            def serve(x, count):
                return crop(x, int(count))     # runtime value as key
        """, rules=["recompile-hazard"])
        assert len(findings) == 1
        assert "static_argnums" in findings[0].message

    def test_shape_derived_static_arg_clean(self, tmp_path):
        findings = lint(tmp_path, """
            import jax

            def f(x, n):
                return x[:n]

            crop = jax.jit(f, static_argnums=(1,))

            def serve(x):
                return crop(x, int(x.shape[0] // 2))  # trace-time math
        """, rules=["recompile-hazard"])
        assert findings == []

    def test_traced_branch_flagged_static_param_exempt(self, tmp_path):
        findings = lint(tmp_path, """
            import functools
            import jax

            @jax.jit
            def bad(x):
                if x > 0:                     # traced-value branch
                    return x
                return -x

            @functools.partial(jax.jit, static_argnums=(1,))
            def ok(x, training):
                if training:                  # static: branch is fine
                    return x * 2
                return x

            @jax.jit
            def shapes_ok(x):
                if x.shape[0] > 1:            # trace-time constant
                    return x[0]
                return x
        """, rules=["recompile-hazard"])
        assert len(findings) == 1
        assert "'x'" in findings[0].message


# ---------------------------------------------------------------------------
# thread-discipline
# ---------------------------------------------------------------------------

PR4_SHAPE = """
    import threading

    class Prefetcher:
        # the PR 4 AsyncDataSetIterator race shape: worker thread and
        # caller both mutate shared state with no lock
        def __init__(self, base):
            self.base = base
            self.depth = 0
            self._worker = threading.Thread(target=self._run,
                                            daemon=True)
            self._worker.start()

        def _run(self):
            while True:
                self.depth += 1      # thread side, no lock

        def reset(self):
            self.depth = 0           # caller side, no lock
"""


class TestThreadDiscipline:
    def test_pr4_reset_race_flagged(self, tmp_path):
        findings = lint(tmp_path, PR4_SHAPE, rules=["thread-discipline"])
        assert len(findings) == 2          # both unlocked writers
        assert all("self.depth" in f.snippet or "depth" in f.message
                   for f in findings)

    def test_common_lock_is_clean(self, tmp_path):
        findings = lint(tmp_path, """
            import threading

            class Prefetcher:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.depth = 0
                    t = threading.Thread(target=self._run, daemon=True)
                    t.start()

                def _run(self):
                    while True:
                        with self._lock:
                            self.depth += 1

                def reset(self):
                    with self._lock:
                        self.depth = 0
        """, rules=["thread-discipline"])
        assert findings == []

    def test_thread_reached_via_self_call_chain(self, tmp_path):
        # queue_depth-miss shape: the mutation happens two calls deep
        # into the thread target
        findings = lint(tmp_path, """
            import threading

            class Engine:
                def __init__(self):
                    self.carry = None
                    t = threading.Thread(target=self._loop, daemon=True)
                    t.start()

                def _loop(self):
                    while True:
                        self._form()

                def _form(self):
                    self.carry = object()      # thread side (indirect)

                def shutdown(self):
                    self.carry = None          # caller side
        """, rules=["thread-discipline"])
        assert len(findings) == 2

    def test_closure_thread_target(self, tmp_path):
        findings = lint(tmp_path, """
            import threading

            class Listener:
                def __init__(self):
                    self.done = False

                def start(self):
                    def run():
                        self.done = True       # thread side
                    threading.Thread(target=run, daemon=True).start()

                def cancel(self):
                    self.done = True           # caller side
        """, rules=["thread-discipline"])
        assert len(findings) == 2

    def test_no_threads_no_findings(self, tmp_path):
        findings = lint(tmp_path, """
            class Plain:
                def a(self):
                    self.x = 1

                def b(self):
                    self.x = 2
        """, rules=["thread-discipline"])
        assert findings == []

    def test_lock_order_inversion_flagged(self, tmp_path):
        findings = lint(tmp_path, """
            import threading

            class Broker:
                def __init__(self):
                    self._alock = threading.Lock()
                    self._block = threading.Lock()
                    threading.Thread(target=self.pump).start()

                def pump(self):
                    with self._alock:
                        with self._block:
                            pass

                def drain(self):
                    with self._block:
                        with self._alock:
                            pass
        """, rules=["thread-discipline"])
        inversions = [f for f in findings
                      if "lock-order inversion" in f.message]
        assert len(inversions) == 1


# ---------------------------------------------------------------------------
# tracer-leak
# ---------------------------------------------------------------------------

class TestTracerLeak:
    def test_self_store_in_jitted_method_flagged(self, tmp_path):
        findings = lint(tmp_path, """
            import functools
            import jax

            class Model:
                @functools.partial(jax.jit, donate_argnums=(0,))
                def step(self, x):
                    self.last_loss = x.sum()    # leaks the tracer
                    return x * 2
        """, rules=["tracer-leak"])
        assert len(findings) == 1
        assert "self.last_loss" in findings[0].message

    def test_global_and_closure_stores_flagged(self, tmp_path):
        findings = lint(tmp_path, """
            import jax

            STATS = {}
            _count = 0

            def make(fn):
                cache = {}

                def traced(x):
                    global _count
                    _count = _count + 1         # global store
                    STATS["x"] = x              # closure subscript
                    return fn(x)
                return jax.jit(traced)
        """, rules=["tracer-leak"])
        assert len(findings) == 2

    def test_pure_jitted_fn_clean(self, tmp_path):
        findings = lint(tmp_path, """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def step(state, batch):
                out = {}
                out["loss"] = jnp.sum(batch)    # local dict: fine
                acc = 0.0
                for i in range(3):
                    acc = acc + i               # local rebind: fine
                return state, out["loss"] + acc
        """, rules=["tracer-leak"])
        assert findings == []

    def test_shard_mapped_fn_covered(self, tmp_path):
        findings = lint(tmp_path, """
            from jax.experimental.shard_map import shard_map

            DIAG = []

            def per_replica(x):
                DIAG[0] = x          # closure store under trace
                return x

            def build(mesh, specs):
                return shard_map(per_replica, mesh, in_specs=specs,
                                 out_specs=specs)
        """, rules=["tracer-leak"])
        assert len(findings) == 1


# ---------------------------------------------------------------------------
# host-sync (ported rule + alias pragma)
# ---------------------------------------------------------------------------

class TestHostSync:
    def test_patterns_flagged_and_alias_pragma_suppresses(self,
                                                          tmp_path):
        findings = lint(tmp_path, """
            import numpy as np

            def hot(loss, arr):
                a = float(loss)
                b = np.asarray(arr)
                c = loss.item()     # host-sync-ok: test constant
                return a, b, c
        """, rules=["host-sync"])
        assert len(findings) == 2
        assert {f.line for f in findings} == {5, 6}

    def test_comment_prose_not_flagged(self, tmp_path):
        findings = lint(tmp_path, """
            def hot(x):
                # never call float(x) here
                return x
        """, rules=["host-sync"])
        assert findings == []

    def test_hot_path_scoping_inside_repo(self):
        # the rule only applies to the curated hot paths: a ui/ module
        # (off the hot-path list, full of legitimate host reads) must
        # be skipped entirely
        rule = HostSyncRule()
        findings = scan(["deeplearning4j_tpu/ui/stats.py"],
                        rules=[rule])
        assert findings == []


# ---------------------------------------------------------------------------
# pragmas, baseline, reports, CLI
# ---------------------------------------------------------------------------

class TestPragmasAndBaseline:
    def test_graftlint_pragma_suppresses_named_rule(self, tmp_path):
        findings = lint(tmp_path, """
            import jax

            def serve(batches):
                for b in batches:
                    f = jax.jit(lambda a: a + 1)  # graftlint: disable=recompile-hazard: test
                    yield f(b)
        """, rules=["recompile-hazard"])
        assert findings == []

    def test_bare_disable_suppresses_all_rules(self, tmp_path):
        findings = lint(tmp_path, """
            import numpy as np

            def hot(loss):
                return float(loss)  # graftlint: disable
        """, rules=["host-sync"])
        assert findings == []

    def test_pragma_for_other_rule_does_not_suppress(self, tmp_path):
        findings = lint(tmp_path, """
            def hot(loss):
                return float(loss)  # graftlint: disable=tracer-leak
        """, rules=["host-sync"])
        assert len(findings) == 1

    def test_baseline_round_trip(self, tmp_path):
        src = tmp_path / "mod.py"
        src.write_text(textwrap.dedent("""
            def hot(loss):
                a = float(loss)
                return a
        """), encoding="utf-8")
        findings = scan([str(src)], rules=get_rules(["host-sync"]))
        assert len(findings) == 1

        bl_path = tmp_path / "baseline.json"
        write_baseline(findings, bl_path)
        baseline = load_baseline(bl_path)
        new, old, stale = split_baselined(findings, baseline)
        assert new == [] and len(old) == 1 and stale == []

        # a NEW finding is not masked by the committed baseline
        src.write_text(textwrap.dedent("""
            def hot(loss, x):
                a = float(loss)
                b = x.item()
                return a, b
        """), encoding="utf-8")
        findings2 = scan([str(src)], rules=get_rules(["host-sync"]))
        new2, old2, _ = split_baselined(findings2, baseline)
        assert len(old2) == 1 and len(new2) == 1
        assert ".item()" in new2[0].snippet

    def test_fingerprint_survives_line_moves(self, tmp_path):
        src = tmp_path / "mod.py"
        src.write_text("def hot(loss):\n    return float(loss)\n",
                       encoding="utf-8")
        [f1] = scan([str(src)], rules=get_rules(["host-sync"]))
        src.write_text("import os\n\n\ndef hot(loss):\n"
                       "    return float(loss)\n", encoding="utf-8")
        [f2] = scan([str(src)], rules=get_rules(["host-sync"]))
        assert f1.line != f2.line
        assert fingerprints([f1]) == fingerprints([f2])

    def test_identical_lines_fingerprint_distinctly(self, tmp_path):
        src = tmp_path / "mod.py"
        src.write_text("def hot(a, b):\n"
                       "    x = float(a)\n"
                       "    x = float(a)\n"
                       "    return x\n", encoding="utf-8")
        findings = scan([str(src)], rules=get_rules(["host-sync"]))
        assert len(findings) == 2
        fps = fingerprints(findings)
        assert len(set(fps)) == 2


class TestCLI:
    def run_cli(self, *args):
        return subprocess.run(
            [sys.executable, "-m", "tools.graftlint", *args],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=120)

    def test_json_format_and_exit_codes(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("def hot(loss):\n    return float(loss)\n",
                       encoding="utf-8")
        r = self.run_cli(str(bad), "--format", "json")
        assert r.returncode == 1
        doc = json.loads(r.stdout)
        assert doc["summary"]["new"] == 1
        assert doc["findings"][0]["rule"] == "host-sync"
        assert doc["findings"][0]["fingerprint"]

        (tmp_path / "ok.py").write_text("x = 1\n", encoding="utf-8")
        r2 = self.run_cli(str(tmp_path / "ok.py"))
        assert r2.returncode == 0

    def test_write_then_check_baseline(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("def hot(loss):\n    return float(loss)\n",
                       encoding="utf-8")
        bl = tmp_path / "bl.json"
        r = self.run_cli(str(bad), "--baseline", str(bl),
                         "--write-baseline")
        assert r.returncode == 0, r.stderr
        r2 = self.run_cli(str(bad), "--baseline", str(bl))
        assert r2.returncode == 0, r2.stderr
        assert "1 baselined" in r2.stderr

    def test_list_rules(self):
        r = self.run_cli("--list-rules")
        assert r.returncode == 0
        for rule in ("host-sync", "donation-safety", "recompile-hazard",
                     "thread-discipline", "tracer-leak"):
            assert rule in r.stdout

    def test_unknown_rule_is_usage_error(self):
        r = self.run_cli("--rules", "no-such-rule")
        assert r.returncode == 2

    def test_shim_cli(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("def hot(loss):\n    return float(loss)\n",
                       encoding="utf-8")
        r = subprocess.run(
            [sys.executable, "tools/check_host_sync.py", "--paths",
             str(bad)], cwd=REPO_ROOT, capture_output=True, text=True,
            timeout=120)
        assert r.returncode == 1
        assert "float() blocks" in r.stderr
        r2 = subprocess.run(
            [sys.executable, "tools/check_host_sync.py"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=120)
        assert r2.returncode == 0, r2.stderr + r2.stdout


# ---------------------------------------------------------------------------
# the false-positive guard: the repo's own (fixed) code passes clean
# ---------------------------------------------------------------------------

class TestTreeIsClean:
    def test_full_default_scan_is_baseline_clean(self):
        findings = scan(["deeplearning4j_tpu", "benchmarks/elastic.py",
                         "tests/multihost_chaos_worker.py"])
        baseline = load_baseline(
            REPO_ROOT / "tools" / "graftlint" / "baseline.json")
        new, _old, _stale = split_baselined(findings, baseline)
        assert new == [], "\n".join(
            f"{f.rel}:{f.line}: [{f.rule}] {f.message}" for f in new)

    def test_fixed_pr1_and_pr4_sites_stay_clean(self):
        # the exact modules whose historical bugs seeded the rules
        findings = scan([
            "deeplearning4j_tpu/nlp/cluster.py",       # PR 1 fix site
            "deeplearning4j_tpu/nlp/glove.py",
            "deeplearning4j_tpu/nlp/sequence_vectors.py",
            "deeplearning4j_tpu/datasets/iterators.py",  # PR 4 fix site
            "deeplearning4j_tpu/parallel/serving.py",    # PR 6 + carry
        ])
        assert findings == [], "\n".join(
            f"{f.rel}:{f.line}: [{f.rule}] {f.message}"
            for f in findings)
