"""Fused conv+BN+ReLU Pallas kernel equivalence vs the plain XLA math
(the accelerated-helper validation tier — reference analog:
deeplearning4j-cuda's ValidateCudnn* tests, SURVEY §4)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.ops.fused_conv import (
    _conv_reference,
    fused_conv_bn_act,
    stats_to_scale_shift,
)

RNG = np.random.default_rng(7)


def _mk(n, h, w, cin, cout, kernel):
    x = jnp.asarray(RNG.normal(0, 1, (n, h, w, cin)).astype(np.float32))
    if kernel == 1:
        wt = jnp.asarray(RNG.normal(0, 0.1, (cin, cout))
                         .astype(np.float32))
    else:
        wt = jnp.asarray(RNG.normal(0, 0.1, (3, 3, cin, cout))
                         .astype(np.float32))
    s = jnp.asarray(RNG.normal(1, 0.1, cin).astype(np.float32))
    b = jnp.asarray(RNG.normal(0, 0.1, cin).astype(np.float32))
    return x, wt, s, b


@pytest.mark.parametrize("case", [
    dict(n=4, h=8, w=8, cin=16, cout=32, kernel=1, stride=1),
    dict(n=4, h=8, w=8, cin=16, cout=32, kernel=1, stride=2),
    dict(n=2, h=33, w=5, cin=24, cout=16, kernel=1, stride=1),  # pad M
    dict(n=4, h=6, w=6, cin=16, cout=24, kernel=3, stride=1),
    dict(n=6, h=2, w=2, cin=32, cout=16, kernel=3, stride=1),   # multi-img
])
def test_forward_matches_reference(case):
    x, wt, s, b = _mk(case["n"], case["h"], case["w"], case["cin"],
                      case["cout"], case["kernel"])
    y, st = fused_conv_bn_act(x, wt, s, b, True, True, case["stride"],
                              True)
    yr, str_ = _conv_reference(x, wt, s, b, True, True, case["stride"])
    np.testing.assert_allclose(np.asarray(y), np.asarray(yr),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(st), np.asarray(str_),
                               rtol=1e-4, atol=1e-3)


@pytest.mark.parametrize("kernel", [1, 3])
def test_no_norm_prologue(kernel):
    """norm_in=False must skip the scale/shift on BOTH conv paths
    (advisor r3 medium: the 3×3 kernel used to apply it
    unconditionally)."""
    x, wt, s, b = _mk(2, 4, 4, 8, 16, kernel)
    y, st = fused_conv_bn_act(x, wt, s, b, False, False, 1, True)
    yr, str_ = _conv_reference(x, wt, s, b, False, False, 1)
    np.testing.assert_allclose(np.asarray(y), np.asarray(yr),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(st), np.asarray(str_),
                               rtol=1e-4, atol=1e-3)


@pytest.mark.parametrize("kernel", [1, 3])
def test_no_norm_grads(kernel):
    """forward/backward consistency for norm_in=False (the advisor-found
    combination: fwd applied the normalize, bwd skipped it)."""
    x, wt, s, b = _mk(2, 4, 4, 8, 12, kernel)

    def loss(f):
        def inner(x, wt):
            y, st = f(x, wt, s, b, False, False, 1)
            return jnp.sum(jnp.tanh(y.astype(jnp.float32))) \
                + 1e-3 * jnp.sum(st)
        return inner

    def fused(x, wt, s, b, r, n, st):
        return fused_conv_bn_act(x, wt, s, b, r, n, st, True)

    gf = jax.grad(loss(fused), argnums=(0, 1))(x, wt)
    gr = jax.grad(loss(_conv_reference), argnums=(0, 1))(x, wt)
    for a, r, name in zip(gf, gr, ["x", "w"]):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(r), rtol=2e-4, atol=2e-4,
            err_msg=f"grad mismatch for {name}")


def test_oversized_plane_falls_back_to_xla():
    """ImageNet-size spatial planes exceed the single-image VMEM budget;
    the op must route to the XLA reference path (fwd AND bwd) instead of
    emitting an uncompilable Pallas call (advisor r3 low)."""
    from deeplearning4j_tpu.ops.fused_conv import _c3_fits_vmem
    assert not _c3_fits_vmem(224, 224, 64, 16)
    assert _c3_fits_vmem(16, 16, 64, 64)
    xb = jnp.asarray(RNG.normal(0, 1, (1, 224, 224, 64))
                     .astype(np.float32))
    wb = jnp.asarray(RNG.normal(0, 0.1, (3, 3, 64, 16))
                     .astype(np.float32))
    sb = jnp.ones(64, jnp.float32)
    bb = jnp.zeros(64, jnp.float32)
    y, st = fused_conv_bn_act(xb, wb, sb, bb, True, True, 1, True)
    yr, str_ = _conv_reference(xb, wb, sb, bb, True, True, 1)
    np.testing.assert_allclose(np.asarray(y), np.asarray(yr),
                               rtol=1e-4, atol=1e-4)
    g = jax.grad(lambda a: jnp.sum(
        fused_conv_bn_act(a, wb, sb, bb, True, True, 1, True)[0]))(xb)
    gr = jax.grad(lambda a: jnp.sum(
        _conv_reference(a, wb, sb, bb, True, True, 1)[0]))(xb)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gr),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("kernel,stride", [(1, 1), (1, 2), (3, 1)])
def test_grads_match_unfused_autodiff(kernel, stride):
    """jax.grad through (y, stats) must equal jax.grad of the plain XLA
    composition — including the batch-stat gradient path (the stats
    outputs are differentiable)."""
    x, wt, s, b = _mk(3, 4, 4, 8, 12, kernel)

    def loss_fused(x, wt, s, b):
        y, st = fused_conv_bn_act(x, wt, s, b, True, True, stride, True)
        # consume y AND the stats the way a downstream BN would
        inv, shift, mean, var = stats_to_scale_shift(
            st, y.size // y.shape[-1], jnp.ones(y.shape[-1]),
            jnp.zeros(y.shape[-1]), 1e-5)
        z = y.astype(jnp.float32) * inv + shift
        return jnp.sum(jnp.tanh(z)) + 0.1 * jnp.sum(mean * mean) \
            + 0.1 * jnp.sum(var)

    def loss_ref(x, wt, s, b):
        y, st = _conv_reference(x, wt, s, b, True, True, stride)
        inv, shift, mean, var = stats_to_scale_shift(
            st, y.size // y.shape[-1], jnp.ones(y.shape[-1]),
            jnp.zeros(y.shape[-1]), 1e-5)
        z = y.astype(jnp.float32) * inv + shift
        return jnp.sum(jnp.tanh(z)) + 0.1 * jnp.sum(mean * mean) \
            + 0.1 * jnp.sum(var)

    gf = jax.grad(loss_fused, argnums=(0, 1, 2, 3))(x, wt, s, b)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(x, wt, s, b)
    for a, r, name in zip(gf, gr, "x w scale shift".split()):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(r), rtol=2e-4, atol=2e-4,
            err_msg=f"grad mismatch for {name}")


def test_bf16_path():
    x, wt, s, b = _mk(2, 4, 4, 16, 16, 1)
    xb, wb = x.astype(jnp.bfloat16), wt.astype(jnp.bfloat16)
    y, st = fused_conv_bn_act(xb, wb, s, b, True, True, 1, True)
    assert y.dtype == jnp.bfloat16
    assert st.dtype == jnp.float32
    yr, _ = _conv_reference(xb, wb, s, b, True, True, 1)
    np.testing.assert_allclose(np.asarray(y, np.float32),
                               np.asarray(yr, np.float32),
                               rtol=0.05, atol=0.05)


class TestXlaGramImpl:
    """conv_bn_stats_xla — the XLA-native sibling: same (y, stats)
    contract, Gram-matrix statistics for expanding 1×1 convs
    (Σy = colsum(e)@W, Σy² = diag(WᵀGW) with G=eᵀe — exact algebra,
    differentiable by plain autodiff)."""

    @pytest.mark.parametrize("case", [
        dict(cin=8, cout=32, kernel=1, stride=1),    # expand → Gram
        dict(cin=8, cout=32, kernel=1, stride=2),
        dict(cin=32, cout=8, kernel=1, stride=1),    # reduce → direct
        dict(cin=8, cout=16, kernel=3, stride=1),
    ])
    def test_matches_reference(self, case):
        from deeplearning4j_tpu.ops.fused_conv import conv_bn_stats_xla
        x, wt, s, b = _mk(3, 6, 6, case["cin"], case["cout"],
                          case["kernel"])
        y, st = conv_bn_stats_xla(x, wt, s, b, True, True,
                                  case["stride"])
        yr, str_ = _conv_reference(x, wt, s, b, True, True,
                                   case["stride"])
        np.testing.assert_allclose(np.asarray(y), np.asarray(yr),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(st), np.asarray(str_),
                                   rtol=1e-3, atol=1e-2)

    def test_grads_match_reference(self):
        from deeplearning4j_tpu.ops.fused_conv import conv_bn_stats_xla
        x, wt, s, b = _mk(3, 4, 4, 8, 24, 1)     # expand → Gram path

        def loss(f):
            def inner(x, wt, s, b):
                y, st = f(x, wt, s, b, True, True, 1)
                inv, shift, mean, var = stats_to_scale_shift(
                    st, y.size // y.shape[-1], jnp.ones(y.shape[-1]),
                    jnp.zeros(y.shape[-1]), 1e-5)
                z = y.astype(jnp.float32) * inv + shift
                return jnp.sum(jnp.tanh(z)) + 0.1 * jnp.sum(var)
            return inner

        gf = jax.grad(loss(conv_bn_stats_xla),
                      argnums=(0, 1, 2, 3))(x, wt, s, b)
        gr = jax.grad(loss(_conv_reference),
                      argnums=(0, 1, 2, 3))(x, wt, s, b)
        for a, r, name in zip(gf, gr, "x w scale shift".split()):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(r), rtol=2e-3, atol=2e-3,
                err_msg=f"grad mismatch for {name}")

    def test_fused_block_xla_impl_matches_pallas(self):
        from deeplearning4j_tpu.nn.layers.fused import (
            FusedBottleneckBlock)
        from deeplearning4j_tpu.nn.layers.base import LayerContext
        from deeplearning4j_tpu.nn.inputs import InputType
        it = InputType.convolutional(8, 8, 16)
        import jax as _jax
        key = _jax.random.PRNGKey(0)
        bp = FusedBottleneckBlock(filters=8, stride=2, downsample=True,
                                  impl="pallas")
        bx = dataclasses.replace(bp, impl="xla")
        params = bp.initialize(key, it)
        state = bp.init_state(it)
        x = jnp.asarray(RNG.normal(0, 1, (4, 8, 8, 16))
                        .astype(np.float32))
        ctx = LayerContext(train=True)
        yp, sp = bp.apply(params, state, x, ctx)
        yx, sx = bx.apply(params, state, x, ctx)
        np.testing.assert_allclose(np.asarray(yp), np.asarray(yx),
                                   rtol=2e-3, atol=2e-3)
        for k in sp:
            np.testing.assert_allclose(np.asarray(sp[k]),
                                       np.asarray(sx[k]),
                                       rtol=2e-3, atol=2e-3,
                                       err_msg=k)
