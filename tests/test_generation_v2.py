"""Generative serving v2 tests (ISSUE 16).

The v1 invariant — continuous batching is bitwise-invisible — must
survive each v2 serving mode: chunked prefill (multi-token jitted
scans), speculative decode (n-gram draft + batched verify under
counter-based sampling keys), and resumable sessions (carry tiers:
device LRU -> host LRU -> shared ArtifactStore checkpoint, resumed
across engines). Plus the scheduler edges the modes open up:
mid-prefill cancel/deadline retirement, the pruned resize-pair warmup
sweep, and int8 carry quantization.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.generation import (
    CarrySnapshot,
    GenerationEngine,
    NGramDraft,
    SessionStore,
    counter_keys,
    extract_decode_spec,
    reference_decode,
)
from deeplearning4j_tpu.generation.engine import _reachable_resize_pairs
from deeplearning4j_tpu.observe.registry import MetricsRegistry
from deeplearning4j_tpu.parallel.aot_cache import ArtifactStore

SMALL_VOCAB = 31


def _small_model():
    from deeplearning4j_tpu.zoo.models import TextGenerationLSTM
    m = TextGenerationLSTM()
    m.lstm_units = 32
    m.vocab_size = SMALL_VOCAB
    m.timesteps = 8
    return m.init()


@pytest.fixture(scope="module")
def model():
    return _small_model()


@pytest.fixture(scope="module")
def spec(model):
    return extract_decode_spec(model)


@pytest.fixture(scope="module")
def plain_engine(model):
    eng = GenerationEngine(model, max_slots=4,
                           registry=MetricsRegistry(),
                           session_id="v2-plain")
    yield eng
    eng.shutdown()


@pytest.fixture(scope="module")
def chunked_engine(model):
    eng = GenerationEngine(model, max_slots=4, prefill_chunk=8,
                           registry=MetricsRegistry(),
                           session_id="v2-chunked")
    yield eng
    eng.shutdown()


@pytest.fixture(scope="module")
def spec_engine(model):
    eng = GenerationEngine(model, max_slots=4, speculative=3,
                           registry=MetricsRegistry(),
                           session_id="v2-spec")
    yield eng
    eng.shutdown()


@pytest.fixture(scope="module")
def counter_engine(model):
    eng = GenerationEngine(model, max_slots=2, sampling="counter",
                           registry=MetricsRegistry(),
                           session_id="v2-counter")
    yield eng
    eng.shutdown()


# ---- chunked prefill ---------------------------------------------------


def test_chunked_staggered_greedy_parity(chunked_engine, model):
    """Long prompts through the chunked scans, short ones through tick
    prefill, joining staggered — every output bitwise-equal to the
    sequential reference."""
    import random
    rng = random.Random(41)
    cfgs = [([rng.randrange(SMALL_VOCAB)
              for _ in range(rng.randrange(2, 40))],
             rng.randrange(8, 24)) for _ in range(8)]
    refs = [reference_decode(model, p, m) for p, m in cfgs]
    streams = []
    for i, (p, m) in enumerate(cfgs):
        streams.append(chunked_engine.submit(p, max_new_tokens=m,
                                             greedy=True))
    for i, (s, ref) in enumerate(zip(streams, refs)):
        assert s.result(timeout=60)["ids"] == ref, f"sequence {i}"
    st = chunked_engine.stats()
    assert st["prefill"]["chunks"] >= 1
    assert st["prefill"]["chunk_tokens"] >= 1
    chunked_engine.assert_warm()


def test_chunked_sampled_matches_tick_prefill(chunked_engine,
                                              plain_engine):
    """Prefill mode is a dispatch-shape choice: the PRNG chain advances
    one split per consumed token either way, so a seeded sampled run is
    bitwise-identical across prefill modes."""
    prompt = list(range(1, 21))     # long enough to take chunked path
    kw = dict(greedy=False, temperature=0.8, top_k=10, seed=11,
              max_new_tokens=16)
    a = chunked_engine.submit(prompt, **kw).result(timeout=60)["ids"]
    b = plain_engine.submit(prompt, **kw).result(timeout=60)["ids"]
    assert a == b


def test_chunked_ttft_ring_split(chunked_engine):
    st = chunked_engine.stats()
    assert set(st["latency_ms"]["ttft_by_mode"]) == {"chunked", "tick"}


# ---- speculative decode ------------------------------------------------


def test_speculative_greedy_parity_staggered(spec_engine, model):
    import random
    rng = random.Random(43)
    cfgs = [([rng.randrange(SMALL_VOCAB)
              for _ in range(rng.randrange(2, 8))],
             rng.randrange(16, 40)) for _ in range(8)]
    refs = [reference_decode(model, p, m) for p, m in cfgs]
    streams = [spec_engine.submit(p, max_new_tokens=m, greedy=True)
               for p, m in cfgs]
    for i, (s, ref) in enumerate(zip(streams, refs)):
        assert s.result(timeout=60)["ids"] == ref, f"sequence {i}"
    st = spec_engine.stats()["speculative"]
    assert st["proposed"] > 0
    spec_engine.assert_warm()


def test_speculative_sampled_matches_plain_counter(spec_engine,
                                                   counter_engine):
    """Acceptance sampling under counter-based keys is exact: the
    speculative stream equals the non-speculative counter-mode stream
    bitwise, token for token."""
    kw = dict(greedy=False, temperature=0.9, top_k=12, seed=5,
              max_new_tokens=24)
    prompt = [2, 7, 2, 7, 2, 7]
    a = spec_engine.submit(prompt, **kw).result(timeout=60)["ids"]
    b = counter_engine.submit(prompt, **kw).result(timeout=60)["ids"]
    assert a == b
    # same-seed replay on the speculative engine is exact too (keys are
    # (seed, position) counters, independent of acceptance history)
    c = spec_engine.submit(prompt, **kw).result(timeout=60)["ids"]
    assert a == c


def test_counter_keys_deterministic():
    seeds = np.array([7, 8], np.uint32)
    pos = np.array([3, 3], np.uint64)
    a = counter_keys(seeds, pos, 4)
    b = counter_keys(seeds, pos, 4)
    assert a.shape == (2, 4, 2) and a.dtype == np.uint32
    assert np.array_equal(a, b)
    assert not np.array_equal(a[0], a[1])        # seed separates
    c = counter_keys(seeds, pos + 1, 4)
    assert not np.array_equal(a, c)              # position separates
    # consecutive draft positions of one dispatch tile the same keys a
    # later plain tick would use — that is the bitwise-equality trick
    d = counter_keys(seeds, pos + 1, 3)
    assert np.array_equal(a[:, 1:, :], d[:, :3, :])


def test_ngram_draft_learns_a_loop():
    d = NGramDraft()
    d.observe_many([1, 2, 3] * 6)
    assert d.propose(3) == [1, 2, 3]
    d2 = NGramDraft()
    assert d2.propose(4) == []                   # no history, no guess


# ---- resumable sessions ------------------------------------------------


def test_session_requires_store(plain_engine):
    with pytest.raises(ValueError):
        plain_engine.submit([1, 2], session="nope")


def test_session_multi_turn_device_tier(model, spec):
    store = SessionStore(spec, registry=MetricsRegistry(),
                         session_id="v2-turns")
    eng = GenerationEngine(model, max_slots=2, session_store=store,
                           registry=MetricsRegistry(),
                           session_id="v2-turns")
    try:
        prompt = [3, 1, 4, 1, 5]
        full = reference_decode(model, prompt, 30)
        got = eng.submit(prompt, max_new_tokens=10,
                         session="t").result(timeout=60)
        assert got["ids"] == full[:10]
        assert got["session"] == "t"
        for turn in (1, 2):
            got = eng.submit([], max_new_tokens=10,
                             session="t").result(timeout=60)
            assert got["ids"] == full[10 * turn:10 * (turn + 1)]
        assert store.stats()["hits"]["device"] >= 2
        eng.assert_warm()
    finally:
        eng.shutdown()


def test_session_cross_engine_resume_zero_compiles(model, spec,
                                                   tmp_path):
    """Node A decodes turn 1 and drains; node B (sharing only the
    ArtifactStore directory) continues turn 2 bitwise from the store
    checkpoint without a single live compile."""
    shared = ArtifactStore(str(tmp_path))
    prompt = [9, 8, 7, 6]
    full = reference_decode(model, prompt, 24)
    eng_a = GenerationEngine(
        model, max_slots=2, registry=MetricsRegistry(),
        session_id="v2-node-a",
        session_store=SessionStore(spec, store=shared,
                                   registry=MetricsRegistry(),
                                   session_id="v2-node-a"))
    try:
        turn1 = eng_a.submit(prompt, max_new_tokens=12,
                             session="xnode").result(timeout=60)
        assert turn1["ids"] == full[:12]
    finally:
        eng_a.shutdown()
    store_b = SessionStore(spec, store=shared,
                           registry=MetricsRegistry(),
                           session_id="v2-node-b")
    eng_b = GenerationEngine(model, max_slots=2, session_store=store_b,
                             registry=MetricsRegistry(),
                             session_id="v2-node-b")
    try:
        turn2 = eng_b.submit([], max_new_tokens=12,
                             session="xnode").result(timeout=60)
        assert turn2["ids"] == full[12:]
        assert store_b.stats()["hits"]["store"] == 1
        eng_b.assert_warm()
    finally:
        eng_b.shutdown()


def test_session_lru_tiers(spec):
    store = SessionStore(spec, device_capacity=2, host_capacity=2,
                         registry=MetricsRegistry(),
                         session_id="v2-lru")

    def snap(seed):
        r = np.random.RandomState(seed)
        return CarrySnapshot(
            [r.randn(hd).astype(np.float32)
             for hd in spec.hidden_sizes],
            [r.randn(hd).astype(np.float32)
             for hd in spec.hidden_sizes],
            np.array([seed, seed], np.uint32), [seed], seed, [seed])

    for i in range(3):
        store.save(f"s{i}", snap(i))
    assert store.resident("s0") == "host"        # LRU'd off the device
    assert store.resident("s2") == "device"
    got = store.load("s0")                       # host hit, repinned
    assert got.pending == [0]
    np.testing.assert_array_equal(got.h[0], snap(0).h[0])
    st = store.stats()
    assert st["hits"]["host"] == 1
    for i in range(3, 7):                        # overflow both tiers
        store.save(f"s{i}", snap(i))
    st = store.stats()
    assert st["evictions"] >= 1
    assert store.load("missing") is None
    assert st["misses"] >= 0


def test_session_store_quarantine(spec, tmp_path):
    shared = ArtifactStore(str(tmp_path))
    a = SessionStore(spec, store=shared, registry=MetricsRegistry(),
                     session_id="v2-qa")
    r = np.random.RandomState(0)
    a.save("tok", CarrySnapshot(
        [r.randn(hd).astype(np.float32) for hd in spec.hidden_sizes],
        [r.randn(hd).astype(np.float32) for hd in spec.hidden_sizes],
        np.array([1, 2], np.uint32), [3], 4, [3]))
    blobs = list(tmp_path.glob("objects/**/*.npz"))
    assert blobs
    raw = bytearray(blobs[0].read_bytes())
    raw[len(raw) // 2] ^= 0xFF
    blobs[0].write_bytes(bytes(raw))
    b = SessionStore(spec, store=shared, registry=MetricsRegistry(),
                     session_id="v2-qb")
    assert b.load("tok") is None                 # checksum mismatch
    assert b.stats()["quarantined"] == 1
    assert list(tmp_path.glob("objects/**/*.quarantine"))


def test_session_int8_carry_roundtrip(spec):
    store = SessionStore(spec, carry_dtype="int8",
                         registry=MetricsRegistry(),
                         session_id="v2-int8")
    r = np.random.RandomState(7)
    h = [r.uniform(-1, 1, hd).astype(np.float32)
         for hd in spec.hidden_sizes]
    c = [r.uniform(-3, 3, hd).astype(np.float32)
         for hd in spec.hidden_sizes]
    store.save("q", CarrySnapshot(h, c, np.array([1, 2], np.uint32),
                                  [0], 1, [0]))
    got = store.load("q")
    for x, y in zip(h + c, got.h + got.c):
        assert y.dtype == np.float32
        scale = float(np.max(np.abs(x))) / 127.0
        assert float(np.max(np.abs(x - y))) <= scale + 1e-6
    np.testing.assert_array_equal(got.rng,
                                  np.array([1, 2], np.uint32))


def test_fleet_session_affinity(model, spec):
    """Without an explicit model=, the router sends a session-tagged
    request to the pool already holding the carry."""
    from deeplearning4j_tpu.parallel.fleet import FleetRouter
    fleet = FleetRouter(session_id="v2-aff")
    engines = []
    try:
        for name in ("a", "b"):
            reg = MetricsRegistry()
            eng = GenerationEngine(
                model, max_slots=2, registry=reg,
                session_id=f"v2-aff-{name}",
                session_store=SessionStore(spec, registry=reg,
                                           session_id=f"v2-aff-{name}"))
            engines.append(eng)
            fleet.add_generation_pool(name, eng)
        prompt = [1, 2, 3, 4]
        full = reference_decode(model, prompt, 20)
        r1 = fleet.generate(prompt, model="a", max_new_tokens=10,
                            session="s").result(timeout=60)
        assert r1["ids"] == full[:10]
        r2 = fleet.generate([], max_new_tokens=10,
                            session="s").result(timeout=60)
        assert r2["ids"] == full[10:]
        assert engines[0].stats()["session_store"]["hits"]["device"] >= 1
        assert engines[1].stats()["session_store"]["hits"]["device"] == 0
    finally:
        fleet.shutdown()


# ---- mid-prefill retirement --------------------------------------------


def test_mid_prefill_cancel(chunked_engine, model):
    prompt = [i % SMALL_VOCAB for i in range(4096)]
    stream = chunked_engine.submit(prompt, max_new_tokens=8,
                                   greedy=True)
    stream.cancel()
    res = stream.result(timeout=60)
    assert res["reason"] == "cancelled"
    # the slot is free and the engine state sane: a normal request
    # still decodes bitwise with zero live compiles
    ref = reference_decode(model, [1, 2, 3], 10)
    assert chunked_engine.submit(
        [1, 2, 3], max_new_tokens=10,
        greedy=True).result(timeout=60)["ids"] == ref
    chunked_engine.assert_warm()


def test_mid_prefill_deadline(chunked_engine, model):
    from deeplearning4j_tpu.parallel.deadline import Deadline
    prompt = [i % SMALL_VOCAB for i in range(4096)]
    stream = chunked_engine.submit(prompt, max_new_tokens=8,
                                   greedy=True,
                                   deadline=Deadline.after_ms(30.0))
    res = stream.result(timeout=60)
    assert res["reason"] == "deadline"
    ref = reference_decode(model, [4, 5], 10)
    assert chunked_engine.submit(
        [4, 5], max_new_tokens=10,
        greedy=True).result(timeout=60)["ids"] == ref
    chunked_engine.assert_warm()


# ---- warmup sweep pruning ----------------------------------------------


def test_reachable_resize_pairs_pruned():
    ladder = [1, 2, 4, 8]
    pairs = set(_reachable_resize_pairs(ladder))
    grows = {(s, d) for i, s in enumerate(ladder)
             for d in ladder[i + 1:]}
    shrinks = {(2, 1), (4, 2), (8, 4)}
    assert pairs == grows | shrinks
    # the quadratic sweep had 12 ordered pairs; multi-rung shrinks are
    # unreachable (the scheduler steps down one rung at a time)
    assert len(pairs) == 9


def test_burst_grow_then_shrink_zero_live_compiles(model):
    eng = GenerationEngine(model, max_slots=8,
                           registry=MetricsRegistry(),
                           session_id="v2-burst")
    try:
        streams = [eng.submit([i % SMALL_VOCAB], max_new_tokens=10)
                   for i in range(8)]        # 1 -> 8 in one admission
        for s in streams:
            s.result(timeout=60)
        # drain, then trickle so the scheduler walks the bucket back
        # down the ladder one rung at a time
        for _ in range(3):
            eng.submit([3], max_new_tokens=4).result(timeout=60)
        eng.assert_warm()
    finally:
        eng.shutdown()


# ---- stats surface -----------------------------------------------------


def test_v2_stats_and_metrics_series(model, spec):
    reg = MetricsRegistry()
    store = SessionStore(spec, registry=reg, session_id="v2-stats")
    eng = GenerationEngine(model, max_slots=2, prefill_chunk=8,
                           speculative=2, session_store=store,
                           registry=reg, session_id="v2-stats")
    try:
        st = eng.stats()
        assert st["sampling"] == "counter"
        assert st["prefill"]["chunk"] == 8
        assert st["speculative"]["k"] == 2
        assert st["session_store"]["capacity"]["device"] >= 1
        text = reg.render()
        for name in ("dl4j_gen_prefill_chunks_total",
                     "dl4j_gen_prefill_tokens_total",
                     "dl4j_gen_prefill_ttft_ms",
                     "dl4j_gen_spec_proposed_total",
                     "dl4j_gen_spec_accepted_total",
                     "dl4j_gen_session_hits_total",
                     "dl4j_gen_session_misses_total",
                     "dl4j_gen_session_evictions_total",
                     "dl4j_gen_session_resident"):
            assert name in text, name
    finally:
        eng.shutdown()
