"""Listener SPI tests: dispatch ordering, PerformanceListener window
accounting, StatsListener update_frequency accumulation + first-record
timing."""

from types import SimpleNamespace

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.optimize.listeners import (
    PerformanceListener,
    TrainingListener,
)


def _tiny_model(seed=1):
    from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.inputs import InputType
    from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
    from deeplearning4j_tpu.nn.layers.output import OutputLayer
    from deeplearning4j_tpu.models.multi_layer_network import (
        MultiLayerNetwork)
    from deeplearning4j_tpu.ops.losses import LossFunction
    from deeplearning4j_tpu.optimize.updaters import Adam
    conf = (NeuralNetConfiguration.Builder().seed(seed).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=8))
            .layer(OutputLayer(n_out=3, loss=LossFunction.MCXENT))
            .set_input_type(InputType.feed_forward(5)).build())
    return MultiLayerNetwork(conf).init()


def _batches(n, batch=16, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        x = rng.normal(size=(batch, 5)).astype(np.float32)
        y = np.zeros((batch, 3), np.float32)
        y[np.arange(batch), rng.integers(0, 3, batch)] = 1.0
        out.append(DataSet(x, y))
    return out


class _ListIter:
    def __init__(self, batches):
        self.batches = batches

    def __iter__(self):
        return iter(self.batches)

    def reset(self):
        pass


class _Recorder(TrainingListener):
    def __init__(self, name, events):
        self.name = name
        self.events = events

    def on_epoch_start(self, model, epoch):
        self.events.append((self.name, "epoch_start", epoch))

    def iteration_done(self, model, iteration, epoch, loss, etl_ms,
                       batch_size):
        self.events.append((self.name, "iter", iteration))

    def on_epoch_end(self, model, epoch):
        self.events.append((self.name, "epoch_end", epoch))


class TestDispatchOrdering:
    def test_listeners_fire_in_registration_order(self):
        """Per event, every listener fires in set_listeners order before
        the loop advances — the reference's listener-list contract."""
        events = []
        m = _tiny_model()
        m.set_listeners(_Recorder("A", events), _Recorder("B", events))
        m.fit(_ListIter(_batches(2)), epochs=1)
        assert events == [
            ("A", "epoch_start", 0), ("B", "epoch_start", 0),
            ("A", "iter", 1), ("B", "iter", 1),
            ("A", "iter", 2), ("B", "iter", 2),
            ("A", "epoch_end", 0), ("B", "epoch_end", 0),
        ]

    def test_add_listeners_appends(self):
        events = []
        m = _tiny_model()
        m.set_listeners(_Recorder("A", events))
        m.add_listeners(_Recorder("B", events), _Recorder("C", events))
        m.fit(_batches(1)[0])
        iters = [e for e in events if e[1] == "iter"]
        assert [n for n, _, _ in iters] == ["A", "B", "C"]


class TestPerformanceListener:
    def test_first_batch_samples_counted_and_etl_is_window_mean(
            self, monkeypatch):
        """The two reported bugs: (1) the first batch's samples were
        dropped because the clock was only seeded inside the first
        iteration_done; (2) etl_ms reported the LAST iteration's value
        instead of the window mean."""
        clock = iter([100.0, 101.0, 102.0, 103.0, 104.0])
        monkeypatch.setattr("time.perf_counter", lambda: next(clock))
        lst = PerformanceListener(frequency=2)
        model = SimpleNamespace()
        lst.on_epoch_start(model, 0)                    # clock = 100
        for it, etl in zip((1, 2, 3, 4), (10.0, 20.0, 30.0, 40.0)):
            lst.iteration_done(model, it, 0, 0.5, etl, 8)
        assert len(lst.history) == 2
        first, second = lst.history
        # window 1 spans epoch start (t=100) .. iter 2 (t=102): BOTH
        # batches' 16 samples over 2s
        assert first["iteration"] == 2
        assert first["samples_per_sec"] == 8.0
        assert first["batches_per_sec"] == 1.0
        assert first["etl_ms"] == 15.0                  # mean(10, 20)
        assert second["samples_per_sec"] == 8.0
        assert second["etl_ms"] == 35.0                 # mean(30, 40)

    def test_direct_calls_without_epoch_seed_still_report(self):
        # no on_epoch_start (direct driving): the first call only anchors
        # the window, later ones report
        lst = PerformanceListener(frequency=1)
        model = SimpleNamespace()
        for it in (1, 2, 3):
            lst.iteration_done(model, it, 0, 0.5, 1.0, 4)
        assert len(lst.history) == 2
        assert all(r["samples_per_sec"] > 0 for r in lst.history)

    def test_fit_integration(self):
        lst = PerformanceListener(frequency=1)
        m = _tiny_model()
        m.set_listeners(lst)
        m.fit(_ListIter(_batches(3)), epochs=1)
        assert len(lst.history) == 3
        assert all(r["samples_per_sec"] > 0 for r in lst.history)
        assert all(np.isfinite(r["etl_ms"]) for r in lst.history)


class TestStatsListenerAccumulation:
    def test_update_frequency_accumulates_and_first_record_timed(self):
        """update_frequency=2 -> records only at even iterations, each
        covering BOTH batches since the last report; the FIRST record
        carries real throughput (seeded from the start timestamp) instead
        of None."""
        from deeplearning4j_tpu.ui import (
            InMemoryStatsStorage, StatsListener)
        storage = InMemoryStatsStorage()
        lst = StatsListener(storage, update_frequency=2,
                            collect_histograms=False)
        m = _tiny_model()
        m.set_listeners(lst)
        m.fit(_ListIter(_batches(4)), epochs=1)
        ups = storage.get_all_updates(lst.session_id)
        assert [u["iteration"] for u in ups] == [2, 4]
        for u in ups:
            # the satellite fix: no None/garbage timing on record #1
            assert u["samples_per_sec"] is not None
            assert u["samples_per_sec"] > 0
            assert u["minibatches_per_sec"] is not None
            assert np.isfinite(u["score"])

    def test_telemetry_backed_score_and_device_metrics(self):
        from deeplearning4j_tpu.observe import (
            MetricsRegistry, TelemetryCollector)
        from deeplearning4j_tpu.ui import (
            InMemoryStatsStorage, StatsListener)
        storage = InMemoryStatsStorage()
        lst = StatsListener(storage, update_frequency=1,
                            collect_histograms=False)
        m = _tiny_model()
        tel = TelemetryCollector(flush_interval=2,
                                 registry=MetricsRegistry())
        m.set_telemetry(tel)
        m.set_listeners(lst)
        m.fit(_ListIter(_batches(4)), epochs=1)
        ups = storage.get_all_updates(lst.session_id)
        assert len(ups) == 4
        # from iteration 2 on, the score is the flushed device value and
        # the device-metric row rides along
        assert ups[-1]["score"] == tel.history[-1 - 1]["loss"] or \
            np.isfinite(ups[-1]["score"])
        flushed = [u for u in ups if "device_metrics" in u]
        assert flushed, "no record carried device metrics"
        dm = flushed[-1]["device_metrics"]
        assert {"loss", "grad_norm", "nonfinite_count"} <= set(dm)
