"""Generative serving tests (ISSUE 12).

The correctness core: continuous batching must be *invisible* — a
sequence decoded in a shared slot batch with co-residents joining and
retiring around it is bitwise-identical to the same sequence decoded
alone through ``rnn_time_step`` (greedy), and a seeded sampling run
reproduces exactly. Plus the serving surface: stop/length retirement,
admission shedding through GenerationPool, SSE streaming + drain over
the UI server, and the decode-level int8 head gate.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from deeplearning4j_tpu.generation import (
    GenerationEngine,
    Vocab,
    extract_decode_spec,
    head_bytes_per_token,
    reference_decode,
)
from deeplearning4j_tpu.generation import decode as D
from deeplearning4j_tpu.observe.registry import MetricsRegistry
from deeplearning4j_tpu.parallel.fleet import FleetRouter, ShedError

SMALL_VOCAB = 31


def _small_model():
    from deeplearning4j_tpu.zoo.models import TextGenerationLSTM
    m = TextGenerationLSTM()
    m.lstm_units = 32
    m.vocab_size = SMALL_VOCAB
    m.timesteps = 8
    return m.init()


@pytest.fixture(scope="module")
def model():
    return _small_model()


@pytest.fixture(scope="module")
def engine(model):
    eng = GenerationEngine(model, max_slots=4,
                           registry=MetricsRegistry(),
                           session_id="gen-test")
    yield eng
    eng.shutdown()


# ---- decode parity ----------------------------------------------------


def test_greedy_parity_static(engine, model):
    prompts = [[1, 2, 3], [7, 11, 13, 17], [30]]
    refs = [reference_decode(model, p, 20) for p in prompts]
    streams = [engine.submit(p, max_new_tokens=20, greedy=True)
               for p in prompts]
    for s, ref in zip(streams, refs):
        assert s.result(timeout=60)["ids"] == ref


def test_greedy_parity_staggered_join_leave(engine, model):
    import random
    rng = random.Random(99)
    cfgs = [([rng.randrange(SMALL_VOCAB)
              for _ in range(rng.randrange(2, 7))],
             rng.randrange(10, 30)) for _ in range(8)]
    refs = [reference_decode(model, p, m) for p, m in cfgs]
    streams = []
    for i, (p, m) in enumerate(cfgs):
        streams.append(engine.submit(p, max_new_tokens=m, greedy=True))
        if i >= 4:          # first burst fills the 4 slots; the rest
            time.sleep(0.002)       # join as retirements free slots
    for i, (s, ref) in enumerate(zip(streams, refs)):
        assert s.result(timeout=60)["ids"] == ref, f"sequence {i}"
    assert engine.stats()["slots"]["max_active"] >= 2


def test_bucket_jump_no_live_compile(model):
    """A demand burst jumps the bucket several ladder rungs at once
    (1 -> 8); the warmup sweep must have covered that resize."""
    eng = GenerationEngine(model, max_slots=8,
                           registry=MetricsRegistry(),
                           session_id="gen-jump")
    try:
        streams = [eng.submit([i % SMALL_VOCAB], max_new_tokens=12)
                   for i in range(8)]
        for s in streams:
            s.result(timeout=60)
        eng.assert_warm()
    finally:
        eng.shutdown()


def test_seeded_sampling_reproducible(engine):
    kw = dict(greedy=False, temperature=0.8, top_k=10,
              max_new_tokens=24)
    a = engine.generate([3, 1, 4], seed=7, **kw)
    b = engine.generate([3, 1, 4], seed=7, **kw)
    c = engine.generate([3, 1, 4], seed=8, **kw)
    assert a["ids"] == b["ids"]
    assert a["ids"] != c["ids"]


# ---- retirement -------------------------------------------------------


def test_stop_token_retirement(engine, model):
    prompt = [5, 9]
    free = reference_decode(model, prompt, 30)
    stop = free[3]      # a token greedy decode actually produces
    ref = reference_decode(model, prompt, 30, stop_id=stop)
    res = engine.generate(prompt, max_new_tokens=30, stop=int(stop))
    assert res["reason"] == "stop"
    assert res["ids"] == ref
    assert res["ids"][-1] == stop


def test_max_length_retirement(engine):
    res = engine.generate([2], max_new_tokens=9)
    assert res["reason"] == "length"
    assert len(res["ids"]) == 9
    assert res["ttft_ms"] is not None and res["ttft_ms"] >= 0.0


def test_invalid_prompt_rejected(engine):
    with pytest.raises(ValueError):
        engine.submit([SMALL_VOCAB + 5])


def test_engine_warm_after_traffic(engine):
    engine.assert_warm()
    st = engine.stats()
    assert st["recompiles_after_warmup"] == 0
    assert st["tokens"]["generated"] > 0


# ---- admission: GenerationPool sheds like the predict pools -----------


def test_generation_pool_shed(model):
    eng = GenerationEngine(model, max_slots=1,
                           registry=MetricsRegistry(),
                           session_id="gen-shed")
    fleet = FleetRouter(max_pending=1, registry=MetricsRegistry(),
                        session_id="gen-shed")
    fleet.add_generation_pool("gen", eng)
    try:
        first = fleet.generate([1], max_new_tokens=200)
        with pytest.raises(ShedError) as exc:
            fleet.generate([2], max_new_tokens=5)
        assert exc.value.reason == "queue"
        first.cancel()
        first.result(timeout=60)
        # the done callback releases the admission slot
        deadline = time.time() + 10
        while fleet.generation_pool("gen").pending and \
                time.time() < deadline:
            time.sleep(0.01)
        assert fleet.generate([2], max_new_tokens=5).result(
            timeout=60)["reason"] == "length"
        st = fleet.stats()["generation"]["gen"]
        assert st["pending"] == 0
        assert st["engine"]["slots"]["max"] == 1
    finally:
        fleet.shutdown()


# ---- HTTP surface: SSE streaming, stats, drain ------------------------


def _read_sse(url, payload, timeout=60.0):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    events = []
    with urllib.request.urlopen(req, timeout=timeout) as r:
        ctype = r.headers.get("Content-Type", "")
        for raw in r:
            line = raw.decode().strip()
            if line.startswith("data:"):
                events.append(json.loads(line[5:].strip()))
    return ctype, events


def test_sse_stream_over_http(model):
    from deeplearning4j_tpu.ui.generation_module import GenerationModule
    from deeplearning4j_tpu.ui.server import UIServer
    from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage
    eng = GenerationEngine(model, max_slots=2,
                           registry=MetricsRegistry(),
                           session_id="gen-http")
    fleet = FleetRouter(registry=MetricsRegistry(),
                        session_id="gen-http")
    fleet.add_generation_pool("gen", eng)
    server = UIServer(port=0)
    server.attach(InMemoryStatsStorage())
    server.register_module(GenerationModule(router=fleet, model="gen"))
    server.start()
    try:
        prompt = [4, 8, 15]
        ref = reference_decode(model, prompt, 16)
        ctype, events = _read_sse(
            server.url + "/api/generate",
            {"prompt": prompt, "max_new_tokens": 16, "greedy": True})
        assert ctype.startswith("text/event-stream")
        toks = [e["token"] for e in events if "token" in e]
        assert toks == ref
        assert events[-1]["done"] and events[-1]["reason"] == "length"
        # non-streamed mode answers one JSON object
        req = urllib.request.Request(
            server.url + "/api/generate",
            data=json.dumps({"prompt": prompt, "max_new_tokens": 16,
                             "stream": False}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as r:
            res = json.loads(r.read())
        assert res["ids"] == ref
        with urllib.request.urlopen(
                server.url + "/api/generation/stats", timeout=60) as r:
            st = json.loads(r.read())
        assert st["engine"]["slots"]["max"] == 2
    finally:
        server.stop()
        fleet.shutdown()


from deeplearning4j_tpu.ui.modules import Route, UIModule  # noqa: E402


class _GatedStream(UIModule):
    """UI module whose generator blocks on an event — controls exactly
    when an in-flight stream finishes, for the drain test."""

    def __init__(self):
        self.gate = threading.Event()
        self.started = threading.Event()

    def get_routes(self):
        return [Route("POST", "/api/generate", self._gen)]

    def _gen(self, ctx, query, body):
        def events():
            yield {"token": 1}
            self.started.set()
            self.gate.wait(timeout=30)
            yield {"done": True}
        return events()


def test_drain_lets_inflight_streams_finish():
    from deeplearning4j_tpu.ui.server import UIServer
    from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage
    mod = _GatedStream()
    server = UIServer(port=0)
    server.attach(InMemoryStatsStorage())
    server.register_module(mod)
    server.start()
    try:
        got = {}

        def client():
            got["ctype"], got["events"] = _read_sse(
                server.url + "/api/generate", {"prompt": "x"})

        t = threading.Thread(target=client)
        t.start()
        assert mod.started.wait(timeout=30)
        assert server.active_requests == 1
        server.drain()      # long-lived stream keeps running...
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(urllib.request.Request(
                server.url + "/api/generate",
                data=b'{"prompt": "y"}',
                headers={"Content-Type": "application/json"}),
                timeout=30)
        assert exc.value.code == 503       # ...but new ingress is gated
        exc.value.read()
        mod.gate.set()
        t.join(timeout=30)
        assert [e for e in got["events"] if "done" in e]
        deadline = time.time() + 10
        while server.active_requests and time.time() < deadline:
            time.sleep(0.01)
        assert server.active_requests == 0
    finally:
        mod.gate.set()
        server.stop()


def test_generic_generator_payload_streams():
    """Any module route returning a generator rides the event-stream
    path — dicts JSON-encoded, strings passed through."""
    from deeplearning4j_tpu.ui.server import UIServer
    from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage

    class Mod(UIModule):
        def get_routes(self):
            return [Route("POST", "/api/things", self._go)]

        def _go(self, ctx, query, body):
            return iter([{"a": 1}, {"b": 2}])

    server = UIServer(port=0)
    server.attach(InMemoryStatsStorage())
    server.register_module(Mod())
    server.start()
    try:
        ctype, events = _read_sse(server.url + "/api/things", {})
        assert ctype.startswith("text/event-stream")
        assert events == [{"a": 1}, {"b": 2}]
    finally:
        server.stop()


# ---- int8 head gate ----------------------------------------------------


def test_int8_gate_mechanism(model):
    from deeplearning4j_tpu.evaluation.quant_gate import QuantGateError
    spec = extract_decode_spec(model)
    probe = list(range(10))
    x_scale, result = D.int8_head_gate(model, spec, probe,
                                       top1_budget=1.0)
    assert x_scale > 0.0
    assert result.passed
    assert 0.0 <= result.top1_agreement <= 1.0
    with pytest.raises(QuantGateError):
        # impossible budget: the gate must refuse, not clamp
        D.int8_head_gate(model, spec, probe, top1_budget=-0.1)


def test_int8_engine_decodes(model):
    eng = GenerationEngine(model, max_slots=2, precision="int8",
                           int8_budget=1.0,
                           registry=MetricsRegistry(),
                           session_id="gen-int8")
    try:
        res = eng.generate([1, 2], max_new_tokens=12)
        assert len(res["ids"]) == 12
        assert eng.stats()["head_agreement"] is not None
        eng.assert_warm()
    finally:
        eng.shutdown()


def test_head_bytes_per_token_ordering(model):
    spec = extract_decode_spec(model)
    h = spec.hidden_sizes[-1]
    f32 = head_bytes_per_token(spec, h, "f32")
    bf16 = head_bytes_per_token(spec, h, "bf16")
    int8 = head_bytes_per_token(spec, h, "int8")
    assert int8 < bf16 < f32


# ---- vocab -------------------------------------------------------------


def test_vocab_identity_and_committed():
    v = Vocab.identity(5)
    assert v.decode([0, 4]) == "��"
    assert v.encode("ab") == [0, 0]
    committed = Vocab.load()
    text = "the quick fox"
    assert committed.decode(committed.encode(text)) == text
