"""Attention layers + ring-attention sequence parallelism.

The correctness pattern follows SURVEY §4's "accelerated-vs-reference
equivalence" idea: the sequence-parallel ring implementation must equal
the single-chip attention bit-for-practical-purposes, on the virtual
8-device CPU mesh (conftest.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from deeplearning4j_tpu.nn.layers.attention import (
    LearnedPositionalEmbedding,
    SelfAttentionLayer,
    TransformerEncoderBlock,
    scaled_dot_product_attention,
)
from deeplearning4j_tpu.parallel.ring_attention import ring_self_attention


def _qkv(n=2, t=16, h=4, dh=8, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(n, t, h, dh))
                             .astype(np.float32))
    return mk(), mk(), mk()


def _mesh():
    return Mesh(np.array(jax.devices()[:8]), ("sp",))


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_single_chip(self, causal):
        q, k, v = _qkv()
        want = scaled_dot_product_attention(q, k, v, causal=causal)
        got = ring_self_attention(q, k, v, _mesh(), causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-6)

    def test_masked_matches_single_chip(self):
        q, k, v = _qkv(seed=1)
        mask = jnp.asarray((np.random.default_rng(2)
                            .random((2, 16)) > 0.3).astype(np.float32))
        want = scaled_dot_product_attention(q, k, v, mask=mask)
        got = ring_self_attention(q, k, v, _mesh(), mask=mask)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-6)

    def test_fully_masked_sample_has_finite_gradients(self):
        """Regression: a fully-padded sequence in the batch must not
        poison gradients with NaN (softmax-VJP over -inf rows)."""
        q, k, v = _qkv(n=2, t=8, seed=9)
        mask = jnp.asarray(np.stack([np.ones(8), np.zeros(8)])
                           .astype(np.float32))

        def loss_single(q, k, v):
            return jnp.sum(scaled_dot_product_attention(
                q, k, v, mask=mask) ** 2)

        g = jax.grad(loss_single)(q, k, v)
        assert np.isfinite(np.asarray(g)).all()

        mesh = _mesh()

        def loss_ring(q, k, v):
            return jnp.sum(ring_self_attention(
                q, k, v, mesh, mask=mask) ** 2)

        gr = jax.grad(loss_ring)(q, k, v)
        assert np.isfinite(np.asarray(gr)).all()
        np.testing.assert_allclose(np.asarray(gr), np.asarray(g),
                                   rtol=1e-4, atol=1e-5)

    def test_gradients_flow_through_ring(self):
        q, k, v = _qkv(t=8, seed=3)
        mesh = _mesh()

        def loss_ring(q, k, v):
            return jnp.sum(ring_self_attention(q, k, v, mesh) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(scaled_dot_product_attention(q, k, v) ** 2)

        g_ring = jax.grad(loss_ring)(q, k, v)
        g_ref = jax.grad(loss_ref)(q, k, v)
        np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_ref),
                                   rtol=1e-4, atol=1e-5)


class TestAttentionLayers:
    def test_self_attention_in_network(self):
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.models.multi_layer_network import (
            MultiLayerNetwork)
        from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.inputs import InputType
        from deeplearning4j_tpu.nn.layers.output import RnnOutputLayer
        from deeplearning4j_tpu.ops.losses import LossFunction
        from deeplearning4j_tpu.optimize.updaters import Adam

        conf = (NeuralNetConfiguration.Builder().seed(1).updater(Adam(1e-2))
                .list()
                .layer(LearnedPositionalEmbedding(max_len=32))
                .layer(TransformerEncoderBlock(n_out=16, n_heads=4))
                .layer(RnnOutputLayer(n_out=3,
                                      loss=LossFunction.MCXENT))
                .set_input_type(InputType.recurrent(16, 10)).build())
        m = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(0)
        x = rng.normal(size=(4, 10, 16)).astype(np.float32)
        y = np.zeros((4, 10, 3), np.float32)
        y[..., 0] = 1.0
        before = m.score(DataSet(x, y))
        for _ in range(10):
            m.fit(DataSet(x, y))
        assert m.score(DataSet(x, y)) < before
        out = m.output(x)
        assert out.shape == (4, 10, 3)

    def test_causal_mask_blocks_future(self):
        layer = SelfAttentionLayer(n_in=8, n_out=8, n_heads=2, causal=True)
        from deeplearning4j_tpu.nn.inputs import InputType
        from deeplearning4j_tpu.nn.layers.base import LayerContext
        params = layer.initialize(jax.random.PRNGKey(0),
                                  InputType.recurrent(8, 6))
        x = jnp.asarray(np.random.default_rng(1).normal(
            size=(1, 6, 8)).astype(np.float32))
        y1, _ = layer.apply(params, {}, x, LayerContext())
        # changing the future must not change step 0
        x2 = x.at[:, 3:].set(0.0)
        y2, _ = layer.apply(params, {}, x2, LayerContext())
        np.testing.assert_allclose(np.asarray(y1[:, :3]),
                                   np.asarray(y2[:, :3]), rtol=1e-5,
                                   atol=1e-6)

    def test_attention_gradient_check(self):
        """Finite-difference vs autodiff on the attention layer — the
        reference's gradient-check backbone (GradientCheckUtil.java:109)
        applied to the new layer family."""
        from deeplearning4j_tpu.gradientcheck.gradient_check_util import (
            check_gradients)
        from deeplearning4j_tpu.nn.inputs import InputType
        from deeplearning4j_tpu.nn.layers.base import LayerContext
        layer = SelfAttentionLayer(n_in=6, n_out=6, n_heads=2)
        params = layer.initialize(jax.random.PRNGKey(0),
                                  InputType.recurrent(6, 5))
        x = jnp.asarray(np.random.default_rng(0).normal(
            size=(2, 5, 6)))

        def loss(p):
            y, _ = layer.apply(p, {}, x, LayerContext())
            return jnp.sum(y ** 2)

        assert check_gradients(loss, params, max_rel_error=1e-5)

    def test_positional_embedding_shape(self):
        from deeplearning4j_tpu.nn.inputs import InputType
        from deeplearning4j_tpu.nn.layers.base import LayerContext
        pe = LearnedPositionalEmbedding(max_len=16)
        params = pe.initialize(jax.random.PRNGKey(0),
                               InputType.recurrent(4, 8))
        x = jnp.zeros((2, 8, 4))
        y, _ = pe.apply(params, {}, x, LayerContext())
        assert y.shape == (2, 8, 4)
        assert not np.allclose(np.asarray(y), 0.0)


class TestUlyssesAttention:
    """All-to-all sequence parallelism (the alternative SP strategy to
    the ring): same math as single-chip attention."""

    def _mesh4(self):
        return Mesh(np.array(jax.devices()[:4]), ("sp",))

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_single_chip(self, causal):
        from deeplearning4j_tpu.parallel.ring_attention import (
            ulysses_self_attention)
        q, k, v = _qkv()
        want = scaled_dot_product_attention(q, k, v, causal=causal)
        got = ulysses_self_attention(q, k, v, self._mesh4(), causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-6)

    def test_masked_matches_single_chip(self):
        from deeplearning4j_tpu.parallel.ring_attention import (
            ulysses_self_attention)
        q, k, v = _qkv(seed=3)
        mask = jnp.asarray((np.random.default_rng(4)
                            .random((2, 16)) > 0.3).astype(np.float32))
        want = scaled_dot_product_attention(q, k, v, mask=mask)
        got = ulysses_self_attention(q, k, v, self._mesh4(), mask=mask)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-6)

    def test_gradients_match_single_chip(self):
        from deeplearning4j_tpu.parallel.ring_attention import (
            ulysses_self_attention)
        q, k, v = _qkv(t=8, seed=5)
        mesh = self._mesh4()

        def loss_sp(q, k, v):
            return jnp.sum(
                ulysses_self_attention(q, k, v, mesh, causal=True) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(
                scaled_dot_product_attention(q, k, v, causal=True) ** 2)

        g_sp = jax.grad(loss_sp, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_sp, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-5)

    def test_heads_divisibility_enforced(self):
        from deeplearning4j_tpu.parallel.ring_attention import (
            ulysses_self_attention)
        q, k, v = _qkv(h=3)
        with pytest.raises(ValueError, match="divisible"):
            ulysses_self_attention(q, k, v, self._mesh4())
