"""Backward-compatibility regression tests.

The analog of the reference's RegressionTest050..080 suites (SURVEY §4):
checkpoint zips produced by a frozen version of the serialization format
are committed under ``tests/resources/regression`` together with recorded
outputs; every future format change must keep them loadable and
numerically identical. Regenerating fixtures to make these pass defeats
their purpose — fix the loader instead."""

import json
import os

import numpy as np
import pytest

from deeplearning4j_tpu.models.serialization import (
    restore_model,
    restore_multi_layer_network,
)

RES = os.path.join(os.path.dirname(__file__), "resources", "regression")


def _expected():
    with open(os.path.join(RES, "expected_outputs.json")) as f:
        return json.load(f)


@pytest.mark.parametrize("name", ["mlp_v1", "cnn_v1", "lstm_v1", "attn_v1"])
class TestRegressionFixtures:
    def test_restore_and_outputs_match(self, name):
        exp = _expected()[name]
        model = restore_multi_layer_network(
            os.path.join(RES, f"{name}.zip"), load_updater=True)
        x = np.asarray(exp["input"], np.float32)
        out = np.asarray(model.output(x))
        np.testing.assert_allclose(out, np.asarray(exp["output"]),
                                   rtol=1e-5, atol=1e-6)

    def test_restore_generic_guesser(self, name):
        model = restore_model(os.path.join(RES, f"{name}.zip"))
        assert model.num_params() > 0

    def test_training_resumes(self, name):
        """Restored models must be trainable (updater state loaded)."""
        import jax.numpy as jnp
        from deeplearning4j_tpu.datasets.dataset import DataSet

        exp = _expected()[name]
        model = restore_multi_layer_network(
            os.path.join(RES, f"{name}.zip"), load_updater=True)
        x = np.asarray(exp["input"], np.float32)
        out = np.asarray(model.output(x))
        # one-hot labels matching the model's output arity
        y = np.zeros_like(out)
        flat = y.reshape(-1, y.shape[-1])
        flat[np.arange(flat.shape[0]), 0] = 1.0
        model.fit(DataSet(x, y))
        out2 = np.asarray(model.output(x))
        assert not np.allclose(out, out2)  # a step actually happened


class TestQkvMigrationExactResume:
    def test_attn_fixture_resumes_bit_identically(self):
        """attn_v1.zip is a pre-0.2.0 (which-major QKV) checkpoint with
        TRAINED Adam moments; after migration, one more training step
        must reproduce the original never-serialized model's output —
        proving params AND optimizer moments were both re-packed."""
        from deeplearning4j_tpu.datasets.dataset import DataSet

        exp = _expected()["attn_v1"]
        model = restore_multi_layer_network(
            os.path.join(RES, "attn_v1.zip"), load_updater=True)
        x = np.asarray(exp["input"], np.float32)
        y = np.asarray(exp["labels"], np.float32)
        model.fit(DataSet(x, y))
        out = np.asarray(model.output(x))
        np.testing.assert_allclose(
            out, np.asarray(exp["output_after_step"]),
            rtol=1e-5, atol=1e-6)


class TestTbpttConfRoundtrip:
    def test_lstm_fixture_keeps_tbptt_conf(self):
        model = restore_multi_layer_network(
            os.path.join(RES, "lstm_v1.zip"))
        assert model.conf.backprop_type == "tbptt"
        assert model.conf.tbptt_fwd_length == 6
