"""Binary stats wire codec (ui/codec.py — the reference SBE codecs'
role, .../stats/sbe/UpdateEncoder): round-trip, size vs JSON,
end-to-end through sqlite storage and the remote router → server path
(VERDICT r3 #8)."""

import json

import numpy as np
import pytest

from deeplearning4j_tpu.ui.codec import (
    decode_stats_record,
    encode_stats_record,
    is_stats_record,
)


def _record():
    rng = np.random.default_rng(0)
    return {
        "session_id": "sess1", "worker_id": "w0", "timestamp": 12.5,
        "iteration": 42, "score": 0.0314, "is_final": False,
        "note": None, "tags": ["a", "b"],
        "param_stats": {
            f"layer_{i}": {
                "mean": float(i), "std": 0.1 * i,
                "histogram": rng.normal(0, 1, 64).tolist(),
                "bins": np.linspace(-3, 3, 65).tolist(),
            } for i in range(6)
        },
    }


def test_round_trip_exact():
    rec = _record()
    data = encode_stats_record(rec)
    assert is_stats_record(data)
    back = decode_stats_record(data)
    assert back["session_id"] == rec["session_id"]
    assert back["iteration"] == 42 and back["is_final"] is False
    assert back["note"] is None and back["tags"] == ["a", "b"]
    for k, v in rec["param_stats"].items():
        np.testing.assert_allclose(back["param_stats"][k]["histogram"],
                                   v["histogram"], rtol=1e-6)
        assert back["param_stats"][k]["mean"] == v["mean"]


def test_smaller_than_json():
    rec = _record()
    binary = len(encode_stats_record(rec))
    as_json = len(json.dumps(rec).encode())
    assert binary < 0.6 * as_json, (binary, as_json)


def test_rejects_corrupt_and_truncated():
    rec = encode_stats_record({"session_id": "x", "v": [1.0] * 32})
    with pytest.raises(ValueError):
        decode_stats_record(b"NOTMAGIC" + rec[8:])
    with pytest.raises(ValueError):
        decode_stats_record(rec[:len(rec) // 2])
    with pytest.raises(TypeError):
        encode_stats_record({"bad": object()})


def test_sqlite_storage_binary_round_trip(tmp_path):
    from deeplearning4j_tpu.ui.storage import SqliteStatsStorage
    st = SqliteStatsStorage(str(tmp_path / "s.db"))
    rec = _record()
    st.put_static_info({"session_id": "sess1", "model": "m"})
    st.put_update(rec)
    ups = st.get_all_updates("sess1")
    assert len(ups) == 1
    np.testing.assert_allclose(
        ups[0]["param_stats"]["layer_0"]["histogram"],
        rec["param_stats"]["layer_0"]["histogram"], rtol=1e-6)
    assert st.get_static_info("sess1")["model"] == "m"
    # stored blob IS binary
    import sqlite3
    rows = sqlite3.connect(str(tmp_path / "s.db")).execute(
        "SELECT blob FROM records").fetchall()
    assert all(is_stats_record(bytes(r[0])) for r in rows)


def test_sqlite_reads_legacy_json_rows(tmp_path):
    import sqlite3
    from deeplearning4j_tpu.ui.storage import SqliteStatsStorage
    st = SqliteStatsStorage(str(tmp_path / "s.db"))
    legacy = {"session_id": "old", "iteration": 7, "score": 1.5}
    with sqlite3.connect(str(tmp_path / "s.db")) as c:
        c.execute("INSERT INTO records VALUES (?,?,?,?)",
                  ("old", "update", 1.0, json.dumps(legacy)))
    assert st.get_all_updates("old")[0]["iteration"] == 7


def test_remote_router_to_server_binary(tmp_path):
    """listener → router → HTTP /remote → storage, binary on the wire."""
    import urllib.request
    from deeplearning4j_tpu.ui.server import UIServer
    from deeplearning4j_tpu.ui.storage import (
        InMemoryStatsStorage, RemoteUIStatsStorageRouter)
    storage = InMemoryStatsStorage()
    srv = UIServer(port=0).attach(storage)
    srv.start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        url = base + "/remote"
        router = RemoteUIStatsStorageRouter(base, async_mode=False)
        rec = _record()
        router.put_update(rec)
        ups = storage.get_all_updates("sess1")
        assert len(ups) == 1
        np.testing.assert_allclose(
            ups[0]["param_stats"]["layer_2"]["histogram"],
            rec["param_stats"]["layer_2"]["histogram"], rtol=1e-6)
        # JSON posters still accepted (third-party integrations)
        body = json.dumps({"kind": "update", "record": {
            "session_id": "sess1", "iteration": 1}}).encode()
        req = urllib.request.Request(url, data=body, headers={
            "Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=5):
            pass
        assert len(storage.get_all_updates("sess1")) == 2
    finally:
        srv.stop()
