"""VAE, YOLO, CenterLoss, Frozen/Lambda/SameDiff, 1D-layer tests.

Analog of reference suites: TestVAE.java, YoloGradientCheckTests /
TestYolo2OutputLayer.java, FrozenLayerTest.java, TestSameDiff*.java,
Convolution1DTest / TestCnn1DLayers.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import ArrayDataSetIterator, DataSet
from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
from deeplearning4j_tpu.nn.inputs import InputType, RecurrentType
from deeplearning4j_tpu.nn.layers.convolution import (
    Convolution1DLayer,
    Cropping1D,
    Subsampling1DLayer,
    Upsampling1D,
    ZeroPadding1DLayer,
)
from deeplearning4j_tpu.nn.layers.feedforward import AutoEncoder, DenseLayer
from deeplearning4j_tpu.nn.layers.misc import (
    FrozenLayer,
    LambdaLayer,
    SameDiffLayer,
)
from deeplearning4j_tpu.nn.layers.objdetect import (
    DetectedObject,
    Yolo2OutputLayer,
    get_predicted_objects,
    iou,
)
from deeplearning4j_tpu.nn.layers.output import (
    CenterLossOutputLayer,
    OutputLayer,
)
from deeplearning4j_tpu.nn.layers.variational import (
    BernoulliReconstructionDistribution,
    CompositeReconstructionDistribution,
    GaussianReconstructionDistribution,
    VariationalAutoencoder,
)
from deeplearning4j_tpu.ops.activations import Activation
from deeplearning4j_tpu.ops.losses import LossFunction
from deeplearning4j_tpu.optimize.updaters import Adam


def _data(n=32, nf=6, nc=3, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, nf)).astype(np.float32)
    y_idx = rng.integers(0, nc, size=n)
    x += y_idx[:, None].astype(np.float32)
    return x, np.eye(nc, dtype=np.float32)[y_idx]


class TestVAE:
    def _vae_layer(self, dist):
        return VariationalAutoencoder(
            n_out=4, encoder_layer_sizes=(16,), decoder_layer_sizes=(16,),
            activation=Activation.TANH, reconstruction_distribution=dist)

    @pytest.mark.parametrize("dist", [
        GaussianReconstructionDistribution(),
        BernoulliReconstructionDistribution(),
    ])
    def test_pretrain_elbo_decreases(self, dist):
        rng = np.random.default_rng(0)
        if isinstance(dist, BernoulliReconstructionDistribution):
            x = (rng.random((64, 6)) > 0.5).astype(np.float32)
        else:
            x = rng.normal(size=(64, 6)).astype(np.float32)
        conf = (NeuralNetConfiguration.Builder()
                .seed(7).updater(Adam(1e-2)).list()
                .layer(self._vae_layer(dist))
                .layer(OutputLayer(n_out=3, loss=LossFunction.MCXENT,
                                   activation=Activation.SOFTMAX))
                .set_input_type(InputType.feed_forward(6))
                .build())
        model = MultiLayerNetwork(conf).init()
        layer = model.layers[0]
        lp0 = model.train_state.params[layer.name]
        key = jax.random.PRNGKey(0)
        before = float(layer.pretrain_loss(lp0, jnp.asarray(x), key))
        it = ArrayDataSetIterator(DataSet(x, x), batch_size=32)
        model.pretrain_layer(0, it, epochs=20)
        lp1 = model.train_state.params[layer.name]
        after = float(layer.pretrain_loss(lp1, jnp.asarray(x), key))
        assert after < before

    def test_supervised_forward_and_fit(self):
        x, y = _data(nf=6)
        conf = (NeuralNetConfiguration.Builder()
                .seed(7).updater(Adam(1e-2)).list()
                .layer(self._vae_layer(GaussianReconstructionDistribution()))
                .layer(OutputLayer(n_out=3, loss=LossFunction.MCXENT,
                                   activation=Activation.SOFTMAX))
                .set_input_type(InputType.feed_forward(6))
                .build())
        model = MultiLayerNetwork(conf).init()
        assert model.output(x[:4]).shape == (4, 3)
        model.fit(DataSet(x, y))
        assert np.isfinite(model.score())

    def test_reconstruct_and_logprob(self):
        x = np.random.default_rng(0).normal(size=(8, 6)).astype(np.float32)
        layer = self._vae_layer(GaussianReconstructionDistribution())
        conf = (NeuralNetConfiguration.Builder().list()
                .layer(layer)
                .layer(OutputLayer(n_out=2))
                .set_input_type(InputType.feed_forward(6)).build())
        model = MultiLayerNetwork(conf).init()
        lp = model.train_state.params[model.layers[0].name]
        rec = model.layers[0].reconstruct(lp, jnp.asarray(x))
        assert rec.shape == (8, 6)
        ll = model.layers[0].reconstruction_log_probability(
            lp, jnp.asarray(x), jax.random.PRNGKey(0), num_samples=3)
        assert ll.shape == (8,)
        assert np.all(np.isfinite(np.asarray(ll)))

    def test_composite_distribution(self):
        comp = CompositeReconstructionDistribution(components=(
            (4, GaussianReconstructionDistribution()),
            (2, BernoulliReconstructionDistribution()),
        ))
        assert comp.total_features() == 6
        assert comp.total_params() == 10
        x = jnp.asarray(np.random.default_rng(0).random((8, 6)),
                        jnp.float32)
        params = jnp.asarray(
            np.random.default_rng(1).normal(size=(8, 10)), jnp.float32)
        ll = comp.log_prob(x, params)
        assert ll.shape == (8,)
        mean = comp.mean(params)
        assert mean.shape == (8, 6)


class TestYolo:
    def _layer(self):
        return Yolo2OutputLayer(boxes=((1.0, 1.0), (2.0, 2.0)),
                                lambda_coord=5.0, lambda_no_obj=0.5)

    def _labels(self, n, h, w, c):
        lab = np.zeros((n, h, w, 4 + c), np.float32)
        # one object in cell (1,1) of every example, class 0
        lab[:, 1, 1, 0] = 1.5   # cx in grid units
        lab[:, 1, 1, 1] = 1.5
        lab[:, 1, 1, 2] = 1.0   # w
        lab[:, 1, 1, 3] = 1.0   # h
        lab[:, 1, 1, 4] = 1.0   # class 0
        return lab

    def test_loss_finite_and_differentiable(self):
        n, h, w, b, c = 2, 4, 4, 2, 3
        x = jnp.asarray(np.random.default_rng(0).normal(
            size=(n, h, w, b * (5 + c))), jnp.float32)
        lab = jnp.asarray(self._labels(n, h, w, c))
        layer = self._layer()
        from deeplearning4j_tpu.nn.layers.base import LayerContext
        ctx = LayerContext(train=True, rng=None, mask=None)
        loss = layer.compute_loss({}, {}, x, lab, ctx)
        assert np.isfinite(float(loss))
        g = jax.grad(lambda x: layer.compute_loss({}, {}, x, lab, ctx))(x)
        assert np.all(np.isfinite(np.asarray(g)))

    def test_training_decreases_loss(self):
        from deeplearning4j_tpu.nn.layers.base import LayerContext
        n, h, w, b, c = 4, 4, 4, 2, 3
        lab = jnp.asarray(self._labels(n, h, w, c))
        layer = self._layer()
        ctx = LayerContext(train=True, rng=None, mask=None)
        x = jnp.asarray(np.random.default_rng(0).normal(
            size=(n, h, w, b * (5 + c))) * 0.1, jnp.float32)

        lf = jax.jit(lambda x: layer.compute_loss({}, {}, x, lab, ctx))
        gf = jax.jit(jax.grad(
            lambda x: layer.compute_loss({}, {}, x, lab, ctx)))
        before = float(lf(x))
        for _ in range(50):
            x = x - 0.01 * gf(x)
        assert float(lf(x)) < before

    def test_decode_and_nms(self):
        n, h, w, b, c = 1, 4, 4, 2, 3
        raw = np.zeros((n, h, w, b * (5 + c)), np.float32)
        raw[0, 1, 1, 4] = 6.0   # box0 conf logit high
        raw[0, 1, 1, 5] = 5.0   # class 0 logit
        layer = self._layer()
        objs = get_predicted_objects(layer, raw, threshold=0.5)
        assert len(objs) >= 1
        top = max(objs, key=lambda d: d.confidence)
        assert top.predicted_class == 0
        assert 1.0 < top.center_x < 2.0

    def test_iou(self):
        a = DetectedObject(0, 1.0, 1.0, 2.0, 2.0, 0, 1.0)
        assert iou(a, a) == pytest.approx(1.0)
        bb = DetectedObject(0, 10.0, 10.0, 2.0, 2.0, 0, 1.0)
        assert iou(a, bb) == 0.0


class TestMiscLayers:
    def test_frozen_layer_wrapper(self):
        x, y = _data(nf=6)
        inner = DenseLayer(n_out=8, activation=Activation.RELU)
        conf = (NeuralNetConfiguration.Builder()
                .seed(1).updater(Adam(1e-2)).list()
                .layer(FrozenLayer(underlying=inner))
                .layer(OutputLayer(n_out=3, loss=LossFunction.MCXENT,
                                   activation=Activation.SOFTMAX))
                .set_input_type(InputType.feed_forward(6))
                .build())
        model = MultiLayerNetwork(conf).init()
        w0 = np.asarray(model.train_state.params["layer_0"]["W"])
        model.fit(DataSet(x, y))
        np.testing.assert_array_equal(
            w0, np.asarray(model.train_state.params["layer_0"]["W"]))

    def test_lambda_layer(self):
        x, y = _data(nf=6)
        conf = (NeuralNetConfiguration.Builder()
                .seed(1).updater(Adam(1e-2)).list()
                .layer(DenseLayer(n_out=8, activation=Activation.RELU))
                .layer(LambdaLayer(fn=lambda t: t * 2.0))
                .layer(OutputLayer(n_out=3, loss=LossFunction.MCXENT,
                                   activation=Activation.SOFTMAX))
                .set_input_type(InputType.feed_forward(6))
                .build())
        model = MultiLayerNetwork(conf).init()
        model.fit(DataSet(x, y))
        assert model.output(x[:4]).shape == (4, 3)

    def test_samediff_layer(self):
        from deeplearning4j_tpu.nn.inputs import FeedForwardType
        x, y = _data(nf=6)
        layer = SameDiffLayer(
            param_shapes={"W": (6, 10), "b": (10,)},
            fn=lambda p, t: jnp.tanh(t @ p["W"] + p["b"]),
            out_type=lambda it: FeedForwardType(10))
        conf = (NeuralNetConfiguration.Builder()
                .seed(1).updater(Adam(1e-2)).list()
                .layer(layer)
                .layer(OutputLayer(n_out=3, loss=LossFunction.MCXENT,
                                   activation=Activation.SOFTMAX))
                .set_input_type(InputType.feed_forward(6))
                .build())
        model = MultiLayerNetwork(conf).init()
        w0 = np.asarray(model.train_state.params["layer_0"]["W"])
        model.fit(DataSet(x, y))
        # params trained
        assert not np.array_equal(
            w0, np.asarray(model.train_state.params["layer_0"]["W"]))

    def test_center_loss(self):
        x, y = _data(nf=6)
        conf = (NeuralNetConfiguration.Builder()
                .seed(1).updater(Adam(1e-2)).list()
                .layer(DenseLayer(n_out=8, activation=Activation.RELU))
                .layer(CenterLossOutputLayer(n_out=3, lambda_=0.1))
                .set_input_type(InputType.feed_forward(6))
                .build())
        model = MultiLayerNetwork(conf).init()
        model.fit(ArrayDataSetIterator(DataSet(x, y), batch_size=16),
                  epochs=3)
        assert np.isfinite(model.score())
        centers = np.asarray(model.train_state.params["layer_1"]["centers"])
        assert centers.shape == (3, 8)
        # centers moved off zero
        assert np.abs(centers).max() > 0


class TestConv1DFamily:
    def test_stack_shapes(self):
        n, t, f = 4, 16, 6
        x = np.random.default_rng(0).normal(size=(n, t, f)).astype(
            np.float32)
        y = np.eye(3, dtype=np.float32)[
            np.random.default_rng(1).integers(0, 3, n)]
        from deeplearning4j_tpu.nn.layers.output import GlobalPoolingLayer
        conf = (NeuralNetConfiguration.Builder()
                .seed(1).updater(Adam(1e-2)).list()
                .layer(ZeroPadding1DLayer(pad=(1, 1)))
                .layer(Convolution1DLayer(
                    n_out=8, kernel_size=3,
                    convolution_mode=__import__(
                        "deeplearning4j_tpu.nn.layers.convolution",
                        fromlist=["ConvolutionMode"]).ConvolutionMode.SAME))
                .layer(Upsampling1D(size=2))
                .layer(Cropping1D(crop=(2, 2)))
                .layer(Subsampling1DLayer(kernel_size=2, stride=2))
                .layer(GlobalPoolingLayer())
                .layer(OutputLayer(n_out=3, loss=LossFunction.MCXENT,
                                   activation=Activation.SOFTMAX))
                .set_input_type(InputType.recurrent(f, t))
                .build())
        model = MultiLayerNetwork(conf).init()
        out = model.output(x)
        assert out.shape == (n, 3)
        model.fit(DataSet(x, y))
        assert np.isfinite(model.score())
