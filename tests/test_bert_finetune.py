"""Imported-BERT fine-tuning through TransferLearning.GraphBuilder —
the reference's flagship workflow (import a Keras model, freeze the
encoder, graft a new head, fine-tune; TransferLearning.java:84
setFeatureExtractor + GraphBuilder)."""

import numpy as np
import pytest

keras = pytest.importorskip("keras")

from deeplearning4j_tpu.datasets.dataset import MultiDataSet
from deeplearning4j_tpu.modelimport.bert import (
    example_inputs,
    import_bert_base,
)
from deeplearning4j_tpu.nn.layers.output import OutputLayer
from deeplearning4j_tpu.nn.layers.output import GlobalPoolingLayer, PoolingType
from deeplearning4j_tpu.nn.transferlearning import (
    FineTuneConfiguration,
    TransferLearning,
)
from deeplearning4j_tpu.optimize.updaters import Adam


def test_finetuned_graph_compiles_to_one_executable():
    """The grafted graph's full train-step loss lowers to ONE XLA module
    (whole-graph compile — the SameDiff-whole-graph north star holds
    through transfer-learning surgery; VERDICT r3 #5)."""
    import jax
    import jax.numpy as jnp
    vocab, width, seq = 40, 16, 12
    model, _km = import_bert_base(seq_len=seq, vocab=vocab, width=width,
                                  n_layers=2, n_heads=2, ffn=32,
                                  max_len=16)
    enc_out = model.conf.network_outputs[0]
    ft = (TransferLearning.GraphBuilder(model)
          .fine_tune_configuration(
              FineTuneConfiguration.Builder().updater(Adam(1e-3)).build())
          .add_layer("pool",
                     GlobalPoolingLayer(pooling_type=PoolingType.AVG),
                     enc_out)
          .add_layer("cls", OutputLayer(n_out=2), "pool")
          .set_outputs("cls")
          .build())
    ids, pos = example_inputs(4, seq, vocab)
    y = np.eye(2, dtype=np.float32)[np.arange(4) % 2]
    ts = ft.train_state

    def loss(params, mstate, ids, pos, y, key):
        return ft._loss(params, mstate, (ids, pos), (y,), None, None,
                        key, ts.iteration)[0]

    compiled = jax.jit(loss).lower(
        ts.params, ts.model_state, jnp.asarray(ids), jnp.asarray(pos),
        jnp.asarray(y), jax.random.PRNGKey(0)).compile()
    assert compiled.as_text().count("HloModule") == 1
    val = compiled(ts.params, ts.model_state, jnp.asarray(ids),
                   jnp.asarray(pos), jnp.asarray(y),
                   jax.random.PRNGKey(0))
    assert np.isfinite(float(val))


def test_imported_bert_freeze_and_finetune():
    keras.utils.set_random_seed(0)   # deterministic encoder features
    vocab, width, seq = 40, 16, 12
    model, _km = import_bert_base(seq_len=seq, vocab=vocab, width=width,
                                  n_layers=2, n_heads=2, ffn=32,
                                  max_len=16)
    encoder_out = model.conf.network_outputs[0]

    ft = (TransferLearning.GraphBuilder(model)
          .fine_tune_configuration(
              FineTuneConfiguration.Builder().updater(Adam(1e-2)).build())
          .set_feature_extractor(encoder_out)
          .add_layer("pool", GlobalPoolingLayer(pooling_type=PoolingType.AVG),
                     encoder_out)
          .add_layer("cls", OutputLayer(n_out=2), "pool")
          .set_outputs("cls")
          .build())

    # snapshot the (frozen) encoder weights
    import jax
    frozen_names = [n for n in ft.layer_names if n not in ("pool", "cls")]
    before = {n: jax.tree_util.tree_map(np.asarray,
                                        ft.train_state.params[n])
              for n in frozen_names if ft.train_state.params.get(n)}

    rng = np.random.default_rng(0)
    ids, pos = example_inputs(64, seq, vocab, seed=1)
    # learnable from frozen random features through MEAN pooling:
    # class = whether the sequence's mean token id is low or high
    y = np.eye(2, dtype=np.float32)[(ids.mean(1) < vocab / 2).astype(int)]
    ds = MultiDataSet((ids, pos), (y,))

    losses = []
    for _ in range(100):
        ft.fit(ds)
        losses.append(float(ft._last_loss))
    assert losses[-1] < losses[0] * 0.9, losses  # seeded, deterministic

    # frozen encoder params bit-unchanged; head moved
    for n, tree in before.items():
        for (path, a), b in zip(
                jax.tree_util.tree_flatten_with_path(tree)[0],
                jax.tree_util.tree_leaves(ft.train_state.params[n])):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                          err_msg=f"{n}{path} moved")
    head_w = np.asarray(ft.train_state.params["cls"]["W"])
    assert np.abs(head_w).sum() > 0
