"""ServingEngine / ParallelInference facade / serve CLI tests (PR 5).

Covers the serving concurrency contract: concurrent requests come back
bitwise-equal to direct ``model.output``, warmup means zero live
compiles, shutdown mid-flight fails waiters instead of hanging them,
malformed requests fail only their caller, and the multi-replica path
holds all of it under the 8-device CPU mesh.
"""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.observe.latency import LatencyRing
from deeplearning4j_tpu.observe.registry import MetricsRegistry
from deeplearning4j_tpu.parallel.inference import (
    InferenceMode,
    ParallelInference,
)
from deeplearning4j_tpu.parallel.serving import ServingEngine

N_IN = 5


def _tiny_model(seed: int = 1):
    from deeplearning4j_tpu.models.multi_layer_network import (
        MultiLayerNetwork)
    from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.inputs import InputType
    from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
    from deeplearning4j_tpu.nn.layers.output import OutputLayer
    from deeplearning4j_tpu.ops.losses import LossFunction
    from deeplearning4j_tpu.optimize.updaters import Adam
    conf = (NeuralNetConfiguration.Builder().seed(seed)
            .updater(Adam(1e-2)).list()
            .layer(DenseLayer(n_out=8))
            .layer(OutputLayer(n_out=3, loss=LossFunction.MCXENT))
            .set_input_type(InputType.feed_forward(N_IN)).build())
    return MultiLayerNetwork(conf).init()


def _engine(model, **kw):
    kw.setdefault("batch_limit", 8)
    kw.setdefault("feature_shape", (N_IN,))
    kw.setdefault("registry", MetricsRegistry())
    return ServingEngine(model, **kw)


class TestServingEngine:
    def test_bitwise_vs_direct_across_sizes(self):
        m = _tiny_model()
        rng = np.random.default_rng(0)
        with _engine(m) as eng:
            for n in (1, 2, 3, 5, 8):
                x = rng.normal(size=(n, N_IN)).astype(np.float32)
                got = eng.output(x)
                want = np.asarray(m.output(x))
                assert got.shape == want.shape
                assert np.array_equal(got, want), \
                    f"size {n} diverged from direct output"

    def test_concurrent_threads_bitwise(self):
        m = _tiny_model()
        rng = np.random.default_rng(1)
        xs = [rng.normal(size=(1 + i % 4, N_IN)).astype(np.float32)
              for i in range(24)]
        want = [np.asarray(m.output(x)) for x in xs]
        results = [None] * len(xs)
        with _engine(m) as eng:
            def worker(lo, hi):
                for i in range(lo, hi):
                    results[i] = eng.output(xs[i])
            threads = [threading.Thread(target=worker,
                                        args=(i * 6, (i + 1) * 6))
                       for i in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            eng.assert_warm()
        for got, exp in zip(results, want):
            assert np.array_equal(got, exp)

    def test_oversized_request_splits_bounded_ladder(self):
        m = _tiny_model()
        rng = np.random.default_rng(2)
        with _engine(m, batch_limit=4) as eng:
            x = rng.normal(size=(19, N_IN)).astype(np.float32)
            got = eng.output(x)
            assert got.shape == (19, 3)
            assert np.array_equal(got, np.asarray(m.output(x)))
            # the ladder stays bounded: no executable above batch_limit
            assert all(b <= 4 for b, _w, _p in eng._exe)
            eng.assert_warm()

    def test_empty_and_misshaped_requests(self):
        m = _tiny_model()
        with _engine(m) as eng:
            with pytest.raises(ValueError, match="non-empty"):
                eng.output(np.zeros((0, N_IN), np.float32))
            with pytest.raises(ValueError, match="non-empty"):
                eng.output(np.float32(3.0))       # 0-d
            with pytest.raises(ValueError, match="feature shape"):
                eng.output(np.zeros((2, N_IN + 1), np.float32))
            # the engine survives bad requests: a good one still lands
            x = np.zeros((2, N_IN), np.float32)
            assert np.array_equal(eng.output(x),
                                  np.asarray(m.output(x)))

    def test_warmup_then_zero_recompiles(self):
        m = _tiny_model()
        rng = np.random.default_rng(3)
        reg = MetricsRegistry()
        with _engine(m, registry=reg) as eng:
            warm = reg.get_metric("dl4j_serving_compiles_total")
            for n in (3, 1, 7, 8, 2, 5):
                eng.output(rng.normal(size=(n, N_IN)).astype(np.float32))
            assert eng.recompiles_after_warmup == 0
            eng.assert_warm()                 # watchdog-backed
            rendered = reg.render()
            assert 'phase="warmup"' in rendered
            assert ('dl4j_serving_compiles_total{phase="live",'
                    'precision="f32",session="serve"} 0.0') in rendered

    def test_shutdown_fails_waiters_no_hang(self):
        class Slow:
            def output(self, x):
                time.sleep(0.05)
                return np.zeros((x.shape[0], 3), np.float32)

        eng = ServingEngine(Slow(), batch_limit=2, timeout_ms=1.0,
                            registry=MetricsRegistry())
        futures = [eng.submit(np.zeros((1, N_IN), np.float32))
                   for _ in range(16)]
        eng.shutdown()
        done = [f for f in futures
                if f.done() or f.exception(timeout=5) is not None
                or f.result(timeout=5) is not None]
        assert len(done) == len(futures)      # nobody hangs
        # at least the tail of the queue was failed, not silently lost
        failed = [f for f in futures if f.exception() is not None]
        for f in failed:
            assert "shut down" in str(f.exception())
        with pytest.raises(RuntimeError, match="shut down"):
            eng.submit(np.zeros((1, N_IN), np.float32))

    def test_error_propagates_to_all_waiters(self):
        class Broken:
            def output(self, x):
                raise RuntimeError("boom")

        with ServingEngine(Broken(), batch_limit=4,
                           registry=MetricsRegistry()) as eng:
            f1 = eng.submit(np.zeros((1, N_IN), np.float32))
            f2 = eng.submit(np.zeros((1, N_IN), np.float32))
            with pytest.raises(RuntimeError, match="boom"):
                f1.result(timeout=5)
            with pytest.raises(RuntimeError, match="boom"):
                f2.result(timeout=5)

    def test_multi_replica_mesh(self):
        import jax
        if len(jax.devices()) < 4:
            pytest.skip("needs the 8-device CPU mesh")
        m = _tiny_model()
        rng = np.random.default_rng(4)
        reg = MetricsRegistry()
        with _engine(m, batch_limit=8, replicas=4,
                     registry=reg, session_id="mr") as eng:
            errs = []

            def hammer(seed):
                r = np.random.default_rng(seed)
                try:
                    for i in range(15):
                        k = 1 + i % 8
                        x = r.normal(size=(k, N_IN)).astype(np.float32)
                        got = eng.output(x)
                        if not np.array_equal(got,
                                              np.asarray(m.output(x))):
                            raise AssertionError(f"size {k} diverged")
                except Exception as e:
                    errs.append(e)
            threads = [threading.Thread(target=hammer, args=(s,))
                       for s in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert not errs, errs
            eng.assert_warm()
            rendered = reg.render()
            # full buckets went data-parallel over the mesh, partials
            # round-robined over the replicas
            assert 'replica="mesh"' in rendered
            assert 'replica="0"' in rendered

    def test_metrics_and_stats_published(self):
        m = _tiny_model()
        reg = MetricsRegistry()
        with _engine(m, registry=reg) as eng:
            for _ in range(4):
                eng.output(np.zeros((2, N_IN), np.float32))
            stats = eng.stats()
            assert stats["requests"] == 4
            assert stats["inflight"] == 0
            assert stats["recompiles_after_warmup"] == 0
            assert set(stats["latency_ms"]) == {"p50", "p95", "p99"}
            rendered = reg.render()
            for series in ("dl4j_serving_requests_total",
                           "dl4j_serving_batches_total",
                           "dl4j_serving_inflight",
                           "dl4j_serving_queue_depth",
                           "dl4j_serving_batch_occupancy",
                           "dl4j_serving_latency_ms"):
                assert series in rendered, series

    def test_serve_spans_traced(self):
        from deeplearning4j_tpu.observe import SpanTracer
        m = _tiny_model()
        tracer = SpanTracer()
        with _engine(m, tracer=tracer) as eng:
            eng.output(np.zeros((2, N_IN), np.float32))
            names = {e["name"] for e in tracer._events}
        for required in ("queue_wait", "batch_form", "dispatch",
                         "device", "fetch", "serve_warmup"):
            assert required in names, required

    def test_queue_depth_counts_carried_chunk(self):
        """Regression (PR 6): a chunk the dispatcher pulled off the
        queue but parked in ``_carry`` (it didn't fit the forming
        batch) is still queued work — ``stats()`` must count it, or a
        loaded engine reports one request less than it owes."""
        m = _tiny_model()
        with _engine(m) as eng:
            assert eng.stats()["queue_depth"] == 0
            # white-box: park a sentinel exactly where the dispatcher
            # parks an overflow chunk
            eng._carry = object()
            try:
                assert eng.stats()["queue_depth"] == 1
            finally:
                eng._carry = None
            assert eng.stats()["queue_depth"] == 0

    def test_carried_chunk_claimed_exactly_once_under_race(self):
        """Regression (PR 8, found by graftlint thread-discipline):
        ``self._carry`` is shared between the dispatcher thread
        (``_form_batch`` parks/reclaims overflow chunks) and caller
        threads (``_drain_queue`` on the submit/shutdown race,
        ``stats``). The original unlocked read-then-clear let two
        racing consumers both take the same parked request (waiter
        failed AND re-dispatched) or lose the park (waiter hangs).
        Hammer both consumers over a parked sentinel: every round,
        exactly one side may claim it."""
        from concurrent.futures import Future

        from deeplearning4j_tpu.parallel.serving import _Request

        m = _tiny_model()
        eng = _engine(m, timeout_ms=1.0)
        eng.shutdown()          # stop the real dispatcher; we drive
        for _ in range(40):     # _form_batch/_drain_queue by hand
            req = _Request(x=np.zeros((1, N_IN), np.float32),
                           future=Future(),
                           t_enqueue=time.perf_counter())
            with eng._carry_lock:
                eng._carry = req
            claims = []
            barrier = threading.Barrier(2)

            def form():
                barrier.wait()
                batch = eng._form_batch()
                if batch and batch[0] is req:
                    claims.append("dispatcher")

            def drain():
                barrier.wait()
                eng._drain_queue()

            threads = [threading.Thread(target=form),
                       threading.Thread(target=drain)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            if req.future.done() and req.future.exception() is not None:
                claims.append("drain")
            assert len(claims) == 1, claims

    def test_bf16_params(self):
        m = _tiny_model()
        rng = np.random.default_rng(5)
        x = rng.normal(size=(3, N_IN)).astype(np.float32)
        with _engine(m, bf16=True) as eng:
            got = eng.output(x)
        # bf16 serving approximates the f32 forward, never replaces it
        np.testing.assert_allclose(
            got, np.asarray(m.output(x)), atol=0.05)


class TestLatencyRing:
    def test_quantiles_nearest_rank(self):
        ring = LatencyRing(capacity=100)
        for v in range(1, 101):                 # 1..100 ms
            ring.record(v / 1e3)
        q = ring.quantiles()
        assert q[0.5] == pytest.approx(0.050)
        assert q[0.95] == pytest.approx(0.095)
        assert q[0.99] == pytest.approx(0.099)

    def test_window_wraps(self):
        ring = LatencyRing(capacity=4)
        for v in (1.0, 2.0, 3.0, 4.0, 5.0, 6.0):
            ring.record(v)
        assert ring.count == 6
        assert sorted(ring.snapshot()) == [3.0, 4.0, 5.0, 6.0]

    def test_quantile_validation_before_sort(self):
        ring = LatencyRing(capacity=8)
        # must raise on an EMPTY ring too — validation happens before
        # any window work
        with pytest.raises(ValueError, match="out of range"):
            ring.quantiles((1.5,))
        with pytest.raises(ValueError, match="out of range"):
            ring.delta_quantiles((-0.1,))
        ring.record(1.0)
        with pytest.raises(ValueError, match="out of range"):
            ring.quantiles((0.5, 2.0))
        # a doomed call must not consume the delta window
        assert ring.delta_quantiles((0.5,)) == {0.5: 1.0}

    def test_delta_quantiles_windowed(self):
        ring = LatencyRing(capacity=100)
        for v in (1.0, 2.0, 3.0):
            ring.record(v)
        q = ring.delta_quantiles((0.5,))
        assert q[0.5] == 2.0
        # nothing new since the last delta read
        assert ring.delta_quantiles((0.5,)) == {}
        # only the NEW observations count, not the whole ring
        ring.record(10.0)
        assert ring.delta_quantiles((0.5,)) == {0.5: 10.0}

    def test_delta_quantiles_wraps_ring(self):
        ring = LatencyRing(capacity=4)
        ring.record(1.0)
        ring.mark()
        # 5 new observations through a capacity-4 ring: the delta
        # window clamps to the newest 4
        for v in (2.0, 3.0, 4.0, 5.0, 6.0):
            ring.record(v)
        q = ring.delta_quantiles((0.0, 1.0))
        assert q[0.0] == 3.0 and q[1.0] == 6.0

    def test_reset_empties_window_keeps_count(self):
        ring = LatencyRing(capacity=8)
        for v in (1.0, 2.0, 3.0):
            ring.record(v)
        ring.reset()
        assert ring.snapshot() == []
        assert ring.quantiles() == {}
        assert ring.count == 3            # cumulative, monotonic
        # pre-reset observations never leak into the next delta window
        ring.record(7.0)
        assert ring.delta_quantiles((0.5,)) == {0.5: 7.0}


class TestParallelInferenceFacade:
    def test_batched_delegates_to_engine(self):
        m = _tiny_model()
        with ParallelInference(m, InferenceMode.BATCHED,
                               batch_limit=8,
                               registry=MetricsRegistry()) as pi:
            assert isinstance(pi.engine, ServingEngine)
            x = np.zeros((3, N_IN), np.float32)
            assert np.array_equal(pi.output(x),
                                  np.asarray(m.output(x)))

    def test_inplace_rejects_empty(self):
        m = _tiny_model()
        pi = ParallelInference(m, InferenceMode.INPLACE)
        with pytest.raises(ValueError, match="non-empty"):
            pi.output(np.zeros((0, N_IN), np.float32))

    def test_batched_rejects_empty(self):
        m = _tiny_model()
        with ParallelInference(m, InferenceMode.BATCHED,
                               registry=MetricsRegistry()) as pi:
            with pytest.raises(ValueError, match="non-empty"):
                pi.output(np.zeros((0, N_IN), np.float32))

    def test_inplace_oversized_clamps_and_splits(self):
        class Recorder:
            def __init__(self, inner):
                self.inner = inner
                self.sizes = []

            def output(self, x):
                self.sizes.append(x.shape[0])
                return self.inner.output(x)

        m = _tiny_model()
        rec = Recorder(m)
        pi = ParallelInference(rec, InferenceMode.INPLACE,
                               batch_limit=4)
        x = np.random.default_rng(6).normal(
            size=(11, N_IN)).astype(np.float32)
        got = pi.output(x)
        assert got.shape == (11, 3)
        np.testing.assert_allclose(got, np.asarray(m.output(x)),
                                   rtol=1e-5, atol=1e-6)
        # every dispatched chunk stayed on the bounded ladder
        assert max(rec.sizes) <= 4


class TestServeCLI:
    def test_serve_in_process(self, tmp_path):
        from deeplearning4j_tpu.__main__ import _build_parser, cmd_serve
        from deeplearning4j_tpu.models.serialization import save_model

        m = _tiny_model()
        path = str(tmp_path / "model.zip")
        save_model(m, path)
        args = _build_parser().parse_args(
            ["serve", "--model", path, "--ui-port", "0",
             "--batch-limit", "8", "--warmup-shape", str(N_IN)])
        pi, server = cmd_serve(args, block=False)
        try:
            body = json.dumps(
                {"features": np.zeros((2, N_IN)).tolist()}).encode()
            req = urllib.request.Request(
                f"{server.url}/api/predict", data=body,
                headers={"Content-Type": "application/json"})
            out = json.loads(urllib.request.urlopen(req).read())
            assert np.asarray(out["output"]).shape == (2, 3)
            want = np.asarray(m.output(np.zeros((2, N_IN), np.float32)))
            assert np.array_equal(np.asarray(out["output"],
                                             np.float32), want)
            stats = json.loads(urllib.request.urlopen(
                f"{server.url}/api/serving/stats").read())
            assert stats["recompiles_after_warmup"] == 0
            metrics = urllib.request.urlopen(
                f"{server.url}/metrics").read().decode()
            assert "dl4j_serving_requests_total" in metrics
            health = urllib.request.urlopen(
                f"{server.url}/healthz").read()
            assert json.loads(health)["status"] == "ok"
        finally:
            pi.shutdown()
            server.stop()

    def test_serve_fleet_flags_round_trip(self, tmp_path):
        """``--slo-ms`` + ``--aot-cache-dir`` (PR 6): serve goes up
        behind the FleetRouter, /api/predict rides admission control,
        the fleet stats/metrics surface is live, and the persisted AOT
        cache is written next to the model."""
        import os

        from deeplearning4j_tpu.__main__ import _build_parser, cmd_serve
        from deeplearning4j_tpu.models.serialization import save_model
        from deeplearning4j_tpu.parallel.fleet import FleetRouter

        m = _tiny_model()
        path = str(tmp_path / "model.zip")
        cache = str(tmp_path / "aot")
        save_model(m, path)
        args = _build_parser().parse_args(
            ["serve", "--model", path, "--ui-port", "0",
             "--batch-limit", "8", "--warmup-shape", str(N_IN),
             "--slo-ms", "250", "--aot-cache-dir", cache,
             "--model-version", "v7"])
        front, server = cmd_serve(args, block=False)
        try:
            assert isinstance(front, FleetRouter)
            body = json.dumps(
                {"features": np.zeros((2, N_IN)).tolist()}).encode()
            req = urllib.request.Request(
                f"{server.url}/api/predict", data=body,
                headers={"Content-Type": "application/json"})
            out = json.loads(urllib.request.urlopen(req).read())
            want = np.asarray(m.output(np.zeros((2, N_IN), np.float32)))
            assert np.array_equal(
                np.asarray(out["output"], np.float32), want)
            st = json.loads(urllib.request.urlopen(
                f"{server.url}/api/fleet/stats").read())
            assert st["slo_ms"] == 250.0
            pool = st["pools"]["model"]
            assert pool["active_version"] == "v7"
            assert pool["pending"] == 0
            metrics = urllib.request.urlopen(
                f"{server.url}/metrics").read().decode()
            assert "dl4j_fleet_admitted_total" in metrics
            # the persisted cache was saved during warmup
            assert os.path.exists(os.path.join(cache, "manifest.json"))
            front.assert_warm()
        finally:
            front.shutdown()
            server.stop()
