"""Deterministic fault injection + end-to-end deadline tests (PR 14).

The contracts under test:

- **plan grammar + determinism** (chaos/plan.py): the ``DL4J_CHAOS``
  string parses into seeded FaultSpecs (malformed input raises), and a
  plan's injection sequence is a pure function of (seed, hit order) —
  two identical drives produce bitwise-equal ``replay_signature()``s.
- **chaos matrix** (the satellite sweep): {delay, error, torn-write,
  corrupt-blob, clock-skew} x {artifact-store warm, registry scan,
  remote dispatch, broker publish} each degrade along the documented
  tier (quarantine-and-miss, dead-classify, retry-onto-other-node,
  reconnect) instead of crashing or hanging — and the whole sweep
  replays bitwise under the same seed.
- **deadlines** (parallel/deadline.py + every tier): ``from_ingress``
  parsing (body beats header, garbage degrades to None), and an
  expired budget sheds SYNCHRONOUSLY at fleet admission (ShedError
  reason ``deadline``), serving ingress, remote ingress + retry gate,
  generation ingress/queue/decode — never reaching the device — with
  the ui tier mapping all of it to HTTP 504.
- **satellites**: malformed ``Retry-After`` falls back to the backoff
  curve (counted), streaming/corpus iterators distinguish a dead
  transport/store from a quiet topic via ``termination_reason``, SSE
  client disconnect frees the generation slot (counted), and the
  ``chaos-hygiene`` graftlint rule rejects plan imports / per-loop
  site resolution on hot paths.

Everything runs on injected clocks/transports where possible; the only
real compiles are the tiny store-tier exports.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.chaos import plan as chaosplan
from deeplearning4j_tpu.chaos.hook import chaos_site
from deeplearning4j_tpu.chaos.plan import (
    ChaosError,
    FaultPlan,
    FaultSpec,
    parse_plan,
    site_seed,
)
from deeplearning4j_tpu.datasets.corpus import (
    CorpusDataSetIterator,
    CorpusShardWriter,
)
from deeplearning4j_tpu.nlp.sentence_iterators import (
    StreamingSentenceIterator,
)
from deeplearning4j_tpu.observe.registry import MetricsRegistry
from deeplearning4j_tpu.parallel.aot_cache import (
    AOTExecutableCache,
    ArtifactStore,
    fingerprint,
)
from deeplearning4j_tpu.parallel.deadline import Deadline, DeadlineExceeded
from deeplearning4j_tpu.parallel.fleet import FleetRouter, ModelPool, ShedError
from deeplearning4j_tpu.parallel.node import NodeRegistry
from deeplearning4j_tpu.parallel.remote import RemoteDispatcher
from deeplearning4j_tpu.streaming.broker import TcpTransport

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OK_BODY = json.dumps({"output": [[0.0]], "n": 1}).encode()


@pytest.fixture(autouse=True)
def _always_disarm():
    """No chaos test may leak an armed plan into the rest of the
    suite."""
    yield
    chaosplan.disarm()


def _arm(text: str, registry=None) -> FaultPlan:
    return chaosplan.arm(
        parse_plan(text, registry=registry or MetricsRegistry()))


class Clock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ---------------------------------------------------------------------------
# plan grammar
# ---------------------------------------------------------------------------


class TestPlanGrammar:
    def test_full_clause(self):
        p = parse_plan(
            "seed=42;remote.send:delay(p=0.25,ms=40);"
            "store.save:corrupt(count=1,after=2,arg=blob)",
            registry=MetricsRegistry())
        assert p.seed == 42
        assert len(p.specs) == 2
        d, c = p.specs
        assert (d.site, d.kind, d.p, d.ms) == ("remote.send", "delay",
                                               0.25, 40.0)
        assert (c.site, c.kind, c.count, c.after, c.arg) == \
            ("store.save", "corrupt", 1, 2, "blob")

    def test_hex_seed_and_empty_clauses(self):
        p = parse_plan("seed=0x10;;broker.publish:error;",
                       registry=MetricsRegistry())
        assert p.seed == 16
        assert [s.kind for s in p.specs] == ["error"]

    @pytest.mark.parametrize("bad", [
        "remote.send",                       # no :kind
        ":error",                            # no site
        "remote.send:error(p=0.5",           # unbalanced parens
        "remote.send:error(p)",              # param without =
        "remote.send:error(bogus=1)",        # unknown param
        "remote.send:frobnicate",            # unknown kind
        "remote.send:error(p=1.5)",          # p out of [0, 1]
        "seed=nope",                         # unparseable seed
    ])
    def test_malformed_raises(self, bad):
        with pytest.raises(ValueError):
            parse_plan(bad, registry=MetricsRegistry())

    def test_unknown_kind_raises_in_spec(self):
        with pytest.raises(ValueError):
            FaultSpec(site="x", kind="explode")


# ---------------------------------------------------------------------------
# determinism
# ---------------------------------------------------------------------------


def _drive_probabilistic(seed: int):
    p = parse_plan(f"seed={seed};s.x:delay(p=0.5,ms=0)",
                   registry=MetricsRegistry())
    site = p.site("s.x")
    for _ in range(256):
        site.hit()
    return p.replay_signature()


class TestDeterminism:
    def test_same_seed_bitwise_identical(self):
        s1, s2 = _drive_probabilistic(7), _drive_probabilistic(7)
        assert s1 == s2
        assert 0 < len(s1) < 256          # p=0.5 fired SOME of the time

    def test_different_seed_differs(self):
        assert _drive_probabilistic(7) != _drive_probabilistic(8)

    def test_site_seeds_independent(self):
        assert site_seed(42, "remote.send") != site_seed(42, "store.save")
        assert site_seed(42, "remote.send") != site_seed(43, "remote.send")

    def test_count_after_arg_discipline(self):
        p = parse_plan("seed=1;s:error(count=2,after=3,arg=a)",
                       registry=MetricsRegistry())
        site = p.site("s")
        fired = []
        for i in range(10):
            inj = site.hit(arg="a" if i % 2 == 0 else "b")
            if inj is not None:
                fired.append((i, inj.hit))
        # after=3 skips hits 0..2; arg=a matches even hits only;
        # count=2 caps the total
        assert fired == [(4, 4), (6, 6)]

    def test_unlisted_site_is_none(self):
        p = parse_plan("s:error", registry=MetricsRegistry())
        assert p.site("other") is None


# ---------------------------------------------------------------------------
# site act-out primitives
# ---------------------------------------------------------------------------


class TestSiteActions:
    def test_error_raises_chaoserror(self):
        p = parse_plan("s:error", registry=MetricsRegistry())
        with pytest.raises(ChaosError, match="injected error at s"):
            p.site("s").fail()

    def test_error_raise_as(self):
        p = parse_plan("s:error", registry=MetricsRegistry())
        with pytest.raises(ConnectionError, match="chaos"):
            p.site("s").fail(raise_as=ConnectionError)

    def test_timeout_kind(self):
        p = parse_plan("s:timeout", registry=MetricsRegistry())
        with pytest.raises(TimeoutError):
            p.site("s").fail()

    def test_delay_returns_injection(self):
        p = parse_plan("s:delay(ms=0)", registry=MetricsRegistry())
        inj = p.site("s").fail()
        assert inj is not None and inj.kind == "delay"

    def test_mangle_torn_write_truncates(self):
        p = parse_plan("s:torn_write", registry=MetricsRegistry())
        data = bytes(range(64))
        out, inj = p.site("s").mangle(data)
        assert inj is not None and out == data[:32]

    def test_mangle_corrupt_flips_one_draw_addressed_byte(self):
        p = parse_plan("seed=9;s:corrupt", registry=MetricsRegistry())
        data = bytes(range(64))
        out, inj = p.site("s").mangle(data)
        assert len(out) == len(data)
        diff = [i for i in range(64) if out[i] != data[i]]
        assert diff == [inj.draw % 64]
        assert out[diff[0]] == data[diff[0]] ^ 0xFF

    def test_mangle_passthrough_when_nothing_fires(self):
        p = parse_plan("s:corrupt(count=1)", registry=MetricsRegistry())
        site = p.site("s")
        site.mangle(b"abc")                 # consumes the count
        out, inj = site.mangle(b"abc")
        assert out == b"abc" and inj is None

    def test_skew(self):
        p = parse_plan("c:clock_skew(skew_ms=5)",
                       registry=MetricsRegistry())
        assert p.site("c").skew() == pytest.approx(0.005)

    def test_injected_counts_and_metric(self):
        reg = MetricsRegistry()
        p = parse_plan("s:error(count=3)", registry=reg)
        site = p.site("s")
        for _ in range(5):
            try:
                site.fail()
            except ChaosError:
                pass
        assert p.injected() == {("s", "error"): 3}
        c = reg.get_metric("dl4j_chaos_injected_total")
        assert c.get(site="s", kind="error") == 3.0


# ---------------------------------------------------------------------------
# arming / disarming / the hot-path hook
# ---------------------------------------------------------------------------


class TestArming:
    def test_arm_and_disarm(self):
        _arm("remote.send:error")
        assert chaosplan.active_plan() is not None
        assert chaosplan.site("remote.send") is not None
        assert chaos_site("remote.send") is not None
        chaosplan.disarm()
        assert chaosplan.active_plan() is None
        assert chaosplan.site("remote.send") is None
        assert chaos_site("remote.send") is None

    def test_disarm_blocks_env_rearm(self, monkeypatch):
        monkeypatch.setenv("DL4J_CHAOS", "remote.send:error")
        chaosplan.disarm()
        assert chaosplan.site("remote.send") is None
        assert chaos_site("remote.send") is None

    def test_arm_from_env(self, monkeypatch):
        monkeypatch.setenv("DL4J_CHAOS", "seed=3;broker.publish:delay(ms=1)")
        p = chaosplan.arm()
        assert p.seed == 3
        assert p.specs[0].site == "broker.publish"

    def test_arm_without_plan_or_env_raises(self, monkeypatch):
        monkeypatch.delenv("DL4J_CHAOS", raising=False)
        with pytest.raises(ValueError):
            chaosplan.arm()

    def test_disarmed_process_never_imports_plan(self):
        """The zero-overhead contract: a process that never arms chaos
        must never import chaos.plan — the hook answers None from the
        env/sys.modules probe alone."""
        code = (
            "import sys\n"
            "import deeplearning4j_tpu.streaming.broker\n"
            "from deeplearning4j_tpu.chaos.hook import chaos_site\n"
            "assert chaos_site('broker.publish') is None\n"
            "assert 'deeplearning4j_tpu.chaos.plan' not in sys.modules\n")
        env = {k: v for k, v in os.environ.items() if k != "DL4J_CHAOS"}
        r = subprocess.run([sys.executable, "-c", code], cwd=_ROOT,
                           env=env, capture_output=True, text=True,
                           timeout=120)
        assert r.returncode == 0, r.stderr


# ---------------------------------------------------------------------------
# the chaos matrix (satellite 4)
# ---------------------------------------------------------------------------

# one plan exercising every kind across the four tiers; ``after=2`` on
# the manifest clause separates the two store cycles (cycle 1: blob
# corrupt, clean manifest; cycle 2: clean blob, torn manifest)
_MATRIX = ("seed={seed};"
           "registry.write:torn_write(count=1);"
           "store.save:corrupt(count=1,arg=blob);"
           "store.save:torn_write(count=1,after=2,arg=manifest);"
           "remote.send:error(count=1);"
           "remote.send:delay(ms=1,count=1);"
           "remote.clock:clock_skew(skew_ms=5,count=2);"
           "broker.publish:error(count=2)")


def _store_cycle(base_dir):
    """Tiny export -> save -> fresh-cache load. Returns the loader."""
    import jax
    import jax.numpy as jnp

    def fwd(params, mstate, x):
        return x * params["w"], mstate

    params = {"w": jnp.asarray(2.0, jnp.float32)}
    fp = fingerprint(params, {}, feature_shape=(3,), dtype=np.float32,
                     ladder=[2])
    saver = AOTExecutableCache(str(base_dir))
    n = saver.save(jax.jit(fwd), (params, {}), fp, [2],
                   np.zeros((1, 3), np.float32))
    assert n == 1
    loader = AOTExecutableCache(str(base_dir))
    loaded = loader.try_load(fp)
    return loader, loaded


def _drive_matrix(tmp, seed):
    """One deterministic pass over all four tiers under the armed
    matrix plan; returns (observations, replay signature)."""
    plan = _arm(_MATRIX.format(seed=seed))
    out = {}

    # -- registry scan tier: torn record -> classified dead ---------------
    nreg = NodeRegistry(str(tmp / "reg"))
    nreg.write("a", "http://a")                    # torn (count=1)
    rec = nreg.snapshot()["a"]
    out["torn"] = (rec["health"], rec.get("corrupt", False))
    nreg.write("a", "http://a")                    # clean overwrite
    out["healed"] = nreg.snapshot()["a"]["health"]

    # -- store warm tier: corrupt blob -> quarantine; torn manifest -------
    l1, loaded1 = _store_cycle(tmp / "aot1")
    out["quarantine"] = (l1.quarantined, sorted(loaded1),
                         "quarantined" in (l1.reason or ""))
    assert os.path.exists(
        str(tmp / "aot1" / "bucket_2.f32.stablehlo.quarantine"))
    l2, loaded2 = _store_cycle(tmp / "aot2")
    out["torn_manifest"] = (l2.state, sorted(loaded2),
                            (l2.reason or "").startswith(
                                "unreadable manifest"))

    # -- remote dispatch tier: injected send error -> retry elsewhere -----
    nreg.write("b", "http://b")
    calls = []

    def transport(url, body, timeout_s):
        calls.append(url)
        return 200, {}, OK_BODY

    metrics = MetricsRegistry()
    disp = RemoteDispatcher(nreg, transport=transport, metrics=metrics,
                            snapshot_ttl_s=0.0, sleep=lambda s: None,
                            seed=0, retries=2)
    try:
        res = disp.predict([[1.0]])
    finally:
        disp.shutdown()
    retries = metrics.get_metric("dl4j_cluster_retries_total").get()
    out["remote"] = (res["n"], len(calls), retries)

    # -- broker publish tier: injected ConnectionError -> reconnect -------
    t = TcpTransport(backoff_base_s=0.001, registry=MetricsRegistry())
    t.serve()
    try:
        t.publish("s", b"hello")       # 2 injected drops, then lands
        out["broker"] = (t.poll("s", timeout=2.0), t.reconnects)
    finally:
        t.close()

    sig = plan.replay_signature()
    chaosplan.disarm()
    return out, sig


class TestChaosMatrix:
    def test_tiered_degradation_and_bitwise_replay(self, tmp_path):
        out1, sig1 = _drive_matrix(tmp_path / "r1", seed=42)
        out2, sig2 = _drive_matrix(tmp_path / "r2", seed=42)
        out3, sig3 = _drive_matrix(tmp_path / "r3", seed=43)

        # degradation, tier by tier
        assert out1["torn"] == ("dead", True)       # torn -> dead, never up
        assert out1["healed"] == "alive"            # next beat overwrites
        q, loaded, reasoned = out1["quarantine"]
        assert q == 1 and loaded == [] and reasoned
        state, loaded2, unreadable = out1["torn_manifest"]
        assert state == "mismatch" and loaded2 == [] and unreadable
        n, transport_calls, retries = out1["remote"]
        # first attempt dies on the injected error BEFORE the transport
        # runs; the retry lands on the other node and succeeds
        assert n == 1 and transport_calls == 1 and retries == 1.0
        assert out1["broker"] == (b"hello", 2)

        # bitwise replay: same seed, same driver -> identical trace
        assert sig1 == sig2 and out1 == out2
        assert sig1 != sig3
        kinds = {(s, k) for s, k, _, _ in sig1}
        assert kinds == {
            ("registry.write", "torn_write"),
            ("store.save", "corrupt"), ("store.save", "torn_write"),
            ("remote.send", "error"), ("remote.send", "delay"),
            ("remote.clock", "clock_skew"),
            ("broker.publish", "error"),
        }

    def test_clock_skew_accumulates_on_dispatcher_clock(self, tmp_path):
        _arm("seed=5;remote.clock:clock_skew(skew_ms=5,count=2)")
        base = Clock(100.0)
        disp = RemoteDispatcher(
            NodeRegistry(str(tmp_path / "reg")),
            transport=lambda *a: (200, {}, OK_BODY),
            metrics=MetricsRegistry(), clock=base, sleep=lambda s: None)
        try:
            for _ in range(5):
                disp.clock()
            assert disp.clock() == pytest.approx(100.010)
        finally:
            disp.shutdown()


# ---------------------------------------------------------------------------
# deadlines: parsing
# ---------------------------------------------------------------------------


class TestDeadlineParsing:
    def test_body_beats_header(self):
        clk = Clock()
        d = Deadline.from_ingress({"X-Deadline-Ms": "50"},
                                  {"deadline_ms": 10000}, clock=clk)
        assert d.remaining_s() == pytest.approx(10.0)

    def test_header_only(self):
        clk = Clock()
        d = Deadline.from_ingress({"X-Deadline-Ms": "250"}, {}, clock=clk)
        assert d.remaining_s() == pytest.approx(0.25)

    @pytest.mark.parametrize("raw", ["abc", "-5", "0", "inf", "nan", ""])
    def test_garbage_degrades_to_none(self, raw):
        assert Deadline.from_ingress({"X-Deadline-Ms": raw}, {},
                                     clock=Clock()) is None
        assert Deadline.from_ingress(None, {"deadline_ms": raw},
                                     clock=Clock()) is None

    def test_absent_is_none(self):
        assert Deadline.from_ingress({}, {}, clock=Clock()) is None
        assert Deadline.from_ingress(None, None, clock=Clock()) is None

    def test_cap_timeout(self):
        clk = Clock()
        d = Deadline.after_ms(100, clock=clk)
        assert d.cap_timeout(5.0) == pytest.approx(0.1)
        assert d.cap_timeout(0.05) == pytest.approx(0.05)
        assert d.cap_timeout(None) == pytest.approx(0.1)
        clk.advance(1.0)
        assert d.cap_timeout(5.0) == 0.0

    def test_check_raises_with_detail(self):
        clk = Clock()
        d = Deadline(clk.t - 1.0, clock=clk)
        assert d.expired
        with pytest.raises(DeadlineExceeded, match="too slow"):
            d.check("too slow")
        Deadline(clk.t + 1.0, clock=clk).check()    # no raise


# ---------------------------------------------------------------------------
# deadlines: tier-by-tier synchronous shed
# ---------------------------------------------------------------------------


class TestDeadlineTiers:
    def test_fleet_admission_sheds_expired(self):
        reg = MetricsRegistry()
        router = FleetRouter(registry=reg, max_pending=4)
        pool = ModelPool("m", router, {}, 1, None)
        clk = Clock()
        with pytest.raises(ShedError) as ei:
            pool.admit(Deadline(clk.t - 0.1, clock=clk))
        assert ei.value.reason == "deadline"
        assert pool.pending == 0            # never consumed a slot
        assert reg.get_metric("dl4j_fleet_shed_total").get(
            model="m", reason="deadline") == 1.0
        pool.admit(Deadline(clk.t + 10.0, clock=clk))
        assert pool.pending == 1

    def test_remote_ingress_sheds_before_any_dispatch(self, tmp_path):
        reg = MetricsRegistry()

        def transport(*a):
            raise AssertionError("expired request reached the transport")

        disp = RemoteDispatcher(NodeRegistry(str(tmp_path / "r")),
                                transport=transport, metrics=reg,
                                sleep=lambda s: None)
        clk = Clock()
        try:
            with pytest.raises(DeadlineExceeded):
                disp.predict([[1.0]],
                             deadline=Deadline(clk.t - 1, clock=clk))
        finally:
            disp.shutdown()
        assert reg.get_metric("dl4j_remote_deadline_total").get(
            stage="ingress") == 1.0

    def test_remote_retry_gate_respects_budget(self, tmp_path):
        """A 503 whose Retry-After overshoots the remaining budget must
        504 NOW instead of sleeping into a guaranteed timeout."""
        nreg = NodeRegistry(str(tmp_path / "r"))
        nreg.write("a", "http://a")
        nreg.write("b", "http://b")
        reg = MetricsRegistry()
        clk = Clock()
        disp = RemoteDispatcher(
            nreg, metrics=reg, snapshot_ttl_s=0.0, clock=clk,
            sleep=lambda s: None, seed=0, retries=3,
            transport=lambda *a: (503, {"Retry-After": "30"}, b""))
        try:
            with pytest.raises(DeadlineExceeded, match="budget"):
                disp.predict([[1.0]],
                             deadline=Deadline(clk.t + 1.0, clock=clk))
        finally:
            disp.shutdown()
        assert reg.get_metric("dl4j_remote_deadline_total").get(
            stage="retry") == 1.0

    def test_serving_ingress_sheds_expired(self):
        from deeplearning4j_tpu.parallel.serving import ServingEngine
        reg = MetricsRegistry()
        eng = ServingEngine(_tiny_model(), batch_limit=4,
                            feature_shape=(5,), registry=reg,
                            session_id="chaos-t")
        clk = Clock()
        try:
            with pytest.raises(DeadlineExceeded):
                eng.submit(np.zeros((1, 5), np.float32),
                           deadline=Deadline(clk.t - 1, clock=clk))
        finally:
            eng.shutdown()
        shed = reg.get_metric("dl4j_serving_deadline_shed_total")
        assert sum(v for key, v in shed.series().items()
                   if ("stage", "ingress") in key) == 1.0

    def test_ui_serving_module_maps_deadline_to_504(self):
        from deeplearning4j_tpu.parallel.serving import ServingEngine
        from deeplearning4j_tpu.ui.modules import UIModuleContext
        from deeplearning4j_tpu.ui.serving_module import ServingModule
        eng = ServingEngine(_tiny_model(), batch_limit=4,
                            feature_shape=(5,),
                            registry=MetricsRegistry())
        try:
            mod = ServingModule(eng)
            handler = {r.path: r.handler
                       for r in mod.get_routes()}["/api/predict"]
            ctx = UIModuleContext(storage=None, server=None,
                                  headers={"X-Deadline-Ms": "1e-06"})
            body, hdrs, status = handler(
                ctx, {}, {"features": [[0.0] * 5]})
        finally:
            eng.shutdown()
        assert status == 504
        assert body == {"error": "deadline", "reason": "deadline"}


def _tiny_model(seed: int = 1):
    from deeplearning4j_tpu.models.multi_layer_network import (
        MultiLayerNetwork)
    from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.inputs import InputType
    from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
    from deeplearning4j_tpu.nn.layers.output import OutputLayer
    from deeplearning4j_tpu.ops.losses import LossFunction
    from deeplearning4j_tpu.optimize.updaters import Adam
    conf = (NeuralNetConfiguration.Builder().seed(seed)
            .updater(Adam(1e-2)).list()
            .layer(DenseLayer(n_out=8))
            .layer(OutputLayer(n_out=3, loss=LossFunction.MCXENT))
            .set_input_type(InputType.feed_forward(5)).build())
    return MultiLayerNetwork(conf).init()


# ---------------------------------------------------------------------------
# generation: deadline + client disconnect (satellite 1)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def gen_setup():
    from deeplearning4j_tpu.generation import GenerationEngine
    from deeplearning4j_tpu.zoo.models import TextGenerationLSTM
    m = TextGenerationLSTM()
    m.lstm_units = 16
    m.vocab_size = 31
    m.timesteps = 8
    reg = MetricsRegistry()
    eng = GenerationEngine(m.init(), max_slots=2, registry=reg,
                           session_id="chaos-gen")
    eng.submit([1, 2], max_new_tokens=2,
               greedy=True).result(timeout=120)    # pay the compile once
    yield eng, reg
    eng.shutdown()


class TestGenerationDeadlineAndDisconnect:
    def test_ingress_shed(self, gen_setup):
        eng, reg = gen_setup
        clk = Clock()
        with pytest.raises(DeadlineExceeded):
            eng.submit([1, 2, 3], max_new_tokens=5,
                       deadline=Deadline(clk.t - 1, clock=clk))
        assert reg.get_metric("dl4j_gen_deadline_shed_total").get(
            session="chaos-gen", stage="ingress") == 1.0

    def test_expires_mid_flight(self, gen_setup):
        eng, reg = gen_setup
        s = eng.submit([1, 2, 3], max_new_tokens=5000, greedy=True,
                       deadline=Deadline.after_ms(30))
        res = s.result(timeout=60)
        assert res["reason"] == "deadline"
        m = reg.get_metric("dl4j_gen_deadline_shed_total")
        assert (m.get(session="chaos-gen", stage="queue") or 0.0) \
            + (m.get(session="chaos-gen", stage="decode") or 0.0) >= 1.0

    def test_client_disconnect_cancels_and_counts(self, gen_setup):
        eng, reg = gen_setup
        before = reg.get_metric(
            "dl4j_gen_client_disconnect_total").get(
                session="chaos-gen") or 0.0
        s = eng.submit([3, 4, 5], max_new_tokens=5000, greedy=True)
        assert eng.cancel(s, disconnect=True) in (True, False)
        assert s.result(timeout=60)["reason"] == "cancelled"
        assert reg.get_metric(
            "dl4j_gen_client_disconnect_total").get(
                session="chaos-gen") == before + 1.0
        # a finished stream is NOT a disconnect
        done = eng.submit([1], max_new_tokens=1, greedy=True)
        done.result(timeout=60)
        eng.cancel(done, disconnect=True)
        assert reg.get_metric(
            "dl4j_gen_client_disconnect_total").get(
                session="chaos-gen") == before + 1.0


# ---------------------------------------------------------------------------
# Retry-After hardening (satellite 3)
# ---------------------------------------------------------------------------


class TestRetryAfterHardening:
    def _disp(self, tmp_path, reg):
        return RemoteDispatcher(NodeRegistry(str(tmp_path / "r")),
                                transport=lambda *a: (200, {}, OK_BODY),
                                metrics=reg, sleep=lambda s: None)

    @pytest.mark.parametrize("bad", ["abc", "nan", "inf", "-1", "1e9",
                                     None, [2]])
    def test_malformed_rejected_and_counted(self, tmp_path, bad):
        reg = MetricsRegistry()
        disp = self._disp(tmp_path, reg)
        try:
            assert disp._parse_retry_after(bad) is None
        finally:
            disp.shutdown()
        assert reg.get_metric(
            "dl4j_remote_bad_retry_after_total").get() == 1.0

    @pytest.mark.parametrize("ok,want", [("2.5", 2.5), ("0", 0.0),
                                         (7, 7.0), ("3600", 3600.0)])
    def test_wellformed_accepted(self, tmp_path, ok, want):
        reg = MetricsRegistry()
        disp = self._disp(tmp_path, reg)
        try:
            assert disp._parse_retry_after(ok) == want
        finally:
            disp.shutdown()
        assert reg.get_metric(
            "dl4j_remote_bad_retry_after_total").get() is None

    def test_malformed_header_falls_back_to_backoff(self, tmp_path):
        """One bad node header must not stall the client: the pause
        comes from the backoff curve, not the garbage value."""
        nreg = NodeRegistry(str(tmp_path / "r"))
        nreg.write("a", "http://a")
        nreg.write("b", "http://b")
        answers = iter([(503, {"Retry-After": "garbage"}, b""),
                        (200, {}, OK_BODY)])
        sleeps = []
        reg = MetricsRegistry()
        disp = RemoteDispatcher(
            nreg, transport=lambda *a: next(answers), metrics=reg,
            snapshot_ttl_s=0.0, sleep=sleeps.append, seed=0, retries=2,
            backoff_s=0.05, backoff_max_s=2.0)
        try:
            assert disp.predict([[1.0]])["n"] == 1
        finally:
            disp.shutdown()
        assert reg.get_metric(
            "dl4j_remote_bad_retry_after_total").get() == 1.0
        assert len(sleeps) == 1 and 0.0 < sleeps[0] <= 2.0


# ---------------------------------------------------------------------------
# iterator termination reasons (satellite 2)
# ---------------------------------------------------------------------------


class _ScriptedTransport:
    """Poll answers from a script; a callable entry raises."""

    def __init__(self, script):
        self.script = list(script)

    def poll(self, topic, timeout):
        if not self.script:
            return None
        item = self.script.pop(0)
        if callable(item):
            raise item()
        return item


class TestStreamTermination:
    def test_dead_transport_is_not_a_quiet_topic(self):
        it = StreamingSentenceIterator(
            _ScriptedTransport([b"one",
                                lambda: ConnectionError("broker gone")]),
            poll_timeout_s=0.01)
        assert list(it) == ["one"]
        assert it.termination_reason == "transport_dead"
        assert "broker gone" in it.transport_error

    def test_quiet_topic_idles_out(self):
        it = StreamingSentenceIterator(
            _ScriptedTransport([]), poll_timeout_s=0.01,
            idle_timeout_s=0.0)
        assert list(it) == []
        assert it.termination_reason == "idle_timeout"
        assert it.transport_error is None

    def test_eos_frame(self):
        it = StreamingSentenceIterator(
            _ScriptedTransport([b"a", b""]), poll_timeout_s=0.01)
        assert list(it) == ["a"]
        assert it.termination_reason == "eos"

    def test_max_sentences_and_stop(self):
        it = StreamingSentenceIterator(
            _ScriptedTransport([b"a", b"b", b"c"]),
            poll_timeout_s=0.01, max_sentences=2)
        assert list(it) == ["a", "b"]
        assert it.termination_reason == "max_sentences"
        ev = threading.Event()
        ev.set()
        it2 = StreamingSentenceIterator(
            _ScriptedTransport([b"a"]), poll_timeout_s=0.01,
            stop_event=ev)
        assert list(it2) == []
        assert it2.termination_reason == "stopped"


class TestCorpusTermination:
    def _spool(self, tmp_path, n=3, complete=True):
        store = ArtifactStore(str(tmp_path / "store"))
        w = CorpusShardWriter(store, "corpus", shard_sentences=2)
        for i in range(n):
            w.append(f"sentence {i}")
        if complete:
            w.close()
        else:
            w._seal_shard()
        return store, w

    def test_snapshot_eos(self, tmp_path):
        store, _ = self._spool(tmp_path)
        it = CorpusDataSetIterator(store, "corpus")
        assert len(list(it)) == 3
        assert it.termination_reason == "eos"

    def test_follow_complete(self, tmp_path):
        store, _ = self._spool(tmp_path)
        it = CorpusDataSetIterator(store, "corpus", follow=True,
                                   poll_interval_s=0.01)
        assert len(list(it)) == 3
        assert it.termination_reason == "complete"

    def test_follow_idle_timeout(self, tmp_path):
        store, _ = self._spool(tmp_path, n=2, complete=False)
        it = CorpusDataSetIterator(store, "corpus", follow=True,
                                   poll_interval_s=0.01,
                                   idle_timeout_s=0.03)
        assert len(list(it)) == 2
        assert it.termination_reason == "idle_timeout"
        assert it.store_error is None

    def test_vanished_manifest_is_store_dead(self, tmp_path):
        store, _ = self._spool(tmp_path, n=2, complete=False)
        it = CorpusDataSetIterator(store, "corpus", follow=True,
                                   poll_interval_s=0.01,
                                   idle_timeout_s=10.0)
        g = iter(it)
        got = [next(g), next(g)]             # drain the sealed shard
        os.remove(os.path.join(store.cache_dir("corpus"),
                               "manifest.json"))
        with pytest.raises(StopIteration):
            next(g)
        assert got == ["sentence 0", "sentence 1"]
        assert it.termination_reason == "store_dead"
        assert "vanished" in it.store_error

    def test_unreadable_shard_is_store_dead(self, tmp_path):
        store, w = self._spool(tmp_path, n=2, complete=False)
        it = CorpusDataSetIterator(store, "corpus", follow=True,
                                   poll_interval_s=0.01,
                                   idle_timeout_s=10.0)
        g = iter(it)
        got = [next(g), next(g)]
        w.append("sentence 2")
        w.append("sentence 3")               # seals shard_000001
        os.remove(os.path.join(store.cache_dir("corpus"),
                               "shard_000001.txt"))
        with pytest.raises(StopIteration):
            next(g)
        assert got == ["sentence 0", "sentence 1"]
        assert it.termination_reason == "store_dead"
        assert it.store_error


# ---------------------------------------------------------------------------
# graftlint chaos-hygiene rule (satellite: the contract is enforced)
# ---------------------------------------------------------------------------


def _lint(tmp_path, source, name="snippet.py"):
    from tools.graftlint import get_rules, scan
    f = tmp_path / name
    f.write_text(textwrap.dedent(source), encoding="utf-8")
    return scan([str(f)], rules=get_rules(["chaos-hygiene"]))


class TestChaosHygieneRule:
    def test_plan_import_flagged(self, tmp_path):
        for src in (
                "from deeplearning4j_tpu.chaos import arm\n",
                "from deeplearning4j_tpu.chaos.plan import FaultPlan\n",
                "import deeplearning4j_tpu.chaos.plan\n"):
            findings = _lint(tmp_path, src)
            assert len(findings) == 1
            assert findings[0].rule == "chaos-hygiene"

    def test_extra_hook_import_flagged(self, tmp_path):
        findings = _lint(
            tmp_path,
            "from deeplearning4j_tpu.chaos.hook import chaos_site, os\n")
        assert len(findings) == 1
        assert "os" in findings[0].message

    def test_per_loop_resolution_flagged(self, tmp_path):
        findings = _lint(tmp_path, """
            from deeplearning4j_tpu.chaos.hook import chaos_site

            def f(xs):
                for x in xs:
                    h = chaos_site("remote.send")
        """)
        assert len(findings) == 1
        assert "loop" in findings[0].message

    def test_bind_once_pattern_is_clean(self, tmp_path):
        findings = _lint(tmp_path, """
            from deeplearning4j_tpu.chaos.hook import chaos_site

            class Seam:
                def __init__(self):
                    self._chaos = chaos_site("remote.send")

                def run(self, xs):
                    for x in xs:
                        if self._chaos is not None:
                            self._chaos.fail(arg=x)
        """)
        assert findings == []
