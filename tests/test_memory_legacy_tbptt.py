"""Tests for memory reports (nn/conf/memory analog), legacy convex
optimizers (ConjugateGradient/LBFGS/BackTrackLineSearch), truncated BPTT,
and the extended dataset fetchers (EMNIST/SVHN/CIFAR/LFW/UCI)."""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.fetchers import (
    CifarDataSetIterator,
    EmnistDataSetIterator,
    IrisDataSetIterator,
    LFWDataSetIterator,
    SvhnDataSetIterator,
    UciSequenceDataSetIterator,
)
from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.convolution import (
    ConvolutionLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
from deeplearning4j_tpu.nn.layers.output import OutputLayer, RnnOutputLayer
from deeplearning4j_tpu.nn.layers.recurrent import LSTM, SimpleRnn
from deeplearning4j_tpu.nn.memory import memory_report, xla_memory_analysis
from deeplearning4j_tpu.ops.activations import Activation
from deeplearning4j_tpu.ops.losses import LossFunction
from deeplearning4j_tpu.optimize.legacy import (
    LBFGS,
    BackTrackLineSearch,
    ConjugateGradient,
    optimize_model,
)
from deeplearning4j_tpu.optimize.updaters import Adam, Sgd


def mlp_conf(updater=None):
    return (NeuralNetConfiguration.Builder()
            .seed(1).updater(updater or Adam(1e-2)).list()
            .layer(DenseLayer(n_out=16, activation=Activation.TANH))
            .layer(OutputLayer(n_out=3, loss=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(4)).build())


class TestMemoryReport:
    def test_param_counts_match_model(self):
        conf = mlp_conf()
        rep = memory_report(conf)
        model = MultiLayerNetwork(conf).init()
        assert rep.total_parameters == model.num_params()
        # dense: 4*16+16 = 80; output: 16*3+3 = 51
        assert [r.parameter_count for r in rep.layer_reports] == [80, 51]
        assert [r.activation_elements_per_example
                for r in rep.layer_reports] == [16, 3]

    def test_updater_state_slots(self):
        rep_adam = memory_report(mlp_conf(Adam(1e-3)))
        rep_sgd = memory_report(mlp_conf(Sgd(1e-3)))
        assert all(r.updater_state_slots == 2 for r in rep_adam.layer_reports)
        assert all(r.updater_state_slots == 0 for r in rep_sgd.layer_reports)
        # training bytes: params*(1+1+slots)*4 + 2*acts*batch*4
        r = rep_sgd.layer_reports[0]
        assert r.total_bytes(batch_size=2) == 80 * 2 * 4 + 2 * 16 * 2 * 4

    def test_conv_report_and_json(self):
        conf = (NeuralNetConfiguration.Builder().updater(Adam(1e-3)).list()
                .layer(ConvolutionLayer(n_out=8, kernel_size=(3, 3)))
                .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
                .layer(OutputLayer(n_out=10, loss=LossFunction.MCXENT,
                                   activation=Activation.SOFTMAX))
                .set_input_type(InputType.convolutional(8, 8, 1)).build())
        rep = memory_report(conf)
        assert rep.layer_reports[0].parameter_count == 3 * 3 * 8 + 8
        assert "layers" in rep.to_json()
        assert "NetworkMemoryReport" in str(rep)

    def test_xla_memory_analysis(self):
        model = MultiLayerNetwork(mlp_conf()).init()
        ma = xla_memory_analysis(model, batch_size=4)
        if not ma:  # backend may not expose buffer stats
            pytest.skip("memory_analysis unavailable on this backend")
        assert ma["argument_size_in_bytes"] > 0
        assert ma["total_bytes"] >= ma["argument_size_in_bytes"]

    def test_xla_memory_analysis_train_includes_optimizer(self):
        """train=True must lower the full train step: its argument size
        includes gradients-producing params AND Adam m/v state, so it
        strictly exceeds the forward-only number (ADVICE round 1)."""
        model = MultiLayerNetwork(mlp_conf(Adam(1e-3))).init()
        fwd = xla_memory_analysis(model, batch_size=4, train=False)
        trn = xla_memory_analysis(model, batch_size=4, train=True)
        if not fwd or not trn:
            pytest.skip("memory_analysis unavailable on this backend")
        assert trn["argument_size_in_bytes"] > fwd["argument_size_in_bytes"]
        assert trn["output_size_in_bytes"] > fwd["output_size_in_bytes"]


class TestLegacyOptimizers:
    def _quadratic(self):
        import jax.numpy as jnp
        target = jnp.asarray(np.arange(5, dtype=np.float32))

        def f(p):
            return jnp.sum((p["w"] - target) ** 2)
        return f, {"w": jnp.zeros(5)}

    def test_lbfgs_quadratic(self):
        f, p0 = self._quadratic()
        res = LBFGS(max_iterations=50, tolerance=1e-10).optimize(f, p0)
        assert res.loss < 1e-6
        np.testing.assert_allclose(np.asarray(res.params["w"]),
                                   np.arange(5), atol=1e-3)

    def test_cg_quadratic(self):
        f, p0 = self._quadratic()
        res = ConjugateGradient(max_iterations=50,
                                tolerance=1e-10).optimize(f, p0)
        assert res.loss < 1e-4

    def test_line_search_rejects_ascent(self):
        import jax.numpy as jnp
        ls = BackTrackLineSearch()
        f = lambda x: jnp.sum(x ** 2)
        x = jnp.ones(3)
        g = 2 * x
        # pass an ASCENT direction; search must flip it and still descend
        step, loss, d = ls.search(f, x, float(f(x)), g, g)
        assert step > 0 and loss < float(f(x))
        # returned direction is the flipped (descent) one
        assert float(jnp.vdot(g, d)) < 0

    def test_optimize_model_on_iris(self):
        ds = next(iter(IrisDataSetIterator(150)))
        model = MultiLayerNetwork(mlp_conf()).init()
        before = model.score(ds)
        res = optimize_model(model, ds, algo="lbfgs", max_iterations=30)
        assert res.loss < before * 0.5
        assert model.score(ds) == pytest.approx(res.loss, rel=1e-3)


class TestTbptt:
    def _conf(self, tbptt: bool, cell=LSTM):
        b = (NeuralNetConfiguration.Builder().seed(3).updater(Adam(5e-3))
             .list()
             .layer(cell(n_out=12, activation=Activation.TANH))
             .layer(RnnOutputLayer(n_out=6, loss=LossFunction.MCXENT,
                                   activation=Activation.SOFTMAX))
             .set_input_type(InputType.recurrent(1, 60)))
        if tbptt:
            b = (b.backprop_type("tbptt").tbptt_fwd_length(20)
                 .tbptt_back_length(20))
        return b.build()

    def test_tbptt_chunks_per_batch(self):
        model = MultiLayerNetwork(self._conf(True)).init()
        it = UciSequenceDataSetIterator(32)
        batches = sum(1 for _ in it)
        it.reset()
        model.fit(it, epochs=1)
        # 60-step sequences / 20-step truncation = 3 optimizer steps/batch
        assert int(model.train_state.iteration) == 3 * batches

    def test_tbptt_learns(self):
        model = MultiLayerNetwork(self._conf(True)).init()
        it = UciSequenceDataSetIterator(32)
        model.fit(it, epochs=3)
        ev = model.evaluate(it)
        assert ev.accuracy() > 0.30  # 6 classes, chance ≈ 0.167

    def test_tbptt_simple_rnn(self):
        model = MultiLayerNetwork(self._conf(True, cell=SimpleRnn)).init()
        it = UciSequenceDataSetIterator(16)
        model.fit(it, epochs=1)
        assert np.isfinite(float(model._last_loss))

    def test_tbptt_ragged_tail_trains(self):
        """T=60 with k=25 → chunks 25/25/10: the padded tail chunk must
        still produce an optimizer step (reference doTruncatedBPTT
        processes the final partial chunk; ADVICE round 1)."""
        b = (NeuralNetConfiguration.Builder().seed(3).updater(Adam(5e-3))
             .list()
             .layer(LSTM(n_out=12, activation=Activation.TANH))
             .layer(RnnOutputLayer(n_out=6, loss=LossFunction.MCXENT,
                                   activation=Activation.SOFTMAX))
             .set_input_type(InputType.recurrent(1, 60))
             .backprop_type("tbptt").tbptt_fwd_length(25)
             .tbptt_back_length(25))
        model = MultiLayerNetwork(b.build()).init()
        it = UciSequenceDataSetIterator(32)
        batches = sum(1 for _ in it)
        it.reset()
        model.fit(it, epochs=1)
        # ceil(60/25) = 3 optimizer steps per batch — tail included
        assert int(model.train_state.iteration) == 3 * batches
        assert np.isfinite(float(model._last_loss))

    def test_tbptt_tail_actually_updates_params(self):
        """The tail chunk's step must move parameters: run the first two
        full chunks only (k=25, stop before tail) vs the full fit — the
        LSTM weights must differ."""
        import jax

        b = (NeuralNetConfiguration.Builder().seed(3).updater(Adam(5e-3))
             .list()
             .layer(LSTM(n_out=12, activation=Activation.TANH))
             .layer(RnnOutputLayer(n_out=6, loss=LossFunction.MCXENT,
                                   activation=Activation.SOFTMAX))
             .set_input_type(InputType.recurrent(1, 60))
             .backprop_type("tbptt").tbptt_fwd_length(25)
             .tbptt_back_length(25))
        it = UciSequenceDataSetIterator(32)
        ds = next(iter(it))

        full = MultiLayerNetwork(b.build()).init()
        full._fit_batch(ds)
        assert int(full.train_state.iteration) == 3

        truncated = MultiLayerNetwork(b.build()).init()
        from deeplearning4j_tpu.datasets.dataset import DataSet
        ds50 = DataSet(np.asarray(ds.features)[:, :50],
                       np.asarray(ds.labels)[:, :50])
        truncated._fit_batch(ds50)
        assert int(truncated.train_state.iteration) == 2

        fw = jax.tree_util.tree_leaves(full.train_state.params)
        tw = jax.tree_util.tree_leaves(truncated.train_state.params)
        assert any(not np.allclose(np.asarray(a), np.asarray(b_))
                   for a, b_ in zip(fw, tw))

    def test_standard_backprop_unaffected(self):
        model = MultiLayerNetwork(self._conf(False)).init()
        it = UciSequenceDataSetIterator(32)
        batches = sum(1 for _ in it)
        it.reset()
        model.fit(it, epochs=1)
        assert int(model.train_state.iteration) == batches


class TestExtendedFetchers:
    @pytest.mark.parametrize("it,fshape,lshape", [
        (lambda: EmnistDataSetIterator(8, "LETTERS", subset=32),
         (8, 784), (8, 26)),
        (lambda: EmnistDataSetIterator(8, "DIGITS", subset=32),
         (8, 784), (8, 10)),
        (lambda: SvhnDataSetIterator(8, subset=32), (8, 32, 32, 3), (8, 10)),
        (lambda: CifarDataSetIterator(8, subset=32), (8, 32, 32, 3), (8, 10)),
        (lambda: LFWDataSetIterator(8, num_examples=32), (8, 64, 64, 3),
         (8, 40)),
        (lambda: UciSequenceDataSetIterator(8), (8, 60, 1), (8, 60, 6)),
    ])
    def test_shapes(self, it, fshape, lshape):
        b = next(iter(it()))
        assert b.features.shape == fshape
        assert b.labels.shape == lshape
        assert b.labels.min() >= 0.0 and b.labels.max() <= 1.0

    def test_emnist_unknown_split_rejected(self):
        with pytest.raises(ValueError):
            EmnistDataSetIterator(8, "NOPE")

    def test_uci_classes_separable(self):
        # the six synthetic-control regimes must be distinguishable
        it = UciSequenceDataSetIterator(450, train=True, seed=5)
        b = next(iter(it))
        lab = b.labels[:, 0, :].argmax(-1)
        assert len(np.unique(lab)) == 6


class TestBfloat16Training:
    """Mixed-precision training path (bf16 compute, f32 master params).
    Regression: an uncast output layer or preferred_element_type on conv
    used to leak f32 cotangents into the bf16 backward pass."""

    def test_conv_net_bf16_step(self):
        import jax
        import jax.numpy as jnp
        import jax.random as jrandom
        from deeplearning4j_tpu.nn.layers.convolution import ConvolutionLayer
        from deeplearning4j_tpu.nn.layers.normalization import (
            BatchNormalization)

        conf = (NeuralNetConfiguration.Builder().seed(1).updater(Adam(1e-3))
                .compute_dtype("bfloat16").list()
                .layer(ConvolutionLayer(n_out=4, kernel_size=(3, 3)))
                .layer(BatchNormalization())
                .layer(OutputLayer(n_out=3, loss=LossFunction.MCXENT,
                                   activation=Activation.SOFTMAX))
                .set_input_type(InputType.convolutional(8, 8, 2)).build())
        m = MultiLayerNetwork(conf).init()
        m._train_step = m._build_train_step()
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(4, 8, 8, 2)).astype(np.float32))
        y = np.zeros((4, 3), np.float32)
        y[:, 0] = 1
        ts = m.train_state
        for i in range(3):
            ts, loss = m._train_step(ts, x, jnp.asarray(y), None, None,
                                     jrandom.PRNGKey(i))
        assert np.isfinite(float(loss))
        # master params stay f32
        assert all(l.dtype == jnp.float32
                   for l in jax.tree_util.tree_leaves(ts.params))

    def test_resnet50_bf16_step(self):
        import jax.numpy as jnp
        import jax.random as jrandom
        from deeplearning4j_tpu.zoo.models import ResNet50

        model = ResNet50(num_classes=8, height=32, width=32, channels=3,
                         compute_dtype="bfloat16").init()
        step = model._build_train_step()
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(4, 32, 32, 3)).astype(np.float32))
        y = np.zeros((4, 8), np.float32)
        y[np.arange(4), rng.integers(0, 8, 4)] = 1.0
        ts, loss = step(model.train_state, (x,), (jnp.asarray(y),),
                        None, None, jrandom.PRNGKey(0))
        assert np.isfinite(float(loss))


class TestGravesBidirectionalLSTM:
    def test_trains_and_roundtrips(self, tmp_path):
        from deeplearning4j_tpu.models.serialization import (
            restore_multi_layer_network,
            save_model,
        )
        from deeplearning4j_tpu.nn.layers.recurrent import (
            GravesBidirectionalLSTM)

        conf = (NeuralNetConfiguration.Builder().seed(1)
                .updater(Adam(5e-3)).list()
                .layer(GravesBidirectionalLSTM(n_out=8,
                                               activation=Activation.TANH))
                .layer(RnnOutputLayer(n_out=6, loss=LossFunction.MCXENT,
                                      activation=Activation.SOFTMAX))
                .set_input_type(InputType.recurrent(1, 60)).build())
        m = MultiLayerNetwork(conf).init()
        it = UciSequenceDataSetIterator(16)
        m.fit(it)
        assert np.isfinite(float(m._last_loss))
        # fwd+bwd outputs concatenate: the output layer consumes 2*n_out
        assert m.train_state.params["layer_1"]["W"].shape[0] == 16
        p = str(tmp_path / "gb.zip")
        save_model(m, p)
        m2 = restore_multi_layer_network(p)
        b = next(iter(it))
        np.testing.assert_allclose(np.asarray(m.output(b.features)),
                                   np.asarray(m2.output(b.features)),
                                   rtol=1e-6)
