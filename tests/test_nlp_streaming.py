"""Streaming corpus ingestion for the embedding trainers (ISSUE 13).

The broker -> object store -> trainer pipeline: sentences published on
a Transport topic feed a ``StreamingSentenceIterator``, spool into an
``ArtifactStore`` corpus bucket (``CorpusShardWriter``), and train
``Word2Vec.fit_stream`` in windows — with refreshed embeddings
hot-promoting into a warm ``OnlineServing`` pool with ZERO live
recompiles (the end-to-end soak).

Also the broker backpressure contract: a full bounded topic queue
sheds frames and counts them in ``dl4j_stream_dropped_total{topic}``
instead of wedging the publisher.
"""

import threading
import time

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.corpus import (
    CorpusDataSetIterator,
    CorpusShardWriter,
    spool_stream,
)
from deeplearning4j_tpu.nlp.sentence_iterators import (
    StreamingSentenceIterator,
    publish_sentences,
)
from deeplearning4j_tpu.nlp.word2vec import Word2Vec
from deeplearning4j_tpu.observe.registry import MetricsRegistry
from deeplearning4j_tpu.online import OnlineServing
from deeplearning4j_tpu.parallel.aot_cache import ArtifactStore
from deeplearning4j_tpu.streaming.broker import (
    InProcessTransport,
    TcpTransport,
)

N_IN = 5


def _sentences(rng, n, vocab=30):
    words = [f"w{i}" for i in range(vocab)]
    return [" ".join(rng.choice(words, rng.integers(4, 11)))
            for _ in range(n)]


# ---------------------------------------------------------------------------
# broker-fed sentence iterator
# ---------------------------------------------------------------------------

class TestStreamingSentenceIterator:
    def test_publish_consume_eos(self, rng):
        t = InProcessTransport(registry=MetricsRegistry())
        sents = _sentences(rng, 10)
        assert publish_sentences(t, sents, "s") == 10
        it = StreamingSentenceIterator(t, "s", poll_timeout_s=0.05)
        assert list(it) == sents          # EOS frame terminates
        assert it.consumed == 10

    def test_max_sentences(self, rng):
        t = InProcessTransport(registry=MetricsRegistry())
        publish_sentences(t, _sentences(rng, 20), "s", eos=False)
        it = StreamingSentenceIterator(t, "s", max_sentences=7,
                                       poll_timeout_s=0.05)
        assert len(list(it)) == 7

    def test_idle_timeout(self):
        t = InProcessTransport(registry=MetricsRegistry())
        t.publish("s", b"only one")
        it = StreamingSentenceIterator(t, "s", poll_timeout_s=0.02,
                                       idle_timeout_s=0.1)
        assert list(it) == ["only one"]   # no EOS: idles out

    def test_stop_event(self):
        t = InProcessTransport(registry=MetricsRegistry())
        stop = threading.Event()
        stop.set()
        it = StreamingSentenceIterator(t, "s", stop_event=stop)
        assert list(it) == []


class TestBrokerBackpressure:
    def test_bounded_publish_sheds_and_counts(self):
        reg = MetricsRegistry()
        t = InProcessTransport(max_queue=4, put_timeout_s=0.01,
                               registry=reg)
        for i in range(50):
            t.publish("t", b"m%d" % i)
        assert t.dropped == 46            # 4 queued, the rest shed
        c = reg.counter("dl4j_stream_dropped_total")
        assert c.get(topic="t") == 46.0
        # the queued head survives untouched
        assert t.poll("t", 0.05) == b"m0"


# ---------------------------------------------------------------------------
# object-store corpus shards
# ---------------------------------------------------------------------------

class TestCorpusStore:
    def test_writer_reader_snapshot_reiterates(self, rng, tmp_path):
        store = ArtifactStore(str(tmp_path))
        sents = _sentences(rng, 90)
        w = CorpusShardWriter(store, "corp", shard_sentences=25)
        w.extend(sents)
        w.close()
        m = store.manifest("corp")
        assert m["kind"] == "corpus" and m["complete"]
        assert m["sentences"] == 90 and len(m["shards"]) == 4
        it = CorpusDataSetIterator(store, "corp")
        assert list(it) == sents
        assert list(it) == sents          # snapshot replays (multi-pass)
        assert it.consumed == 180

    def test_spool_stream_roundtrip(self, rng, tmp_path):
        store = ArtifactStore(str(tmp_path))
        t = InProcessTransport(registry=MetricsRegistry())
        sents = _sentences(rng, 30)
        publish_sentences(t, sents, "s")
        src = StreamingSentenceIterator(t, "s", poll_timeout_s=0.05)
        assert spool_stream(src, store, "corp",
                            shard_sentences=8) == 30
        assert list(CorpusDataSetIterator(store, "corp")) == sents

    def test_rejects_foreign_manifest_kind(self, tmp_path):
        store = ArtifactStore(str(tmp_path))
        d = store.cache_dir("notcorpus")
        import json
        import os
        with open(os.path.join(d, "manifest.json"), "w") as f:
            json.dump({"kind": "aot_cache", "buckets": []}, f)
        with pytest.raises(ValueError, match="not a corpus"):
            list(CorpusDataSetIterator(store, "notcorpus"))

    def test_follow_mode_tails_live_writer(self, rng, tmp_path):
        store = ArtifactStore(str(tmp_path))
        sents = _sentences(rng, 120)
        w = CorpusShardWriter(store, "corp", shard_sentences=20)

        def write():
            for s in sents:
                w.append(s)
                time.sleep(0.0005)
            w.close()

        wt = threading.Thread(target=write, daemon=True)
        wt.start()
        got = list(CorpusDataSetIterator(store, "corp", follow=True,
                                         poll_interval_s=0.01))
        wt.join(10)
        assert got == sents               # complete manifest terminates

    def test_follow_mode_idles_out_on_stalled_writer(self, rng,
                                                     tmp_path):
        store = ArtifactStore(str(tmp_path))
        w = CorpusShardWriter(store, "corp", shard_sentences=5)
        w.extend(_sentences(rng, 10))     # 2 sealed shards, NO close
        got = list(CorpusDataSetIterator(store, "corp", follow=True,
                                         poll_interval_s=0.01,
                                         idle_timeout_s=0.1))
        assert len(got) == 10


# ---------------------------------------------------------------------------
# windowed streaming fit
# ---------------------------------------------------------------------------

class TestFitStreamWindows:
    def test_windows_and_fixed_vocab(self, rng):
        # first window builds the vocab; a later window full of unseen
        # words must NOT grow it (stable syn0 geometry is what makes
        # the promotion path recompile-free)
        first = _sentences(rng, 100, vocab=25)
        later = [" ".join(f"zz{i}_{j}" for j in range(6))
                 for i in range(50)]
        seen = []

        def on_window(model, idx, n):
            seen.append((idx, n, model.vocab.num_words(),
                         np.asarray(model.syn0).shape))

        m = Word2Vec(layer_size=8, window_size=2, min_word_frequency=1,
                     epochs=1, seed=7, batch_size=256)
        m.fit_stream(iter(first + later), window_sentences=50,
                     on_window=on_window)
        assert [(i, n) for i, n, _v, _s in seen] == [
            (0, 50), (1, 50), (2, 50)]
        vocabs = {v for _i, _n, v, _s in seen}
        shapes = {s for _i, _n, _v, s in seen}
        assert len(vocabs) == 1 and len(shapes) == 1

    def test_max_windows(self, rng):
        sents = _sentences(rng, 200)
        seen = []
        m = Word2Vec(layer_size=8, window_size=2, min_word_frequency=1,
                     epochs=1, seed=7, batch_size=256)
        m.fit_stream(iter(sents), window_sentences=40, max_windows=2,
                     on_window=lambda _m, i, n: seen.append((i, n)))
        assert seen == [(0, 40), (1, 40)]


# ---------------------------------------------------------------------------
# the end-to-end soak: TCP broker -> spool -> follow-mode corpus ->
# fit_stream -> hot promotion into warm serving, zero live recompiles
# ---------------------------------------------------------------------------

def _tiny_model(seed=1):
    from deeplearning4j_tpu.models.multi_layer_network import (
        MultiLayerNetwork)
    from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.inputs import InputType
    from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
    from deeplearning4j_tpu.nn.layers.output import OutputLayer
    from deeplearning4j_tpu.ops.losses import LossFunction
    from deeplearning4j_tpu.optimize.updaters import Adam
    conf = (NeuralNetConfiguration.Builder().seed(seed)
            .updater(Adam(1e-2)).list()
            .layer(DenseLayer(n_out=8))
            .layer(OutputLayer(n_out=3, loss=LossFunction.MCXENT))
            .set_input_type(InputType.feed_forward(N_IN)).build())
    return MultiLayerNetwork(conf).init()


class TestStreamingSoak:
    def test_tcp_corpus_to_hot_promoted_serving(self, rng, tmp_path):
        n_sent = 200
        server = TcpTransport().serve()
        client = TcpTransport(port=server.port)
        try:
            sents = _sentences(rng, n_sent)
            # unbounded-stream face: the TCP framing can't carry the
            # empty EOS frame, so the reader bounds itself by count
            assert publish_sentences(server, sents, "sentences",
                                     eos=False) == n_sent
            src = StreamingSentenceIterator(
                client, "sentences", poll_timeout_s=0.1,
                max_sentences=n_sent, idle_timeout_s=10.0)
            store = ArtifactStore(str(tmp_path))

            spooled = []
            spool = threading.Thread(
                target=lambda: spooled.append(spool_stream(
                    src, store, "stream-corpus", shard_sentences=50)),
                daemon=True)
            spool.start()

            online = OnlineServing(
                _tiny_model(), InProcessTransport(
                    registry=MetricsRegistry()),
                topic="train", model_name="m", feature_shape=(N_IN,),
                batch_limit=8, registry=MetricsRegistry())
            try:
                windows = []

                def on_window(model, idx, n):
                    windows.append((idx, n))
                    syn0 = np.asarray(model.syn0)
                    params, state = \
                        online.pool.engines[0].committed_host()
                    hits = []

                    def repl(leaf):
                        a = np.asarray(leaf)
                        if a.shape == (N_IN, 8):
                            hits.append(1)
                            return syn0[:N_IN].astype(a.dtype)
                        return a

                    params = jax.tree_util.tree_map(repl, params)
                    assert len(hits) == 1
                    online.promote_params(params, state,
                                          version=f"w2v-{idx}")

                reader = CorpusDataSetIterator(
                    store, "stream-corpus", follow=True,
                    poll_interval_s=0.02, idle_timeout_s=15.0)
                w2v = Word2Vec(layer_size=8, window_size=2,
                               min_word_frequency=1, epochs=1, seed=7,
                               batch_size=256)
                w2v.fit_stream(reader, window_sentences=60,
                               on_window=on_window)
                spool.join(15)
                assert spooled == [n_sent]
                assert store.manifest("stream-corpus")["complete"]
                assert len(windows) >= 3
                assert sum(n for _i, n in windows) == n_sent
                # the last promotion is live and serves
                assert (online.pool.active_version
                        == f"w2v-{windows[-1][0]}")
                params, _state = \
                    online.pool.engines[0].committed_host()
                leaves = [np.asarray(a) for a in
                          jax.tree_util.tree_leaves(params)
                          if np.asarray(a).shape == (N_IN, 8)]
                np.testing.assert_array_equal(
                    leaves[0], np.asarray(w2v.syn0)[:N_IN])
                out = np.asarray(online.output(
                    rng.normal(size=(4, N_IN)).astype(np.float32)))
                assert out.shape == (4, 3)
                assert np.isfinite(out).all()
                # the acceptance gate: every swap was param-only
                online.router.assert_warm()
            finally:
                online.router.shutdown()
        finally:
            client.close()
            server.close()
