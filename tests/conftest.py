"""Test fixture — the analog of the reference's BaseDL4JTest
(deeplearning4j-core/src/test/java/org/deeplearning4j/BaseDL4JTest.java).

All tests run on a virtual 8-device CPU mesh so multi-chip sharding logic is
exercised without TPU hardware (the reference's analog: Spark local[N] +
ParallelWrapper CPU workers, SURVEY §4).

Note: this image ships a TPU PJRT shim that force-selects the 'axon'
platform at interpreter start (its backend dial blocks for minutes when no
chip is attached). ``jax.config.update("jax_platforms", "cpu")`` below runs
before any backend is initialized and wins over the shim, pinning the whole
test session to the virtual CPU mesh.
"""

import os

# Must be set before jax initializes backends: 8 virtual CPU devices.
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    return jax.devices()


@pytest.fixture()
def rng():
    return np.random.default_rng(12345)
