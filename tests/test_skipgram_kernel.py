"""SkipGram device-kernel unit tests: duplicate-row clipping semantics
(the batched-vs-sequential stability deviation documented in
nlp/skipgram.py's module docstring)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nlp import skipgram as sk
from deeplearning4j_tpu.nlp.skipgram import (
    _clipped_scatter,
    _max_row_norm,
    infer_step,
    skipgram_step,
)

_CLIP = jnp.float32(1.0)


def test_unique_rows_match_plain_scatter():
    rng = np.random.default_rng(0)
    table = jnp.asarray(rng.normal(size=(10, 4)).astype(np.float32))
    idx = jnp.asarray([1, 3, 7], np.int32)
    upd = jnp.asarray(rng.normal(0, 0.01, (3, 4)).astype(np.float32))
    got = _clipped_scatter(table, idx, upd, _CLIP)
    ref = table.at[idx].add(upd)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-6, atol=1e-7)


def test_duplicate_rows_sum_below_threshold():
    """Duplicates whose accumulated update stays under the clip sum
    exactly (up to float reassociation)."""
    table = jnp.zeros((4, 3))
    idx = jnp.asarray([2, 2, 2, 1], np.int32)
    upd = jnp.asarray([[0.1, 0, 0], [0.1, 0, 0], [0.1, 0, 0],
                       [0, 0.2, 0]], np.float32)
    got = np.asarray(_clipped_scatter(table, idx, upd, _CLIP))
    np.testing.assert_allclose(got[2], [0.3, 0, 0], rtol=1e-6)
    np.testing.assert_allclose(got[1], [0, 0.2, 0], rtol=1e-6)


def test_duplicate_rows_clip_above_threshold():
    """A row whose accumulated update exceeds the threshold moves by
    exactly the clip norm in the same direction."""
    table = jnp.zeros((4, 3))
    idx = jnp.asarray([0] * 8, np.int32)
    upd = jnp.full((8, 3), 1.0, jnp.float32)   # sum norm = 8*sqrt(3)
    got = np.asarray(_clipped_scatter(table, idx, upd, _CLIP))
    np.testing.assert_allclose(np.linalg.norm(got[0]), float(_CLIP),
                               rtol=1e-5)
    # direction preserved
    np.testing.assert_allclose(got[0] / np.linalg.norm(got[0]),
                               np.ones(3) / np.sqrt(3), rtol=1e-5)
    # untouched rows stay put
    assert np.all(got[1:] == 0)


def test_skipgram_step_stable_on_degenerate_batch():
    """All pairs hitting the same rows with big lr: norms stay bounded
    over many steps instead of running away."""
    syn0 = jnp.asarray(np.random.default_rng(0).normal(
        0, 0.5, (4, 16)).astype(np.float32))
    syn1 = jnp.zeros((4, 16), jnp.float32)
    centers = jnp.zeros((256,), jnp.int32)
    targets = jnp.ones((256, 3), jnp.int32)
    labels = jnp.tile(jnp.asarray([1.0, 0.0, 0.0]), (256, 1))
    mask = jnp.ones((256, 3), jnp.float32)
    for _ in range(50):
        syn0, syn1 = skipgram_step(syn0, syn1, centers, targets, labels,
                                   mask, jnp.float32(0.5))
    n0 = float(jnp.linalg.norm(syn0, axis=1).max())
    n1 = float(jnp.linalg.norm(syn1, axis=1).max())
    assert np.isfinite(n0) and np.isfinite(n1)
    # ≤ init + steps * clip, with lots of slack
    assert n0 < 60 and n1 < 60, (n0, n1)


def test_infer_step_clipped():
    """The single-docvec inference update (worst duplicate case: every
    pair lands on one row) is norm-clipped too."""
    rng = np.random.default_rng(1)
    syn1 = jnp.asarray(rng.normal(0, 5.0, (32, 8)).astype(np.float32))
    docvec = jnp.zeros((8,), jnp.float32)
    targets = jnp.asarray(rng.integers(0, 32, (64, 4)), jnp.int32)
    labels = jnp.zeros((64, 4), jnp.float32).at[:, 0].set(1.0)
    mask = jnp.ones((64, 4), jnp.float32)
    out = infer_step(docvec, syn1, targets, labels, mask,
                     jnp.float32(1.0))
    clip = float(_max_row_norm(jnp.float32(1.0), 8))
    assert float(jnp.linalg.norm(out)) <= clip + 1e-4
    assert np.isfinite(np.asarray(out)).all()


class TestTokenStep:
    """Device-side pair generation (skipgram_token_step)."""

    def test_window1_updates_exactly_neighbor_targets(self):
        """window=1 makes the pair set deterministic: with zero syn1 and
        n_neg over a 1-entry table, exactly the neighbor/negative rows
        move."""
        from deeplearning4j_tpu.nlp.skipgram import skipgram_token_step
        syn0_host = np.random.default_rng(0).normal(
            0, 0.3, (6, 8)).astype(np.float32)
        syn0 = jnp.asarray(syn0_host)   # donated by the step
        syn1 = jnp.zeros((6, 8), jnp.float32)
        tokens = jnp.asarray([[1, 2, 3, 0]], jnp.int32)
        lengths = jnp.asarray([3], jnp.int32)
        table = jnp.asarray([5], jnp.int32)   # all negatives hit row 5
        out0, out1 = skipgram_token_step(
            syn0, syn1, tokens, lengths, table,
            jax.random.PRNGKey(0), jnp.float32(0.1), window=1, n_neg=1)
        changed = np.where(np.abs(np.asarray(out1)).sum(1) > 0)[0]
        # positives: contexts {1,2,3}; negatives: row 5 (or cycled 0 on
        # collision — impossible here since contexts != 5)
        assert set(changed.tolist()) <= {1, 2, 3, 5}
        assert {1, 2, 3} <= set(changed.tolist())
        # step 1 leaves syn0 untouched (zero syn1 → zero dh, as in
        # word2vec.c); step 2 moves exactly the center rows {1,2,3}
        np.testing.assert_array_equal(np.asarray(out0), syn0_host)
        out0b, _ = skipgram_token_step(
            out0, out1, tokens, lengths, table,
            jax.random.PRNGKey(1), jnp.float32(0.1), window=1, n_neg=1)
        d0 = np.abs(np.asarray(out0b) - syn0_host).sum(1)
        assert (d0[[1, 2, 3]] > 0).all()
        assert d0[[0, 4, 5]].sum() == 0.0

    def test_word2vec_token_path_learns_structure(self):
        """End-to-end through Word2Vec with the opt-in device pair
        generation: learns topic structure on the toy corpus."""
        from deeplearning4j_tpu.nlp.word2vec import Word2Vec
        rng = np.random.default_rng(0)
        pools = (["cat", "dog", "pet", "fur", "paw"],
                 ["car", "truck", "road", "wheel", "engine"])
        corpus = [" ".join(rng.choice(pools[rng.random() < 0.5], size=6))
                  for _ in range(150)]
        m = Word2Vec(layer_size=24, window_size=3, epochs=15, negative=4,
                     learning_rate=0.05, seed=7,
                     device_pair_generation=True)
        m.fit(corpus)
        assert m.similarity("cat", "dog") > m.similarity("cat", "truck")
        assert np.isfinite(np.asarray(m.syn0)).all()


class TestSharedNegatives:
    """The round-4 grouped shared-negative kernel vs a naive numpy
    reference of the same math (code-review r4: the default SGNS path
    needs a direct equivalence test, not just corpus-quality checks)."""

    def _numpy_ref(self, syn0, syn1, cen, ctx, negs, nv, lr):
        import numpy as np
        s0, s1 = syn0.copy(), syn1.copy()
        b, d = len(cen), syn0.shape[1]
        g, n_neg = negs.shape
        group = b // g
        sig = lambda x: 1.0 / (1.0 + np.exp(-x))
        dh_all = np.zeros((b, d))
        upd1 = {}          # row -> accumulated syn1 update
        for i in range(b):
            if i >= nv:
                continue
            h = syn0[cen[i]]
            wt = syn1[ctx[i]]
            gp = (1.0 - sig(h @ wt)) * lr
            dh_all[i] += gp * wt
            upd1[ctx[i]] = upd1.get(ctx[i], 0) + gp * h
            for t in negs[i // group]:
                wn = syn1[t]
                gn = -sig(h @ wn) * lr
                dh_all[i] += gn * wn
                upd1[t] = upd1.get(t, 0) + gn * h
        upd0 = {}
        for i in range(b):
            upd0[cen[i]] = upd0.get(cen[i], 0) + dh_all[i]
        for r, u in upd1.items():
            s1[r] += u
        for r, u in upd0.items():
            s0[r] += u
        return s0, s1

    def test_matches_numpy_reference(self):
        rng = np.random.default_rng(5)
        V, D, B, NEG, G = 40, 16, 8, 3, 2
        syn0 = rng.normal(0, 0.1, (V, D)).astype(np.float32)
        syn1 = rng.normal(0, 0.1, (V, D)).astype(np.float32)
        cen = rng.integers(0, V, B).astype(np.int32)
        ctx = rng.integers(0, V, B).astype(np.int32)
        negs = rng.integers(0, V, (G, NEG)).astype(np.int32)
        lr = 0.025     # small: the clip must not bind
        s0r, s1r = self._numpy_ref(syn0, syn1, cen, ctx, negs, B, lr)
        s0, s1 = sk._sg_update_shared(
            jnp.asarray(syn0), jnp.asarray(syn1), jnp.asarray(cen),
            jnp.asarray(ctx), jnp.asarray(negs), jnp.int32(B),
            jnp.float32(lr))
        np.testing.assert_allclose(np.asarray(s0), s0r, rtol=1e-5,
                                   atol=1e-6)
        np.testing.assert_allclose(np.asarray(s1), s1r, rtol=1e-5,
                                   atol=1e-6)

    def test_group_pairing_is_per_group(self):
        """Group g's pairs must see group g's negatives — a wrong
        reshape pairing groups with the wrong centers would move the
        OTHER group's negative rows."""
        rng = np.random.default_rng(6)
        V, D, B, NEG, G = 30, 8, 4, 2, 2
        syn0 = rng.normal(0, 0.1, (V, D)).astype(np.float32)
        syn1 = rng.normal(0, 0.1, (V, D)).astype(np.float32)
        cen = np.array([1, 2, 3, 4], np.int32)
        ctx = np.array([5, 6, 7, 8], np.int32)
        negs = np.array([[10, 11], [20, 21]], np.int32)
        s0, s1 = sk._sg_update_shared(
            jnp.asarray(syn0), jnp.asarray(syn1), jnp.asarray(cen),
            jnp.asarray(ctx), jnp.asarray(negs), jnp.int32(B),
            jnp.float32(0.01))
        s0r, s1r = self._numpy_ref(syn0, syn1, cen, ctx, negs, B, 0.01)
        np.testing.assert_allclose(np.asarray(s1), s1r, rtol=1e-5,
                                   atol=1e-6)
        # the dh side must pair with its OWN group's negatives too
        np.testing.assert_allclose(np.asarray(s0), s0r, rtol=1e-5,
                                   atol=1e-6)

    def test_invalid_rows_inert(self):
        rng = np.random.default_rng(7)
        V, D, B, NEG, G = 20, 8, 4, 2, 1
        syn0 = rng.normal(0, 0.1, (V, D)).astype(np.float32)
        syn1 = rng.normal(0, 0.1, (V, D)).astype(np.float32)
        cen = np.array([1, 2, 3, 4], np.int32)
        ctx = np.array([5, 6, 7, 8], np.int32)
        negs = np.array([[10, 11]], np.int32)
        s0a, s1a = sk._sg_update_shared(
            jnp.asarray(syn0), jnp.asarray(syn1), jnp.asarray(cen),
            jnp.asarray(ctx), jnp.asarray(negs), jnp.int32(2),
            jnp.float32(0.05))
        s0r, s1r = self._numpy_ref(syn0, syn1, cen, ctx, negs, 2, 0.05)
        np.testing.assert_allclose(np.asarray(s0a), s0r, rtol=1e-5,
                                   atol=1e-6)
        np.testing.assert_allclose(np.asarray(s1a), s1r, rtol=1e-5,
                                   atol=1e-6)


def test_slab_push_keeps_lr_decay():
    """A one-slab small corpus must still see the lr anneal from
    learning_rate down — not train wholly at min_learning_rate
    (code-review r4: seen-before-push collapsed the schedule)."""
    from deeplearning4j_tpu.nlp.sequence_vectors import SequenceVectors
    rng = np.random.default_rng(0)
    seqs = [[f"w{t}" for t in rng.integers(0, 50, 40)]
            for _ in range(100)]
    sv = SequenceVectors(layer_size=8, negative=2, min_word_frequency=1,
                         epochs=1, batch_size=256, seed=1)
    sv.build_vocab(seqs)
    sv._init_tables()
    lrs = []
    from deeplearning4j_tpu.nlp import sequence_vectors as svmod
    orig_seal = svmod._PairStream._seal_chunk

    def spy(self):
        lrs.append(float(self.m._lr(self.seen, self.total)))
        return orig_seal(self)
    svmod._PairStream._seal_chunk = spy
    try:
        sv._fit_fast_sgns(seqs, total_words=sum(len(s) for s in seqs))
    finally:
        svmod._PairStream._seal_chunk = orig_seal
    assert lrs[0] > 0.5 * sv.learning_rate, lrs[:3]
    assert lrs[-1] < lrs[0]
