"""SkipGram device-kernel unit tests: duplicate-row clipping semantics
(the batched-vs-sequential stability deviation documented in
nlp/skipgram.py's module docstring)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nlp.skipgram import (
    _MAX_ROW_UPDATE,
    _clipped_scatter,
    infer_step,
    skipgram_step,
)


def test_unique_rows_match_plain_scatter():
    rng = np.random.default_rng(0)
    table = jnp.asarray(rng.normal(size=(10, 4)).astype(np.float32))
    idx = jnp.asarray([1, 3, 7], np.int32)
    upd = jnp.asarray(rng.normal(0, 0.01, (3, 4)).astype(np.float32))
    got = _clipped_scatter(table, idx, upd)
    ref = table.at[idx].add(upd)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-6, atol=1e-7)


def test_duplicate_rows_sum_below_threshold():
    """Duplicates whose accumulated update stays under the clip sum
    exactly (up to float reassociation)."""
    table = jnp.zeros((4, 3))
    idx = jnp.asarray([2, 2, 2, 1], np.int32)
    upd = jnp.asarray([[0.1, 0, 0], [0.1, 0, 0], [0.1, 0, 0],
                       [0, 0.2, 0]], np.float32)
    got = np.asarray(_clipped_scatter(table, idx, upd))
    np.testing.assert_allclose(got[2], [0.3, 0, 0], rtol=1e-6)
    np.testing.assert_allclose(got[1], [0, 0.2, 0], rtol=1e-6)


def test_duplicate_rows_clip_above_threshold():
    """A row whose accumulated update exceeds the threshold moves by
    exactly _MAX_ROW_UPDATE in the same direction."""
    table = jnp.zeros((4, 3))
    idx = jnp.asarray([0] * 8, np.int32)
    upd = jnp.full((8, 3), 1.0, jnp.float32)   # sum norm = 8*sqrt(3)
    got = np.asarray(_clipped_scatter(table, idx, upd))
    np.testing.assert_allclose(np.linalg.norm(got[0]), _MAX_ROW_UPDATE,
                               rtol=1e-5)
    # direction preserved
    np.testing.assert_allclose(got[0] / np.linalg.norm(got[0]),
                               np.ones(3) / np.sqrt(3), rtol=1e-5)
    # untouched rows stay put
    assert np.all(got[1:] == 0)


def test_skipgram_step_stable_on_degenerate_batch():
    """All pairs hitting the same rows with big lr: norms stay bounded
    over many steps instead of running away."""
    syn0 = jnp.asarray(np.random.default_rng(0).normal(
        0, 0.5, (4, 16)).astype(np.float32))
    syn1 = jnp.zeros((4, 16), jnp.float32)
    centers = jnp.zeros((256,), jnp.int32)
    targets = jnp.ones((256, 3), jnp.int32)
    labels = jnp.tile(jnp.asarray([1.0, 0.0, 0.0]), (256, 1))
    mask = jnp.ones((256, 3), jnp.float32)
    for _ in range(50):
        syn0, syn1 = skipgram_step(syn0, syn1, centers, targets, labels,
                                   mask, jnp.float32(0.5))
    n0 = float(jnp.linalg.norm(syn0, axis=1).max())
    n1 = float(jnp.linalg.norm(syn1, axis=1).max())
    assert np.isfinite(n0) and np.isfinite(n1)
    # ≤ init + steps * clip, with lots of slack
    assert n0 < 60 and n1 < 60, (n0, n1)


def test_infer_step_clipped():
    """The single-docvec inference update (worst duplicate case: every
    pair lands on one row) is norm-clipped too."""
    rng = np.random.default_rng(1)
    syn1 = jnp.asarray(rng.normal(0, 5.0, (32, 8)).astype(np.float32))
    docvec = jnp.zeros((8,), jnp.float32)
    targets = jnp.asarray(rng.integers(0, 32, (64, 4)), jnp.int32)
    labels = jnp.zeros((64, 4), jnp.float32).at[:, 0].set(1.0)
    mask = jnp.ones((64, 4), jnp.float32)
    out = infer_step(docvec, syn1, targets, labels, mask,
                     jnp.float32(1.0))
    assert float(jnp.linalg.norm(out)) <= _MAX_ROW_UPDATE + 1e-5
    assert np.isfinite(np.asarray(out)).all()
