"""Pipeline (PP) and mixture-of-experts (EP) parallelism tests.

Both strategies are ABSENT in the reference (SURVEY §2.11 row 7) and
designed fresh; tested on the 8-virtual-device CPU mesh per the
"distributed == single-machine math" golden-test pattern (SURVEY §4:
TestCompareParameterAveragingSparkVsSingleMachine analog).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.parallel.mesh import create_mesh
from deeplearning4j_tpu.parallel.moe import (
    EXPERT_AXIS, moe_ffn, route_top_k, set_default_mesh)
from deeplearning4j_tpu.parallel.pipeline import (
    PIPE_AXIS, pipeline_apply, stack_stage_params)


def _stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def _make_stage_params(key, d, n_stages):
    ks = jax.random.split(key, n_stages)
    return [{"w": jax.random.normal(k, (d, d)) * 0.3,
             "b": jnp.zeros((d,))} for k in ks]


class TestPipeline:
    def test_forward_matches_sequential(self, rng):
        d, batch, n_stages = 16, 32, 4
        mesh = create_mesh({PIPE_AXIS: n_stages}, jax.devices()[:n_stages])
        per_stage = _make_stage_params(jax.random.PRNGKey(0), d, n_stages)
        stacked = stack_stage_params(per_stage)
        x = jnp.asarray(rng.normal(size=(batch, d)).astype(np.float32))

        ref = x
        for p in per_stage:
            ref = _stage_fn(p, ref)

        out = pipeline_apply(_stage_fn, stacked, x, mesh,
                             num_microbatches=8)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_gradients_match_sequential(self, rng):
        """jax.grad through the pipelined region IS the backward pipeline
        (ppermute VJP = reverse permute) — must equal sequential grads."""
        d, batch, n_stages = 8, 16, 4
        mesh = create_mesh({PIPE_AXIS: n_stages}, jax.devices()[:n_stages])
        per_stage = _make_stage_params(jax.random.PRNGKey(1), d, n_stages)
        stacked = stack_stage_params(per_stage)
        x = jnp.asarray(rng.normal(size=(batch, d)).astype(np.float32))

        def loss_pipe(p):
            return jnp.sum(pipeline_apply(_stage_fn, p, x, mesh) ** 2)

        def loss_seq(plist):
            h = x
            for p in plist:
                h = _stage_fn(p, h)
            return jnp.sum(h ** 2)

        g_pipe = jax.grad(loss_pipe)(stacked)
        g_seq = stack_stage_params(
            jax.grad(loss_seq)(per_stage))
        for a, b in zip(jax.tree_util.tree_leaves(g_pipe),
                        jax.tree_util.tree_leaves(g_seq)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)

    def test_microbatch_default_and_validation(self, rng):
        d, n_stages = 4, 2
        mesh = create_mesh({PIPE_AXIS: n_stages}, jax.devices()[:n_stages])
        stacked = stack_stage_params(
            _make_stage_params(jax.random.PRNGKey(2), d, n_stages))
        x = jnp.zeros((6, d))
        out = pipeline_apply(_stage_fn, stacked, x, mesh)  # default m=2
        assert out.shape == (6, d)
        with pytest.raises(ValueError):
            pipeline_apply(_stage_fn, stacked, jnp.zeros((7, d)), mesh,
                           num_microbatches=4)

    def test_circular_schedule_matches_sequential(self, rng):
        """R=2 interleaved stages per device (device d owns stages d and
        S+d): forward + grads must equal the 8-layer sequential stack."""
        d, batch, S, R = 8, 16, 4, 2
        mesh = create_mesh({PIPE_AXIS: S}, jax.devices()[:S])
        per_stage = _make_stage_params(jax.random.PRNGKey(4), d, S * R)
        stacked = stack_stage_params(per_stage, num_devices=S)
        x = jnp.asarray(rng.normal(size=(batch, d)).astype(np.float32))

        def loss_pipe(p):
            return jnp.sum(pipeline_apply(
                _stage_fn, p, x, mesh, repeats=R, num_microbatches=S) ** 2)

        def loss_seq(plist):
            h = x
            for p in plist:
                h = _stage_fn(p, h)
            return jnp.sum(h ** 2)

        np.testing.assert_allclose(float(loss_pipe(stacked)),
                                   float(loss_seq(per_stage)),
                                   rtol=1e-5)
        g_pipe = jax.grad(loss_pipe)(stacked)
        g_seq = stack_stage_params(jax.grad(loss_seq)(per_stage),
                                   num_devices=S)
        for a, b in zip(jax.tree_util.tree_leaves(g_pipe),
                        jax.tree_util.tree_leaves(g_seq)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)
        with pytest.raises(ValueError):
            pipeline_apply(_stage_fn, stacked, x, mesh, repeats=R,
                           num_microbatches=8)

    def test_consts_ride_with_microbatches(self, rng):
        """Per-example side inputs (e.g. masks) are split like the batch
        and delivered to whichever stage processes that microbatch."""
        d, batch, S = 4, 8, 4
        mesh = create_mesh({PIPE_AXIS: S}, jax.devices()[:S])
        per_stage = _make_stage_params(jax.random.PRNGKey(5), d, S)
        stacked = stack_stage_params(per_stage)
        x = jnp.asarray(rng.normal(size=(batch, d)).astype(np.float32))
        scale = jnp.arange(1.0, batch + 1.0)[:, None]

        def fn(p, h, c):
            return jnp.tanh(h @ p["w"] + p["b"]) * c

        out = pipeline_apply(fn, stacked, x, mesh, consts=scale)
        ref = x
        for p in per_stage:
            ref = fn(p, ref, scale)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)


class TestPipelinedTransformerLM:
    def test_pipelined_lm_matches_sequential(self, rng):
        """The real-model upgrade (VERDICT next#6): embed/unembed outside
        the region, TransformerEncoderBlock stages, circular schedule,
        remat on — loss and grads equal the non-pipelined run."""
        from deeplearning4j_tpu.parallel.pipeline import (
            PipelinedTransformerLM)
        S, R = 4, 2
        mesh = create_mesh({PIPE_AXIS: S}, jax.devices()[:S])
        lm = PipelinedTransformerLM(vocab=16, width=8, n_heads=2,
                                    n_layers=S * R, max_len=12, mesh=mesh,
                                    remat=True)
        params = lm.init(jax.random.PRNGKey(0))
        toks = jnp.asarray(rng.integers(0, 16, (8, 10)))
        tgts = jnp.asarray(rng.integers(0, 16, (8, 10)))

        l_pipe, g_pipe = jax.value_and_grad(
            lambda p: lm.loss(p, toks, tgts))(params)
        l_seq, g_seq = jax.value_and_grad(
            lambda p: lm.loss(p, toks, tgts, pipelined=False))(params)

        np.testing.assert_allclose(float(l_pipe), float(l_seq), rtol=1e-5)
        for a, b in zip(jax.tree_util.tree_leaves(g_pipe),
                        jax.tree_util.tree_leaves(g_seq)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-5)

    def test_pipelined_lm_trains(self, rng):
        """A few SGD steps on the pipelined loss reduce it — the train-a-
        small-LM criterion."""
        from deeplearning4j_tpu.parallel.pipeline import (
            PipelinedTransformerLM)
        S = 4
        mesh = create_mesh({PIPE_AXIS: S}, jax.devices()[:S])
        lm = PipelinedTransformerLM(vocab=12, width=8, n_heads=2,
                                    n_layers=S, max_len=8, mesh=mesh)
        params = lm.init(jax.random.PRNGKey(1))
        # learnable sequences: next token = (token + 1) % vocab
        toks = jnp.asarray(rng.integers(0, 12, (16, 7)))
        tgts = (toks + 1) % 12

        @jax.jit
        def step(p):
            l, g = jax.value_and_grad(lm.loss)(p, toks, tgts)
            return jax.tree_util.tree_map(lambda a, b: a - 0.5 * b, p, g), l

        losses = []
        for _ in range(40):
            params, l = step(params)
            losses.append(float(l))
        assert losses[-1] < losses[0] * 0.7, losses


class TestRouting:
    def test_dispatch_combine_shapes_and_bounds(self):
        t, e, k, c = 24, 4, 2, 12
        logits = jax.random.normal(jax.random.PRNGKey(0), (t, e))
        dispatch, combine, aux, z = route_top_k(logits, k, c)
        assert dispatch.shape == (t, e, c)
        assert combine.shape == (t, e, c)
        # each token dispatched to at most k (expert, slot) pairs
        per_token = np.asarray(dispatch.sum((1, 2)))
        assert (per_token <= k + 1e-6).all()
        # each (expert, slot) holds at most one token
        per_slot = np.asarray(dispatch.sum(0))
        assert (per_slot <= 1 + 1e-6).all()
        # combine weights are probabilities
        assert (np.asarray(combine) >= 0).all()
        assert float(combine.sum(-1).sum(-1).max()) <= 1.0 + 1e-5
        assert np.isfinite(float(aux)) and np.isfinite(float(z))

    def test_padding_tokens_not_routed(self):
        """Masked (padding) tokens consume no capacity and don't skew the
        aux statistics (code-review finding: mask-aware routing)."""
        t, e, k, c = 16, 4, 1, 16
        logits = jax.random.normal(jax.random.PRNGKey(3), (t, e))
        tm = jnp.asarray([1.0] * 8 + [0.0] * 8)
        dispatch, combine, aux, _ = route_top_k(logits, k, c, token_mask=tm)
        # padding rows get zero dispatch/combine
        assert float(dispatch[8:].sum()) == 0.0
        assert float(combine[8:].sum()) == 0.0
        # valid rows all dispatched (capacity ample)
        assert float(dispatch[:8].sum()) == 8.0
        # aux equals aux computed on the valid prefix alone
        _, _, aux_ref, _ = route_top_k(logits[:8], k, c)
        np.testing.assert_allclose(float(aux), float(aux_ref), rtol=1e-6)

    def test_capacity_drops_overflow(self):
        # All tokens prefer expert 0 with capacity 2 → only 2 dispatched.
        logits = jnp.tile(jnp.array([[10.0, 0.0]]), (8, 1))
        dispatch, _, _, _ = route_top_k(logits, 1, 2)
        assert float(dispatch[:, 0].sum()) == 2.0


class TestMoE:
    def _params(self, key, d, d_ff, e):
        k1, k2, k3 = jax.random.split(key, 3)
        return dict(
            gate_w=jax.random.normal(k1, (d, e)) * 0.1,
            w_in=jax.random.normal(k2, (e, d, d_ff)) * 0.1,
            b_in=jnp.zeros((e, d_ff)),
            w_out=jax.random.normal(k3, (e, d_ff, d)) * 0.1,
            b_out=jnp.zeros((e, d)),
        )

    def test_output_shape_and_finite(self, rng):
        d, d_ff, e = 8, 16, 4
        p = self._params(jax.random.PRNGKey(0), d, d_ff, e)
        x = jnp.asarray(rng.normal(size=(4, 6, d)).astype(np.float32))
        out = moe_ffn(x, p["gate_w"], p["w_in"], p["b_in"], p["w_out"],
                      p["b_out"], top_k=2)
        assert out.y.shape == (4, 6, d)
        assert np.isfinite(np.asarray(out.y)).all()
        assert float(out.aux_loss) >= 1.0 - 1e-5  # >= 1 by Cauchy-Schwarz

    def test_expert_parallel_matches_unsharded(self, rng):
        """EP golden test: same math with and without the expert mesh."""
        d, d_ff, e = 8, 16, 8
        p = self._params(jax.random.PRNGKey(1), d, d_ff, e)
        x = jnp.asarray(rng.normal(size=(32, d)).astype(np.float32))

        ref = moe_ffn(x, p["gate_w"], p["w_in"], p["b_in"], p["w_out"],
                      p["b_out"], top_k=2)
        mesh = create_mesh({EXPERT_AXIS: 8})
        set_default_mesh(mesh)
        try:
            sharded = jax.jit(lambda xx: moe_ffn(
                xx, p["gate_w"], p["w_in"], p["b_in"], p["w_out"],
                p["b_out"], top_k=2).y)(x)
        finally:
            set_default_mesh(None)
        np.testing.assert_allclose(np.asarray(sharded), np.asarray(ref.y),
                                   rtol=1e-5, atol=1e-5)

    def test_moe_layer_in_network(self, rng):
        """MixtureOfExperts as a first-class layer: train a tiny net, aux
        loss flows into the training loss via layer state."""
        from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.layers.feedforward import (
            DenseLayer, MixtureOfExperts)
        from deeplearning4j_tpu.nn.layers.output import OutputLayer
        from deeplearning4j_tpu.ops.activations import Activation
        from deeplearning4j_tpu.ops.losses import LossFunction
        from deeplearning4j_tpu.models.multi_layer_network import (
            MultiLayerNetwork)
        from deeplearning4j_tpu.nn.inputs import InputType
        from deeplearning4j_tpu.datasets.dataset import DataSet

        conf = (NeuralNetConfiguration.Builder()
                .seed(7)
                .list()
                .layer(DenseLayer(n_out=16, activation=Activation.RELU))
                .layer(MixtureOfExperts(n_out=16, num_experts=4, hidden=32,
                                        top_k=2))
                .layer(OutputLayer(n_out=3,
                                   activation=Activation.SOFTMAX,
                                   loss=LossFunction.MCXENT))
                .set_input_type(InputType.feed_forward(8))
                .build())
        net = MultiLayerNetwork(conf).init()
        x = rng.normal(size=(16, 8)).astype(np.float32)
        idx = rng.integers(0, 3, 16)
        y = np.zeros((16, 3), np.float32)
        y[np.arange(16), idx] = 1.0
        ds = DataSet(x, y)
        net.fit(ds)
        l0 = net.score()
        for _ in range(15):
            net.fit(ds)
        ln = net.score()
        assert np.isfinite(ln) and ln < l0
        out = net.output(x)
        assert out.shape == (16, 3)
