"""ComputationGraph RNN parity: stateful rnn_time_step + TBPTT fit on
graph models (VERDICT r3 missing #1 — reference:
ComputationGraph.java:2720 rnnTimeStep, :955 TBPTT fit,
:2828 rnnClearPreviousState)."""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.models.computation_graph import ComputationGraph
from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
from deeplearning4j_tpu.nn.layers.output import RnnOutputLayer
from deeplearning4j_tpu.nn.layers.recurrent import LSTM, Bidirectional, SimpleRnn
from deeplearning4j_tpu.nn.graph.vertices import MergeVertex
from deeplearning4j_tpu.ops.activations import Activation
from deeplearning4j_tpu.ops.losses import LossFunction
from deeplearning4j_tpu.optimize.updaters import Adam, Sgd

RNG = np.random.default_rng(2720)
F, H, C = 3, 5, 2


def _graph(seed=1, tbptt=False, k=4):
    g = (NeuralNetConfiguration.Builder().seed(seed).updater(Adam(5e-3))
         .graph_builder()
         .add_inputs("in")
         .set_input_types(InputType.recurrent(F)))
    g.add_layer("lstm", LSTM(n_out=H, activation=Activation.TANH), "in")
    g.add_layer("rnn", SimpleRnn(n_out=H, activation=Activation.TANH),
                "lstm")
    g.add_layer("out", RnnOutputLayer(n_out=C, loss=LossFunction.MCXENT,
                                      activation=Activation.SOFTMAX),
                "rnn")
    g.set_outputs("out")
    if tbptt:
        g.backprop_type("tbptt").tbptt_fwd_length(k)
    return ComputationGraph(g.build()).init()


def _mln(seed=1, tbptt=False, k=4):
    b = (NeuralNetConfiguration.Builder().seed(seed).updater(Adam(5e-3))
         .list()
         .layer(LSTM(n_out=H, activation=Activation.TANH))
         .layer(SimpleRnn(n_out=H, activation=Activation.TANH))
         .layer(RnnOutputLayer(n_out=C, loss=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX)))
    if tbptt:
        b = b.backprop_type("tbptt").tbptt_fwd_length(k)
    return MultiLayerNetwork(
        b.set_input_type(InputType.recurrent(F)).build()).init()


def _copy_params_from_mln(cg, mln):
    """Same architecture ⇒ transplant MLN params into the graph (layer
    order matches node order)."""
    import jax.numpy as jnp
    mp = mln.train_state.params
    names_mln = [l.name for l in mln.layers]
    names_cg = ["lstm", "rnn", "out"]
    new = dict(cg.train_state.params)
    for a, b in zip(names_cg, names_mln):
        # real copies: the MLN train step donates its buffers, so views
        # would die at mln.fit()
        new[a] = {k: jnp.array(v, copy=True) for k, v in mp[b].items()}
    cg.train_state = cg.train_state._replace(params=new)
    return cg


def seq_labels(n, t):
    y = np.zeros((n, t, C), np.float32)
    y[np.arange(n)[:, None], np.arange(t)[None, :],
      RNG.integers(0, C, (n, t))] = 1.0
    return y


def test_rnn_time_step_matches_full_sequence_forward():
    cg = _graph()
    n, t = 4, 6
    x = RNG.normal(size=(n, t, F)).astype(np.float32)
    full = np.asarray(cg.output(x))
    cg.rnn_clear_previous_state()
    step_outs = []
    for ti in range(t):
        step_outs.append(np.asarray(cg.rnn_time_step(x[:, ti])))
    streamed = np.stack(step_outs, axis=1)
    np.testing.assert_allclose(streamed, full, rtol=1e-4, atol=1e-5)


def test_rnn_time_step_chunked_multi_step():
    cg = _graph()
    n, t = 3, 8
    x = RNG.normal(size=(n, t, F)).astype(np.float32)
    full = np.asarray(cg.output(x))
    cg.rnn_clear_previous_state()
    a = np.asarray(cg.rnn_time_step(x[:, :5]))
    b = np.asarray(cg.rnn_time_step(x[:, 5:]))
    np.testing.assert_allclose(np.concatenate([a, b], axis=1), full,
                               rtol=1e-4, atol=1e-5)


def test_rnn_time_step_state_is_stored_and_clearable():
    cg = _graph()
    x = RNG.normal(size=(2, F)).astype(np.float32)
    o1 = np.asarray(cg.rnn_time_step(x))
    assert cg.rnn_get_previous_state() is not None
    o2 = np.asarray(cg.rnn_time_step(x))
    assert not np.allclose(o1, o2)          # state advanced
    cg.rnn_clear_previous_state()
    o3 = np.asarray(cg.rnn_time_step(x))
    np.testing.assert_allclose(o1, o3, rtol=1e-5)
    # get/set round-trip
    st = cg.rnn_get_previous_state()
    o4 = np.asarray(cg.rnn_time_step(x))
    cg.rnn_set_previous_state(st)
    o5 = np.asarray(cg.rnn_time_step(x))
    np.testing.assert_allclose(o4, o5, rtol=1e-5)


def test_rnn_time_step_matches_mln():
    mln = _mln(seed=7)
    cg = _copy_params_from_mln(_graph(seed=7), mln)
    n, t = 3, 5
    x = RNG.normal(size=(n, t, F)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(cg.output(x)),
                               np.asarray(mln.output(x)),
                               rtol=1e-4, atol=1e-5)
    out, _ = mln.rnn_time_step(x)
    cg.rnn_clear_previous_state()
    np.testing.assert_allclose(np.asarray(cg.rnn_time_step(x)),
                               np.asarray(out), rtol=1e-4, atol=1e-5)


def test_rnn_time_step_rejects_bidirectional():
    g = (NeuralNetConfiguration.Builder().seed(3).updater(Sgd(0.1))
         .graph_builder().add_inputs("in")
         .set_input_types(InputType.recurrent(F)))
    g.add_layer("bi", Bidirectional(fwd=LSTM(n_out=H)), "in")
    g.add_layer("out", RnnOutputLayer(n_out=C), "bi")
    g.set_outputs("out")
    cg = ComputationGraph(g.build()).init()
    with pytest.raises(ValueError, match="bidirectional"):
        cg.rnn_time_step(RNG.normal(size=(2, F)).astype(np.float32))


def test_tbptt_bidirectional_warns_on_both_model_types():
    """TBPTT chunking silently truncates a bidirectional backward at
    chunk boundaries — both model types must warn (advisor r4)."""
    n, t = 2, 8
    x = RNG.normal(size=(n, t, F)).astype(np.float32)
    y = seq_labels(n, t)
    g = (NeuralNetConfiguration.Builder().seed(3).updater(Sgd(0.1))
         .graph_builder().add_inputs("in")
         .set_input_types(InputType.recurrent(F)))
    g.add_layer("lstm", LSTM(n_out=H), "in")
    g.add_layer("bi", Bidirectional(fwd=LSTM(n_out=H)), "lstm")
    g.add_layer("out", RnnOutputLayer(n_out=C), "bi")
    g.set_outputs("out")
    g.backprop_type("tbptt").tbptt_fwd_length(4)
    cg = ComputationGraph(g.build()).init()
    with pytest.warns(UserWarning, match="bidirectional layer 'bi'"):
        cg.fit(DataSet(x, y))

    mln = MultiLayerNetwork(
        NeuralNetConfiguration.Builder().seed(3).updater(Sgd(0.1))
        .list()
        .layer(LSTM(n_out=H))
        .layer(Bidirectional(fwd=LSTM(n_out=H)))
        .layer(RnnOutputLayer(n_out=C))
        .backprop_type("tbptt").tbptt_fwd_length(4)
        .set_input_type(InputType.recurrent(F)).build()).init()
    with pytest.warns(UserWarning, match="bidirectional layer"):
        mln.fit(DataSet(x, y))


def test_tbptt_fit_trains_graph():
    cg = _graph(tbptt=True, k=4)
    n, t = 8, 12
    x = RNG.normal(size=(n, t, F)).astype(np.float32)
    y = seq_labels(n, t)
    ds = DataSet(x, y)
    s0 = float(cg.score(ds))
    for _ in range(15):
        cg.fit(ds)
    assert float(cg.score(ds)) < s0
    # 12 timesteps / k=4 → 3 chunks per fit call
    assert int(cg.train_state.iteration) == 45


def test_tbptt_ragged_tail_and_masking():
    cg = _graph(tbptt=True, k=5)
    n, t = 4, 7                              # 5 + ragged 2
    x = RNG.normal(size=(n, t, F)).astype(np.float32)
    y = seq_labels(n, t)
    mask = np.ones((n, t), np.float32)
    mask[:, 6:] = 0.0
    ds = DataSet(x, y, features_mask=mask, labels_mask=mask)
    s0 = float(cg.score(ds))
    for _ in range(12):
        cg.fit(ds)
    assert np.isfinite(float(cg.score(ds)))
    assert float(cg.score(ds)) < s0


def test_tbptt_matches_mln_losses():
    """Same params, same data: the CG TBPTT chunk losses must equal the
    MLN TBPTT chunk losses step for step."""
    mln = _mln(seed=11, tbptt=True, k=3)
    cg = _copy_params_from_mln(_graph(seed=11, tbptt=True, k=3), mln)
    n, t = 4, 9
    x = RNG.normal(size=(n, t, F)).astype(np.float32)
    y = seq_labels(n, t)
    ds = DataSet(x, y)
    mln.fit(ds)
    cg.fit(ds)
    np.testing.assert_allclose(float(cg._last_loss),
                               float(mln._last_loss), rtol=1e-4)
    # and after a few more steps they stay in lockstep
    for _ in range(3):
        mln.fit(ds)
        cg.fit(ds)
    np.testing.assert_allclose(float(cg._last_loss),
                               float(mln._last_loss), rtol=1e-3)


def test_wrapped_recurrent_carries_state():
    """A MaskZeroLayer-wrapped LSTM must carry hidden state across
    rnn_time_step calls and TBPTT chunks — the wrapper delegates state to
    its core (code-review r4 finding: wrappers used to run stateless)."""
    from deeplearning4j_tpu.nn.layers.recurrent import MaskZeroLayer
    g = (NeuralNetConfiguration.Builder().seed(9).updater(Adam(5e-3))
         .graph_builder().add_inputs("in")
         .set_input_types(InputType.recurrent(F)))
    g.add_layer("mz", MaskZeroLayer(
        inner=LSTM(n_out=H, activation=Activation.TANH),
        mask_value=-999.0), "in")
    g.add_layer("out", RnnOutputLayer(n_out=C, loss=LossFunction.MCXENT,
                                      activation=Activation.SOFTMAX),
                "mz")
    g.set_outputs("out")
    cg = ComputationGraph(g.build()).init()
    assert [nm for nm, _, _ in cg._recurrent_carry_nodes()] == ["mz"]
    n, t = 3, 6
    x = RNG.normal(size=(n, t, F)).astype(np.float32)
    full = np.asarray(cg.output(x))
    cg.rnn_clear_previous_state()
    streamed = np.stack([np.asarray(cg.rnn_time_step(x[:, ti]))
                         for ti in range(t)], axis=1)
    np.testing.assert_allclose(streamed, full, rtol=1e-4, atol=1e-5)


def test_tbptt_multi_input_static_side_input():
    """A 2-D (static) side input must repeat whole into every chunk."""
    g = (NeuralNetConfiguration.Builder().seed(5).updater(Adam(5e-3))
         .graph_builder()
         .add_inputs("seq", "static")
         .set_input_types(InputType.recurrent(F),
                          InputType.feed_forward(2)))
    g.add_layer("lstm", LSTM(n_out=H, activation=Activation.TANH), "seq")
    g.add_layer("emb", DenseLayer(n_out=H, activation=Activation.TANH),
                "static")
    from deeplearning4j_tpu.nn.graph.vertices import (
        DuplicateToTimeSeriesVertex)
    g.add_vertex("rep", DuplicateToTimeSeriesVertex(), "emb", "seq")
    g.add_vertex("merge", MergeVertex(), "lstm", "rep")
    g.add_layer("out", RnnOutputLayer(n_out=C, loss=LossFunction.MCXENT,
                                      activation=Activation.SOFTMAX),
                "merge")
    g.set_outputs("out")
    g.backprop_type("tbptt").tbptt_fwd_length(4)
    cg = ComputationGraph(g.build()).init()
    n, t = 4, 8
    xs = RNG.normal(size=(n, t, F)).astype(np.float32)
    xst = RNG.normal(size=(n, 2)).astype(np.float32)
    y = seq_labels(n, t)
    mds = MultiDataSet([xs, xst], [y])
    s0 = float(cg.score(mds))
    for _ in range(10):
        cg.fit(mds)
    assert float(cg.score(mds)) < s0
