"""Cluster training masters + threshold gradient compression.

Mirrors the reference's test strategy (SURVEY §4):
- gradient-sharing codecs tested in isolation (reference:
  SharedTrainingAccumulationFunctionTest, ThresholdCompression natives);
- "distributed == single-machine math" golden test (reference:
  TestCompareParameterAveragingSparkVsSingleMachine.java) on the
  in-process 8-device CPU mesh (BaseSparkTest local[N] analog).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.parallel import compression as C
from deeplearning4j_tpu.parallel.cluster import (
    DistributedNetwork,
    ParameterAveragingTrainingMaster,
    SharedTrainingMaster,
    TrainingStats,
)
from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator


# ---------------------------------------------------------------- codecs --

def test_quantize_residual_roundtrip():
    g = jnp.asarray(np.array([0.5, -0.2, 0.01, -0.9, 0.0], np.float32))
    r = jnp.zeros_like(g)
    signs, new_r = C.quantize(g, r, 0.1)
    np.testing.assert_array_equal(np.asarray(signs), [1, -1, 0, -1, 0])
    # transmitted + residual reconstructs the input exactly
    np.testing.assert_allclose(
        np.asarray(signs).astype(np.float32) * 0.1 + np.asarray(new_r),
        np.asarray(g), rtol=1e-6)


def test_residual_accumulates_subthreshold():
    g = jnp.full((4,), 0.04, jnp.float32)
    r = jnp.zeros_like(g)
    for _ in range(2):
        signs, r = C.quantize(g, r, 0.1)
        assert int(np.count_nonzero(np.asarray(signs))) == 0
    signs, r = C.quantize(g, r, 0.1)  # 3rd step: 0.12 > 0.1 fires
    np.testing.assert_array_equal(np.asarray(signs), [1, 1, 1, 1])


@pytest.mark.parametrize("codec", [C.encode_flexible, C.encode_bitmap])
def test_wire_codec_roundtrip(codec, rng):
    signs = rng.choice([-1, 0, 0, 0, 1], size=257).astype(np.int8)
    msg = codec(signs)
    out = C.decode(msg)
    np.testing.assert_array_equal(out, signs)


def test_encode_auto_selects_by_density(rng):
    sparse = np.zeros(1024, np.int8)
    sparse[:10] = 1
    assert int(C.encode(sparse)[0]) == C.FLEXIBLE_ENCODING
    dense = rng.choice([-1, 1], size=1024).astype(np.int8)
    assert int(C.encode(dense)[0]) == C.BITMAP_ENCODING
    # dense sign vectors compress ~16x as 2-bit codes
    assert C.compression_ratio(C.encode(dense), 1024) > 10


def test_threshold_schedule_adapts():
    s = C.ThresholdSchedule(threshold=1e-2, min_threshold=1e-4,
                            threshold_step=2.0, step_trigger=0.05,
                            step_delay=3)
    for _ in range(3):
        s.current()
        s.observe(0.0)   # nothing passed the threshold
    assert s.threshold == pytest.approx(5e-3)
    s.observe(0.5)       # dense round resets the countdown
    assert s._low_count == 0


def test_accumulator_broadcasts_to_peers():
    acc = C.EncodedGradientsAccumulator(n_workers=2)
    grads = {"dense": {"W": jnp.asarray(np.array([[0.5, -0.5]], np.float32)),
                       "b": jnp.asarray(np.array([0.0], np.float32))}}
    acc.store_update(0, grads)
    got = acc.apply_updates(1)
    assert got is not None
    t = acc.schedule.threshold
    np.testing.assert_allclose(np.asarray(got["dense"]["W"]),
                               [[t, -t]], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(got["dense"]["b"]), [0.0])
    # worker 0 must not receive its own update back
    assert acc.apply_updates(0) is None


# ------------------------------------------------------- training masters --

def _mlp_and_data(seed=0, n=64, nin=6, nout=3):
    from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork
    from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.inputs import InputType
    from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
    from deeplearning4j_tpu.nn.layers.output import OutputLayer
    from deeplearning4j_tpu.ops.losses import LossFunction
    from deeplearning4j_tpu.ops.activations import Activation
    from deeplearning4j_tpu.optimize.updaters import Sgd

    conf = (NeuralNetConfiguration.Builder()
            .seed(seed)
            .updater(Sgd(0.1))
            .list()
            .layer(DenseLayer(n_out=16, activation=Activation.TANH))
            .layer(OutputLayer(n_out=nout, loss=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(nin))
            .build())
    net = MultiLayerNetwork(conf).init(seed=seed)

    rng = np.random.default_rng(seed)
    feats = rng.normal(size=(n, nin)).astype(np.float32)
    labels = np.eye(nout, dtype=np.float32)[rng.integers(0, nout, size=n)]
    return net, feats, labels


def test_shared_training_master_fits():
    net, feats, labels = _mlp_and_data()
    it = ListDataSetIterator(
        [DataSet(feats[i:i + 16], labels[i:i + 16]) for i in range(0, 64, 16)])
    master = (SharedTrainingMaster.Builder(threshold=1e-3)
              .workers(8).collect_training_stats(True).build())
    dist = DistributedNetwork(net, master)
    before = net.compute_loss(DataSet(feats, labels))
    dist.fit(it, epochs=3)
    after = net.compute_loss(DataSet(feats, labels))
    assert float(after) < float(before)
    assert dist.stats is not None and len(dist.stats.events) >= 1
    ev = dist.evaluate(it, num_classes=3)
    assert 0.0 <= ev.accuracy() <= 1.0


def test_param_averaging_equals_single_machine():
    """Averaging N workers that each saw identical data must equal one
    single-machine step on that data (the reference's Spark-vs-local
    golden test, TestCompareParameterAveragingSparkVsSingleMachine)."""
    w = 8
    net_d, feats, labels = _mlp_and_data(seed=3, n=8)
    net_s, _, _ = _mlp_and_data(seed=3, n=8)

    # distributed: each worker sees the SAME 8 rows (tile over workers)
    tiled = DataSet(np.tile(feats, (w, 1)), np.tile(labels, (w, 1)))
    master = (ParameterAveragingTrainingMaster.Builder(batch_size_per_worker=8)
              .averaging_frequency(1).workers(w).build())
    DistributedNetwork(net_d, master).fit(
        ListDataSetIterator([tiled]), epochs=1)

    # single machine: one step on the 8 rows
    it = ListDataSetIterator([DataSet(feats, labels)])
    net_s.fit(it, epochs=1)

    pd = jax.tree_util.tree_leaves(net_d.train_state.params)
    ps = jax.tree_util.tree_leaves(net_s.train_state.params)
    for a, b in zip(pd, ps):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_training_stats_timeline(tmp_path):
    st = TrainingStats()
    with st.time("fit split 1"):
        pass
    path = tmp_path / "timeline.html"
    st.export_timeline_html(str(path))
    assert "fit split 1" in path.read_text()
    assert "fit split 1" in st.as_json()
