"""Tests for streaming pub-sub, node2vec, language packs, MagicQueue,
provisioning generation, UI components, and the ML pipeline API."""

import os

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.graph import (
    Graph,
    Node2Vec,
    Node2VecWalkIterator,
)
from deeplearning4j_tpu.ml_pipeline import (
    NetworkEstimator,
    Pipeline,
    StandardScaler,
)
from deeplearning4j_tpu.nlp.language_packs import (
    AnalysisPipeline,
    ChineseTokenizerFactory,
    JapaneseTokenizerFactory,
    KoreanTokenizerFactory,
    SentenceAnnotator,
    UimaSentenceIterator,
    UimaTokenizerFactory,
)
from deeplearning4j_tpu.parallel.magic_queue import MagicQueue
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.streaming import (
    InProcessTransport,
    NDArrayMessage,
    NDArrayStreamingClient,
    Route,
    TcpTransport,
    deserialize_ndarray,
    serialize_ndarray,
)
from deeplearning4j_tpu.ui.components import (
    ChartHistogram,
    ChartLine,
    ChartScatter,
    ComponentTable,
    ComponentText,
    render_html,
)


class TestStreaming:
    def test_serde_roundtrip_dtypes(self, rng):
        for dtype in ("float32", "float64", "int32", "uint8", "bool"):
            a = (rng.normal(size=(3, 4)) * 10).astype(dtype)
            b, ts = deserialize_ndarray(serialize_ndarray(a))
            np.testing.assert_array_equal(a, b)
            assert ts > 0

    def test_serde_rejects_garbage(self):
        with pytest.raises(ValueError):
            deserialize_ndarray(b"NOTMAGIC" + b"\x00" * 32)

    def test_message_key_roundtrip(self, rng):
        a = rng.normal(size=(2, 2)).astype(np.float32)
        m = NDArrayMessage.from_bytes(NDArrayMessage(a, "k9").to_bytes())
        assert m.key == "k9"
        np.testing.assert_array_equal(m.array, a)

    def test_inprocess_pubsub(self, rng):
        c = NDArrayStreamingClient()
        a = rng.normal(size=(4,)).astype(np.float32)
        c.publisher("t").publish(a, key="x")
        msg = c.consumer("t").poll()
        assert msg.key == "x"
        np.testing.assert_array_equal(msg.array, a)
        assert c.consumer("t").poll(timeout=0.05) is None

    def test_route_transform(self, rng):
        c = NDArrayStreamingClient()
        route = (Route(c.transport).from_topic("in")
                 .process(lambda x: x * 2).to_topic("out").start())
        a = rng.normal(size=(3,)).astype(np.float32)
        c.publisher("in").publish(a)
        out = c.consumer("out").poll(timeout=5)
        route.stop()
        np.testing.assert_allclose(out.array, a * 2, rtol=1e-6)

    def test_tcp_transport(self, rng):
        srv = TcpTransport().serve()
        try:
            client = NDArrayStreamingClient(TcpTransport(port=srv.port))
            a = rng.normal(size=(5,)).astype(np.float32)
            client.publisher("x").publish(a, key="remote")
            msg = client.consumer("x").poll(timeout=5)
            assert msg.key == "remote"
            np.testing.assert_array_equal(msg.array, a)
            client.transport.close()
        finally:
            srv.close()


class TestNode2Vec:
    def _two_communities(self):
        edges = [(a, b) for a in range(10) for b in range(a + 1, 10)]
        edges += [(a, b) for a in range(10, 20) for b in range(a + 1, 20)]
        edges.append((9, 10))
        return Graph.from_edges(20, edges)

    def test_walk_shapes(self):
        g = self._two_communities()
        walks = list(Node2VecWalkIterator(g, 10, p=0.5, q=2.0, seed=1))
        assert len(walks) == 20
        assert all(len(w) == 10 for w in walks)
        # walks stay on edges (or self-loop)
        for w in walks:
            for a, b in zip(w, w[1:]):
                assert b in g.get_connected_vertices(a) or b == a

    def test_community_embeddings(self):
        g = self._two_communities()
        n2v = Node2Vec(vector_size=16, walk_length=20, walks_per_vertex=8,
                       window_size=4, seed=3, epochs=3)
        n2v.fit(g)
        assert n2v.similarity("0", "5") > n2v.similarity("0", "15")


class TestLanguagePacks:
    def test_chinese_segmentation(self):
        toks = ChineseTokenizerFactory().create(
            "我们在学习深度神经网络").get_tokens()
        assert "我们" in toks and "学习" in toks and "网络" in toks

    def test_chinese_custom_dictionary(self):
        f = ChineseTokenizerFactory(dictionary={"甲乙丙"})
        assert "甲乙丙" in f.create("甲乙丙丁").get_tokens()

    def test_japanese_scripts(self):
        toks = JapaneseTokenizerFactory().create(
            "私はカタカナとJAXで学習します").get_tokens()
        assert "カタカナ" in toks and "JAX" in toks

    def test_korean_josa_stripping(self):
        toks = KoreanTokenizerFactory().create("나는 학교에 갑니다").get_tokens()
        assert "학교" in toks  # 에 stripped

    def test_uima_pipeline(self):
        toks = UimaTokenizerFactory().create("Hello world. Bye!").get_tokens()
        assert toks == ["Hello", "world.", "Bye!"]
        sents = list(UimaSentenceIterator(["One. Two! Three?"]))
        assert len(sents) == 3
        cas = AnalysisPipeline([SentenceAnnotator()]).process("A. B.")
        spans = cas.select("sentence")
        assert [s.text for s in spans] == ["A.", "B."]


class TestMagicQueue:
    def test_sequential_round_robin(self, rng, devices):
        q = MagicQueue(devices=devices[:2])
        for i in range(4):
            q.add(DataSet(rng.normal(size=(2, 3)).astype(np.float32),
                          rng.normal(size=(2, 1)).astype(np.float32)))
        assert q.size(0) == 2 and q.size(1) == 2
        b = q.poll(0)
        assert b.features.devices() == {devices[0]}
        b = q.poll(1)
        assert b.features.devices() == {devices[1]}

    def test_throughput_replicates(self, rng, devices):
        q = MagicQueue(devices=devices[:3], mode=MagicQueue.THROUGHPUT)
        q.add(DataSet(rng.normal(size=(2, 3)).astype(np.float32),
                      rng.normal(size=(2, 1)).astype(np.float32)))
        assert all(q.size(i) == 1 for i in range(3))

    def test_poll_empty(self, devices):
        q = MagicQueue(devices=devices[:1])
        assert q.poll(0, timeout=0.05) is None


class TestProvisioning:
    def test_bundle(self, tmp_path):
        from deeplearning4j_tpu.provision import (
            TpuClusterSpec, write_provisioning_bundle)
        spec = TpuClusterSpec(name="job1", num_slices=2,
                              env={"FOO": "bar"})
        files = write_provisioning_bundle(spec, str(tmp_path),
                                          "python train.py --steps 10")
        names = {os.path.basename(f) for f in files}
        assert names == {"create_cluster.sh", "launch.sh",
                         "delete_cluster.sh", "gke_jobset.json"}
        create = open(os.path.join(tmp_path, "create_cluster.sh")).read()
        assert "job1-s0" in create and "job1-s1" in create
        launch = open(os.path.join(tmp_path, "launch.sh")).read()
        assert "FOO=bar" in launch and "--worker=all" in launch
        import json
        manifest = json.load(
            open(os.path.join(tmp_path, "gke_jobset.json")))
        assert manifest["spec"]["replicatedJobs"][0]["replicas"] == 2


class TestUIComponents:
    def test_chart_json_and_html(self):
        line = (ChartLine(title="loss")
                .add_series("train", [0, 1, 2], [1.0, 0.5, 0.2]))
        hist = ChartHistogram(title="weights")
        hist.add_bin(-1, 0, 10).add_bin(0, 1, 20)
        scatter = ChartScatter(title="pts").add_series("a", [1, 2], [3, 4])
        table = ComponentTable(header=["k", "v"], rows=[["acc", "0.9"]],
                               title="metrics")
        text = ComponentText(text="hello")
        for c in (line, hist, scatter, table, text):
            d = c.to_dict()
            assert d["componentType"] == c.component_type
        html = render_html([line, hist, scatter, table, text])
        assert "<svg" in html and "polyline" in html and "circle" in html
        assert "<table" in html and "hello" in html

    def test_series_length_mismatch(self):
        with pytest.raises(ValueError):
            ChartLine().add_series("bad", [1, 2], [1])


class TestMlPipeline:
    def test_pipeline_fit_predict(self):
        from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.inputs import InputType
        from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
        from deeplearning4j_tpu.nn.layers.output import OutputLayer
        from deeplearning4j_tpu.ops.activations import Activation
        from deeplearning4j_tpu.ops.losses import LossFunction
        from deeplearning4j_tpu.optimize.updaters import Adam

        rng = np.random.default_rng(0)
        # two shifted gaussian blobs, unscaled features
        X = np.concatenate([rng.normal(0, 1, (80, 4)) * 100,
                            rng.normal(4, 1, (80, 4)) * 100])
        y = np.concatenate([np.zeros(80, int), np.ones(80, int)])
        conf = (NeuralNetConfiguration.Builder().seed(1)
                .updater(Adam(1e-2)).list()
                .layer(DenseLayer(n_out=8, activation=Activation.RELU))
                .layer(OutputLayer(n_out=2, loss=LossFunction.MCXENT,
                                   activation=Activation.SOFTMAX))
                .set_input_type(InputType.feed_forward(4)).build())
        pipe = Pipeline([StandardScaler(),
                         NetworkEstimator(conf, epochs=10, batch_size=32)])
        model = pipe.fit(X, y)
        acc = (model.predict(X) == y).mean()
        assert acc > 0.9
        probs = model.transform(X)
        assert probs.shape == (160, 2)
        np.testing.assert_allclose(probs.sum(-1), 1.0, atol=1e-4)
