"""RemoteDispatcher / CircuitBreaker tests (cluster tier, PR 11).

The contracts under test (parallel/remote.py):

- breaker state machine: closed -> open on N consecutive failures ->
  half-open after the reset window admitting EXACTLY ONE probe ->
  closed on probe success / re-open on probe failure; success resets
  the consecutive-failure count;
- retry goes to a DIFFERENT node, and a request is never double-counted
  in per-node inflight across retries (the least-loaded signal stays
  truthful under failures);
- a 503 (shed/draining) is NOT a breaker failure — the node is alive —
  and its ``Retry-After`` header overrides the backoff curve;
- 4xx is non-retriable (the request is bad, not the node);
- a breaker-open node is excluded from the pick entirely;
- hedged requests: a slow primary gets a duplicate on a different node
  and the first answer wins;
- an empty registry raises ``NoNodesError`` after firing the
  ``on_no_nodes`` demand hook (the scale-from-zero signal).

Everything runs on injected transports/clocks/sleeps — no sockets, no
real time.
"""

import json
import threading

import pytest

from deeplearning4j_tpu.observe.registry import MetricsRegistry
from deeplearning4j_tpu.parallel.node import NodeRegistry
from deeplearning4j_tpu.parallel.remote import (
    CircuitBreaker,
    NoNodesError,
    RemoteDispatcher,
    RemoteError,
)

OK_BODY = json.dumps({"output": [[0.0]], "n": 1}).encode()


class Clock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


class TestCircuitBreaker:
    def test_closed_open_half_open_closed(self):
        clk = Clock()
        br = CircuitBreaker(failure_threshold=3, reset_after_s=5.0,
                            clock=clk)
        assert br.state == "closed" and br.allow()
        br.record_failure()
        br.record_failure()
        assert br.state == "closed"         # under threshold
        br.record_failure()
        assert br.state == "open"
        assert br.opened_total == 1
        assert not br.allow() and not br.would_allow()
        clk.advance(5.0)                    # reset window elapsed
        assert br.would_allow()
        assert br.allow()                   # the half-open probe
        assert br.state == "half_open"
        br.record_success()
        assert br.state == "closed"
        assert br.allow()

    def test_probe_failure_reopens(self):
        clk = Clock()
        br = CircuitBreaker(failure_threshold=1, reset_after_s=2.0,
                            clock=clk)
        br.record_failure()
        assert br.state == "open"
        clk.advance(2.0)
        assert br.allow()
        br.record_failure()                 # the probe failed
        assert br.state == "open"
        assert br.opened_total == 2
        assert not br.allow()               # a fresh reset window

    def test_success_resets_consecutive_count(self):
        br = CircuitBreaker(failure_threshold=3, clock=Clock())
        br.record_failure()
        br.record_failure()
        br.record_success()
        br.record_failure()
        br.record_failure()
        assert br.state == "closed"         # never 3 IN A ROW

    def test_half_open_admits_exactly_one_concurrently(self):
        clk = Clock()
        br = CircuitBreaker(failure_threshold=1, reset_after_s=1.0,
                            clock=clk)
        br.record_failure()
        clk.advance(1.0)
        admitted = []
        barrier = threading.Barrier(8)

        def probe():
            barrier.wait()
            if br.allow():
                admitted.append(threading.get_ident())

        threads = [threading.Thread(target=probe) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(admitted) == 1
        # would_allow is a PEEK: it must not have consumed the slot
        assert not br.would_allow()
        br.record_success()
        assert br.state == "closed"


def _registry(tmp_path, *nodes, stats=None):
    reg = NodeRegistry(str(tmp_path / "reg"))
    for i, nid in enumerate(nodes):
        reg.write(nid, f"http://{nid}",
                  stats=(stats or {}).get(nid, {"pending": 0,
                                                "inflight": 0}))
    return reg


def _node_of(url):
    return url.split("/")[2]


def _dispatcher(reg, transport, **kw):
    kw.setdefault("metrics", MetricsRegistry())
    kw.setdefault("snapshot_ttl_s", 0.0)    # always re-read the gossip
    kw.setdefault("sleep", lambda s: None)
    kw.setdefault("seed", 0)
    return RemoteDispatcher(reg, transport=transport, **kw)


class TestDispatch:
    def test_least_loaded_pick_by_gossip(self, tmp_path):
        reg = _registry(tmp_path, "a", "b", stats={
            "a": {"pending": 5, "inflight": 2},
            "b": {"pending": 0, "inflight": 0}})
        calls = []

        def transport(url, body, timeout):
            calls.append(_node_of(url))
            return 200, {}, OK_BODY

        d = _dispatcher(reg, transport)
        out = d.predict([[1.0]])
        assert out == {"output": [[0.0]], "n": 1}
        assert calls == ["b"]               # the unloaded node

    def test_retry_lands_on_a_different_node(self, tmp_path):
        reg = _registry(tmp_path, "a", "b")
        calls = []

        def transport(url, body, timeout):
            calls.append(_node_of(url))
            if _node_of(url) == "a":
                raise ConnectionError("boom")
            return 200, {}, OK_BODY

        d = _dispatcher(reg, transport, retries=2)
        out = d.predict([[1.0]])
        assert out["n"] == 1
        assert calls == ["a", "b"]          # never a->a
        assert d.inflight() == {}           # fully released

    def test_retry_never_double_counts_inflight(self, tmp_path):
        """The idempotency invariant: during each attempt, exactly that
        node carries exactly one in-flight — the failed attempt's count
        is released BEFORE the retry's increment."""
        reg = _registry(tmp_path, "a", "b")
        seen = []
        holder = {}

        def transport(url, body, timeout):
            seen.append((_node_of(url), dict(holder["d"].inflight())))
            if _node_of(url) == "a":
                raise TimeoutError("slow")
            return 200, {}, OK_BODY

        d = _dispatcher(reg, transport, retries=2)
        holder["d"] = d
        d.predict([[1.0]])
        assert seen == [("a", {"a": 1}), ("b", {"b": 1})]

    def test_503_honors_retry_after_and_spares_breaker(self, tmp_path):
        reg = _registry(tmp_path, "a", "b", stats={
            "a": {"pending": 0, "inflight": 0},
            "b": {"pending": 9, "inflight": 9}})   # a picked first
        sleeps = []

        def transport(url, body, timeout):
            if _node_of(url) == "a":
                return 503, {"Retry-After": "7"}, b'{"error": "shed"}'
            return 200, {}, OK_BODY

        d = _dispatcher(reg, transport, retries=2,
                        sleep=lambda s: sleeps.append(s))
        out = d.predict([[1.0]])
        assert out["n"] == 1
        assert 7.0 in sleeps                # the header, not the curve
        # shedding is NOT a failure: the node answered
        assert d.breaker_state("a") == "closed"

    def test_4xx_is_not_retriable(self, tmp_path):
        reg = _registry(tmp_path, "a", "b")
        calls = []

        def transport(url, body, timeout):
            calls.append(_node_of(url))
            return 400, {}, b'{"error": "bad features"}'

        d = _dispatcher(reg, transport, retries=3)
        with pytest.raises(RemoteError, match="rejected"):
            d.predict([[1.0]])
        assert len(calls) == 1              # no retry can fix a 400
        assert d.breaker_state(calls[0]) == "closed"

    def test_open_breaker_excludes_node_from_pick(self, tmp_path):
        reg = _registry(tmp_path, "a", "b")
        calls = []
        clk = Clock()

        def transport(url, body, timeout):
            calls.append(_node_of(url))
            if _node_of(url) == "a":
                raise ConnectionError("down")
            return 200, {}, OK_BODY

        d = _dispatcher(reg, transport, retries=2, breaker_failures=2,
                        breaker_reset_s=60.0, clock=clk)
        for _ in range(2):                  # trips a's breaker
            d.predict([[1.0]])
        assert d.breaker_state("a") == "open"
        calls.clear()
        d.predict([[1.0]])
        assert calls == ["b"]               # a not even attempted
        # after the reset window the half-open probe goes out again
        clk.advance(60.0)
        calls.clear()
        d.predict([[1.0]])
        assert calls[0] == "a"              # the probe (a sorts first)

    def test_all_nodes_failing_raises_remote_error(self, tmp_path):
        reg = _registry(tmp_path, "a", "b")

        def transport(url, body, timeout):
            raise ConnectionError("down")

        d = _dispatcher(reg, transport, retries=3)
        with pytest.raises(RemoteError) as ei:
            d.predict([[1.0]])
        tried = [n for n, _ in ei.value.attempts]
        assert set(tried) == {"a", "b"}     # both tried, neither twice
        assert len(tried) == 2

    def test_empty_registry_raises_no_nodes_and_signals(self, tmp_path):
        reg = NodeRegistry(str(tmp_path / "reg"))
        demands = []
        d = _dispatcher(reg, lambda *a: (200, {}, OK_BODY),
                        on_no_nodes=lambda: demands.append(1))
        with pytest.raises(NoNodesError):
            d.predict([[1.0]])
        assert demands == [1]               # the scale-from-zero signal

    def test_draining_node_not_dispatched(self, tmp_path):
        reg = _registry(tmp_path, "b")
        reg.write("a", "http://a", state="draining", stats={})
        calls = []

        def transport(url, body, timeout):
            calls.append(_node_of(url))
            return 200, {}, OK_BODY

        d = _dispatcher(reg, transport)
        d.predict([[1.0]])
        assert calls == ["b"]

    def test_hedge_fires_on_slow_primary_and_wins(self, tmp_path):
        import time as _time
        reg = _registry(tmp_path, "a", "b", stats={
            "a": {"pending": 0, "inflight": 0},
            "b": {"pending": 9, "inflight": 9}})   # a is the primary
        release = threading.Event()
        calls = []

        def transport(url, body, timeout):
            calls.append(_node_of(url))
            if _node_of(url) == "a":
                release.wait(5.0)           # a never answers in time
                return 200, {}, json.dumps(
                    {"output": [[1.0]], "n": 1}).encode()
            return 200, {}, OK_BODY

        # real clock/sleep here: hedging is about wall time
        d = RemoteDispatcher(reg, transport=transport,
                             metrics=MetricsRegistry(),
                             snapshot_ttl_s=0.0, hedge_after_s=0.05,
                             seed=0)
        t0 = _time.perf_counter()
        out = d.predict([[1.0]])
        took = _time.perf_counter() - t0
        release.set()
        assert out == {"output": [[0.0]], "n": 1}   # b's (hedge) answer
        assert set(calls) == {"a", "b"}
        assert took < 4.0                   # did NOT wait out the primary
        d.shutdown()
