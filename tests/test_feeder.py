"""Input-pipeline tests: DeviceFeeder prefetch, K-step fused dispatch,
ragged-batch normalization, AsyncDataSetIterator lifecycle, and the
fit() integration contract (bitwise trajectories, zero recompiles, no
new per-step device fetches)."""

import threading
import time

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import (
    DataSet,
    DataSetIterator,
    ListDataSetIterator,
)
from deeplearning4j_tpu.datasets.feeder import (
    DeviceFeeder,
    StagingPool,
    ensure_labels_mask,
    ones_labels_mask,
    pad_to_bucket,
)
from deeplearning4j_tpu.datasets.iterators import (
    AsyncDataSetIterator,
    AsyncShieldDataSetIterator,
)
from deeplearning4j_tpu.observe import (
    MetricsRegistry,
    RecompileWatchdog,
    SpanTracer,
    TelemetryCollector,
)


# ---- shared fixtures ----------------------------------------------------

def _tiny_model(seed=1):
    from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.inputs import InputType
    from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
    from deeplearning4j_tpu.nn.layers.output import OutputLayer
    from deeplearning4j_tpu.models.multi_layer_network import (
        MultiLayerNetwork)
    from deeplearning4j_tpu.ops.losses import LossFunction
    from deeplearning4j_tpu.optimize.updaters import Adam
    conf = (NeuralNetConfiguration.Builder().seed(seed).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=8))
            .layer(OutputLayer(n_out=3, loss=LossFunction.MCXENT))
            .set_input_type(InputType.feed_forward(5)).build())
    return MultiLayerNetwork(conf).init()


def _batches(n, batch=16, seed=0, tail=None):
    """n full batches, optionally followed by one ragged tail batch."""
    rng = np.random.default_rng(seed)
    sizes = [batch] * n + ([tail] if tail else [])
    out = []
    for b in sizes:
        x = rng.normal(size=(b, 5)).astype(np.float32)
        y = np.zeros((b, 3), np.float32)
        y[np.arange(b), rng.integers(0, 3, b)] = 1.0
        out.append(DataSet(x, y))
    return out


def _params(m):
    return jax.device_get(m.train_state.params)


def _assert_params_equal(pa, pb):
    la = jax.tree_util.tree_leaves(pa)
    lb = jax.tree_util.tree_leaves(pb)
    assert len(la) == len(lb)
    for a, b in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class _Recording(ListDataSetIterator):
    """ListDataSetIterator that counts reset() calls."""

    def __init__(self, batches):
        super().__init__(batches)
        self.resets = 0

    def reset(self):
        self.resets += 1


# ---- ragged-batch normalization -----------------------------------------

class TestRaggedNormalization:
    def test_pad_to_bucket_shapes_and_mask(self):
        (b,) = _batches(0, tail=5)
        p = pad_to_bucket(b, 16)
        assert p.features.shape == (16, 5)
        assert p.labels.shape == (16, 3)
        assert p.labels_mask.shape == (16,)
        np.testing.assert_array_equal(p.labels_mask[:5], np.ones(5))
        np.testing.assert_array_equal(p.labels_mask[5:], np.zeros(11))
        # padding duplicates the last real row (finite activations)
        np.testing.assert_array_equal(p.features[5:],
                                      np.repeat(b.features[-1:], 11, 0))

    def test_pad_noop_on_full_batch_keeps_mask_ones(self):
        (b,) = _batches(1)
        p = pad_to_bucket(b, 16)
        assert p.features is b.features
        np.testing.assert_array_equal(p.labels_mask, np.ones(16))

    def test_oversized_batch_rejected(self):
        (b,) = _batches(1)
        with pytest.raises(ValueError):
            pad_to_bucket(b, 8)

    def test_ones_mask_is_masked_mean_identity(self):
        """sum(per * ones)/sum(ones) == mean(per) bitwise — the property
        the whole normalization scheme leans on."""
        from deeplearning4j_tpu.ops.losses import _masked_mean
        import jax.numpy as jnp
        per = jnp.asarray(
            np.random.default_rng(7).normal(size=(16,)).astype(np.float32))
        ones = jnp.ones((16,), jnp.float32)
        assert jax.jit(_masked_mean)(per, ones) == jax.jit(
            lambda p: _masked_mean(p, None))(per)

    def test_padded_loss_matches_unpadded(self):
        """Masked loss of the padded tail equals the raw tail's loss.
        The compiled programs differ (different shapes), so this is a
        tight-tolerance check; the bitwise guarantees live at the
        trajectory level (TestFitIntegration)."""
        m = _tiny_model()
        (tail,) = _batches(0, tail=5)
        raw = float(m.compute_loss(tail))
        padded = float(m.compute_loss(pad_to_bucket(tail, 16)))
        assert raw == pytest.approx(padded, rel=1e-6)

    def test_ensure_labels_mask_sequence_uses_features_mask(self):
        x = np.zeros((2, 4, 5), np.float32)
        y = np.zeros((2, 4, 3), np.float32)
        fm = np.asarray([[1, 1, 0, 0], [1, 1, 1, 0]], np.float32)
        b = ensure_labels_mask(DataSet(x, y, fm, None))
        np.testing.assert_array_equal(b.labels_mask, fm)
        assert ones_labels_mask(DataSet(x, y)).shape == (2, 4)


# ---- DeviceFeeder mechanics ---------------------------------------------

class TestDeviceFeeder:
    def test_ordering_and_exactness(self):
        batches = _batches(4, tail=5)
        feeder = DeviceFeeder(ListDataSetIterator(batches),
                              registry=MetricsRegistry())
        items = list(feeder)
        assert [it.k for it in items] == [1] * 5
        assert [it.n_examples for it in items] == [16, 16, 16, 16, 5]
        for it, b in zip(items, batches):
            np.testing.assert_array_equal(np.asarray(it.features),
                                          b.features)
            np.testing.assert_array_equal(np.asarray(it.labels), b.labels)

    def test_depth_bounded_under_slow_consumer(self):
        """A stalled consumer must not let the feeder stage the whole
        epoch: staged depth stays <= depth (the byte/HBM bound)."""
        feeder = DeviceFeeder(ListDataSetIterator(_batches(10)),
                              depth=2, registry=MetricsRegistry())
        it = iter(feeder)
        next(it)
        time.sleep(0.02)      # consumer stalls; feeder must not run ahead
        for _ in it:
            pass
        assert 1 <= feeder.max_depth_seen <= 2

    def test_byte_budget_limits_depth(self):
        batches = _batches(6)
        per_batch = batches[0].features.nbytes + batches[0].labels.nbytes
        feeder = DeviceFeeder(ListDataSetIterator(batches), depth=4,
                              byte_budget=per_batch,  # room for ~1 batch
                              registry=MetricsRegistry())
        assert len(list(feeder)) == 6
        assert feeder.max_depth_seen <= 2   # 1 staged + 1 in-flight refill

    def test_k_groups_and_split_tail(self):
        """7 batches at K=3 -> two stacked groups + one padded single
        (no dummy optimizer steps for the tail)."""
        feeder = DeviceFeeder(ListDataSetIterator(_batches(6, tail=5)),
                              k_steps=3, registry=MetricsRegistry())
        items = list(feeder)
        assert [it.k for it in items] == [3, 3, 1]
        assert [it.n_examples for it in items] == [48, 48, 5]
        assert items[0].features.shape == (3, 16, 5)
        assert items[0].labels_mask.shape == (3, 16)
        # tail single arrives at the bucket shape with a zeroed pad mask
        assert items[2].features.shape == (16, 5)
        np.testing.assert_array_equal(np.asarray(items[2].labels_mask[5:]),
                                      np.zeros(11))

    def test_group_remainder_pad_repeats_tail(self):
        """'pad' remainder (the AVERAGING-round contract): the short tail
        group is filled by repeating its last batch, repeats counted."""
        feeder = DeviceFeeder(ListDataSetIterator(_batches(4)),
                              k_steps=3, group_remainder="pad",
                              pad_ragged=False,
                              registry=MetricsRegistry())
        items = list(feeder)
        assert [it.k for it in items] == [3, 3]
        # repeats are COUNTED (the round is the unit — matches the old
        # _run_averaging_round accounting)
        assert items[1].n_examples == 48
        np.testing.assert_array_equal(np.asarray(items[1].features[1]),
                                      np.asarray(items[1].features[2]))

    def test_group_prepare_runs_at_k1(self):
        """A group_prepare hook defines the staged LAYOUT (the parallel
        wrapper's stacked (K, B, ...) AVERAGING rounds), so it must run
        even when averaging_frequency == 1 — regression for the raw
        (B, ...) array reaching the stacked-round sharding."""
        calls = []

        def gp(batches):
            calls.append(len(batches))
            return (np.stack([b.features for b in batches]),
                    np.stack([b.labels for b in batches]), None, None)

        feeder = DeviceFeeder(ListDataSetIterator(_batches(3)),
                              k_steps=1, pad_ragged=False,
                              group_prepare=gp, group_remainder="pad",
                              registry=MetricsRegistry())
        items = list(feeder)
        assert calls == [1, 1, 1]
        assert [it.k for it in items] == [1, 1, 1]
        assert items[0].features.shape == (1, 16, 5)

    def test_foreign_objects_pass_through(self):
        marker = object()
        feeder = DeviceFeeder([marker], registry=MetricsRegistry())
        (item,) = list(feeder)
        assert item.k == 0 and item.raw is marker

    def test_gauges_registered_and_set(self):
        reg = MetricsRegistry()
        feeder = DeviceFeeder(ListDataSetIterator(_batches(3)),
                              registry=reg, session_id="t")
        list(feeder)
        assert reg.gauge("dl4j_feed_depth").get(session="t") >= 1.0
        assert reg.gauge("dl4j_etl_stall_ms").get(session="t") >= 0.0

    def test_tracer_spans_emitted(self):
        tracer = SpanTracer()
        feeder = DeviceFeeder(ListDataSetIterator(_batches(3)),
                              tracer=tracer, registry=MetricsRegistry())
        list(feeder)
        names = {e["name"] for e in tracer._events}
        assert {"etl", "host_to_device", "feed_stall"} <= names
        wire = [e for e in tracer._events if e["name"] == "host_to_device"]
        assert all(e["args"]["wire"] for e in wire)
        assert all(e["args"]["bytes"] > 0 for e in wire)

    def test_staging_pool_rotates_and_copies(self):
        pool = StagingPool(2)
        a = np.arange(6, dtype=np.float32).reshape(2, 3)
        b1 = pool.stage(a)
        b2 = pool.stage(a + 1)
        assert b1 is not b2
        np.testing.assert_array_equal(b1, a)
        np.testing.assert_array_equal(b2, a + 1)
        assert pool.stage(a) is b1      # ring wraps

    def test_staging_pool_disabled_on_cpu(self):
        """CPU device_put zero-copy adopts numpy buffers — reusing one
        would corrupt staged batches, so the auto mode disables the
        pool here (this suite runs on the CPU backend)."""
        feeder = DeviceFeeder(ListDataSetIterator(_batches(1)),
                              registry=MetricsRegistry())
        assert feeder._pool is None

    def test_rejects_bad_config(self):
        src = ListDataSetIterator(_batches(1))
        with pytest.raises(ValueError):
            DeviceFeeder(src, depth=0, registry=MetricsRegistry())
        with pytest.raises(ValueError):
            DeviceFeeder(src, k_steps=0, registry=MetricsRegistry())
        with pytest.raises(ValueError):
            DeviceFeeder(src, group_remainder="drop",
                         registry=MetricsRegistry())


# ---- AsyncDataSetIterator lifecycle -------------------------------------

class TestAsyncIterator:
    def test_exactness_and_order(self):
        batches = _batches(8, tail=3)
        got = list(AsyncDataSetIterator(ListDataSetIterator(batches)))
        assert len(got) == 9
        for a, b in zip(got, batches):
            np.testing.assert_array_equal(a.features, b.features)

    def test_reset_joins_worker_before_base_reset(self):
        """The race this PR fixes: reset() during an active pass must
        stop + drain + JOIN the worker before touching the base, so no
        stale batch from the old pass leaks into the new one."""
        base = _Recording(_batches(50, batch=4))
        it = AsyncDataSetIterator(base, queue_size=2)
        gen = iter(it)
        next(gen)                       # worker running, queue full
        worker = it._worker
        assert worker is not None and worker.is_alive()
        it.reset()
        assert not worker.is_alive()    # joined, not abandoned
        assert base.resets == 1
        assert it._worker is None
        fresh = list(it)
        assert len(fresh) == 50
        np.testing.assert_array_equal(fresh[0].features,
                                      base._batches[0].features)

    def test_abandoned_pass_reaps_worker(self):
        it = AsyncDataSetIterator(ListDataSetIterator(
            _batches(50, batch=4)), queue_size=2)
        gen = iter(it)
        next(gen)
        gen.close()                     # consumer breaks out early
        assert it._worker is None
        deadline = time.time() + 2.0
        while threading.active_count() > 0 and time.time() < deadline:
            if all(not t.name.startswith("Thread-") or not t.is_alive()
                   for t in threading.enumerate()
                   if t is not threading.main_thread()):
                break
            time.sleep(0.01)

    def test_worker_error_propagates(self):
        class Boom(DataSetIterator):
            def __iter__(self):
                yield _batches(1)[0]
                raise RuntimeError("bad shard")

        with pytest.raises(RuntimeError, match="bad shard"):
            list(AsyncDataSetIterator(Boom()))

    def test_two_sequential_passes(self):
        it = AsyncDataSetIterator(ListDataSetIterator(_batches(5)))
        assert len(list(it)) == 5
        assert len(list(it)) == 5


# ---- fit() integration ---------------------------------------------------

class TestFitIntegration:
    def test_fed_k1_bitwise_equals_unfed(self):
        """The headline acceptance: the fed path (prefetch + staged
        dispatch) replays the exact unfed trajectory bit for bit,
        ragged final batch included."""
        batches = _batches(6, tail=5)
        m_fed = _tiny_model()
        m_ref = _tiny_model()
        m_fed.fit(ListDataSetIterator(batches), epochs=2)
        m_ref.fit(ListDataSetIterator(batches), epochs=2, prefetch=0)
        _assert_params_equal(_params(m_fed), _params(m_ref))
        assert float(m_fed.score()) == float(m_ref.score())

    def test_fused_ksteps_bitwise_equals_per_batch(self):
        """fit(k_steps=3) over the raw ragged stream must replay the
        per-batch trajectory over the bucket-normalized stream bitwise
        (the normalization itself is loss-neutral, see
        TestRaggedNormalization; XLA compiles masked and mask-free
        programs differently, so the bitwise comparison normalizes
        both sides)."""
        batches = _batches(6, tail=5)
        normalized = [pad_to_bucket(b, 16) for b in batches]
        m_fused = _tiny_model()
        m_ref = _tiny_model()
        m_fused.fit(ListDataSetIterator(batches), epochs=2, k_steps=3)
        m_ref.fit(ListDataSetIterator(normalized), epochs=2, prefetch=0)
        _assert_params_equal(_params(m_fused), _params(m_ref))

    def test_fused_listener_semantics(self):
        """Iteration advances by K per dispatch; listeners see the
        group's REAL example count (48 for full groups, 5 for the
        ragged tail dispatched as a bucket-shaped single)."""
        from deeplearning4j_tpu.optimize.listeners import (
            ScoreIterationListener)

        class Spy(ScoreIterationListener):
            rows = []

            def iteration_done(self, model, iteration, epoch, loss,
                               etl_ms, n_examples):
                self.rows.append((iteration, n_examples))

        m = _tiny_model()
        spy = Spy(frequency=1)
        m.set_listeners(spy)
        m.fit(ListDataSetIterator(_batches(6, tail=5)), k_steps=3)
        assert spy.rows == [(3, 48), (6, 48), (7, 5)]

    def test_zero_recompiles_across_ragged_epochs(self):
        """The watchdog acceptance: two epochs with a partial final
        batch at k_steps=3 compile exactly one signature per step key —
        zero recompiles (the ragged tail used to cost one per epoch)."""
        wd = RecompileWatchdog(registry=MetricsRegistry())
        m = _tiny_model()
        m.set_recompile_watchdog(wd)
        m.fit(ListDataSetIterator(_batches(6, tail=5)), epochs=2,
              k_steps=3)
        assert wd.count() == 0

    def test_zero_recompiles_k1_padded(self):
        """Same property on the K=1 fed path when bucket padding is on
        explicitly (pad_ragged defaults off at K=1, where the tail's
        own signature is the first and only one for its shape... so
        instead: unpadded K=1 costs exactly the one tail signature)."""
        wd = RecompileWatchdog(registry=MetricsRegistry())
        m = _tiny_model()
        m.set_recompile_watchdog(wd)
        m.fit(ListDataSetIterator(_batches(6, tail=5)), epochs=2)
        # full-batch sig is free; the ragged tail adds ONE signature
        # total (not one per epoch)
        assert wd.count("train_step") == 1

    def test_shield_opts_out_of_feeder(self, monkeypatch):
        import deeplearning4j_tpu.datasets.feeder as feeder_mod
        built = []
        real = feeder_mod.DeviceFeeder

        def spy(*a, **k):
            built.append(1)
            return real(*a, **k)

        monkeypatch.setattr(feeder_mod, "DeviceFeeder", spy)
        batches = _batches(3)
        m = _tiny_model()
        m.fit(AsyncShieldDataSetIterator(ListDataSetIterator(batches)))
        assert not built                  # shield -> strictly sync loop
        m.fit(ListDataSetIterator(batches))
        assert built                      # plain iterator -> fed

    def test_ksteps_require_feeder(self):
        m = _tiny_model()
        shield = AsyncShieldDataSetIterator(
            ListDataSetIterator(_batches(3)))
        with pytest.raises(ValueError):
            m.fit(shield, k_steps=2)
        with pytest.raises(ValueError):
            m.fit(ListDataSetIterator(_batches(3)), k_steps=2, prefetch=0)

    def test_source_reset_per_epoch(self):
        base = _Recording(_batches(3))
        m = _tiny_model()
        m.fit(base, epochs=3)
        assert base.resets == 3

    def test_no_new_per_step_device_fetch(self, monkeypatch):
        """The one-fetch telemetry contract survives the fed + fused
        path: 12 inner steps at flush_interval=4 -> exactly 4 host
        transfers (3 interval flushes + the tail flush) — the same
        count the unfed loop performs (test_observe), so the feeder
        and the scan dispatch added NO new per-step fetch."""
        fetches = []
        real = jax.device_get

        def counting(x):
            fetches.append(x)
            return real(x)

        m = _tiny_model()
        tel = TelemetryCollector(flush_interval=4,
                                 registry=MetricsRegistry())
        m.set_telemetry(tel)
        monkeypatch.setattr(jax, "device_get", counting)
        m.fit(ListDataSetIterator(_batches(12)), k_steps=4)
        monkeypatch.setattr(jax, "device_get", real)
        assert tel.fetch_count == 4
        assert len(fetches) == 4
        assert [r["iteration"] for r in tel.history] == list(range(1, 13))
