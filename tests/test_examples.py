"""Smoke tier for examples/ — every walkthrough must run to rc=0.

Each example is launched as a subprocess with DL4J_EXAMPLE_SMOKE=1
(examples shrink shapes/step counts and skip interactive waits — see
examples/_bootstrap.sized). Marked slow: excluded from the tier-1
``-m 'not slow'`` run; invoke via ``./runtests.sh --examples``.
"""

import os
import subprocess
import sys

import jax
import pytest

EXAMPLES_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "examples")

EXAMPLES = sorted(
    f for f in os.listdir(EXAMPLES_DIR)
    if f.endswith(".py") and not f.startswith("_"))


def _needs_keras(name: str) -> bool:
    return name in ("keras_import_finetune.py", "custom_keras_layer.py")


@pytest.mark.slow
@pytest.mark.parametrize("name", EXAMPLES)
def test_example_runs(name):
    if name == "three_d_parallelism.py" and not hasattr(jax, "shard_map"):
        pytest.skip("partial-auto shard_map needs jax>=0.5 "
                    "(see tests/test_3d_parallel.py)")
    if _needs_keras(name):
        pytest.importorskip("keras")
    env = dict(os.environ)
    env["DL4J_EXAMPLE_SMOKE"] = "1"
    # examples choose their own mesh via _bootstrap.pin_cpu_mesh; drop
    # the test session's 8-device XLA_FLAGS so they start clean
    env.pop("XLA_FLAGS", None)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(EXAMPLES_DIR, name)],
        capture_output=True, text=True, timeout=600, env=env)
    assert proc.returncode == 0, (
        f"{name} exited rc={proc.returncode}\n"
        f"--- stdout ---\n{proc.stdout[-4000:]}\n"
        f"--- stderr ---\n{proc.stderr[-4000:]}")
