"""Shadow-cast scan train step: trajectory equivalence.

``make_scan_train_step(shadow_cast=...)`` carries a bf16 copy of the
parameters through the scan so the forward/backward consume the shadow
instead of re-casting every f32 master at the top of each step. The
design claim (solver.py docstring) is that numerics are UNCHANGED —
the values the matmuls see are bit-identical either way: the model's
internal ``cast_params`` is an identity on already-bf16 leaves, and
the cast's VJP is exactly the ``astype`` back to master dtype that the
shadow path applies to its gradients. These tests pin that claim.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from deeplearning4j_tpu.models.base import cast_params
from deeplearning4j_tpu.optimize.solver import TrainState, make_scan_train_step


def _make_problem(rng, dtype="bfloat16"):
    """Tiny two-layer net whose loss casts params internally — the
    same shape as MultiLayerNetwork._forward's per-layer cast_params
    call, which the shadow is designed to make a no-op."""
    params = {
        "dense": {"W": jnp.asarray(rng.normal(size=(6, 8)) * 0.3,
                                   jnp.float32),
                  "b": jnp.zeros((8,), jnp.float32)},
        "out": {"W": jnp.asarray(rng.normal(size=(8, 3)) * 0.3,
                                 jnp.float32),
                "b": jnp.zeros((3,), jnp.float32)},
    }

    def loss_fn(p, mstate, feats, labels, fmask, lmask, rng_, it):
        x = feats.astype(dtype)
        for name in ("dense", "out"):
            lp = cast_params(p[name], dtype)
            x = jnp.tanh(x @ lp["W"] + lp["b"])
        loss = jnp.mean((x.astype(jnp.float32) - labels) ** 2)
        return loss, mstate

    return params, loss_fn


def _run(loss_fn, params, shadow_cast, k=5, donate=False):
    tx = optax.adam(1e-2)
    ts = TrainState(params, {}, tx.init(params), jnp.zeros((), jnp.int32))
    steps_fn = make_scan_train_step(loss_fn, tx, donate=donate,
                                    shadow_cast=shadow_cast)
    rng = np.random.default_rng(0)
    feats = jnp.asarray(rng.normal(size=(k, 4, 6)), jnp.float32)
    labels = jnp.asarray(rng.normal(size=(k, 4, 3)), jnp.float32)
    new_ts, losses = steps_fn(ts, feats, labels,
                              jnp.zeros((k, 1)), jnp.zeros((k, 1)),
                              jax.random.PRNGKey(0))
    return new_ts, losses


def test_shadow_trajectory_bitwise_matches_plain():
    rng = np.random.default_rng(3)
    params, loss_fn = _make_problem(rng)
    ts_plain, losses_plain = _run(loss_fn, params, shadow_cast=None)
    ts_shadow, losses_shadow = _run(
        loss_fn, params, shadow_cast=lambda p: cast_params(p, "bfloat16"))

    np.testing.assert_array_equal(np.asarray(losses_plain),
                                  np.asarray(losses_shadow))
    for (kp, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(ts_plain.params),
            jax.tree_util.tree_leaves_with_path(ts_shadow.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=str(kp))


def test_shadow_params_stay_master_precision():
    rng = np.random.default_rng(4)
    params, loss_fn = _make_problem(rng)
    ts, losses = _run(loss_fn, params,
                      shadow_cast=lambda p: cast_params(p, "bfloat16"))
    assert losses.shape == (5,)
    for leaf in jax.tree_util.tree_leaves(ts.params):
        assert leaf.dtype == jnp.float32
    assert int(ts.iteration) == 5


def test_shadow_with_donation_runs():
    rng = np.random.default_rng(5)
    params, loss_fn = _make_problem(rng)
    ts, losses = _run(loss_fn, params,
                      shadow_cast=lambda p: cast_params(p, "bfloat16"),
                      donate=True)
    assert np.isfinite(np.asarray(losses)).all()
