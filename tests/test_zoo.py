"""Zoo model instantiation + tiny-training tests.

Analog of deeplearning4j-zoo's TestInstantiation (SURVEY §4) — instantiate
each zoo model, check shapes, run a step. Full-size nets are built at
reduced input sizes to keep CI fast; topology code paths are identical.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import ArrayDataSetIterator, DataSet
from deeplearning4j_tpu.zoo.models import (
    AlexNet,
    LeNet,
    ResNet50,
    SimpleCNN,
    TextGenerationLSTM,
    VGG16,
)


def onehot(idx, n):
    out = np.zeros((len(idx), n), np.float32)
    out[np.arange(len(idx)), idx] = 1.0
    return out


def test_lenet_shapes_and_training():
    model = LeNet(num_classes=10).init()
    assert model.num_params() == 431080
    rng = np.random.default_rng(0)
    x = rng.normal(size=(16, 784)).astype(np.float32)
    y = model.output(x)
    assert y.shape == (16, 10)
    np.testing.assert_allclose(np.asarray(y.sum(-1)), 1.0, rtol=1e-4)


def test_simplecnn_instantiates():
    model = SimpleCNN(num_classes=5, height=32, width=32).init()
    x = np.random.default_rng(0).normal(size=(2, 32, 32, 3)).astype(np.float32)
    assert model.output(x).shape == (2, 5)


def test_resnet50_topology():
    model = ResNet50(num_classes=10, height=64, width=64).init()
    # 3+4+6+3 = 16 bottleneck blocks, 53 conv layers (48 in blocks + 4 ds + 1 stem)
    conv_nodes = [n for n in model.conf.nodes
                  if n.layer is not None and n.name.endswith("_conv")]
    assert len(conv_nodes) == 53
    x = np.random.default_rng(0).normal(size=(2, 64, 64, 3)).astype(np.float32)
    y = model.output(x)
    assert y.shape == (2, 10)
    np.testing.assert_allclose(np.asarray(y.sum(-1)), 1.0, rtol=1e-4)


def test_resnet50_trains():
    from deeplearning4j_tpu.optimize.listeners import (
        CollectScoresIterationListener)
    from deeplearning4j_tpu.optimize.updaters import Adam
    model = ResNet50(num_classes=8, height=32, width=32,
                     updater=Adam(1e-3)).init()
    scores = CollectScoresIterationListener()
    model.set_listeners(scores)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(8, 32, 32, 3)).astype(np.float32)
    y = onehot(rng.integers(0, 8, 8), 8)
    ds = DataSet(x, y)
    model.fit(ArrayDataSetIterator(ds, 8), epochs=8)
    first = scores.scores[0][1]
    last = scores.scores[-1][1]
    assert last < first, (first, last)


def test_vgg16_instantiates_small():
    model = VGG16(num_classes=10, height=32, width=32).init()
    x = np.random.default_rng(0).normal(size=(2, 32, 32, 3)).astype(np.float32)
    assert model.output(x).shape == (2, 10)


def test_alexnet_instantiates():
    model = AlexNet(num_classes=10, height=224, width=224).init()
    x = np.random.default_rng(0).normal(size=(1, 224, 224, 3)).astype(np.float32)
    assert model.output(x).shape == (1, 10)


def test_textgen_lstm():
    model = TextGenerationLSTM(vocab_size=20, timesteps=8).init()
    rng = np.random.default_rng(0)
    x = onehot(rng.integers(0, 20, 4 * 8), 20).reshape(4, 8, 20)
    y = model.output(x)
    assert y.shape == (4, 8, 20)
