"""Retrieval subsystem tests (PR 19): fused distance+top-k kernels,
the sharded int8/IVF corpus index, the AOT-warmed serving engine, the
HTTP ingress, and the cluster scatter-gather tier.

The acceptance contract under test:

- the jitted brute kernel is EXACT vs a numpy oracle (f32), and the
  int8 and IVF arms hold recall@10 >= 0.95 against the exact f32
  oracle on a blob-structured corpus (the embedding-like case the
  index is built for) — a recall regression fails tests, not just a
  benchmark;
- zero live compiles after ``warmup()``: every (mode, bucket, k)
  ladder cell is AOT-warmed and the RecompileWatchdog asserts no cell
  recompiles under traffic, including after a gated ``refresh()``;
- top-k is bitwise deterministic across repeats, and cross-shard ties
  break by (distance, id) so the merged answer is shard-layout
  invariant;
- ``refresh()`` hot-promotes only same-geometry, recall-gated
  indexes; a geometry change is rejected (it would force live
  compiles);
- the scatter-gather dispatcher answers every query full or flagged
  ``partial: True`` when a shard's owners die, and retries missing
  shards on replicas;
- the legacy /knn shim keeps the old NearestNeighborsServer JSON
  contract bit-for-bit (self-first, query-by-index, 400 on a body
  with neither vector nor index).
"""

import json
import os
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.parallel.aot_cache import ArtifactStore
from deeplearning4j_tpu.parallel.node import NodeRegistry
from deeplearning4j_tpu.retrieval.engine import RetrievalEngine, merge_topk
from deeplearning4j_tpu.retrieval.index import ShardedCorpusIndex


def _blob_corpus(n=4096, dim=32, k_blobs=32, seed=0, spread=0.15):
    """Mixture-of-gaussians corpus: the clustered geometry real
    embedding spaces have (and the case IVF routing is built for —
    uniform noise is its worst case and not what it is for)."""
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(k_blobs, dim)).astype(np.float32) * 3.0
    assign = rng.integers(k_blobs, size=n)
    pts = centers[assign] + \
        rng.normal(size=(n, dim)).astype(np.float32) * spread
    return pts.astype(np.float32)


def _exact_topk(corpus, queries, k):
    """The f32 oracle: exact squared-L2 top-k by full sort."""
    d2 = ((queries[:, None, :] - corpus[None, :, :]) ** 2).sum(-1)
    order = np.argsort(d2, axis=1, kind="stable")[:, :k]
    return np.take_along_axis(d2, order, axis=1), order


def _recall(found_ids, oracle_ids):
    hits = sum(len(set(f.tolist()) & set(o.tolist()))
               for f, o in zip(found_ids, oracle_ids))
    return hits / oracle_ids.size


def _post(url, body, timeout=30):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


class TestKernels:
    def test_brute_f32_exact(self):
        corpus = _blob_corpus(n=512, dim=16, seed=1)
        q = _blob_corpus(n=8, dim=16, seed=2)
        idx = ShardedCorpusIndex.build(corpus, shard_rows=512)
        eng = RetrievalEngine(idx, k_ladder=(10,), max_batch=8)
        eng.warmup()
        d, i = eng.search(q, 10)
        od, oi = _exact_topk(corpus, q, 10)
        assert (np.asarray(i) == oi).all()
        np.testing.assert_allclose(np.asarray(d), od, rtol=1e-4,
                                   atol=1e-3)
        eng.shutdown()

    def test_topk_bitwise_deterministic_with_ties(self):
        # duplicated rows force distance ties; the (distance, id)
        # tie-break must make repeats and shard layouts agree bitwise
        rng = np.random.default_rng(3)
        base = rng.normal(size=(128, 8)).astype(np.float32)
        corpus = np.concatenate([base, base])    # every row twice
        idx = ShardedCorpusIndex.build(corpus, shard_rows=64)
        eng = RetrievalEngine(idx, k_ladder=(10,), max_batch=4)
        eng.warmup()
        q = base[:4]
        d1, i1 = eng.search(q, 10)
        d2, i2 = eng.search(q, 10)
        assert (np.asarray(i1) == np.asarray(i2)).all()
        assert np.asarray(d1).tobytes() == np.asarray(d2).tobytes()
        eng.shutdown()

    def test_merge_topk_shard_layout_invariant(self):
        corpus = _blob_corpus(n=1024, dim=8, seed=4)
        q = corpus[:6] + 1e-4
        answers = []
        for rows in (128, 256, 1024):
            idx = ShardedCorpusIndex.build(corpus, shard_rows=rows)
            eng = RetrievalEngine(idx, k_ladder=(10,), max_batch=8)
            eng.warmup()
            _, ids = eng.search(q, 10)
            answers.append(np.asarray(ids))
            eng.shutdown()
        assert (answers[0] == answers[1]).all()
        assert (answers[0] == answers[2]).all()

    def test_merge_topk_padding_never_surfaces(self):
        # more k than real rows: -1 padding ids must sort last
        d = np.array([[[0.5, np.inf, np.inf]]], np.float32)
        i = np.array([[[7, -1, -1]]], np.int32)
        md, mi = merge_topk(d, i, 3)
        assert mi[0, 0] == 7 and (mi[0, 1:] == -1).all()
        assert md[0, 0] == pytest.approx(0.5)
        assert np.isinf(md[0, 1:]).all()


class TestRecallGates:
    """The acceptance gates: quantized and routed arms vs the exact
    f32 oracle at recall@10 >= 0.95 on a seeded structured corpus."""

    CORPUS = None

    @classmethod
    def _corpus(cls):
        if cls.CORPUS is None:
            cls.CORPUS = _blob_corpus(n=8192, dim=32, k_blobs=64,
                                      seed=7)
        return cls.CORPUS

    def _gate(self, precision, ivf_clusters, floor=0.95):
        corpus = self._corpus()
        rng = np.random.default_rng(11)
        probes = corpus[rng.integers(len(corpus), size=64)] + \
            rng.normal(size=(64, corpus.shape[1])).astype(
                np.float32) * 0.05
        idx = ShardedCorpusIndex.build(
            corpus, shard_rows=4096, precision=precision,
            ivf_clusters=ivf_clusters, nprobe_hint=8, seed=0)
        # the 40-rung is the int8 arm's overfetch depth (2k rule picks
        # the first rung >= 20); f32/IVF arms just serve k=10 off 10
        eng = RetrievalEngine(idx, k_ladder=(10, 40), max_batch=64)
        eng.warmup()
        _, ids = eng.search(probes, 10)
        _, oracle = _exact_topk(corpus, probes, 10)
        r = _recall(np.asarray(ids), oracle)
        eng.shutdown()
        return r

    def test_int8_recall_gate(self):
        r = self._gate("int8", ivf_clusters=0)
        assert r >= 0.95, f"int8 recall@10 {r:.3f} below 0.95 gate"

    def test_ivf_recall_gate(self):
        r = self._gate("f32", ivf_clusters=64)
        assert r >= 0.95, f"IVF recall@10 {r:.3f} below 0.95 gate"

    def test_ivf_int8_recall_gate(self):
        r = self._gate("int8", ivf_clusters=64)
        assert r >= 0.95, \
            f"IVF+int8 recall@10 {r:.3f} below 0.95 gate"


class TestIndex:
    def test_build_save_load_roundtrip(self, tmp_path):
        corpus = _blob_corpus(n=300, dim=8, seed=5)
        store = ArtifactStore(str(tmp_path / "store"))
        idx = ShardedCorpusIndex.build(corpus, shard_rows=128,
                                       precision="int8")
        idx.save(store, "rt")
        back = ShardedCorpusIndex.load(store, "rt")
        assert back.geometry() == idx.geometry()
        assert back.n_total == 300
        assert back.shard_ids == idx.shard_ids
        # a shard subset load keeps the full universe in view
        part = ShardedCorpusIndex.load(store, "rt", shard_ids=[1])
        assert part.shard_ids == [1]
        assert part.all_shard_ids == idx.shard_ids

    def test_manifest_names_existing_shards(self, tmp_path):
        corpus = _blob_corpus(n=100, dim=8, seed=6)
        store = ArtifactStore(str(tmp_path / "store"))
        ShardedCorpusIndex.build(corpus, shard_rows=128).save(
            store, "m")
        from deeplearning4j_tpu.retrieval.index import INDEX_MANIFEST
        d = store.cache_dir("m")
        with open(os.path.join(d, INDEX_MANIFEST)) as f:
            man = json.load(f)
        # publish order: every shard file the manifest references was
        # written before the manifest flip, so each must exist
        for sh in man["shards"]:
            assert os.path.exists(os.path.join(d, sh["file"]))
        assert man["n_total"] == 100

    def test_ivf_drops_no_rows(self):
        corpus = _blob_corpus(n=1000, dim=8, k_blobs=4, seed=8)
        idx = ShardedCorpusIndex.build(corpus, shard_rows=1024,
                                       ivf_clusters=8)
        sh = idx.shards[0]
        real = np.asarray(sh.c_ids).ravel()
        assert len(set(int(i) for i in real if i >= 0)) == 1000


class TestEngine:
    def test_zero_recompiles_after_warmup(self):
        corpus = _blob_corpus(n=2048, dim=16, seed=9)
        idx = ShardedCorpusIndex.build(corpus, shard_rows=1024,
                                       precision="int8",
                                       ivf_clusters=16)
        eng = RetrievalEngine(idx, k_ladder=(1, 10), max_batch=16)
        eng.warmup()
        rng = np.random.default_rng(0)
        # odd batch sizes, both modes, k below and at ladder rungs
        for b, k, mode in [(1, 1, None), (3, 5, "brute"), (16, 10,
                           "ivf"), (7, 10, None), (16, 2, "brute")]:
            eng.search(rng.normal(size=(b, 16)).astype(np.float32), k,
                       mode=mode)
        assert eng.recompiles_after_warmup == 0
        eng.assert_warm()
        eng.shutdown()

    def test_k_above_ladder_rejected(self):
        corpus = _blob_corpus(n=256, dim=8, seed=10)
        idx = ShardedCorpusIndex.build(corpus, shard_rows=256)
        eng = RetrievalEngine(idx, k_ladder=(10,), max_batch=4)
        eng.warmup()
        with pytest.raises(ValueError):
            eng.search(corpus[:2], 50)
        eng.shutdown()

    def test_refresh_gates(self, tmp_path):
        corpus = _blob_corpus(n=1024, dim=16, seed=12)
        store = ArtifactStore(str(tmp_path / "store"))
        idx = ShardedCorpusIndex.build(corpus, shard_rows=512,
                                       version="v1")
        idx.save(store, "ref")
        eng = RetrievalEngine(idx, k_ladder=(10,), max_batch=8)
        eng.warmup()

        # same version -> noop
        out = eng.refresh(store, "ref")
        assert out["promoted"] is False and out["reason"] == \
            "same version"

        # same geometry, new rows -> promoted with zero live compiles
        corpus2 = _blob_corpus(n=1024, dim=16, seed=13)
        ShardedCorpusIndex.build(corpus2, shard_rows=512,
                                 version="v2").save(store, "ref")
        out = eng.refresh(store, "ref")
        assert out["promoted"] is True and out["version"] == "v2"
        d, i = eng.search(corpus2[:4] + 1e-4, 10)
        assert (np.asarray(i)[:, 0] == np.arange(4)).all()
        assert eng.recompiles_after_warmup == 0

        # geometry change -> rejected (would force live compiles)
        ShardedCorpusIndex.build(_blob_corpus(n=1024, dim=16, seed=14),
                                 shard_rows=256,
                                 version="v3").save(store, "ref")
        out = eng.refresh(store, "ref")
        assert out["promoted"] is False and "geometry" in out["reason"]
        assert eng.version == "v2"
        eng.shutdown()

    def test_single_query_and_stats(self):
        corpus = _blob_corpus(n=256, dim=8, seed=15)
        idx = ShardedCorpusIndex.build(corpus, shard_rows=256)
        eng = RetrievalEngine(idx, k_ladder=(10,), max_batch=4)
        eng.warmup()
        d, i = eng.search(corpus[5], 3)        # 1-D query, 1-D answer
        assert np.asarray(i).shape == (3,)
        assert int(np.asarray(i)[0]) == 5
        st = eng.stats()
        assert st["warm"] and st["recompiles_after_warmup"] == 0
        assert st["vectors_total"] == 256
        eng.shutdown()


class TestRouterPool:
    def test_admission_and_shed(self):
        from deeplearning4j_tpu.observe.registry import MetricsRegistry
        from deeplearning4j_tpu.parallel.fleet import FleetRouter
        corpus = _blob_corpus(n=256, dim=8, seed=16)
        idx = ShardedCorpusIndex.build(corpus, shard_rows=256)
        eng = RetrievalEngine(idx, k_ladder=(10,), max_batch=4)
        eng.warmup()
        router = FleetRouter(registry=MetricsRegistry(),
                             session_id="t-nn")
        router.add_retrieval_pool("neighbors", eng)
        d, i = router.neighbors(corpus[:3], 10)
        assert np.asarray(i).shape == (3, 10)
        assert "neighbors" in router.stats()["retrieval"]
        router.assert_warm()
        router.shutdown()


class TestHTTPIngress:
    def _serve(self, tmp_path, **build_kw):
        from deeplearning4j_tpu.parallel.fleet import FleetRouter
        from deeplearning4j_tpu.ui.neighbors_module import \
            NeighborsModule
        from deeplearning4j_tpu.ui.server import UIServer
        from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage
        corpus = _blob_corpus(n=512, dim=8, seed=17)
        idx = ShardedCorpusIndex.build(corpus, shard_rows=256,
                                       **build_kw)
        eng = RetrievalEngine(idx, k_ladder=(10,), max_batch=8)
        eng.warmup()
        router = FleetRouter(session_id="t-nn-http")
        router.add_retrieval_pool("neighbors", eng)
        server = UIServer(port=0)
        server.attach(InMemoryStatsStorage())
        server.register_module(NeighborsModule(router=router))
        server.start()
        return corpus, router, server

    def test_routes(self, tmp_path):
        corpus, router, server = self._serve(tmp_path)
        try:
            st, out = _post(server.url + "/api/neighbors",
                            {"vector": corpus[9].tolist(), "k": 5})
            assert st == 200 and out["ids"][0] == 9
            assert len(out["ids"]) == 5
            st, out = _post(server.url + "/api/neighbors",
                            {"queries": corpus[:3].tolist()})
            assert st == 200 and len(out["ids"]) == 3
            # shard-scoped: every id from shard 1's row range
            st, out = _post(server.url + "/api/neighbors/shard",
                            {"queries": corpus[:2].tolist(), "k": 5,
                             "shards": [1]})
            assert st == 200
            assert all(i >= 256 for i in np.ravel(out["ids"]))
            st, out = _post(server.url + "/api/neighbors/shard",
                            {"queries": [[0.0] * 8], "k": 5,
                             "shards": [99]})
            assert st == 404
            st, out = _post(server.url + "/api/neighbors",
                            {"bogus": 1})
            assert st == 400
            st, out = _post(server.url + "/api/neighbors",
                            {"vector": corpus[0].tolist(), "k": 9999})
            assert st == 400
            with urllib.request.urlopen(
                    server.url + "/api/neighbors/stats") as r:
                stats = json.loads(r.read())
            assert stats["engine"]["recompiles_after_warmup"] == 0
        finally:
            server.stop()
            router.shutdown()


class TestClusterScatterGather:
    def _cluster(self, tmp_path, n_nodes=2, replicate=False):
        from deeplearning4j_tpu.retrieval.cluster import RetrievalNode
        corpus = _blob_corpus(n=1024, dim=16, seed=18)
        store = ArtifactStore(str(tmp_path / "store"))
        ShardedCorpusIndex.build(corpus, shard_rows=256).save(
            store, "c")
        reg = NodeRegistry(str(tmp_path / "reg"))
        nodes = []
        all_ids = ShardedCorpusIndex.load(store, "c").shard_ids
        for n in range(n_nodes):
            mine = all_ids if replicate else \
                [s for s in all_ids if s % n_nodes == n]
            eng = RetrievalEngine(
                ShardedCorpusIndex.load(store, "c", shard_ids=mine),
                k_ladder=(10,), max_batch=8)
            nodes.append(RetrievalNode(eng, node_id=f"n{n}",
                                       registry=reg))
        return corpus, store, reg, nodes

    def test_full_cluster_matches_single_engine(self, tmp_path):
        from deeplearning4j_tpu.retrieval.cluster import \
            NeighborsDispatcher
        corpus, store, reg, nodes = self._cluster(tmp_path)
        disp = NeighborsDispatcher(reg, timeout_s=15.0)
        try:
            q = corpus[:5] + 1e-4
            out = disp.search(q, 10)
            assert out["partial"] is False
            assert out["shards_answered"] == out["shards_total"] == 4
            ref = RetrievalEngine(
                ShardedCorpusIndex.load(store, "c"),
                k_ladder=(10,), max_batch=8)
            ref.warmup()
            _, oi = ref.search(q, 10)
            assert (out["ids"] == np.asarray(oi)).all()
            ref.shutdown()
        finally:
            disp.shutdown()
            for n in nodes:
                n.shutdown()

    def test_dead_node_degrades_to_partial(self, tmp_path):
        from deeplearning4j_tpu.retrieval.cluster import (
            NeighborsDispatcher, PartialResultError)
        corpus, store, reg, nodes = self._cluster(tmp_path)
        disp = NeighborsDispatcher(reg, timeout_s=15.0)
        try:
            nodes[1].shutdown()
            out = disp.search(corpus[:3], 10)
            assert out["partial"] is True
            assert 0 < out["shards_answered"] < out["shards_total"]
            assert out["ids"].shape == (3, 10)
            with pytest.raises(PartialResultError):
                disp.search(corpus[:3], 10, require_full=True)
        finally:
            disp.shutdown()
            for n in nodes:
                n.shutdown()

    def test_replica_covers_dead_primary(self, tmp_path):
        # both nodes own every shard: killing one must NOT go partial
        from deeplearning4j_tpu.retrieval.cluster import \
            NeighborsDispatcher
        corpus, store, reg, nodes = self._cluster(tmp_path,
                                                  replicate=True)
        disp = NeighborsDispatcher(reg, timeout_s=15.0)
        try:
            nodes[0].shutdown()
            out = disp.search(corpus[:3], 10)
            assert out["partial"] is False
            assert out["shards_answered"] == out["shards_total"]
        finally:
            disp.shutdown()
            for n in nodes:
                n.shutdown()

    def test_chaos_fanout_injection(self, tmp_path):
        # the deterministic fault layer reaches the shard fan-out seam:
        # an injected leg error must behave exactly like a dead owner
        # (replica retry when one exists, partial:true when none does)
        from deeplearning4j_tpu.chaos import plan as chaosplan
        from deeplearning4j_tpu.observe.registry import MetricsRegistry
        from deeplearning4j_tpu.retrieval.cluster import \
            NeighborsDispatcher
        corpus, store, reg, nodes = self._cluster(tmp_path)
        try:
            # every leg to n0 fails -> its shards have no replica ->
            # degraded, never an exception
            chaosplan.arm(chaosplan.parse_plan(
                "seed=7;neighbors.fanout:error(arg=n0)",
                registry=MetricsRegistry()))
            disp = NeighborsDispatcher(reg, timeout_s=15.0)
            out = disp.search(corpus[:3], 10)
            assert out["partial"] is True
            assert 0 < out["shards_answered"] < out["shards_total"]
            disp.shutdown()
        finally:
            chaosplan.disarm()
            for n in nodes:
                n.shutdown()

    def test_chaos_fanout_retry_covers_single_fault(self, tmp_path):
        from deeplearning4j_tpu.chaos import plan as chaosplan
        from deeplearning4j_tpu.observe.registry import MetricsRegistry
        from deeplearning4j_tpu.retrieval.cluster import \
            NeighborsDispatcher
        corpus, store, reg, nodes = self._cluster(tmp_path,
                                                  replicate=True)
        try:
            # one injected failure with a replica owning every shard:
            # the retry round must restore a FULL answer
            chaosplan.arm(chaosplan.parse_plan(
                "seed=7;neighbors.fanout:error(count=1)",
                registry=MetricsRegistry()))
            disp = NeighborsDispatcher(reg, timeout_s=15.0)
            out = disp.search(corpus[:3], 10)
            assert out["partial"] is False
            assert out["shards_answered"] == out["shards_total"]
            disp.shutdown()
        finally:
            chaosplan.disarm()
            for n in nodes:
                n.shutdown()

    def test_node_drain_contract(self, tmp_path):
        corpus, store, reg, nodes = self._cluster(tmp_path, n_nodes=1,
                                                  replicate=True)
        node = nodes[0]
        out = node.drain(timeout_s=10.0)
        assert out["drained"] is True
        # a drained node deregisters: it must be gone from the gossip
        assert node.node_id not in reg.snapshot()


class TestLegacyShim:
    def test_contract_euclidean_and_cosine(self):
        from deeplearning4j_tpu.clustering.server import \
            NearestNeighborsServer
        from deeplearning4j_tpu.clustering.vptree import VPTree
        rng = np.random.default_rng(19)
        pts = rng.normal(size=(80, 8))
        for metric in ("euclidean", "cosine"):
            srv = NearestNeighborsServer(pts, distance=metric)
            vt = VPTree(pts, distance=metric)
            ids, ds = srv.search(pts[7] + 1e-5, 5)
            vids, vds = vt.search(pts[7] + 1e-5, 5)
            assert ids == list(vids)
            np.testing.assert_allclose(ds, vds, atol=1e-4)
            ids, _ = srv.search(pts[0], 500)     # k > n clamps to n
            assert len(ids) == 80
            srv.stop()

    def test_rest_contract(self):
        from deeplearning4j_tpu.clustering.server import \
            NearestNeighborsServer
        rng = np.random.default_rng(20)
        pts = rng.normal(size=(64, 8))
        srv = NearestNeighborsServer(pts).start()
        try:
            st, out = _post(srv.url + "/knn",
                            {"vector": pts[3].tolist(), "k": 3})
            assert st == 200
            assert out["results"][0]["index"] == 3
            assert len(out["results"]) == 3
            st, out = _post(srv.url + "/knn", {"index": 5, "k": 2})
            assert st == 200 and out["results"][0]["index"] == 5
            st, out = _post(srv.url + "/knn", {})
            assert st == 400 and "error" in out
        finally:
            srv.stop()
