"""Cluster text pipeline tests (the dl4j-spark-nlp analog).

Golden-test pattern (SURVEY §4): sharded map/reduce vocab == single-host
vocab; distributed Word2Vec with parameter averaging learns the same
similarity structure as the single-host trainer.
"""

import numpy as np

from deeplearning4j_tpu.nlp.cluster import DistributedWord2Vec, TextPipeline
from deeplearning4j_tpu.nlp.word2vec import Word2Vec

CORPUS = [
    "the cat sat on the mat",
    "the dog sat on the rug",
    "a cat and a dog played",
    "the cat chased the dog",
    "dogs and cats are pets",
    "the mat and the rug are flat",
] * 8


def test_sharded_vocab_matches_single_host():
    pipe = TextPipeline(num_shards=4, min_word_frequency=2)
    vocab = pipe.build_vocab(CORPUS)

    single = Word2Vec(min_word_frequency=2)
    single.build_vocab(CORPUS)

    # index-IDENTICAL, not just same set: frequency ties break in
    # first-appearance order on both paths, so Huffman codes / syn1
    # rows line up across sharded and single-host vocab builds
    assert vocab.words() == single.vocab.words()
    for w in vocab.words():
        assert vocab.word_frequency(w) == single.vocab.word_frequency(w)
    assert vocab.total_word_count == single.vocab.total_word_count


def test_shard_partition_covers_corpus():
    pipe = TextPipeline(num_shards=3)
    shards = pipe.shard(CORPUS)
    assert sum(len(s) for s in shards) == len(CORPUS)
    assert all(len(s) > 0 for s in shards)


def test_distributed_word2vec_learns_structure():
    # two topic clusters with disjoint vocabularies: within-cluster
    # similarity must beat cross-cluster (which never co-occur)
    corpus = (["the cat chased the dog past the mouse"] * 24
              + ["red and blue mix into green and purple"] * 24)
    dw = DistributedWord2Vec(num_workers=4, averaging_rounds=4,
                             layer_size=24, window_size=3,
                             min_word_frequency=1, epochs=32, negative=4,
                             seed=7)
    model = dw.fit(corpus)
    assert model.has_word("cat") and model.has_word("dog")
    assert model.similarity("cat", "dog") > model.similarity("cat", "red")
    assert model.similarity("red", "blue") > model.similarity("blue", "dog")


def test_distributed_matches_single_when_one_worker():
    """num_workers=1, one round == plain single-host training on the
    same vocab/tables: parameter averaging over one shard is the
    identity. (The pipeline vocab breaks frequency ties differently
    than corpus-order insertion, so the oracle shares its vocab.)"""
    kw = dict(layer_size=16, window_size=2, min_word_frequency=1,
              epochs=2, negative=3, seed=11)
    dw = DistributedWord2Vec(num_workers=1, averaging_rounds=1, **kw)
    dist = dw.fit(CORPUS)

    single = Word2Vec(**kw)
    single.vocab = dw.pipeline.build_vocab(CORPUS)
    single._init_tables()
    single.fit(CORPUS)
    assert set(single.vocab.words()) == set(dist.vocab.words())
    for w in ("cat", "dog", "mat"):
        np.testing.assert_allclose(
            np.asarray(dist.get_word_vector(w)),
            np.asarray(single.get_word_vector(w)), rtol=1e-4, atol=1e-5)


def test_hs_resume_after_deserialize(tmp_path):
    """A deserialized HS model (tables installed without _init_tables)
    must keep training via the fast path — the HS matrices are built
    lazily (regression: AttributeError _hs_points)."""
    from deeplearning4j_tpu.nlp.serializer import (read_full_model,
                                                   write_full_model)
    m = Word2Vec(layer_size=12, window_size=2, epochs=2, seed=5,
                 use_hierarchic_softmax=True)
    m.fit(CORPUS)
    p = str(tmp_path / "w2v_hs.npz")
    write_full_model(m, p)
    m2 = read_full_model(p)
    assert m2.use_hs
    m2.fit(CORPUS)          # crashed before the lazy-matrix fix
    assert np.isfinite(np.asarray(m2.syn0)).all()


def test_distributed_hs_workers_train():
    dw = DistributedWord2Vec(num_workers=3, averaging_rounds=2,
                             layer_size=12, window_size=2, epochs=8,
                             use_hierarchic_softmax=True,
                             min_word_frequency=1, seed=9)
    model = dw.fit(CORPUS)
    assert model.use_hs
    assert np.isfinite(np.asarray(model.syn0)).all()
    assert model.similarity("cat", "cat") > 0.99
