"""The driver-visible contract of ``__graft_entry__``.

The driver imports the module and calls ``dryrun_multichip(8)`` directly —
no env prep, no ``__main__`` block — in a process where the image's TPU
PJRT shim is active.  Round 1 failed exactly this invocation (the mesh saw
1 device), so the regression test here replicates it byte-for-byte in a
fresh subprocess with the parent's env untouched.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_entry_compiles_and_runs():
    import jax
    import __graft_entry__ as ge
    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert out.shape[0] == args[-1].shape[0]


def test_dryrun_multichip_errors_clearly_when_mesh_too_small():
    # jax is already up with 8 CPU devices under pytest; asking for more
    # must raise the descriptive error, not the old bare mesh ValueError.
    import __graft_entry__ as ge
    with pytest.raises(RuntimeError, match="already"):
        ge.dryrun_multichip(64)


@pytest.mark.slow
def test_dryrun_multichip_driver_invocation():
    """Exactly what the driver runs: import + call, inherited env."""
    env = dict(os.environ)
    # Undo pytest's own pinning so the subprocess is as unprepared as the
    # driver's: no force_host_platform flag, no JAX_PLATFORMS.
    env.pop("JAX_PLATFORMS", None)
    flags = env.get("XLA_FLAGS", "")
    env["XLA_FLAGS"] = " ".join(
        f for f in flags.split()
        if "xla_force_host_platform_device_count" not in f)
    proc = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__; __graft_entry__.dryrun_multichip(8)"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=900)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "ResNet50 train step OK" in proc.stdout
    assert "ring-attention + Ulysses a2a + MoE train step OK" in proc.stdout
    assert "circular pipeline" in proc.stdout
    assert "Megatron-paired transformer train step OK" in proc.stdout
