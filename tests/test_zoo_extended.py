"""Instantiation tests for the extended model zoo (SURVEY §2.6 full list).

Mirrors deeplearning4j-zoo's TestInstantiation: build each architecture at
reduced input size, run a forward pass, check the output arity. Small
shapes keep the CPU-mesh compile times reasonable; topology (branching,
residuals, passthrough, reductions) is identical to full size.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.zoo.models import (
    Darknet19,
    FaceNetNN4Small2,
    GoogLeNet,
    InceptionResNetV1,
    TinyYOLO,
    VGG19,
    YOLO2,
)


def _img(rng, n, h, w, c=3):
    return rng.normal(size=(n, h, w, c)).astype(np.float32)


def test_vgg19_forward(rng):
    m = VGG19(num_classes=10, height=32, width=32).init()
    out = np.asarray(m.output(_img(rng, 2, 32, 32)))
    assert out.shape == (2, 10)
    np.testing.assert_allclose(out.sum(1), 1.0, rtol=1e-4)


def test_darknet19_forward(rng):
    m = Darknet19(num_classes=12, height=64, width=64).init()
    out = np.asarray(m.output(_img(rng, 2, 64, 64)))
    assert out.shape == (2, 12)
    np.testing.assert_allclose(out.sum(1), 1.0, rtol=1e-4)


def test_tiny_yolo_forward(rng):
    m = TinyYOLO(num_classes=4, height=64, width=64).init()
    out = np.asarray(m.output(_img(rng, 1, 64, 64)))
    # 64 / 2^5 = 2 grid; 5 boxes * (5 + 4 classes)
    assert out.shape == (1, 2, 2, 5 * 9)


def test_yolo2_forward(rng):
    m = YOLO2(num_classes=4, height=64, width=64).init()
    out = np.asarray(m.output(_img(rng, 1, 64, 64)))
    assert out.shape == (1, 2, 2, 5 * 9)


def test_googlenet_forward(rng):
    m = GoogLeNet(num_classes=7, height=64, width=64).init()
    out = np.asarray(m.output(_img(rng, 2, 64, 64)))
    assert out.shape == (2, 7)
    np.testing.assert_allclose(out.sum(1), 1.0, rtol=1e-4)


def test_inception_resnet_v1_embeddings(rng):
    m = InceptionResNetV1(num_classes=9, height=64, width=64).init()
    out = np.asarray(m.output(_img(rng, 2, 64, 64)))
    assert out.shape == (2, 9)


def test_facenet_nn4_small2_forward(rng):
    m = FaceNetNN4Small2(num_classes=11, height=64, width=64).init()
    out = np.asarray(m.output(_img(rng, 2, 64, 64)))
    assert out.shape == (2, 11)
    np.testing.assert_allclose(out.sum(1), 1.0, rtol=1e-4)
