"""Instantiation tests for the extended model zoo (SURVEY §2.6 full list).

Mirrors deeplearning4j-zoo's TestInstantiation: build each architecture at
reduced input size, run a forward pass, check the output arity. Small
shapes keep the CPU-mesh compile times reasonable; topology (branching,
residuals, passthrough, reductions) is identical to full size.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.zoo.models import (
    Darknet19,
    FaceNetNN4Small2,
    GoogLeNet,
    InceptionResNetV1,
    TinyYOLO,
    VGG19,
    YOLO2,
)


def _img(rng, n, h, w, c=3):
    return rng.normal(size=(n, h, w, c)).astype(np.float32)


def test_vgg19_forward(rng):
    m = VGG19(num_classes=10, height=32, width=32).init()
    out = np.asarray(m.output(_img(rng, 2, 32, 32)))
    assert out.shape == (2, 10)
    np.testing.assert_allclose(out.sum(1), 1.0, rtol=1e-4)


def test_darknet19_forward(rng):
    m = Darknet19(num_classes=12, height=64, width=64).init()
    out = np.asarray(m.output(_img(rng, 2, 64, 64)))
    assert out.shape == (2, 12)
    np.testing.assert_allclose(out.sum(1), 1.0, rtol=1e-4)


def test_tiny_yolo_forward(rng):
    m = TinyYOLO(num_classes=4, height=64, width=64).init()
    out = np.asarray(m.output(_img(rng, 1, 64, 64)))
    # 64 / 2^5 = 2 grid; 5 boxes * (5 + 4 classes)
    assert out.shape == (1, 2, 2, 5 * 9)


def test_yolo2_forward(rng):
    m = YOLO2(num_classes=4, height=64, width=64).init()
    out = np.asarray(m.output(_img(rng, 1, 64, 64)))
    assert out.shape == (1, 2, 2, 5 * 9)


def test_googlenet_forward(rng):
    m = GoogLeNet(num_classes=7, height=64, width=64).init()
    out = np.asarray(m.output(_img(rng, 2, 64, 64)))
    assert out.shape == (2, 7)
    np.testing.assert_allclose(out.sum(1), 1.0, rtol=1e-4)


def test_inception_resnet_v1_embeddings(rng):
    m = InceptionResNetV1(num_classes=9, height=64, width=64).init()
    out = np.asarray(m.output(_img(rng, 2, 64, 64)))
    assert out.shape == (2, 9)


def test_facenet_nn4_small2_forward(rng):
    m = FaceNetNN4Small2(num_classes=11, height=64, width=64).init()
    out = np.asarray(m.output(_img(rng, 2, 64, 64)))
    assert out.shape == (2, 11)
    np.testing.assert_allclose(out.sum(1), 1.0, rtol=1e-4)


class TestS2dStem:
    """Space-to-depth ResNet50 stem (round 5): exact refold equivalence
    + end-to-end model build."""

    def test_fold_is_exact(self, rng):
        import jax.numpy as jnp
        from jax import lax
        from deeplearning4j_tpu.zoo.models import fold_stem_weights

        x = jnp.asarray(rng.normal(size=(2, 64, 64, 3)), jnp.float32)
        w7 = jnp.asarray(rng.normal(size=(7, 7, 3, 64)), jnp.float32)
        y_ref = lax.conv_general_dilated(
            x, w7, window_strides=(2, 2), padding="SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        # s2d + pad (1,2) + 4x4/1 VALID with folded weights
        n, h, w, c = x.shape
        x2 = x.reshape(n, h // 2, 2, w // 2, 2, c)
        x2 = x2.transpose(0, 1, 3, 2, 4, 5).reshape(n, h // 2, w // 2,
                                                    4 * c)
        x2 = jnp.pad(x2, ((0, 0), (1, 2), (1, 2), (0, 0)))
        wf = jnp.asarray(fold_stem_weights(w7))
        y_s2d = lax.conv_general_dilated(
            x2, wf, window_strides=(1, 1), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        np.testing.assert_allclose(np.asarray(y_s2d), np.asarray(y_ref),
                                   rtol=1e-5, atol=1e-5)

    def test_s2d_model_matches_standard_with_folded_weights(self, rng):
        import jax.numpy as jnp
        from deeplearning4j_tpu.zoo.models import (ResNet50,
                                                   fold_stem_weights)

        std = ResNet50(num_classes=8, height=32, width=32).init()
        s2d = ResNet50(num_classes=8, height=32, width=32,
                       s2d_stem=True).init()
        # carry ALL params over; conv1 via the fold
        p = dict(std.train_state.params)
        p2 = dict(s2d.train_state.params)
        for k in p2:
            if k == "conv1_conv":
                p2[k] = {"W": jnp.asarray(
                    fold_stem_weights(p["conv1_conv"]["W"]))}
            elif k in p:
                p2[k] = p[k]
        s2d.train_state = s2d.train_state._replace(params=p2)
        x = _img(rng, 2, 32, 32)
        np.testing.assert_allclose(np.asarray(s2d.output(x)),
                                   np.asarray(std.output(x)),
                                   rtol=1e-4, atol=1e-5)
