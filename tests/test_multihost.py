"""REAL multi-process distributed training test (VERDICT L5: "real
multi-host is unexercised").

Two OS processes, each owning 2 virtual CPU devices, form one global
4-device mesh via ``jax.distributed`` (gloo over TCP — the same wiring
a 2-host TPU pod uses over DCN, minus the hardware). This exercises
what the single-process 8-device mesh cannot: cross-process
collectives, per-process data staging
(make_array_from_process_local_data), and per-process sharded
checkpoint writes.

Golden assertion (TestCompareParameterAveragingSparkVsSingleMachine
pattern): distributed training across processes == single-process
training on the full batch, and the sharded checkpoint written by two
processes restores in ONE process to the same parameters.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_dp_matches_single_process(tmp_path):
    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("JAX_PLATFORMS", None)
    flags = env.get("XLA_FLAGS", "")
    env["XLA_FLAGS"] = " ".join(
        f for f in flags.split()
        if "xla_force_host_platform_device_count" not in f)
    worker = os.path.join(REPO, "tests", "multihost_worker.py")
    procs = [subprocess.Popen(
        [sys.executable, worker, str(r), "2", str(port), str(tmp_path)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True) for r in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=600)
            outs.append(out)
    finally:
        for p in procs:       # a crashed peer leaves the other blocked
            if p.poll() is None:
                p.kill()
                p.communicate()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-3000:]

    results = []
    for r in range(2):
        with open(tmp_path / f"result_{r}.json") as f:
            results.append(json.load(f))
    # both processes ended with identical (replicated) params + loss
    assert results[0]["param_sum"] == pytest.approx(
        results[1]["param_sum"], rel=1e-6)
    assert results[0]["loss"] == pytest.approx(results[1]["loss"],
                                               rel=1e-6)
    # AVERAGING (local-SGD) across processes stayed in sync too
    assert results[0]["avg_param_sum"] == pytest.approx(
        results[1]["avg_param_sum"], rel=1e-6)

    # ---- single-process golden reference (this pytest process) ---------
    import jax
    from deeplearning4j_tpu.datasets.dataset import (
        ArrayDataSetIterator, DataSet)
    from deeplearning4j_tpu.models.multi_layer_network import (
        MultiLayerNetwork)
    from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.inputs import InputType
    from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
    from deeplearning4j_tpu.nn.layers.output import OutputLayer
    from deeplearning4j_tpu.ops.activations import Activation
    from deeplearning4j_tpu.optimize.updaters import Sgd

    conf = (NeuralNetConfiguration.Builder().seed(1).updater(Sgd(0.1))
            .list()
            .layer(DenseLayer(n_out=16, activation=Activation.TANH))
            .layer(OutputLayer(n_out=3))
            .set_input_type(InputType.feed_forward(4)).build())
    single = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    gx = rng.normal(size=(64, 4)).astype(np.float32)
    gy = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 64)]
    single.fit(ArrayDataSetIterator(DataSet(gx, gy), batch_size=64,
                                    shuffle=False), epochs=5)
    flat = np.concatenate([np.asarray(l).ravel() for l in
                           jax.tree_util.tree_leaves(single.params)])
    assert results[0]["param_sum"] == pytest.approx(float(flat.sum()),
                                                    rel=2e-4)
    np.testing.assert_allclose(results[0]["param_head"], flat[:5],
                               rtol=2e-4, atol=2e-5)

    # ---- cross-process-count restore: 2-proc checkpoint, 1-proc load --
    from deeplearning4j_tpu.parallel.checkpoint import (
        latest_checkpoint, restore_sharded)
    from deeplearning4j_tpu.parallel.mesh import DATA_AXIS, create_mesh
    restored = MultiLayerNetwork(conf).init()
    mesh1 = create_mesh({DATA_AXIS: 4}, jax.devices()[:4])
    ckpt = latest_checkpoint(str(tmp_path / "ckpt"))
    assert ckpt is not None
    restore_sharded(restored, ckpt, mesh1)
    rflat = np.concatenate([np.asarray(l).ravel() for l in
                            jax.tree_util.tree_leaves(restored.params)])
    assert float(rflat.sum()) == pytest.approx(results[0]["param_sum"],
                                               rel=1e-6)
