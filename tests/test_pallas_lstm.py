"""Fused Pallas LSTM recurrence (ops/pallas_lstm.py): equivalence with
the lax.scan cell — forward, custom-VJP gradients, masking, TBPTT
carries — plus the helper-SPI dispatch rules.

All kernel tests run in interpret mode (CPU); on-TPU timing lives in
benchmarks/lstm_crossover.py.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.layers.base import LayerContext
from deeplearning4j_tpu.nn.layers.recurrent import LSTM, GravesLSTM
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.ops import pallas_lstm
from deeplearning4j_tpu.ops.activations import Activation


def _ref_scan(zx, h0, c0, wh, mask=None):
    """The lax.scan cell, verbatim semantics of LSTM._cell (gate-major,
    sigmoid gates, tanh activation, carry-freezing mask)."""
    t, n, g4 = zx.shape
    h = g4 // 4

    def cell(carry, inp):
        h_prev, c_prev = carry
        zx_t, m = inp if mask is not None else (inp, None)
        z = zx_t + h_prev @ wh
        i = jax.nn.sigmoid(z[:, :h])
        f = jax.nn.sigmoid(z[:, h:2 * h])
        o = jax.nn.sigmoid(z[:, 2 * h:3 * h])
        g = jnp.tanh(z[:, 3 * h:])
        c = f * c_prev + i * g
        hy = o * jnp.tanh(c)
        if m is not None:
            mm = m[:, None]
            hy = mm * hy + (1 - mm) * h_prev
            c = mm * c + (1 - mm) * c_prev
        return (hy, c), hy

    inputs = zx if mask is None else (zx, mask)
    (hT, cT), ys = jax.lax.scan(cell, (h0, c0), inputs)
    return ys, hT, cT


def _inputs(rng, t=7, n=4, h=8, dtype=jnp.float32):
    zx = jnp.asarray(rng.normal(size=(t, n, 4 * h)), dtype)
    wh = jnp.asarray(rng.normal(size=(h, 4 * h)) * 0.3, dtype)
    h0 = jnp.asarray(rng.normal(size=(n, h)), dtype)
    c0 = jnp.asarray(rng.normal(size=(n, h)), dtype)
    mask = jnp.asarray(rng.random((t, n)) > 0.3, dtype)
    return zx, h0, c0, wh, mask


@pytest.mark.parametrize("use_mask", [False, True])
@pytest.mark.parametrize("block_t", [1, 4])
def test_forward_matches_scan(rng, use_mask, block_t):
    zx, h0, c0, wh, mask = _inputs(rng)
    m = mask if use_mask else None
    ys_f, hT_f, cT_f = pallas_lstm.lstm_fused(zx, h0, c0, wh, m,
                                              block_t=block_t)
    ys_r, hT_r, cT_r = _ref_scan(zx, h0, c0, wh, m)
    np.testing.assert_allclose(ys_f, ys_r, rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(hT_f, hT_r, rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(cT_f, cT_r, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("use_mask", [False, True])
@pytest.mark.parametrize("block_t", [1, 4])
def test_gradients_match_scan(rng, use_mask, block_t):
    """Custom-VJP vs autodiff-through-scan on a loss touching all three
    outputs (ys, hT, cT) and all four diff inputs (zx, h0, c0, Wh)."""
    zx, h0, c0, wh, mask = _inputs(rng)
    m = mask if use_mask else None

    def loss(fn):
        def f(zx, h0, c0, wh):
            ys, hT, cT = fn(zx, h0, c0, wh)
            return (jnp.sum(ys * ys) + jnp.sum(2.0 * hT)
                    + jnp.sum(jnp.tanh(cT)))
        return jax.grad(f, argnums=(0, 1, 2, 3))(zx, h0, c0, wh)

    gf = loss(lambda *a: pallas_lstm.lstm_fused(*a, m, block_t=block_t))
    gr = loss(lambda *a: _ref_scan(*a, m))
    for a, b, name in zip(gf, gr, ("dzx", "dh0", "dc0", "dWh")):
        np.testing.assert_allclose(a, b, rtol=5e-4, atol=1e-5,
                                   err_msg=name)


def test_masked_ticks_freeze_carry(rng):
    """A fully-masked tail must leave (hT, cT) at their values from the
    last unmasked tick, and contribute zero gradient."""
    zx, h0, c0, wh, _ = _inputs(rng, t=8)
    mask = jnp.ones((8, 4), jnp.float32).at[5:].set(0.0)
    ys, hT, cT = pallas_lstm.lstm_fused(zx, h0, c0, wh, mask)
    np.testing.assert_allclose(hT, ys[4], rtol=1e-6)
    # tail outputs equal the frozen carry (the LAYER zeroes them)
    np.testing.assert_allclose(ys[7], ys[4], rtol=1e-6)

    # gradient w.r.t. masked-tick inputs is exactly zero
    g = jax.grad(lambda zx: jnp.sum(
        pallas_lstm.lstm_fused(zx, h0, c0, wh, mask)[1] ** 2))(zx)
    np.testing.assert_array_equal(np.asarray(g[5:]), 0.0)
    assert np.abs(np.asarray(g[:5])).max() > 0.0


def test_tbptt_chunked_carry_matches_full(rng):
    """Two fused chunks chained through (hT, cT) == one full pass — the
    invariant TBPTT relies on."""
    zx, h0, c0, wh, mask = _inputs(rng, t=10)
    ys, hT, cT = pallas_lstm.lstm_fused(zx, h0, c0, wh, mask)
    ys_a, h_a, c_a = pallas_lstm.lstm_fused(zx[:6], h0, c0, wh, mask[:6])
    ys_b, h_b, c_b = pallas_lstm.lstm_fused(zx[6:], h_a, c_a, wh, mask[6:])
    np.testing.assert_allclose(np.concatenate([ys_a, ys_b]), ys,
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(h_b, hT, rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(c_b, cT, rtol=2e-5, atol=2e-5)


def test_bfloat16_close_to_scan(rng):
    zx, h0, c0, wh, mask = _inputs(rng, dtype=jnp.bfloat16)
    ys_f, hT_f, _ = pallas_lstm.lstm_fused(zx, h0, c0, wh, mask)
    ys_r, hT_r, _ = _ref_scan(zx, h0, c0, wh, mask)
    np.testing.assert_allclose(np.asarray(ys_f, np.float32),
                               np.asarray(ys_r, np.float32),
                               rtol=0.05, atol=0.05)
    assert ys_f.dtype == jnp.bfloat16


class TestLayerWiring:
    def _layer_out(self, monkeypatch, impl, mask=None, layer_cls=LSTM,
                   **kw):
        monkeypatch.setenv(pallas_lstm._IMPL_ENV, impl)
        layer = layer_cls(n_out=8, n_in=5, name="l", **kw)
        params = layer.initialize(jax.random.PRNGKey(0),
                                  InputType.recurrent(5, 6))
        x = jnp.asarray(np.random.default_rng(3).normal(size=(4, 6, 5)),
                        jnp.float32)
        ctx = LayerContext(train=False, mask=mask)
        out, st = layer.apply(params, {}, x, ctx)
        return out, st

    @pytest.mark.parametrize("use_mask", [False, True])
    def test_apply_fused_equals_scan(self, monkeypatch, use_mask):
        mask = (jnp.ones((4, 6), jnp.float32).at[:, 4:].set(0.0)
                if use_mask else None)
        out_s, st_s = self._layer_out(monkeypatch, "scan", mask)
        out_f, st_f = self._layer_out(monkeypatch, "fused", mask)
        np.testing.assert_allclose(out_f, out_s, rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(st_f["last_h"], st_s["last_h"],
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(st_f["last_c"], st_s["last_c"],
                                   rtol=2e-5, atol=2e-5)
        if use_mask:  # layer zeroes masked outputs in both impls
            np.testing.assert_array_equal(
                np.asarray(out_f[:, 4:]), 0.0)

    def test_fused_route_actually_taken(self, monkeypatch):
        calls = []
        orig = pallas_lstm.lstm_fused
        monkeypatch.setattr(pallas_lstm, "lstm_fused",
                            lambda *a, **k: calls.append(1) or orig(*a, **k))
        self._layer_out(monkeypatch, "fused")
        assert calls
        calls.clear()
        self._layer_out(monkeypatch, "scan")
        assert not calls

    def test_graves_and_nondefault_stay_on_scan(self, monkeypatch):
        """Peepholes / non-default activations / hidden_major are not
        what the kernel computes — they must never route to it."""
        assert not GravesLSTM(n_out=8, n_in=5)._fused_eligible()
        assert not LSTM(n_out=8, n_in=5,
                        gate_layout="hidden_major")._fused_eligible()
        assert not LSTM(n_out=8, n_in=5,
                        gate_activation=Activation.HARDSIGMOID
                        )._fused_eligible()
        assert LSTM(n_out=8, n_in=5)._fused_eligible()

        calls = []
        orig = pallas_lstm.lstm_fused
        monkeypatch.setattr(pallas_lstm, "lstm_fused",
                            lambda *a, **k: calls.append(1) or orig(*a, **k))
        self._layer_out(monkeypatch, "fused", layer_cls=GravesLSTM)
        assert not calls

    def test_layer_gradients_match(self, monkeypatch):
        layer = LSTM(n_out=8, n_in=5, name="l")
        params = layer.initialize(jax.random.PRNGKey(0),
                                  InputType.recurrent(5, 6))
        x = jnp.asarray(np.random.default_rng(3).normal(size=(4, 6, 5)),
                        jnp.float32)
        mask = jnp.ones((4, 6), jnp.float32).at[:, 4:].set(0.0)

        def grads(impl):
            monkeypatch.setenv(pallas_lstm._IMPL_ENV, impl)
            def f(p, x):
                out, _ = layer.apply(p, {}, x,
                                     LayerContext(train=False, mask=mask))
                return jnp.sum(out * out)
            return jax.grad(f, argnums=(0, 1))(params, x)

        gs, gf = grads("scan"), grads("fused")
        np.testing.assert_allclose(gf[1], gs[1], rtol=5e-4, atol=1e-5)
        for k in ("Wx", "Wh", "b"):
            np.testing.assert_allclose(gf[0][k], gs[0][k], rtol=5e-4,
                                       atol=1e-5, err_msg=k)


class TestDispatch:
    def test_auto_is_scan_without_measured_thresholds(self, monkeypatch):
        """Honest-threshold discipline: with no crossover measurements
        recorded, auto must not route to the kernel anywhere."""
        monkeypatch.delenv(pallas_lstm._IMPL_ENV, raising=False)
        monkeypatch.setattr(pallas_lstm, "_MEASURED_FUSED_WINS", ())
        assert pallas_lstm.choose_impl(256, 512, 128,
                                       backend="tpu") == "scan"
        assert pallas_lstm.choose_impl(256, 512, 128,
                                       backend="cpu") == "scan"

    def test_measured_rule_routes_on_tpu_only(self, monkeypatch):
        monkeypatch.delenv(pallas_lstm._IMPL_ENV, raising=False)
        monkeypatch.setattr(pallas_lstm, "_MEASURED_FUSED_WINS",
                            ((64, 256, 32),))
        assert pallas_lstm.choose_impl(256, 512, 128,
                                       backend="tpu") == "fused"
        assert pallas_lstm.choose_impl(32, 512, 128,
                                       backend="tpu") == "scan"
        assert pallas_lstm.choose_impl(256, 512, 128,
                                       backend="cpu") == "scan"

    def test_env_override_wins(self, monkeypatch):
        monkeypatch.setenv(pallas_lstm._IMPL_ENV, "fused")
        assert pallas_lstm.choose_impl(1, 1, 1, backend="cpu") == "fused"
        monkeypatch.setenv(pallas_lstm._IMPL_ENV, "scan")
        monkeypatch.setattr(pallas_lstm, "_MEASURED_FUSED_WINS",
                            ((1, 1, 1),))
        assert pallas_lstm.choose_impl(256, 512, 128,
                                       backend="tpu") == "scan"
