"""Early stopping + transfer learning + regularization-conf tests.

Analog of the reference's deeplearning4j-core/src/test suites
TestEarlyStopping.java, TransferLearningMLNTest.java,
TestDropout/TestConstraints/TestWeightNoise.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import ArrayDataSetIterator, DataSet
from deeplearning4j_tpu.earlystopping import (
    DataSetLossCalculator,
    EarlyStoppingConfiguration,
    EarlyStoppingTrainer,
    InMemoryModelSaver,
    InvalidScoreIterationTerminationCondition,
    LocalFileModelSaver,
    MaxEpochsTerminationCondition,
    MaxTimeIterationTerminationCondition,
    ScoreImprovementEpochsTerminationCondition,
    TerminationReason,
)
from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
from deeplearning4j_tpu.nn.constraints import (
    MaxNormConstraint,
    NonNegativeConstraint,
    UnitNormConstraint,
)
from deeplearning4j_tpu.nn.distributions import (
    NormalDistribution,
    OrthogonalDistribution,
    UniformDistribution,
)
from deeplearning4j_tpu.nn.dropout import (
    AlphaDropout,
    Dropout,
    GaussianDropout,
    GaussianNoise,
)
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
from deeplearning4j_tpu.nn.layers.output import OutputLayer
from deeplearning4j_tpu.nn.transferlearning import (
    FineTuneConfiguration,
    TransferLearning,
    TransferLearningHelper,
)
from deeplearning4j_tpu.nn.weightnoise import DropConnect, WeightNoise
from deeplearning4j_tpu.ops.activations import Activation
from deeplearning4j_tpu.ops.losses import LossFunction
from deeplearning4j_tpu.optimize.updaters import Adam, Sgd


def _toy_data(n=64, nf=4, nc=3, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, nf)).astype(np.float32)
    y_idx = rng.integers(0, nc, size=n)
    # make it learnable: shift x by class
    x += y_idx[:, None].astype(np.float32)
    y = np.eye(nc, dtype=np.float32)[y_idx]
    return x, y


def _mlp(seed=123, **layer_kw):
    return (NeuralNetConfiguration.Builder()
            .seed(seed)
            .updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=16, activation=Activation.RELU,
                              **layer_kw))
            .layer(DenseLayer(n_out=8, activation=Activation.RELU))
            .layer(OutputLayer(n_out=3, loss=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(4))
            .build())


class TestEarlyStopping:
    def test_max_epochs(self):
        x, y = _toy_data()
        train = ArrayDataSetIterator(DataSet(x, y), batch_size=16)
        test = ArrayDataSetIterator(DataSet(x, y), batch_size=32)
        esc = (EarlyStoppingConfiguration.Builder()
               .epoch_termination_conditions(MaxEpochsTerminationCondition(3))
               .score_calculator(DataSetLossCalculator(test))
               .model_saver(InMemoryModelSaver())
               .build())
        model = MultiLayerNetwork(_mlp())
        result = EarlyStoppingTrainer(esc, model, train).fit()
        assert result.termination_reason is \
            TerminationReason.EPOCH_TERMINATION_CONDITION
        assert result.total_epochs == 3
        assert result.best_model is not None
        assert len(result.score_vs_epoch) == 3
        # best model predicts
        out = result.best_model.output(x[:4])
        assert out.shape == (4, 3)

    def test_score_improvement_stop(self):
        x, y = _toy_data()
        train = ArrayDataSetIterator(DataSet(x, y), batch_size=16)
        test = ArrayDataSetIterator(DataSet(x, y), batch_size=32)
        esc = (EarlyStoppingConfiguration.Builder()
               .epoch_termination_conditions(
                   ScoreImprovementEpochsTerminationCondition(1, 1e9),
                   MaxEpochsTerminationCondition(50))
               .score_calculator(DataSetLossCalculator(test))
               .build())
        model = MultiLayerNetwork(_mlp())
        result = EarlyStoppingTrainer(esc, model, train).fit()
        # improvement threshold is absurd, stops after 2 evals
        assert result.total_epochs <= 3

    def test_time_termination(self):
        x, y = _toy_data()
        train = ArrayDataSetIterator(DataSet(x, y), batch_size=16)
        esc = (EarlyStoppingConfiguration.Builder()
               .iteration_termination_conditions(
                   MaxTimeIterationTerminationCondition(0.0))
               .epoch_termination_conditions(
                   MaxEpochsTerminationCondition(100))
               .build())
        model = MultiLayerNetwork(_mlp())
        result = EarlyStoppingTrainer(esc, model, train).fit()
        assert result.termination_reason is \
            TerminationReason.ITERATION_TERMINATION_CONDITION

    def test_invalid_score_guard(self):
        assert InvalidScoreIterationTerminationCondition().terminate(
            float("nan"))
        assert InvalidScoreIterationTerminationCondition().terminate(
            float("inf"))
        assert not InvalidScoreIterationTerminationCondition().terminate(1.0)

    def test_local_file_saver(self, tmp_path):
        x, y = _toy_data()
        train = ArrayDataSetIterator(DataSet(x, y), batch_size=16)
        test = ArrayDataSetIterator(DataSet(x, y), batch_size=32)
        esc = (EarlyStoppingConfiguration.Builder()
               .epoch_termination_conditions(MaxEpochsTerminationCondition(2))
               .score_calculator(DataSetLossCalculator(test))
               .model_saver(LocalFileModelSaver(str(tmp_path)))
               .build())
        model = MultiLayerNetwork(_mlp())
        result = EarlyStoppingTrainer(esc, model, train).fit()
        assert (tmp_path / "bestModel.bin").exists()
        out = result.best_model.output(x[:2])
        assert out.shape == (2, 3)


class TestTransferLearning:
    def test_freeze_and_nout_replace(self):
        x, y = _toy_data()
        orig = MultiLayerNetwork(_mlp()).init()
        orig.fit(DataSet(x, y))
        new = (TransferLearning.Builder(orig)
               .fine_tune_configuration(
                   FineTuneConfiguration.Builder().updater(Sgd(1e-3)).build())
               .set_feature_extractor(0)
               .n_out_replace(2, 5)
               .build())
        assert new.conf.layers[0].frozen
        assert not new.conf.layers[2].frozen
        assert new.conf.layers[2].n_out == 5
        # frozen layer kept original weights
        w_old = np.asarray(orig.train_state.params["layer_0"]["W"])
        w_new = np.asarray(new.train_state.params["layer_0"]["W"])
        np.testing.assert_array_equal(w_old, w_new)
        out = new.output(x[:4])
        assert out.shape == (4, 5)
        # frozen layer does not move during training
        new.fit(DataSet(x, np.eye(5, dtype=np.float32)[
            np.random.default_rng(0).integers(0, 5, len(x))]))
        np.testing.assert_array_equal(
            w_old, np.asarray(new.train_state.params["layer_0"]["W"]))

    def test_remove_and_add_layers(self):
        x, y = _toy_data()
        orig = MultiLayerNetwork(_mlp()).init()
        new = (TransferLearning.Builder(orig)
               .remove_output_layer()
               .add_layer(DenseLayer(n_out=12, activation=Activation.RELU))
               .add_layer(OutputLayer(n_out=3, loss=LossFunction.MCXENT,
                                      activation=Activation.SOFTMAX))
               .build())
        assert len(new.conf.layers) == 4
        assert new.output(x[:2]).shape == (2, 3)

    def test_compute_dtype_override(self):
        """FineTuneConfiguration.compute_dtype flips the whole fine-tuned
        model to bf16 compute (the standard recipe for f32 Keras imports
        on TPU — round 5); params stay f32 and training still works."""
        x, y = _toy_data()
        orig = MultiLayerNetwork(_mlp()).init()
        new = (TransferLearning.Builder(orig)
               .fine_tune_configuration(
                   FineTuneConfiguration.Builder().updater(Sgd(1e-3))
                   .compute_dtype("bfloat16").build())
               .build())
        assert new.conf.global_config.compute_dtype == "bfloat16"
        # param dtype untouched
        assert np.asarray(
            new.train_state.params["layer_0"]["W"]).dtype == np.float32
        new.fit(DataSet(x, y))
        out = np.asarray(new.output(x[:4]), np.float32)
        assert np.isfinite(out).all()

    def test_helper_featurize(self):
        x, y = _toy_data()
        orig = MultiLayerNetwork(_mlp()).init()
        frozen = (TransferLearning.Builder(orig)
                  .set_feature_extractor(1)
                  .build())
        helper = TransferLearningHelper(frozen)
        feat = helper.featurize(DataSet(x, y))
        assert feat.features.shape == (64, 8)
        helper.fit_featurized(feat)
        out = helper.unfrozen_mln().output(feat.features[:4])
        assert out.shape == (4, 3)


class TestRegularizationConf:
    @pytest.mark.parametrize("do", [Dropout(0.5), AlphaDropout(0.2),
                                    GaussianDropout(0.3), GaussianNoise(0.1)])
    def test_dropout_family_trains(self, do):
        x, y = _toy_data()
        model = MultiLayerNetwork(_mlp(dropout=do)).init()
        before = model.score(DataSet(x, y))
        model.fit(ArrayDataSetIterator(DataSet(x, y), batch_size=32), epochs=3)
        assert np.isfinite(model.score(DataSet(x, y)))
        # inference must be deterministic (no dropout at eval)
        o1 = np.asarray(model.output(x[:8]))
        o2 = np.asarray(model.output(x[:8]))
        np.testing.assert_array_equal(o1, o2)

    @pytest.mark.parametrize("wn", [
        WeightNoise(NormalDistribution(0.0, 0.05)),
        WeightNoise(NormalDistribution(1.0, 0.05), additive=False),
        DropConnect(0.3),
    ])
    def test_weight_noise_trains(self, wn):
        x, y = _toy_data()
        model = MultiLayerNetwork(_mlp(weight_noise=wn)).init()
        model.fit(DataSet(x, y))
        assert np.isfinite(model.score())
        # stored params not perturbed by inference
        o1 = np.asarray(model.output(x[:8]))
        o2 = np.asarray(model.output(x[:8]))
        np.testing.assert_array_equal(o1, o2)

    def test_max_norm_constraint(self):
        x, y = _toy_data()
        model = MultiLayerNetwork(
            _mlp(constraints=(MaxNormConstraint(max_norm=0.5),))).init()
        model.fit(ArrayDataSetIterator(DataSet(x, y), batch_size=32), epochs=2)
        w = np.asarray(model.train_state.params["layer_0"]["W"])
        norms = np.sqrt((w ** 2).sum(axis=0))
        assert np.all(norms <= 0.5 + 1e-5)

    def test_unit_norm_and_nonneg(self):
        x, y = _toy_data()
        model = MultiLayerNetwork(
            _mlp(constraints=(NonNegativeConstraint(),))).init()
        model.fit(DataSet(x, y))
        w = np.asarray(model.train_state.params["layer_0"]["W"])
        assert np.all(w >= 0.0)

        model2 = MultiLayerNetwork(
            _mlp(constraints=(UnitNormConstraint(),))).init()
        model2.fit(DataSet(x, y))
        w2 = np.asarray(model2.train_state.params["layer_0"]["W"])
        np.testing.assert_allclose(np.sqrt((w2 ** 2).sum(axis=0)), 1.0,
                                   atol=1e-5)

    def test_distribution_weight_init(self):
        x, y = _toy_data()
        for dist in (NormalDistribution(0.0, 0.01),
                     UniformDistribution(-0.1, 0.1),
                     OrthogonalDistribution()):
            model = MultiLayerNetwork(_mlp(weight_init=dist)).init()
            assert model.output(x[:2]).shape == (2, 3)
