"""Flash-attention Pallas kernel vs the plain XLA attention path.

The reference validates accelerated helpers against the built-in path
(deeplearning4j-cuda tests: ValidateCudnnLSTM, CuDNNGradientChecks —
SURVEY §4 "accelerated-vs-reference validation"); same idea here, with
the kernel run in interpreter mode on the CPU mesh.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.layers.attention import (
    scaled_dot_product_attention)
from deeplearning4j_tpu.ops.pallas_kernels import attention, flash_attention


def _qkv(rng, n=2, t=48, h=4, dh=16):
    q = rng.normal(size=(n, t, h, dh)).astype(np.float32)
    k = rng.normal(size=(n, t, h, dh)).astype(np.float32)
    v = rng.normal(size=(n, t, h, dh)).astype(np.float32)
    return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_plain_forward(rng, causal):
    q, k, v = _qkv(rng)
    ref = scaled_dot_product_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_respects_key_mask(rng):
    q, k, v = _qkv(rng, t=32)
    mask = np.ones((2, 32), np.float32)
    mask[0, 20:] = 0.0
    mask[1, 5:] = 0.0
    ref = scaled_dot_product_attention(q, k, v, mask=jnp.asarray(mask))
    out = flash_attention(q, k, v, mask=jnp.asarray(mask), block_q=8,
                          block_k=8, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_unaligned_lengths(rng):
    """T not a multiple of the block size exercises the padding path."""
    q, k, v = _qkv(rng, t=37)
    ref = scaled_dot_product_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, block_q=16, block_k=16,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gradients_match(rng, causal):
    q, k, v = _qkv(rng, n=1, t=32, h=2, dh=8)
    mask = np.ones((1, 32), np.float32)
    mask[0, 28:] = 0.0
    mask = jnp.asarray(mask)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, mask=mask, causal=causal, block_q=8,
                            block_k=8, interpret=True)
        return jnp.sum(o * o)

    def loss_ref(q, k, v):
        o = scaled_dot_product_attention(q, k, v, mask=mask, causal=causal)
        return jnp.sum(o * o)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)


def test_attention_dispatch_falls_back(rng):
    """Helper-SPI: off-TPU the dispatcher uses the plain path and the
    result is identical to calling it directly."""
    q, k, v = _qkv(rng, t=16)
    out = attention(q, k, v)
    ref = scaled_dot_product_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-6)


class TestFlashBlockLayout:
    """Regression for the TPU lowering constraint: the mask rides as
    (n, 1, tk) and lse as (n, h, tq, 1) so block trailing dims are legal.
    On CPU this runs the same kernel in interpret mode; on TPU it must
    compile WITHOUT falling back (the silent-fallback path once hid a
    never-ran kernel)."""

    def test_flash_direct_no_fallback(self, rng):
        import jax.numpy as jnp
        from deeplearning4j_tpu.ops.pallas_kernels import flash_attention

        N, T, H, Dh = 2, 256, 4, 64
        mk = lambda: jnp.asarray(
            rng.normal(size=(N, T, H, Dh)).astype(np.float32))
        q, k, v = mk(), mk(), mk()
        # call flash_attention directly: any lowering error raises here
        o = flash_attention(q, k, v, causal=True)
        s = jnp.einsum("nthd,nshd->nhts", q, k) / np.sqrt(Dh)
        m = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(m[None, None], s, -1e30)
        ref = jnp.einsum("nhts,nshd->nthd", jax.nn.softmax(s, -1), v)
        np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                                   rtol=1e-2, atol=1e-2)

    def test_large_blocks_clamp_to_sequence(self, rng):
        import jax.numpy as jnp
        from deeplearning4j_tpu.ops.pallas_kernels import flash_attention

        # default blocks (1024) larger than T: must clamp and still work
        N, T, H, Dh = 1, 64, 2, 16
        mk = lambda: jnp.asarray(
            rng.normal(size=(N, T, H, Dh)).astype(np.float32))
        o = flash_attention(mk(), mk(), mk())
        assert o.shape == (N, T, H, Dh)
        assert np.isfinite(np.asarray(o)).all()

    def test_fully_masked_row_outputs_zero(self, rng):
        """Regression: a fully-padded sequence must produce zeros (the
        reference path's behavior), not mean(v) — the online-softmax
        accumulator sees exp(0)=1 garbage until the first valid key."""
        import jax.numpy as jnp
        from deeplearning4j_tpu.nn.layers.attention import (
            scaled_dot_product_attention)
        from deeplearning4j_tpu.ops.pallas_kernels import flash_attention

        N, T, H, Dh = 3, 64, 2, 16
        mk = lambda: jnp.asarray(
            rng.normal(size=(N, T, H, Dh)).astype(np.float32))
        q, k, v = mk(), mk(), mk()
        mask = np.ones((N, T), np.float32)
        mask[1] = 0.0          # fully padded sequence
        mask[2, 20:] = 0.0     # ragged tail
        mask = jnp.asarray(mask)
        o = flash_attention(q, k, v, mask=mask)
        r = scaled_dot_product_attention(q, k, v, mask=mask)
        np.testing.assert_allclose(np.asarray(o), np.asarray(r),
                                   rtol=1e-2, atol=1e-2)
        assert np.abs(np.asarray(o[1])).max() < 1e-6
        # gradients through the masked batch match the reference too
        g1 = jax.grad(lambda v: jnp.sum(
            flash_attention(q, k, v, mask=mask) ** 2))(v)
        g2 = jax.grad(lambda v: jnp.sum(
            scaled_dot_product_attention(q, k, v, mask=mask) ** 2))(v)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                   rtol=1e-2, atol=1e-2)

    def test_masked_rows_nonzero_cotangent(self, rng):
        """Backward with sum() loss (cotangent 1 on padded-row outputs):
        grads through fully-masked rows must be zero, not exp(0) garbage."""
        import jax.numpy as jnp
        from deeplearning4j_tpu.nn.layers.attention import (
            scaled_dot_product_attention)
        from deeplearning4j_tpu.ops.pallas_kernels import flash_attention

        N, T, H, Dh = 2, 64, 2, 16
        mk = lambda: jnp.asarray(
            rng.normal(size=(N, T, H, Dh)).astype(np.float32))
        q, k, v = mk(), mk(), mk()
        mask = np.ones((N, T), np.float32)
        mask[1] = 0.0
        mask = jnp.asarray(mask)
        for wrt in (0, 1, 2):
            g1 = jax.grad(lambda *a: jnp.sum(
                flash_attention(*a, mask=mask)), argnums=wrt)(q, k, v)
            g2 = jax.grad(lambda *a: jnp.sum(
                scaled_dot_product_attention(*a, mask=mask)),
                argnums=wrt)(q, k, v)
            np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                       rtol=1e-2, atol=1e-2)


class TestFlashPallasBackward:
    """The round-4 Pallas dq/dk/dv kernels vs the jnp/scan reference VJP
    (DL4J_FLASH_BWD=xla) and vs plain-XLA attention gradients — both
    passes in kernels, reference analog: ValidateCudnnLSTM checking
    backprop too."""

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_xla_vjp_reference(self, rng, causal, monkeypatch):
        q, k, v = _qkv(rng, n=2, t=64, h=2, dh=16)
        mask = np.ones((2, 64), np.float32)
        mask[0, 50:] = 0.0
        mask = jnp.asarray(mask)
        do = jnp.asarray(rng.normal(size=(2, 64, 2, 16))
                         .astype(np.float32))

        def run():
            def f(q, k, v):
                o = flash_attention(q, k, v, mask=mask, causal=causal,
                                    block_q=16, block_k=16,
                                    interpret=True)
                return jnp.sum(o * do)
            return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

        monkeypatch.setenv("DL4J_FLASH_BWD", "pallas")
        gp = run()
        monkeypatch.setenv("DL4J_FLASH_BWD", "xla")
        gx = run()
        for a, b, name in zip(gp, gx, "q k v".split()):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5,
                err_msg=f"d{name} mismatch vs scan reference")

    @pytest.mark.parametrize("causal", [False, True])
    def test_bwd_impl_parameter(self, rng, causal, monkeypatch):
        """Explicit bwd_impl selects the backward programmatically and
        overrides the env var (advisor r4: no ambient-state dependence).
        The pallas/xla backwards agree numerically, so the override is
        made OBSERVABLE by instrumenting the pallas entry point."""
        from deeplearning4j_tpu.ops import pallas_kernels as pk
        q, k, v = _qkv(rng, n=2, t=32, h=2, dh=16)
        do = jnp.asarray(rng.normal(size=(2, 32, 2, 16))
                         .astype(np.float32))
        calls = []
        real = pk._flash_backward_pallas
        monkeypatch.setattr(
            pk, "_flash_backward_pallas",
            lambda *a, **kw: calls.append(1) or real(*a, **kw))
        # env says xla; the explicit param must still control the choice
        monkeypatch.setenv("DL4J_FLASH_BWD", "xla")

        def run(impl):
            def f(q, k, v):
                o = flash_attention(q, k, v, causal=causal, block_q=16,
                                    block_k=16, interpret=True,
                                    bwd_impl=impl)
                return jnp.sum(o * do)
            return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

        gx = run("xla")
        assert not calls, "bwd_impl='xla' must not touch the pallas bwd"
        gp = run("pallas")
        assert calls, "bwd_impl='pallas' must override DL4J_FLASH_BWD=xla"
        for a, b, name in zip(gp, gx, "q k v".split()):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5,
                err_msg=f"d{name} mismatch pallas vs xla bwd_impl")
        with pytest.raises(ValueError, match="bwd_impl"):
            flash_attention(q, k, v, bwd_impl="cuda")

    def test_unaligned_causal_masked_grads(self, rng):
        """Padding path + causal + key mask through the Pallas bwd."""
        q, k, v = _qkv(rng, n=1, t=37, h=2, dh=8)
        mask = np.ones((1, 37), np.float32)
        mask[0, 30:] = 0.0
        mask = jnp.asarray(mask)

        def loss_flash(q, k, v):
            o = flash_attention(q, k, v, mask=mask, causal=True,
                                block_q=8, block_k=8, interpret=True)
            return jnp.sum(jnp.tanh(o))

        def loss_ref(q, k, v):
            o = scaled_dot_product_attention(q, k, v, mask=mask,
                                             causal=True)
            return jnp.sum(jnp.tanh(o))

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-4, atol=5e-5)

    def test_fully_masked_rows_zero_grads(self, rng):
        """lse == _NEG rows (query padding / fully-masked) must emit
        exactly zero dq and contribute nothing to dk/dv."""
        q, k, v = _qkv(rng, n=1, t=16, h=1, dh=8)
        mask = np.zeros((1, 16), np.float32)
        mask[0, :4] = 1.0
        mask = jnp.asarray(mask)

        def f(q, k, v):
            o = flash_attention(q, k, v, mask=mask, causal=False,
                                block_q=8, block_k=8, interpret=True)
            return jnp.sum(o)

        dq, dk, dv = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        assert np.isfinite(np.asarray(dq)).all()
        assert np.isfinite(np.asarray(dk)).all()
        np.testing.assert_allclose(np.asarray(dk)[0, 4:], 0.0, atol=1e-6)
        np.testing.assert_allclose(np.asarray(dv)[0, 4:], 0.0, atol=1e-6)
