"""Flash-attention Pallas kernel vs the plain XLA attention path.

The reference validates accelerated helpers against the built-in path
(deeplearning4j-cuda tests: ValidateCudnnLSTM, CuDNNGradientChecks —
SURVEY §4 "accelerated-vs-reference validation"); same idea here, with
the kernel run in interpreter mode on the CPU mesh.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.layers.attention import (
    scaled_dot_product_attention)
from deeplearning4j_tpu.ops.pallas_kernels import attention, flash_attention


def _qkv(rng, n=2, t=48, h=4, dh=16):
    q = rng.normal(size=(n, t, h, dh)).astype(np.float32)
    k = rng.normal(size=(n, t, h, dh)).astype(np.float32)
    v = rng.normal(size=(n, t, h, dh)).astype(np.float32)
    return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_plain_forward(rng, causal):
    q, k, v = _qkv(rng)
    ref = scaled_dot_product_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_respects_key_mask(rng):
    q, k, v = _qkv(rng, t=32)
    mask = np.ones((2, 32), np.float32)
    mask[0, 20:] = 0.0
    mask[1, 5:] = 0.0
    ref = scaled_dot_product_attention(q, k, v, mask=jnp.asarray(mask))
    out = flash_attention(q, k, v, mask=jnp.asarray(mask), block_q=8,
                          block_k=8, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_unaligned_lengths(rng):
    """T not a multiple of the block size exercises the padding path."""
    q, k, v = _qkv(rng, t=37)
    ref = scaled_dot_product_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, block_q=16, block_k=16,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gradients_match(rng, causal):
    q, k, v = _qkv(rng, n=1, t=32, h=2, dh=8)
    mask = np.ones((1, 32), np.float32)
    mask[0, 28:] = 0.0
    mask = jnp.asarray(mask)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, mask=mask, causal=causal, block_q=8,
                            block_k=8, interpret=True)
        return jnp.sum(o * o)

    def loss_ref(q, k, v):
        o = scaled_dot_product_attention(q, k, v, mask=mask, causal=causal)
        return jnp.sum(o * o)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)


def test_attention_dispatch_falls_back(rng):
    """Helper-SPI: off-TPU the dispatcher uses the plain path and the
    result is identical to calling it directly."""
    q, k, v = _qkv(rng, t=16)
    out = attention(q, k, v)
    ref = scaled_dot_product_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-6)
