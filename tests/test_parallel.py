"""Parallel training tests on the 8-device virtual CPU mesh.

Analog of the reference's distributed test strategy (SURVEY §4): Spark
local[N] + 'distributed == single-machine math' golden tests
(TestCompareParameterAveragingSparkVsSingleMachine), ParallelWrapper
multi-worker suites — here: sharded-vs-single-device equivalence for
sync data parallelism, and convergence for local-SGD averaging.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import ArrayDataSetIterator, DataSet
from deeplearning4j_tpu.datasets.fetchers import IrisDataSetIterator
from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
from deeplearning4j_tpu.nn.layers.output import OutputLayer
from deeplearning4j_tpu.ops.activations import Activation
from deeplearning4j_tpu.optimize.updaters import Adam, Sgd
from deeplearning4j_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, create_mesh
from deeplearning4j_tpu.parallel.sharding import infer_param_shardings
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper, TrainingMode


def mlp_conf(seed=1, lr=0.1):
    return (NeuralNetConfiguration.Builder()
            .seed(seed)
            .updater(Sgd(lr))
            .list()
            .layer(DenseLayer(n_out=16, activation=Activation.TANH))
            .layer(OutputLayer(n_out=3))
            .set_input_type(InputType.feed_forward(4))
            .build())


def test_mesh_creation():
    mesh = create_mesh()
    assert mesh.shape[DATA_AXIS] == 8
    mesh2 = create_mesh({DATA_AXIS: -1, MODEL_AXIS: 2})
    assert mesh2.shape == {DATA_AXIS: 4, MODEL_AXIS: 2}
    with pytest.raises(ValueError):
        create_mesh({DATA_AXIS: 3})


def test_sync_dp_matches_single_device():
    """SHARED_GRADIENTS over 8 shards == single-device training on the full
    batch (same math: mean loss over the global batch). The reference's
    golden-test pattern (TestCompareParameterAveragingSparkVsSingleMachine)."""
    it = IrisDataSetIterator(batch_size=64)

    single = MultiLayerNetwork(mlp_conf()).init()
    single.fit(it, epochs=3)

    parallel_model = MultiLayerNetwork(mlp_conf()).init()
    w = (ParallelWrapper.builder(parallel_model)
         .training_mode(TrainingMode.SHARED_GRADIENTS)
         .workers(8)
         .build())
    w.fit(it, epochs=3)

    for a, b in zip(jax.tree_util.tree_leaves(single.params),
                    jax.tree_util.tree_leaves(parallel_model.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_averaging_mode_converges():
    model = MultiLayerNetwork(mlp_conf(lr=0.05)).init()
    w = (ParallelWrapper.builder(model)
         .training_mode(TrainingMode.AVERAGING)
         .workers(4)
         .averaging_frequency(4)
         .build())
    it = IrisDataSetIterator(batch_size=32)
    w.fit(it, epochs=40)
    acc = model.evaluate(IrisDataSetIterator(batch_size=150)).accuracy()
    assert acc > 0.85, acc


def test_averaging_replicas_stay_in_sync():
    """After each averaging round, params are identical across the mesh
    (pmean makes them so) — the analog of the reference's uniform-model
    assertions in ParallelWrapper tests."""
    model = MultiLayerNetwork(mlp_conf()).init()
    w = (ParallelWrapper.builder(model)
         .training_mode(TrainingMode.AVERAGING)
         .workers(8).averaging_frequency(2).build())
    it = IrisDataSetIterator(batch_size=16)
    w.fit(it, epochs=1)
    # params are fully-replicated jax arrays: is_fully_replicated property
    for leaf in jax.tree_util.tree_leaves(model.params):
        assert leaf.sharding.is_fully_replicated


def test_tensor_parallel_forward_matches_replicated():
    """TP param sharding over the model axis must not change results —
    GSPMD inserts collectives, math is identical."""
    conf = mlp_conf()
    model = MultiLayerNetwork(conf).init()
    x = np.random.default_rng(0).normal(size=(8, 4)).astype(np.float32)
    y_repl = np.asarray(model.output(x))

    mesh = create_mesh({DATA_AXIS: 2, MODEL_AXIS: 4})
    shardings = infer_param_shardings(model.params, mesh)
    sharded_params = jax.tree_util.tree_map(jax.device_put, model.params,
                                            shardings)
    # the 16-wide hidden layer shards 4-way on model axis
    assert not sharded_params["layer_0"]["W"].sharding.is_fully_replicated

    def fwd(params, state, xx):
        hidden, _ = model._forward(params, state, xx, None, False, None,
                                   upto=len(model.layers) - 1)
        logits = model.layers[-1].pre_output(params["layer_1"], hidden)
        return jax.nn.softmax(logits, axis=-1)

    y_tp = np.asarray(jax.jit(fwd)(sharded_params,
                                   model.train_state.model_state,
                                   jnp.asarray(x)))
    np.testing.assert_allclose(y_repl, y_tp, rtol=1e-5, atol=1e-6)
