"""Round-3 TP extensions: bottleneck conv-chain pairing and hidden-major
LSTM sharding — golden "TP grads == replicated grads" tests on the
8-device virtual CPU mesh, plus the collective census."""

import jax
import numpy as np
from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu.datasets.dataset import ArrayDataSetIterator, DataSet
from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork
from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.recurrent import LSTM
from deeplearning4j_tpu.nn.layers.output import RnnOutputLayer
from deeplearning4j_tpu.ops.activations import Activation
from deeplearning4j_tpu.optimize.updaters import Sgd
from deeplearning4j_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, create_mesh
from deeplearning4j_tpu.parallel.tensor_parallel import (
    count_collectives,
    plan_tp,
)
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper, TrainingMode
from deeplearning4j_tpu.zoo.models import ResNet50


def _assert_trees_close(a, b, rtol=5e-4, atol=5e-5):
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=rtol, atol=atol)


def small_resnet():
    # 32x32 + tiny lr: at smaller geometry the BatchNorms over 1x1
    # spatial planes make gradients chaotic (max |grad| ~300 at init),
    # so cross-device float reassociation would swamp the comparison
    return ResNet50(num_classes=4, height=32, width=32, channels=3,
                    seed=5, updater=Sgd(1e-3))


def test_conv_chain_plan_pairs_bottlenecks():
    """Every bottleneck's a/b convs go column-parallel, c row-parallel,
    and the in-chain BatchNorms (params AND running stats) shard."""
    model = small_resnet().init()
    mesh = create_mesh({DATA_AXIS: 4, MODEL_AXIS: 2})
    plan = plan_tp(model, mesh)
    sh = plan.param_shardings
    assert sh["s0b0_a_conv"]["W"].spec == P(None, None, None, MODEL_AXIS)
    assert sh["s0b0_b_conv"]["W"].spec == P(None, None, None, MODEL_AXIS)
    assert sh["s0b0_c_conv"]["W"].spec == P(None, None, MODEL_AXIS, None)
    assert sh["s0b0_a_bn"]["gamma"].spec == P(MODEL_AXIS)
    assert plan.state_shardings["s0b0_a_bn"]["mean"].spec == P(MODEL_AXIS)
    assert plan.act_kinds["s0b0_a_conv"] == "sharded"
    assert plan.act_kinds["s0b0_c_conv"] == "replicated"
    # downsample convs are NOT part of a chain: fallback column rules
    assert sh["s0b0_ds_conv"]["W"].spec == P(None, None, None, MODEL_AXIS)


def bottleneck_graph(filters=8, classes=4):
    """One ResNet bottleneck (a/b/c convs + BNs + ds shortcut) + head —
    shallow enough that BatchNorm statistics are well-conditioned, so
    the TP-vs-replicated comparison is not swamped by the chaotic
    1/σ³ amplification a 50-layer random-init stack exhibits."""
    from deeplearning4j_tpu.nn.graph.vertices import ElementWiseVertex
    from deeplearning4j_tpu.nn.layers.convolution import (
        ConvolutionLayer, ConvolutionMode)
    from deeplearning4j_tpu.nn.layers.feedforward import ActivationLayer
    from deeplearning4j_tpu.nn.layers.normalization import (
        BatchNormalization)
    from deeplearning4j_tpu.nn.layers.output import (
        GlobalPoolingLayer, OutputLayer)
    from deeplearning4j_tpu.ops.losses import LossFunction

    g = (NeuralNetConfiguration.Builder()
         .seed(5).updater(Sgd(0.01)).graph_builder()
         .add_inputs("in")
         .set_input_types(InputType.convolutional(8, 8, 6)))

    def conv_bn(name, src, n_out, k, act=True):
        g.add_layer(f"{name}_conv", ConvolutionLayer(
            n_out=n_out, kernel_size=k, stride=(1, 1),
            convolution_mode=ConvolutionMode.SAME, has_bias=False,
            activation=Activation.IDENTITY), src)
        g.add_layer(f"{name}_bn", BatchNormalization(), f"{name}_conv")
        if not act:
            return f"{name}_bn"
        g.add_layer(f"{name}_act",
                    ActivationLayer(activation=Activation.RELU),
                    f"{name}_bn")
        return f"{name}_act"

    x = conv_bn("a", "in", filters, (1, 1))
    x = conv_bn("b", x, filters, (3, 3))
    x = conv_bn("c", x, filters * 4, (1, 1), act=False)
    sc = conv_bn("ds", "in", filters * 4, (1, 1), act=False)
    g.add_vertex("add", ElementWiseVertex(op="add"), x, sc)
    g.add_layer("out_act", ActivationLayer(activation=Activation.RELU),
                "add")
    g.add_layer("pool", GlobalPoolingLayer(), "out_act")
    g.add_layer("out", OutputLayer(n_out=classes,
                                   loss=LossFunction.MCXENT,
                                   activation=Activation.SOFTMAX),
                "pool")
    g.set_outputs("out")
    return g.build()


def test_tp_conv_grads_match_replicated():
    """One SGD step of the TP-paired bottleneck == replicated model."""
    from deeplearning4j_tpu.models.computation_graph import (
        ComputationGraph)
    rng = np.random.default_rng(0)
    x = rng.normal(0, 1, (16, 8, 8, 6)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 16)]
    it = ArrayDataSetIterator(DataSet(x, y), batch_size=16)

    single = ComputationGraph(bottleneck_graph()).init()
    single.fit(it, epochs=2)
    it.reset()

    tp_model = ComputationGraph(bottleneck_graph()).init()
    mesh = create_mesh({DATA_AXIS: 4, MODEL_AXIS: 2})
    plan = plan_tp(tp_model, mesh)
    # the structural chain detector must have paired this block
    assert plan.param_shardings["a_conv"]["W"].spec == \
        P(None, None, None, MODEL_AXIS)
    assert plan.param_shardings["c_conv"]["W"].spec == \
        P(None, None, MODEL_AXIS, None)
    w = (ParallelWrapper.builder(tp_model)
         .mesh(mesh)
         .training_mode(TrainingMode.SHARED_GRADIENTS)
         .tensor_parallel()
         .build())
    w.fit(it, epochs=2)
    _assert_trees_close(single.params, tp_model.params,
                        rtol=2e-3, atol=2e-4)


def lstm_conf(hidden=16, gate_layout="hidden_major"):
    return (NeuralNetConfiguration.Builder()
            .seed(9)
            .updater(Sgd(0.05))
            .list()
            .layer(LSTM(n_out=hidden, gate_layout=gate_layout))
            .layer(LSTM(n_out=hidden, gate_layout=gate_layout))
            .layer(RnnOutputLayer(n_out=3))
            .set_input_type(InputType.recurrent(6, 5))
            .build())


def test_hidden_major_lstm_matches_gate_major_math():
    """The two packings are the same function of their own params: with
    permuted-equivalent weights the outputs coincide."""
    rng = np.random.default_rng(2)
    x = rng.normal(0, 1, (4, 5, 6)).astype(np.float32)
    gm = MultiLayerNetwork(lstm_conf(gate_layout="gate_major")).init()
    hm = MultiLayerNetwork(lstm_conf(gate_layout="hidden_major")).init()
    # copy gate-major params into hidden-major layout: col h*4+g <- g*H+h
    h = 16
    perm = np.arange(4 * h).reshape(4, h).T.reshape(-1)
    new_p = dict(hm.params)
    for lname in ("layer_0", "layer_1"):
        src = gm.params[lname]
        new_p[lname] = {"Wx": src["Wx"][:, perm], "Wh": src["Wh"][:, perm],
                        "b": src["b"][perm]}
    new_p["layer_2"] = gm.params["layer_2"]
    hm.train_state = hm.train_state._replace(params=new_p)
    np.testing.assert_allclose(np.asarray(hm.output(x)),
                               np.asarray(gm.output(x)),
                               rtol=1e-5, atol=1e-6)


def test_tp_lstm_grads_match_replicated():
    """One SGD step of the hidden-sharded LSTM stack == replicated."""
    rng = np.random.default_rng(3)
    x = rng.normal(0, 1, (8, 5, 6)).astype(np.float32)
    y = np.zeros((8, 5, 3), np.float32)
    y[np.arange(8)[:, None], np.arange(5)[None, :],
      rng.integers(0, 3, (8, 5))] = 1.0
    it = ArrayDataSetIterator(DataSet(x, y), batch_size=8)

    single = MultiLayerNetwork(lstm_conf()).init()
    single.fit(it, epochs=1)
    it.reset()

    tp_model = MultiLayerNetwork(lstm_conf()).init()
    mesh = create_mesh({DATA_AXIS: 4, MODEL_AXIS: 2})
    plan = plan_tp(tp_model, mesh)
    assert plan.param_shardings["layer_0"]["Wx"].spec == \
        P(None, MODEL_AXIS)
    assert plan.param_shardings["layer_0"]["Wh"].spec == \
        P(None, MODEL_AXIS)
    w = (ParallelWrapper.builder(tp_model)
         .mesh(mesh)
         .training_mode(TrainingMode.SHARED_GRADIENTS)
         .tensor_parallel()
         .build())
    w.fit(it, epochs=1)
    _assert_trees_close(single.params, tp_model.params,
                        rtol=1e-3, atol=1e-4)


def test_collective_census_counts_tp_comms():
    """The conv-paired plan's compiled step contains collectives and the
    wrapper's census reports them (per-block design: 1 all-gather +
    1 psum, plus the gradient all-reduce over the data axis)."""
    from deeplearning4j_tpu.models.computation_graph import (
        ComputationGraph)
    rng = np.random.default_rng(1)
    x = rng.normal(0, 1, (16, 8, 8, 6)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 16)]
    tp_model = ComputationGraph(bottleneck_graph()).init()
    mesh = create_mesh({DATA_AXIS: 4, MODEL_AXIS: 2})
    w = (ParallelWrapper.builder(tp_model).mesh(mesh)
         .training_mode(TrainingMode.SHARED_GRADIENTS)
         .tensor_parallel().build())
    counts = w.collective_census(DataSet(x, y))
    assert counts.get("all-reduce", 0) >= 1
    assert sum(counts.values()) >= 2, counts
