"""Canonical dataset-format round-trips (VERDICT r3 #7): SVHN MATLAB
``.mat`` cropped digits, the TinyImageNet JPEG directory tree, and the
Adler32 checksum / file:// mirror contract — the formats the reference's
fetchers parse (SvhnDataFetcher.java:41, TinyImageNetFetcher.java:48,
CacheableExtractableDataSetFetcher.java)."""

import os
import zlib

import numpy as np
import pytest

from deeplearning4j_tpu.datasets import fetchers
from deeplearning4j_tpu.datasets.fetchers import (
    SvhnDataFetcher,
    SvhnDataSetIterator,
    TinyImageNetFetcher,
    fetch_with_mirror,
    verify_checksum,
)

RNG = np.random.default_rng(99)


def _adler32(path):
    a = 1
    with open(path, "rb") as fh:
        a = zlib.adler32(fh.read(), a)
    return a


def _write_svhn_mat(path, n=40):
    """Genuine MATLAB v5/v7 bytes via scipy's libmat writer — the same
    C-format family the canonical distribution uses."""
    from scipy.io import savemat
    x = RNG.integers(0, 256, (32, 32, 3, n), dtype=np.uint8)
    # canonical labels are 1..10 with 10 == digit zero
    y = RNG.integers(1, 11, (n, 1)).astype(np.uint8)
    savemat(path, {"X": x, "y": y})
    return x, y


class TestSvhnMat:
    def test_mat_roundtrip(self, tmp_path, monkeypatch):
        base = tmp_path / "svhn"
        base.mkdir()
        x, y = _write_svhn_mat(str(base / "train_32x32.mat"))
        monkeypatch.setattr(fetchers, "DATA_DIR", str(tmp_path))
        images, labels = SvhnDataFetcher(train=True).fetch()
        assert images.shape == (40, 32, 32, 3)
        # NHWC transpose against the (32,32,3,N) source, exact bytes
        np.testing.assert_allclose(
            images[7], x[:, :, :, 7].astype(np.float32) / 255.0)
        # label 10 → digit 0
        np.testing.assert_array_equal(labels, y.reshape(-1) % 10)

    def test_iterator_over_mat(self, tmp_path, monkeypatch):
        base = tmp_path / "svhn"
        base.mkdir()
        _write_svhn_mat(str(base / "test_32x32.mat"))
        monkeypatch.setattr(fetchers, "DATA_DIR", str(tmp_path))
        it = SvhnDataSetIterator(batch_size=8, train=False)
        batch = next(iter(it))
        assert batch.features.shape == (8, 32, 32, 3)
        assert batch.labels.shape == (8, 10)

    def test_checksum_sidecar_rejects_corruption(self, tmp_path,
                                                 monkeypatch):
        base = tmp_path / "svhn"
        base.mkdir()
        p = str(base / "train_32x32.mat")
        _write_svhn_mat(p)
        good = _adler32(p)
        with open(p + ".adler32", "w") as fh:
            fh.write(str(good))
        monkeypatch.setattr(fetchers, "DATA_DIR", str(tmp_path))
        SvhnDataFetcher(train=True).fetch()          # verifies + stamps
        # corrupt the file; the stale stamp must not mask it
        with open(p, "r+b") as fh:
            fh.seek(100)
            fh.write(b"\xff\xff\xff\xff")
        os.utime(p, (1, 1))
        with pytest.raises(IOError, match="checksum"):
            SvhnDataFetcher(train=True).fetch()

    def test_explicit_checksum_param(self, tmp_path, monkeypatch):
        base = tmp_path / "svhn"
        base.mkdir()
        p = str(base / "train_32x32.mat")
        _write_svhn_mat(p)
        monkeypatch.setattr(fetchers, "DATA_DIR", str(tmp_path))
        SvhnDataFetcher(train=True,
                        expected_checksum=_adler32(p)).fetch()
        with pytest.raises(IOError, match="checksum"):
            SvhnDataFetcher(train=True, expected_checksum=123).fetch()


def _write_tin_tree(root, wnids=("n01443537", "n01629819"), per_class=3):
    """The canonical tiny-imagenet-200 layout with real JPEG bytes."""
    from PIL import Image
    os.makedirs(root)
    with open(os.path.join(root, "wnids.txt"), "w") as fh:
        fh.write("\n".join(wnids) + "\n")
    arrays = {}
    for w in wnids:
        d = os.path.join(root, "train", w, "images")
        os.makedirs(d)
        for i in range(per_class):
            a = RNG.integers(0, 256, (64, 64, 3), dtype=np.uint8)
            name = f"{w}_{i}.JPEG"
            Image.fromarray(a).save(os.path.join(d, name), quality=95)
            arrays[name] = a
    vdir = os.path.join(root, "val", "images")
    os.makedirs(vdir)
    lines = []
    for i, w in enumerate(wnids):
        a = RNG.integers(0, 256, (64, 64, 3), dtype=np.uint8)
        name = f"val_{i}.JPEG"
        Image.fromarray(a).save(os.path.join(vdir, name), quality=95)
        lines.append(f"{name}\t{w}\t0\t0\t62\t62")
    with open(os.path.join(root, "val", "val_annotations.txt"), "w") as fh:
        fh.write("\n".join(lines) + "\n")
    return arrays


class TestTinyImageNetTree:
    def test_train_tree_roundtrip(self, tmp_path, monkeypatch):
        root = str(tmp_path / "tinyimagenet" / "tiny-imagenet-200")
        _write_tin_tree(root)
        monkeypatch.setattr(fetchers, "DATA_DIR", str(tmp_path))
        images, labels = TinyImageNetFetcher(subset=6, train=True).fetch()
        assert images.shape == (6, 64, 64, 3)
        assert images.dtype == np.float32
        assert 0.0 <= images.min() and images.max() <= 1.0
        # round-robin over wnids.txt order → class-balanced subset
        assert sorted(labels.tolist()) == [0, 0, 0, 1, 1, 1]
        # JPEG decode is lossy: same scene within compression tolerance
        assert np.mean(np.abs(images * 255 - np.float32(127))) > 1

    def test_val_split(self, tmp_path, monkeypatch):
        root = str(tmp_path / "tinyimagenet" / "tiny-imagenet-200")
        _write_tin_tree(root)
        monkeypatch.setattr(fetchers, "DATA_DIR", str(tmp_path))
        images, labels = TinyImageNetFetcher(subset=2, train=False).fetch()
        assert images.shape == (2, 64, 64, 3)
        assert labels.tolist() == [0, 1]

    def test_subset_larger_than_corpus_is_capped(self, tmp_path,
                                                 monkeypatch):
        root = str(tmp_path / "tinyimagenet" / "tiny-imagenet-200")
        _write_tin_tree(root, per_class=2)
        monkeypatch.setattr(fetchers, "DATA_DIR", str(tmp_path))
        images, labels = TinyImageNetFetcher(subset=50,
                                             train=True).fetch()
        assert images.shape[0] == 4


class TestPretrainedMirror:
    """ZooModel.init_pretrained's download+checksum path, exercised
    against a file:// mirror (the reference's
    ZooModel.initPretrained:51 contract — VERDICT r2 missing #4)."""

    def test_init_pretrained_from_mirror(self, tmp_path, monkeypatch):
        from deeplearning4j_tpu.models.serialization import save_model
        from deeplearning4j_tpu.zoo.models import LeNet

        # "publish" trained weights on the mirror
        trained = LeNet(num_classes=4).init()
        mirror = tmp_path / "mirror" / "lenet.zip"
        mirror.parent.mkdir()
        save_model(trained, str(mirror))
        monkeypatch.setattr(fetchers, "DATA_DIR",
                            str(tmp_path / "cache"))

        restored = LeNet(num_classes=4).init_pretrained(
            url=mirror.as_uri(), checksum=_adler32(str(mirror)))
        x = RNG.normal(0, 1, (2, 28, 28, 1)).astype(np.float32)
        np.testing.assert_allclose(np.asarray(restored.output(x)),
                                   np.asarray(trained.output(x)),
                                   rtol=1e-6)
        # cached under the zoo's pretrained dir, keyed by url
        cached = os.listdir(os.path.join(str(tmp_path / "cache"),
                                         "pretrained"))
        assert any(f.startswith("LeNet_default_") for f in cached)

    def test_init_pretrained_bad_checksum(self, tmp_path, monkeypatch):
        from deeplearning4j_tpu.models.serialization import save_model
        from deeplearning4j_tpu.zoo.models import LeNet
        mirror = tmp_path / "mirror" / "lenet.zip"
        mirror.parent.mkdir()
        save_model(LeNet(num_classes=4).init(), str(mirror))
        monkeypatch.setattr(fetchers, "DATA_DIR",
                            str(tmp_path / "cache"))
        with pytest.raises(IOError, match="checksum"):
            LeNet(num_classes=4).init_pretrained(url=mirror.as_uri(),
                                                 checksum=99)

    def test_init_pretrained_no_source_errors_clearly(self):
        from deeplearning4j_tpu.zoo.models import LeNet
        with pytest.raises(FileNotFoundError, match="file://"):
            LeNet().init_pretrained()


class TestMirrorContract:
    def test_file_mirror_download_and_verify(self, tmp_path):
        src = tmp_path / "mirror" / "corpus.bin"
        src.parent.mkdir()
        src.write_bytes(b"canonical-corpus-bytes" * 100)
        expected = _adler32(str(src))
        dest = str(tmp_path / "cache" / "corpus.bin")
        out = fetch_with_mirror(src.as_uri(), dest,
                                expected_checksum=expected)
        assert out == dest and os.path.exists(dest)
        # cached path verifies again without re-downloading
        fetch_with_mirror(src.as_uri(), dest, expected_checksum=expected)

    def test_mirror_bad_checksum_purges_file(self, tmp_path):
        src = tmp_path / "mirror" / "corpus.bin"
        src.parent.mkdir()
        src.write_bytes(b"payload")
        dest = str(tmp_path / "cache" / "corpus.bin")
        with pytest.raises(IOError, match="checksum"):
            fetch_with_mirror(src.as_uri(), dest, expected_checksum=42)
        assert not os.path.exists(dest)

    def test_verify_checksum_stamp_skips_rehash(self, tmp_path):
        p = tmp_path / "f.bin"
        p.write_bytes(b"abc")
        good = _adler32(str(p))
        verify_checksum(str(p), good)
        assert os.path.exists(str(p) + ".adler32.ok")
        verify_checksum(str(p), good)   # hits the stamp path
