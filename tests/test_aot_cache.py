"""Persisted AOT executable cache tests (PR 6, parallel/aot_cache.py).

The cache contract: a fresh PROCESS that points at a saved cache reaches
``assert_warm()`` with zero live compiles and produces outputs bitwise
equal to an uncached engine; ANY fingerprint divergence (weights, shapes,
serving contract, versions) falls through to live compile — the cache can
make a cold start fast, never wrong.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from deeplearning4j_tpu.observe.registry import MetricsRegistry
from deeplearning4j_tpu.parallel.aot_cache import (
    AOTExecutableCache,
    enable_xla_cache,
    fingerprint,
)
from deeplearning4j_tpu.parallel.serving import ServingEngine

N_IN = 5
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tiny_model(seed: int = 1):
    from deeplearning4j_tpu.models.multi_layer_network import (
        MultiLayerNetwork)
    from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.inputs import InputType
    from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
    from deeplearning4j_tpu.nn.layers.output import OutputLayer
    from deeplearning4j_tpu.ops.losses import LossFunction
    from deeplearning4j_tpu.optimize.updaters import Adam
    conf = (NeuralNetConfiguration.Builder().seed(seed)
            .updater(Adam(1e-2)).list()
            .layer(DenseLayer(n_out=8))
            .layer(OutputLayer(n_out=3, loss=LossFunction.MCXENT))
            .set_input_type(InputType.feed_forward(N_IN)).build())
    return MultiLayerNetwork(conf).init()


def _engine(model, cache_dir, **kw):
    kw.setdefault("batch_limit", 4)
    kw.setdefault("feature_shape", (N_IN,))
    kw.setdefault("registry", MetricsRegistry())
    return ServingEngine(model, aot_cache_dir=cache_dir,
                         model_version="t1", **kw)


# child script: load the cache in a FRESH process (the only honest test
# of a cold start), prove zero live compiles + bitwise-equal output
_CHILD = """
import json, sys
import numpy as np
sys.path.insert(0, {root!r})
from tests.test_aot_cache import _tiny_model, _engine
from deeplearning4j_tpu.observe.registry import MetricsRegistry

reg = MetricsRegistry()
eng = _engine(_tiny_model(), {cache!r}, registry=reg)
try:
    eng.assert_warm()
    x = np.asarray(json.loads({x!r}), np.float32)
    out = eng.output(x)
    stats = eng.stats()
finally:
    eng.shutdown()
live = 0.0
m = reg.get_metric("dl4j_serving_compiles_total")
for key, v in m.series().items():
    if ("phase", "live") in key:
        live += v
print(json.dumps({{"out": np.asarray(out).tolist(),
                   "aot": stats["aot_cache"],
                   "live_compiles": live,
                   "recompiles": stats["recompiles_after_warmup"]}}))
"""


class TestRoundTrip:
    def test_fresh_process_loads_warm_bitwise(self, tmp_path):
        cache = str(tmp_path / "aot")
        m = _tiny_model()
        rng = np.random.default_rng(0)
        x = rng.normal(size=(3, N_IN)).astype(np.float32)
        # process A: cold cache -> live warmup, auto-save
        eng = _engine(m, cache)
        try:
            want = eng.output(x)
            assert eng.aot_cache.state == "cold"       # saved from cold
            assert os.path.exists(os.path.join(cache, "manifest.json"))
        finally:
            eng.shutdown()
        # process B (fresh python): must load every bucket, compile
        # nothing live, and reproduce process A's bytes exactly
        child = _CHILD.format(root=_ROOT, cache=cache,
                              x=json.dumps(x.tolist()))
        proc = subprocess.run(
            [sys.executable, "-c", child], cwd=_ROOT,
            capture_output=True, text=True, timeout=300,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert proc.returncode == 0, proc.stderr[-2000:]
        got = json.loads(proc.stdout.strip().splitlines()[-1])
        assert got["aot"]["state"] == "warm"
        assert got["aot"]["hits"] > 0
        assert got["live_compiles"] == 0.0
        assert got["recompiles"] == 0
        assert np.array_equal(
            np.asarray(got["out"], np.float32), want)

    def test_same_process_second_engine_hits(self, tmp_path):
        cache = str(tmp_path / "aot")
        m = _tiny_model()
        e1 = _engine(m, cache)
        e1.shutdown()
        e2 = _engine(m, cache)
        try:
            assert e2.aot_cache.state == "warm"
            assert e2.aot_cache.hits > 0
            e2.assert_warm()
        finally:
            e2.shutdown()


class TestFingerprint:
    def test_weights_divergence_misses(self, tmp_path):
        cache = str(tmp_path / "aot")
        e1 = _engine(_tiny_model(seed=1), cache)
        e1.shutdown()
        # different weights, same everything else -> mismatch, live path
        e2 = _engine(_tiny_model(seed=2), cache)
        try:
            assert e2.aot_cache.state == "mismatch"
            assert "weights_sha256" in e2.aot_cache.reason
            e2.assert_warm()            # live warmup still ran
            x = np.zeros((2, N_IN), np.float32)
            e2.output(x)
        finally:
            e2.shutdown()

    def test_contract_divergence_misses(self, tmp_path):
        cache = str(tmp_path / "aot")
        m = _tiny_model()
        e1 = _engine(m, cache, batch_limit=4)
        e1.shutdown()
        # a different ladder is a different serving contract
        e2 = _engine(m, cache, batch_limit=8)
        try:
            assert e2.aot_cache.state == "mismatch"
            assert "serving" in e2.aot_cache.reason
        finally:
            e2.shutdown()

    def test_corrupt_manifest_falls_through(self, tmp_path):
        cache = str(tmp_path / "aot")
        m = _tiny_model()
        e1 = _engine(m, cache)
        e1.shutdown()
        with open(os.path.join(cache, "manifest.json"), "w") as f:
            f.write("{not json")
        e2 = _engine(m, cache)
        try:
            assert e2.aot_cache.state == "mismatch"
            assert "manifest" in e2.aot_cache.reason
            e2.assert_warm()
        finally:
            e2.shutdown()

    def test_corrupt_blob_partial_load(self, tmp_path):
        cache = str(tmp_path / "aot")
        m = _tiny_model()
        e1 = _engine(m, cache)
        e1.shutdown()
        with open(os.path.join(cache, "bucket_2.f32.stablehlo"),
                  "wb") as f:
            f.write(b"garbage")
        e2 = _engine(m, cache)
        try:
            # the other buckets still load; bucket 2 warms live
            assert e2.aot_cache.state == "warm"
            assert e2.aot_cache.misses >= 1
            e2.assert_warm()
            x = np.zeros((2, N_IN), np.float32)
            assert np.array_equal(e2.output(x), np.asarray(m.output(x)))
        finally:
            e2.shutdown()

    def test_fingerprint_covers_the_contract(self):
        m = _tiny_model()
        params = m.train_state.params
        mstate = m.train_state.model_state
        fp = fingerprint(params, mstate, feature_shape=(N_IN,),
                         dtype=np.float32, ladder=(1, 2, 4),
                         bf16=False, model_version="v1")
        for key in ("weights_sha256", "params_spec", "jax", "jaxlib",
                    "backend", "serving", "model_version"):
            assert key in fp, key
        assert fp["serving"]["ladder"] == [1, 2, 4]


class TestPrecisionEntries:
    """Format-2 manifests hold one entry per precision: an int8 save
    must never satisfy an f32 lookup (and vice versa), while both
    coexist in one cache dir with precision-tagged blobs."""

    def _int8_engine(self, model, cache, **kw):
        from deeplearning4j_tpu.parallel.quant import PrecisionPolicy
        rng = np.random.default_rng(7)
        feats = rng.normal(size=(32, N_IN)).astype(np.float32)
        return _engine(model, cache,
                       precision=PrecisionPolicy.int8(feats), **kw)

    def test_precisions_coexist_and_never_cross(self, tmp_path):
        cache = str(tmp_path / "aot")
        m = _tiny_model()
        # f32 saves first
        e1 = _engine(m, cache)
        e1.shutdown()
        # int8 must NOT hit the f32 entry: cold, with a reason that
        # names the diverged axis
        e2 = self._int8_engine(m, cache)
        try:
            assert e2.aot_cache.state == "cold"
            assert "int8" in e2.aot_cache.reason
            e2.assert_warm()
        finally:
            e2.shutdown()
        with open(os.path.join(cache, "manifest.json")) as f:
            manifest = json.load(f)
        assert manifest["format_version"] == 2
        assert sorted(manifest["entries"]) == ["f32", "int8"]
        blobs = sorted(os.listdir(cache))
        assert any(b.endswith(".f32.stablehlo") for b in blobs)
        assert any(b.endswith(".int8.stablehlo") for b in blobs)
        # both precisions now warm-load from the same dir
        for build in (lambda: _engine(m, cache),
                      lambda: self._int8_engine(m, cache)):
            e = build()
            try:
                assert e.aot_cache.state == "warm"
                assert e.aot_cache.hits > 0
                e.assert_warm()
            finally:
                e.shutdown()

    def test_calibration_divergence_named_in_reason(self, tmp_path):
        cache = str(tmp_path / "aot")
        m = _tiny_model()
        e1 = self._int8_engine(m, cache)
        e1.shutdown()
        # tamper with the stored calibration hash: the mismatch reason
        # must name the exact diverged field
        path = os.path.join(cache, "manifest.json")
        with open(path) as f:
            manifest = json.load(f)
        fp = manifest["entries"]["int8"]["fingerprint"]
        fp["serving"]["calibration"] = "deadbeef" * 8
        with open(path, "w") as f:
            json.dump(manifest, f)
        e2 = self._int8_engine(m, cache)
        try:
            assert e2.aot_cache.state == "mismatch"
            assert "serving.calibration" in e2.aot_cache.reason
            e2.assert_warm()
        finally:
            e2.shutdown()


# child: calibrate + quantize in a FRESH process and report the scale
# bits, the calibration hash, and the engine's AOT fingerprint — run
# twice, everything must be bitwise identical (the determinism the
# int8 cache entry's reuse story rests on)
_CALIB_CHILD = """
import json, sys
import numpy as np
sys.path.insert(0, {root!r})
from tests.test_aot_cache import _tiny_model, N_IN
from deeplearning4j_tpu.parallel.aot_cache import fingerprint
from deeplearning4j_tpu.parallel.quant import (
    PrecisionPolicy, quantize_model)

m = _tiny_model()
rng = np.random.default_rng(21)
feats = rng.normal(size=(64, N_IN)).astype(np.float32)
qm = quantize_model(m, PrecisionPolicy.int8(feats))
fp = fingerprint(qm.params, m.train_state.model_state,
                 feature_shape=(N_IN,), dtype=np.float32,
                 ladder=(1, 2, 4), precision="int8",
                 calibration=qm.calibration_hash(), model_version="t1")
print(json.dumps({{
    "scales": {{k: float(np.float32(v)).hex()
               for k, v in sorted(qm.calibration.scales.items())}},
    "calib_hash": qm.calibration.hash(),
    "provenance": qm.calibration_hash(),
    "fingerprint": fp}}, sort_keys=True))
"""


class TestCalibrationDeterminism:
    def test_two_fresh_processes_bitwise_identical(self):
        child = _CALIB_CHILD.format(root=_ROOT)
        runs = []
        for _ in range(2):
            proc = subprocess.run(
                [sys.executable, "-c", child], cwd=_ROOT,
                capture_output=True, text=True, timeout=300,
                env={**os.environ, "JAX_PLATFORMS": "cpu"})
            assert proc.returncode == 0, proc.stderr[-2000:]
            runs.append(json.loads(proc.stdout.strip().splitlines()[-1]))
        a, b = runs
        assert a["scales"] == b["scales"]       # bit-exact hex floats
        assert a["calib_hash"] == b["calib_hash"]
        assert a["provenance"] == b["provenance"]
        assert a["fingerprint"] == b["fingerprint"]
        assert a["fingerprint"]["serving"]["precision"] == "int8"
        assert a["fingerprint"]["serving"]["calibration"] == \
            a["provenance"]


class TestXlaCacheConfig:
    def test_enable_idempotent(self, tmp_path):
        # process-global, first wins; later calls are True no-ops
        assert enable_xla_cache(str(tmp_path / "x1")) is True
        assert enable_xla_cache(str(tmp_path / "x2")) is True

    def test_disabled_without_export(self, tmp_path, monkeypatch):
        c = AOTExecutableCache(str(tmp_path / "a"))
        # simulate a jax without usable export support
        c._export = None
        c.state = "disabled"
        assert c.try_load({}) == {}
        assert c.save(None, (None, None), {}, (1,), None) == 0
