"""FusedBottleneckBlock == unfused conv/BN/ReLU composition, with the
same weights (the accelerated-path-vs-reference-path equivalence tier,
SURVEY §4)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import LayerContext
from deeplearning4j_tpu.nn.layers.fused import FusedBottleneckBlock

RNG = np.random.default_rng(11)


def reference_block(params, state, x, block: FusedBottleneckBlock,
                    train: bool):
    """Plain jnp composition of the same math (conv → BN → ReLU ×3 +
    shortcut), returning (out, new_state)."""
    f32 = jnp.float32
    eps, decay = block.eps, block.decay
    new_state = dict(state)

    def bn(name, y):
        yf = y.astype(f32)
        if train:
            mean = jnp.mean(yf, axis=(0, 1, 2))
            var = jnp.var(yf, axis=(0, 1, 2))
            new_state[f"{name}_mean"] = (decay * state[f"{name}_mean"]
                                         + (1 - decay) * mean)
            new_state[f"{name}_var"] = (decay * state[f"{name}_var"]
                                        + (1 - decay) * var)
        else:
            mean = state[f"{name}_mean"].astype(f32)
            var = state[f"{name}_var"].astype(f32)
        xhat = (yf - mean) * jax.lax.rsqrt(var + eps)
        return xhat * params[f"{name}_gamma"].astype(f32) \
            + params[f"{name}_beta"].astype(f32)

    def conv1x1(y, w, stride=1):
        if stride != 1:
            y = y[:, ::stride, ::stride, :]
        return jnp.einsum("nhwc,co->nhwo", y, w,
                          preferred_element_type=f32).astype(y.dtype)

    def conv3x3(y, w):
        return jax.lax.conv_general_dilated(
            y, w, window_strides=(1, 1), padding="SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            preferred_element_type=f32).astype(y.dtype)

    z = jnp.maximum(bn("bn1", conv1x1(x, params["W1"], block.stride)),
                    0.0).astype(x.dtype)
    z = jnp.maximum(bn("bn2", conv3x3(z, params["W2"])), 0.0) \
        .astype(x.dtype)
    main = bn("bn3", conv1x1(z, params["W3"]))
    if block.downsample:
        shortcut = bn("bnds", conv1x1(x, params["Wds"], block.stride))
    else:
        shortcut = x.astype(f32)
    return jnp.maximum(main + shortcut, 0.0).astype(x.dtype), new_state


@pytest.mark.parametrize("stride,downsample", [(1, False), (2, True),
                                               (1, True)])
def test_block_matches_reference(stride, downsample):
    cin = 32 if not downsample else 16
    block = FusedBottleneckBlock(filters=8, stride=stride,
                                 downsample=downsample)
    it = InputType.convolutional(8, 8, cin)
    params = block.initialize(jax.random.PRNGKey(0), it)
    state = block.init_state(it)
    x = jnp.asarray(RNG.normal(0, 1, (4, 8, 8, cin)).astype(np.float32))

    for train in (True, False):
        ctx = LayerContext(train=train, rng=jax.random.PRNGKey(1))
        y, st = block.apply(params, state, x, ctx)
        yr, str_ = reference_block(params, state, x, block, train)
        np.testing.assert_allclose(np.asarray(y), np.asarray(yr),
                                   rtol=2e-4, atol=2e-4)
        for k in st:
            np.testing.assert_allclose(
                np.asarray(st[k]), np.asarray(str_[k]), rtol=2e-4,
                atol=2e-4, err_msg=f"state {k} (train={train})")


def test_block_grads_match_reference():
    block = FusedBottleneckBlock(filters=4, stride=2, downsample=True)
    it = InputType.convolutional(4, 4, 8)
    params = block.initialize(jax.random.PRNGKey(0), it)
    state = block.init_state(it)
    x = jnp.asarray(RNG.normal(0, 1, (4, 4, 4, 8)).astype(np.float32))
    ctx = LayerContext(train=True)

    def loss_fused(p):
        y, _ = block.apply(p, state, x, ctx)
        return jnp.sum(jnp.tanh(y.astype(jnp.float32)))

    def loss_ref(p):
        y, _ = reference_block(p, state, x, block, True)
        return jnp.sum(jnp.tanh(y.astype(jnp.float32)))

    gf = jax.grad(loss_fused)(params)
    gr = jax.grad(loss_ref)(params)
    for k in gr:
        np.testing.assert_allclose(np.asarray(gf[k]), np.asarray(gr[k]),
                                   rtol=5e-4, atol=5e-4, err_msg=k)


def test_fused_resnet50_trains():
    """ResNet50(fused_blocks=True) compiles and the loss moves. A
    random-init 50-layer BN stack is chaotic over a handful of steps, so
    train enough steps for the trend to dominate the noise and compare
    against the best mid-run score."""
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.zoo.models import ResNet50
    model = ResNet50(num_classes=5, height=32, width=32, channels=3,
                     fused_blocks=True).init()
    rng = np.random.default_rng(0)
    x = rng.normal(0, 1, (8, 32, 32, 3)).astype(np.float32)
    y = np.eye(5, dtype=np.float32)[rng.integers(0, 5, 8)]
    ds = DataSet(x, y)
    model.fit(ds)
    l0 = float(model.score(ds))
    scores = []
    for _ in range(12):
        model.fit(ds)
        scores.append(float(model.score(ds)))
    assert np.isfinite(scores).all()
    assert min(scores) < l0, (l0, scores)


def test_fused_resnet50_matches_unfused_geometry():
    from deeplearning4j_tpu.zoo.models import ResNet50
    m1 = ResNet50(num_classes=7, height=32, width=32, channels=3,
                  fused_blocks=True).init()
    m2 = ResNet50(num_classes=7, height=32, width=32, channels=3,
                  fused_blocks=False).init()
    x = RNG.normal(0, 1, (2, 32, 32, 3)).astype(np.float32)
    assert np.asarray(m1.output(x)).shape == \
        np.asarray(m2.output(x)).shape == (2, 7)
