"""FleetRouter tests (PR 6): admission control, SLO shedding, pools,
hot version swap/rollback, and the persisted AOT executable cache.

The fleet contract under test:

- a shed request fails FAST with a distinct ``ShedError`` (reason
  ``"queue"`` or ``"slo"``) raised synchronously from submit — a caller
  never holds a Future that hangs behind a full queue;
- dispatch goes to the least-loaded engine of the active version;
- ``swap()`` warms the new version before switching, keeps the old one
  as rollback standby, and ``rollback()`` flips back instantly — all
  bitwise-faithful to the respective version's direct output;
- the AIMD controller reacts to the WINDOWED p99 (delta_quantiles), so
  one old spike cannot shed forever;
- ``dl4j_fleet_*`` Prometheus series render.
"""

import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.observe.registry import MetricsRegistry
from deeplearning4j_tpu.parallel.fleet import (
    FleetRouter,
    ShedError,
    _materialize,
)

N_IN = 5


def _tiny_model(seed: int = 1):
    from deeplearning4j_tpu.models.multi_layer_network import (
        MultiLayerNetwork)
    from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.inputs import InputType
    from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
    from deeplearning4j_tpu.nn.layers.output import OutputLayer
    from deeplearning4j_tpu.ops.losses import LossFunction
    from deeplearning4j_tpu.optimize.updaters import Adam
    conf = (NeuralNetConfiguration.Builder().seed(seed)
            .updater(Adam(1e-2)).list()
            .layer(DenseLayer(n_out=8))
            .layer(OutputLayer(n_out=3, loss=LossFunction.MCXENT))
            .set_input_type(InputType.feed_forward(N_IN)).build())
    return MultiLayerNetwork(conf).init()


def _router(**kw):
    kw.setdefault("registry", MetricsRegistry())
    kw.setdefault("window_s", 10.0)     # controller quiet unless asked
    return FleetRouter(**kw)


def _pool_kw():
    return dict(batch_limit=8, feature_shape=(N_IN,))


class Slow:
    """Duck-typed model whose forward blocks — lets tests hold requests
    in flight deterministically."""

    def __init__(self, delay=0.2):
        self.delay = delay

    def output(self, x):
        time.sleep(self.delay)
        return np.zeros((x.shape[0], 3), np.float32)


class TestAdmission:
    def test_queue_shed_fails_fast_distinct_error(self):
        with _router(max_pending=1) as r:
            r.add_pool("slow", Slow(), batch_limit=2)
            f1 = r.submit(np.zeros((1, N_IN), np.float32), model="slow")
            t0 = time.perf_counter()
            with pytest.raises(ShedError) as ei:
                r.submit(np.zeros((1, N_IN), np.float32), model="slow")
            # synchronous refusal, not a timeout: well under the 0.2s
            # the in-flight request takes
            assert time.perf_counter() - t0 < 0.1
            assert ei.value.reason == "queue"
            assert ei.value.model == "slow"
            assert "shed by fleet admission control" in str(ei.value)
            f1.result(timeout=5)        # the admitted one still lands

    def test_slo_shed_reason_and_recovery(self):
        reg = MetricsRegistry()
        with _router(slo_ms=50.0, window_s=0.01, registry=reg) as r:
            r.add_pool("m", _tiny_model(), **_pool_kw())
            pool = r.pool("m")
            # a window of over-SLO completions drives the AIMD up
            for _ in range(20):
                pool.ring.record(0.5)           # 500 ms >> 50 ms SLO
            pool._last_tick = 0.0
            with pool.lock:
                pool._tick_controller(time.monotonic())
            assert pool.shed_fraction == pytest.approx(r.shed_step)
            # force the coin deterministically: always shed
            pool._rand.random = lambda: 0.0
            with pytest.raises(ShedError) as ei:
                r.submit(np.zeros((1, N_IN), np.float32), model="m")
            assert ei.value.reason == "slo"
            # under-SLO windows decay the fraction back to open
            pool._rand.random = lambda: 1.0
            for _ in range(8):
                for _ in range(20):
                    pool.ring.record(0.001)
                pool._last_tick = 0.0
                with pool.lock:
                    pool._tick_controller(time.monotonic())
            assert pool.shed_fraction == 0.0
            # and traffic flows again
            r.output(np.zeros((1, N_IN), np.float32), model="m")
            rendered = reg.render()
            assert 'dl4j_fleet_shed_total' in rendered
            assert 'reason="slo"' in rendered

    def test_windowed_not_cumulative(self):
        """The controller must react to the LAST window, not the whole
        ring: after one spiky window, a clean window reads clean."""
        with _router(slo_ms=50.0, window_s=0.01) as r:
            r.add_pool("m", _tiny_model(), **_pool_kw())
            pool = r.pool("m")
            for _ in range(50):
                pool.ring.record(0.5)
            pool._last_tick = 0.0
            with pool.lock:
                pool._tick_controller(time.monotonic())
            assert pool.windowed_p99_ms > 50.0
            for _ in range(50):
                pool.ring.record(0.001)
            pool._last_tick = 0.0
            with pool.lock:
                pool._tick_controller(time.monotonic())
            # full-ring p99 would still see the 500ms spike; the
            # windowed read must not
            assert pool.windowed_p99_ms < 50.0


class TestDispatch:
    def test_least_loaded(self):
        with _router() as r:
            r.add_pool("m", _tiny_model(), pool_size=2, **_pool_kw())
            pool = r.pool("m")

            class Fake:
                def __init__(self, inflight):
                    self.inflight = inflight
            real = pool.engines
            try:
                a, b = Fake(3), Fake(1)
                pool.engines = [a, b]
                assert pool.least_loaded() is b
                b.inflight = 5
                assert pool.least_loaded() is a
            finally:
                pool.engines = real

    def test_pool_serves_bitwise(self):
        m = _tiny_model()
        rng = np.random.default_rng(0)
        with _router() as r:
            r.add_pool("m", m, pool_size=2, **_pool_kw())
            for n in (1, 3, 8):
                x = rng.normal(size=(n, N_IN)).astype(np.float32)
                assert np.array_equal(r.output(x),
                                      np.asarray(m.output(x)))
            r.assert_warm()

    def test_default_pool_and_unknown_model(self):
        with _router() as r:
            r.add_pool("only", _tiny_model(), **_pool_kw())
            r.output(np.zeros((1, N_IN), np.float32))   # no name needed
            with pytest.raises(ValueError, match="no pool named"):
                r.submit(np.zeros((1, N_IN), np.float32), model="nope")


class TestSwapRollback:
    def test_swap_bitwise_then_rollback(self):
        reg = MetricsRegistry()
        m1, m2 = _tiny_model(1), _tiny_model(2)
        x = np.random.default_rng(3).normal(
            size=(3, N_IN)).astype(np.float32)
        with _router(registry=reg) as r:
            r.add_pool("m", m1, version="v1", **_pool_kw())
            assert np.array_equal(r.output(x), np.asarray(m1.output(x)))
            pool = r.swap("m", m2, "v2")
            assert pool.active_version == "v2"
            assert pool.standby[0] == "v1"
            assert np.array_equal(r.output(x), np.asarray(m2.output(x)))
            r.assert_warm()             # standby stays warm too
            r.rollback("m")
            assert pool.active_version == "v1"
            assert pool.standby[0] == "v2"
            assert np.array_equal(r.output(x), np.asarray(m1.output(x)))
            rendered = reg.render()
            assert 'event="swap"' in rendered
            assert 'event="rollback"' in rendered

    def test_second_swap_retires_oldest(self):
        m1, m2, m3 = _tiny_model(1), _tiny_model(2), _tiny_model(3)
        with _router() as r:
            r.add_pool("m", m1, version="v1", **_pool_kw())
            r.swap("m", m2, "v2")
            v1_engines = r.pool("m").standby[1]
            r.swap("m", m3, "v3")
            pool = r.pool("m")
            assert pool.active_version == "v3"
            assert pool.standby[0] == "v2"
            # v1's engines were shut down, not leaked
            for e in v1_engines:
                with pytest.raises(RuntimeError, match="shut down"):
                    e.submit(np.zeros((1, N_IN), np.float32))

    def test_rollback_without_standby_raises(self):
        with _router() as r:
            r.add_pool("m", _tiny_model(), **_pool_kw())
            with pytest.raises(RuntimeError, match="no standby"):
                r.rollback("m")


class TestMaterialize:
    def test_factory_and_builtin(self):
        m = _tiny_model()
        assert _materialize(m, "p") is m
        built = _materialize(lambda: m, "p")
        assert built is m

    def test_zoo_name(self, monkeypatch):
        from deeplearning4j_tpu.zoo import models as zoo_models
        m = _tiny_model()
        monkeypatch.setattr(zoo_models, "TinyTestEntry", lambda: m,
                            raising=False)
        assert _materialize("TinyTestEntry", "p") is m
        with pytest.raises(ValueError, match="no zoo model"):
            _materialize("NoSuchZooModel", "p")


class TestStatsAndMetrics:
    def test_stats_and_series(self):
        reg = MetricsRegistry()
        with _router(registry=reg, slo_ms=100.0) as r:
            r.add_pool("m", _tiny_model(), **_pool_kw())
            for _ in range(3):
                r.output(np.zeros((2, N_IN), np.float32))
            st = r.stats()
            p = st["pools"]["m"]
            assert p["active_version"] == "v1"
            assert p["pending"] == 0
            assert p["requests"] == 3
            assert st["slo_ms"] == 100.0
            rendered = reg.render()
            for series in ("dl4j_fleet_admitted_total",
                           "dl4j_fleet_pool_depth",
                           "dl4j_fleet_pool_engines"):
                assert series in rendered, series

    def test_shed_maps_to_http_503(self):
        """FleetModule answers a ShedError with 503 + a machine-readable
        body — never a hung request, never a generic 500."""
        from deeplearning4j_tpu.ui.serving_module import FleetModule

        class Refusing:
            def output(self, features, model=None):
                raise ShedError("m", "slo", "over SLO")
        payload, ctype, status = FleetModule(Refusing())._predict(
            None, {}, {"features": [[0.0] * N_IN]})
        assert status == 503
        assert payload == {"error": "shed", "model": "m",
                           "reason": "slo"}
