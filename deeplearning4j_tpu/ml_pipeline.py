"""Pipeline-style estimator API around networks.

Analog of the reference's ``dl4j-spark-ml`` module (SURVEY §2.11:
``SparkDl4jNetwork.scala`` / ``SparkDl4jModel`` — Spark ML Pipeline
stages wrapping a DL4J network). The TPU build has no Spark DataFrames;
the equivalent composable-pipeline surface is estimator/transformer
stages over arrays (the scikit-learn convention), so networks slot into
feature pipelines exactly the way the reference slots into Spark ML.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Tuple, Union

import numpy as np

from deeplearning4j_tpu.datasets.dataset import ArrayDataSetIterator, DataSet


class Transformer:
    """A fitted stage: transform(X) -> X'."""

    def transform(self, X: np.ndarray) -> np.ndarray:
        raise NotImplementedError


class Estimator:
    """An unfitted stage: fit(X, y) -> Transformer."""

    def fit(self, X: np.ndarray, y: Optional[np.ndarray] = None
            ) -> Transformer:
        raise NotImplementedError


class StandardScaler(Estimator):
    """Feature standardization stage (the VectorAssembler/scaler role in
    reference pipelines)."""

    class Model(Transformer):
        def __init__(self, mean: np.ndarray, std: np.ndarray):
            self.mean = mean
            self.std = std

        def transform(self, X: np.ndarray) -> np.ndarray:
            return (np.asarray(X, np.float32) - self.mean) / self.std

    def fit(self, X: np.ndarray, y=None) -> "StandardScaler.Model":
        X = np.asarray(X, np.float32)
        return self.Model(X.mean(0), X.std(0) + 1e-8)


class NetworkModel(Transformer):
    """Fitted network stage (reference: SparkDl4jModel.transform adds a
    prediction column; here transform returns class probabilities)."""

    def __init__(self, model):
        self.model = model

    def transform(self, X: np.ndarray) -> np.ndarray:
        return np.asarray(self.model.output(np.asarray(X, np.float32)))

    def predict(self, X: np.ndarray) -> np.ndarray:
        return self.transform(X).argmax(axis=-1)


class NetworkEstimator(Estimator):
    """Trains a network from a configuration inside a pipeline
    (reference: SparkDl4jNetwork(conf, ...).fit(dataset))."""

    def __init__(self, conf, epochs: int = 5, batch_size: int = 32,
                 model_factory: Optional[Callable] = None):
        self.conf = conf
        self.epochs = epochs
        self.batch_size = batch_size
        self._factory = model_factory

    def fit(self, X: np.ndarray, y: Optional[np.ndarray] = None
            ) -> NetworkModel:
        if y is None:
            raise ValueError("NetworkEstimator requires labels")
        if self._factory is not None:
            model = self._factory(self.conf)
        else:
            from deeplearning4j_tpu.models.multi_layer_network import (
                MultiLayerNetwork)
            model = MultiLayerNetwork(self.conf)
        model.init()
        y = np.asarray(y)
        if y.ndim == 1:  # integer labels → one-hot, like the reference's
            n_cls = int(y.max()) + 1
            oh = np.zeros((len(y), n_cls), np.float32)
            oh[np.arange(len(y)), y.astype(int)] = 1.0
            y = oh
        ds = DataSet(np.asarray(X, np.float32), y)
        # clamp so datasets smaller than batch_size still yield a batch
        bs = min(self.batch_size, ds.features.shape[0])
        it = ArrayDataSetIterator(ds, bs, shuffle=True,
                                  seed=0, drop_last=True)
        model.fit(it, epochs=self.epochs)
        return NetworkModel(model)


class PipelineModel(Transformer):
    def __init__(self, stages: List[Transformer]):
        self.stages = stages

    def transform(self, X: np.ndarray) -> np.ndarray:
        for s in self.stages:
            X = s.transform(X)
        return X

    def predict(self, X: np.ndarray) -> np.ndarray:
        out = self.transform(X)
        return np.asarray(out).argmax(axis=-1)


class Pipeline(Estimator):
    """Chains estimators/transformers; fitting threads transformed
    features through (reference: Spark ML Pipeline.fit)."""

    def __init__(self, stages: Sequence[Union[Estimator, Transformer]]):
        self.stages = list(stages)

    def fit(self, X: np.ndarray, y: Optional[np.ndarray] = None
            ) -> PipelineModel:
        fitted: List[Transformer] = []
        cur = np.asarray(X)
        for stage in self.stages:
            if isinstance(stage, Estimator):
                t = stage.fit(cur, y)
            else:
                t = stage
            cur = t.transform(cur)
            fitted.append(t)
        return PipelineModel(fitted)
