"""``python -m deeplearning4j_tpu`` — operational entry points.

``serve`` mirrors the reference's ParallelWrapperMain flag set
(ParallelWrapperMain.java / VERDICT open item 7) for the inference
half: load a saved model, start the ServingEngine and the UI server so
``/metrics`` (Prometheus), ``/healthz`` (degradation verdict),
``POST /api/predict`` and ``GET /api/serving/stats`` are live.

    python -m deeplearning4j_tpu serve --model model.zip \
        --warmup-shape 784 --batch-limit 32 --replicas auto --ui-port 9000
"""

from __future__ import annotations

import argparse
import sys
import time
from typing import List, Optional


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu",
        description="deeplearning4j_tpu operational CLI")
    sub = p.add_subparsers(dest="command", required=True)

    s = sub.add_parser(
        "serve", help="serve a saved model over the batching engine "
        "(ParallelWrapperMain analog for inference)")
    s.add_argument("--model", required=True,
                   help="path to a save_model() zip")
    # the reference's flag names, snake-cased: --workers -> --replicas
    # (model-per-device fan-out), --batchLimit/--queueLimit/--timeout
    # keep their meaning, --inferenceMode keeps its two values
    s.add_argument("--replicas", default="1",
                   help="device replicas to serve on; an int or 'auto' "
                   "for every visible device (reference: --workers)")
    s.add_argument("--batch-limit", type=int, default=32,
                   help="max examples per device batch")
    s.add_argument("--queue-limit", type=int, default=128,
                   help="bound on queued request chunks")
    s.add_argument("--timeout-ms", type=float, default=5.0,
                   help="upper bound on batch aggregation")
    s.add_argument("--inference-mode", default="batched",
                   choices=["batched", "inplace"],
                   help="batched = the serving engine; inplace = direct "
                   "locked calls (reference: --inferenceMode)")
    s.add_argument("--depth", type=int, default=1,
                   help="in-flight batches between dispatcher and "
                   "completion thread (pipeline double-buffer depth)")
    s.add_argument("--no-pipeline", action="store_true",
                   help="blocking dispatcher (the pre-PR5 semantics); "
                   "for A/B comparison only")
    s.add_argument("--bf16", action="store_true",
                   help="serve a bfloat16 copy of the float params")
    s.add_argument("--warmup-shape", type=int, nargs="*", default=None,
                   metavar="DIM",
                   help="per-example feature shape (no batch dim), e.g. "
                   "'--warmup-shape 784' or '--warmup-shape 28 28 1'; "
                   "enables the bucket-ladder warmup sweep so no live "
                   "request pays a compile")
    s.add_argument("--dtype", default="float32",
                   help="request feature dtype")
    s.add_argument("--aot-cache-dir", default=None, metavar="DIR",
                   help="persist the warmed AOT executable table here; "
                   "a fresh process reaches assert_warm() in a "
                   "fraction of the warmup sweep (falls through to "
                   "live compile on any fingerprint mismatch)")
    s.add_argument("--slo-ms", type=float, default=None, metavar="MS",
                   help="serve behind the fleet front door with this "
                   "p99 SLO: admission control + windowed-p99 shedding "
                   "(503 on shed) + hot version swap/rollback routes")
    s.add_argument("--model-version", default="v1",
                   help="version label for the fleet pool / AOT cache "
                   "fingerprint")
    s.add_argument("--ui-port", type=int, default=9000,
                   help="UI/metrics port (0 picks a free one)")
    s.add_argument("--duration", type=float, default=None,
                   help="serve for N seconds then exit (default: until "
                   "interrupted)")
    return p


def cmd_serve(args, block: bool = True):
    """Start engine + UI server. ``block=False`` returns
    ``(front, server)`` for in-process use (tests, notebooks) — front
    is the ParallelInference facade, or the FleetRouter when
    ``--slo-ms`` puts the fleet front door up. Both expose
    ``shutdown()``."""
    import os

    import numpy as np

    from deeplearning4j_tpu.models.serialization import restore_model
    from deeplearning4j_tpu.parallel.inference import (
        InferenceMode, ParallelInference)
    from deeplearning4j_tpu.ui.server import UIServer
    from deeplearning4j_tpu.ui.serving_module import (
        FleetModule, ServingModule)
    from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage

    model = restore_model(args.model)
    replicas = args.replicas if args.replicas == "auto" \
        else int(args.replicas)
    mode = InferenceMode(args.inference_mode)
    kwargs = {}
    if mode == InferenceMode.BATCHED:
        kwargs = dict(
            replicas=replicas, depth=args.depth,
            pipelined=not args.no_pipeline, bf16=args.bf16,
            dtype=np.dtype(args.dtype),
            aot_cache_dir=args.aot_cache_dir,
            feature_shape=(tuple(args.warmup_shape)
                           if args.warmup_shape else None))

    fleet = None
    engine = None
    if args.slo_ms is not None and mode == InferenceMode.BATCHED:
        # fleet front door: admission control + SLO shedding wrap the
        # engine; the pool is named after the model file
        from deeplearning4j_tpu.parallel.fleet import FleetRouter
        name = os.path.splitext(os.path.basename(args.model))[0] \
            or "default"
        fleet = FleetRouter(slo_ms=args.slo_ms)
        fleet.add_pool(
            name, model, version=args.model_version,
            batch_limit=args.batch_limit, queue_limit=args.queue_limit,
            timeout_ms=args.timeout_ms, **kwargs)
        engine = fleet.pool(name).engines[0]
        front = fleet
    else:
        front = ParallelInference(
            model, inference_mode=mode, batch_limit=args.batch_limit,
            queue_limit=args.queue_limit, timeout_ms=args.timeout_ms,
            **kwargs)
        engine = front.engine

    server = UIServer(port=args.ui_port)
    server.attach(InMemoryStatsStorage())
    if fleet is not None:
        # FleetModule first: its admission-controlled /api/predict wins
        # the route merge; ServingModule keeps /api/serving/stats live
        server.register_module(FleetModule(fleet))
    if engine is not None:
        server.register_module(ServingModule(engine))
    server.start()
    print(f"serving {args.model} at {server.url} "
          f"(mode={mode.value}, replicas={replicas}, "
          f"batch_limit={args.batch_limit}"
          + (f", slo={args.slo_ms}ms" if fleet is not None else "")
          + (f", aot_cache={args.aot_cache_dir}"
             if args.aot_cache_dir else "") + ")")
    print(f"  metrics:  {server.url}/metrics")
    print(f"  health:   {server.url}/healthz")
    if engine is not None:
        print(f"  predict:  POST {server.url}/api/predict "
              '{"features": [[...], ...]}')
        print(f"  stats:    GET  {server.url}/api/serving/stats")
    if fleet is not None:
        print(f"  fleet:    GET  {server.url}/api/fleet/stats, "
              f"POST {server.url}/api/fleet/swap|rollback")
    if not block:
        return front, server
    try:
        if args.duration is not None:
            time.sleep(args.duration)
        else:
            while True:
                time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        front.shutdown()
        server.stop()
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    args = _build_parser().parse_args(argv)
    if args.command == "serve":
        rc = cmd_serve(args)
        return rc if isinstance(rc, int) else 0
    return 2


if __name__ == "__main__":
    sys.exit(main())
