"""``python -m deeplearning4j_tpu`` — operational entry points.

``serve`` mirrors the reference's ParallelWrapperMain flag set
(ParallelWrapperMain.java / VERDICT open item 7) for the inference
half: load a saved model, start the ServingEngine and the UI server so
``/metrics`` (Prometheus), ``/healthz`` (degradation verdict),
``POST /api/predict`` and ``GET /api/serving/stats`` are live.

    python -m deeplearning4j_tpu serve --model model.zip \
        --warmup-shape 784 --batch-limit 32 --replicas auto --ui-port 9000
"""

from __future__ import annotations

import argparse
import sys
import time
from typing import List, Optional


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu",
        description="deeplearning4j_tpu operational CLI")
    sub = p.add_subparsers(dest="command", required=True)

    s = sub.add_parser(
        "serve", help="serve a saved model over the batching engine "
        "(ParallelWrapperMain analog for inference)")
    s.add_argument("--model", required=False, default=None,
                   help="path to a save_model() zip (required unless "
                   "--neighbors-index serves a retrieval index "
                   "instead)")
    # the reference's flag names, snake-cased: --workers -> --replicas
    # (model-per-device fan-out), --batchLimit/--queueLimit/--timeout
    # keep their meaning, --inferenceMode keeps its two values
    s.add_argument("--replicas", default="1",
                   help="device replicas to serve on; an int or 'auto' "
                   "for every visible device (reference: --workers)")
    s.add_argument("--batch-limit", type=int, default=None,
                   help="max examples per device batch (default: the "
                   "tuned-config value when one loads, else 32)")
    s.add_argument("--queue-limit", type=int, default=128,
                   help="bound on queued request chunks")
    s.add_argument("--timeout-ms", type=float, default=5.0,
                   help="upper bound on batch aggregation")
    s.add_argument("--inference-mode", default="batched",
                   choices=["batched", "inplace"],
                   help="batched = the serving engine; inplace = direct "
                   "locked calls (reference: --inferenceMode)")
    s.add_argument("--depth", type=int, default=1,
                   help="in-flight batches between dispatcher and "
                   "completion thread (pipeline double-buffer depth)")
    s.add_argument("--no-pipeline", action="store_true",
                   help="blocking dispatcher (the pre-PR5 semantics); "
                   "for A/B comparison only")
    s.add_argument("--bf16", action="store_true",
                   help="serve a bfloat16 copy of the float params")
    s.add_argument("--warmup-shape", type=int, nargs="*", default=None,
                   metavar="DIM",
                   help="per-example feature shape (no batch dim), e.g. "
                   "'--warmup-shape 784' or '--warmup-shape 28 28 1'; "
                   "enables the bucket-ladder warmup sweep so no live "
                   "request pays a compile")
    s.add_argument("--dtype", default="float32",
                   help="request feature dtype")
    s.add_argument("--aot-cache-dir", default=None, metavar="DIR",
                   help="persist the warmed AOT executable table here; "
                   "a fresh process reaches assert_warm() in a "
                   "fraction of the warmup sweep (falls through to "
                   "live compile on any fingerprint mismatch)")
    s.add_argument("--slo-ms", type=float, default=None, metavar="MS",
                   help="serve behind the fleet front door with this "
                   "p99 SLO: admission control + windowed-p99 shedding "
                   "(503 on shed) + hot version swap/rollback routes")
    s.add_argument("--model-version", default="v1",
                   help="version label for the fleet pool / AOT cache "
                   "fingerprint")
    s.add_argument("--ui-port", type=int, default=9000,
                   help="UI/metrics port (0 picks a free one)")
    s.add_argument("--duration", type=float, default=None,
                   help="serve for N seconds then exit (default: until "
                   "interrupted)")
    # ---- multi-node fleet (join a serving cluster) -------------------
    n = s.add_argument_group(
        "multi-node fleet", "join a health-gossiped serving cluster "
        "(parallel/node.py): heartbeat into a shared registry dir, "
        "warm the AOT table from a shared artifact store, and drain "
        "gracefully on SIGTERM (finish in-flight, deregister, exit 0)")
    n.add_argument("--join", default=None, metavar="DIR",
                   help="node registry directory to gossip into "
                   "(a shared filesystem path); enables node mode")
    n.add_argument("--node-id", default=None,
                   help="stable node identity in the registry "
                   "(default: the pid); a rejoining node reuses its id")
    n.add_argument("--artifact-store", default=None, metavar="DIR",
                   help="shared AOT/calibration artifact store root "
                   "(bucket layout); joining nodes warm from one saved "
                   "sweep with zero live compiles")
    n.add_argument("--model-key", default=None,
                   help="artifact-store key for this model (default: "
                   "the model file's basename)")
    n.add_argument("--tuned-config", default=None, metavar="KEY",
                   nargs="?", const="tuned_config",
                   help="load a measured TunedConfig artifact from "
                   "--artifact-store under KEY (bare flag: the default "
                   "key) and let it size every engine this process "
                   "starts; with --artifact-store set, the default key "
                   "is auto-discovered even without this flag. A "
                   "fingerprint mismatch falls through to the "
                   "committed defaults, never a crash")
    n.add_argument("--no-tuned", action="store_true",
                   help="skip TunedConfig auto-discovery from "
                   "--artifact-store; every knob keeps its explicit "
                   "or committed-default value")
    n.add_argument("--drain-timeout", type=float, default=30.0,
                   metavar="S",
                   help="SIGTERM grace: max seconds to finish in-flight "
                   "requests before exiting anyway")
    # ---- retrieval serving (nearest-neighbor index) -----------------
    r = s.add_argument_group(
        "retrieval serving", "serve a nearest-neighbor index "
        "(retrieval/) instead of a model: jitted fused distance+top-k "
        "over the index's shards, POST /api/neighbors. With --join, "
        "the node gossips its shard ownership so NeighborsDispatcher "
        "can scatter-gather across the cluster")
    r.add_argument("--neighbors-index", default=None, metavar="KEY",
                   help="artifact-store key of a saved "
                   "ShardedCorpusIndex (requires --artifact-store); "
                   "enables retrieval mode, --model becomes optional")
    r.add_argument("--neighbors-shards", default=None, metavar="IDS",
                   help="comma-separated shard ids this node loads and "
                   "owns (default: every shard in the manifest)")
    r.add_argument("--neighbors-k-ladder", default=None,
                   metavar="KS", help="warmed k values; a request's k "
                   "is served by the next rung up and sliced "
                   "(default: the tuned-config ladder when one loads, "
                   "else 1,10,100)")
    r.add_argument("--neighbors-batch", type=int, default=64,
                   metavar="N", help="max query batch per dispatch "
                   "(pow2 bucket ladder below it is warmed too)")
    r.add_argument("--nprobe", type=int, default=None, metavar="N",
                   help="IVF clusters probed per query (default: the "
                   "index build's hint; ignored for brute indexes)")
    # ---- online learning (train-and-serve in one process) -----------
    o = s.add_argument_group(
        "online learning", "train-and-serve in one process: consume a "
        "broker sample stream, incrementally fit the restored model, "
        "and hot-promote holdout-gated candidates into the warm "
        "serving engines (zero recompiles); a regression sentinel "
        "auto-rolls-back on live p99/score regressions")
    o.add_argument("--online", action="store_true",
                   help="enable the online-learning loop (needs "
                   "--stream-endpoint and batched inference mode)")
    o.add_argument("--stream-endpoint", default=None, metavar="HOST:PORT",
                   help="TCP broker to consume training samples from "
                   "(streaming/broker.py TcpTransport)")
    o.add_argument("--stream-topic", default="train",
                   help="broker topic carrying packed sample frames")
    o.add_argument("--promote-interval-s", type=float, default=5.0,
                   help="seconds between promotion-gate cycles")
    o.add_argument("--min-delta", type=float, default=0.0,
                   help="required holdout-score improvement margin; "
                   "candidates within it are rejected as 'equal'")
    o.add_argument("--score-budget-s", type=float, default=None,
                   help="advisory wall-clock budget for one holdout "
                   "scoring pass (over-budget is flagged, not fatal)")
    o.add_argument("--rollback-p99-factor", type=float, default=3.0,
                   help="sentinel: live p99 over baseline*factor (and "
                   "over the floor) rolls the promotion back")
    o.add_argument("--rollback-p99-floor-ms", type=float, default=50.0,
                   help="sentinel: absolute p99 floor (ms) the live "
                   "value must also exceed before a p99 rollback")
    o.add_argument("--rollback-score-delta", type=float, default=0.0,
                   help="sentinel: tolerated live holdout-score slack "
                   "vs the pre-swap baseline before a score rollback")
    o.add_argument("--sentinel-window-s", type=float, default=30.0,
                   help="how long the sentinel watches after each "
                   "promotion")
    o.add_argument("--holdout-every", type=int, default=8,
                   help="divert every Nth stream micro-batch to the "
                   "holdout reservoir (never trained on)")
    o.add_argument("--holdout-max", type=int, default=512,
                   help="holdout reservoir bound, in examples")
    # ---- generative serving (autoregressive decode) ------------------
    g = s.add_argument_group(
        "generative serving", "serve autoregressive decode next to "
        "predict: a continuous-batching GenerationEngine "
        "(generation/engine.py) over the restored recurrent model "
        "streams tokens at POST /api/generate (SSE); "
        "--gen-slo-token-ms puts it behind the fleet front door's "
        "admission control with per-token-p99 shedding")
    g.add_argument("--generate", action="store_true",
                   help="enable decode serving (the model must be a "
                   "stacked-LSTM + dense-head network, e.g. the "
                   "committed TextGenerationLSTM artifact)")
    g.add_argument("--gen-slots", type=int, default=None, metavar="N",
                   help="continuous-batching slot count: concurrent "
                   "sequences decoding in one device batch; the AOT "
                   "warmup sweeps the pow2 bucket ladder up to this "
                   "(default: the tuned-config value when one loads, "
                   "else 8)")
    g.add_argument("--gen-max-new", type=int, default=256, metavar="N",
                   help="default per-request max generated tokens")
    g.add_argument("--gen-precision", default="f32",
                   choices=["f32", "bf16", "int8"],
                   help="dense-head precision arm; int8 rides "
                   "ops/quantize.py and must pass the decode-level "
                   "next-token-agreement gate at startup")
    g.add_argument("--gen-slo-token-ms", type=float, default=None,
                   metavar="MS",
                   help="per-token p99 SLO: routes /api/generate "
                   "through fleet admission control (503 + Retry-After "
                   "on shed)")
    g.add_argument("--gen-queue-limit", type=int, default=128,
                   help="bound on sequences waiting for a slot")
    g.add_argument("--gen-prefill-chunk", type=int, default=None,
                   metavar="C",
                   help="chunked prefill: consume prompts in jitted "
                   "scans of up to C tokens (pow2 ladder, AOT-warmed) "
                   "instead of one tick per char; 0 disables (default: "
                   "the tuned-config value when one loads, else 0)")
    g.add_argument("--gen-speculative", type=int, default=0,
                   metavar="K",
                   help="speculative decode: n-gram draft proposes up "
                   "to K tokens per slot, verified in one batched "
                   "dispatch; accepted output stays bitwise-equal to "
                   "plain decode. 0 disables")
    g.add_argument("--gen-sampling", default=None,
                   choices=["chain", "counter"],
                   help="seeded-sampling key derivation: chain (legacy "
                   "carried split chain) or counter (splitmix64 of "
                   "(seed, position) — replayable anywhere; the "
                   "default when --gen-speculative is on)")
    g.add_argument("--gen-session-dir", default=None, metavar="DIR",
                   help="enable resumable sessions, checkpointing "
                   "carries into this shared ArtifactStore root so a "
                   "session resumes on another node after a drain")
    g.add_argument("--gen-session-cap", type=int, default=0,
                   metavar="N",
                   help="enable resumable sessions with N carries "
                   "pinned device-side (LRU to host beyond that); "
                   "local-only unless --gen-session-dir adds the "
                   "cross-node checkpoint tier")
    g.add_argument("--gen-carry-int8", action="store_true",
                   help="store session carries int8-quantized "
                   "(ops/quantize.py rows) — ~4x more resumable "
                   "sessions per chip, trades away bitwise resume")
    return p


def _load_tuned_for_serve(args):
    """Resolve the machine-measured TunedConfig for this serve process.

    With ``--artifact-store`` the tuned artifact is auto-discovered
    under the default key; ``--tuned-config [KEY]`` names another key.
    The loaded (or fallen-through) config installs process-wide, so
    every engine built below — serving pools, generation, retrieval,
    the device feeder — resolves its un-flagged knobs from it. The
    expectation is machine-level (no weights binding): whatever model
    this node serves, a config measured on this backend + jax pair
    applies; any fingerprint-field mismatch means committed defaults.
    """
    key = getattr(args, "tuned_config", None)
    store_dir = getattr(args, "artifact_store", None)
    if store_dir is None or getattr(args, "no_tuned", False):
        return None
    from deeplearning4j_tpu.observe.flight_recorder import (
        default_flight_recorder)
    from deeplearning4j_tpu.optimize import autotune
    from deeplearning4j_tpu.parallel.aot_cache import ArtifactStore
    cfg = autotune.load_tuned(
        ArtifactStore(store_dir), expect=autotune.fingerprint(),
        key=key or autotune.TUNED_KEY,
        recorder=default_flight_recorder())
    autotune.set_process_tuned(cfg)
    if cfg.load_outcome == "loaded":
        print(f"tuned config: loaded {sorted(cfg.values)} from "
              f"{store_dir}")
    else:
        print(f"tuned config: {cfg.load_outcome} "
              f"({cfg.load_reason}) — committed defaults in effect")
    return cfg


def _cmd_serve_neighbors(args, block: bool):
    """Retrieval mode of ``serve``: load a saved ShardedCorpusIndex
    from the artifact store and serve POST /api/neighbors through a
    FleetRouter retrieval pool. ``--join`` runs it as a gossiping
    RetrievalNode instead (shard ownership in the heartbeat, SIGTERM
    drain). ``block=False`` returns ``(front, server)``."""
    import os

    from deeplearning4j_tpu.parallel.aot_cache import ArtifactStore
    from deeplearning4j_tpu.retrieval.engine import RetrievalEngine
    from deeplearning4j_tpu.retrieval.index import ShardedCorpusIndex

    if not args.artifact_store:
        raise SystemExit("--neighbors-index requires --artifact-store")
    store = ArtifactStore(args.artifact_store)
    _load_tuned_for_serve(args)
    shard_ids = None
    if args.neighbors_shards:
        shard_ids = [int(s) for s in
                     args.neighbors_shards.split(",") if s != ""]
    # an explicit --neighbors-k-ladder wins; None lets the engine pick
    # the tuned ladder (process config installed above), else (1,10,100)
    ladder = None if args.neighbors_k_ladder is None else tuple(
        int(k) for k in args.neighbors_k_ladder.split(",") if k != "")
    index = ShardedCorpusIndex.load(store, args.neighbors_index,
                                    shard_ids=shard_ids)
    engine = RetrievalEngine(index, k_ladder=ladder,
                             max_batch=args.neighbors_batch,
                             nprobe=args.nprobe,
                             session_id=f"nn-{args.neighbors_index}")

    if getattr(args, "join", None):
        from deeplearning4j_tpu.parallel.node import (
            NodeRegistry, install_sigterm_drain)
        from deeplearning4j_tpu.retrieval.cluster import RetrievalNode
        node = RetrievalNode(
            engine, node_id=args.node_id or str(os.getpid()),
            registry=NodeRegistry(args.join), slo_ms=args.slo_ms,
            ui_port=args.ui_port, store=store,
            index_key=args.neighbors_index)
        install_sigterm_drain(node, timeout_s=args.drain_timeout)
        print(f"node {node.node_id} serving index "
              f"{args.neighbors_index} (shards "
              f"{list(engine.shard_ids)}) at {node.url} "
              f"(registry={args.join})")
        if not block:
            return node, node.server
        try:
            if args.duration is not None:
                time.sleep(args.duration)
            else:
                while True:
                    time.sleep(3600)
        except KeyboardInterrupt:
            pass
        finally:
            node.shutdown()
        return 0

    from deeplearning4j_tpu.parallel.fleet import FleetRouter
    from deeplearning4j_tpu.ui.neighbors_module import NeighborsModule
    from deeplearning4j_tpu.ui.server import UIServer
    from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage
    engine.warmup()
    router = FleetRouter(slo_ms=args.slo_ms,
                         session_id=f"nn-{args.neighbors_index}")
    router.add_retrieval_pool("neighbors", engine, slo_ms=args.slo_ms)
    server = UIServer(port=args.ui_port)
    server.attach(InMemoryStatsStorage())
    server.register_module(NeighborsModule(
        router=router, model="neighbors", store=store,
        index_key=args.neighbors_index))
    server.start()
    print(f"serving index {args.neighbors_index} "
          f"({engine.index.n_total} vectors, "
          f"{len(engine.shard_ids)} shards) at {server.url}")
    print(f"  neighbors: POST {server.url}/api/neighbors "
          '{"vector": [...], "k": 10}')
    print(f"  stats:     GET  {server.url}/api/neighbors/stats")
    print(f"  metrics:   {server.url}/metrics")
    if not block:
        return router, server
    try:
        if args.duration is not None:
            time.sleep(args.duration)
        else:
            while True:
                time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
        router.shutdown()
    return 0


def cmd_serve(args, block: bool = True):
    """Start engine + UI server. ``block=False`` returns
    ``(front, server)`` for in-process use (tests, notebooks) — front
    is the ParallelInference facade, or the FleetRouter when
    ``--slo-ms`` puts the fleet front door up. Both expose
    ``shutdown()``."""
    import os

    import numpy as np

    from deeplearning4j_tpu.models.serialization import restore_model
    from deeplearning4j_tpu.parallel.inference import (
        InferenceMode, ParallelInference)
    from deeplearning4j_tpu.ui.server import UIServer
    from deeplearning4j_tpu.ui.serving_module import (
        FleetModule, ServingModule)
    from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage

    if getattr(args, "neighbors_index", None):
        return _cmd_serve_neighbors(args, block)
    if not args.model:
        raise SystemExit("--model is required (or --neighbors-index "
                         "to serve a retrieval index)")
    # measured tuned config (auto-discovered from --artifact-store)
    # installs process-wide, then the un-flagged knobs resolve through
    # it HERE so every construction and banner below sees real values
    from deeplearning4j_tpu.optimize.autotune import resolve_tuned
    tuned = _load_tuned_for_serve(args)
    args.batch_limit = int(resolve_tuned(
        args.batch_limit, tuned, "serving.batch_limit"))
    args.gen_slots = int(resolve_tuned(
        args.gen_slots, tuned, "generation.max_slots"))
    args.gen_prefill_chunk = int(resolve_tuned(
        args.gen_prefill_chunk, tuned, "generation.prefill_chunk"))
    model = restore_model(args.model)
    replicas = args.replicas if args.replicas == "auto" \
        else int(args.replicas)
    mode = InferenceMode(args.inference_mode)
    kwargs = {}
    if mode == InferenceMode.BATCHED:
        kwargs = dict(
            replicas=replicas, depth=args.depth,
            pipelined=not args.no_pipeline, bf16=args.bf16,
            dtype=np.dtype(args.dtype),
            aot_cache_dir=args.aot_cache_dir,
            feature_shape=(tuple(args.warmup_shape)
                           if args.warmup_shape else None))

    if getattr(args, "join", None):
        # cluster node mode: FleetRouter + engine behind the HTTP
        # surface, heartbeating into the shared registry; SIGTERM
        # drains gracefully (finish in-flight, deregister, exit 0)
        if mode != InferenceMode.BATCHED:
            raise SystemExit("--join requires --inference-mode batched")
        if getattr(args, "generate", False):
            raise SystemExit(
                "--generate is not supported in --join node mode; run "
                "it as a standalone serve process")
        from deeplearning4j_tpu.parallel.aot_cache import ArtifactStore
        from deeplearning4j_tpu.parallel.node import (
            NodeRegistry, ServingNode, install_sigterm_drain)
        name = os.path.splitext(os.path.basename(args.model))[0] \
            or "default"
        store = ArtifactStore(args.artifact_store) \
            if args.artifact_store else None
        node_kwargs = dict(kwargs)
        node_kwargs.pop("replicas", None)   # pool_size is the spelling
        node = ServingNode(
            model, node_id=args.node_id or str(os.getpid()),
            registry=NodeRegistry(args.join),
            model_name=name, version=args.model_version,
            slo_ms=args.slo_ms, artifact_store=store,
            model_key=args.model_key,
            pool_size=(1 if replicas == "auto" else int(replicas)),
            ui_port=args.ui_port, batch_limit=args.batch_limit,
            queue_limit=args.queue_limit, timeout_ms=args.timeout_ms,
            **{k: v for k, v in node_kwargs.items()
               if k in ("aot_cache_dir", "feature_shape", "dtype",
                        "bf16", "depth", "pipelined")})
        install_sigterm_drain(node, timeout_s=args.drain_timeout)
        print(f"node {node.node_id} serving {args.model} at {node.url} "
              f"(registry={args.join}"
              + (f", artifact_store={args.artifact_store}"
                 if args.artifact_store else "") + ")")
        print(f"  predict:  POST {node.url}/api/predict "
              '{"features": [[...], ...]}')
        print(f"  metrics:  {node.url}/metrics")
        if not block:
            return node, node.server
        try:
            if args.duration is not None:
                time.sleep(args.duration)
            else:
                while True:
                    time.sleep(3600)
        except KeyboardInterrupt:
            pass
        finally:
            node.shutdown()
        return 0

    fleet = None
    engine = None
    online = None
    if args.online:
        if mode != InferenceMode.BATCHED:
            raise SystemExit(
                "--online requires --inference-mode batched")
        if args.stream_endpoint is None:
            raise SystemExit(
                "--online requires --stream-endpoint HOST:PORT")
        from deeplearning4j_tpu.online import OnlineServing
        from deeplearning4j_tpu.streaming.broker import TcpTransport
        host, _, port = args.stream_endpoint.rpartition(":")
        transport = TcpTransport(host or "127.0.0.1", int(port))
        name = os.path.splitext(os.path.basename(args.model))[0] \
            or "default"
        online = OnlineServing(
            model, transport, topic=args.stream_topic,
            model_name=name,
            feature_shape=kwargs.pop("feature_shape", None),
            batch_limit=args.batch_limit,
            queue_limit=args.queue_limit, timeout_ms=args.timeout_ms,
            slo_ms=args.slo_ms,
            promote_interval_s=args.promote_interval_s,
            min_delta=args.min_delta,
            score_budget_s=args.score_budget_s,
            rollback_p99_factor=args.rollback_p99_factor,
            rollback_p99_floor_s=args.rollback_p99_floor_ms / 1000.0,
            rollback_score_delta=args.rollback_score_delta,
            sentinel_window_s=args.sentinel_window_s,
            holdout_every=args.holdout_every,
            holdout_max=args.holdout_max, **kwargs)
        online.start()
        fleet = online.router
        engine = online.pool.engines[0]
        front = online
    elif args.slo_ms is not None and mode == InferenceMode.BATCHED:
        # fleet front door: admission control + SLO shedding wrap the
        # engine; the pool is named after the model file
        from deeplearning4j_tpu.parallel.fleet import FleetRouter
        name = os.path.splitext(os.path.basename(args.model))[0] \
            or "default"
        fleet = FleetRouter(slo_ms=args.slo_ms)
        fleet.add_pool(
            name, model, version=args.model_version,
            batch_limit=args.batch_limit, queue_limit=args.queue_limit,
            timeout_ms=args.timeout_ms, **kwargs)
        engine = fleet.pool(name).engines[0]
        front = fleet
    else:
        front = ParallelInference(
            model, inference_mode=mode, batch_limit=args.batch_limit,
            queue_limit=args.queue_limit, timeout_ms=args.timeout_ms,
            **kwargs)
        engine = front.engine

    # generative serving rides the same process: a GenerationEngine
    # over the same restored model, exposed at /api/generate — behind
    # fleet admission when an SLO (request- or token-level) is armed
    gen_engine = None
    gen_router = None
    if getattr(args, "generate", False):
        from deeplearning4j_tpu.generation import (
            GenerationEngine, SessionStore, extract_decode_spec)
        gen_store = None
        if args.gen_session_dir or args.gen_session_cap:
            art_store = None
            if args.gen_session_dir:
                from deeplearning4j_tpu.parallel.aot_cache import (
                    ArtifactStore)
                art_store = ArtifactStore(args.gen_session_dir)
            gen_store = SessionStore(
                extract_decode_spec(model),
                device_capacity=args.gen_session_cap or 32,
                store=art_store,
                carry_dtype="int8" if args.gen_carry_int8 else "f32")
        gen_engine = GenerationEngine(
            model, max_slots=args.gen_slots,
            precision=args.gen_precision,
            max_new_tokens=args.gen_max_new,
            queue_limit=args.gen_queue_limit,
            prefill_chunk=args.gen_prefill_chunk,
            speculative=args.gen_speculative,
            sampling=args.gen_sampling,
            session_store=gen_store)
        if fleet is not None or args.gen_slo_token_ms is not None:
            gen_router = fleet
            if gen_router is None:
                from deeplearning4j_tpu.parallel.fleet import FleetRouter
                gen_router = FleetRouter(session_id="generate")
            gen_name = (os.path.splitext(
                os.path.basename(args.model))[0] or "default") + "-gen"
            gen_router.add_generation_pool(
                gen_name, gen_engine,
                slo_token_ms=args.gen_slo_token_ms)

    server = UIServer(port=args.ui_port)
    server.attach(InMemoryStatsStorage())
    if fleet is not None:
        # FleetModule first: its admission-controlled /api/predict wins
        # the route merge; ServingModule keeps /api/serving/stats live
        server.register_module(FleetModule(fleet))
    if engine is not None:
        server.register_module(ServingModule(engine))
    if online is not None:
        from deeplearning4j_tpu.ui.online_module import OnlineModule
        server.register_module(OnlineModule(online))
    if gen_engine is not None:
        from deeplearning4j_tpu.ui.generation_module import (
            GenerationModule)
        server.register_module(
            GenerationModule(router=gen_router, model=gen_name)
            if gen_router is not None
            else GenerationModule(engine=gen_engine))
    server.start()
    print(f"serving {args.model} at {server.url} "
          f"(mode={mode.value}, replicas={replicas}, "
          f"batch_limit={args.batch_limit}"
          + (f", slo={args.slo_ms}ms" if fleet is not None else "")
          + (f", aot_cache={args.aot_cache_dir}"
             if args.aot_cache_dir else "") + ")")
    print(f"  metrics:  {server.url}/metrics")
    print(f"  health:   {server.url}/healthz")
    if engine is not None:
        print(f"  predict:  POST {server.url}/api/predict "
              '{"features": [[...], ...]}')
        print(f"  stats:    GET  {server.url}/api/serving/stats")
    if fleet is not None:
        print(f"  fleet:    GET  {server.url}/api/fleet/stats, "
              f"POST {server.url}/api/fleet/swap|rollback")
    if online is not None:
        print(f"  online:   GET  {server.url}/api/online/stats, "
              f"POST {server.url}/api/online/promote|rollback")
    if gen_engine is not None:
        print(f"  generate: POST {server.url}/api/generate "
              '{"prompt": "...", "stream": true}  (SSE token stream, '
              f"slots={args.gen_slots}, "
              f"precision={args.gen_precision}"
              + (f", prefill_chunk={args.gen_prefill_chunk}"
                 if args.gen_prefill_chunk else "")
              + (f", speculative={args.gen_speculative}"
                 if args.gen_speculative else "")
              + (", sessions=on" if gen_engine.session_store
                 is not None else "") + ")")
        print(f"  genstats: GET  {server.url}/api/generation/stats")
    if not block:
        return front, server
    try:
        if args.duration is not None:
            time.sleep(args.duration)
        else:
            while True:
                time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        front.shutdown()
        # front.shutdown() covers the generation engine only when its
        # pool rides the same fleet router; the standalone cases are
        # shut down here
        if gen_router is not None and gen_router is not fleet:
            gen_router.shutdown()
        elif gen_engine is not None and gen_router is None:
            gen_engine.shutdown()
        server.stop()
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    args = _build_parser().parse_args(argv)
    if args.command == "serve":
        rc = cmd_serve(args)
        return rc if isinstance(rc, int) else 0
    return 2


if __name__ == "__main__":
    sys.exit(main())
