"""MultiLayerNetwork — sequential-stack model.

Analog of the reference's ``MultiLayerNetwork``
(deeplearning4j-nn/.../nn/multilayer/MultiLayerNetwork.java:94 — init():549,
fit(DataSetIterator):1268, backprop():1363, output:2031,
computeGradientAndScore:2360), redesigned around a functional core:

- parameters/state are pytrees keyed by layer name,
- the full forward+loss is one pure function; ``jax.grad`` replaces
  ``calcBackpropGradients``, and the whole train step compiles to a single
  XLA executable with donated buffers (no workspaces needed),
- stochastic layers get per-layer fold_in keys from one step key,
- feature/label masks thread through like the reference's
  ``setLayerMaskArrays`` path (SURVEY §5.7).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.models.base import BaseModel, cast_params, compute_cast
from deeplearning4j_tpu.nn.config import MultiLayerConfiguration
from deeplearning4j_tpu.nn.inputs import RecurrentType
from deeplearning4j_tpu.nn.layers.base import LayerContext
from deeplearning4j_tpu.optimize.solver import (
    TrainState,
    make_constrain_fn,
    build_optimizer,
    make_scan_train_step,
    make_train_step,
)




def _pad_time(a, pad):
    """Zero-pad ``pad`` steps onto the time axis (shared by the MLN and
    ComputationGraph TBPTT ragged-tail paths)."""
    return np.concatenate(
        [a, np.zeros((a.shape[0], pad) + a.shape[2:], a.dtype)], axis=1)


def _pad_tbptt_tail(f, l, fm, lm, k, seq_labels):
    """Pad a ragged final TBPTT chunk to length k along time, masking the
    padded steps out of both the recurrent math and the loss."""
    n, t = f.shape[0], f.shape[1]
    pad = k - t
    f = _pad_time(f, pad)
    base_fm = fm if fm is not None else np.ones((n, t), np.float32)
    fm = _pad_time(base_fm, pad)
    if seq_labels:
        l = _pad_time(l, pad)
        if lm is not None:
            lm = _pad_time(lm, pad)
        else:
            # _loss falls back to fmask when lmask is None; the padded fm
            # already carries per-example valid steps + zeroed padding, so
            # synthesizing an all-ones lmask here would UNmask steps the
            # features mask excludes
            lm = fm
    return f, l, fm, lm


class MultiLayerNetwork(BaseModel):
    def __init__(self, conf: MultiLayerConfiguration):
        super().__init__()
        self.conf = conf
        conf.resolve_shapes()
        self.layers = conf.layers
        self.layer_names = tuple(l.name for l in self.layers)
        self._preprocessors = conf.preprocessors()
        self._input_types = conf.layer_input_types()
        self._output_fn = None
        self._loss_eval_fn = None
        self._tbptt_step = None
        # tensor-parallel activation specs (parallel/tensor_parallel.py);
        # set by ParallelWrapper when TP is enabled
        self._tp_plan = None

    @property
    def conf_global(self):
        return self.conf.global_config

    # ---- init -----------------------------------------------------------
    def init(self, seed: Optional[int] = None):
        """Build params/state pytrees (reference: init():549 — flattened
        buffer + per-layer views; here: named pytree, flattening only needed
        for checkpoint/averaging utilities)."""
        g = self.conf.global_config
        root = jax.random.PRNGKey(g.seed if seed is None else seed)
        self._rng = jax.random.fold_in(root, 0x5eed)
        params: Dict[str, Any] = {}
        state: Dict[str, Any] = {}
        for i, layer in enumerate(self.layers):
            it = self._input_types[i]
            k = jax.random.fold_in(root, i)
            params[layer.name] = layer.initialize(k, it) if layer.has_params else {}
            state[layer.name] = layer.init_state(it)
        tx = self._make_tx()
        opt_state = tx.init(params)
        self.train_state = TrainState(params, state, opt_state,
                                      jnp.zeros((), jnp.int32))
        self._tx = tx
        return self

    def _make_tx(self):
        g = self.conf.global_config
        return build_optimizer(
            self.layer_names,
            {l.name: l.updater for l in self.layers},
            {l.name: l.frozen for l in self.layers},
            g.updater,
            g.gradient_normalization,
        )

    # ---- functional forward --------------------------------------------
    def _forward(self, params, model_state, x, fmask, train: bool, rng,
                 upto: Optional[int] = None, collect: bool = False,
                 carries: Optional[dict] = None):
        """Pure forward through layers [0, upto). Returns (activation,
        new_state) or (list_of_activations, new_state) when collect
        (reference: feedForwardToLayer:955). ``carries`` maps recurrent
        layer name → initial hidden state (TBPTT chunk chaining,
        reference: rnnActivateUsingStoredState:2881)."""
        g = self.conf.global_config
        x = compute_cast(jnp.asarray(x), g.compute_dtype)
        n = len(self.layers) if upto is None else upto
        new_state = dict(model_state)
        acts = []
        for i in range(n):
            layer = self.layers[i]
            pp = self._preprocessors.get(i)
            if pp is not None:
                x = pp.apply(x)
            key = None if rng is None else jax.random.fold_in(rng, i)
            mask = fmask if isinstance(self._input_types[i], RecurrentType) else None
            ctx = LayerContext(train=train, rng=key, mask=mask)
            lp = cast_params(params.get(layer.name, {}), g.compute_dtype)
            lp = layer.apply_weight_noise(lp, ctx, key)
            if carries is not None and layer.name in carries:
                x, s = layer.apply(lp, model_state.get(layer.name, {}), x,
                                   ctx, initial_state=carries[layer.name])
            else:
                x, s = layer.apply(lp, model_state.get(layer.name, {}), x, ctx)
            new_state[layer.name] = s
            if self._tp_plan is not None:
                # pin the boundary activation layout (Megatron pairing) so
                # GSPMD places exactly one psum per row/column pair
                x = self._tp_plan.constrain(layer.name, x)
            if collect:
                acts.append(x)
        return (acts if collect else x), new_state

    def _loss(self, params, model_state, features, labels, fmask, lmask, rng,
              iteration, carries: Optional[dict] = None):
        """Full training loss: forward to the last hidden layer, output
        layer loss, plus L1/L2 (reference: computeGradientAndScore:2360 +
        outputLayer.computeScore)."""
        n = len(self.layers)
        x, new_state = self._forward(params, model_state, features, fmask,
                                     True, rng, upto=n - 1, carries=carries)
        out_layer = self.layers[-1]
        pp = self._preprocessors.get(n - 1)
        if pp is not None:
            x = pp.apply(x)
        key = None if rng is None else jax.random.fold_in(rng, n - 1)
        mask = lmask if lmask is not None else (
            fmask if isinstance(self._input_types[n - 1], RecurrentType) else None)
        ctx = LayerContext(train=True, rng=key, mask=mask)
        if not hasattr(out_layer, "compute_loss"):
            raise TypeError(f"last layer {type(out_layer).__name__} is not an"
                            " output/loss layer")
        # keep the loss matmul in the compute dtype; a mixed-dtype einsum
        # here leaks f32 cotangents into the bf16 backward pass
        out_lp = cast_params(params.get(out_layer.name, {}),
                             self.conf.global_config.compute_dtype)
        out_lp = out_layer.apply_weight_noise(out_lp, ctx, key)
        loss = out_layer.compute_loss(out_lp,
                                      model_state.get(out_layer.name, {}),
                                      x, labels, ctx)
        reg = sum((l.regularization_loss(params.get(l.name, {}))
                   for l in self.layers), jnp.zeros((), jnp.float32))
        # auxiliary losses surfaced via layer state (MoE load balancing)
        aux = sum((s["moe_aux_loss"] for s in new_state.values()
                   if isinstance(s, dict) and "moe_aux_loss" in s),
                  jnp.zeros((), jnp.float32))
        # promote (not truncate): float64 under gradient checks, else float32
        acc = jnp.promote_types(jnp.float32, loss.dtype)
        return loss.astype(acc) + reg.astype(acc) + aux.astype(acc), new_state

    def _constraint_layers(self):
        return self.layers

    def _build_train_step(self):
        def loss_fn(params, model_state, features, labels, fmask, lmask, rng,
                    iteration):
            return self._loss(params, model_state, features, labels, fmask,
                              lmask, rng, iteration)
        return make_train_step(
            loss_fn, self._tx,
            constrain_fn=make_constrain_fn(
                [l for l in self._constraint_layers()]),
            telemetry=self._telemetry_spec())

    def _build_scan_train_step(self):
        """K fused optimizer steps per dispatch (fit(k_steps=K)); same
        loss/constraint/telemetry spec as the per-batch step, scanned
        over a leading K dim. No bf16 shadow here: the regularization
        term reads master params, and the fed path promises a bitwise
        match with the per-batch trajectory."""
        def loss_fn(params, model_state, features, labels, fmask, lmask,
                    rng, iteration):
            return self._loss(params, model_state, features, labels, fmask,
                              lmask, rng, iteration)
        return make_scan_train_step(
            loss_fn, self._tx,
            constrain_fn=make_constrain_fn(
                [l for l in self._constraint_layers()]),
            telemetry=self._telemetry_spec())

    # ---- truncated BPTT (reference: doTruncatedBPTT:1521, SURVEY §5.7) --
    def _recurrent_carry_layers(self):
        """(layer, is_lstm) for every layer whose hidden state crosses
        TBPTT chunks — including cores wrapped in LastTimeStep /
        MaskZeroLayer (the wrappers delegate state + initial_state)."""
        from deeplearning4j_tpu.nn.layers.recurrent import (
            LSTM, SimpleRnn, unwrap_recurrent)
        out = []
        for l in self.layers:
            core = unwrap_recurrent(l)
            if isinstance(core, (LSTM, SimpleRnn)):
                out.append((l, core, isinstance(core, LSTM)))
        return out

    def _zero_carries(self, batch_size: int):
        dt = (jnp.bfloat16 if self.conf.global_config.compute_dtype ==
              "bfloat16" else jnp.float32)
        out = {}
        for layer, core, is_lstm in self._recurrent_carry_layers():
            h = jnp.zeros((batch_size, core.n_out), dt)
            out[layer.name] = (h, h) if is_lstm else h
        return out

    def _build_tbptt_step(self):
        import optax
        from deeplearning4j_tpu.optimize.solver import TrainState
        constrain_fn = make_constrain_fn(list(self._constraint_layers()))
        carry_layers = self._recurrent_carry_layers()
        telemetry = self._telemetry_spec()

        def step(ts, features, labels, fmask, lmask, rng, carries):
            def lf(params):
                return self._loss(params, ts.model_state, features, labels,
                                  fmask, lmask, rng, ts.iteration,
                                  carries=carries)
            (loss, new_ms), grads = jax.value_and_grad(
                lf, has_aux=True)(ts.params)
            updates, new_opt = self._tx.update(grads, ts.opt_state, ts.params)
            new_params = optax.apply_updates(ts.params, updates)
            if constrain_fn is not None:
                new_params = constrain_fn(new_params)
            buf = ts.telemetry
            if telemetry is not None:
                buf = telemetry.record(buf, loss=loss, grads=grads,
                                       params=new_params,
                                       prev_params=ts.params,
                                       iteration=ts.iteration)
            # carries cross the chunk boundary with gradients cut — this IS
            # the truncation (reference: tbpttBackLength; here back==fwd)
            new_carries = {}
            for layer, _core, is_lstm in carry_layers:
                s = new_ms[layer.name]
                c = ((s["last_h"], s["last_c"]) if is_lstm else s["last_h"])
                new_carries[layer.name] = jax.lax.stop_gradient(c)
            return (TrainState(new_params, new_ms, new_opt,
                               ts.iteration + 1, buf), loss, new_carries)

        return jax.jit(step, donate_argnums=(0,))

    def _fit_batch(self, batch, etl_ms: float = 0.0):
        conf = self.conf
        feats = np.asarray(batch.features)  # host-sync-ok: eval host staging
        if (conf.backprop_type != "tbptt" or feats.ndim != 3
                or not self._recurrent_carry_layers()):
            return super()._fit_batch(batch, etl_ms=etl_ms)
        from deeplearning4j_tpu.nn.layers.recurrent import (
            first_bidirectional_name, warn_tbptt_bidirectional)
        bidi = first_bidirectional_name(
            (l.name, l) for l in self.layers)
        if bidi is not None:
            warn_tbptt_bidirectional(bidi)
        if self._tbptt_step is None:
            self._tbptt_step = self._build_tbptt_step()
        k = conf.tbptt_fwd_length
        T = feats.shape[1]
        labels = np.asarray(batch.labels)  # host-sync-ok: eval host staging
        seq_labels = labels.ndim == 3
        fmask = (None if batch.features_mask is None
                 else np.asarray(batch.features_mask))  # host-sync-ok: eval host staging
        lmask = (None if batch.labels_mask is None
                 else np.asarray(batch.labels_mask))  # host-sync-ok: eval host staging
        from deeplearning4j_tpu.observe.tracer import get_tracer
        tracer = get_tracer(self)
        if self._telemetry is not None:
            self.train_state = self._telemetry.ensure_buffer(
                self.train_state)
        carries = self._zero_carries(feats.shape[0])
        loss = None
        n_chunks = 0
        for lo in range(0, T, k):
            hi = min(lo + k, T)
            f = feats[:, lo:hi]
            l = labels[:, lo:hi] if seq_labels else labels
            fm = None if fmask is None else fmask[:, lo:hi]
            # a labels mask is per-timestep only for sequence labels; for
            # 2-D labels it is per-output and must not be time-sliced
            lm = (lmask if not seq_labels
                  else None if lmask is None else lmask[:, lo:hi])
            if hi - lo < k:
                # Ragged tail: pad to length k with a zeroed feature mask so
                # the final partial chunk still trains (reference:
                # doTruncatedBPTT processes it; costs one extra compiled
                # shape because fm/lm go from None to arrays).
                f, l, fm, lm = _pad_tbptt_tail(f, l, fm, lm, k, seq_labels)
            self._rng, step_key = jax.random.split(self._rng)
            fm = None if fm is None else jnp.asarray(fm)
            lm = None if lm is None else jnp.asarray(lm)
            f, l = jnp.asarray(f), jnp.asarray(l)
            if self.recompile_watchdog is not None:
                self.recompile_watchdog.observe("tbptt_step", f, l, fm, lm)
            with tracer.span("dispatch", cat="step"):
                self.train_state, loss, carries = self._tbptt_step(
                    self.train_state, f, l, fm, lm, step_key, carries)
            n_chunks += 1
        it = self._post_step(n_chunks)
        for lst in self.listeners:
            lst.iteration_done(self, it, self.epoch_count, loss, etl_ms,
                               batch.num_examples())
        self._last_loss = loss

    # ---- inference ------------------------------------------------------
    def build_inference_fn(self):
        """The pure inference forward ``(params, model_state, x, fmask)
        -> y`` behind ``output()``. The serving engine
        (parallel/serving.py) compiles this against its OWN committed
        (optionally bf16) parameter copies — one executable per batch
        bucket — instead of going through ``output()``'s trace cache
        keyed on ``self.train_state``."""
        if self.train_state is None:
            self.init()

        def fwd(params, model_state, x, fmask):
            n = len(self.layers)
            h, _ = self._forward(params, model_state, x, fmask, False,
                                 None, upto=n - 1)
            out = self.layers[-1]
            pp = self._preprocessors.get(n - 1)
            if pp is not None:
                h = pp.apply(h)
            ctx = LayerContext(train=False, rng=None, mask=fmask)
            y, _ = out.apply(params.get(out.name, {}),
                             model_state.get(out.name, {}), h, ctx)
            if hasattr(out, "pre_output") and hasattr(out, "activation"):
                # OutputLayer.apply already applies activation
                pass
            return y
        return fwd

    def output(self, features, train: bool = False, mask=None):
        """Inference forward pass (reference: output:2031 /
        output(INDArray, ..., featuresMask)). Jit-cached; the final output
        layer applies its activation (e.g. softmax). ``mask`` is the
        (N, T) features mask for padded sequence batches."""
        if self.train_state is None:
            self.init()
        if self._output_fn is None:
            self._output_fn = jax.jit(self.build_inference_fn())
        return self._output_fn(self.train_state.params,
                               self.train_state.model_state,
                               jnp.asarray(features),
                               None if mask is None else jnp.asarray(mask))

    def feed_forward(self, features, train: bool = False) -> List[jnp.ndarray]:
        """All layer activations (reference: feedForward())."""
        acts, _ = self._forward(self.train_state.params,
                                self.train_state.model_state,
                                jnp.asarray(features), None, train,
                                None, collect=True)
        return acts

    def compute_loss(self, dataset: DataSet):
        if self._loss_eval_fn is None:
            def lf(params, model_state, f, l, fm, lm):
                loss, _ = self._loss(params, model_state, f, l, fm, lm, None,
                                     jnp.zeros((), jnp.int32))
                return loss
            self._loss_eval_fn = jax.jit(lf)
        return self._loss_eval_fn(
            self.train_state.params, self.train_state.model_state,
            jnp.asarray(dataset.features), jnp.asarray(dataset.labels),
            None if dataset.features_mask is None else jnp.asarray(dataset.features_mask),
            None if dataset.labels_mask is None else jnp.asarray(dataset.labels_mask))

    # ---- rnn streaming inference ---------------------------------------
    def rnn_time_step(self, features, carries: Optional[dict] = None):
        """Stateful single/multi-step inference for recurrent nets —
        reference: rnnTimeStep (MultiLayerNetwork.java:2806). ``carries``
        maps layer name → (h, c); returns (output, new_carries).
        Functional: the caller threads the state."""
        from deeplearning4j_tpu.nn.layers.recurrent import (
            LSTM, SimpleRnn, unwrap_recurrent)
        if self.train_state is None:
            self.init()
        x = jnp.asarray(features)
        if x.ndim == 2:
            x = x[:, None, :]  # single timestep
        carries = dict(carries or {})
        params = self.train_state.params
        n = len(self.layers)
        for i, layer in enumerate(self.layers):
            pp = self._preprocessors.get(i)
            if pp is not None:
                x = pp.apply(x)
            ctx = LayerContext(train=False)
            lp = params.get(layer.name, {})
            st = self.train_state.model_state.get(layer.name, {})
            core = unwrap_recurrent(layer)
            if isinstance(core, (LSTM, SimpleRnn)):
                init = carries.get(layer.name)
                x, s = layer.apply(lp, st, x, ctx, initial_state=init)
                if isinstance(core, LSTM):
                    carries[layer.name] = (s["last_h"], s["last_c"])
                else:
                    carries[layer.name] = s["last_h"]
            elif i == n - 1 and hasattr(layer, "pre_output"):
                x, _ = layer.apply(lp, st, x, ctx)
            else:
                x, _ = layer.apply(lp, st, x, ctx)
        return x, carries

    # ---- misc -----------------------------------------------------------
    def summary(self) -> str:
        lines = [f"{'idx':<4}{'name':<22}{'type':<26}{'params':>10}  out"]
        for i, l in enumerate(self.layers):
            nparams = 0
            if self.train_state is not None:
                nparams = sum(int(np.prod(a.shape)) for a in
                              jax.tree_util.tree_leaves(
                                  self.train_state.params.get(l.name, {})))
            out_t = l.output_type(self._input_types[i])
            lines.append(f"{i:<4}{l.name:<22}{type(l).__name__:<26}"
                         f"{nparams:>10}  {out_t.shape()}")
        lines.append(f"total params: {self.num_params() if self.train_state else '?'}")
        return "\n".join(lines)

    def clone(self) -> "MultiLayerNetwork":
        m = MultiLayerNetwork(self.conf)
        if self.train_state is not None:
            # no init(): build just the optimizer transform and DEEP-copy
            # the state (the train step donates its input buffers, so
            # sharing references would let future fit() calls invalidate
            # the clone's arrays on TPU)
            m._tx = m._make_tx()
            m._rng = self._rng
            copy = lambda t: jax.tree_util.tree_map(jnp.array, t)
            m.train_state = TrainState(
                copy(self.train_state.params),
                copy(self.train_state.model_state),
                copy(self.train_state.opt_state),
                jnp.array(self.train_state.iteration))
            m.epoch_count = self.epoch_count
        return m

    # ---- layerwise pretraining ------------------------------------------
    def pretrain(self, iterator, epochs: int = 1):
        """Greedy layerwise unsupervised pretraining of every layer that
        defines ``pretrain_loss`` (AutoEncoder, VariationalAutoencoder) —
        the reference's MultiLayerNetwork.pretrain(DataSetIterator)."""
        for i, layer in enumerate(self.layers):
            if getattr(layer, "supports_pretrain", False):
                self.pretrain_layer(i, iterator, epochs)
        return self

    def pretrain_layer(self, idx: int, iterator, epochs: int = 1):
        """Pretrain one layer on activations from the (frozen) layers below
        it (reference: pretrainLayer(int, DataSetIterator))."""
        import optax
        if self.train_state is None:
            self.init()
        layer = self.layers[idx]
        if not getattr(layer, "supports_pretrain", False):
            return self
        g = self.conf.global_config
        updater = layer.updater or g.updater
        tx = updater.to_optax()
        lp = self.train_state.params[layer.name]
        opt_state = tx.init(lp)
        all_params = self.train_state.params
        model_state = self.train_state.model_state
        pp = self._preprocessors.get(idx)

        def step(lp, opt_state, x, key):
            def lf(lp):
                h, _ = self._forward(all_params, model_state, x, None,
                                     False, None, upto=idx)
                if pp is not None:
                    h = pp.apply(h)
                return layer.pretrain_loss(lp, h, key)

            loss, grads = jax.value_and_grad(lf)(lp)
            updates, opt_state2 = tx.update(grads, opt_state, lp)
            return optax.apply_updates(lp, updates), opt_state2, loss

        jstep = jax.jit(step, donate_argnums=(0, 1))
        for _ in range(epochs):
            for ds in iterator:
                self._rng, k = jax.random.split(self._rng)
                lp, opt_state, loss = jstep(
                    lp, opt_state, jnp.asarray(ds.features), k)
            if hasattr(iterator, "reset"):
                iterator.reset()
        new_params = dict(self.train_state.params)
        new_params[layer.name] = lp
        self.train_state = self.train_state._replace(params=new_params)
        self._last_loss = float(loss)  # host-sync-ok: end-of-pretrain loss read, once per layer
        return self
