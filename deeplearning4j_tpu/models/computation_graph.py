"""ComputationGraph — arbitrary-DAG model (multi-input / multi-output).

Analog of the reference's ``ComputationGraph``
(deeplearning4j-nn/.../nn/graph/ComputationGraph.java:93 — init():377,
topologicalSortOrder():1216, calcBackpropGradients:1947). Execution walks
the topological order computed at config time; the whole DAG — every
branch, merge, and loss — compiles to one XLA executable. Backprop in
reverse topo order is replaced by ``jax.grad`` through the forward walk.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.models.base import BaseModel, cast_params, compute_cast
from deeplearning4j_tpu.nn.graph.config import ComputationGraphConfiguration
from deeplearning4j_tpu.nn.inputs import RecurrentType
from deeplearning4j_tpu.nn.layers.base import LayerContext
from deeplearning4j_tpu.optimize.solver import (
    TrainState,
    make_constrain_fn,
    build_optimizer,
    make_scan_train_step,
    make_train_step,
)


class ComputationGraph(BaseModel):
    def __init__(self, conf: ComputationGraphConfiguration):
        super().__init__()
        self.conf = conf
        conf.resolve()
        self._topo = conf.topological_order()
        self._nodes = {n.name: n for n in conf.nodes}
        self._layer_nodes = [n for n in conf.nodes if n.layer is not None]
        self.layer_names = tuple(n.name for n in self._layer_nodes)
        self._output_fn = None
        self._loss_eval_fn = None
        self._tbptt_step = None
        self._rnn_step_fn = None
        self._rnn_carries = None   # stored state for rnn_time_step
        # tensor-parallel activation specs (parallel/tensor_parallel.py);
        # set by ParallelWrapper when TP is enabled
        self._tp_plan = None

    @property
    def conf_global(self):
        return self.conf.global_config

    # ---- init -----------------------------------------------------------
    def init(self, seed: Optional[int] = None):
        g = self.conf.global_config
        root = jax.random.PRNGKey(g.seed if seed is None else seed)
        self._rng = jax.random.fold_in(root, 0x5eed)
        params: Dict[str, Any] = {}
        state: Dict[str, Any] = {}
        for i, node in enumerate(self._layer_nodes):
            it = self.conf.layer_input_type(node.name)
            k = jax.random.fold_in(root, i)
            layer = node.layer
            params[node.name] = (layer.initialize(k, it)
                                 if layer.has_params else {})
            state[node.name] = layer.init_state(it)
        tx = self._make_tx()
        opt_state = tx.init(params)
        self.train_state = TrainState(params, state, opt_state,
                                      jnp.zeros((), jnp.int32))
        self._tx = tx
        return self

    def _make_tx(self):
        g = self.conf.global_config
        return build_optimizer(
            self.layer_names,
            {n.name: n.layer.updater for n in self._layer_nodes},
            {n.name: n.layer.frozen for n in self._layer_nodes},
            g.updater,
            g.gradient_normalization,
        )

    # ---- functional forward --------------------------------------------
    def _walk(self, params, model_state, inputs: Dict[str, jnp.ndarray],
              fmasks: Dict[str, Optional[jnp.ndarray]], train: bool, rng,
              stop_before_loss: bool, carries: Optional[dict] = None):
        """Execute the DAG. Returns (activations dict, new_state).
        When ``stop_before_loss`` the output layers' pre-activations are
        stored for the fused-loss path. ``carries`` maps recurrent node
        name → initial hidden state (TBPTT chunk chaining + stateful
        rnn_time_step — reference: rnnActivateUsingStoredState,
        ComputationGraph.java:2753)."""
        g = self.conf.global_config
        acts: Dict[str, jnp.ndarray] = {}
        for k, v in inputs.items():
            acts[k] = compute_cast(jnp.asarray(v), g.compute_dtype)
        new_state = dict(model_state)
        for li, name in enumerate(self._topo):
            node = self._nodes[name]
            xs = [acts[s] for s in node.inputs]
            if node.layer is not None:
                x = xs[0]
                if node.preprocessor is not None:
                    x = node.preprocessor.apply(x)
                key = None if rng is None else jax.random.fold_in(rng, li)
                it = self.conf.layer_input_type(name)
                mask = None
                if isinstance(it, RecurrentType):
                    mask = fmasks.get(node.inputs[0])
                    if mask is None:
                        mask = fmasks.get("__default__")
                ctx = LayerContext(train=train, rng=key, mask=mask)
                lp = cast_params(params.get(name, {}), g.compute_dtype)
                lp = node.layer.apply_weight_noise(lp, ctx, key)
                is_output = name in self.conf.network_outputs
                if is_output and stop_before_loss and hasattr(
                        node.layer, "compute_loss"):
                    acts[name] = (x, lp, ctx)  # defer to loss
                    continue
                if carries is not None and name in carries:
                    y, s = node.layer.apply(lp, model_state.get(name, {}),
                                            x, ctx,
                                            initial_state=carries[name])
                else:
                    y, s = node.layer.apply(lp, model_state.get(name, {}),
                                            x, ctx)
                new_state[name] = s
                if self._tp_plan is not None:
                    y = self._tp_plan.constrain(name, y)
                acts[name] = y
            else:
                from deeplearning4j_tpu.nn.graph.vertices import (
                    LastTimeStepVertex)
                if isinstance(node.vertex, LastTimeStepVertex):
                    m = fmasks.get(node.inputs[0])
                    if m is None:
                        m = fmasks.get("__default__")
                    acts[name] = node.vertex.apply(*xs, mask=m)
                else:
                    acts[name] = node.vertex.apply(*xs)
        return acts, new_state

    def _loss(self, params, model_state, features, labels, fmasks, lmasks,
              rng, iteration, carries: Optional[dict] = None):
        inputs = dict(zip(self.conf.network_inputs, features))
        fm = {"__default__": fmasks[0] if fmasks else None}
        for i, k in enumerate(self.conf.network_inputs):
            fm[k] = fmasks[i] if fmasks and i < len(fmasks) else None
        acts, new_state = self._walk(params, model_state, inputs, fm, True,
                                     rng, stop_before_loss=True,
                                     carries=carries)
        any_leaf = jax.tree_util.tree_leaves(params)
        acc = (jnp.promote_types(jnp.float32, any_leaf[0].dtype)
               if any_leaf else jnp.float32)
        total = jnp.zeros((), acc)
        for i, out_name in enumerate(self.conf.network_outputs):
            node = self._nodes[out_name]
            entry = acts[out_name]
            label = labels[i]
            lmask = lmasks[i] if lmasks and i < len(lmasks) else None
            if isinstance(entry, tuple) and hasattr(node.layer, "compute_loss"):
                x, lp, ctx = entry
                if lmask is not None:
                    ctx = dataclasses.replace(ctx, mask=lmask)
                loss = node.layer.compute_loss(
                    lp, model_state.get(out_name, {}), x, label, ctx)
            else:
                raise TypeError(f"output node '{out_name}' is not a loss-"
                                "bearing layer")
            total = total + loss.astype(acc)
        for n in self._layer_nodes:
            total = total + n.layer.regularization_loss(params.get(n.name, {}))
        # auxiliary losses surfaced via layer state (MoE load balancing)
        for s in new_state.values():
            if isinstance(s, dict) and "moe_aux_loss" in s:
                total = total + s["moe_aux_loss"].astype(acc)
        return total, new_state

    def _constraint_layers(self):
        return [n.layer for n in self._layer_nodes]

    def _build_train_step(self):
        def loss_fn(params, model_state, features, labels, fmask, lmask, rng,
                    iteration):
            # features/labels arrive as tuples (multi-input safe)
            return self._loss(params, model_state, features, labels, fmask,
                              lmask, rng, iteration)
        return make_train_step(
            loss_fn, self._tx,
            constrain_fn=make_constrain_fn(
                [l for l in self._constraint_layers()]),
            telemetry=self._telemetry_spec())

    def _build_scan_train_step(self):
        """K fused steps per dispatch; the scan carries the input/output
        tuples so each inner step sees per-batch (B, ...) elements."""
        def loss_fn(params, model_state, features, labels, fmask, lmask,
                    rng, iteration):
            return self._loss(params, model_state, features, labels, fmask,
                              lmask, rng, iteration)
        return make_scan_train_step(
            loss_fn, self._tx,
            constrain_fn=make_constrain_fn(
                [l for l in self._constraint_layers()]),
            telemetry=self._telemetry_spec())

    def _staged_step_args(self, features, labels, fmask, lmask):
        # the DeviceFeeder stages plain DataSets; this graph's step takes
        # input/output tuples (multi-input safe) like _fit_batch_standard
        return ((features,), (labels,),
                None if fmask is None else (fmask,),
                None if lmask is None else (lmask,))

    # ---- fit ------------------------------------------------------------
    def _fit_batch_standard(self, batch: Union[DataSet, MultiDataSet],
                            etl_ms: float = 0.0):
        from deeplearning4j_tpu.observe.tracer import get_tracer
        tracer = get_tracer(self)
        self._rng, step_key = jax.random.split(self._rng)
        with tracer.span("host_to_device", cat="data"):
            if isinstance(batch, MultiDataSet):
                feats = tuple(jnp.asarray(f) for f in batch.features)
                labels = tuple(jnp.asarray(l) for l in batch.labels)
                fmasks = tuple(None if m is None else jnp.asarray(m)
                               for m in (batch.features_masks or [])) or None
                lmasks = tuple(None if m is None else jnp.asarray(m)
                               for m in (batch.labels_masks or [])) or None
                n_examples = batch.num_examples()
            else:
                feats = (jnp.asarray(batch.features),)
                labels = (jnp.asarray(batch.labels),)
                fmasks = (None if batch.features_mask is None
                          else (jnp.asarray(batch.features_mask),))
                lmasks = (None if batch.labels_mask is None
                          else (jnp.asarray(batch.labels_mask),))
                n_examples = batch.num_examples()
        if self._telemetry is not None:
            self.train_state = self._telemetry.ensure_buffer(
                self.train_state)
        if self.recompile_watchdog is not None:
            self.recompile_watchdog.observe(
                "train_step", feats, labels, fmasks, lmasks)
        with tracer.span("dispatch", cat="step"):
            self.train_state, loss = self._train_step(
                self.train_state, feats, labels, fmasks, lmasks, step_key)
        it = self._post_step()
        for lst in self.listeners:
            lst.iteration_done(self, it, self.epoch_count, loss, etl_ms,
                               n_examples)
        self._last_loss = loss

    # ---- truncated BPTT (reference: ComputationGraph.java:955,1184) -----
    def _recurrent_carry_nodes(self):
        """(node name, stateful core layer, is_lstm) for every node whose
        hidden state crosses TBPTT chunks / rnn_time_step calls —
        including LSTM/SimpleRnn wrapped in LastTimeStep/MaskZeroLayer
        (the wrappers delegate state to the core)."""
        from deeplearning4j_tpu.nn.layers.recurrent import (
            LSTM, SimpleRnn, unwrap_recurrent)
        out = []
        for n in self._layer_nodes:
            core = unwrap_recurrent(n.layer)
            if isinstance(core, (LSTM, SimpleRnn)):
                out.append((n.name, core, isinstance(core, LSTM)))
        return out

    def _zero_carries(self, batch_size: int):
        dt = (jnp.bfloat16 if self.conf.global_config.compute_dtype ==
              "bfloat16" else jnp.float32)
        out = {}
        for name, core, is_lstm in self._recurrent_carry_nodes():
            h = jnp.zeros((batch_size, core.n_out), dt)
            out[name] = (h, h) if is_lstm else h
        return out

    def _build_tbptt_step(self):
        import optax
        constrain_fn = make_constrain_fn(list(self._constraint_layers()))
        carry_nodes = self._recurrent_carry_nodes()
        telemetry = self._telemetry_spec()

        def step(ts, features, labels, fmasks, lmasks, rng, carries):
            def lf(params):
                return self._loss(params, ts.model_state, features, labels,
                                  fmasks, lmasks, rng, ts.iteration,
                                  carries=carries)
            (loss, new_ms), grads = jax.value_and_grad(
                lf, has_aux=True)(ts.params)
            updates, new_opt = self._tx.update(grads, ts.opt_state,
                                               ts.params)
            new_params = optax.apply_updates(ts.params, updates)
            if constrain_fn is not None:
                new_params = constrain_fn(new_params)
            buf = ts.telemetry
            if telemetry is not None:
                buf = telemetry.record(buf, loss=loss, grads=grads,
                                       params=new_params,
                                       prev_params=ts.params,
                                       iteration=ts.iteration)
            # carries cross the chunk boundary with gradients cut — this
            # IS the truncation (same contract as the MLN TBPTT step)
            new_carries = {}
            for name, _, is_lstm in carry_nodes:
                s = new_ms[name]
                c = ((s["last_h"], s["last_c"]) if is_lstm
                     else s["last_h"])
                new_carries[name] = jax.lax.stop_gradient(c)
            return (TrainState(new_params, new_ms, new_opt,
                               ts.iteration + 1, buf), loss, new_carries)

        return jax.jit(step, donate_argnums=(0,))

    def _fit_batch_tbptt(self, batch, etl_ms: float = 0.0):
        """Chunked-time fit over a DAG (reference: doTruncatedBPTT path of
        ComputationGraph.fit, ComputationGraph.java:955). 3-D features and
        sequence labels are sliced along time; 2-D (static) inputs repeat
        whole into every chunk, exactly like the reference's handling of
        non-sequence graph inputs."""
        from deeplearning4j_tpu.nn.layers.recurrent import (
            first_bidirectional_name, warn_tbptt_bidirectional)
        bidi = first_bidirectional_name(
            (n.name, n.layer) for n in self._layer_nodes)
        if bidi is not None:
            warn_tbptt_bidirectional(bidi)
        if self._tbptt_step is None:
            self._tbptt_step = self._build_tbptt_step()
        if isinstance(batch, MultiDataSet):
            feats = [np.asarray(f) for f in batch.features]  # host-sync-ok: eval host staging
            labels = [np.asarray(l) for l in batch.labels]  # host-sync-ok: eval host staging
            fmasks = [None if m is None else np.asarray(m)  # host-sync-ok: eval host staging
                      for m in (batch.features_masks
                                or [None] * len(feats))]
            lmasks = [None if m is None else np.asarray(m)  # host-sync-ok: eval host staging
                      for m in (batch.labels_masks
                                or [None] * len(labels))]
        else:
            feats = [np.asarray(batch.features)]  # host-sync-ok: eval host staging
            labels = [np.asarray(batch.labels)]  # host-sync-ok: eval host staging
            fmasks = [None if batch.features_mask is None
                      else np.asarray(batch.features_mask)]  # host-sync-ok: eval host staging
            lmasks = [None if batch.labels_mask is None
                      else np.asarray(batch.labels_mask)]  # host-sync-ok: eval host staging
        k = self.conf.tbptt_fwd_length
        seq_lens = {f.shape[1] for f in feats if f.ndim == 3}
        if len(seq_lens) > 1:
            raise ValueError(
                "TBPTT fit needs equal sequence lengths across all 3-D "
                f"inputs (got {sorted(seq_lens)}): chunking slices every "
                "sequence with the same time window. Pad the shorter "
                "streams (with a features mask) to a common length.")
        T = seq_lens.pop()
        n = feats[0].shape[0]
        from deeplearning4j_tpu.observe.tracer import get_tracer
        tracer = get_tracer(self)
        if self._telemetry is not None:
            self.train_state = self._telemetry.ensure_buffer(
                self.train_state)
        carries = self._zero_carries(n)
        loss = None
        n_chunks = 0
        for lo in range(0, T, k):
            hi = min(lo + k, T)
            cf, cl, cfm, clm = [], [], [], []
            for f, fm in zip(feats, fmasks):
                if f.ndim == 3:
                    cf.append(f[:, lo:hi])
                    cfm.append(None if fm is None else fm[:, lo:hi])
                else:
                    cf.append(f)
                    cfm.append(fm)
            for l, lm in zip(labels, lmasks):
                if l.ndim == 3:
                    cl.append(l[:, lo:hi])
                    clm.append(None if lm is None else lm[:, lo:hi])
                else:
                    cl.append(l)
                    clm.append(lm)
            if hi - lo < k:
                # Ragged tail: pad every 3-D stream to length k, masking
                # padded steps out of the recurrent math and the loss —
                # the multi-stream generalization of _pad_tbptt_tail
                # (multi_layer_network.py), sharing its _pad_time
                from deeplearning4j_tpu.models.multi_layer_network import (
                    _pad_time)
                pad = k - (hi - lo)

                def padt(a):
                    return _pad_time(a, pad)

                for i in range(len(cf)):
                    if cf[i].ndim != 3:
                        continue
                    base = (cfm[i] if cfm[i] is not None
                            else np.ones((n, hi - lo), np.float32))
                    cf[i] = padt(cf[i])
                    cfm[i] = padt(base)
                # the loss falls back to the DEFAULT features mask (the
                # first input's) when an output has no labels mask; the
                # synthesized tail mask must inherit it, or the padding
                # would unmask fmask-excluded real steps (MLN contract)
                default_fm = next(
                    (m for f, m in zip(cf, cfm)
                     if f.ndim == 3 and m is not None and m.ndim == 2),
                    None)
                for i in range(len(cl)):
                    if cl[i].ndim != 3:
                        continue
                    if clm[i] is None:
                        clm[i] = (default_fm if default_fm is not None
                                  else padt(np.ones((n, hi - lo),
                                            np.float32)))
                    else:
                        clm[i] = padt(clm[i])
                    cl[i] = padt(cl[i])
            self._rng, step_key = jax.random.split(self._rng)
            tj = lambda seq: tuple(None if a is None else jnp.asarray(a)
                                   for a in seq)
            cf, cl, cfm, clm = tj(cf), tj(cl), tj(cfm), tj(clm)
            if self.recompile_watchdog is not None:
                self.recompile_watchdog.observe("tbptt_step", cf, cl,
                                                cfm, clm)
            with tracer.span("dispatch", cat="step"):
                self.train_state, loss, carries = self._tbptt_step(
                    self.train_state, cf, cl, cfm, clm, step_key, carries)
            n_chunks += 1
        it = self._post_step(n_chunks)
        for lst in self.listeners:
            lst.iteration_done(self, it, self.epoch_count, loss, etl_ms,
                               n)
        self._last_loss = loss

    def _fit_batch(self, batch: Union[DataSet, MultiDataSet],
                   etl_ms: float = 0.0):
        if (self.conf.backprop_type == "tbptt"
                and self._recurrent_carry_nodes()
                and any(np.ndim(f) == 3 for f in
                        (batch.features if isinstance(batch, MultiDataSet)
                         else [batch.features]))):
            return self._fit_batch_tbptt(batch, etl_ms=etl_ms)
        return self._fit_batch_standard(batch, etl_ms=etl_ms)

    # ---- stateful rnn inference (reference: CG.rnnTimeStep:2720) --------
    def rnn_time_step(self, *features, mask=None):
        """Streaming inference with internally stored recurrent state —
        reference: ComputationGraph.rnnTimeStep (ComputationGraph.java:
        2720). 2-D inputs are treated as one timestep and the time axis
        is squeezed from the outputs; 3-D inputs run multiple steps.
        State persists across calls until ``rnn_clear_previous_state``;
        batch-size changes reset it (same contract as the reference)."""
        from deeplearning4j_tpu.nn.layers.recurrent import (
            first_bidirectional_name)
        # unwrap inside the helper: a wrapped core must not slip past
        bidi = first_bidirectional_name(
            (n.name, n.layer) for n in self._layer_nodes)
        if bidi is not None:
            raise ValueError(
                "rnn_time_step is not supported on graphs with "
                f"bidirectional layers ('{bidi}'): the backward "
                "pass needs future timesteps")
        if self.train_state is None:
            self.init()
        if len(features) == 1 and isinstance(features[0], (list, tuple)):
            features = tuple(features[0])
        squeeze = all(np.ndim(f) == 2 for f in features)
        feats = tuple(jnp.asarray(f)[:, None, :]
                      if np.ndim(f) == 2 else jnp.asarray(f)
                      for f in features)
        n = feats[0].shape[0]
        leaves = (None if self._rnn_carries is None
                  else jax.tree_util.tree_leaves(self._rnn_carries))
        if self._rnn_carries is None or (leaves
                                         and leaves[0].shape[0] != n):
            self._rnn_carries = self._zero_carries(n)
        if self._rnn_step_fn is None:
            carry_nodes = self._recurrent_carry_nodes()

            def stepf(params, model_state, feats, default_mask, carries):
                inputs = dict(zip(self.conf.network_inputs, feats))
                fm = {"__default__": default_mask}
                acts, new_state = self._walk(
                    params, model_state, inputs, fm, False, None,
                    stop_before_loss=False, carries=carries)
                new_carries = {}
                for name, _, is_lstm in carry_nodes:
                    s = new_state[name]
                    new_carries[name] = ((s["last_h"], s["last_c"])
                                         if is_lstm else s["last_h"])
                return ([acts[o] for o in self.conf.network_outputs],
                        new_carries)
            self._rnn_step_fn = jax.jit(stepf)
        outs, self._rnn_carries = self._rnn_step_fn(
            self.train_state.params, self.train_state.model_state, feats,
            None if mask is None else jnp.asarray(mask),
            self._rnn_carries)
        if squeeze:
            outs = [o[:, 0] if o.ndim >= 3 else o for o in outs]
        return outs[0] if len(outs) == 1 else outs

    def rnn_clear_previous_state(self):
        """Reference: ComputationGraph.rnnClearPreviousState():2828."""
        self._rnn_carries = None

    def rnn_get_previous_state(self) -> Optional[dict]:
        """node name → stored hidden state ((h, c) for LSTM, h for
        SimpleRnn) — reference: rnnGetPreviousState(layer)."""
        return self._rnn_carries

    def rnn_set_previous_state(self, carries: dict):
        self._rnn_carries = None if carries is None else dict(carries)

    # ---- inference ------------------------------------------------------
    def build_inference_fn(self):
        """Pure inference forward ``(params, model_state, x, fmask) ->
        y`` for single-input single-output graphs — the shape the
        serving engine (parallel/serving.py) batches over. Multi-input /
        multi-output graphs have no single batchable signature; serve
        those through ``output()`` directly."""
        if len(self.conf.network_inputs) != 1 or \
                len(self.conf.network_outputs) != 1:
            raise ValueError(
                "build_inference_fn requires a single-input single-output"
                f" graph; this one has inputs={self.conf.network_inputs}"
                f" outputs={self.conf.network_outputs}")
        if self.train_state is None:
            self.init()
        in_name = self.conf.network_inputs[0]
        out_name = self.conf.network_outputs[0]

        def fwd(params, model_state, x, fmask):
            inputs = {in_name: x}
            fm = {"__default__": fmask}
            acts, _ = self._walk(params, model_state, inputs, fm, False,
                                 None, stop_before_loss=False)
            return acts[out_name]
        return fwd

    def output(self, *features, train: bool = False, mask=None):
        """Forward pass; returns a single array for single-output graphs,
        else a list (reference: ComputationGraph.output(INDArray...)).
        ``mask`` is the default (N, T) sequence mask for recurrent inputs."""
        if self.train_state is None:
            self.init()
        if len(features) == 1 and isinstance(features[0], (list, tuple)):
            features = tuple(features[0])
        if self._output_fn is None:
            def fwd(params, model_state, feats, default_mask):
                inputs = dict(zip(self.conf.network_inputs, feats))
                fm = {"__default__": default_mask}
                acts, _ = self._walk(params, model_state, inputs, fm, False,
                                     None, stop_before_loss=False)
                return [acts[o] for o in self.conf.network_outputs]
            self._output_fn = jax.jit(fwd)
        outs = self._output_fn(self.train_state.params,
                               self.train_state.model_state,
                               tuple(jnp.asarray(f) for f in features),
                               None if mask is None else jnp.asarray(mask))
        return outs[0] if len(outs) == 1 else outs

    def compute_loss(self, dataset: Union[DataSet, MultiDataSet]):
        if isinstance(dataset, MultiDataSet):
            feats = tuple(jnp.asarray(f) for f in dataset.features)
            labels = tuple(jnp.asarray(l) for l in dataset.labels)
        else:
            feats = (jnp.asarray(dataset.features),)
            labels = (jnp.asarray(dataset.labels),)
        if self._loss_eval_fn is None:
            def lf(params, model_state, f, l):
                loss, _ = self._loss(params, model_state, f, l, None, None,
                                     None, jnp.zeros((), jnp.int32))
                return loss
            self._loss_eval_fn = jax.jit(lf)
        return self._loss_eval_fn(self.train_state.params,
                                  self.train_state.model_state, feats, labels)

    def summary(self) -> str:
        lines = [f"{'name':<24}{'type':<26}{'inputs':<30}{'params':>10}"]
        for name in self._topo:
            node = self._nodes[name]
            kind = (type(node.layer).__name__ if node.layer is not None
                    else type(node.vertex).__name__)
            nparams = 0
            if self.train_state is not None and node.layer is not None:
                nparams = sum(int(np.prod(a.shape)) for a in
                              jax.tree_util.tree_leaves(
                                  self.train_state.params.get(name, {})))
            lines.append(f"{name:<24}{kind:<26}"
                         f"{','.join(node.inputs):<30}{nparams:>10}")
        if self.train_state is not None:
            lines.append(f"total params: {self.num_params()}")
        return "\n".join(lines)

    def clone(self) -> "ComputationGraph":
        m = ComputationGraph(self.conf)
        if self.train_state is not None:
            # see MultiLayerNetwork.clone: no wasted init, real copies
            # (donation safety)
            m._tx = m._make_tx()
            m._rng = self._rng
            copy = lambda t: jax.tree_util.tree_map(jnp.array, t)
            m.train_state = TrainState(
                copy(self.train_state.params),
                copy(self.train_state.model_state),
                copy(self.train_state.opt_state),
                jnp.array(self.train_state.iteration))
            m.epoch_count = self.epoch_count
        return m
