"""Model serialization — zip checkpoint format.

Analog of the reference's ``ModelSerializer``
(deeplearning4j-nn/.../util/ModelSerializer.java — writeModel:109 writes
``configuration.json``, ``coefficients.bin``, ``updaterState.bin``).
Same zip layout idea, arrays stored as .npy entries:

    configuration.json    — MultiLayerConfiguration / CGC JSON (serde)
    params/<path>.npy     — one entry per parameter leaf
    state/<path>.npy      — non-trainable state (BN stats)
    updater/<path>.npy    — optimizer state leaves (optional, for exact resume)
    meta.json             — model class, iteration/epoch counters

Path encoding: pytree paths joined with '/'. Restores are exact: a model
saved with its updater resumes training bit-identically (the reference's
``restoreMultiLayerNetwork(..., loadUpdater=true)``).
"""

from __future__ import annotations

import io
import json
import zipfile
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.optimize.solver import TrainState
from deeplearning4j_tpu.utils import serde


import functools


@functools.cache
def _ensure_registry():
    """Import every module that registers serializable config types, so a
    checkpoint loads in a fresh interpreter without the caller having
    imported the layer zoo first (the reference gets this for free from
    classpath scanning — NeuralNetConfiguration.java:434). Walks the whole
    ``nn`` package so newly added layer modules register automatically;
    cached so repeated restores skip the filesystem walk."""
    import importlib
    import pkgutil

    import deeplearning4j_tpu.nn as nn_pkg
    for info in pkgutil.walk_packages(nn_pkg.__path__,
                                      prefix="deeplearning4j_tpu.nn."):
        importlib.import_module(info.name)
    importlib.import_module("deeplearning4j_tpu.optimize.updaters")
    importlib.import_module("deeplearning4j_tpu.optimize.schedules")


def _flatten_with_paths(tree) -> Dict[str, np.ndarray]:
    flat = {}
    leaves_with_paths = jax.tree_util.tree_flatten_with_path(tree)[0]
    for path, leaf in leaves_with_paths:
        key = "/".join(_path_part(p) for p in path)
        flat[key] = np.asarray(leaf)  # host-sync-ok: checkpoint save copies to host by design
    return flat


def _path_part(p) -> str:
    if hasattr(p, "key"):
        return str(p.key)
    if hasattr(p, "idx"):
        return f"#{p.idx}"
    return str(p)


def _unflatten_like(template, flat: Dict[str, np.ndarray]):
    """Rebuild arrays into the same treedef as ``template``."""
    leaves_with_paths, treedef = jax.tree_util.tree_flatten_with_path(template)
    new_leaves = []
    for path, leaf in leaves_with_paths:
        key = "/".join(_path_part(p) for p in path)
        if key not in flat:
            raise KeyError(f"checkpoint missing array: {key}")
        arr = flat[key]
        # jnp.array(copy=True), never asarray: on the CPU backend asarray
        # zero-copy aliases any 64-byte-aligned host array (astype/reshape
        # to the same dtype/shape are no-ops that keep the alias), and a
        # donated train step after restore would then hand XLA a buffer
        # numpy still owns — intermittent heap corruption on restore->fit
        new_leaves.append(
            jnp.array(arr, copy=True).astype(leaf.dtype).reshape(leaf.shape))
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


def _write_tree(zf: zipfile.ZipFile, prefix: str, tree):
    for key, arr in _flatten_with_paths(tree).items():
        buf = io.BytesIO()
        np.save(buf, arr)
        zf.writestr(f"{prefix}/{key}.npy", buf.getvalue())


def _read_tree(zf: zipfile.ZipFile, prefix: str) -> Dict[str, np.ndarray]:
    out = {}
    plen = len(prefix) + 1
    for name in zf.namelist():
        if name.startswith(prefix + "/") and name.endswith(".npy"):
            with zf.open(name) as f:
                out[name[plen:-4]] = np.load(io.BytesIO(f.read()))
    return out


def save_model(model, path: str, save_updater: bool = False):
    """reference: ModelSerializer.writeModel:109."""
    from deeplearning4j_tpu.models.computation_graph import ComputationGraph
    from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork

    if model.train_state is None:
        model.init()
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
        zf.writestr("configuration.json", model.conf.to_json())
        _write_tree(zf, "params", model.train_state.params)
        _write_tree(zf, "state", model.train_state.model_state)
        if save_updater:
            _write_tree(zf, "updater", model.train_state.opt_state)
        meta = {
            "model_class": type(model).__name__,
            "iteration": int(model.train_state.iteration),
            "epoch": model.epoch_count,
            "has_updater": save_updater,
            "framework_version": "0.2.0",
            # packed-QKV column order for attention layers; 0.1.0
            # checkpoints (no tag) used which-major ([q|k|v] blocks)
            "qkv_layout": "head_major",
        }
        zf.writestr("meta.json", json.dumps(meta))


def _named_layers(model) -> Dict[str, Any]:
    if hasattr(model, "layers"):          # MultiLayerNetwork
        return {l.name: l for l in model.layers}
    return {n.name: n.layer for n in model._layer_nodes}  # ComputationGraph


def _migrate_qkv_layout(model, params):
    """Upgrade pre-0.2.0 checkpoints: attention QKV packing changed from
    which-major ([q|k|v] column blocks) to head-major ((head, which, dh))
    so tensor parallelism can shard whole heads with contiguous tiles.
    Returns params with every Wqkv/bqkv re-packed; other leaves shared."""
    from deeplearning4j_tpu.nn.layers.attention import (
        SelfAttentionLayer, TransformerEncoderBlock)

    def repack(p, n_heads, n_out):
        dh = n_out // n_heads
        out = dict(p)
        if "Wqkv" in p:
            w = p["Wqkv"]
            f = w.shape[0]
            out["Wqkv"] = (w.reshape(f, 3, n_heads, dh)
                           .transpose(0, 2, 1, 3).reshape(f, 3 * n_out))
        if "bqkv" in p:
            out["bqkv"] = (p["bqkv"].reshape(3, n_heads, dh)
                           .transpose(1, 0, 2).reshape(-1))
        return out

    new = dict(params)
    for name, layer in _named_layers(model).items():
        lp = new.get(name)
        if not isinstance(lp, dict):
            continue
        if isinstance(layer, TransformerEncoderBlock) and "attn" in lp:
            lp = dict(lp)
            lp["attn"] = repack(lp["attn"], layer.n_heads, layer.n_out)
            new[name] = lp
        elif isinstance(layer, SelfAttentionLayer) and "Wqkv" in lp:
            new[name] = repack(lp, layer.n_heads, layer.n_out)
    return new


def _migrate_qkv_opt_state(model, opt_state):
    """Apply the same which-major → head-major repack to optimizer-state
    leaves that mirror an attention param (Adam mu/nu etc.): each leaf's
    path names the layer and ends in Wqkv/bqkv. Without this, restored
    moments pair with the wrong weight columns after migration."""
    from deeplearning4j_tpu.nn.layers.attention import (
        SelfAttentionLayer, TransformerEncoderBlock)
    heads = {}
    for name, layer in _named_layers(model).items():
        if isinstance(layer, (SelfAttentionLayer, TransformerEncoderBlock)):
            heads[name] = (layer.n_heads, layer.n_out)

    def fix(path, leaf):
        keys = [getattr(p, "key", None) for p in path]
        last = keys[-1] if keys else None
        if last not in ("Wqkv", "bqkv"):
            return leaf
        layer_name = next((k for k in keys if k in heads), None)
        if layer_name is None:
            return leaf
        n_heads, n_out = heads[layer_name]
        dh = n_out // n_heads
        if last == "Wqkv" and leaf.ndim == 2 \
                and leaf.shape[1] == 3 * n_out:
            f = leaf.shape[0]
            return (leaf.reshape(f, 3, n_heads, dh)
                    .transpose(0, 2, 1, 3).reshape(f, 3 * n_out))
        if last == "bqkv" and leaf.ndim == 1 \
                and leaf.shape[0] == 3 * n_out:
            return (leaf.reshape(3, n_heads, dh)
                    .transpose(1, 0, 2).reshape(-1))
        return leaf

    flat, tree = jax.tree_util.tree_flatten_with_path(opt_state)
    return jax.tree_util.tree_unflatten(
        tree, [fix(p, l) for p, l in flat])


def _restore(path: str, expected_class: str, loader, load_updater: bool):
    _ensure_registry()
    with zipfile.ZipFile(path, "r") as zf:
        meta = json.loads(zf.read("meta.json"))
        if meta["model_class"] != expected_class:
            raise TypeError(f"checkpoint holds a {meta['model_class']}, not a"
                            f" {expected_class}")
        conf = loader(zf.read("configuration.json").decode())
        from deeplearning4j_tpu.models.computation_graph import ComputationGraph
        from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork
        cls = (MultiLayerNetwork if expected_class == "MultiLayerNetwork"
               else ComputationGraph)
        model = cls(conf)
        model.init()
        migrate = meta.get("qkv_layout") != "head_major"
        params = _unflatten_like(model.train_state.params, _read_tree(zf, "params"))
        if migrate:
            params = _migrate_qkv_layout(model, params)
        state = _unflatten_like(model.train_state.model_state,
                                _read_tree(zf, "state"))
        opt_state = model.train_state.opt_state
        if load_updater and meta.get("has_updater"):
            opt_state = _unflatten_like(opt_state, _read_tree(zf, "updater"))
            if migrate:
                opt_state = _migrate_qkv_opt_state(model, opt_state)
        model.train_state = TrainState(params, state, opt_state,
                                       jnp.asarray(meta["iteration"], jnp.int32))
        model.epoch_count = meta.get("epoch", 0)
        return model


def restore_model(path: str, load_updater: bool = False):
    """Class-agnostic restore: reads the checkpoint's own class tag
    (reference analog: ModelGuesser.loadModelGuess for DL4J zips)."""
    with zipfile.ZipFile(path, "r") as zf:
        cls_name = json.loads(zf.read("meta.json"))["model_class"]
    if cls_name == "MultiLayerNetwork":
        return restore_multi_layer_network(path, load_updater)
    return restore_computation_graph(path, load_updater)


def restore_multi_layer_network(path: str, load_updater: bool = False):
    """reference: ModelSerializer.restoreMultiLayerNetwork."""
    from deeplearning4j_tpu.nn.config import MultiLayerConfiguration
    return _restore(path, "MultiLayerNetwork",
                    MultiLayerConfiguration.from_json, load_updater)


def restore_computation_graph(path: str, load_updater: bool = False):
    """reference: ModelSerializer.restoreComputationGraph."""
    from deeplearning4j_tpu.nn.graph.config import ComputationGraphConfiguration
    return _restore(path, "ComputationGraph",
                    ComputationGraphConfiguration.from_json, load_updater)
