"""Base model: shared fit/evaluate machinery for MultiLayerNetwork and
ComputationGraph.

Analog of the reference's ``Model``/``NeuralNetwork`` contracts
(deeplearning4j-nn/.../nn/api/Model.java) and the shared parts of the fit
loop (MultiLayerNetwork.fit at nn/multilayer/MultiLayerNetwork.java:1268):
iterate minibatches, record ETL time, run the optimizer step, fire
listeners. Here the optimizer step is one donated jitted function
(optimize/solver.py) and 'workspaces' are XLA's memory plan.
"""

from __future__ import annotations

import time
from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet, DataSetIterator
from deeplearning4j_tpu.evaluation.evaluation import Evaluation, RegressionEvaluation
from deeplearning4j_tpu.optimize.listeners import TrainingListener
from deeplearning4j_tpu.optimize.solver import TrainState


def compute_cast(x, dt: str):
    """Cast an activation to the configured compute dtype (bf16 policy)."""
    if dt == "bfloat16" and jnp.issubdtype(x.dtype, jnp.floating):
        return x.astype(jnp.bfloat16)
    return x


def cast_params(lp, dt: str):
    """Cast a layer's float params to the compute dtype (master copies
    stay f32 in the optimizer; this is the per-step working copy)."""
    if dt != "bfloat16":
        return lp
    return jax.tree_util.tree_map(
        lambda a: a.astype(jnp.bfloat16)
        if jnp.issubdtype(a.dtype, jnp.floating) else a, lp)



class BaseModel:
    def __init__(self):
        self.train_state: Optional[TrainState] = None
        self.listeners: List[TrainingListener] = []
        self._train_step = None
        self._scan_step = None
        self._rng = None
        self.epoch_count = 0
        self._last_loss = None
        # observability (observe/): in-step telemetry collector, span
        # tracer, recompile watchdog. All optional; the defaults cost one
        # branch per step.
        self._telemetry = None
        self.tracer = None
        self.recompile_watchdog = None
        # flight recorder: None means "use the process-wide default",
        # which is armed unless DL4J_CRASH_DUMPS=0 (the reference's
        # CrashReportingUtil is likewise on by default)
        self._flight_recorder = None
        # host-side mirror of train_state.iteration: reading the device
        # scalar every step (int(ts.iteration)) is itself a per-step
        # device sync; the mirror is re-adopted from the device once per
        # fit() call and advanced locally afterwards
        self._host_iteration: Optional[int] = None

    # ---- to be provided by subclasses -----------------------------------
    def init(self, seed: Optional[int] = None):
        raise NotImplementedError

    def _build_train_step(self):
        raise NotImplementedError

    def _build_scan_train_step(self):
        """K-step fused variant (optimize/solver.make_scan_train_step);
        built lazily by the fit loop when ``k_steps > 1``."""
        raise NotImplementedError

    def output(self, features, train: bool = False):
        raise NotImplementedError

    @property
    def conf_global(self):
        raise NotImplementedError

    # ---- params ---------------------------------------------------------
    @property
    def params(self):
        return self.train_state.params

    @property
    def model_state(self):
        return self.train_state.model_state

    def num_params(self) -> int:
        leaves = jax.tree_util.tree_leaves(self.train_state.params)
        return int(sum(np.prod(l.shape) for l in leaves))

    def set_params(self, params):
        # numpy leaves are copied onto the device, never zero-copy
        # aliased: the donated train step must own every buffer it is
        # handed, and CPU asarray/device_put alias aligned host arrays
        params = jax.tree_util.tree_map(
            lambda a: jnp.array(a, copy=True)
            if isinstance(a, np.ndarray) else a, params)
        self.train_state = self.train_state._replace(params=params)

    def set_listeners(self, *listeners: TrainingListener):
        self.listeners = list(listeners)
        return self

    def add_listeners(self, *listeners: TrainingListener):
        self.listeners.extend(listeners)
        return self

    @property
    def iteration_count(self) -> int:
        return int(self.train_state.iteration)

    # ---- observability ---------------------------------------------------
    @property
    def telemetry(self):
        """The attached TelemetryCollector, or None."""
        return self._telemetry

    def set_telemetry(self, collector):
        """Attach an ``observe.TelemetryCollector``: the metric spec is
        compiled into the next train step built, the ring buffer rides in
        the TrainState, and the collector flushes it every N steps in one
        device fetch. Pass None to detach."""
        if collector is not None:
            collector.spec_for(self)
        self._telemetry = collector
        # the spec is baked into the jitted steps — force rebuilds
        self._train_step = None
        self._scan_step = None
        if hasattr(self, "_tbptt_step"):
            self._tbptt_step = None
        return self

    def set_tracer(self, tracer):
        """Attach an ``observe.SpanTracer`` recording etl / transfer /
        dispatch / flush spans around the fit loop."""
        self.tracer = tracer
        return self

    def set_recompile_watchdog(self, watchdog):
        self.recompile_watchdog = watchdog
        return self

    def set_flight_recorder(self, recorder):
        """Attach an ``observe.FlightRecorder`` (post-mortem dumps on
        NaN/OOM/crash). Without one the process-wide default recorder is
        used; attach a recorder with ``enabled=False`` to opt this model
        out without touching the environment."""
        self._flight_recorder = recorder
        return self

    def _recorder(self):
        if self._flight_recorder is not None:
            return self._flight_recorder
        from deeplearning4j_tpu.observe.flight_recorder import (
            default_flight_recorder)
        return default_flight_recorder()

    def _telemetry_spec(self):
        return (None if self._telemetry is None
                else self._telemetry.spec_for(self))

    def _advance_iteration(self, steps: int = 1) -> int:
        """Host-tracked iteration count after a dispatched step. Syncs
        with the device scalar only when the mirror is stale (once per
        fit() call), so steady-state listener dispatch costs no
        device→host round trip."""
        if self._host_iteration is None:
            self._host_iteration = int(self.train_state.iteration)
        else:
            self._host_iteration += steps
        return self._host_iteration

    def _post_step(self, steps: int = 1) -> int:
        """Shared per-dispatch epilogue: advance the iteration mirror,
        give the telemetry collector its flush opportunity, and let the
        flight recorder scan whatever that flush decoded (the recorder
        reads host-side history only — no device interaction)."""
        it = self._advance_iteration(steps)
        tel = self._telemetry
        if tel is not None:
            flushed = tel.will_flush(steps)
            if flushed:
                from deeplearning4j_tpu.observe.tracer import get_tracer
                with get_tracer(self).span("telemetry_flush",
                                           cat="telemetry"):
                    tel.on_step(self.train_state, steps)
            else:
                tel.on_step(self.train_state, steps)
            if flushed:
                rec = self._recorder()
                if rec is not None:
                    rec.poll(self)
        return it

    # ---- fit loop -------------------------------------------------------
    def fit(self, data, epochs: int = 1, k_steps: Optional[int] = None,
            prefetch: Optional[int] = None,
            byte_budget: Optional[int] = None):
        """fit(DataSet) / fit(DataSetIterator[, epochs]) — the reference's
        MultiLayerNetwork.fit(DataSetIterator) hot loop.

        Iterator fits run through the DeviceFeeder input pipeline
        (datasets/feeder.py): the next ``prefetch`` batches (default 2)
        are staged onto the device while the current step computes, and
        plain iterators are auto-wrapped in an AsyncDataSetIterator so
        host-side batch production overlaps too (the reference wraps at
        MultiLayerNetwork.java:1273). Wrap the iterator in
        AsyncShieldDataSetIterator (``async_supported = False``) or pass
        ``prefetch=0`` to opt out and get the strictly synchronous loop.

        ``k_steps > 1`` additionally fuses K prefetched batches into ONE
        device dispatch via the scanned train step — per-dispatch
        overhead is paid once per K optimizer steps. Ragged batches are
        padded to the bucket size with a zero labels mask (bitwise-
        neutral for the masked loss), so the whole epoch — partial final
        batch included — runs on one compiled signature. Iteration
        counts advance by K and telemetry still records one row per
        inner step; listeners fire once per dispatch with the last inner
        loss.

        Any exception escaping the loop (including XLA OOM) first passes
        through the flight recorder, which writes a post-mortem dump and
        re-raises — the CrashReportingUtil contract: the crash still
        surfaces, but the evidence survives."""
        try:
            return self._fit_inner(data, epochs, k_steps=k_steps,
                                   prefetch=prefetch,
                                   byte_budget=byte_budget)
        except Exception as e:
            rec = self._recorder()
            if rec is not None:
                rec.record_crash(self, exc=e)
            raise

    def _feed_supported(self) -> bool:
        """TBPTT slices batches along time on the host, so those configs
        take the unfed path; everything else can be staged ahead."""
        return getattr(getattr(self, "conf", None), "backprop_type",
                       None) != "tbptt"

    def _staged_step_args(self, features, labels, fmask, lmask):
        """Adapt device-staged arrays to this model's step signature
        (ComputationGraph wraps singles into input/output tuples)."""
        return features, labels, fmask, lmask

    def _fit_inner(self, data, epochs: int = 1,
                   k_steps: Optional[int] = None,
                   prefetch: Optional[int] = None,
                   byte_budget: Optional[int] = None):
        if self.train_state is None:
            self.init()
        else:
            # scope-panic analog (utils/sanitizers.py): a donated/stale
            # TrainState must fail HERE with a clear message, not at the
            # next dispatch deep inside jit
            from deeplearning4j_tpu.utils.sanitizers import (
                check_not_donated)
            check_not_donated(self.train_state.params,
                              what="fit() train state")
        if self._train_step is None:
            self._train_step = self._build_train_step()
        from deeplearning4j_tpu.datasets.dataset import MultiDataSet
        if isinstance(data, MultiDataSet):
            from deeplearning4j_tpu.models.computation_graph import (
                ComputationGraph)
            if not isinstance(self, ComputationGraph):
                raise TypeError(
                    "MultiDataSet requires a ComputationGraph; wrap "
                    "single-input data in a DataSet for "
                    "MultiLayerNetwork")
        # re-adopt the device iteration once per fit() call: external
        # code may have swapped train_state (checkpoint load, transfer
        # learning) since the last fit
        self._host_iteration = None
        from deeplearning4j_tpu.observe.tracer import get_tracer
        tracer = get_tracer(self)
        if isinstance(data, (DataSet, MultiDataSet)):
            # single-batch fit: _post_step already flushed if an interval
            # completed; flushing unconditionally here would turn the
            # common fit-per-batch driver loop into one fetch per step
            self._fit_batch(data)
            return self
        iterator = data
        # k_steps/prefetch left at None pick up the machine-measured
        # TunedConfig when one is installed (serve/train started with
        # --tuned-config), else the committed defaults — explicit
        # arguments always win
        from deeplearning4j_tpu.optimize.autotune import tuned_value
        k_tuned = False
        if k_steps is None:
            k_steps = tuned_value("fit.k_steps")
            k_tuned = k_steps is not None
        k = 1 if k_steps is None else int(k_steps)
        if k < 1:
            raise ValueError("k_steps must be >= 1")
        from deeplearning4j_tpu.datasets.feeder import (
            DEFAULT_DEPTH, DeviceFeeder)
        from deeplearning4j_tpu.datasets.iterators import (
            AsyncDataSetIterator)
        if prefetch is None:
            prefetch = tuned_value("feeder.depth")
        depth = DEFAULT_DEPTH if prefetch is None else int(prefetch)
        feed = (depth > 0 and self._feed_supported()
                and getattr(iterator, "async_supported", True))
        if k > 1 and not feed:
            if k_tuned:
                # a machine-tuned k must never break a fit the feeder
                # can't serve (shielded iterator, TBPTT, prefetch=0) —
                # implicit tuning degrades, only explicit asks raise
                k = 1
            else:
                raise ValueError(
                    "k_steps > 1 needs the device feeder: prefetch must "
                    "be >= 1, the iterator async-capable (no "
                    "AsyncShield), and the model not configured for "
                    "TBPTT")
        source = iterator
        if (feed and isinstance(iterator, DataSetIterator)
                and not isinstance(iterator, AsyncDataSetIterator)):
            # the reference's contract: fit() itself provides the
            # prefetch thread unless the iterator opted out (shield) or
            # already is one
            source = AsyncDataSetIterator(iterator)
        feeder = (DeviceFeeder(source, depth=depth, byte_budget=byte_budget,
                               k_steps=k, tracer=tracer)
                  if feed else None)
        for epoch in range(epochs):
            for lst in self.listeners:
                lst.on_epoch_start(self, self.epoch_count)
            if feeder is not None:
                self._fit_epoch_fed(feeder, tracer)
            else:
                it_start = time.perf_counter()
                for batch in iterator:
                    now = time.perf_counter()
                    etl_ms = (now - it_start) * 1000.0
                    tracer.add_span("etl", it_start, now, cat="data")
                    self._fit_batch(batch, etl_ms=etl_ms)
                    it_start = time.perf_counter()
            if isinstance(source, DataSetIterator):
                source.reset()
            for lst in self.listeners:
                lst.on_epoch_end(self, self.epoch_count)
            self.epoch_count += 1
        # tail flush so the last (< flush_interval) rows aren't stranded
        # on device when training ends
        if self._telemetry is not None:
            with tracer.span("telemetry_flush", cat="telemetry"):
                self._telemetry.flush(self.train_state)
            rec = self._recorder()
            if rec is not None:
                rec.poll(self)
        return self

    def _fit_batch(self, batch: DataSet, etl_ms: float = 0.0):
        from deeplearning4j_tpu.observe.tracer import get_tracer
        tracer = get_tracer(self)
        self._rng, step_key = jax.random.split(self._rng)
        with tracer.span("host_to_device", cat="data"):
            features = jnp.asarray(batch.features)
            labels = jnp.asarray(batch.labels)
            fmask = None if batch.features_mask is None else jnp.asarray(
                batch.features_mask)
            lmask = None if batch.labels_mask is None else jnp.asarray(
                batch.labels_mask)
        if self._telemetry is not None:
            self.train_state = self._telemetry.ensure_buffer(
                self.train_state)
        if self.recompile_watchdog is not None:
            self.recompile_watchdog.observe(
                "train_step", features, labels, fmask, lmask)
        with tracer.span("dispatch", cat="step"):
            self.train_state, loss = self._train_step(
                self.train_state, features, labels, fmask, lmask, step_key)
        it = self._post_step()
        for lst in self.listeners:
            lst.iteration_done(self, it, self.epoch_count, loss, etl_ms,
                               batch.num_examples())
        self._last_loss = loss

    # ---- fed fit path (datasets/feeder.DeviceFeeder) --------------------
    def _fit_epoch_fed(self, feeder, tracer):
        """One epoch off the device feeder: arrays arrive pre-staged, so
        the only host work per dispatch is handing them to the jitted
        step. ``k == 0`` items are foreign objects (e.g. MultiDataSet)
        the feeder passed through — they take the classic unfed path."""
        for item in feeder:
            if item.k == 0:
                self._fit_batch(item.raw, etl_ms=item.queue_wait_ms)
            elif item.k == 1:
                self._fit_staged(item, tracer)
            else:
                self._fit_group(item, tracer)

    def _fit_staged(self, item, tracer):
        """Single pre-staged batch → one step dispatch. Mirrors
        _fit_batch exactly (same rng split, same step, same watchdog
        key), minus the host→device transfer that already happened in
        the feeder — the K=1 fed trajectory is bitwise-equal to unfed."""
        self._rng, step_key = jax.random.split(self._rng)
        args = self._staged_step_args(item.features, item.labels,
                                      item.features_mask, item.labels_mask)
        if self._telemetry is not None:
            self.train_state = self._telemetry.ensure_buffer(
                self.train_state)
        if self.recompile_watchdog is not None:
            self.recompile_watchdog.observe("train_step", *args)
        with tracer.span("dispatch", cat="step"):
            self.train_state, loss = self._train_step(
                self.train_state, *args, step_key)
        it = self._post_step()
        for lst in self.listeners:
            lst.iteration_done(self, it, self.epoch_count, loss,
                               item.queue_wait_ms, item.n_examples)
        self._last_loss = loss

    def _fit_group(self, item, tracer):
        """K stacked pre-staged batches → ONE scanned dispatch running K
        optimizer steps (bench.py's amortization, promoted to fit).
        Iteration advances by K, telemetry records a row per inner step
        on-device, listeners fire once with the last inner loss and the
        group's REAL (pre-padding) example count."""
        if self._scan_step is None:
            self._scan_step = self._build_scan_train_step()
        self._rng, group_key = jax.random.split(self._rng)
        args = self._staged_step_args(item.features, item.labels,
                                      item.features_mask, item.labels_mask)
        if self._telemetry is not None:
            self.train_state = self._telemetry.ensure_buffer(
                self.train_state)
        if self.recompile_watchdog is not None:
            self.recompile_watchdog.observe("scan_train_step", *args)
        with tracer.span("dispatch", cat="step", k=item.k):
            self.train_state, losses = self._scan_step(
                self.train_state, *args, group_key)
        it = self._post_step(item.k)
        loss = losses[-1]
        for lst in self.listeners:
            lst.iteration_done(self, it, self.epoch_count, loss,
                               item.queue_wait_ms, item.n_examples)
        self._last_loss = loss

    def score(self, dataset: Optional[DataSet] = None) -> float:
        """Loss on a dataset (reference: MultiLayerNetwork.score(DataSet)),
        or the last training loss when called without arguments."""
        if dataset is None:
            if self._last_loss is None:
                raise RuntimeError("no score yet: call fit() first or pass a"
                                   " DataSet to score(dataset)")
            return float(self._last_loss)  # host-sync-ok: score() API returns a Python float
        return float(self.compute_loss(dataset))  # host-sync-ok: eval-path loss read, not the train loop

    def compute_loss(self, dataset: DataSet):
        raise NotImplementedError

    def _output_for_eval(self, batch: DataSet):
        """Inference with the batch's features mask threaded through (both
        model classes accept mask=; CG uses it as the default input mask)."""
        return self.output(batch.features, mask=batch.features_mask)

    # ---- evaluation -----------------------------------------------------
    def evaluate(self, iterator, evaluation: Optional[Evaluation] = None
                 ) -> Evaluation:
        e = evaluation or Evaluation()
        single = isinstance(iterator, DataSet)
        batches = [iterator] if single else iterator
        for batch in batches:
            preds = self._output_for_eval(batch)
            e.eval(batch.labels, np.asarray(preds),  # host-sync-ok: evaluation consumes host arrays
                   mask=batch.labels_mask if batch.labels_mask is not None
                   else batch.features_mask)
        if not single and isinstance(iterator, DataSetIterator):
            iterator.reset()
        return e

    def evaluate_regression(self, iterator) -> RegressionEvaluation:
        e = RegressionEvaluation()
        single = isinstance(iterator, DataSet)
        batches = [iterator] if single else iterator
        for batch in batches:
            preds = self._output_for_eval(batch)
            e.eval(batch.labels, np.asarray(preds), mask=batch.labels_mask)  # host-sync-ok: evaluation consumes host arrays
        if not single and isinstance(iterator, DataSetIterator):
            iterator.reset()
        return e
