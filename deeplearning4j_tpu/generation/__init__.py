"""Generative serving: continuous-batching autoregressive decode.

The decode analog of ``parallel/serving.py``'s predict path. A jitted
single-tick step advances every slot of a fixed-size batch by one token;
the (h, c) LSTM carry and the per-slot PRNG state stay device-resident
across ticks, sequences join and leave the batch mid-flight, and the
sampled tokens stream back to HTTP clients as they decode
(``POST /api/generate``, SSE).

v2 serving modes (opt-in per engine): chunked prefill (one jitted scan
per prompt chunk instead of one tick per char), resumable sessions
(retired carries pinned device-side, LRU-tiered to host, checkpointed
into the shared ArtifactStore for cross-node resume), and speculative
decode (n-gram draft + one-dispatch batched verify, bitwise-equal to
plain decode under counter-based splitmix64 sampling keys).

- ``decode.py``      pure tick/prefill builders, vocab, reference decode
- ``engine.py``      GenerationEngine: slots, scheduler, AOT warmup
- ``session.py``     SessionStore: tiered resumable carries
- ``speculative.py`` NGramDraft + the batched verify step
"""

from deeplearning4j_tpu.generation.decode import (
    DecodeSpec, Vocab, extract_decode_spec, head_bytes_per_token,
    prefill_chunk_ladder, reference_decode)
from deeplearning4j_tpu.generation.engine import (
    GenerationEngine, GenerationStream)
from deeplearning4j_tpu.generation.session import (
    CarrySnapshot, SessionStore)
from deeplearning4j_tpu.generation.speculative import (
    NGramDraft, counter_keys)

__all__ = ["DecodeSpec", "Vocab", "extract_decode_spec",
           "head_bytes_per_token", "prefill_chunk_ladder",
           "reference_decode", "GenerationEngine", "GenerationStream",
           "CarrySnapshot", "SessionStore", "NGramDraft",
           "counter_keys"]
