"""Generative serving: continuous-batching autoregressive decode.

The decode analog of ``parallel/serving.py``'s predict path. A jitted
single-tick step advances every slot of a fixed-size batch by one token;
the (h, c) LSTM carry and the per-slot PRNG state stay device-resident
across ticks, sequences join and leave the batch mid-flight, and the
sampled tokens stream back to HTTP clients as they decode
(``POST /api/generate``, SSE).

- ``decode.py``   pure tick builder, vocab, reference decode, int8 head
- ``engine.py``   GenerationEngine: slots, scheduler, AOT warmup, metrics
"""

from deeplearning4j_tpu.generation.decode import (
    DecodeSpec, Vocab, extract_decode_spec, head_bytes_per_token,
    reference_decode)
from deeplearning4j_tpu.generation.engine import (
    GenerationEngine, GenerationStream)

__all__ = ["DecodeSpec", "Vocab", "extract_decode_spec",
           "head_bytes_per_token", "reference_decode",
           "GenerationEngine", "GenerationStream"]
