"""Pure decode building blocks: model → tick function.

A *tick* advances every slot of an (S,)-shaped decode batch by exactly
one token: one-hot embed the input tokens, run each stacked LSTM cell's
``step_one``, project through the dense head, sample. Everything that
crosses ticks — the (h, c) carries and the per-slot PRNG keys — stays
on device; the only per-tick host traffic is the small int32 control
arrays in (tokens, reset flags, seeds, sampling knobs) and the sampled
tokens out (which *are* the streamed response payload).

Join/leave mid-flight rides the same masked-neutral trick as the
feeder's ragged buckets: a joining slot's ``reset`` flag zeroes its
carry rows and reseeds its PRNG key inside the tick; an inactive slot's
rows pass through untouched, so co-resident sequences are bitwise
independent of who else occupies the batch.

The head has three precision arms (f32 / bf16 / int8 via
``ops/quantize.py``); the LSTM stack is always f32, so the arms are an
apples-to-apples $/token comparison of the dense projection alone.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

_WEIGHTS_DIR = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "..", "zoo", "weights")
DEFAULT_VOCAB_PATH = os.path.abspath(
    os.path.join(_WEIGHTS_DIR, "textgen_vocab.json"))


# ---- vocab ------------------------------------------------------------


class Vocab:
    """char <-> id mapping for the streamed text surface.

    Index 0 is the unknown bucket (the committed textgen vocab starts
    at 1); decoding an id with no char yields U+FFFD so a stream is
    always valid UTF-8 even for an untrained model babbling id 0.
    """

    def __init__(self, stoi: Dict[str, int], size: int):
        self.stoi = dict(stoi)
        self.size = size
        self.itos = ["�"] * size
        for ch, i in self.stoi.items():
            if 0 <= i < size:
                self.itos[i] = ch

    @classmethod
    def load(cls, path: str = DEFAULT_VOCAB_PATH) -> "Vocab":
        with open(path) as f:
            stoi = json.load(f)
        return cls(stoi, max(stoi.values()) + 1)

    @classmethod
    def identity(cls, size: int) -> "Vocab":
        """No-text fallback for models without a committed char map."""
        return cls({}, size)

    @classmethod
    def default_for(cls, vocab_size: int) -> "Vocab":
        """The committed textgen vocab when sizes line up, else ids."""
        try:
            v = cls.load()
            if v.size == vocab_size:
                return v
        except OSError:
            pass
        return cls.identity(vocab_size)

    def encode(self, text: str) -> List[int]:
        return [self.stoi.get(ch, 0) for ch in text]

    def decode(self, ids: Sequence[int]) -> str:
        return "".join(self.itos[i] if 0 <= i < self.size else "�"
                       for i in ids)


# ---- model -> decode spec --------------------------------------------


@dataclasses.dataclass(frozen=True)
class DecodeSpec:
    """Static decode structure extracted (and validated) once."""
    lstm_names: Tuple[str, ...]
    hidden_sizes: Tuple[int, ...]
    head_name: str
    vocab_size: int

    @property
    def n_layers(self) -> int:
        return len(self.lstm_names)


def extract_decode_spec(model) -> DecodeSpec:
    """Validate the network shape the tick supports: a stack of LSTM
    cells (Graves peepholes included — ``step_one`` dispatches through
    the subclass ``_cell``) under a dense softmax head. Anything else
    fails here, at engine construction, not inside the first trace."""
    from deeplearning4j_tpu.nn.layers.recurrent import (
        LSTM, unwrap_recurrent)
    if model.train_state is None:
        model.init()
    layers = model.layers
    if len(layers) < 2:
        raise ValueError("decode needs >= 1 LSTM layer + a dense head")
    if getattr(model, "_preprocessors", None):
        raise ValueError(
            "decode tick does not support input preprocessors; got "
            f"{sorted(model._preprocessors)}")
    names, sizes, cores = [], [], []
    for l in layers[:-1]:
        core = unwrap_recurrent(l)
        if not isinstance(core, LSTM):
            raise ValueError(
                f"decode supports stacked LSTM cores only; layer "
                f"{l.name!r} is {type(core).__name__}")
        names.append(l.name)
        sizes.append(core.n_out)
        cores.append(core)
    head = layers[-1]
    if not hasattr(head, "pre_output"):
        raise ValueError(
            f"last layer {head.name!r} ({type(head).__name__}) has no "
            "dense pre_output; decode needs a projection head")
    hp = model.train_state.params.get(head.name, {})
    if "W" not in hp or "b" not in hp:
        raise ValueError(f"head {head.name!r} params missing W/b")
    return DecodeSpec(tuple(names), tuple(sizes), head.name,
                      int(hp["W"].shape[-1]))


def _lstm_cores(model, spec: DecodeSpec):
    from deeplearning4j_tpu.nn.layers.recurrent import unwrap_recurrent
    by_name = {l.name: l for l in model.layers}
    return [unwrap_recurrent(by_name[n]) for n in spec.lstm_names]


# ---- decode params (per-precision head) ------------------------------


def commit_decode_params(model, spec: DecodeSpec, precision: str,
                         x_scale: Optional[float] = None):
    """Device-resident decode param tree: f32 LSTM stack + the head in
    the requested precision arm. int8 rides ops/quantize (per-output-
    channel weight scales, one calibrated activation scale)."""
    from deeplearning4j_tpu.ops.quantize import quantize_weight
    p = model.train_state.params
    lstm = [{k: jnp.asarray(v, jnp.float32)
             for k, v in p[name].items()} for name in spec.lstm_names]
    W = np.array(p[spec.head_name]["W"], dtype=np.float32, copy=True)
    b = np.array(p[spec.head_name]["b"], dtype=np.float32, copy=True)
    if precision == "f32":
        head = {"W": jnp.asarray(W), "b": jnp.asarray(b)}
    elif precision == "bf16":
        head = {"W": jnp.asarray(W, jnp.bfloat16),
                "b": jnp.asarray(b, jnp.bfloat16)}
    elif precision == "int8":
        if x_scale is None:
            raise ValueError("int8 head needs a calibrated x_scale")
        w_q, w_scale = quantize_weight(W, reduce_axes=(0,))
        head = {"Wq": jnp.asarray(w_q), "w_scale": jnp.asarray(w_scale),
                "x_scale": jnp.asarray(np.float32(x_scale)),
                "b": jnp.asarray(b)}
    else:
        raise ValueError(f"unknown decode precision {precision!r}")
    return jax.device_put({"lstm": lstm, "head": head})


def head_bytes_per_token(spec: DecodeSpec, hidden: int,
                         precision: str) -> int:
    """Bytes the head moves per decode tick per slot: the weight matrix
    is re-read every tick (decode is memory-bound), plus bias/scales.
    The $/token A/B's 'bytes moved' column."""
    V = spec.vocab_size
    if precision == "f32":
        return hidden * V * 4 + V * 4
    if precision == "bf16":
        return hidden * V * 2 + V * 2
    if precision == "int8":
        # int8 weights + f32 per-channel scales + f32 bias + one x_scale
        return hidden * V * 1 + V * 4 + V * 4 + 4
    raise ValueError(precision)


# ---- the tick ---------------------------------------------------------


def _stack_step(cores, dp, x, hs, cs):
    """One position through the stacked LSTM cells. Shared verbatim by
    the tick, the chunked prefill scan and the speculative verify scan
    so every path computes bitwise-identical carries and head inputs —
    the parity guarantees all hang off this one function."""
    h_new, c_new = [], []
    for i, core in enumerate(cores):
        hy, cy = core.step_one(dp["lstm"][i], x, (hs[i], cs[i]))
        h_new.append(hy)
        c_new.append(cy)
        x = hy
    return h_new, c_new, x


def _head_logits(head, h):
    if "Wq" in head:
        from deeplearning4j_tpu.ops.quantize import int8_dot
        return int8_dot(h, head["Wq"], head["w_scale"],
                        head["x_scale"]) + head["b"]
    W = head["W"]
    if W.dtype == jnp.bfloat16:
        return (h.astype(jnp.bfloat16) @ W + head["b"]).astype(
            jnp.float32)
    return h @ W + head["b"]


def _sample_one(key, logits, temp, top_k, greedy):
    """One slot's sampling: greedy argmax, or temperature + top-k
    categorical. ``top_k <= 0`` means no truncation. argmax is taken on
    raw logits — identical to argmax of the model's softmax output, so
    greedy decode is bitwise-comparable to the reference path."""
    V = logits.shape[-1]
    greedy_tok = jnp.argmax(logits).astype(jnp.int32)
    scaled = logits / jnp.maximum(temp, 1e-3)
    k = jnp.clip(jnp.where(top_k <= 0, V, top_k), 1, V)
    kth = jnp.sort(scaled)[::-1][k - 1]
    masked = jnp.where(scaled >= kth, scaled, -jnp.inf)
    tok = jax.random.categorical(key, masked).astype(jnp.int32)
    return jnp.where(greedy, greedy_tok, tok)


def build_tick(model, spec: DecodeSpec):
    """The jittable single-tick decode step.

    tick(dp, h, c, rng, tokens, reset, seeds, active, temp, top_k,
    greedy, ext_key, use_ext) -> (h', c', rng', next_tokens)

    - dp: committed decode params ({"lstm": [...], "head": {...}})
    - h, c: per-layer lists of (S, H_l) f32 — device-resident carries
    - rng: (S, 2) uint32 per-slot PRNG keys — device-resident
    - tokens (S,) i32 in, reset/active (S,) bool, seeds (S,) u32,
      temp (S,) f32, top_k (S,) i32, greedy (S,) bool — host controls
    - ext_key (S, 2) u32 + use_ext (S,) bool — counter-mode sampling
      keys (splitmix64 of (seed, position), computed host-side by
      ``speculative.counter_keys``). A slot with ``use_ext`` samples
      with its externally-derived key instead of the carried split
      chain, which is what lets the speculative verify step reproduce
      any position's sampling without replaying the chain. The chain
      still advances either way, so chain-mode slots are unaffected
      by counter-mode co-residents.
    - next_tokens (S,) i32 — the streamed payload

    A reset slot's carries are zeroed and its key re-derived from its
    seed *inside* the tick; an inactive slot's state rows and token pass
    through unchanged (masked-neutral), which is what makes each slot's
    trajectory — including its PRNG stream, advanced exactly one split
    per active tick — independent of its co-residents.
    """
    cores = _lstm_cores(model, spec)
    V = spec.vocab_size

    def tick(dp, h, c, rng, tokens, reset, seeds, active, temp, top_k,
             greedy, ext_key, use_ext):
        rmask = reset[:, None]
        fresh = jax.vmap(jax.random.PRNGKey)(seeds)
        rng_in = jnp.where(rmask, fresh, rng)
        hs = [jnp.where(rmask, 0.0, hl) for hl in h]
        cs = [jnp.where(rmask, 0.0, cl) for cl in c]
        x = jax.nn.one_hot(tokens, V, dtype=jnp.float32)
        h_new, c_new, top = _stack_step(cores, dp, x, hs, cs)
        logits = _head_logits(dp["head"], top)
        split = jax.vmap(lambda k: jax.random.split(k, 2))(rng_in)
        key = jnp.where(use_ext[:, None], ext_key, split[:, 1])
        sampled = jax.vmap(_sample_one)(
            key, logits, temp, top_k, greedy)
        amask = active[:, None]
        h_out = [jnp.where(amask, hn, hi)
                 for hn, hi in zip(h_new, hs)]
        c_out = [jnp.where(amask, cn, ci)
                 for cn, ci in zip(c_new, cs)]
        rng_out = jnp.where(amask, split[:, 0], rng_in)
        next_tokens = jnp.where(active, sampled, tokens)
        return h_out, c_out, rng_out, next_tokens

    return tick


# ---- chunked prefill ---------------------------------------------------


def prefill_chunk_ladder(max_chunk: int) -> List[int]:
    """pow2 chunk sizes up to ``max_chunk`` (always included) — the
    prefill analog of the slot-bucket ladder, AOT-warmed the same way
    so a live prompt of any length dispatches warm executables only."""
    if max_chunk < 1:
        raise ValueError("max_chunk must be >= 1")
    out, b = [], 8
    while b < max_chunk:
        out.append(b)
        b <<= 1
    if not out or out[-1] != max_chunk:
        out.append(max_chunk)
    return sorted(set(out))


def build_prefill(model, spec: DecodeSpec):
    """The jittable multi-token prefill step: one ``lax.scan`` over a
    padded (S, C) prompt chunk with per-slot valid lengths.

    prefill(dp, h, c, rng, chunk, lens, reset, seeds, active)
        -> (h', c', rng')

    A slot consumes ``lens[i]`` tokens of its chunk row; positions past
    its length (and inactive slots) are masked-neutral pass-throughs.
    No head projection and no sampling — prefill only advances carries,
    which is most of the win (the head matmul is the dominant per-tick
    FLOP and prefill never needed it). The per-slot PRNG chain advances
    exactly one split per consumed token, identical to feeding the same
    tokens through the tick one at a time, so chunked and tick-at-a-time
    prefill are bitwise-interchangeable mid-sequence.
    """
    cores = _lstm_cores(model, spec)
    V = spec.vocab_size

    def prefill(dp, h, c, rng, chunk, lens, reset, seeds, active):
        rmask = reset[:, None]
        fresh = jax.vmap(jax.random.PRNGKey)(seeds)
        rng0 = jnp.where(rmask, fresh, rng)
        h0 = [jnp.where(rmask, 0.0, hl) for hl in h]
        c0 = [jnp.where(rmask, 0.0, cl) for cl in c]

        def step(carry, xs):
            hs, cs, r = carry
            tok_t, t = xs
            valid = active & (t < lens)
            vmask = valid[:, None]
            x = jax.nn.one_hot(tok_t, V, dtype=jnp.float32)
            h_new, c_new, _ = _stack_step(cores, dp, x, hs, cs)
            hs2 = [jnp.where(vmask, hn, hi)
                   for hn, hi in zip(h_new, hs)]
            cs2 = [jnp.where(vmask, cn, ci)
                   for cn, ci in zip(c_new, cs)]
            split = jax.vmap(lambda k: jax.random.split(k, 2))(r)
            r2 = jnp.where(vmask, split[:, 0], r)
            return (hs2, cs2, r2), None

        C = chunk.shape[1]
        xs = (jnp.transpose(chunk), jnp.arange(C, dtype=jnp.int32))
        (h1, c1, rng1), _ = jax.lax.scan(step, (h0, c0, rng0), xs)
        return h1, c1, rng1

    return prefill


# ---- per-slot carry extract/restore (session store) -------------------


def build_slot_extract(spec: DecodeSpec):
    """Jittable gather of one slot's device state rows — the capture
    half of session resume. ``idx`` is traced, so one executable per
    bucket covers every slot index (warmed like the tick)."""
    def extract(h, c, rng, idx):
        hr = [jnp.take(hl, idx, axis=0) for hl in h]
        cr = [jnp.take(cl, idx, axis=0) for cl in c]
        return hr, cr, jnp.take(rng, idx, axis=0)

    return extract


def build_slot_restore(spec: DecodeSpec):
    """Jittable scatter of saved carry rows into one slot of the live
    batch — the resume half. The joining slot skips its reset (its
    state IS the restored rows) and continues the sequence as if it
    had never retired."""
    def restore(h, c, rng, hr, cr, rr, idx):
        h2 = [hl.at[idx].set(r) for hl, r in zip(h, hr)]
        c2 = [cl.at[idx].set(r) for cl, r in zip(c, cr)]
        return h2, c2, rng.at[idx].set(rr)

    return restore


def zero_carries(spec: DecodeSpec, n_slots: int):
    """Fresh device state for a bucket: zero carries + zero PRNG rows
    (every slot is reseeded through its reset flag before first use)."""
    h = [jnp.zeros((n_slots, hd), jnp.float32)
         for hd in spec.hidden_sizes]
    c = [jnp.zeros((n_slots, hd), jnp.float32)
         for hd in spec.hidden_sizes]
    rng = jnp.zeros((n_slots, 2), jnp.uint32)
    return h, c, rng


def build_resize(spec: DecodeSpec, src: int, dst: int):
    """Jittable bucket resize for the device state. Growing zero-pads
    new slot rows (they get reseeded on join); shrinking slices — the
    scheduler only shrinks when no active slot lives above ``dst``.
    AOT-warmed like the tick so a mid-flight resize never live-compiles.
    """
    def resize(h, c, rng):
        if dst > src:
            pad = dst - src
            h2 = [jnp.pad(hl, ((0, pad), (0, 0))) for hl in h]
            c2 = [jnp.pad(cl, ((0, pad), (0, 0))) for cl in c]
            r2 = jnp.pad(rng, ((0, pad), (0, 0)))
        else:
            h2 = [hl[:dst] for hl in h]
            c2 = [cl[:dst] for cl in c]
            r2 = rng[:dst]
        return h2, c2, r2

    return resize


# ---- reference decode (test/bench oracle) ----------------------------


def _jit_time_step(model):
    """One jitted ``(features, carries) -> (probs, carries)`` step,
    cached on the model instance. The oracle loops below call
    ``rnn_time_step`` once per token, and the eager path re-lowers
    every call — ~100 ms/step on CPU against ~40 µs for the step
    itself. Keyed on the train state so a retrained model re-traces
    instead of serving stale closed-over params."""
    key = id(model.train_state)
    cached = model.__dict__.get("_rnn_step_jit")
    if cached is None or cached[0] != key:
        cached = (key, jax.jit(model.rnn_time_step))
        model.__dict__["_rnn_step_jit"] = cached
    return cached[1]


def reference_decode(model, prompt_ids: Sequence[int], max_new: int,
                     stop_id: Optional[int] = None) -> List[int]:
    """Greedy single-sequence decode through the model's own
    ``rnn_time_step`` path, one token per call — the oracle the
    continuous-batched engine must match bitwise in greedy mode. A
    host loop by design (it is the test reference, not the serving
    path), hence the pragmas."""
    spec = extract_decode_spec(model)
    if not prompt_ids:
        raise ValueError("reference_decode needs a non-empty prompt")
    step = _jit_time_step(model)
    carries = None
    out: List[int] = []
    feed = list(prompt_ids)
    pos = 0
    tok = feed[pos]
    pos += 1
    while len(out) < max_new:
        x = np.zeros((1, spec.vocab_size), np.float32)
        x[0, tok] = 1.0
        probs, carries = step(x, carries)
        if pos < len(feed):       # still consuming the prompt
            tok = feed[pos]
            pos += 1
            continue
        nxt = int(np.asarray(probs).argmax())  # host-sync-ok: test oracle host loop, not the serving path
        out.append(nxt)
        if stop_id is not None and nxt == stop_id:
            break
        tok = nxt
    return out


# ---- int8 head calibration + decode-level quant gate -----------------


def probe_head(model, spec: DecodeSpec, probe_ids: Sequence[int],
               free_run: int = 32):
    """Greedy f32 probe drive: consume ``probe_ids`` then free-run
    ``free_run`` ticks, collecting the head's input activations (the
    last LSTM's h — bounded in (-1, 1) since h = o*tanh(c)) and the f32
    logits at every position. Feeds both the int8 activation-scale
    calibration and the decode-level quant gate. Host loop by design:
    runs once at engine init, pre-traffic."""
    if not probe_ids:
        raise ValueError("probe needs a non-empty id stream")
    last = spec.lstm_names[-1]
    p = model.train_state.params
    W = np.array(p[spec.head_name]["W"], np.float32, copy=True)
    b = np.array(p[spec.head_name]["b"], np.float32, copy=True)
    step = _jit_time_step(model)
    carries = None
    hs: List[np.ndarray] = []
    feed = list(probe_ids)
    pos = 0
    tok = feed[pos]
    pos += 1
    total = len(feed) - 1 + free_run
    for _ in range(total):
        x = np.zeros((1, spec.vocab_size), np.float32)
        x[0, tok] = 1.0
        probs, carries = step(x, carries)
        hs.append(np.asarray(carries[last][0][0]))  # host-sync-ok: init-time calibration probe, pre-traffic
        if pos < len(feed):
            tok = feed[pos]
            pos += 1
        else:
            tok = int(np.asarray(probs).argmax())  # host-sync-ok: init-time calibration probe, pre-traffic
    h_stream = np.stack(hs)                        # (T, H)
    logits_f32 = h_stream @ W + b                  # (T, V)
    return h_stream, logits_f32


def int8_head_gate(model, spec: DecodeSpec, probe_ids: Sequence[int],
                   top1_budget: float = 0.03, logit_budget: float = 0.25,
                   free_run: int = 32, model_name: str = "generate",
                   registry=None):
    """Calibrate the int8 head and gate it at the decode level: next-
    token (argmax) agreement against the f32 head over the probe
    trajectory must stay within ``top1_budget``. Reuses the PTQ gate's
    result/error types so callers get the same summary surface as the
    predict-path quant gate. Returns (x_scale, GateResult); raises
    QuantGateError on a miss."""
    from deeplearning4j_tpu.evaluation.quant_gate import (
        GateResult, QuantGateError)
    from deeplearning4j_tpu.ops.quantize import (
        activation_scale, int8_dot, quantize_weight)
    h_stream, logits_f32 = probe_head(model, spec, probe_ids, free_run)
    amax = float(np.abs(h_stream).max())  # host-sync-ok: init-time calibration probe, pre-traffic
    x_scale = activation_scale(amax)
    p = model.train_state.params
    W = np.array(p[spec.head_name]["W"], np.float32, copy=True)
    b = np.array(p[spec.head_name]["b"], np.float32, copy=True)
    w_q, w_scale = quantize_weight(W, reduce_axes=(0,))
    logits_q = np.asarray(int8_dot(  # host-sync-ok: init-time gate evaluation, pre-traffic
        jnp.asarray(h_stream), jnp.asarray(w_q), jnp.asarray(w_scale),
        jnp.asarray(np.float32(x_scale)))) + b
    agree = float((logits_q.argmax(-1) == logits_f32.argmax(-1)).mean())  # host-sync-ok: init-time gate evaluation, pre-traffic
    delta = np.abs(logits_q - logits_f32)
    denom = float(np.abs(logits_f32).mean()) or 1.0  # host-sync-ok: init-time gate evaluation, pre-traffic
    rel = float(np.linalg.norm(logits_q - logits_f32)  # host-sync-ok: init-time gate evaluation, pre-traffic
                / (np.linalg.norm(logits_f32) or 1.0))
    result = GateResult(
        model=model_name, n_examples=int(h_stream.shape[0]),
        n_positions=int(h_stream.shape[0]),
        top1_agreement=agree, top1_delta=1.0 - agree,
        max_logit_delta=float(delta.max()) / denom,  # host-sync-ok: init-time gate evaluation, pre-traffic
        mean_logit_delta=float(delta.mean()) / denom,  # host-sync-ok: init-time gate evaluation, pre-traffic
        top1_budget=top1_budget, logit_budget=logit_budget,
        layer_errors={spec.head_name: rel}, fallback=[],
        passed=(1.0 - agree) <= top1_budget)
    if registry is not None:
        registry.gauge(
            "dl4j_gen_int8_agreement",
            "decode-level next-token agreement, int8 head vs f32"
        ).set(agree, model=model_name)
    if not result.passed:
        raise QuantGateError(result)
    return float(x_scale), result  # host-sync-ok: init-time calibration scalar, pre-traffic
