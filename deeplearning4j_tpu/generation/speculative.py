"""Speculative decode: n-gram draft + one-dispatch batched verify.

The CPU/TPU decode loop is dispatch-bound — one jitted tick per token
costs far more in launch overhead than in FLOPs at decode batch sizes.
Speculative decoding amortizes that: a host-side n-gram/suffix-table
draft proposes up to ``k`` continuation tokens per slot from the
sequence's own history, and ONE jitted scan feeds the slot's current
input plus all k drafts through the stack, samples every position, and
commits the longest accepted prefix in-graph. A dispatch emits
``n_acc + 1`` tokens (the accepted drafts plus the model's own token at
the first divergence — the "bonus" token), so acceptance rate converts
directly into tokens/s.

Exactness discipline (the part that makes this a serving feature and
not a sampler): a draft token is accepted iff it equals the token plain
decode *would* have emitted at that position. For greedy that is the
argmax; for seeded sampling the per-position key must be reproducible
without replaying the carried split chain, so sampling keys derive from
**counter-based splitmix64** over (request seed, absolute position) —
the same construction as ``nlp/pairgen.py``'s fused draw streams (PR
13) and ``chaos/plan.py``'s schedules (PR 14). Accepted output is
bitwise-equal to non-speculative decode in the same sampling mode, and
same-seed replay is exact regardless of batching, drafts, or which
node runs the sequence. Keys are keyed on (seed, position) only —
never the physical slot index — so co-residency stays invisible.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from deeplearning4j_tpu.nlp.pairgen import GOLDEN, _mix_np

# domain-separation salt so generation draws never collide with the
# embedding pair streams sharing the splitmix64 construction
_SALT = np.uint64(0x47454E5350454331)          # "GENSPEC1"
_LO32 = np.uint64(0xFFFFFFFF)


def counter_keys(seeds: np.ndarray, pos: np.ndarray,
                 k: int) -> np.ndarray:
    """(S,) request seeds + (S,) absolute positions -> (S, k, 2) uint32
    sampling keys; ``key[s, j]`` covers position ``pos[s] + j``.

    key64 = mix(mix(seed ^ SALT) + (pos + j + 1) * GOLDEN) — the
    pairgen ``draws_at`` shape with a generation-domain salt. Pure
    counter arithmetic: any position's key is computable from (seed,
    position) alone, which is what the speculative verify step and
    cross-node session resume both rely on.
    """
    s = np.asarray(seeds, np.uint64).reshape(-1, 1) ^ _SALT  # host-sync-ok: seeds are host scalars, keys are host-computed by design
    base = _mix_np(s.copy())
    p = (np.asarray(pos, np.uint64).reshape(-1, 1)  # host-sync-ok: positions are host counters
         + np.arange(k, dtype=np.uint64)[None, :])
    z = _mix_np(base + (p + np.uint64(1)) * np.uint64(GOLDEN))
    out = np.empty(z.shape + (2,), np.uint32)
    out[..., 0] = (z >> np.uint64(32)).astype(np.uint32)
    out[..., 1] = (z & _LO32).astype(np.uint32)
    return out


class NGramDraft:
    """Per-sequence n-gram/suffix draft table.

    Observes every token the sequence consumes or emits and keeps, for
    each context length 1..max_order, the most recent continuation seen
    after that context. ``propose(k)`` walks the longest-match table
    greedily to extend the current suffix — character LSTM output is
    highly self-repetitive, so recency-biased longest-suffix matching
    is a strong cheap draft (and a wrong draft only costs the already
    amortized verify dispatch, never correctness)."""

    __slots__ = ("max_order", "max_history", "history", "tables")

    def __init__(self, max_order: int = 3, max_history: int = 512):
        self.max_order = int(max_order)
        self.max_history = int(max_history)
        self.history: List[int] = []
        self.tables = [dict() for _ in range(self.max_order)]

    def observe(self, tok: int) -> None:
        h = self.history
        for o in range(self.max_order):
            n = o + 1
            if len(h) >= n:
                self.tables[o][tuple(h[-n:])] = tok
        h.append(tok)
        if len(h) > self.max_history:
            del h[:len(h) - self.max_history]

    def observe_many(self, toks) -> None:
        for t in toks:
            self.observe(int(t))

    def _lookup(self, ctx: List[int]) -> Optional[int]:
        for o in reversed(range(self.max_order)):
            n = o + 1
            if len(ctx) >= n:
                hit = self.tables[o].get(tuple(ctx[-n:]))
                if hit is not None:
                    return hit
        return None

    def propose(self, k: int) -> List[int]:
        out: List[int] = []
        ctx = list(self.history)
        for _ in range(k):
            tok = self._lookup(ctx)
            if tok is None:
                break
            out.append(tok)
            ctx.append(tok)
        return out


def build_spec_tick(model, spec, k: int):
    """The jittable draft-verify-commit step for up to ``k`` drafts.

    spec_tick(dp, h, c, rng, tokens, n_draft, reset, seeds, active,
    temp, top_k, greedy, ext_keys, use_ext)
        -> (h', c', rng', emitted, n_emit)

    - tokens (S, k+1) i32: position 0 is the slot's current input, the
      rest its draft continuation (padded past ``n_draft``)
    - n_draft (S,) i32: drafts attached this dispatch (0 = plain tick
      semantics — exactly one token emits)
    - ext_keys (S, k+1, 2) u32 + use_ext (S,): counter-mode sampling
      keys per position (see ``counter_keys``); chain-mode slots use
      the carried split chain, advanced one split per emitted token —
      bitwise the same chain plain decode would have consumed
    - emitted (S, k+1) i32: per-position sampled tokens; the scheduler
      streams ``emitted[i, :n_emit[i]]``
    - n_emit (S,) i32: accepted drafts + 1 bonus token (0 for inactive
      slots)

    The commit is in-graph: acceptance compares each draft against the
    token sampled at its position, the carries/rng roll back to the
    state after the last *emitted* token via ``take_along_axis`` over
    the scan's stacked states, and masked-neutral slots pass through —
    one dispatch, no host round-trip inside.
    """
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.generation.decode import (
        _head_logits, _lstm_cores, _sample_one, _stack_step)
    if k < 1:
        raise ValueError("speculative k must be >= 1")
    cores = _lstm_cores(model, spec)
    V = spec.vocab_size
    K1 = k + 1

    def spec_tick(dp, h, c, rng, tokens, n_draft, reset, seeds, active,
                  temp, top_k, greedy, ext_keys, use_ext):
        S = tokens.shape[0]
        rmask = reset[:, None]
        fresh = jax.vmap(jax.random.PRNGKey)(seeds)
        rng0 = jnp.where(rmask, fresh, rng)
        h0 = [jnp.where(rmask, 0.0, hl) for hl in h]
        c0 = [jnp.where(rmask, 0.0, cl) for cl in c]

        def step(carry, xs):
            hs, cs, r = carry
            tok_t, ext_t = xs
            x = jax.nn.one_hot(tok_t, V, dtype=jnp.float32)
            h_new, c_new, top = _stack_step(cores, dp, x, hs, cs)
            logits = _head_logits(dp["head"], top)
            split = jax.vmap(lambda kk: jax.random.split(kk, 2))(r)
            key = jnp.where(use_ext[:, None], ext_t, split[:, 1])
            sampled = jax.vmap(_sample_one)(
                key, logits, temp, top_k, greedy)
            r2 = split[:, 0]
            return (h_new, c_new, r2), (h_new, c_new, r2, sampled)

        xs = (jnp.transpose(tokens),
              jnp.swapaxes(ext_keys, 0, 1))
        _, (ys_h, ys_c, ys_rng, ys_tok) = jax.lax.scan(
            step, (h0, c0, rng0), xs)
        targets = jnp.transpose(ys_tok).astype(jnp.int32)   # (S, K1)
        drafts = tokens[:, 1:]
        dpos = jnp.arange(k, dtype=jnp.int32)[None, :]
        ok = (targets[:, :k] == drafts) & (dpos < n_draft[:, None])
        n_acc = jnp.sum(jnp.cumprod(ok.astype(jnp.int32), axis=1),
                        axis=1)                              # (S,)

        def sel(y):
            idx = jnp.broadcast_to(
                n_acc.reshape((1, S) + (1,) * (y.ndim - 2)), (1,) + y.shape[1:])
            return jnp.take_along_axis(y, idx, axis=0)[0]

        amask = active[:, None]
        h_out = [jnp.where(amask, sel(y), hi)
                 for y, hi in zip(ys_h, h0)]
        c_out = [jnp.where(amask, sel(y), ci)
                 for y, ci in zip(ys_c, c0)]
        rng_out = jnp.where(amask, sel(ys_rng), rng0)
        n_emit = jnp.where(active, n_acc + 1, 0).astype(jnp.int32)
        return h_out, c_out, rng_out, targets, n_emit

    return spec_tick
